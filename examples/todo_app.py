"""A tiny collaborative todo list over SharedMap + SharedDirectory.

Demonstrates map-family DDSes through the full runtime stack
(container -> datastore -> channel), last-writer-wins convergence and
summary boot of a cold replica.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.dds import DirectoryFactory, MapFactory
from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
from fluidframework_tpu.runtime.summary import SummaryTree
from fluidframework_tpu.testing.mocks import MultiClientHarness


def main() -> None:
    registry = ChannelRegistry([MapFactory(), DirectoryFactory()])
    h = MultiClientHarness(
        2, registry,
        channel_types=[("todos", MapFactory.type_name),
                       ("meta", DirectoryFactory.type_name)],
    )
    a = h.runtimes[0].get_datastore("default")
    b = h.runtimes[1].get_datastore("default")

    a.get_channel("todos").set("1", {"title": "write demo", "done": False})
    b.get_channel("todos").set("2", {"title": "ship round 4", "done": False})
    a.get_channel("meta").create_subdirectory("settings").set("theme", "dark")
    h.process_all()

    # Concurrent update of the same todo: last sequenced wins on both.
    a.get_channel("todos").set("1", {"title": "write demo", "done": True})
    h.process_all()
    assert (a.get_channel("todos").get("1")
            == b.get_channel("todos").get("1"))
    for key in sorted(a.get_channel("todos").keys()):
        item = a.get_channel("todos").get(key)
        mark = "x" if item["done"] else " "
        print(f"[{mark}] {item['title']}")

    # Cold boot from a summary sees the same state.
    wire = h.runtimes[0].summarize().to_json()
    cold = ContainerRuntime(registry)
    cold.load(SummaryTree.from_json(wire))
    todos = cold.get_datastore("default").get_channel("todos")
    print("cold boot sees", len(list(todos.keys())), "todos; theme =",
          cold.get_datastore("default").get_channel("meta")
          .get_subdirectory("settings").get("theme"))


if __name__ == "__main__":
    main()
