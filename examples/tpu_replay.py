"""Replay a concurrent SharedString op stream on the TPU overlay
engine and verify bit-identity against the scalar oracle.

On a TPU host the fused pallas kernel runs compiled; elsewhere set
REPLAY_INTERPRET=1 (default on CPU) to run the same kernel through
the interpreter. The stream is the honest concurrency shape: per-
client refSeq lag, so the engine resolves real concurrent
perspectives (insert tie-breaks, unseen-remove skips) on most ops.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax

    from fluidframework_tpu.core.mergetree import replay_passive
    from fluidframework_tpu.core.overlay_replay import OverlayDeviceReplica
    from fluidframework_tpu.testing.digest import state_digest
    from fluidframework_tpu.testing.synthetic import generate_lagged_stream

    on_tpu = jax.default_backend() in ("tpu", "axon")
    interpret = os.environ.get(
        "REPLAY_INTERPRET", "0" if on_tpu else "1"
    ) == "1"
    n_ops = int(os.environ.get("REPLAY_OPS", 20_000 if on_tpu else 2_000))

    stream = generate_lagged_stream(
        n_ops, n_clients=64, seed=42, window=256, initial_len=32
    )
    lagged = (stream.ref_seq < stream.seq - 1).mean()
    print(f"{n_ops} ops from 64 clients ({lagged:.0%} at lagging refSeqs)")

    replica = OverlayDeviceReplica(
        stream, initial_len=32, chunk_size=256, window=2048,
        n_removers=24, interpret=interpret,
    )
    replica.prepare()
    t0 = time.perf_counter()
    replica.replay()
    replica.check_errors()
    dt = time.perf_counter() - t0
    mode = "interpreted" if interpret else "compiled"
    print(f"overlay engine ({mode}): {n_ops / dt:,.0f} ops/s")

    oracle = replay_passive(
        stream.as_messages(),
        initial="".join(map(chr, stream.text[:32])),
    )
    assert state_digest(replica.annotated_spans()) == state_digest(
        oracle.annotated_spans()
    )
    print("final state bit-identical to the scalar oracle "
          f"({len(replica.get_text())} chars)")


if __name__ == "__main__":
    main()
