"""Two clients collaboratively editing a SharedString.

Demonstrates the user-facing surface: container runtimes over an
in-proc ordering service, concurrent inserts converging, interval
collections with endpoint sidedness, and per-position attribution.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.dds import StringFactory
from fluidframework_tpu.dds.sequence import SIDE_AFTER, SIDE_BEFORE
from fluidframework_tpu.framework.attributor import mixin_attributor
from fluidframework_tpu.runtime import ChannelRegistry
from fluidframework_tpu.testing.mocks import MultiClientHarness


def main() -> None:
    registry = ChannelRegistry([StringFactory()])
    h = MultiClientHarness(
        2, registry, channel_types=[("text", StringFactory.type_name)]
    )
    alice = h.runtimes[0].get_datastore("default").get_channel("text")
    bob = h.runtimes[1].get_datastore("default").get_channel("text")
    attributor = mixin_attributor(h.runtimes[0])
    alice.enable_attribution()
    bob.enable_attribution()

    alice.insert_text(0, "Hello world")
    h.process_all()

    # Concurrent edits at the same region: both land deterministically.
    alice.insert_text(5, ",")
    bob.insert_text(11, "!")
    h.process_all()
    assert alice.get_text() == bob.get_text()
    print("converged text:", alice.get_text())

    # An interval marking "world" that expands with boundary inserts
    # on the left but not the right.
    coll = alice.get_interval_collection("highlights")
    iv = coll.add(7, 12, {"style": "bold"},
                  start_side=SIDE_BEFORE, end_side=SIDE_AFTER)
    h.process_all()
    bob.insert_text(7, ">>")
    h.process_all()
    s, e = coll.get_interval_by_id(iv.interval_id).bounds(alice.engine)
    print("highlight now covers:", repr(alice.get_text()[s:e]))

    # Who wrote the exclamation mark?
    pos = alice.get_text().index("!")
    entry = attributor.entry_at(alice, pos)
    print(f"'!' was written by client {entry['client']}")


if __name__ == "__main__":
    main()
