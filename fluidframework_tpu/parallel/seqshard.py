"""Sequence-sharded overlay replay compiled over a device mesh.

The shard_map form of `parallel.seqshard_ref.SeqShardedOverlay`
(which is the executable spec, differentially gated against the
single-doc overlay engine): ONE document's settled coordinate space
partitioned contiguously across the mesh's `seq` axis, each device
holding one shard's settled slice + overlay rows.

Per op, the only cross-device traffic is tiny all-gathers over ICI:

- each shard's (visible length, delta) at the op's perspective — the
  associative partial-lengths combine (partialLengths.ts:256) as an
  exclusive prefix over the gathered vector;
- insert-landing arbitration: per-shard landing bits + target
  coordinates; the first landing shard (document order) wins, and the
  shard owning the target coordinate stores the row.

Range ops (remove/annotate) need NO arbitration: every shard applies
its clipped local sub-range independently (splits, gap
materialization, covered-row updates are shard-local).

This build runs fold-free (rows accumulate; the window IS the whole
replay) — fold is proven entirely shard-local by the numpy spec, and
a window larger than one device's capacity is exactly the case
sequence sharding exists for. States extract back into the numpy spec
for digest comparison.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.mergetree_kernel import (
    ERR_BAD_POS,
    ERR_CAPACITY,
    ERR_REMOVERS,
    NOT_REMOVED,
    OP_ANNOTATE,
    OP_INSERT,
    OP_REMOVE,
    PROP_ABSENT,
    PROP_DELETE,
)
from ..ops.overlay_ref import SETTLED_BASE
from ..protocol.constants import NO_CLIENT
from ..utils.jax_compat import shard_map_compat


class ShardState(NamedTuple):
    """One sequence shard's overlay rows (capacity C) + settled len."""

    anchor: jnp.ndarray   # [C] int32, local settled coordinate
    buf: jnp.ndarray      # [C] int32, arena offset | SETTLED_BASE+coord
    length: jnp.ndarray   # [C] int32
    iseq: jnp.ndarray     # [C] int32
    iclient: jnp.ndarray  # [C] int32
    rseq: jnp.ndarray     # [C] int32
    rcl: jnp.ndarray      # [C, KR] int32
    props: jnp.ndarray    # [C, KK] int32
    n: jnp.ndarray        # [] int32 live rows
    S: jnp.ndarray        # [] int32 settled length (static: fold-free)
    error: jnp.ndarray    # [] int32


def make_shard_state(settled_len: int, capacity: int, n_removers: int,
                     n_prop_keys: int) -> ShardState:
    C = capacity
    return ShardState(
        anchor=jnp.zeros(C, jnp.int32),
        buf=jnp.zeros(C, jnp.int32),
        length=jnp.zeros(C, jnp.int32),
        iseq=jnp.zeros(C, jnp.int32),
        iclient=jnp.zeros(C, jnp.int32),
        rseq=jnp.full(C, NOT_REMOVED, jnp.int32),
        rcl=jnp.full((C, n_removers), NO_CLIENT, jnp.int32),
        props=jnp.full((C, n_prop_keys), PROP_ABSENT, jnp.int32),
        n=jnp.int32(0),
        S=jnp.int32(settled_len),
        error=jnp.int32(0),
    )


def _row_insert(st: ShardState, j, anchor, buf, length, iseq, iclient,
                rseq, rcl_row, props_row, do: jnp.ndarray) -> ShardState:
    """Insert one row at local index j (rows at/after j shift right),
    masked by `do`. Capacity overflow raises the error bit."""
    C = st.anchor.shape[0]
    idx = jnp.arange(C, dtype=jnp.int32)
    full = st.n >= C
    overflow = do & full  # the error must observe the UNmasked intent
    do = do & ~full

    def shift(a, val):
        rolled = jnp.roll(a, 1, axis=0)
        keep = _expand((idx < j) | ~do, a)
        at = _expand((idx == j) & do, a)
        return jnp.where(keep, a, jnp.where(at, jnp.asarray(val, a.dtype),
                                            rolled))
    st2 = ShardState(
        anchor=shift(st.anchor, anchor),
        buf=shift(st.buf, buf),
        length=shift(st.length, length),
        iseq=shift(st.iseq, iseq),
        iclient=shift(st.iclient, iclient),
        rseq=shift(st.rseq, rseq),
        rcl=shift(st.rcl, rcl_row),
        props=shift(st.props, props_row),
        n=st.n + jnp.where(do, 1, 0).astype(jnp.int32),
        S=st.S,
        error=st.error | jnp.where(
            overflow, ERR_CAPACITY, 0
        ).astype(jnp.int32),
    )
    return st2


def _expand(mask, a):
    return mask[:, None] if a.ndim > 1 else mask


def _visibility(st: ShardState, ref_seq, client):
    C = st.anchor.shape[0]
    idx = jnp.arange(C, dtype=jnp.int32)
    live = idx < st.n
    is_span = live & (st.buf >= SETTLED_BASE)
    consume = jnp.where(is_span, st.length, 0)
    removed = live & (st.rseq != NOT_REMOVED)
    tomb = removed & (st.rseq <= ref_seq)
    ins_vis = (st.iclient == client) | (st.iseq <= ref_seq)
    among = (st.rcl == client).any(axis=1)
    skip = tomb | (removed & ~ins_vis)
    visible = live & ~skip & ins_vis & ~(removed & among)
    vis_len = jnp.where(visible, st.length, 0)
    delta = jnp.where(live, vis_len - consume, 0)
    cum = jnp.cumsum(delta) - delta
    pre = st.anchor + cum
    return live, is_span, skip, vis_len, delta, pre


def _split(st: ShardState, q, ref_seq, client) -> ShardState:
    """Boundary split at local visible position q (no-op when no row
    strictly contains q)."""
    live, is_span, skip, vis, _, pre = _visibility(st, ref_seq, client)
    inside = live & ~skip & (pre < q) & (pre + vis > q)
    do = inside.any()
    j = jnp.argmax(inside).astype(jnp.int32)
    off = q - pre[j]
    span_j = is_span[j]
    tail_anchor = st.anchor[j] + jnp.where(span_j, off, 0)
    st2 = _row_insert(
        st, j + 1, tail_anchor, st.buf[j] + off, st.length[j] - off,
        st.iseq[j], st.iclient[j], st.rseq[j], st.rcl[j], st.props[j],
        do,
    )
    new_len = jnp.where(
        (jnp.arange(st.anchor.shape[0]) == j) & do, off, st2.length
    ).astype(jnp.int32)
    return st2._replace(length=new_len)


def sequence_sharded_replay(mesh: Mesh, capacity: int, n_removers: int,
                            n_prop_keys: int, axis: str = "seq"):
    """Compile the sequence-sharded replay for `mesh`.

    Returns a jitted ``replay(states, ops) -> (states', error)`` where
    `states` is a ShardState with a leading shard axis of size
    ``mesh.size`` laid out across the mesh, and `ops` is a dict of
    replicated op arrays [N]: op_type, pos1, pos2, seq, ref_seq,
    client, buf_start, ins_len, prop_key, prop_val.
    """
    D = mesh.size

    def local_replay(st_batched, ops):
        st = jax.tree_util.tree_map(lambda a: a[0], st_batched)
        rank = jax.lax.axis_index(axis)

        def step(st: ShardState, op):
            (op_type, pos1, pos2, seq, ref_seq, client, buf_start,
             ins_len, pk, pv) = op

            # Gather every shard's settled length once per step (it is
            # fold-free static, but gathering keeps the code honest
            # for a future folding build).
            S_all = jax.lax.all_gather(st.S, axis)
            bases = jnp.cumsum(S_all) - S_all
            my_base = bases[rank]
            S_total = S_all.sum()

            def partials(s):
                _, _, _, _, delta, _ = _visibility(s, ref_seq, client)
                ds = delta.sum()
                return s.S + ds, ds

            # ----------------------------------------------- insert
            def do_insert(st: ShardState) -> ShardState:
                v_loc, d_loc = partials(st)
                v_all = jax.lax.all_gather(v_loc, axis)
                d_all = jax.lax.all_gather(d_loc, axis)
                off = jnp.cumsum(v_all) - v_all
                q = pos1 - off[rank]
                # Local split (no-op unless a row strictly contains q).
                st = _split(st, q, ref_seq, client)
                live, is_span, skip, vis, delta, pre = _visibility(
                    st, ref_seq, client
                )
                land = live & (
                    (pre > q)
                    | ((pre == q) & ~skip & ((vis > 0) | (seq > st.iseq)))
                )
                land_any = land.any()
                j = jnp.argmax(land).astype(jnp.int32)
                c_cand = st.anchor[j] + my_base - (pre[j] - q)
                land_all = jax.lax.all_gather(land_any, axis)
                c_all = jax.lax.all_gather(c_cand, axis)
                exists = land_all.any()
                winner = jnp.argmax(land_all).astype(jnp.int32)
                c_land = c_all[winner]
                total = off[-1] + v_all[-1]
                delta_total = d_all.sum()
                c_append = jnp.minimum(pos1 - delta_total, S_total)
                c_final = jnp.where(exists, c_land, c_append)
                # Owner shard of coordinate c_final (half-open; the
                # last shard owns its own end).
                owner = jnp.minimum(
                    jnp.searchsorted(
                        bases[1:], c_final, side="right"
                    ).astype(jnp.int32),
                    D - 1,
                )
                winner_stores = exists & (c_land >= bases[winner])
                storer = jnp.where(winner_stores, winner, owner)
                i_store = rank == storer
                at_j = winner_stores & (rank == winner)
                local_pos = jnp.where(at_j, j, st.n)
                local_anchor = jnp.clip(c_final - my_base, 0, st.S)
                props_row = jnp.full(n_prop_keys, PROP_ABSENT, jnp.int32)
                props_row = jnp.where(
                    (jnp.arange(n_prop_keys) == pk) & (pk >= 0),
                    jnp.where(pv == PROP_DELETE, PROP_ABSENT, pv),
                    props_row,
                )
                st = _row_insert(
                    st, local_pos, local_anchor, buf_start, ins_len,
                    seq, client, NOT_REMOVED,
                    jnp.full(n_removers, NO_CLIENT, jnp.int32),
                    props_row, i_store,
                )
                err = jnp.where(
                    ~exists & (pos1 > total), ERR_BAD_POS, 0
                ).astype(jnp.int32)
                return st._replace(error=st.error | err)

            # ------------------------------------------------ range
            def do_range(st: ShardState) -> ShardState:
                v_loc, d_loc = partials(st)
                v_all = jax.lax.all_gather(v_loc, axis)
                off = jnp.cumsum(v_all) - v_all
                total = off[-1] + v_all[-1]
                lo = jnp.clip(pos1 - off[rank], 0, v_loc)
                hi = jnp.clip(pos2 - off[rank], 0, v_loc)
                err = jnp.where(pos2 > total, ERR_BAD_POS, 0)
                st = st._replace(
                    error=st.error | err.astype(jnp.int32)
                )

                def apply_local(st: ShardState) -> ShardState:
                    st = _split(st, lo, ref_seq, client)
                    st = _split(st, hi, ref_seq, client)
                    C = st.anchor.shape[0]
                    idx = jnp.arange(C, dtype=jnp.int32)
                    live, is_span, skip, vis, delta, pre = _visibility(
                        st, ref_seq, client
                    )
                    # Settled coordinates of the clipped range ends.
                    def coord_of(p):
                        cand = live & (pre >= p)
                        any_c = cand.any()
                        k = jnp.argmax(cand)
                        return jnp.where(
                            any_c,
                            st.anchor[k] - (pre[k] - p),
                            p - delta.sum(),
                        )

                    c1 = coord_of(lo)
                    c2 = coord_of(hi)
                    # Gap materialization: gap k sits before row k
                    # (gap C'=n is the tail up to S). Materialized
                    # gaps become span rows via one scatter remap.
                    consume = jnp.where(is_span, st.length, 0)
                    prev_end = jnp.where(
                        idx == 0, 0,
                        jnp.roll(st.anchor + consume, 1),
                    )
                    glo = jnp.where(idx < st.n, prev_end, 0)
                    ghi = jnp.where(idx < st.n, st.anchor, 0)
                    # tail gap (index n): [last end, S)
                    last_end = jnp.where(
                        st.n > 0,
                        (st.anchor + consume)[
                            jnp.maximum(st.n - 1, 0)
                        ],
                        0,
                    )
                    glo = jnp.where(idx == st.n, last_end, glo)
                    ghi = jnp.where(idx == st.n, st.S, ghi)
                    in_gap = idx <= st.n
                    mlo = jnp.maximum(glo, c1)
                    mhi = jnp.minimum(ghi, c2)
                    mat = in_gap & (mlo < mhi)
                    n_mat = mat.sum().astype(jnp.int32)
                    # Remap: old row i -> i + (# materialized gaps <= i).
                    mat_incl = jnp.cumsum(mat.astype(jnp.int32))
                    row_dst = idx + mat_incl
                    gap_dst = idx + mat_incl - 1  # gap k before row k

                    def scatter(a, gap_vals):
                        """Remap old rows to row_dst and write the
                        materialized gap rows at gap_dst (out-of-range
                        dummies drop)."""
                        gv = jnp.broadcast_to(
                            jnp.asarray(gap_vals, a.dtype), a.shape
                        )
                        out = jnp.zeros_like(a)
                        out = out.at[
                            jnp.where(idx < st.n, row_dst, C)
                        ].set(a, mode="drop")
                        out = out.at[
                            jnp.where(mat, gap_dst, C)
                        ].set(gv, mode="drop")
                        return out

                    overflow = st.n + n_mat > C
                    st2 = ShardState(
                        anchor=scatter(st.anchor, mlo),
                        buf=scatter(st.buf, SETTLED_BASE + mlo),
                        length=scatter(st.length, mhi - mlo),
                        iseq=scatter(st.iseq, jnp.zeros(C, jnp.int32)),
                        iclient=scatter(
                            st.iclient, jnp.full(C, NO_CLIENT, jnp.int32)
                        ),
                        rseq=scatter(
                            st.rseq, jnp.full(C, NOT_REMOVED, jnp.int32)
                        ),
                        rcl=scatter(
                            st.rcl,
                            jnp.full((C, n_removers), NO_CLIENT,
                                     jnp.int32),
                        ),
                        props=scatter(
                            st.props,
                            jnp.full((C, n_prop_keys), PROP_ABSENT,
                                     jnp.int32),
                        ),
                        n=jnp.minimum(st.n + n_mat, C),
                        S=st.S,
                        error=st.error | jnp.where(
                            overflow, ERR_CAPACITY, 0
                        ).astype(jnp.int32),
                    )
                    # Covered-row updates.
                    live, is_span, skip, vis, delta, pre = _visibility(
                        st2, ref_seq, client
                    )
                    covered = (
                        live & ~skip & (vis > 0)
                        & (pre >= lo) & (pre + vis <= hi)
                    )
                    is_rm = op_type == OP_REMOVE
                    already = st2.rseq != NOT_REMOVED
                    new_rseq = jnp.where(
                        covered & is_rm & ~already, seq, st2.rseq
                    ).astype(jnp.int32)
                    free = st2.rcl == NO_CLIENT
                    first_free = jnp.argmax(free, axis=1)
                    no_free = ~free.any(axis=1)
                    slot = jnp.where(already, first_free, 0)
                    write_rcl = covered & is_rm & ~(already & no_free)
                    kk = jnp.arange(st2.rcl.shape[1])
                    new_rcl = jnp.where(
                        write_rcl[:, None] & (kk[None, :] == slot[:, None]),
                        client, st2.rcl,
                    ).astype(jnp.int32)
                    err2 = jnp.where(
                        (covered & is_rm & already & no_free).any(),
                        ERR_REMOVERS, 0,
                    )
                    # Annotate: last-writer per key; deletes tombstone
                    # on spans, clear on text rows.
                    is_an = op_type == OP_ANNOTATE
                    pkk = jnp.arange(n_prop_keys)
                    an_write = (
                        covered[:, None] & is_an
                        & (pkk[None, :] == pk) & (pk >= 0)
                    )
                    an_val = jnp.where(
                        pv == PROP_DELETE,
                        jnp.where(is_span, PROP_DELETE, PROP_ABSENT)[
                            :, None
                        ],
                        pv,
                    )
                    new_props = jnp.where(
                        an_write, an_val, st2.props
                    ).astype(jnp.int32)
                    return st2._replace(
                        rseq=new_rseq, rcl=new_rcl, props=new_props,
                        error=st2.error | err2.astype(jnp.int32),
                    )

                return jax.lax.cond(
                    lo < hi, apply_local, lambda s: s, st
                )

            is_insert = op_type == OP_INSERT
            is_range = (op_type == OP_REMOVE) | (op_type == OP_ANNOTATE)
            st = jax.lax.cond(is_insert, do_insert,
                              lambda s: jax.lax.cond(
                                  is_range, do_range, lambda x: x, s),
                              st)
            return st, None

        ops_tuple = (
            ops["op_type"], ops["pos1"], ops["pos2"], ops["seq"],
            ops["ref_seq"], ops["client"], ops["buf_start"],
            ops["ins_len"], ops["prop_key"], ops["prop_val"],
        )
        st, _ = jax.lax.scan(step, st, ops_tuple)
        bits = jnp.arange(31, dtype=jnp.int32)
        err = jax.lax.pmax((st.error >> bits) & 1, axis)
        gerr = jnp.sum(err << bits)
        return jax.tree_util.tree_map(lambda a: a[None], st), gerr

    shard_specs = ShardState(
        anchor=P(axis), buf=P(axis), length=P(axis), iseq=P(axis),
        iclient=P(axis), rseq=P(axis), rcl=P(axis), props=P(axis),
        n=P(axis), S=P(axis), error=P(axis),
    )
    fn = shard_map_compat(
        local_replay,
        mesh=mesh,
        in_specs=(shard_specs, P()),
        out_specs=(shard_specs, P()),
        check=False,
    )
    return jax.jit(fn)


def run_sequence_sharded(stream, mesh: Mesh, initial_len: int,
                         capacity: int = 4096, n_removers: int = 10,
                         n_prop_keys: int = 8, axis: str = "seq"):
    """Replay `stream` sequence-sharded over `mesh`; returns a
    `SeqShardedOverlay` (numpy spec object) rebuilt from the final
    device states for digest/text comparison."""
    from .seqshard_ref import SeqShardedOverlay

    D = mesh.size
    bounds = np.linspace(0, initial_len, D + 1).astype(int)
    states = [
        make_shard_state(
            int(bounds[d + 1] - bounds[d]), capacity, n_removers,
            n_prop_keys,
        )
        for d in range(D)
    ]
    batched = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *states
    )
    ops = {
        k: jnp.asarray(getattr(stream, k), jnp.int32)
        for k in ("op_type", "pos1", "pos2", "seq", "ref_seq", "client",
                  "buf_start", "ins_len")
    }
    ops["prop_key"] = jnp.asarray(stream.prop_key, jnp.int32)
    ops["prop_val"] = jnp.asarray(stream.prop_val, jnp.int32)
    replay = sequence_sharded_replay(
        mesh, capacity, n_removers, n_prop_keys, axis
    )
    out, gerr = replay(batched, ops)
    out = jax.tree_util.tree_map(np.asarray, out)
    # Rebuild the numpy spec object from the device states.
    sharded = SeqShardedOverlay(
        stream, D, initial_len=initial_len, n_removers=n_removers,
        n_prop_keys=n_prop_keys,
    )
    for d, sh in enumerate(sharded.shards):
        n = int(out.n[d])
        sh.anchor = out.anchor[d, :n].copy()
        sh.buf = out.buf[d, :n].copy()
        sh.length = out.length[d, :n].copy()
        sh.iseq = out.iseq[d, :n].copy()
        sh.iclient = out.iclient[d, :n].copy()
        sh.rseq = out.rseq[d, :n].copy()
        sh.rcl = out.rcl[d, :n].copy()
        sh.props = out.props[d, :n].copy()
        sh.error = int(out.error[d])
    return sharded, int(gerr)
