"""Sequence-sharded overlay: ONE document split across shards.

SURVEY.md §2.6 row 3: the reference scales document LENGTH with
chunked snapshots (snapshotV1.ts:37) and the associative per-block
`PartialSequenceLengths` (partialLengths.ts:256 `combine`); the
TPU-native form shards the segment table along the sequence dimension
so a single pathological document with a huge live window spreads
across devices.

Model
-----
The settled coordinate space ``[0, S_total)`` partitions CONTIGUOUSLY:
shard ``d`` owns a slice of settled text (local coordinates
``[0, S_d)``) plus every overlay row anchored inside it — each shard
IS a standalone `ops.overlay_ref.OverlayDoc`. Cross-shard structure:

- **Position resolve** — per-op, each shard computes its visible
  length at the op's perspective (its local partial-lengths sum); the
  exclusive prefix over shards (the associative `combine`) gives each
  shard its global offset. On a mesh this is one tiny all-gather of D
  scalars per op batch over ICI.
- **Insert landing** — candidate shards (those whose visible range
  can contain the position) split locally, then evaluate the landing
  predicate (insertingWalk + breakTie, mergeTree.ts:1740,:1719)
  locally; the FIRST shard (document order) that lands takes the row.
  If none lands, the insert appends at the global storage end: the
  shard owning the target settled coordinate stores it.
- **Range ops** — each shard applies its clipped sub-range in local
  visible coordinates (splits, gap materialization, covered-row
  updates are all shard-local).
- **Fold** (zamboni role) — entirely shard-local: rows settle into or
  excise from the shard's own settled text; boundaries shift
  implicitly because they are DERIVED (B_d = sum of earlier shards'
  settled lengths), never stored.
- **Rebalance** — boundary segment exchange: straddling rows split at
  the new boundaries, then settled text + rows redistribute evenly.

This module is the executable semantic spec (numpy, one op at a
time), differentially gated against the single-doc OverlayDoc /
OverlayStreamReplica digests; `parallel.seqshard` is the compiled
shard_map form of exactly these semantics.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..ops.mergetree_kernel import (
    ERR_BAD_POS,
    NOT_REMOVED,
    OP_ANNOTATE,
    OP_INSERT,
    OP_REMOVE,
    PROP_ABSENT,
    PROP_DELETE,
)
from ..ops.overlay_ref import SETTLED_BASE, OverlayDoc, merge_span_props


class SeqShardedOverlay:
    """A single overlay document sequence-sharded over `n_shards`
    shard docs. Streams resolve text through the stream arena like
    OverlayStreamReplica (offsets into ``stream.text``)."""

    def __init__(self, stream, n_shards: int, initial_len: int = 0,
                 fold_interval: int = 2048, n_removers: int = 4,
                 n_prop_keys: int = 8):
        self.stream = stream
        self.D = n_shards
        self.fold_interval = fold_interval
        self.error = 0
        stream_text = np.asarray(stream.text, np.int32)
        self._stream_text = stream_text
        # Partition the initial settled text evenly.
        bounds = np.linspace(0, initial_len, n_shards + 1).astype(int)
        self.shards: List[OverlayDoc] = []
        for d in range(n_shards):
            doc = OverlayDoc(
                stream_text[bounds[d]:bounds[d + 1]].copy(),
                n_removers, n_prop_keys,
            )
            self._wire_row_text(doc)
            self.shards.append(doc)

    def _wire_row_text(self, doc: OverlayDoc) -> None:
        stream_text = self._stream_text

        def row_text(i: int) -> np.ndarray:
            b = int(doc.buf[i])
            ln = int(doc.length[i])
            if b >= SETTLED_BASE:
                a = b - SETTLED_BASE
                return doc.settled_text[a: a + ln]
            return stream_text[b: b + ln]

        doc._row_text = row_text  # type: ignore[assignment]

    # ------------------------------------------------------ partials

    def _partials(self, ref_seq: int, client: int):
        """Per-shard (visible_len, delta_sum) at a perspective plus
        the exclusive visible-offset prefix — the cross-shard
        associative partial-lengths combine."""
        vis = np.zeros(self.D, np.int64)
        delta = np.zeros(self.D, np.int64)
        for d, sh in enumerate(self.shards):
            _, vl = sh._visibility(ref_seq, client)
            _, ds = sh._pre(vl)
            delta[d] = ds
            vis[d] = sh.S + ds
        off = np.concatenate([[0], np.cumsum(vis)[:-1]])
        return vis, delta, off

    @property
    def S_total(self) -> int:
        return sum(sh.S for sh in self.shards)

    # --------------------------------------------------------- apply

    def apply(self, op_type: int, pos1: int, pos2: int, seq: int,
              ref_seq: int, client: int, buf_start: int, ins_len: int,
              prop_keys, prop_vals) -> None:
        if op_type == OP_INSERT:
            self._apply_insert(pos1, seq, ref_seq, client, buf_start,
                               ins_len, prop_keys, prop_vals)
        elif op_type in (OP_REMOVE, OP_ANNOTATE):
            self._apply_range(op_type, pos1, pos2, seq, ref_seq, client,
                              prop_keys, prop_vals)

    def _candidates(self, pos: int, vis, off):
        return [
            d for d in range(self.D)
            if off[d] <= pos <= off[d] + vis[d]
        ]

    def _props_row(self, prop_keys, prop_vals) -> np.ndarray:
        props_row = np.full(self.shards[0].KK, PROP_ABSENT, np.int32)
        for k, v in zip(prop_keys, prop_vals):
            if k >= 0:
                props_row[k] = PROP_ABSENT if v == PROP_DELETE else v
        return props_row

    def _owner_of(self, c: int) -> Tuple[int, int]:
        """(shard, shard base coordinate) owning settled coordinate
        `c`: half-open ranges, last shard owns its own end."""
        base = 0
        for d, sh in enumerate(self.shards):
            if c < base + sh.S or d == self.D - 1:
                return d, base
            base += sh.S
        return self.D - 1, base

    def _apply_insert(self, pos1, seq, ref_seq, client, buf_start,
                      ins_len, prop_keys, prop_vals) -> None:
        vis, delta, off = self._partials(ref_seq, client)
        # Splits are local: only a shard whose row strictly contains
        # the local position has anything to split (no-op elsewhere).
        for d in self._candidates(pos1, vis, off):
            self.shards[d]._split(int(pos1 - off[d]), ref_seq, client)
        # Landing walk over ALL shards in document order (a landing
        # row with pre > pos can live in a shard whose visible range
        # starts after the position — invisible-at-perspective content
        # pulls later rows' pre below their shard offset).
        bases = np.concatenate(
            [[0], np.cumsum([sh.S for sh in self.shards])]
        )
        for e, sh in enumerate(self.shards):
            q = int(pos1 - off[e])
            skip, vl = sh._visibility(ref_seq, client)
            pre, _ = sh._pre(vl)
            land = (pre > q) | (
                (pre == q) & ~skip & ((vl > 0) | (seq > sh.iseq))
            )
            if not land.any():
                continue
            j = int(np.argmax(land))
            # The landed row's target coordinate can precede this
            # shard: store at the OWNER shard's storage end then (the
            # walk guarantees every shard in between is rowless).
            c_global = int(sh.anchor[j]) + int(bases[e]) - (
                int(pre[j]) - q
            )
            if c_global >= bases[e]:
                sh._insert_row(
                    j, c_global - int(bases[e]), buf_start, ins_len,
                    seq, client, NOT_REMOVED, None,
                    self._props_row(prop_keys, prop_vals),
                )
            else:
                d, base = self._owner_of(c_global)
                own = self.shards[d]
                # Every non-landing row bounds the target coordinate
                # from below (c >= its anchor), so nothing can sit
                # between the owner's end and the landed row.
                assert j == 0 and all(
                    self.shards[f].n == 0 for f in range(d + 1, e)
                ), "rows between landing shard and owner"
                own._insert_row(
                    own.n, min(c_global - base, own.S), buf_start,
                    ins_len, seq, client, NOT_REMOVED, None,
                    self._props_row(prop_keys, prop_vals),
                )
            return
        # No landing row anywhere: append at the global storage end —
        # the shard owning the target settled coordinate stores it
        # (exact single-doc semantics: anchor = min(pos - delta, S)).
        total = int(off[-1] + vis[-1]) if self.D else 0
        if pos1 > total:
            self.error |= ERR_BAD_POS
        c = min(int(pos1 - delta.sum()), self.S_total)
        d, base = self._owner_of(c)
        own = self.shards[d]
        own._insert_row(
            own.n, min(c - base, own.S), buf_start, ins_len, seq,
            client, NOT_REMOVED, None,
            self._props_row(prop_keys, prop_vals),
        )

    def _apply_range(self, op_type, pos1, pos2, seq, ref_seq, client,
                     prop_keys, prop_vals) -> None:
        vis, delta, off = self._partials(ref_seq, client)
        total = int(off[-1] + vis[-1]) if self.D else 0
        if pos2 > total:
            self.error |= ERR_BAD_POS
        for d, sh in enumerate(self.shards):
            lo = max(int(pos1 - off[d]), 0)
            hi = min(int(pos2 - off[d]), int(vis[d]))
            if lo >= hi:
                continue
            sh._apply_range(op_type, lo, hi, seq, ref_seq, client,
                            prop_keys, prop_vals)
            self.error |= sh.error

    # ---------------------------------------------------------- fold

    def fold(self, msn: int) -> None:
        """Settle-merge: ENTIRELY shard-local (boundaries are derived,
        so a shard growing or shrinking needs no exchange)."""
        for sh in self.shards:
            sh.fold(msn)

    # ----------------------------------------------------- rebalance

    def rebalance(self) -> None:
        """Boundary segment exchange: split rows straddling the new
        even boundaries, then redistribute settled text and rows. (On
        a mesh: ppermute of boundary slices over ICI.)"""
        S_total = self.S_total
        new_bounds = np.linspace(0, S_total, self.D + 1).astype(int)
        # Split any span row straddling a new boundary at that
        # boundary (coordinate-space split: tail advances its anchor).
        base = 0
        for sh in self.shards:
            for b in new_bounds[1:-1]:
                lb = int(b) - base
                if lb <= 0 or lb >= sh.S:
                    continue
                is_span = sh._is_span()
                inside = (
                    is_span & (sh.anchor < lb)
                    & (sh.anchor + sh.length > lb)
                )
                if inside.any():
                    j = int(np.argmax(inside))
                    off_in = lb - int(sh.anchor[j])
                    sh._insert_row(
                        j + 1, lb, SETTLED_BASE + lb,
                        int(sh.length[j]) - off_in, sh.iseq[j],
                        sh.iclient[j], sh.rseq[j], sh.rcl[j].copy(),
                        sh.props[j].copy(),
                    )
                    sh.length[j] = off_in
            base += sh.S
        # Concatenate global state, then re-slice.
        g_text = np.concatenate([sh.settled_text for sh in self.shards])
        g_props = np.concatenate([sh.settled_props for sh in self.shards])
        g_attr = np.concatenate([sh.settled_attr for sh in self.shards])
        rows = []
        base = 0
        for sh in self.shards:
            for i in range(sh.n):
                rows.append((
                    int(sh.anchor[i]) + base, int(sh.buf[i]),
                    int(sh.length[i]), int(sh.iseq[i]),
                    int(sh.iclient[i]), int(sh.rseq[i]),
                    sh.rcl[i].copy(), sh.props[i].copy(),
                    bool(sh._is_span()[i]),
                ))
            base += sh.S
        KR, KK = self.shards[0].KR, self.shards[0].KK
        errors = [sh.error for sh in self.shards]
        new_shards: List[OverlayDoc] = []
        for d in range(self.D):
            blo, bhi = int(new_bounds[d]), int(new_bounds[d + 1])
            doc = OverlayDoc(g_text[blo:bhi].copy(), KR, KK)
            doc.settled_props = g_props[blo:bhi].copy()
            doc.settled_attr = g_attr[blo:bhi].copy()
            self._wire_row_text(doc)
            new_shards.append(doc)
        # Rows: anchor in [B_d, B_{d+1}) -> shard d; anchor == S_total
        # -> last shard. Storage order is preserved (rows were read in
        # document order; anchors are globally non-decreasing).
        for (a, buf, ln, iseq, icl, rseq, rcl, props, is_span) in rows:
            d = min(
                int(np.searchsorted(new_bounds[1:], a, side="right")),
                self.D - 1,
            )
            doc = new_shards[d]
            la = a - int(new_bounds[d])
            doc._insert_row(
                doc.n, la, SETTLED_BASE + la if is_span else buf, ln,
                iseq, icl, rseq, rcl, props,
            )
        self.shards = new_shards
        for sh, e in zip(self.shards, errors):
            sh.error |= e

    # -------------------------------------------------------- replay

    def replay(self) -> None:
        s = self.stream
        n = len(s)
        for i in range(n):
            self.apply(
                int(s.op_type[i]), int(s.pos1[i]), int(s.pos2[i]),
                int(s.seq[i]), int(s.ref_seq[i]), int(s.client[i]),
                int(s.buf_start[i]), int(s.ins_len[i]),
                [int(s.prop_key[i])], [int(s.prop_val[i])],
            )
            if (i + 1) % self.fold_interval == 0 or i + 1 == n:
                self.fold(int(s.min_seq[i]))

    def check_errors(self) -> None:
        from ..ops.mergetree_kernel import raise_kernel_errors

        err = self.error
        for sh in self.shards:
            err |= sh.error
        raise_kernel_errors(err)

    def verify_invariants(self) -> None:
        for sh in self.shards:
            sh.verify_invariants()

    # -------------------------------------------------------- output

    def _doc_order(self):
        out = []
        for sh in self.shards:
            cursor = 0
            is_span = sh._is_span()
            for i in range(sh.n):
                a = int(sh.anchor[i])
                if a > cursor:
                    out.append((
                        sh.settled_text[cursor:a],
                        sh.settled_props[cursor:a],
                    ))
                    cursor = a
                if int(sh.rseq[i]) != NOT_REMOVED:
                    if is_span[i]:
                        cursor = a + int(sh.length[i])
                    continue
                ln = int(sh.length[i])
                if is_span[i]:
                    out.append((
                        sh.settled_text[a: a + ln],
                        merge_span_props(
                            sh.settled_props[a: a + ln], sh.props[i]
                        ),
                    ))
                    cursor = a + ln
                else:
                    row_p = sh.props[i].copy()
                    row_p[row_p == PROP_DELETE] = PROP_ABSENT
                    out.append((
                        sh._row_text(i),
                        np.broadcast_to(row_p, (ln, sh.KK)),
                    ))
            if cursor < sh.S:
                out.append((
                    sh.settled_text[cursor:], sh.settled_props[cursor:]
                ))
        return out

    def get_text(self) -> str:
        return "".join(
            "".join(map(chr, t)) for t, _ in self._doc_order()
        )

    def annotated_spans(self) -> List[Tuple[str, Optional[dict]]]:
        spans: List[Tuple[str, Optional[dict]]] = []
        KK = self.shards[0].KK
        for text, props in self._doc_order():
            for j in range(len(text)):
                p = {
                    f"k{k}": int(props[j, k])
                    for k in range(KK)
                    if props[j, k] != PROP_ABSENT
                }
                spans.append((chr(int(text[j])), p or None))
        return spans

    def attribution_spans(self) -> List[Tuple[int, int]]:
        out: List[Tuple[int, int]] = []

        def push(arr):
            for k in np.asarray(arr).tolist():
                if out and out[-1][1] == k:
                    out[-1] = (out[-1][0] + 1, k)
                else:
                    out.append((1, k))

        for sh in self.shards:
            cursor = 0
            is_span = sh._is_span()
            for i in range(sh.n):
                a = int(sh.anchor[i])
                if a > cursor:
                    push(sh.settled_attr[cursor:a])
                    cursor = a
                if int(sh.rseq[i]) != NOT_REMOVED:
                    if is_span[i]:
                        cursor = a + int(sh.length[i])
                    continue
                ln = int(sh.length[i])
                if is_span[i]:
                    push(sh.settled_attr[a: a + ln])
                    cursor = a + ln
                else:
                    push(np.full(ln, int(sh.iseq[i]), np.int32))
            push(sh.settled_attr[cursor:])
        return out
