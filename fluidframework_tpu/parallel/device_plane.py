"""Device-placement plane: ONE 2-D ``docs × model`` mesh serving the
sequencer AND the summarizer folds (ROADMAP item 5).

Until this module the two device tenants scheduled blindly against
each other: PR 6's sequencer shards its ``[D, C]`` doc-slot pool over
a private 1-D docs mesh, while the summarizer's merge-tree folds
(PR 10/14) dispatch onto whatever the default device is. `DevicePlane`
owns one process-wide 2-D `jax.sharding.Mesh` over ``('docs',
'model')`` and hands each tenant a TYPED slice of it:

- **sequencer** — `seq_mesh(column)` returns a 1-D ``docs`` mesh over
  one *model column* of the device grid; every per-doc array keeps its
  `PartitionSpec('docs')` layout (`ops.sequencer_kernel
  .sharded_sequence_fn` unchanged), and the fabric's placement rule is
  one partition = one worker = one mesh slice: worker *k* orders its
  documents on column ``k % model`` while the folds span the plane, so
  ordering tenants tile the pool instead of contending for all of it.
- **summarizer folds** — `fold_sharding()` lays the stacked per-doc
  fold inputs over the WHOLE plane: the stacked doc axis tiles
  ``('docs', 'model')`` (the overlay-pallas fold backend,
  `core.overlay_fold` — one replica per plane cell), and the vmapped
  merge-tree fold shards its row/segment axis on ``'model'`` with
  `PartitionSpec('docs', 'model')` (`table_sharding`) — both tenants
  on one chip pool, no host round-trips between ordering and
  summarization.

On CPU hosts the plane lands on XLA's forced virtual host devices
exactly like `parallel.mesh` (the supervisor seams force
``docs*model`` devices into children); the code is identical on a
real TPU slice. Specs are strings — ``"2x2"`` = 2 docs × 2 model —
so they ride argv/env (`PLANE_ENV`) into farm children.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

__all__ = [
    "PLANE_ENV",
    "DevicePlane",
    "parse_plane_spec",
    "plane_column_of",
    "resolve_plane",
    "shared_plane",
]

# Process-wide plane spec (the supervisor child_env seam): "DxM".
PLANE_ENV = "FLUID_DEVICE_PLANE"


def parse_plane_spec(spec: Union[str, Tuple[int, int]]) -> Tuple[int, int]:
    """``"2x2"`` / ``(2, 2)`` → (docs, model). Loud on nonsense — a
    mis-parsed plane must not silently fall back to one device."""
    if isinstance(spec, tuple):
        d, m = spec
    else:
        parts = str(spec).lower().replace("*", "x").split("x")
        if len(parts) != 2:
            raise ValueError(
                f"device-plane spec {spec!r} is not 'DOCSxMODEL' "
                f"(e.g. '2x2', '4x2')"
            )
        d, m = parts
    d, m = int(d), int(m)
    if d < 1 or m < 1:
        raise ValueError(f"device-plane axes must be >= 1: {spec!r}")
    return d, m


class DevicePlane:
    """One 2-D ``('docs', 'model')`` mesh + its typed slices.

    Construction initializes jax (device discovery) — build planes
    through `shared_plane`/`resolve_plane` so every pool, role and
    bench in a process shares ONE plane object and therefore one jit
    cache per compiled fn (the `parallel.mesh.shared_docs_mesh`
    discipline, two axes now)."""

    def __init__(self, docs: int, model: int, devices=None):
        import numpy as np
        import jax

        self.docs = int(docs)
        self.model = int(model)
        n = self.docs * self.model
        devs = list(jax.devices()) if devices is None else list(devices)
        if len(devs) < n:
            # Validating an NxM plane on a host with fewer accelerator
            # devices: fall back to the CPU backend's forced virtual
            # host devices, exactly like parallel.mesh.make_docs_mesh.
            try:
                cpu = jax.devices("cpu")
            except RuntimeError:
                cpu = []
            if n <= len(cpu):
                devs = list(cpu)
            else:
                raise ValueError(
                    f"device plane {self.docs}x{self.model} needs {n} "
                    f"devices; {len(devs)} "
                    f"{devs[0].platform if devs else ''} and "
                    f"{len(cpu)} cpu present"
                )
        from jax.sharding import Mesh

        grid = np.asarray(devs[:n]).reshape(self.docs, self.model)
        self.mesh = Mesh(grid, ("docs", "model"))
        self._grid = grid
        self._seq_meshes: dict = {}

    # ------------------------------------------------------------- slices

    @property
    def size(self) -> int:
        return self.docs * self.model

    def seq_mesh(self, column: int = 0):
        """The sequencer's typed slice: a 1-D ``docs`` mesh over model
        column ``column % model`` of the plane — `deli_kernel.SeqPool`
        consumes it unchanged (PartitionSpec('docs') on every per-doc
        array). Cached per column so every pool on a column shares one
        compiled `sharded_sequence_fn`."""
        from jax.sharding import Mesh

        col = int(column) % self.model
        mesh = self._seq_meshes.get(col)
        if mesh is None:
            mesh = self._seq_meshes[col] = Mesh(
                self._grid[:, col], ("docs",)
            )
        return mesh

    def fold_spec(self):
        """PartitionSpec for a stacked fold's leading doc axis: the
        stack tiles the WHOLE plane (docs-major), so K stacked docs
        spread over every device of the pool."""
        from jax.sharding import PartitionSpec

        return PartitionSpec(("docs", "model"))

    def fold_sharding(self):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self.fold_spec())

    def table_sharding(self, extra_dims: int = 0):
        """NamedSharding for stacked ``[K, rows, ...]`` fold tables:
        doc axis on ``docs``, the row/segment axis on ``model``
        (the vmapped merge-tree fold's layout — XLA partitions the
        row-axis gathers with model-axis collectives)."""
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(
            self.mesh,
            PartitionSpec("docs", "model", *([None] * extra_dims)),
        )

    def doc_sharding(self):
        """NamedSharding for stacked per-doc 1-D values ([K])."""
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self.fold_spec())

    # ------------------------------------------------------------ surface

    def spec(self) -> str:
        return f"{self.docs}x{self.model}"

    def describe(self) -> dict:
        devs = self._grid.reshape(-1)
        return {
            "docs": self.docs,
            "model": self.model,
            "devices": int(self.size),
            "platform": devs[0].platform if len(devs) else "none",
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"DevicePlane({self.spec()!r})"


_PLANE_CACHE: dict = {}


def shared_plane(docs: int, model: int) -> DevicePlane:
    """The process-wide cached plane for (docs, model) — every caller
    shares ONE mesh object so jit caches keyed on the mesh hit across
    pools/roles/benches instead of re-tracing per instance."""
    key = (int(docs), int(model))
    plane = _PLANE_CACHE.get(key)
    if plane is None:
        plane = _PLANE_CACHE[key] = DevicePlane(*key)
    return plane


def resolve_plane(
    plane: Union[None, str, Tuple[int, int], DevicePlane],
    env: bool = False,
) -> Optional[DevicePlane]:
    """The seam resolver every ``device_plane=`` parameter funnels
    through: DevicePlane passes through, specs resolve via the shared
    cache, None consults `PLANE_ENV` when ``env=True`` (farm children
    inherit the supervisor's plane without per-role argv plumbing)."""
    if plane is None and env:
        import os

        plane = os.environ.get(PLANE_ENV) or None
    if plane is None:
        return None
    if isinstance(plane, DevicePlane):
        return plane
    return shared_plane(*parse_plane_spec(plane))


def plane_column_of(key, model: int) -> int:
    """Deterministic model-column assignment for a partition/worker
    key: ints map round-robin, strings hash (crc32, the fabric's
    stable doc-hash discipline) — one partition = one worker = one
    mesh slice, stable across restarts."""
    if isinstance(key, int):
        return key % max(1, model)
    import zlib

    return zlib.crc32(str(key).encode()) % max(1, model)
