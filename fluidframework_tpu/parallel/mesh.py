"""Document-sharded execution over a `jax.sharding.Mesh`.

Documents are embarrassingly parallel (the reference partitions Kafka
topics by document id and runs one deli sequencer per partition —
SURVEY.md §2.6 row 1). Here that becomes: every per-document state
array gets a leading `docs` axis laid out across the mesh, the merge
kernel runs as one SPMD computation, and the only cross-device traffic
is tiny reductions (global MSN = min, error flags = bitwise-or) that
XLA lowers to ICI collectives.

On a CPU host this runs over virtual devices
(``--xla_force_host_platform_device_count``); the code is identical on
a real multi-chip TPU slice.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.mergetree_kernel import OpBatch, SegmentTable, apply_op_batch
from ..utils.jax_compat import shard_map_compat


def make_docs_mesh(n_devices: Optional[int] = None, axis: str = "docs") -> Mesh:
    """A 1-D mesh over the first `n_devices` devices (default: all).

    If the default backend has fewer than `n_devices` (e.g. one real
    TPU chip while validating an 8-way sharding), falls back to the
    host CPU backend, which provides
    ``--xla_force_host_platform_device_count`` virtual devices."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            try:
                cpu = jax.devices("cpu")
            except RuntimeError:
                cpu = []
            if n_devices <= len(cpu):
                devs = cpu
            else:
                raise ValueError(
                    f"requested {n_devices} devices, only {len(devs)} "
                    f"{devs[0].platform if devs else ''} and {len(cpu)} cpu present"
                )
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


_MESH_CACHE: dict = {}


def shared_docs_mesh(n_devices: Optional[int] = None,
                     axis: str = "docs") -> Mesh:
    """The process-wide cached form of `make_docs_mesh`: every caller
    asking for the same (n_devices, axis) shares ONE Mesh object, so
    jit caches keyed on the mesh hit across pools/benches instead of
    re-tracing per instance (and repeated bench runs in one process
    pay compilation once)."""
    key = (n_devices, axis)
    mesh = _MESH_CACHE.get(key)
    if mesh is None:
        mesh = _MESH_CACHE[key] = make_docs_mesh(n_devices, axis)
    return mesh


def docs_sharding(mesh: Mesh, axis: str = "docs") -> NamedSharding:
    """Shard the leading (document) axis across the mesh."""
    return NamedSharding(mesh, P(axis))


def replicate_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_tables(tables: SegmentTable, mesh: Mesh, axis: str = "docs") -> SegmentTable:
    """Place a batched (leading docs axis) SegmentTable onto the mesh."""
    sh = docs_sharding(mesh, axis)
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tables)


def sharded_overlay_replay(
    mesh: Mesh, chunk: int, interpret: bool = False, axis: str = "docs"
):
    """Compile the doc-sharded OVERLAY fused replay for `mesh` — the
    one-document-per-device form of `sharded_overlay_replay_multi`
    (which this delegates to; pass a leading docs axis equal to
    ``mesh.size``)."""
    return sharded_overlay_replay_multi(mesh, chunk, interpret, axis)


def sharded_overlay_replay_multi(
    mesh: Mesh, chunk: int, interpret: bool = False, axis: str = "docs"
):
    """The flagship overlay replay with MULTIPLE documents per device:
    the leading docs axis is ``mesh.size * docs_per_device`` and
    shards across the mesh; inside `shard_map` each device runs its
    local documents SERIALLY through the whole fused replay
    (`lax.map` — exactly the per-partition deli model: one sequencer/
    replayer instance working through its partition's documents,
    lambdas-driver/src/document-router/), then the fleet min-reduces
    the applied MSN and or-combines error bits over ICI.

    Same signature/returns as `sharded_overlay_replay`; the leading
    axis may be any multiple of ``mesh.size``.
    """
    from ..ops.overlay_pallas import OverlayTable, replay_fused

    docs = P(axis)

    def local_replay(tables, ops, logs, counts, msns):
        def one(args):
            t, o, log, cnt, msn = args
            return replay_fused(t, o, log, cnt, msn, chunk, interpret)

        t, log, cnt, cursor = jax.lax.map(
            one, (tables, ops, logs, counts, msns)
        )
        gmsn = jax.lax.pmin(jnp.min(msns[:, -1]), axis)
        bits = jnp.arange(31, dtype=jnp.int32)
        local_err = jnp.max((t.error[:, None] >> bits) & 1, axis=0)
        err = jax.lax.pmax(local_err, axis)
        gerr = jnp.sum(err << bits)
        return t, log, cnt, cursor, gmsn, gerr

    table_specs = OverlayTable(
        n_rows=docs, anchor=docs, buf_start=docs, length=docs,
        ins_seq=docs, ins_client=docs, rem_seq=docs, rem_clients=docs,
        props=docs, settled_len=docs, error=docs,
    )
    op_specs = OpBatch(
        op_type=docs, pos1=docs, pos2=docs, seq=docs, ref_seq=docs,
        client=docs, buf_start=docs, ins_len=docs, prop_keys=docs,
        prop_vals=docs,
    )
    step = shard_map_compat(
        local_replay,
        mesh=mesh,
        in_specs=(table_specs, op_specs, docs, docs, docs),
        out_specs=(table_specs, docs, docs, docs, P(), P()),
        check=False,
    )
    return jax.jit(step)


def sharded_pipeline_step(mesh: Mesh, axis: str = "docs"):
    """Compile the full multi-document op-application step for `mesh`.

    The step is the SPMD form of one ordering-service tick (SURVEY.md
    §3.2-3.3): each document applies its chunk of the totally ordered
    stream (vmapped merge kernel), then the fleet reduces a global
    minimum sequence number (the deli MSN min-reduce,
    server/.../deli/clientSeqManager.ts:22 — here an ICI collective
    inserted by XLA) and or-combines error flags.

    Returns a jitted ``step(tables, ops, doc_min_seqs) ->
    (tables, global_min_seq, error)`` with document-sharded in/out
    shardings.
    """
    docs = docs_sharding(mesh, axis)
    repl = replicate_sharding(mesh)

    def step(tables: SegmentTable, ops: OpBatch, doc_min_seqs: jnp.ndarray):
        new_tables = jax.vmap(apply_op_batch)(tables, ops)
        # Cross-document reductions: XLA lowers these to all-reduce
        # over the docs mesh axis (ICI), the TPU-native form of the
        # reference's cross-partition MSN bookkeeping.
        global_min_seq = jnp.min(doc_min_seqs)
        # Bitwise-or of the per-doc error flags, expressed as a per-bit
        # max-reduce (some collective backends lack an integer or-reduce).
        bits = jnp.arange(31, dtype=jnp.int32)
        per_bit = (new_tables.error[:, None] >> bits[None, :]) & 1
        error = jnp.sum(jnp.max(per_bit, axis=0) << bits)
        return new_tables, global_min_seq, error

    table_shardings = SegmentTable(
        n_rows=docs, buf_start=docs, length=docs, ins_seq=docs,
        ins_client=docs, rem_seq=docs, rem_clients=docs, props=docs,
        error=docs,
    )
    op_shardings = OpBatch(
        op_type=docs, pos1=docs, pos2=docs, seq=docs, ref_seq=docs,
        client=docs, buf_start=docs, ins_len=docs, prop_keys=docs,
        prop_vals=docs,
    )
    return jax.jit(
        step,
        in_shardings=(table_shardings, op_shardings, docs),
        out_shardings=(table_shardings, repl, repl),
    )
