"""Multi-chip parallelism: device meshes and sharded op pipelines.

The reference scales horizontally by partitioning *documents* across
Kafka partitions and deli instances (SURVEY.md §2.6: document = shard
unit, server/routerlicious/packages/lambdas-driver/src/document-router).
The TPU-native equivalent is an SPMD mesh: document state (segment
tables) and op batches carry a leading `docs` axis sharded across
devices; cross-document reductions (fleet MSN, error flags) ride ICI
collectives inserted by XLA.
"""

from .device_plane import (
    DevicePlane,
    parse_plane_spec,
    plane_column_of,
    resolve_plane,
    shared_plane,
)
from .mesh import (
    docs_sharding,
    make_docs_mesh,
    replicate_sharding,
    shared_docs_mesh,
    sharded_overlay_replay,
    sharded_overlay_replay_multi,
    sharded_pipeline_step,
    shard_tables,
)
from .seqshard import run_sequence_sharded, sequence_sharded_replay
from .seqshard_ref import SeqShardedOverlay

__all__ = [
    "DevicePlane",
    "parse_plane_spec",
    "plane_column_of",
    "resolve_plane",
    "shared_plane",
    "make_docs_mesh",
    "shared_docs_mesh",
    "docs_sharding",
    "replicate_sharding",
    "shard_tables",
    "sharded_overlay_replay",
    "sharded_overlay_replay_multi",
    "sharded_pipeline_step",
    "sequence_sharded_replay",
    "run_sequence_sharded",
    "SeqShardedOverlay",
]
