"""Protocol layer (L0): wire types shared by client and server.

Mirrors the roles of the reference's `common/lib/protocol-definitions`
(`src/protocol.ts`, `src/summary.ts`, `src/clients.ts`) without copying
its shape byte-for-byte: Python dataclasses for host-side plumbing plus
integer encodings chosen so op batches lower directly into int32 arrays
for the TPU kernels.
"""

from .constants import (
    UNASSIGNED_SEQ,
    UNIVERSAL_SEQ,
    TREE_MAINT_SEQ,
    NON_COLLAB_CLIENT,
    NO_CLIENT,
)
from .messages import (
    MessageType,
    DocumentMessage,
    SequencedMessage,
    NackMessage,
    SignalMessage,
)
from .mergetree_ops import (
    MergeTreeDeltaType,
    InsertOp,
    RemoveOp,
    AnnotateOp,
    GroupOp,
    MergeTreeOp,
    op_to_json,
    op_from_json,
)

__all__ = [
    "UNASSIGNED_SEQ",
    "UNIVERSAL_SEQ",
    "TREE_MAINT_SEQ",
    "NON_COLLAB_CLIENT",
    "NO_CLIENT",
    "MessageType",
    "DocumentMessage",
    "SequencedMessage",
    "NackMessage",
    "SignalMessage",
    "MergeTreeDeltaType",
    "InsertOp",
    "RemoveOp",
    "AnnotateOp",
    "GroupOp",
    "MergeTreeOp",
    "op_to_json",
    "op_from_json",
]
