"""Sentinel sequence numbers and client ids.

Reference: packages/dds/merge-tree/src/constants.ts:11-15. The values are
kept identical so recorded op streams and snapshots from the reference
replay bit-identically.
"""

# An op/segment that has been applied locally but not yet sequenced by the
# ordering service.
UNASSIGNED_SEQ = -1

# Applies to every perspective: content present "from the beginning"
# (e.g. segments loaded from a summary, or edits made outside
# collaboration).
UNIVERSAL_SEQ = 0

# Internal structural maintenance (segment splits for interval
# boundaries); never wins a tie-break.
TREE_MAINT_SEQ = -2

# Client id used when not collaborating.
NON_COLLAB_CLIENT = -2

# "No client" marker for int32 tables (removing client slots, etc.).
NO_CLIENT = -3

# Provisional local identity for a rehydrating session applying
# stashed ops before its first server connection assigns a real
# client id (the reference's applyStashedOp runs on a container that
# is not yet connected). Replaced — and pending segments re-stamped —
# by the reconnect/resubmit path on connect.
PROVISIONAL_CLIENT = -4

# Effective-sequence-number encoding used by tie-breaks
# (reference: mergeTree.ts:1719 breakTie). A *new* local pending op
# compares as +inf; an *existing* local pending segment as +inf - 1.
# For the int32 kernels we use INT32_MAX / INT32_MAX - 1.
INT32_MAX = 2**31 - 1
EFF_SEQ_NEW_LOCAL = INT32_MAX
EFF_SEQ_EXISTING_LOCAL = INT32_MAX - 1
