"""Merge-tree (sequence CRDT) op schema.

Mirrors the op vocabulary of reference
packages/dds/merge-tree/src/ops.ts:43 (INSERT / REMOVE / ANNOTATE /
GROUP) with a JSON encoding compatible in spirit (pos1/pos2/seg/props)
plus a flat integer view used to lower op batches into the TPU kernels.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional, Union


class MergeTreeDeltaType(enum.IntEnum):
    # Values match reference ops.ts:43 so recorded streams replay as-is.
    INSERT = 0
    REMOVE = 1
    ANNOTATE = 2
    GROUP = 3


@dataclass
class InsertOp:
    pos: int
    text: str = ""
    # Marker/atomic-segment payload (non-text DDSes reuse the sequence
    # kernel with opaque items, e.g. SharedMatrix permutation vectors).
    seg: Any = None
    props: Optional[dict] = None
    type: MergeTreeDeltaType = field(default=MergeTreeDeltaType.INSERT, init=False)


@dataclass
class RemoveOp:
    start: int
    end: int
    type: MergeTreeDeltaType = field(default=MergeTreeDeltaType.REMOVE, init=False)


@dataclass
class AnnotateOp:
    start: int
    end: int
    props: dict = field(default_factory=dict)
    type: MergeTreeDeltaType = field(default=MergeTreeDeltaType.ANNOTATE, init=False)


@dataclass
class GroupOp:
    ops: list = field(default_factory=list)
    type: MergeTreeDeltaType = field(default=MergeTreeDeltaType.GROUP, init=False)


MergeTreeOp = Union[InsertOp, RemoveOp, AnnotateOp, GroupOp]


def op_to_json(op: MergeTreeOp) -> dict:
    """Encode an op in a reference-compatible JSON shape.

    Reference wire shape: {type, pos1, pos2?, seg?, props?} (ops.ts
    IMergeTreeInsertMsg / IMergeTreeRemoveMsg / IMergeTreeAnnotateMsg).
    """
    if isinstance(op, InsertOp):
        out = {"type": int(MergeTreeDeltaType.INSERT), "pos1": op.pos}
        if op.seg is not None:
            out["seg"] = op.seg
        else:
            out["seg"] = op.text
        if op.props:
            out["props"] = op.props
        return out
    if isinstance(op, RemoveOp):
        return {"type": int(MergeTreeDeltaType.REMOVE), "pos1": op.start, "pos2": op.end}
    if isinstance(op, AnnotateOp):
        return {
            "type": int(MergeTreeDeltaType.ANNOTATE),
            "pos1": op.start,
            "pos2": op.end,
            "props": op.props,
        }
    if isinstance(op, GroupOp):
        return {"type": int(MergeTreeDeltaType.GROUP), "ops": [op_to_json(o) for o in op.ops]}
    raise TypeError(f"unknown op {op!r}")


def op_from_json(data: dict) -> MergeTreeOp:
    t = data["type"]
    if t == MergeTreeDeltaType.INSERT:
        seg = data.get("seg")
        if isinstance(seg, str):
            return InsertOp(pos=data["pos1"], text=seg, props=data.get("props"))
        return InsertOp(pos=data["pos1"], seg=seg, props=data.get("props"))
    if t == MergeTreeDeltaType.REMOVE:
        return RemoveOp(start=data["pos1"], end=data["pos2"])
    if t == MergeTreeDeltaType.ANNOTATE:
        return AnnotateOp(start=data["pos1"], end=data["pos2"], props=data["props"])
    if t == MergeTreeDeltaType.GROUP:
        return GroupOp(ops=[op_from_json(o) for o in data["ops"]])
    raise ValueError(f"unknown op type {t}")
