"""Quorum and protocol-op handling (shared by client and server).

Mirrors the reference's `protocol-base` package (shared the same way:
server/routerlicious/packages/protocol-base, used by both the loader's
protocol state and scribe): `QuorumClients` (quorum.ts:60) tracks the
connected-client set; `QuorumProposals` (quorum.ts:142) tracks
proposals, which commit when the MSN passes the proposal's sequence
number (every connected client has seen it); `ProtocolOpHandler`
(protocol.ts:68, processMessage :109) folds the protocol message types
(join/leave/propose) into that state.

The canonical use is the "code" proposal (which runtime package a
container runs), but any key/value can be proposed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.events import EventEmitter
from .messages import MessageType, SequencedMessage


@dataclass
class QuorumClient:
    client_id: int
    joined_seq: int
    detail: Any = None  # IClient payload (user identity, capabilities)


@dataclass
class _Proposal:
    key: str
    value: Any
    seq: int  # sequence number of the propose message
    proposer: int


class QuorumClients(EventEmitter):
    """Connected-client set keyed by client id (quorum.ts:60)."""

    def __init__(self):
        super().__init__()
        self.members: Dict[int, QuorumClient] = {}

    def add(self, client_id: int, joined_seq: int, detail: Any = None) -> None:
        self.members[client_id] = QuorumClient(client_id, joined_seq, detail)
        self.emit("addMember", client_id)

    def remove(self, client_id: int) -> None:
        if self.members.pop(client_id, None) is not None:
            self.emit("removeMember", client_id)

    def oldest(self) -> Optional[QuorumClient]:
        """Lowest join seq — the basis of summarizer election
        (OrderedClientElection)."""
        if not self.members:
            return None
        return min(self.members.values(), key=lambda c: (c.joined_seq, c.client_id))

    def __contains__(self, client_id: int) -> bool:
        return client_id in self.members

    def __len__(self) -> int:
        return len(self.members)


class QuorumProposals(EventEmitter):
    """Pending + committed proposals (quorum.ts:142). A proposal
    commits when the MSN reaches its sequence number."""

    def __init__(self):
        super().__init__()
        self.pending: List[_Proposal] = []
        self.values: Dict[str, Tuple[Any, int]] = {}  # key -> (value, commit seq)

    def add(self, key: str, value: Any, seq: int, proposer: int) -> None:
        self.pending.append(_Proposal(key, value, seq, proposer))

    def update_msn(self, msn: int) -> None:
        ready = [p for p in self.pending if p.seq <= msn]
        if not ready:
            return
        self.pending = [p for p in self.pending if p.seq > msn]
        for p in ready:
            self.values[p.key] = (p.value, p.seq)
            self.emit("approveProposal", p.key, p.value, p.seq)

    def get(self, key: str) -> Any:
        entry = self.values.get(key)
        return entry[0] if entry else None


class ProtocolOpHandler:
    """Folds protocol messages into quorum state (protocol.ts:68)."""

    def __init__(self, current_seq: int = 0, min_seq: int = 0):
        self.quorum = QuorumClients()
        self.proposals = QuorumProposals()
        self.current_seq = current_seq
        self.min_seq = min_seq

    def process_data_op(self, seq: int, msn: int) -> None:
        """The plain-data-op tail of `process_message` (the dominant
        message type): advance seq/MSN, re-check proposal commitment
        only when the MSN moved. ONE owner of this invariant — the
        container runtime's hot path calls this instead of inlining."""
        self.current_seq = seq
        if msn > self.min_seq:
            self.min_seq = msn
            self.proposals.update_msn(msn)

    def process_message(self, msg: SequencedMessage) -> None:
        """protocol.ts:109 processMessage."""
        if msg.type == MessageType.CLIENT_JOIN:
            detail = None
            client_id = msg.contents
            if isinstance(msg.contents, dict):
                client_id = msg.contents.get("clientId", msg.client_id)
                detail = msg.contents.get("detail")
            self.quorum.add(client_id, msg.sequence_number, detail)
        elif msg.type == MessageType.CLIENT_LEAVE:
            client_id = msg.contents
            if isinstance(msg.contents, dict):
                client_id = msg.contents.get("clientId", msg.client_id)
            self.quorum.remove(client_id)
        elif msg.type == MessageType.PROPOSE:
            # Malformed proposals are ignored rather than poisoning the
            # op stream for every replica (a single bad message must
            # not halt processing).
            if (
                isinstance(msg.contents, dict)
                and "key" in msg.contents
                and "value" in msg.contents
            ):
                self.proposals.add(
                    msg.contents["key"], msg.contents["value"],
                    msg.sequence_number, msg.client_id,
                )
        self.current_seq = msg.sequence_number
        self.min_seq = max(self.min_seq, msg.minimum_sequence_number)
        self.proposals.update_msn(self.min_seq)

    # ------------------------------------------------------------ state

    def snapshot(self) -> dict:
        """Serializable protocol state (the .protocol summary subtree,
        blobs.ts/scribeHelper.ts roles)."""
        return {
            "sequenceNumber": self.current_seq,
            "minimumSequenceNumber": self.min_seq,
            "members": [
                [c.client_id, {"joined_seq": c.joined_seq, "detail": c.detail}]
                for c in self.quorum.members.values()
            ],
            "values": [[k, [v, s]] for k, (v, s) in self.proposals.values.items()],
            "proposals": [
                [p.seq, {"key": p.key, "value": p.value, "proposer": p.proposer}]
                for p in self.proposals.pending
            ],
        }

    @classmethod
    def from_snapshot(cls, data: dict) -> "ProtocolOpHandler":
        h = cls(data["sequenceNumber"], data["minimumSequenceNumber"])
        for cid, info in data["members"]:
            h.quorum.add(cid, info["joined_seq"], info["detail"])
        for k, (v, s) in data["values"]:
            h.proposals.values[k] = (v, s)
        for seq, p in data["proposals"]:
            h.proposals.add(p["key"], p["value"], seq, p["proposer"])
        return h
