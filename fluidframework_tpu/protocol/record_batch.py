"""Versioned columnar record-batch codec (the binary op-log wire form).

The pipeline's topics carried one `json.dumps` line per record, so the
batched deli kernel's device win drowned in per-record JSON encode/
decode (ROADMAP item (a)). This module is the storage-side fix: a
record BATCH is one length-prefixed, CRC-guarded, fence-stamped binary
frame whose raw-op fields (doc id, client id, client seq, ref seq, op
kind) are stored as columnar arrays with the payload blobs side by
side — so `server.deli_kernel` ingests a batch as numpy arrays with
zero per-record JSON decode, while legacy consumers decode records
lazily one batch at a time and see plain Python values.

Frame layout (versions 1 and 2, little-endian — the version byte is
per FRAME, so one file mixes both freely):

    magic "FRB1" | u8 version | u8 flags | u32 n_records
    | u32 payload_len | u32 crc32(payload) | i64 fence
    payload:
      u16 owner_len + owner utf-8           (fence stamp's owner)
      [u16 src_len + src utf-8]             (iff flags & FLAG_SRC: the
                                             frame-level ``inSrc`` tag
                                             — see below)
      u32 n_docs + (u16 len + utf-8) * n    (batch-local doc dictionary)
      u8  kind[n]        (K_* codes below)
      u8  type_code[n]   (MessageType table index; 255 = n/a)
      i32 doc_idx[n]
      i64 client[n] | client_seq[n] | ref_seq[n] | seq[n] | msn[n]
      i64 in_off[n]      (-1 = absent)
      u32 blob_off[n+1] + blob heap          (JSON bytes per record)

Schema per kind (records that don't fit a kind exactly ride
``K_GENERIC`` with the whole record as one JSON blob, so the codec is
lossless over arbitrary JSON values):

    K_RAW_OP     {"kind":"op","doc","client","clientSeq","refSeq",
                  "contents"}                blob = contents
    K_RAW_JOIN   {"kind":"join","doc","client"}
    K_RAW_LEAVE  {"kind":"leave","doc","client"}
    K_RAW_BOXCAR {"kind":"boxcar","doc","client","ops":[...]}
                  v1 blob = JSON [[clientSeq, refSeq, contents], ...]
                  v2 blob = NESTED binary (the codec-v2 rev):
                    u32 n_ops | i64 clientSeq[n] | i64 refSeq[n]
                    | u32 off[n+1] | per-op contents JSON heap
                  so a boxcar's per-op ints read as arrays and its
                  per-op contents slice out as raw blobs — no
                  once-per-boxcar JSON decode on ingest, no re-encode
                  when the sequenced ops are emitted (`boxcar()`).
    K_SEQ_OP     {"kind":"op","doc","seq","msn","client","clientSeq",
                  "refSeq","type","contents","inOff"} blob = contents
    K_NACK       {"kind":"nack","doc","client","clientSeq","code",
                  "reason","inOff"}  code rides the seq column,
                  blob = reason
    K_GENERIC    anything else        blob = full record

Raw kinds may ADDITIONALLY carry an ``inOff`` key (the supervised
ingress front door stamps its input offset onto every admitted
record — `server.ingress`): it rides the existing ``in_off`` column
(-1 = absent), so an admission-stamped submit keeps the columnar fast
path instead of falling to K_GENERIC.

The ``FLAG_SRC`` frame flag carries a frame-level ``inSrc`` string
(the elastic fabric's predecessor-drain tag, `server.shard_fabric`):
every record decoded out of a src-tagged frame gains ``"inSrc": src``
— one tag per frame instead of one generic-schema dict per record, so
a ranged role's pred drains keep the `encode_columns` emit fast path.

The EMIT half mirrors the ingest half: `ColumnarRecords` is a batch of
already-columnized records (flat int columns + a blob heap — what the
kernel deli's verdict gather produces), and `encode_columns` turns one
or more of them into a frame with zero per-record classification or
dict building. `encode_batch` accepts ColumnarRecords segments mixed
with plain records in one list, so a columnar producer's stray
dict-path records keep their stream position.

The codec is pure (no I/O, no fencing): `server.columnar_log` owns the
topic semantics (torn-tail safety, fence gating, offsets). Codec
throughput metrics (`codec_encode_*` / `codec_decode_*` /
`codec_encode_columns_total`) report through `utils.metrics`;
`tools/metrics_report.py` renders them.
"""

from __future__ import annotations

import json
import struct
import time
import zlib
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .messages import MessageType

__all__ = [
    "ColumnarRecords",
    "DEFAULT_VERSION",
    "FLAG_SRC",
    "HEADER",
    "JsonBlob",
    "K_GENERIC",
    "K_NACK",
    "K_RAW_BOXCAR",
    "K_RAW_JOIN",
    "K_RAW_LEAVE",
    "K_RAW_OP",
    "K_SEQ_OP",
    "MAGIC",
    "MAX_BATCH_BYTES",
    "MAX_RESYNC_CANDIDATES",
    "RecordBatch",
    "SCHEMA_VERSION",
    "SCHEMA_VERSIONS",
    "count_records",
    "decode_batch",
    "encode_batch",
    "encode_columns",
    "iter_units",
    "mask_runs",
]

MAGIC = b"FRB1"
SCHEMA_VERSION = 1
SCHEMA_VERSION_2 = 2
SCHEMA_VERSIONS = (1, 2)
# What new frames are written as. v2 only changes the K_RAW_BOXCAR blob
# layout (nested binary offsets instead of a JSON list), and the
# version byte is per frame, so v1 and v2 frames coexist in one file —
# upgrades need no migration, downgrades only a drained topic (like the
# json⇄columnar rule, one rung smaller).
DEFAULT_VERSION = 2
HEADER = struct.Struct("<4sBBIIIq")  # magic, ver, flags, n, plen, crc, fence
# Frame flag bits. FLAG_SRC: the payload carries a frame-level src
# string (after the owner) applied as ``inSrc`` to every decoded
# record. Flags ride the CRC preimage like every other header field.
FLAG_SRC = 0x01
_KNOWN_FLAGS = FLAG_SRC
MAX_BATCH_BYTES = 256 << 20  # sanity cap: junk that fakes the magic must
#                              not trigger a multi-GB allocation

# Record kinds (the `kind` column).
K_RAW_OP = 0
K_RAW_JOIN = 1
K_RAW_LEAVE = 2
K_RAW_BOXCAR = 3
K_SEQ_OP = 4
K_NACK = 5
K_GENERIC = 255

# Wire `type` strings <-> u8 codes (closed MessageType table; custom
# type strings fall back to K_GENERIC).
_TYPES: Tuple[str, ...] = tuple(t.value for t in MessageType)
_TYPE_CODE: Dict[str, int] = {t: i for i, t in enumerate(_TYPES)}
_NO_TYPE = 255

_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1

# Exact key sets the columnar kinds require (anything else -> generic).
# Raw kinds come in two flavors: the bare client-submit shape, and the
# same + "inOff" (the ingress front door's admission stamp, riding the
# existing in_off column).
_RAW_OP_KEYS = frozenset(("kind", "doc", "client", "clientSeq", "refSeq",
                          "contents"))
_RAW_OP_KEYS_OFF = _RAW_OP_KEYS | {"inOff"}
_RAW_MEMBER_KEYS = frozenset(("kind", "doc", "client"))
_RAW_MEMBER_KEYS_OFF = _RAW_MEMBER_KEYS | {"inOff"}
_RAW_BOXCAR_KEYS = frozenset(("kind", "doc", "client", "ops"))
_RAW_BOXCAR_KEYS_OFF = _RAW_BOXCAR_KEYS | {"inOff"}
_SEQ_OP_KEYS = frozenset(("kind", "doc", "seq", "msn", "client",
                          "clientSeq", "refSeq", "type", "contents",
                          "inOff"))
_NACK_KEYS = frozenset(("kind", "doc", "client", "clientSeq", "code",
                        "reason", "inOff"))


class JsonBlob:
    """Pre-encoded JSON bytes that decode lazily.

    The zero-copy pass-through handle: a consumer that re-emits a
    record's `contents` into another columnar topic hands the raw blob
    straight back to the encoder — no decode, no re-encode. Compares
    (and reprs) by VALUE, so differential/digest comparisons treat it
    as the plain value it encodes."""

    __slots__ = ("raw", "_val", "_decoded")

    def __init__(self, raw: bytes):
        self.raw = bytes(raw)
        self._val = None
        self._decoded = False

    @property
    def value(self) -> Any:
        if not self._decoded:
            self._val = json.loads(self.raw)
            self._decoded = True
        return self._val

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, JsonBlob):
            return self.value == other.value
        return self.value == other

    def __hash__(self):
        return hash(self.raw)

    def __repr__(self) -> str:
        return repr(self.value)


def _json_default(o: Any) -> Any:
    if isinstance(o, JsonBlob):
        return o.value  # a blob NESTED in a generic record: by value
    raise TypeError(
        f"Object of type {o.__class__.__name__} is not JSON serializable"
    )


def _dumps(v: Any) -> bytes:
    """JSON-encode one blob value; a top-level JsonBlob passes through
    raw (zero re-encode), a nested one — a pass-through `contents`
    inside a record that fell to K_GENERIC (extra keys, e.g. a wire
    "tr" trace) — serializes by value."""
    if isinstance(v, JsonBlob):
        return v.raw
    return json.dumps(v, separators=(",", ":"),
                      default=_json_default).encode()


def _is_i64(v: Any) -> bool:
    return type(v) is int and _I64_MIN <= v <= _I64_MAX


def _metrics(kind: str, records: int, nbytes: int, seconds: float) -> None:
    from ..utils.metrics import get_registry

    m = get_registry()
    m.counter(f"codec_{kind}_records_total", codec="columnar").inc(records)
    m.counter(f"codec_{kind}_bytes_total", codec="columnar").inc(nbytes)
    m.histogram(f"codec_{kind}_ms", codec="columnar").observe(
        seconds * 1000.0
    )


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------


_BOXCAR_OP_KEYS = frozenset(("clientSeq", "refSeq", "contents"))


def _classify(rec: Any) -> int:
    """The columnar kind for one record (K_GENERIC when it doesn't fit
    a schema exactly — the codec must round-trip arbitrary values)."""
    if not isinstance(rec, dict):
        return K_GENERIC
    kind = rec.get("kind")
    if not isinstance(rec.get("doc"), str):
        return K_GENERIC
    keys = rec.keys()  # dict_keys == set compares C-side, no new set
    if kind == "op":
        if (keys == _RAW_OP_KEYS
                or (keys == _RAW_OP_KEYS_OFF
                    and _is_i64(rec["inOff"]) and rec["inOff"] >= 0)) \
                and _is_i64(rec["client"]) \
                and _is_i64(rec["clientSeq"]) and _is_i64(rec["refSeq"]):
            return K_RAW_OP
        if keys == _SEQ_OP_KEYS and _is_i64(rec["client"]) \
                and _is_i64(rec["clientSeq"]) and _is_i64(rec["refSeq"]) \
                and _is_i64(rec["seq"]) and _is_i64(rec["msn"]) \
                and _is_i64(rec["inOff"]) \
                and rec["type"] in _TYPE_CODE:
            return K_SEQ_OP
        return K_GENERIC
    if kind == "join" and (keys == _RAW_MEMBER_KEYS
                           or (keys == _RAW_MEMBER_KEYS_OFF
                               and _is_i64(rec["inOff"]) and rec["inOff"] >= 0)) \
            and _is_i64(rec["client"]):
        return K_RAW_JOIN
    if kind == "leave" and (keys == _RAW_MEMBER_KEYS
                            or (keys == _RAW_MEMBER_KEYS_OFF
                                and _is_i64(rec["inOff"])
                                and rec["inOff"] >= 0)) \
            and _is_i64(rec["client"]):
        return K_RAW_LEAVE
    if kind == "boxcar" and (keys == _RAW_BOXCAR_KEYS
                             or (keys == _RAW_BOXCAR_KEYS_OFF
                                 and _is_i64(rec["inOff"]) and rec["inOff"] >= 0)) \
            and _is_i64(rec["client"]) and isinstance(rec["ops"], list):
        ok = all(
            isinstance(op, dict) and op.keys() == _BOXCAR_OP_KEYS
            and _is_i64(op["clientSeq"]) and _is_i64(op["refSeq"])
            for op in rec["ops"]
        )
        return K_RAW_BOXCAR if ok else K_GENERIC
    if kind == "nack" and keys == _NACK_KEYS and _is_i64(rec["client"]) \
            and _is_i64(rec["clientSeq"]) and _is_i64(rec["code"]) \
            and _is_i64(rec["inOff"]) and isinstance(rec["reason"], str):
        return K_NACK
    return K_GENERIC


# Per-kind revalidators for the homogeneous-run fast path: once a
# record's exact key set (and kind string) matched the previous
# record's, only the VALUE checks the _classify ladder would have run
# remain — the branch ladder itself is hoisted out of the run. Each
# entry mirrors its _classify branch exactly (the regression test
# compares frames against per-record classification).
def _rv_off(r):
    # Raw kinds' optional admission stamp: the key set already matched
    # the previous record's, so only the value check remains. MUST be
    # non-negative — the column encodes absence as -1, so a negative
    # value would silently drop the key on decode (lossless contract);
    # such records ride K_GENERIC instead.
    return "inOff" not in r or (_is_i64(r["inOff"]) and r["inOff"] >= 0)


def _rv_raw_op(r):
    return isinstance(r["doc"], str) and _is_i64(r["client"]) \
        and _is_i64(r["clientSeq"]) and _is_i64(r["refSeq"]) \
        and _rv_off(r)


def _rv_member(r):
    return isinstance(r["doc"], str) and _is_i64(r["client"]) \
        and _rv_off(r)


def _rv_boxcar(r):
    if not (isinstance(r["doc"], str) and _is_i64(r["client"])
            and isinstance(r["ops"], list) and _rv_off(r)):
        return False
    return all(
        isinstance(op, dict) and op.keys() == _BOXCAR_OP_KEYS
        and _is_i64(op["clientSeq"]) and _is_i64(op["refSeq"])
        for op in r["ops"]
    )


def _rv_seq_op(r):
    return isinstance(r["doc"], str) and _is_i64(r["client"]) \
        and _is_i64(r["clientSeq"]) and _is_i64(r["refSeq"]) \
        and _is_i64(r["seq"]) and _is_i64(r["msn"]) \
        and _is_i64(r["inOff"]) and r["type"] in _TYPE_CODE


def _rv_nack(r):
    return isinstance(r["doc"], str) and _is_i64(r["client"]) \
        and _is_i64(r["clientSeq"]) and _is_i64(r["code"]) \
        and _is_i64(r["inOff"]) and isinstance(r["reason"], str)


_REVALIDATE = {
    K_RAW_OP: _rv_raw_op,
    K_RAW_JOIN: _rv_member,
    K_RAW_LEAVE: _rv_member,
    K_RAW_BOXCAR: _rv_boxcar,
    K_SEQ_OP: _rv_seq_op,
    K_NACK: _rv_nack,
}

_BOX_HDR = struct.Struct("<I")


def _encode_boxcar_v2(ops: List[dict]) -> bytes:
    """The nested v2 K_RAW_BOXCAR blob: per-op ints as columns, per-op
    contents as raw slices of an inner heap — a boxcar rides through
    sequencing with its op blobs untouched."""
    n = len(ops)
    blobs = [_dumps(op["contents"]) for op in ops]
    cs = np.fromiter((op["clientSeq"] for op in ops), np.int64, n)
    rf = np.fromiter((op["refSeq"] for op in ops), np.int64, n)
    offs = np.zeros(n + 1, np.uint32)
    if n:
        offs[1:] = np.cumsum([len(b) for b in blobs])
    return b"".join([_BOX_HDR.pack(n), cs.tobytes(), rf.tobytes(),
                     offs.tobytes(), *blobs])


def _decode_boxcar_v2(blob) -> Tuple[np.ndarray, np.ndarray,
                                     np.ndarray, memoryview]:
    """(clientSeq[n], refSeq[n], off[n+1], contents heap) views over a
    nested v2 boxcar blob."""
    view = memoryview(blob)
    (n,) = _BOX_HDR.unpack_from(view, 0)
    pos = _BOX_HDR.size
    cs = np.frombuffer(view, "<i8", n, pos)
    pos += 8 * n
    rf = np.frombuffer(view, "<i8", n, pos)
    pos += 8 * n
    offs = np.frombuffer(view, "<u4", n + 1, pos)
    pos += 4 * (n + 1)
    return cs, rf, offs, view[pos:]


class ColumnarRecords:
    """A batch of PRE-COLUMNIZED records: the emit twin of the decoded
    `RecordBatch` (same columns, same blob-heap layout, a batch-local
    doc dictionary), built by producers that already hold verdict
    columns — the kernel deli's emission, the fused durable+broadcast
    hop's frame pass-through. `encode_columns`/`encode_batch` splice it
    into a frame with zero per-record work; `record(i)`/iteration
    decode lazily for dict-path consumers (recovery replay, tests).

    K_RAW_BOXCAR rows are rejected: their blob layout is
    frame-VERSION-dependent, so a pass-through segment carrying one
    could silently splice a v1 blob into a v2 frame. (Nothing emits
    boxcars post-sequencing — the deli unpacks them — so the
    restriction costs no real producer anything.)"""

    __slots__ = ("n", "docs", "kind", "type_code", "doc_idx", "client",
                 "client_seq", "ref_seq", "seq", "msn", "in_off",
                 "blob_off", "heap")

    # Segments never carry a frame-level src themselves — the tag is
    # applied at append time (`append_many(src=...)`); the class attr
    # keeps the `_decode_record` column protocol uniform.
    src: Optional[str] = None

    def __init__(self, docs: Sequence[str], kind, type_code, doc_idx,
                 client, client_seq, ref_seq, seq, msn, in_off,
                 blob_off, heap: bytes):
        self.kind = np.ascontiguousarray(kind, np.uint8)
        self.n = int(self.kind.shape[0])
        if np.any(self.kind == K_RAW_BOXCAR):
            raise ValueError(
                "K_RAW_BOXCAR cannot ride a pre-columnized segment "
                "(version-dependent blob layout)"
            )
        self.docs = list(docs)
        self.type_code = np.ascontiguousarray(type_code, np.uint8)
        self.doc_idx = np.ascontiguousarray(doc_idx, np.int32)
        self.client = np.ascontiguousarray(client, np.int64)
        self.client_seq = np.ascontiguousarray(client_seq, np.int64)
        self.ref_seq = np.ascontiguousarray(ref_seq, np.int64)
        self.seq = np.ascontiguousarray(seq, np.int64)
        self.msn = np.ascontiguousarray(msn, np.int64)
        self.in_off = np.ascontiguousarray(in_off, np.int64)
        self.blob_off = np.ascontiguousarray(blob_off, np.uint32)
        self.heap = bytes(heap)

    @classmethod
    def from_batch(cls, rb: "RecordBatch", rows,
                   in_off) -> "ColumnarRecords":
        """Slice `rows` of a decoded `RecordBatch` into an emit segment
        with fresh input offsets (`in_off`: scalar base or per-row
        array) — the zero-decode pass-through a 1:1 consumer (the fused
        durable+broadcast hop) re-emits frames with. Blob bytes copy
        span-wise: consecutive rows share one memcpy."""
        rows = np.ascontiguousarray(rows, np.int64)
        n = rows.shape[0]
        off = rb._blob_off
        lens = off[rows + 1].astype(np.int64) - off[rows]
        new_off = np.zeros(n + 1, np.uint32)
        if n:
            new_off[1:] = np.cumsum(lens)
        heap = _gather_spans(rb._heap, off, rows)
        io = np.broadcast_to(np.asarray(in_off, np.int64), (n,)) \
            if np.ndim(in_off) == 0 else np.asarray(in_off, np.int64)
        return cls(
            rb.docs, rb.kind[rows], rb.type_code[rows],
            rb.doc_idx[rows], rb.client[rows], rb.client_seq[rows],
            rb.ref_seq[rows], rb.seq[rows], rb.msn[rows], io,
            new_off, heap,
        )

    def __len__(self) -> int:
        return self.n

    def blob(self, i: int) -> bytes:
        return bytes(self.heap[self.blob_off[i]:self.blob_off[i + 1]])

    def record(self, i: int) -> Any:
        """Record `i` as a plain Python value (the dict-path view)."""
        return _decode_record(self, i, DEFAULT_VERSION)

    def __iter__(self):
        return (self.record(i) for i in range(self.n))

    def records(self) -> List[Any]:
        return [self.record(i) for i in range(self.n)]


def _gather_spans(heap, off, rows) -> bytes:
    """Concatenate `rows`' blob slices out of `heap`, one copy per
    CONSECUTIVE-row span (the all-kept fast path is a single memcpy)."""
    n = rows.shape[0]
    if n == 0:
        return b""
    breaks = np.flatnonzero(np.diff(rows) != 1)
    starts = np.concatenate(([0], breaks + 1))
    ends = np.concatenate((breaks + 1, [n]))
    parts = []
    for s, e in zip(starts.tolist(), ends.tolist()):
        parts.append(bytes(
            heap[off[rows[s]]:off[rows[e - 1] + 1]]
        ))
    return b"".join(parts)


def count_records(messages: Sequence[Any]) -> int:
    """Record count of a message list that may mix plain records and
    `ColumnarRecords` segments (what topic offsets advance by)."""
    n = 0
    for m in messages:
        n += m.n if isinstance(m, ColumnarRecords) else 1
    return n


def mask_runs(values) -> List[Tuple[Any, int, int]]:
    """Maximal constant runs of a 1-D array as ``[(value, lo, hi)]``
    (host-side numpy) — the span decomposition every columnar
    ingest/emit path shares: a homogeneous run vectorizes (one bulk
    ingest call, one verdict slice, one blob-heap memcpy), while
    category boundaries fall back to per-record handling without
    losing stream order. ONE definition so the run rule can never fork
    between the deli's columnar ingest, its verdict emission, and the
    fused durable+broadcast hop's frame pass-through."""
    v = np.asarray(values)
    n = v.shape[0]
    if n == 0:
        return []
    bounds = np.flatnonzero(np.diff(v.astype(np.int64))) + 1
    edges = [0, *bounds.tolist(), n]
    return [(v[lo].item(), lo, hi) for lo, hi in zip(edges, edges[1:])]


class _Part:
    """One assembled frame part: plain-loop columns or a spliced
    segment, in stream order."""

    __slots__ = ("docs", "kind", "type_code", "doc_idx", "i64",
                 "blob_off", "heap", "n", "from_columns")

    def __init__(self, docs, kind, type_code, doc_idx, i64, blob_off,
                 heap, n, from_columns):
        self.docs = docs
        self.kind = kind
        self.type_code = type_code
        self.doc_idx = doc_idx
        self.i64 = i64  # (6, n) int64: client/cseq/ref/seq/msn/inOff
        self.blob_off = blob_off  # (n+1,) uint32, part-local
        self.heap = heap
        self.n = n
        self.from_columns = from_columns


def _part_from_segment(seg: ColumnarRecords) -> _Part:
    i64 = np.empty((6, seg.n), np.int64)
    i64[0] = seg.client
    i64[1] = seg.client_seq
    i64[2] = seg.ref_seq
    i64[3] = seg.seq
    i64[4] = seg.msn
    i64[5] = seg.in_off
    return _Part(seg.docs, seg.kind, seg.type_code, seg.doc_idx, i64,
                 seg.blob_off, seg.heap, seg.n, from_columns=True)


def _assemble_frame(parts: List[_Part], fence: Optional[int],
                    owner: Optional[str], version: int,
                    src: Optional[str] = None) -> bytes:
    """Splice frame parts (doc dictionaries remapped VECTORIZED, blob
    heaps shifted as arrays) and wrap the header+CRC."""
    doc_ids: List[str] = []
    doc_of: Dict[str, int] = {}
    kind_a: List[np.ndarray] = []
    tc_a: List[np.ndarray] = []
    didx_a: List[np.ndarray] = []
    i64_a: List[np.ndarray] = []
    off_a: List[np.ndarray] = []
    heaps: List[bytes] = []
    n = 0
    heap_base = 0
    for p in parts:
        remap = np.empty(max(1, len(p.docs)), np.int32)
        for j, d in enumerate(p.docs):
            di = doc_of.get(d)
            if di is None:
                di = doc_of[d] = len(doc_ids)
                doc_ids.append(d)
            remap[j] = di
        kind_a.append(p.kind)
        tc_a.append(p.type_code)
        didx_a.append(remap[p.doc_idx] if len(p.docs) else p.doc_idx)
        i64_a.append(p.i64)
        off_a.append(p.blob_off[:-1].astype(np.uint32) + heap_base)
        heaps.append(p.heap)
        heap_base += int(p.blob_off[-1])
        n += p.n
    heap = b"".join(heaps)
    if len(parts) == 1:
        p = parts[0]
        kind_b = p.kind.tobytes()
        tc_b = tc_a[0].tobytes()
        didx_b = didx_a[0].tobytes()
        i64_b = p.i64.tobytes()
        offs = np.empty(n + 1, np.uint32)
        offs[:n] = off_a[0]
        offs[n] = heap_base
        offs_b = offs.tobytes()
    else:
        kind_b = np.concatenate(kind_a).tobytes() if parts else b""
        tc_b = np.concatenate(tc_a).tobytes() if parts else b""
        didx_b = np.concatenate(didx_a).tobytes() if parts else b""
        i64_b = (np.concatenate(i64_a, axis=1).tobytes()
                 if parts else b"")
        offs = np.empty(n + 1, np.uint32)
        if parts:
            offs[:n] = np.concatenate(off_a)
        offs[n] = heap_base
        offs_b = offs.tobytes()
    owner_b = (owner or "").encode()
    flags = 0
    src_parts: List[bytes] = []
    if src:
        flags |= FLAG_SRC
        src_b = src.encode()
        src_parts = [struct.pack("<H", len(src_b)), src_b]
    doc_parts = [struct.pack("<I", len(doc_ids))]
    for d in doc_ids:
        db = d.encode()
        doc_parts.append(struct.pack("<H", len(db)) + db)
    payload = b"".join([
        struct.pack("<H", len(owner_b)), owner_b, *src_parts,
        *doc_parts, kind_b, tc_b, didx_b, i64_b, offs_b, heap,
    ])
    if len(payload) > MAX_BATCH_BYTES:
        raise ValueError(f"record batch too large: {len(payload)} bytes")
    # The CRC covers the HEADER FIELDS (with the crc slot zeroed) as
    # well as the payload: a flipped record count, length or flag byte
    # would otherwise mis-frame a payload whose own CRC still matches.
    fence_i = int(fence or 0)
    hdr0 = HEADER.pack(MAGIC, version, flags, n, len(payload), 0, fence_i)
    crc = zlib.crc32(payload, zlib.crc32(hdr0))
    return HEADER.pack(
        MAGIC, version, flags, n, len(payload), crc, fence_i,
    ) + payload


def encode_columns(segments, fence: Optional[int] = None,
                   owner: Optional[str] = None,
                   version: Optional[int] = None,
                   src: Optional[str] = None) -> bytes:
    """One binary frame from pre-columnized records — the emit hot
    path: no per-record classification, no dict building, blob heaps
    spliced as whole byte runs. `segments` is one `ColumnarRecords` or
    a sequence of them (spliced in order). `src` stamps the
    frame-level ``inSrc`` tag (FLAG_SRC — every decoded record gains
    it), the pred-drain emit path's answer to per-record dict
    tagging."""
    t0 = time.perf_counter()
    ver = DEFAULT_VERSION if version is None else int(version)
    if ver not in SCHEMA_VERSIONS:
        raise ValueError(f"unknown record-batch version {ver}")
    if isinstance(segments, ColumnarRecords):
        segments = (segments,)
    parts = [_part_from_segment(s) for s in segments]
    frame = _assemble_frame(parts, fence, owner, ver, src=src)
    n = sum(p.n for p in parts)
    _metrics("encode", n, len(frame), time.perf_counter() - t0)
    if n:
        from ..utils.metrics import get_registry

        get_registry().counter(
            "codec_encode_columns_total", codec="columnar"
        ).inc(n)
    return frame


def encode_batch(records: Sequence[Any], fence: Optional[int] = None,
                 owner: Optional[str] = None,
                 version: Optional[int] = None,
                 src: Optional[str] = None) -> bytes:
    """One binary frame for `records` (arbitrary JSON values, plus
    `ColumnarRecords` segments spliced in stream order), stamped with
    the accepted (fence, owner). `version` picks the frame rev (the
    module default otherwise); only the K_RAW_BOXCAR blob layout
    differs between revs. `src` stamps the frame-level ``inSrc`` tag
    (see `encode_columns`); records that ALREADY carry an ``inSrc``
    key must not mix into a src frame (the frame tag would be
    ambiguous) — callers pick one mechanism per append."""
    if records and all(isinstance(r, ColumnarRecords) for r in records):
        # Segment-only batch (the columnar emit steady state: a fused
        # pass-through pump, a nack-free kernel pump): the pure-column
        # encoder, no per-record machinery at all.
        return encode_columns(records, fence=fence, owner=owner,
                              version=version, src=src)
    t0 = time.perf_counter()
    ver = DEFAULT_VERSION if version is None else int(version)
    if ver not in SCHEMA_VERSIONS:
        raise ValueError(f"unknown record-batch version {ver}")
    doc_ids: List[str] = []
    doc_of: Dict[str, int] = {}
    # Hot path: plain list appends per record, ONE numpy conversion per
    # column at the end (scalar ndarray stores cost ~10x a list append).
    kinds: List[int] = []
    type_codes: List[int] = []
    doc_idx: List[int] = []
    clients: List[int] = []
    cseqs: List[int] = []
    refs: List[int] = []
    seqs: List[int] = []
    msns: List[int] = []
    inoffs: List[int] = []
    blobs: List[bytes] = []
    blob_lens: List[int] = []
    parts: List[_Part] = []
    col_records = 0

    # One fused pass: the key-set comparison routes each record AND the
    # same lookups fill the columns (classification re-reads nothing).
    ka, ta, da, ca = (kinds.append, type_codes.append, doc_idx.append,
                      clients.append)
    qa, ra, sa, ma = (cseqs.append, refs.append, seqs.append,
                      msns.append)
    ia, ba, la = inoffs.append, blobs.append, blob_lens.append

    def flush_plain() -> None:
        # Close the current plain run into an ordered frame part
        # (segments must splice at their stream position).
        m = len(kinds)
        if not m:
            return
        i64 = np.array([clients, cseqs, refs, seqs, msns, inoffs],
                       np.int64)
        offs = np.zeros(m + 1, np.uint32)
        offs[1:] = np.cumsum(blob_lens)
        parts.append(_Part(
            doc_ids, np.array(kinds, np.uint8),
            np.array(type_codes, np.uint8),
            np.array(doc_idx, np.int32), i64, offs, b"".join(blobs),
            m, from_columns=False,
        ))
        for lst in (kinds, type_codes, doc_idx, clients, cseqs, refs,
                    seqs, msns, inoffs, blobs, blob_lens):
            lst.clear()
        # doc_ids/doc_of persist across plain runs: the dict remap in
        # _assemble_frame dedups identical ids anyway.

    def generic(rec):
        ka(K_GENERIC)
        ta(_NO_TYPE)
        da(0)
        ca(0)
        qa(0)
        ra(0)
        sa(0)
        ma(0)
        ia(-1)
        blob = _dumps(rec)
        ba(blob)
        la(len(blob))

    # Homogeneous-run classification hoist: consecutive records with
    # the SAME exact key set and kind string skip the _classify branch
    # ladder — only that kind's value checks rerun per record. Streams
    # are overwhelmingly single-schema runs (a deltas pump is K_SEQ_OP
    # wall-to-wall), so the ladder cost amortizes to once per run.
    prev_keys = None
    prev_kind_s = None
    prev_k = K_GENERIC
    prev_rv = None

    for rec in records:
        if isinstance(rec, ColumnarRecords):
            flush_plain()
            parts.append(_part_from_segment(rec))
            col_records += rec.n
            prev_keys = None
            continue
        if (type(rec) is dict and rec.keys() == prev_keys
                and rec.get("kind") == prev_kind_s):
            k = prev_k if prev_rv(rec) else K_GENERIC
        else:
            k = _classify(rec)
            if k != K_GENERIC and type(rec) is dict:
                prev_keys = rec.keys()
                prev_kind_s = rec.get("kind")
                prev_k = k
                prev_rv = _REVALIDATE[k]
            else:
                prev_keys = None
        if k == K_GENERIC:
            generic(rec)
            continue
        doc = rec["doc"]
        di = doc_of.get(doc)
        if di is None:
            di = doc_of[doc] = len(doc_ids)
            doc_ids.append(doc)
        ka(k)
        da(di)
        ca(rec["client"])
        if k == K_RAW_OP:
            qa(rec["clientSeq"])
            ra(rec["refSeq"])
            sa(0)
            ma(0)
            ia(rec.get("inOff", -1))
            ta(_NO_TYPE)
            blob = _dumps(rec["contents"])
        elif k == K_SEQ_OP:
            qa(rec["clientSeq"])
            ra(rec["refSeq"])
            sa(rec["seq"])
            ma(rec["msn"])
            ia(rec["inOff"])
            ta(_TYPE_CODE[rec["type"]])
            blob = _dumps(rec["contents"])
        elif k == K_NACK:
            qa(rec["clientSeq"])
            ra(0)
            sa(rec["code"])
            ma(0)
            ia(rec["inOff"])
            ta(_NO_TYPE)
            blob = _dumps(rec["reason"])
        else:
            qa(0)
            ra(0)
            sa(0)
            ma(0)
            ia(rec.get("inOff", -1))
            ta(_NO_TYPE)
            if k != K_RAW_BOXCAR:
                blob = b""
            elif ver >= SCHEMA_VERSION_2:
                blob = _encode_boxcar_v2(rec["ops"])
            else:
                blob = _dumps([
                    [op["clientSeq"], op["refSeq"], op["contents"]]
                    for op in rec["ops"]
                ])
        ba(blob)
        la(len(blob))

    flush_plain()
    frame = _assemble_frame(parts, fence, owner, ver, src=src)
    n = sum(p.n for p in parts)
    _metrics("encode", n, len(frame), time.perf_counter() - t0)
    if col_records:
        from ..utils.metrics import get_registry

        get_registry().counter(
            "codec_encode_columns_total", codec="columnar"
        ).inc(col_records)
    return frame


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------


class RecordBatch:
    """One decoded frame: columns up front, blobs/records lazily.

    `kind`/`type_code`/`doc_idx`/`client`/`client_seq`/`ref_seq`/
    `seq`/`msn`/`in_off` are numpy views over the payload — the
    zero-JSON ingest surface for the kernel deli. `records()` is the
    legacy path: full per-record decode into plain Python values.
    `version` is the frame's schema rev (it decides the K_RAW_BOXCAR
    blob layout `boxcar()` parses)."""

    __slots__ = ("n", "fence", "owner", "docs", "kind", "type_code",
                 "doc_idx", "client", "client_seq", "ref_seq", "seq",
                 "msn", "in_off", "_blob_off", "_heap", "_records",
                 "_frame_bytes", "version", "src")

    def __init__(self, n: int, fence: int, payload: memoryview,
                 version: int = SCHEMA_VERSION, flags: int = 0):
        self.n = n
        self.fence = fence
        self.version = version
        self._frame_bytes = HEADER.size + len(payload)
        pos = 0
        (olen,) = struct.unpack_from("<H", payload, pos)
        pos += 2
        self.owner = bytes(payload[pos:pos + olen]).decode() or None
        pos += olen
        self.src: Optional[str] = None
        if flags & FLAG_SRC:
            (slen,) = struct.unpack_from("<H", payload, pos)
            pos += 2
            self.src = bytes(payload[pos:pos + slen]).decode() or None
            pos += slen
        (ndocs,) = struct.unpack_from("<I", payload, pos)
        pos += 4
        docs: List[str] = []
        for _ in range(ndocs):
            (dlen,) = struct.unpack_from("<H", payload, pos)
            pos += 2
            docs.append(bytes(payload[pos:pos + dlen]).decode())
            pos += dlen
        self.docs = docs
        self.kind = np.frombuffer(payload, np.uint8, n, pos)
        pos += n
        self.type_code = np.frombuffer(payload, np.uint8, n, pos)
        pos += n
        self.doc_idx = np.frombuffer(payload, "<i4", n, pos)
        pos += 4 * n
        i64 = np.frombuffer(payload, "<i8", 6 * n, pos).reshape(6, n)
        pos += 48 * n
        (self.client, self.client_seq, self.ref_seq,
         self.seq, self.msn, self.in_off) = i64
        self._blob_off = np.frombuffer(payload, "<u4", n + 1, pos)
        pos += 4 * (n + 1)
        self._heap = payload[pos:]
        self._records: Optional[List[Any]] = None

    def blob(self, i: int) -> bytes:
        """Record `i`'s raw JSON blob bytes (contents / boxcar ops /
        reason / whole generic record, per kind)."""
        return bytes(self._heap[self._blob_off[i]:self._blob_off[i + 1]])

    def boxcar(self, i: int) -> List[Tuple[int, int, Any]]:
        """Record `i`'s boxcar ops as ``[(clientSeq, refSeq,
        contents), ...]``. On a v2 frame `contents` is a lazy
        `JsonBlob` sliced straight off the nested heap — the
        pass-through handle a columnar emitter hands back untouched;
        on v1 it is the decoded plain value (one JSON parse per
        boxcar, the pre-rev cost)."""
        if self.version >= SCHEMA_VERSION_2:
            view = self._heap[self._blob_off[i]:self._blob_off[i + 1]]
            cs, rf, offs, heap = _decode_boxcar_v2(view)
            return [
                (int(cs[k]), int(rf[k]),
                 JsonBlob(bytes(heap[offs[k]:offs[k + 1]])))
                for k in range(cs.shape[0])
            ]
        return [(cs, rf, c) for cs, rf, c in json.loads(self.blob(i))]

    def record(self, i: int) -> Any:
        """Record `i` as a plain Python value (lazy, uncached)."""
        return _decode_record(self, i, self.version)

    def records(self) -> List[Any]:
        """All records, decoded once and cached (the legacy-consumer
        path: one batch at a time, plain values)."""
        if self._records is None:
            t0 = time.perf_counter()
            self._records = [self.record(i) for i in range(self.n)]
            _metrics("decode", self.n, self._frame_bytes,
                     time.perf_counter() - t0)
        return self._records


def _decode_record(obj, i: int, version: int) -> Any:
    """One record as a plain Python value, off any column holder
    (`RecordBatch` or `ColumnarRecords` — same column protocol). A
    frame-level `src` (FLAG_SRC) tags every decoded dict with
    ``inSrc``, reproducing the dict-path tagging exactly."""
    k = int(obj.kind[i])
    if k == K_GENERIC:
        rec = json.loads(obj.blob(i))
    else:
        doc = obj.docs[int(obj.doc_idx[i])]
        client = int(obj.client[i])
        if k == K_RAW_OP:
            rec = {"kind": "op", "doc": doc, "client": client,
                   "clientSeq": int(obj.client_seq[i]),
                   "refSeq": int(obj.ref_seq[i]),
                   "contents": json.loads(obj.blob(i))}
            if obj.in_off[i] >= 0:
                rec["inOff"] = int(obj.in_off[i])
        elif k == K_RAW_JOIN:
            rec = {"kind": "join", "doc": doc, "client": client}
            if obj.in_off[i] >= 0:
                rec["inOff"] = int(obj.in_off[i])
        elif k == K_RAW_LEAVE:
            rec = {"kind": "leave", "doc": doc, "client": client}
            if obj.in_off[i] >= 0:
                rec["inOff"] = int(obj.in_off[i])
        elif k == K_RAW_BOXCAR:
            rec = {"kind": "boxcar", "doc": doc, "client": client,
                   "ops": [
                       {"clientSeq": cs, "refSeq": rf,
                        "contents": c.value if isinstance(c, JsonBlob)
                        else c}
                       for cs, rf, c in obj.boxcar(i)
                   ]}
            if obj.in_off[i] >= 0:
                rec["inOff"] = int(obj.in_off[i])
        elif k == K_SEQ_OP:
            rec = {"kind": "op", "doc": doc,
                   "seq": int(obj.seq[i]), "msn": int(obj.msn[i]),
                   "client": client,
                   "clientSeq": int(obj.client_seq[i]),
                   "refSeq": int(obj.ref_seq[i]),
                   "type": _TYPES[int(obj.type_code[i])],
                   "contents": json.loads(obj.blob(i)),
                   "inOff": int(obj.in_off[i])}
        else:
            rec = {"kind": "nack", "doc": doc, "client": client,
                   "clientSeq": int(obj.client_seq[i]),
                   "code": int(obj.seq[i]),
                   "reason": json.loads(obj.blob(i)),
                   "inOff": int(obj.in_off[i])}
    src = getattr(obj, "src", None)
    if src and isinstance(rec, dict) and "inSrc" not in rec:
        rec["inSrc"] = src
    return rec


# Header-corruption resync probe budget: how many MAGIC candidates one
# `_resync_scan` call validates before sealing the probed region as
# junk and letting the scan continue from its far edge (pathological
# corruption only — e.g. payload bytes stuffed with false MAGICs).
MAX_RESYNC_CANDIDATES = 4096


def decode_batch(buf, pos: int = 0,
                 verify_crc: bool = True) -> Tuple[Optional[RecordBatch],
                                                   int, int]:
    """Parse one frame at `pos`. Returns ``(batch, end, n_records)``:

    - complete + CRC ok  → ``(RecordBatch, frame_end, n)``
    - complete + CRC bad → ``(None, frame_end, n)`` — the batch is
      skipped but its records stay COUNTED, so offsets are stable
      across every reader (the sealed-junk-line rule, batch-sized)
    - incomplete (torn tail) → ``(None, pos, -1)`` — not consumed;
      re-read complete on a later poll

    Raises ValueError when the bytes at `pos` are not a frame header
    at all (callers fall back to line-oriented parsing)."""
    view = memoryview(buf)
    if len(view) - pos < HEADER.size:
        if view[pos:pos + 4] == MAGIC:
            return None, pos, -1  # header itself still in flight
        raise ValueError("not a record-batch frame")
    magic, ver, flags, n, plen, crc, fence = HEADER.unpack_from(view, pos)
    if magic != MAGIC:
        raise ValueError("not a record-batch frame")
    if ver not in SCHEMA_VERSIONS or plen > MAX_BATCH_BYTES \
            or flags & ~_KNOWN_FLAGS:
        # Unknown version / flag / insane length: treat as a corrupt
        # frame of unknowable extent — callers skip the rest of the
        # file region the same way a junk JSON line is skipped.
        raise ValueError(
            f"bad record-batch header (ver={ver}, flags={flags}, "
            f"len={plen})"
        )
    end = pos + HEADER.size + plen
    if end > len(view):
        return None, pos, -1  # torn frame: an append in progress
    payload = view[pos + HEADER.size:end]
    hdr0 = HEADER.pack(MAGIC, ver, flags, n, plen, 0, fence)
    if zlib.crc32(payload, zlib.crc32(hdr0)) != crc:
        # Corrupt in place: skip, keep the count. (If the corruption
        # hit the header's count/length fields themselves, the skip
        # may land mid-junk — the walker then stops at the first
        # unparseable unit, the documented header-corruption floor.)
        return None, end, n
    return RecordBatch(n, fence, payload, version=ver,
                       flags=flags), end, n


def _resync_scan(data, pos: int) -> Optional[int]:
    """Find a trustworthy unit boundary past a poisoned region (a
    frame whose HEADER bytes were corrupted in place — version/length
    fields garbled, so the frame's extent is unknowable).

    Two boundary kinds are trustworthy: a MAGIC candidate whose header
    decodes AND whose frame is complete, and a newline-delimited,
    parseable JSON line (the mixed-history case: JSONL records after
    the poisoned frame). The scan probes every MAGIC occurrence within
    the longest extent any legitimate frame could have had
    (``HEADER + MAX_BATCH_BYTES`` — the true boundary, if one exists,
    must lie inside that window), then takes the EARLIEST confirmed
    boundary of either kind. Earliest-wins is what keeps the result a
    function of file content alone, never poll timing: an early reader
    that sees the next frame still torn and a late reader that sees it
    complete both resolve to the same earlier line boundary if one
    exists, so every reader computes the same record slotting (the
    cross-reader offset parity the exactly-once ``inOff`` scan rests
    on). A torn-but-plausible candidate may be an append IN PROGRESS:
    nothing at or past it is decided — return None (wait) unless an
    earlier confirmed boundary already exists.

    The scan always makes deterministic progress past settled bytes:
    when the probe budget (pathological false-MAGIC density) or the
    window is exhausted with more data beyond it, the probed region is
    itself sealed as junk and the scan continues from its far edge on
    the next unit, rather than stalling at the poison forever.

    Returns the resync byte offset, or None (wait for more data)."""
    window_end = pos + HEADER.size + MAX_BATCH_BYTES + 1
    i = data.find(MAGIC, pos + 1)
    probed = 0
    frame_at = None  # earliest confirmed complete-frame boundary
    torn_at = None  # first torn-but-plausible candidate (undecided)
    budget_at = None  # first unprobed candidate after budget exhaustion
    while 0 <= i < window_end:
        if probed >= MAX_RESYNC_CANDIDATES:
            budget_at = i
            break
        probed += 1
        try:
            _batch, _end, cnt = decode_batch(data, i)
        except ValueError:
            i = data.find(MAGIC, i + 1)
            continue
        if cnt < 0:
            torn_at = i
        else:
            frame_at = i
        break
    # Line scan: only bytes BEFORE the first undecided/confirmed point
    # are settled enough to search (everything earlier is fixed content
    # — data is append-only — so the earliest line there is final).
    stops = [min(len(data), window_end)]
    stops += [s for s in (frame_at, torn_at, budget_at) if s is not None]
    stop = min(stops)
    line_at = None
    j = data.find(b"\n", pos)
    while 0 <= j < stop:
        start = j + 1
        k = data.find(b"\n", start)
        if k < 0 or k >= stop:
            break
        line = data[start:k].strip()
        if line:
            try:
                json.loads(line)
                line_at = start
                break
            except ValueError:
                pass
        j = k
    if line_at is not None:
        return line_at
    if frame_at is not None:
        return frame_at
    if torn_at is not None:
        return None  # possibly the live append: wait for more bytes
    if budget_at is not None:
        # Probe budget exhausted with nothing confirmed: seal the
        # probed region and resume at the first unprobed candidate —
        # content-deterministic, and progress.
        return budget_at
    if len(data) > window_end:
        # Nothing parseable within the longest extent any legitimate
        # frame could have had, and the file continues past it: seal
        # the window as junk and keep scanning from its far edge.
        return window_end
    return None  # nothing confirmed: wait for more bytes


def iter_units(data, start_index: int = 0) -> Iterator[Tuple]:
    """Walk a mixed log region: binary record-batch frames AND JSONL
    lines in one byte string — THE shared scanner every reader of the
    columnar op-log family uses (topic reads, tail readers, journal
    replay, clean-length scans), so the torn-tail / CRC-skip /
    junk-line counting rules exist exactly once.

    Yields ``(kind, index, count, payload, end)`` per COMPLETE unit:

    - ``("batch", index, n_records, RecordBatch | None, end)`` —
      `None` payload means the frame's CRC failed; its records are
      skipped but still COUNT `n_records` toward offsets.
    - ``("line", index, 1, raw_line_bytes, end)`` — one newline-
      terminated line (possibly junk; callers parse/skip, the count
      always holds). A POISONED region (frame header corrupted in
      place — extent unknowable) is yielded in this form too, once a
      bounded magic-scan (`_resync_scan`) confirms the next unit
      boundary: the region is skipped but counts ONE record slot, so
      readers resume instead of stalling forever (the pre-resync
      behavior), at the cost of the poisoned frame's true record
      count (unknowable — its header is gone).

    `index` is the record offset of the unit's first record (starting
    at `start_index`); `end` is the byte offset just past the unit
    within `data`. Iteration stops at the first torn unit (incomplete
    frame, unterminated line, unconfirmed resync) — an append in
    progress, re-read complete on a later poll."""
    pos = 0
    idx = start_index
    n = len(data)
    while pos < n:
        if data[pos:pos + 4] == MAGIC:
            try:
                batch, end, cnt = decode_batch(data, pos)
            except ValueError:
                # Poisoned header: skip-but-count the region up to a
                # CONFIRMED resync boundary; without one, stop here
                # (the bytes may still be arriving).
                resync = _resync_scan(data, pos)
                if resync is None:
                    return
                yield "line", idx, 1, data[pos:resync], resync
                idx += 1
                pos = resync
                continue
            if cnt < 0:
                return  # torn frame
            yield "batch", idx, cnt, batch, end
            idx += cnt
            pos = end
        else:
            nl = data.find(b"\n", pos)
            if nl < 0:
                return  # torn line
            yield "line", idx, 1, data[pos:nl], nl + 1
            idx += 1
            pos = nl + 1
