"""Wire message types.

Mirrors the roles of `IDocumentMessage` (reference:
common/lib/protocol-definitions/src/protocol.ts:133) and
`ISequencedDocumentMessage` (protocol.ts:212): a client submits a
DocumentMessage carrying (clientSequenceNumber, referenceSequenceNumber,
type, contents); the ordering service stamps (sequenceNumber,
minimumSequenceNumber) to produce a SequencedMessage that every replica
applies in order.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional


class MessageType(str, enum.Enum):
    # Reference: protocol-definitions/src/protocol.ts MessageType
    OP = "op"
    NOOP = "noop"
    CLIENT_JOIN = "join"
    CLIENT_LEAVE = "leave"
    PROPOSE = "propose"
    REJECT = "reject"
    SUMMARIZE = "summarize"
    SUMMARY_ACK = "summaryAck"
    SUMMARY_NACK = "summaryNack"
    NO_CLIENT = "noClient"
    CONTROL = "control"


@dataclass
class DocumentMessage:
    """A client-originated, not-yet-sequenced message."""

    client_seq: int  # clientSequenceNumber: per-client monotone counter
    ref_seq: int  # referenceSequenceNumber: last sequenced seq the client saw
    type: MessageType = MessageType.OP
    contents: Any = None
    metadata: Any = None
    # Which datastore / channel this op addresses (runtime envelope).
    address: Optional[str] = None


@dataclass
class SequencedMessage:
    """A message stamped with a total order by the sequencing service."""

    sequence_number: int
    minimum_sequence_number: int
    client_id: int  # integer client id (quorum-assigned slot)
    client_seq: int
    ref_seq: int
    type: MessageType = MessageType.OP
    contents: Any = None
    metadata: Any = None
    address: Optional[str] = None
    timestamp: float = 0.0
    # Trace annotations (reference: ISequencedDocumentMessage.traces).
    traces: list = field(default_factory=list)


def trace_submit_ts(metadata: Any) -> Optional[float]:
    """The client-driver submit timestamp riding op metadata under
    "tr_sub" (stamped by the runtime's flush; foreign producers simply
    omit it). Lives here, next to the metadata/traces wire contract,
    so both deli implementations share one definition."""
    if isinstance(metadata, dict):
        ts = metadata.get("tr_sub")
        if isinstance(ts, (int, float)):
            return float(ts)
    return None


def trace_stage_once(traces: list, stage: str, now: float) -> Optional[float]:
    """Record `stage` in an op's lifecycle trace exactly once.

    No-op returning None when the stage is already present (a restarted
    consumer re-polling shared log objects must not re-stamp or
    re-observe); otherwise appends ``(stage, now)`` and returns the
    op's "stamp" timestamp, if any, so the caller can observe the
    stamp→stage latency. One definition for every post-stamp consumer
    (scriptorium, broadcaster, ...)."""
    for s, _ in traces:
        if s == stage:
            return None
    stamp = None
    for s, ts in traces:
        if s == "stamp":
            stamp = ts
            break
    traces.append((stage, now))
    return stamp


@dataclass
class NackMessage:
    """Rejection from the sequencing service (stale refSeq, throttle...).

    Reference: deli nacks at server/routerlicious/packages/lambdas/src/
    deli/lambda.ts:967-982.
    """

    client_id: int
    client_seq: int
    code: int
    reason: str


@dataclass
class SignalMessage:
    """Transient (non-sequenced) broadcast message."""

    client_id: int
    contents: Any = None
