"""Columnar replay engine: the high-throughput sequenced-replay path.

`ColumnarReplica` plays the same convergence role as
`core.kernel_replica.KernelReplica` (consume the totally ordered
stream, maintain a `SegmentTable` on device) but takes its input as
pre-decoded columnar arrays (`testing.synthetic.ColumnarStream`) so the
host never touches per-op Python objects — the analog of the reference
replay tool pre-parsing recorded op files before the timed loop
(packages/tools/replay-tool/src/replayMessages.ts).

Compaction (the zamboni role, reference
packages/dds/merge-tree/src/zamboni.ts:19) is fully vectorized numpy:

- tombstones with removal seq ≤ the applied MSN are dropped;
- maximal runs of *settled* rows (insert seq ≤ MSN, not removed,
  identical props) are coalesced into single rows — this is what
  keeps the live table O(collab window), which in turn keeps the
  kernel's O(capacity)-per-op cost flat over arbitrarily long streams;
- all surviving text is gathered into a fresh contiguous codepoint
  arena with one fancy-index gather (no per-row Python).

Two text address spaces share the int32 offset coordinate: compacted
document text lives at [0, STREAM_BASE) and immutable stream-insert
text at [STREAM_BASE, ...). Splits only ever do offset arithmetic
within one region, so the kernel stays oblivious.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.mergetree_kernel import (
    NO_CLIENT,
    NO_KEY,
    NOT_REMOVED,
    OP_NOOP,
    PROP_ABSENT,
    OpBatch,
    SegmentTable,
    apply_op_batch_jit,
    grow_table,
    make_table,
    raise_kernel_errors,
)
from ..protocol.constants import UNIVERSAL_SEQ
from ..testing.synthetic import ColumnarStream

STREAM_BASE = 1 << 28  # stream-arena offsets start here


@jax.jit
def _pack_table(t: SegmentTable) -> jnp.ndarray:
    """Flatten the whole table into one int32 vector so a device→host
    pull is a single transfer (each transfer pays a full RTT on a
    tunneled device, so one big beats many small)."""
    return jnp.concatenate(
        [
            t.buf_start, t.length, t.ins_seq, t.ins_client, t.rem_seq,
            t.rem_clients.ravel(), t.props.ravel(),
            jnp.stack([t.n_rows, t.error]),
        ]
    )


def _unpack_table(flat: np.ndarray, capacity: int, kr: int, kk: int):
    """Host-side view of a packed table (numpy, no copies)."""
    c = capacity
    out = {}
    off = 0
    for name in ("buf_start", "length", "ins_seq", "ins_client", "rem_seq"):
        out[name] = flat[off : off + c]
        off += c
    out["rem_clients"] = flat[off : off + c * kr].reshape(c, kr)
    off += c * kr
    out["props"] = flat[off : off + c * kk].reshape(c, kk)
    off += c * kk
    out["n_rows"] = int(flat[off])
    out["error"] = int(flat[off + 1])
    return out


def _device_table(host: dict, capacity: int) -> SegmentTable:
    """Push a host table back as ONE transfer + on-device slicing."""
    flat = np.concatenate(
        [
            host["buf_start"], host["length"], host["ins_seq"],
            host["ins_client"], host["rem_seq"],
            host["rem_clients"].ravel(), host["props"].ravel(),
            np.asarray([host["n_rows"], host["error"]], np.int32),
        ]
    ).astype(np.int32)
    kr = host["rem_clients"].shape[1]
    kk = host["props"].shape[1]
    return _slice_table(jnp.asarray(flat), capacity, kr, kk)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _slice_table(flat: jnp.ndarray, c: int, kr: int, kk: int) -> SegmentTable:
    off = 0

    def take(n):
        nonlocal off
        part = lax.dynamic_slice_in_dim(flat, off, n)
        off += n
        return part

    buf_start = take(c)
    length = take(c)
    ins_seq = take(c)
    ins_client = take(c)
    rem_seq = take(c)
    rem_clients = take(c * kr).reshape(c, kr)
    props = take(c * kk).reshape(c, kk)
    tail = take(2)
    return SegmentTable(
        n_rows=tail[0], buf_start=buf_start, length=length, ins_seq=ins_seq,
        ins_client=ins_client, rem_seq=rem_seq, rem_clients=rem_clients,
        props=props, error=tail[1],
    )


class ColumnarReplica:
    """Device-resident replica driven by columnar op arrays.

    Two engines drive the same SegmentTable semantics:

    - ``scan``: `ops.mergetree_kernel.apply_op_batch_jit` (lax.scan,
      one op per step) + host-side compaction — runs on any backend;
      the differential-test workhorse.
    - ``pallas``: `ops.mergetree_pallas.apply_chunk` (whole chunk in
      one Mosaic kernel, table resident in VMEM) + device-side zamboni
      (`ops.zamboni.zamboni_device`, no host round trip) — the TPU
      fast path (~100x the scan engine on real hardware). `interpret`
      runs the same kernel through the pallas interpreter so CPU tests
      can gate it bit-identically.

    ``auto`` picks pallas on TPU-like backends, scan elsewhere.
    """

    def __init__(
        self,
        stream: ColumnarStream,
        initial_len: int = 0,
        chunk_size: int = 1024,
        capacity: int = 16384,
        n_removers: int = 4,
        n_prop_keys: int = 8,
        compact_watermark: float = 0.7,
        engine: str = "auto",
        interpret: bool = False,
        sync_interval: int = 4,
        arena_cap: Optional[int] = None,
    ):
        self.stream = stream
        self.chunk_size = chunk_size
        self.capacity = capacity
        self.n_removers = n_removers
        self.n_prop_keys = n_prop_keys
        self.compact_watermark = compact_watermark
        if engine == "auto":
            engine = (
                "pallas"
                if jax.default_backend() in ("tpu", "axon")
                else "scan"
            )
        self.engine = engine
        self.interpret = interpret
        self.sync_interval = sync_interval
        self.arena_cap = arena_cap

        # Document arena: compacted text (region [0, STREAM_BASE)).
        self.doc_text = np.asarray(stream.text[:initial_len], np.int32)
        self.table = make_table(capacity, n_removers, n_prop_keys)
        if initial_len:
            self.table = self.table._replace(
                n_rows=jnp.int32(1),
                length=self.table.length.at[0].set(initial_len),
                ins_seq=self.table.ins_seq.at[0].set(UNIVERSAL_SEQ),
                ins_client=self.table.ins_client.at[0].set(NO_CLIENT),
            )
        self._rows_bound = int(self.table.n_rows)
        self._applied_min_seq = 0
        self.compactions = 0

    # -------------------------------------------------------------- replay

    def replay(self, limit_chunks: Optional[int] = None) -> None:
        """Replay the stream. `limit_chunks` stops after that many
        chunks — used to warm compile caches with shapes identical to
        a later full run (share the same stream object)."""
        s = self.stream
        n = len(s)
        B = self.chunk_size
        # Stream insert offsets are rebased into the stream region.
        buf = s.buf_start + STREAM_BASE
        if self.engine == "pallas":
            self._replay_pallas(s, buf, n, B, limit_chunks)
            return
        for ci, lo in enumerate(range(0, n, B)):
            if limit_chunks is not None and ci >= limit_chunks:
                break
            hi = min(lo + B, n)
            self._apply_chunk(s, buf, lo, hi)

    def _replay_pallas(self, s: ColumnarStream, buf: np.ndarray,
                       n: int, B: int,
                       limit_chunks: Optional[int] = None) -> None:
        """TPU fast path. The whole NOOP-padded op stream uploads to
        the device ONCE; each chunk is one pallas dispatch slicing it
        on device (`apply_chunk_at`), and every `sync_interval` chunks
        a full device-side compaction runs (tombstone drop + text
        re-gather + maximal coalescing — one XLA dispatch,
        ops/zamboni.py compact_gather_text). The steady-state loop
        performs ZERO host↔device transfers and no blocking sync; the
        error flag rides the table and is checked once at the end
        (capacity is provisioned up front — live rows grow with the
        document's annotation-boundary count, measured ~0.1/op on the
        bench mix — so mid-replay growth is not expected; if it does
        overflow, ERR_CAPACITY fails the replay loudly).

        The device doc arena is sized initial_len + len(stream text):
        no live document can exceed that, so it never grows and no
        kernel recompiles mid-replay."""
        from ..ops.mergetree_pallas import apply_chunk_at
        from ..ops.zamboni import compact_gather_text

        assert self.capacity % 1024 == 0, "pallas path: capacity % 1024"
        # The table must absorb a FULL sync window before the first
        # compaction can trim it: worst case 2 rows per op.
        self._ensure_window_capacity(int(self.table.n_rows), B)
        arena_cap = self.arena_cap or (
            -(-(len(self.doc_text) + len(s.text) + 1) // (1 << 18)) * (1 << 18)
        )
        # Shape stability = compile stability: every device array is
        # padded to a fixed grid (op segments of SEG ops, text to
        # TXT_GRID multiples) so apply_chunk_at / compact_gather_text
        # compile once per (B, capacity, grid) REGARDLESS of stream
        # length, and a 2-chunk warm-up run on the same stream warms
        # every cache a full run needs.
        SEG = -(-(1 << 18) // B) * B
        TXT_GRID = 1 << 18
        arena = jnp.zeros(arena_cap, jnp.int32)
        arena = arena.at[: len(self.doc_text)].set(jnp.asarray(self.doc_text))
        txt_pad = -(-max(len(s.text), 1) // TXT_GRID) * TXT_GRID
        st = np.zeros(txt_pad, np.int32)
        st[: len(s.text)] = s.text
        stream_text = jnp.asarray(st)

        fills = {
            "op_type": OP_NOOP, "client": NO_CLIENT,
            "prop_key": NO_KEY, "prop_val": PROP_ABSENT,
        }

        def upload_segment(lo: int, hi: int) -> OpBatch:
            def up(name: str, a: np.ndarray) -> jnp.ndarray:
                out = np.full(SEG, fills.get(name, 0), np.int32)
                out[: hi - lo] = a[lo:hi]
                return jnp.asarray(out)

            return OpBatch(
                op_type=up("op_type", s.op_type),
                pos1=up("pos1", s.pos1), pos2=up("pos2", s.pos2),
                seq=up("seq", s.seq), ref_seq=up("ref_seq", s.ref_seq),
                client=up("client", s.client),
                buf_start=up("buf", buf), ins_len=up("ins_len", s.ins_len),
                prop_keys=up("prop_key", s.prop_key)[:, None],
                prop_vals=up("prop_val", s.prop_val)[:, None],
            )

        chunks_since = 0
        chunks_done = 0
        for seg_lo in range(0, n, SEG):
            seg_hi = min(seg_lo + SEG, n)
            dev = upload_segment(seg_lo, seg_hi)
            for off in range(0, seg_hi - seg_lo, B):
                hi = min(seg_lo + off + B, n)
                self.table = apply_chunk_at(
                    self.table, dev, jnp.int32(off), B, self.interpret
                )
                self._applied_min_seq = int(s.min_seq[hi - 1])
                chunks_since += 1
                chunks_done += 1
                done = hi >= n or (
                    limit_chunks is not None and chunks_done >= limit_chunks
                )
                if chunks_since >= self.sync_interval or done:
                    chunks_since = 0
                    self.table, arena = compact_gather_text(
                        self.table, jnp.int32(self._applied_min_seq),
                        arena, stream_text,
                    )
                    self.compactions += 1
                    # Tiered capacity: per-op kernel cost scales with
                    # capacity, so the table starts small and doubles
                    # only when occupancy demands (this sync costs one
                    # host round trip; it rides the compaction cadence).
                    n_rows = int(self.table.n_rows)
                    self.check_errors()
                    self._ensure_window_capacity(n_rows, B)
                if done and limit_chunks is not None:
                    break
            if limit_chunks is not None and chunks_done >= limit_chunks:
                break
        # Hand the final arena to the host-side text gather (get_text).
        self.doc_text = np.asarray(arena)
        self._rows_bound = int(self.table.n_rows)
        self.check_errors()

    def _apply_chunk(self, s: ColumnarStream, buf: np.ndarray, lo: int, hi: int) -> None:
        B = self.chunk_size
        m = hi - lo

        def pad(a: np.ndarray, fill: int = 0) -> jnp.ndarray:
            if m == B:
                return jnp.asarray(a[lo:hi])
            out = np.full(B, fill, np.int32)
            out[:m] = a[lo:hi]
            return jnp.asarray(out)

        self._rows_bound += 2 * m
        if self._rows_bound + 2 > self.capacity:
            self.compact()  # emergency compact before overflow
            need = self._rows_bound + 2 * m + 2
            if need > self.capacity:
                self._grow(max(self.capacity * 2, 2 * need))
            self._rows_bound += 2 * m

        pk = pad(s.prop_key, NO_KEY)[:, None]
        pv = pad(s.prop_val, PROP_ABSENT)[:, None]
        batch = OpBatch(
            op_type=pad(s.op_type, OP_NOOP),
            pos1=pad(s.pos1),
            pos2=pad(s.pos2),
            seq=pad(s.seq),
            ref_seq=pad(s.ref_seq),
            client=pad(s.client, NO_CLIENT),
            buf_start=pad(buf),
            ins_len=pad(s.ins_len),
            prop_keys=pk,
            prop_vals=pv,
        )
        self.table = apply_op_batch_jit(self.table, batch)
        self._applied_min_seq = int(s.min_seq[hi - 1])
        if self._rows_bound > self.capacity * self.compact_watermark:
            self.compact()

    # ----------------------------------------------------------- capacity

    def _grow(self, new_cap: int) -> None:
        self.table = grow_table(self.table, self.capacity, new_cap)
        self.capacity = new_cap

    def _ensure_window_capacity(self, n_rows: int, B: int) -> None:
        """Grow (doubling) until `n_rows` plus a full sync window's
        worst-case growth (2 rows/op) fits."""
        margin = 2 * B * self.sync_interval
        if n_rows + margin <= self.capacity:
            return
        new_cap = self.capacity
        while n_rows + margin > new_cap:
            new_cap *= 2
        self._grow(new_cap)

    # --------------------------------------------------------- compaction

    def _gather_text(self, buf: np.ndarray, lens: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Concatenate the spans (buf[i], lens[i]) from both arenas into
        one contiguous array; returns (text, new_offsets)."""
        total = int(lens.sum())
        new_off = np.cumsum(lens) - lens
        if total == 0:
            return np.empty(0, np.int32), new_off.astype(np.int32)
        flat_src = np.repeat(buf, lens) + (
            np.arange(total) - np.repeat(new_off, lens)
        )
        # Gather per region (the immutable stream arena is large; never
        # copy it wholesale just to index a few live spans).
        out = np.empty(total, np.int32)
        in_stream = flat_src >= STREAM_BASE
        out[~in_stream] = self.doc_text[flat_src[~in_stream]]
        out[in_stream] = self.stream.text[flat_src[in_stream] - STREAM_BASE]
        return out, new_off.astype(np.int32)

    def compact(self) -> None:
        flat = np.asarray(_pack_table(self.table))  # ONE device→host pull
        t = _unpack_table(flat, self.capacity, self.n_removers, self.n_prop_keys)
        n = t["n_rows"]
        msn = self._applied_min_seq
        live = np.arange(len(t["length"])) < n
        removed = t["rem_seq"] != NOT_REMOVED
        keep = live & ~(removed & (t["rem_seq"] <= msn))
        idx = np.nonzero(keep)[0]
        k = len(idx)

        buf = t["buf_start"][idx]
        lens = t["length"][idx].astype(np.int64)
        props = t["props"][idx]
        settled = (~removed[idx]) & (t["ins_seq"][idx] <= msn)

        # Run grouping: consecutive settled rows with identical props
        # coalesce; every unsettled row is its own run.
        if k:
            prev_settled = np.concatenate([[False], settled[:-1]])
            same_props = np.concatenate(
                [[False], (props[1:] == props[:-1]).all(axis=1)]
            )
            start_run = ~(settled & prev_settled & same_props)
            start_run[0] = True
            run_id = np.cumsum(start_run) - 1
            m = int(run_id[-1]) + 1
        else:
            start_run = np.zeros(0, bool)
            run_id = np.zeros(0, np.int64)
            m = 0

        new_text, new_off = self._gather_text(buf, lens)
        first = np.nonzero(start_run)[0]  # first kept-row index of each run
        run_len = np.bincount(run_id, weights=lens, minlength=m).astype(np.int32)

        cap = self.capacity
        nb = np.zeros(cap, np.int32)
        nl = np.zeros(cap, np.int32)
        nis = np.zeros(cap, np.int32)
        nic = np.full(cap, NO_CLIENT, np.int32)
        nrs = np.full(cap, NOT_REMOVED, np.int32)
        nrc = np.full((cap, self.n_removers), NO_CLIENT, np.int32)
        npr = np.full((cap, self.n_prop_keys), PROP_ABSENT, np.int32)
        if m:
            nb[:m] = new_off[first]
            nl[:m] = run_len[:m]
            nis[:m] = t["ins_seq"][idx][first]
            nic[:m] = t["ins_client"][idx][first]
            nrs[:m] = t["rem_seq"][idx][first]
            nrc[:m] = t["rem_clients"][idx][first]
            npr[:m] = props[first]

        self.doc_text = new_text
        # ONE host→device push.
        self.table = _device_table(
            {
                "buf_start": nb, "length": nl, "ins_seq": nis,
                "ins_client": nic, "rem_seq": nrs, "rem_clients": nrc,
                "props": npr, "n_rows": m, "error": t["error"],
            },
            cap,
        )
        self._rows_bound = m
        self.compactions += 1

    # ------------------------------------------------------------- output

    def check_errors(self) -> None:
        raise_kernel_errors(int(self.table.error))

    def get_text(self) -> str:
        flat = np.asarray(_pack_table(self.table))
        t = _unpack_table(flat, self.capacity, self.n_removers, self.n_prop_keys)
        live = (np.arange(len(t["length"])) < t["n_rows"]) & (
            t["rem_seq"] == NOT_REMOVED
        )
        idx = np.nonzero(live)[0]
        text, _ = self._gather_text(
            t["buf_start"][idx], t["length"][idx].astype(np.int64)
        )
        return "".join(map(chr, text))

    def annotated_spans(self):
        """(text, props) per visible row, dictionary-decoded to the
        synthetic stream's key naming (k<idx>) — the same surface the
        scalar oracle's annotated_spans exposes, for cross-engine
        digest comparison (testing/digest.py)."""
        flat = np.asarray(_pack_table(self.table))
        t = _unpack_table(flat, self.capacity, self.n_removers, self.n_prop_keys)
        live = (np.arange(len(t["length"])) < t["n_rows"]) & (
            t["rem_seq"] == NOT_REMOVED
        )
        idx = np.nonzero(live)[0]
        text, offs = self._gather_text(
            t["buf_start"][idx], t["length"][idx].astype(np.int64)
        )
        spans = []
        lens = t["length"][idx]
        props = t["props"][idx]
        for i in range(len(idx)):
            chunk = "".join(map(chr, text[offs[i]: offs[i] + lens[i]]))
            p = {
                f"k{k}": int(props[i, k])
                for k in range(self.n_prop_keys)
                if props[i, k] != PROP_ABSENT
            }
            spans.append((chunk, p or None))
        return spans
