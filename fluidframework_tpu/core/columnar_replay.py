"""Columnar replay engine: the high-throughput sequenced-replay path.

`ColumnarReplica` plays the same convergence role as
`core.kernel_replica.KernelReplica` (consume the totally ordered
stream, maintain a `SegmentTable` on device) but takes its input as
pre-decoded columnar arrays (`testing.synthetic.ColumnarStream`) so the
host never touches per-op Python objects — the analog of the reference
replay tool pre-parsing recorded op files before the timed loop
(packages/tools/replay-tool/src/replayMessages.ts).

Compaction (the zamboni role, reference
packages/dds/merge-tree/src/zamboni.ts:19) is fully vectorized numpy:

- tombstones with removal seq ≤ the applied MSN are dropped;
- maximal runs of *settled* rows (insert seq ≤ MSN, not removed,
  identical props) are coalesced into single rows — this is what
  keeps the live table O(collab window), which in turn keeps the
  kernel's O(capacity)-per-op cost flat over arbitrarily long streams;
- all surviving text is gathered into a fresh contiguous codepoint
  arena with one fancy-index gather (no per-row Python).

Two text address spaces share the int32 offset coordinate: compacted
document text lives at [0, STREAM_BASE) and immutable stream-insert
text at [STREAM_BASE, ...). Splits only ever do offset arithmetic
within one region, so the kernel stays oblivious.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.mergetree_kernel import (
    NO_CLIENT,
    NO_KEY,
    NOT_REMOVED,
    OP_NOOP,
    PROP_ABSENT,
    OpBatch,
    SegmentTable,
    apply_op_batch_jit,
    grow_table,
    make_table,
    raise_kernel_errors,
)
from ..protocol.constants import UNIVERSAL_SEQ
from ..testing.synthetic import ColumnarStream

STREAM_BASE = 1 << 28  # stream-arena offsets start here


@jax.jit
def _pack_table(t: SegmentTable) -> jnp.ndarray:
    """Flatten the whole table into one int32 vector so a device→host
    pull is a single transfer (each transfer pays a full RTT on a
    tunneled device, so one big beats many small)."""
    return jnp.concatenate(
        [
            t.buf_start, t.length, t.ins_seq, t.ins_client, t.rem_seq,
            t.rem_clients.ravel(), t.props.ravel(),
            jnp.stack([t.n_rows, t.error]),
        ]
    )


def _unpack_table(flat: np.ndarray, capacity: int, kr: int, kk: int):
    """Host-side view of a packed table (numpy, no copies)."""
    c = capacity
    out = {}
    off = 0
    for name in ("buf_start", "length", "ins_seq", "ins_client", "rem_seq"):
        out[name] = flat[off : off + c]
        off += c
    out["rem_clients"] = flat[off : off + c * kr].reshape(c, kr)
    off += c * kr
    out["props"] = flat[off : off + c * kk].reshape(c, kk)
    off += c * kk
    out["n_rows"] = int(flat[off])
    out["error"] = int(flat[off + 1])
    return out


def _device_table(host: dict, capacity: int) -> SegmentTable:
    """Push a host table back as ONE transfer + on-device slicing."""
    flat = np.concatenate(
        [
            host["buf_start"], host["length"], host["ins_seq"],
            host["ins_client"], host["rem_seq"],
            host["rem_clients"].ravel(), host["props"].ravel(),
            np.asarray([host["n_rows"], host["error"]], np.int32),
        ]
    ).astype(np.int32)
    kr = host["rem_clients"].shape[1]
    kk = host["props"].shape[1]
    return _slice_table(jnp.asarray(flat), capacity, kr, kk)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _slice_table(flat: jnp.ndarray, c: int, kr: int, kk: int) -> SegmentTable:
    off = 0

    def take(n):
        nonlocal off
        part = lax.dynamic_slice_in_dim(flat, off, n)
        off += n
        return part

    buf_start = take(c)
    length = take(c)
    ins_seq = take(c)
    ins_client = take(c)
    rem_seq = take(c)
    rem_clients = take(c * kr).reshape(c, kr)
    props = take(c * kk).reshape(c, kk)
    tail = take(2)
    return SegmentTable(
        n_rows=tail[0], buf_start=buf_start, length=length, ins_seq=ins_seq,
        ins_client=ins_client, rem_seq=rem_seq, rem_clients=rem_clients,
        props=props, error=tail[1],
    )


class ColumnarReplica:
    """Device-resident replica driven by columnar op arrays."""

    def __init__(
        self,
        stream: ColumnarStream,
        initial_len: int = 0,
        chunk_size: int = 1024,
        capacity: int = 16384,
        n_removers: int = 4,
        n_prop_keys: int = 8,
        compact_watermark: float = 0.7,
    ):
        self.stream = stream
        self.chunk_size = chunk_size
        self.capacity = capacity
        self.n_removers = n_removers
        self.n_prop_keys = n_prop_keys
        self.compact_watermark = compact_watermark

        # Document arena: compacted text (region [0, STREAM_BASE)).
        self.doc_text = np.asarray(stream.text[:initial_len], np.int32)
        self.table = make_table(capacity, n_removers, n_prop_keys)
        if initial_len:
            self.table = self.table._replace(
                n_rows=jnp.int32(1),
                length=self.table.length.at[0].set(initial_len),
                ins_seq=self.table.ins_seq.at[0].set(UNIVERSAL_SEQ),
                ins_client=self.table.ins_client.at[0].set(NO_CLIENT),
            )
        self._rows_bound = int(self.table.n_rows)
        self._applied_min_seq = 0
        self.compactions = 0

    # -------------------------------------------------------------- replay

    def replay(self) -> None:
        s = self.stream
        n = len(s)
        B = self.chunk_size
        # Stream insert offsets are rebased into the stream region.
        buf = s.buf_start + STREAM_BASE
        for lo in range(0, n, B):
            hi = min(lo + B, n)
            self._apply_chunk(s, buf, lo, hi)

    def _apply_chunk(self, s: ColumnarStream, buf: np.ndarray, lo: int, hi: int) -> None:
        B = self.chunk_size
        m = hi - lo

        def pad(a: np.ndarray, fill: int = 0) -> jnp.ndarray:
            if m == B:
                return jnp.asarray(a[lo:hi])
            out = np.full(B, fill, np.int32)
            out[:m] = a[lo:hi]
            return jnp.asarray(out)

        self._rows_bound += 2 * m
        if self._rows_bound + 2 > self.capacity:
            self.compact()  # emergency compact before overflow
            need = self._rows_bound + 2 * m + 2
            if need > self.capacity:
                self._grow(max(self.capacity * 2, 2 * need))
            self._rows_bound += 2 * m

        pk = pad(s.prop_key, NO_KEY)[:, None]
        pv = pad(s.prop_val, PROP_ABSENT)[:, None]
        batch = OpBatch(
            op_type=pad(s.op_type, OP_NOOP),
            pos1=pad(s.pos1),
            pos2=pad(s.pos2),
            seq=pad(s.seq),
            ref_seq=pad(s.ref_seq),
            client=pad(s.client, NO_CLIENT),
            buf_start=pad(buf),
            ins_len=pad(s.ins_len),
            prop_keys=pk,
            prop_vals=pv,
        )
        self.table = apply_op_batch_jit(self.table, batch)
        self._applied_min_seq = int(s.min_seq[hi - 1])
        if self._rows_bound > self.capacity * self.compact_watermark:
            self.compact()

    # ----------------------------------------------------------- capacity

    def _grow(self, new_cap: int) -> None:
        self.table = grow_table(self.table, self.capacity, new_cap)
        self.capacity = new_cap

    # --------------------------------------------------------- compaction

    def _gather_text(self, buf: np.ndarray, lens: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Concatenate the spans (buf[i], lens[i]) from both arenas into
        one contiguous array; returns (text, new_offsets)."""
        total = int(lens.sum())
        new_off = np.cumsum(lens) - lens
        if total == 0:
            return np.empty(0, np.int32), new_off.astype(np.int32)
        flat_src = np.repeat(buf, lens) + (
            np.arange(total) - np.repeat(new_off, lens)
        )
        # Gather per region (the immutable stream arena is large; never
        # copy it wholesale just to index a few live spans).
        out = np.empty(total, np.int32)
        in_stream = flat_src >= STREAM_BASE
        out[~in_stream] = self.doc_text[flat_src[~in_stream]]
        out[in_stream] = self.stream.text[flat_src[in_stream] - STREAM_BASE]
        return out, new_off.astype(np.int32)

    def compact(self) -> None:
        flat = np.asarray(_pack_table(self.table))  # ONE device→host pull
        t = _unpack_table(flat, self.capacity, self.n_removers, self.n_prop_keys)
        n = t["n_rows"]
        msn = self._applied_min_seq
        live = np.arange(len(t["length"])) < n
        removed = t["rem_seq"] != NOT_REMOVED
        keep = live & ~(removed & (t["rem_seq"] <= msn))
        idx = np.nonzero(keep)[0]
        k = len(idx)

        buf = t["buf_start"][idx]
        lens = t["length"][idx].astype(np.int64)
        props = t["props"][idx]
        settled = (~removed[idx]) & (t["ins_seq"][idx] <= msn)

        # Run grouping: consecutive settled rows with identical props
        # coalesce; every unsettled row is its own run.
        if k:
            prev_settled = np.concatenate([[False], settled[:-1]])
            same_props = np.concatenate(
                [[False], (props[1:] == props[:-1]).all(axis=1)]
            )
            start_run = ~(settled & prev_settled & same_props)
            start_run[0] = True
            run_id = np.cumsum(start_run) - 1
            m = int(run_id[-1]) + 1
        else:
            start_run = np.zeros(0, bool)
            run_id = np.zeros(0, np.int64)
            m = 0

        new_text, new_off = self._gather_text(buf, lens)
        first = np.nonzero(start_run)[0]  # first kept-row index of each run
        run_len = np.bincount(run_id, weights=lens, minlength=m).astype(np.int32)

        cap = self.capacity
        nb = np.zeros(cap, np.int32)
        nl = np.zeros(cap, np.int32)
        nis = np.zeros(cap, np.int32)
        nic = np.full(cap, NO_CLIENT, np.int32)
        nrs = np.full(cap, NOT_REMOVED, np.int32)
        nrc = np.full((cap, self.n_removers), NO_CLIENT, np.int32)
        npr = np.full((cap, self.n_prop_keys), PROP_ABSENT, np.int32)
        if m:
            nb[:m] = new_off[first]
            nl[:m] = run_len[:m]
            nis[:m] = t["ins_seq"][idx][first]
            nic[:m] = t["ins_client"][idx][first]
            nrs[:m] = t["rem_seq"][idx][first]
            nrc[:m] = t["rem_clients"][idx][first]
            npr[:m] = props[first]

        self.doc_text = new_text
        # ONE host→device push.
        self.table = _device_table(
            {
                "buf_start": nb, "length": nl, "ins_seq": nis,
                "ins_client": nic, "rem_seq": nrs, "rem_clients": nrc,
                "props": npr, "n_rows": m, "error": t["error"],
            },
            cap,
        )
        self._rows_bound = m
        self.compactions += 1

    # ------------------------------------------------------------- output

    def check_errors(self) -> None:
        raise_kernel_errors(int(self.table.error))

    def get_text(self) -> str:
        flat = np.asarray(_pack_table(self.table))
        t = _unpack_table(flat, self.capacity, self.n_removers, self.n_prop_keys)
        live = (np.arange(len(t["length"])) < t["n_rows"]) & (
            t["rem_seq"] == NOT_REMOVED
        )
        idx = np.nonzero(live)[0]
        text, _ = self._gather_text(
            t["buf_start"][idx], t["length"][idx].astype(np.int64)
        )
        return "".join(map(chr, text))
