"""Overlay-pallas fold backend for the summary service.

The serving summarizer (`server.summarizer.SummarizerRole`) folds
merge-tree docs through the vectorized ROW-MODEL kernel
(`ops.mergetree_kernel.apply_op_batch_docs_jit`) — O(capacity) vector
work per op. The in-tree overlay engine replays the same semantics at
O(collab window) per op (BENCH_r04/r05: ~38x the vmapped kernel
replay), but until this module it had no live consumer on the summary
path. `OverlayFoldReplica` is the summarizer-shaped driver:

- **boot from canonical rows** (`boot_overlay`) — the restart path,
  identical in contract to `summarizer._boot_mergetree`: settled rows
  (ins normalized to UNIVERSAL_SEQ, not removed) become the settled
  text/props space, everything else (unsettled inserts, tombstones
  above the window) boots as overlay TEXT rows over a fresh arena.
- **fold rounds** through the fused overlay replay
  (`ops.overlay_pallas.replay_fused`): one device dispatch per round
  per doc, per-chunk zamboni folds riding the dispatch, fold records
  pulled once per round and applied to the host settled state
  (`core.overlay_replay.reconstruct_settled`, incremental form).
  Several docs folding in one emission round STACK over the 2-D
  device plane (`parallel.device_plane.DevicePlane`): the stacked doc
  axis tiles ``PartitionSpec(('docs', 'model'))`` — the summarizer's
  half of the one-chip-pool composition (the sequencer holds the
  ``docs`` axis of the same mesh).
- **canonical serialization** (`canonical_rows`) — bit-identical to
  `summarizer._canonical_rows` over the kernel table BY CONTRACT: the
  same normalization (tombstones <= msn dropped, settled ins
  normalized to (UNIVERSAL_SEQ, NO_CLIENT), adjacent equal-semantic
  rows merged maximally) applied to the overlay state, so blob bytes
  and content-addressed handles are backend-invariant. The
  differential gates (tests/test_device_plane.py,
  `config15_device_plane`) hold the two backends byte-equal on every
  host; `overlay_available` is the loud-fallback probe for hosts
  where pallas cannot lower (CPU children run the interpreter via
  ``FLUID_FOLD_INTERPRET`` for correctness gates).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "OverlayFoldReplica",
    "boot_overlay",
    "fold_jobs_overlay",
    "merge_canonical_rows",
    "overlay_available",
]

# Fold-engine shape knobs: chunk mirrors the summarizer's kernel-fold
# chunk; the window is the overlay table's unsettled-row capacity
# (pallas tiling wants multiples of 1024) and grows ahead of need.
_CHUNK = 128
_MIN_WINDOW = 1024
_PK = 4  # max prop pairs per encoded op (KernelReplica default)
_KR = 4  # removers per row (KernelReplica default)
_KK = 8  # prop keys (KernelReplica default)


def merge_canonical_rows(raw_rows) -> List[list]:
    """THE canonical-row merge rule, shared by both fold backends:
    adjacent rows whose semantic fields all match coalesce into
    maximal runs, erasing split/chunk/engine history from the bytes.
    `raw_rows` yields ``(text, ins, icl, rem|None, rcl|None, props)``
    tuples in document order."""
    out: List[list] = []
    last_key: Optional[tuple] = None
    for seg, ins, icl, rem, rcl, props in raw_rows:
        key = (ins, icl, rem, tuple(rcl) if rcl else None,
               json.dumps(props, sort_keys=True))
        if key == last_key and out:
            out[-1][0] += seg
        else:
            out.append([seg, ins, icl, rem, rcl, props])
            last_key = key
    return out


# ---------------------------------------------------------------------------
# availability probe
# ---------------------------------------------------------------------------

_AVAILABLE: Dict[bool, bool] = {}


def overlay_available(interpret: bool = False) -> bool:
    """Whether the overlay-pallas fold can run here (process-cached):
    one tiny apply+fold dispatch proves lowering works. On CPU hosts
    the non-interpret kernel cannot lower (Mosaic is TPU-only) — the
    summarizer falls back LOUDLY to the kernel backend unless
    interpreter mode is requested for a correctness run."""
    key = bool(interpret)
    cached = _AVAILABLE.get(key)
    if cached is not None:
        return cached
    try:
        import jax.numpy as jnp

        from ..ops.mergetree_kernel import (
            NO_KEY,
            OP_NOOP,
            PROP_ABSENT,
            OpBatch,
        )
        from ..ops.overlay_pallas import (
            fold_device,
            make_overlay_table,
            overlay_apply_chunk,
        )
        from ..protocol.constants import NO_CLIENT

        table = make_overlay_table(_MIN_WINDOW, _KR, _KK)
        B = 8
        batch = OpBatch(
            op_type=jnp.full(B, OP_NOOP, jnp.int32),
            pos1=jnp.zeros(B, jnp.int32), pos2=jnp.zeros(B, jnp.int32),
            seq=jnp.zeros(B, jnp.int32), ref_seq=jnp.zeros(B, jnp.int32),
            client=jnp.full(B, NO_CLIENT, jnp.int32),
            buf_start=jnp.zeros(B, jnp.int32),
            ins_len=jnp.zeros(B, jnp.int32),
            prop_keys=jnp.full((B, _PK), NO_KEY, jnp.int32),
            prop_vals=jnp.full((B, _PK), PROP_ABSENT, jnp.int32),
        )
        table = overlay_apply_chunk(table, batch, key)
        table, _records, _n = fold_device(table, jnp.int32(0))
        int(table.n_rows)  # force execution
        ok = True
    except Exception:  # noqa: BLE001 - any lowering failure means "no"
        ok = False
    _AVAILABLE[key] = ok
    return ok


# ---------------------------------------------------------------------------
# the replica
# ---------------------------------------------------------------------------


class OverlayFoldReplica:
    """Overlay-engine twin of the summarizer's `KernelReplica` fold
    state: same encode surface (`kernel_replica.encode_op` writes into
    `_encoded` through the arena/prop-interner attrs), same
    boot-from-rows restart contract, same canonical serialization —
    different engine underneath."""

    def __init__(self, interpret: bool = False,
                 window: int = _MIN_WINDOW):
        import jax.numpy as jnp  # noqa: F401  (asserts jax present)

        from ..ops.overlay_pallas import make_overlay_table
        from .kernel_replica import PropInterner, TextArena

        self.interpret = bool(interpret)
        self.chunk_size = _CHUNK
        self.max_prop_pairs = _PK
        self.n_removers = _KR
        self.n_prop_keys = _KK
        self.window = int(window)
        self.arena = TextArena("")
        self.props = PropInterner(_KK)
        self.table = make_overlay_table(self.window, _KR, _KK)
        # Host settled state (text/props/attr as np arrays of
        # codepoints / interned ids), advanced per round from the fold
        # records — the `OverlayDeviceReplica.reconstruct_settled`
        # walk in incremental form.
        self.settled_t = np.zeros(0, np.int32)
        self.settled_p = np.zeros((0, _KK), np.int32)
        self.settled_a = np.zeros(0, np.int32)
        # encode_op contract fields.
        self._encoded: List[tuple] = []
        self._pending_rows_bound = 0
        # _encode_fold contract fields.
        self.min_seq = 0
        self.current_seq = 0
        self._applied_min_seq = 0

    # --------------------------------------------------------- capacity

    def _ensure_window(self, need: int) -> None:
        """Grow the overlay table's row capacity ahead of a round (the
        `KernelReplica._ensure_capacity` role): padding preserves every
        field's empty-row sentinel, in 1024-row steps (pallas tiling).
        """
        if need <= self.window:
            return
        import jax.numpy as jnp

        from ..ops.mergetree_kernel import NOT_REMOVED, PROP_ABSENT
        from ..protocol.constants import NO_CLIENT

        new_w = self.window
        while new_w < need:
            new_w += _MIN_WINDOW
        pad = new_w - self.window
        t = self.table
        self.table = t._replace(
            anchor=jnp.pad(t.anchor, (0, pad)),
            buf_start=jnp.pad(t.buf_start, (0, pad)),
            length=jnp.pad(t.length, (0, pad)),
            ins_seq=jnp.pad(t.ins_seq, (0, pad)),
            ins_client=jnp.pad(t.ins_client, (0, pad),
                               constant_values=NO_CLIENT),
            rem_seq=jnp.pad(t.rem_seq, (0, pad),
                            constant_values=NOT_REMOVED),
            rem_clients=jnp.pad(t.rem_clients, ((0, pad), (0, 0)),
                                constant_values=NO_CLIENT),
            props=jnp.pad(t.props, ((0, pad), (0, 0)),
                          constant_values=PROP_ABSENT),
        )
        self.window = new_w

    # ------------------------------------------------------------ round

    def build_round(self) -> Optional[dict]:
        """Drain `_encoded` into one padded fold-round job: columnar
        OpBatch host arrays (NOOP-padded to whole chunks), the
        per-chunk MSN fold schedule (each chunk folds at its last real
        row's msn — semantics-free boundaries, the zamboni watermark
        riding the dispatch), a fresh per-round fold log, and the
        window sized so ERR_CAPACITY cannot fire for this round's row
        bound. Returns None when nothing is pending."""
        from ..ops.mergetree_kernel import (
            NO_KEY,
            OP_NOOP,
            PROP_ABSENT,
        )
        from ..protocol.constants import NO_CLIENT

        rows = self._encoded
        if not rows:
            return None
        self._encoded = []
        n = len(rows)
        B = self.chunk_size
        n_chunks = -(-n // B)
        pad = n_chunks * B
        self._ensure_window(int(self._rows_now()) + 4 * n + 64)
        op_type = np.full(pad, OP_NOOP, np.int32)
        pos1 = np.zeros(pad, np.int32)
        pos2 = np.zeros(pad, np.int32)
        seq = np.zeros(pad, np.int32)
        ref = np.zeros(pad, np.int32)
        client = np.full(pad, NO_CLIENT, np.int32)
        buf = np.zeros(pad, np.int32)
        ilen = np.zeros(pad, np.int32)
        pkeys = np.full((pad, _PK), NO_KEY, np.int32)
        pvals = np.full((pad, _PK), PROP_ABSENT, np.int32)
        msns = np.zeros(n_chunks, np.int32)
        for i, (t, p1, p2, s, r, c, b, ln, ks, vs, msn) in \
                enumerate(rows):
            op_type[i], pos1[i], pos2[i] = t, p1, p2
            seq[i], ref[i], client[i], buf[i], ilen[i] = s, r, c, b, ln
            for j, (k, v) in enumerate(zip(ks, vs)):
                pkeys[i, j], pvals[i, j] = k, v
            msns[i // B] = msn
        self._applied_min_seq = rows[-1][10]
        self._pending_rows_bound = 0
        return {
            "rep": self,
            "window": self.window,
            "n": n,
            "n_chunks": n_chunks,
            "batch": (op_type, pos1, pos2, seq, ref, client, buf, ilen,
                      pkeys, pvals),
            "msns": msns,
            # Worst case: every fold emits at most `window` records
            # (only table rows fold), one fold per chunk.
            "log_cap": (n_chunks + 1) * self.window,
        }

    def _rows_now(self) -> int:
        return int(self.table.n_rows)

    def apply_round(self, table, log, counts) -> None:
        """Fold a finished round's outputs back into this replica:
        adopt the table and replay the round's fold records into the
        host settled state (one reconstruct epoch per chunk)."""
        from .overlay_replay import reconstruct_settled

        self.table = table
        counts_l = [int(c) for c in np.asarray(counts)]
        total = sum(counts_l)
        if total:
            stream_text = np.frombuffer(
                self.arena.snapshot().encode("utf-32-le"), np.uint32
            ).astype(np.int32)
            self.settled_t, self.settled_p, self.settled_a = \
                reconstruct_settled(
                    self.settled_t, stream_text,
                    np.asarray(log)[:total], counts_l, _KK,
                    initial_props=self.settled_p,
                    initial_attr=self.settled_a,
                )
        if len(self.settled_t) != int(self.table.settled_len):
            raise RuntimeError(
                f"overlay fold settled desync: host "
                f"{len(self.settled_t)} != device "
                f"{int(self.table.settled_len)}"
            )

    def fold_pending(self) -> None:
        """Single-replica round (the unstacked path — also the
        defensive flush `canonical_rows` takes if encoded rows are
        still pending)."""
        job = self.build_round()
        if job is None:
            return
        _run_rounds([job], plane=None, interpret=self.interpret)

    # -------------------------------------------------- serialization

    def _check_invariants(self, t) -> None:
        """Host-side structural invariants of the overlay table
        (`overlay_ref.OverlayDoc.verify_invariants`' serving twin),
        checked BEFORE every serialization: a corrupt table — however
        it got that way — must freeze the doc loudly (RuntimeError →
        the role's freeze path, longer tails), never ship a wrong
        content-addressed blob."""
        from ..ops.mergetree_kernel import NOT_REMOVED
        from ..ops.overlay_pallas import SETTLED_BASE
        from ..protocol.constants import NO_CLIENT

        n = int(t.n_rows)
        if n < 0 or n > self.window:
            raise RuntimeError(f"overlay n_rows corrupt: {n}")
        if n == 0:
            return
        length = t.length[:n]
        anchor = t.anchor[:n]
        is_span = t.buf_start[:n] >= SETTLED_BASE
        removed = t.rem_seq[:n] != NOT_REMOVED
        has_removers = (t.rem_clients[:n] != NO_CLIENT).any(axis=1)
        S = int(t.settled_len)
        consume = np.where(is_span, length, 0)
        end = anchor + consume
        bad = (
            (length <= 0).any()
            or (anchor < 0).any() or (end > S).any()
            or (n > 1 and (anchor[1:] < end[:-1]).any())
            or bool((removed != has_removers).any())
            or (t.ins_seq[:n] < 0).any()
            or (t.ins_client[:n] < NO_CLIENT).any()
        )
        if bad:
            raise RuntimeError(
                "overlay table failed structural invariants at "
                "serialization (corrupt row state); freezing the doc "
                "rather than shipping a wrong summary"
            )

    def canonical_rows(self, msn: int) -> List[list]:
        """The canonical serialized row form at fold msn `msn` —
        byte-identical to `summarizer._canonical_rows` over the kernel
        table for the same op prefix (the backend-invariance contract
        the content-addressed handles rest on). Runs the final zamboni
        fold at `msn` first, so the table holds only rows the window
        still needs."""
        import jax
        import jax.numpy as jnp

        from ..ops.mergetree_kernel import (
            NOT_REMOVED,
            PROP_DELETE,
            PROP_ABSENT,
            raise_kernel_errors,
        )
        from ..ops.overlay_pallas import SETTLED_BASE, fold_device
        from ..ops.overlay_ref import merge_span_props
        from ..protocol.constants import NO_CLIENT, UNIVERSAL_SEQ

        self.fold_pending()
        self.table, records, n_rec = fold_device(
            self.table, jnp.int32(msn)
        )
        self.apply_round(self.table, np.asarray(records),
                         [int(n_rec)])
        t = jax.tree_util.tree_map(np.asarray, self.table)
        raise_kernel_errors(int(t.error))
        self._check_invariants(t)
        arena_text = self.arena.snapshot()
        decode = self.props.decode_row
        settled_t, settled_p = self.settled_t, self.settled_p
        raw: List[tuple] = []

        def emit_settled(lo: int, hi: int) -> None:
            # Settled content: ins normalized by construction; split
            # into maximal equal-prop runs (the canonical merge below
            # re-merges across row boundaries with the full key).
            i = lo
            while i < hi:
                j = i + 1
                while j < hi and np.array_equal(settled_p[j],
                                                settled_p[i]):
                    j += 1
                raw.append((
                    "".join(map(chr, settled_t[i:j].tolist())),
                    UNIVERSAL_SEQ, NO_CLIENT, None, None,
                    decode(settled_p[i]),
                ))
                i = j

        cursor = 0
        for i in range(int(t.n_rows)):
            a = int(t.anchor[i])
            if a > cursor:
                emit_settled(cursor, a)
                cursor = a
            rem = int(t.rem_seq[i])
            removed = rem != NOT_REMOVED
            ln = int(t.length[i])
            is_span = int(t.buf_start[i]) >= SETTLED_BASE
            if removed and rem <= msn:
                # Tombstone below the window: zamboni (the final fold
                # above dropped these; defensive for exactness).
                if is_span:
                    cursor = a + ln
                continue
            rcl = (sorted(int(c) for c in t.rem_clients[i]
                          if int(c) != NO_CLIENT) if removed else None)
            if is_span:
                # Removed settled text (a live span cannot survive the
                # fold — spans fold unconditionally): per-position
                # merged props split into runs, insert identity is
                # settled == universal.
                merged = merge_span_props(
                    settled_p[a: a + ln], t.props[i]
                )
                k = 0
                while k < ln:
                    k2 = k + 1
                    while k2 < ln and np.array_equal(merged[k2],
                                                     merged[k]):
                        k2 += 1
                    raw.append((
                        "".join(map(chr,
                                    settled_t[a + k: a + k2].tolist())),
                        UNIVERSAL_SEQ, NO_CLIENT, rem, rcl,
                        decode(merged[k]),
                    ))
                    k = k2
                cursor = a + ln
            else:
                b = int(t.buf_start[i])
                seg = arena_text[b: b + ln]
                ins = int(t.ins_seq[i])
                icl = int(t.ins_client[i])
                if ins <= msn:
                    ins, icl = UNIVERSAL_SEQ, NO_CLIENT
                row_p = np.asarray(t.props[i]).copy()
                row_p[row_p == PROP_DELETE] = PROP_ABSENT
                raw.append((seg, ins, icl, rem if removed else None,
                            rcl, decode(row_p)))
        emit_settled(cursor, len(settled_t))
        return merge_canonical_rows(raw)


def boot_overlay(rows: List[list], msn: int,
                 interpret: bool = False) -> OverlayFoldReplica:
    """Build a live overlay fold replica from serialized canonical
    rows — THE restart path, run after every emission exactly like
    `summarizer._boot_mergetree` so interrupted and uninterrupted
    summarizers proceed from the identical state."""
    import jax.numpy as jnp

    from ..ops.mergetree_kernel import NOT_REMOVED, PROP_ABSENT
    from ..ops.overlay_pallas import make_overlay_table
    from ..protocol.constants import NO_CLIENT, UNIVERSAL_SEQ

    rep = OverlayFoldReplica(interpret=interpret)
    n = len(rows)
    need_w = _MIN_WINDOW
    while need_w < n + 2 * _CHUNK + 8:
        need_w += _MIN_WINDOW
    W = need_w
    anchor = np.zeros(W, np.int32)
    buf = np.zeros(W, np.int32)
    length = np.zeros(W, np.int32)
    iseq = np.zeros(W, np.int32)
    icl_a = np.full(W, NO_CLIENT, np.int32)
    rseq = np.full(W, NOT_REMOVED, np.int32)
    rcl_a = np.full((W, _KR), NO_CLIENT, np.int32)
    props_a = np.full((W, _KK), PROP_ABSENT, np.int32)
    settled_t: List[int] = []
    settled_p: List[np.ndarray] = []
    m = 0
    for seg, ins, icl, rem, rcl, prow in rows:
        prow_ids = np.full(_KK, PROP_ABSENT, np.int32)
        if prow:
            for k, v in prow.items():
                prow_ids[rep.props.key_id(k)] = rep.props.value_id(v)
        if rem is None and ins <= msn:
            # Settled run: text/props join the settled space directly
            # (ins is UNIVERSAL_SEQ in canonical form; <= msn keeps
            # the rule identical to the kernel boot's semantics).
            settled_t.extend(ord(c) for c in seg)
            settled_p.extend([prow_ids] * len(seg))
            continue
        # Window TEXT row: unsettled insert or an above-window
        # tombstone; anchor = current settled position, text in the
        # arena. Normalized-identity tombstones keep
        # (UNIVERSAL_SEQ, NO_CLIENT) — visible to every perspective,
        # exactly the settled-content rule.
        anchor[m] = len(settled_t)
        buf[m] = rep.arena.append(seg)
        length[m] = len(seg)
        iseq[m] = UNIVERSAL_SEQ if ins <= msn else ins
        icl_a[m] = NO_CLIENT if ins <= msn else icl
        if rem is not None:
            rseq[m] = rem
            if rcl:
                rcl_a[m, : len(rcl)] = rcl
        props_a[m] = prow_ids
        m += 1
    rep.window = W
    rep.settled_t = np.asarray(settled_t, np.int32)
    rep.settled_p = (
        np.stack(settled_p) if settled_p
        else np.zeros((0, _KK), np.int32)
    )
    rep.settled_a = np.zeros(len(settled_t), np.int32)
    rep.table = make_overlay_table(W, _KR, _KK)._replace(
        n_rows=jnp.int32(m),
        anchor=jnp.asarray(anchor),
        buf_start=jnp.asarray(buf),
        length=jnp.asarray(length),
        ins_seq=jnp.asarray(iseq),
        ins_client=jnp.asarray(icl_a),
        rem_seq=jnp.asarray(rseq),
        rem_clients=jnp.asarray(rcl_a),
        props=jnp.asarray(props_a),
        settled_len=jnp.int32(len(settled_t)),
    )
    rep.min_seq = rep._applied_min_seq = int(msn)
    rep._pending_rows_bound = m
    return rep


# ---------------------------------------------------------------------------
# stacked rounds over the device plane
# ---------------------------------------------------------------------------


_STACKED_FN_CACHE: Dict[tuple, Any] = {}


def _stacked_fold_fn(mesh, chunk: int, interpret: bool):
    """Compile the stacked whole-round fold: `lax.map` over the
    stacked doc axis running the fused overlay replay per doc. With a
    device plane the map body shard_maps over BOTH mesh axes — the
    stacked doc axis tiles ``P(('docs', 'model'))``, so K docs spread
    over the whole pool (the `parallel.mesh.sharded_overlay_replay
    _multi` idiom on the 2-D plane). Cached process-wide per (mesh,
    chunk, interpret) — paired with `shared_plane`, every summarizer
    round in a process reuses ONE compiled callable per shape instead
    of re-tracing per emission."""
    key = (mesh, chunk, bool(interpret))
    cached = _STACKED_FN_CACHE.get(key)
    if cached is not None:
        return cached
    import jax

    from ..ops.mergetree_kernel import OpBatch
    from ..ops.overlay_pallas import OverlayTable, replay_fused

    def local(tables, ops, logs, counts, msns):
        def one(args):
            t, o, log, cnt, msn = args
            return replay_fused(t, o, log, cnt, msn, chunk, interpret)

        return jax.lax.map(one, (tables, ops, logs, counts, msns))

    if mesh is None:
        fn = _STACKED_FN_CACHE[key] = jax.jit(local)
        return fn
    from jax.sharding import PartitionSpec as P

    from ..utils.jax_compat import shard_map_compat

    docs = P(("docs", "model"))
    table_specs = OverlayTable(
        n_rows=docs, anchor=docs, buf_start=docs, length=docs,
        ins_seq=docs, ins_client=docs, rem_seq=docs, rem_clients=docs,
        props=docs, settled_len=docs, error=docs,
    )
    op_specs = OpBatch(
        op_type=docs, pos1=docs, pos2=docs, seq=docs, ref_seq=docs,
        client=docs, buf_start=docs, ins_len=docs, prop_keys=docs,
        prop_vals=docs,
    )
    fn = shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(table_specs, op_specs, docs, docs, docs),
        out_specs=(table_specs, docs, docs, docs),
        check=False,
    )
    jitted = _STACKED_FN_CACHE[key] = jax.jit(fn)
    return jitted


def _run_rounds(jobs: List[dict], plane=None,
                interpret: bool = False) -> None:
    """Execute fold-round jobs: singletons run the fused replay
    directly; same-shape groups stack into ONE dispatch (padded with
    empty dummy replicas up to the plane size so the shard_map's doc
    axis divides the mesh). Outputs unstack back into each replica."""
    import jax
    import jax.numpy as jnp

    from ..ops.mergetree_kernel import OpBatch

    def job_device_inputs(job):
        (op_type, pos1, pos2, seq, ref, client, buf, ilen,
         pkeys, pvals) = job["batch"]
        batch = OpBatch(
            op_type=jnp.asarray(op_type), pos1=jnp.asarray(pos1),
            pos2=jnp.asarray(pos2), seq=jnp.asarray(seq),
            ref_seq=jnp.asarray(ref), client=jnp.asarray(client),
            buf_start=jnp.asarray(buf), ins_len=jnp.asarray(ilen),
            prop_keys=jnp.asarray(pkeys), prop_vals=jnp.asarray(pvals),
        )
        log = jnp.zeros((job["log_cap"], 5 + _KK), jnp.int32)
        counts = jnp.zeros(job["n_chunks"], jnp.int32)
        return batch, log, counts, jnp.asarray(job["msns"])

    # Group by the shapes stacking requires to be uniform; chunk
    # padding inside a group re-folds at the same msn — idempotent
    # (nothing new settles, nothing new drops), so padded chunks are
    # semantics-free.
    groups: Dict[tuple, List[dict]] = {}
    for job in jobs:
        groups.setdefault((job["window"],), []).append(job)
    for _key, grp in groups.items():
        # Singletons ride the SAME undonated jitted map as groups
        # (stack of one): the overlay fold never donates a live
        # replica's table buffers — `replay_fused`'s donation only
        # exists inside the traced map body, where it is inert.
        # Uniform chunk count / log cap across the group (pad by
        # repeating the last chunk's msn — an msn-idempotent no-op).
        n_chunks = max(j["n_chunks"] for j in grp)
        log_cap = max(j["log_cap"] for j in grp)
        for j in grp:
            (op_type, pos1, pos2, seq, ref, client, buf, ilen,
             pkeys, pvals) = j["batch"]
            pad = n_chunks * _CHUNK - len(op_type)
            if pad:
                from ..ops.mergetree_kernel import (
                    NO_KEY,
                    OP_NOOP,
                    PROP_ABSENT,
                )
                from ..protocol.constants import NO_CLIENT

                j["batch"] = (
                    np.concatenate([op_type,
                                    np.full(pad, OP_NOOP, np.int32)]),
                    np.concatenate([pos1, np.zeros(pad, np.int32)]),
                    np.concatenate([pos2, np.zeros(pad, np.int32)]),
                    np.concatenate([seq, np.zeros(pad, np.int32)]),
                    np.concatenate([ref, np.zeros(pad, np.int32)]),
                    np.concatenate([client,
                                    np.full(pad, NO_CLIENT, np.int32)]),
                    np.concatenate([buf, np.zeros(pad, np.int32)]),
                    np.concatenate([ilen, np.zeros(pad, np.int32)]),
                    np.concatenate([pkeys,
                                    np.full((pad, _PK), NO_KEY,
                                            np.int32)]),
                    np.concatenate([pvals,
                                    np.full((pad, _PK), PROP_ABSENT,
                                            np.int32)]),
                )
            if j["n_chunks"] < n_chunks:
                j["msns"] = np.concatenate([
                    j["msns"],
                    np.full(n_chunks - j["n_chunks"], j["msns"][-1],
                            np.int32),
                ])
            j["n_chunks"] = n_chunks
            j["log_cap"] = log_cap
        real = len(grp)
        mesh = plane.mesh if plane is not None else None
        if mesh is not None:
            # Pad the stack to a mesh multiple with empty dummies so
            # the shard_map's doc axis divides the device grid.
            size = plane.size
            while len(grp) % size:
                grp.append(_dummy_job(grp[0]))
        stack = lambda *xs: jnp.stack(xs)  # noqa: E731
        tables = jax.tree_util.tree_map(
            stack, *[j["rep"].table if j["rep"] is not None
                     else j["table"] for j in grp]
        )
        devs = [job_device_inputs(j) for j in grp]
        opss = jax.tree_util.tree_map(stack, *[d[0] for d in devs])
        logs = jnp.stack([d[1] for d in devs])
        countss = jnp.stack([d[2] for d in devs])
        msnss = jnp.stack([d[3] for d in devs])
        fn = _stacked_fold_fn(mesh, _CHUNK, interpret)
        out_tables, out_logs, out_counts, _cursors = fn(
            tables, opss, logs, countss, msnss
        )
        out_logs = np.asarray(out_logs)
        out_counts = np.asarray(out_counts)
        for d, j in enumerate(grp[:real]):
            rep = j["rep"]
            table = jax.tree_util.tree_map(
                lambda a, _d=d: a[_d], out_tables
            )
            rep.apply_round(table, out_logs[d], out_counts[d])


def _dummy_job(like: dict) -> dict:
    """An empty padding replica shaped like `like` (rep=None: outputs
    are discarded)."""
    from ..ops.mergetree_kernel import (
        NO_KEY,
        OP_NOOP,
        PROP_ABSENT,
    )
    from ..ops.overlay_pallas import make_overlay_table
    from ..protocol.constants import NO_CLIENT

    pad = like["n_chunks"] * _CHUNK
    return {
        "rep": None,
        "table": make_overlay_table(like["window"], _KR, _KK),
        "window": like["window"],
        "n": 0,
        "n_chunks": like["n_chunks"],
        "batch": (
            np.full(pad, OP_NOOP, np.int32), np.zeros(pad, np.int32),
            np.zeros(pad, np.int32), np.zeros(pad, np.int32),
            np.zeros(pad, np.int32), np.full(pad, NO_CLIENT, np.int32),
            np.zeros(pad, np.int32), np.zeros(pad, np.int32),
            np.full((pad, _PK), NO_KEY, np.int32),
            np.full((pad, _PK), PROP_ABSENT, np.int32),
        ),
        "msns": np.zeros(like["n_chunks"], np.int32),
        "log_cap": like["log_cap"],
    }


def fold_jobs_overlay(jobs: List[Tuple[Any, list]], plane=None,
                      interpret: bool = False) -> None:
    """Drain the pending encoded rows of several overlay replicas —
    the `summarizer._fold_jobs` twin for the overlay backend: each
    replica's round is ONE fused replay dispatch, and same-shape
    replicas stack across the device plane (K summarizing docs tile
    the 2-D pool in one dispatch instead of K)."""
    round_jobs: List[dict] = []
    for rep, _take in jobs:
        job = rep.build_round()
        if job is not None:
            round_jobs.append(job)
    if round_jobs:
        _run_rounds(round_jobs, plane=plane, interpret=interpret)
