"""Host driver for the vectorized merge-tree kernel.

`KernelReplica` is the TPU-backed counterpart of a passive
`MergeTreeEngine` replica: it consumes the totally ordered
SequencedMessage stream (the convergence contract — every replica
replaying the same stream reaches the same state, SURVEY.md §3.3) and
maintains document state on-device as a `SegmentTable`.

Host responsibilities (deliberately outside the kernel):

- Text arena: inserted content is appended to a host-side arena; the
  kernel only moves `(buf_start, length)` spans. `get_text()` gathers
  the final visible spans (reference: merge-tree text is materialized
  the same lazy way via `getText` walks, mergeTree.ts).
- Dictionary encoding: property keys → static columns, values → int
  ids (TPU-idiomatic columnar encoding of the reference's arbitrary
  PropertySet JSON, packages/dds/merge-tree/src/properties.ts).
- Chunking: ops are applied in fixed-size batches (one `lax.scan` jit
  call per chunk) with noop padding; chunk boundaries are
  semantics-free.
- Window compaction (the zamboni role, zamboni.ts:19): tombstones
  whose removal seq is at/below the MSN are physically dropped, and
  maximal runs of "settled" segments (insert seq ≤ MSN, not removed,
  identical props) are coalesced into single rows over a freshly
  rewritten arena. This bounds the live table size by the collab
  window + annotation structure rather than total edit history —
  which is exactly what makes the O(capacity)-per-op kernel fast.
- Capacity: tables are grown (padded) ahead of need so the kernel's
  ERR_CAPACITY can never fire; each op adds at most 2 rows.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..protocol.constants import NO_CLIENT, UNIVERSAL_SEQ
from ..protocol.mergetree_ops import (
    AnnotateOp,
    GroupOp,
    InsertOp,
    MergeTreeOp,
    RemoveOp,
)
from ..protocol.messages import MessageType, SequencedMessage
from .mergetree import MergeTreeEngine  # noqa: F401  (oracle counterpart)
from ..ops.mergetree_kernel import (
    NO_KEY,
    NOT_REMOVED,
    OP_ANNOTATE,
    OP_INSERT,
    OP_NOOP,
    OP_REMOVE,
    PROP_ABSENT,
    PROP_DELETE,
    OpBatch,
    SegmentTable,
    apply_op_batch_jit,
    grow_table,
    make_table,
    raise_kernel_errors,
)


class TextArena:
    """Append-only host text arena addressed by code-point offset."""

    def __init__(self, initial: str = ""):
        self._parts: List[str] = [initial] if initial else []
        self._len = len(initial)

    def append(self, text: str) -> int:
        off = self._len
        self._parts.append(text)
        self._len += len(text)
        return off

    def __len__(self) -> int:
        return self._len

    def snapshot(self) -> str:
        if len(self._parts) != 1:
            self._parts = ["".join(self._parts)]
        return self._parts[0] if self._parts else ""


class PropInterner:
    """key → props column id; value → int id (None/delete is a sentinel)."""

    def __init__(self, max_keys: int):
        self.max_keys = max_keys
        self.key_ids: Dict[str, int] = {}
        self.values: List[Any] = []
        self._value_ids: Dict[str, int] = {}

    def key_id(self, key: str) -> int:
        kid = self.key_ids.get(key)
        if kid is None:
            kid = len(self.key_ids)
            if kid >= self.max_keys:
                raise ValueError(
                    f"more than {self.max_keys} distinct property keys; "
                    "raise n_prop_keys"
                )
            self.key_ids[key] = kid
        return kid

    def value_id(self, value: Any) -> int:
        if value is None:
            return PROP_DELETE
        token = json.dumps(value, sort_keys=True, default=repr)
        vid = self._value_ids.get(token)
        if vid is None:
            vid = len(self.values)
            self.values.append(value)
            self._value_ids[token] = vid
        return vid

    def decode_row(self, row: np.ndarray) -> Optional[dict]:
        out = {}
        for key, kid in self.key_ids.items():
            vid = int(row[kid])
            if vid != PROP_ABSENT:
                out[key] = self.values[vid]
        return out or None


class KernelReplica:
    """TPU-backed passive replica over the totally ordered op stream."""

    def __init__(
        self,
        initial: str = "",
        chunk_size: int = 512,
        capacity: int = 4096,
        n_removers: int = 4,
        n_prop_keys: int = 8,
        max_prop_pairs: int = 4,
        compact_watermark: float = 0.65,
    ):
        self.chunk_size = chunk_size
        self.capacity = capacity
        self.n_removers = n_removers
        self.n_prop_keys = n_prop_keys
        self.max_prop_pairs = max_prop_pairs
        self.compact_watermark = compact_watermark

        self.arena = TextArena(initial)
        self.props = PropInterner(n_prop_keys)
        self.table = make_table(capacity, n_removers, n_prop_keys)
        if initial:
            self.table = self.table._replace(
                n_rows=jnp.int32(1),
                buf_start=self.table.buf_start.at[0].set(0),
                length=self.table.length.at[0].set(len(initial)),
                ins_seq=self.table.ins_seq.at[0].set(UNIVERSAL_SEQ),
                ins_client=self.table.ins_client.at[0].set(NO_CLIENT),
            )
        self.min_seq = 0
        self.current_seq = 0
        # MSN as of the last op actually applied on-device. Compaction
        # must use this (not self.min_seq): encoded-but-unapplied ops
        # have refSeq ≥ the MSN at their sequencing time ≥ this value,
        # so tombstones at/below it are SKIP for every pending op too.
        self._applied_min_seq = 0
        self._pending_rows_bound = int(self.table.n_rows)  # host row-count bound
        self._encoded: List[tuple] = []
        self._applied_since_compact = False

    # ------------------------------------------------------------ encode

    def _encode_op(self, op: MergeTreeOp, msg: SequencedMessage) -> None:
        encode_op(self, op, msg)

    # ------------------------------------------------------------- apply

    def apply_messages(self, msgs: Iterable[SequencedMessage]) -> None:
        for msg in msgs:
            if msg.type == MessageType.OP and msg.contents is not None:
                self._encode_op(msg.contents, msg)
            self.current_seq = msg.sequence_number
            self.min_seq = max(self.min_seq, msg.minimum_sequence_number)
            if len(self._encoded) >= self.chunk_size:
                self._flush_chunks(final=False)
        self._flush_chunks(final=True)

    def _flush_chunks(self, final: bool) -> None:
        while len(self._encoded) >= self.chunk_size or (final and self._encoded):
            chunk = self._encoded[: self.chunk_size]
            del self._encoded[: self.chunk_size]
            self._ensure_capacity()
            batch = self._build_batch(chunk)
            self.table = apply_op_batch_jit(self.table, batch)
            self._applied_min_seq = chunk[-1][10]
            self._applied_since_compact = True
        if (
            self._applied_since_compact
            and self._pending_rows_bound > self.capacity * self.compact_watermark
        ):
            # Guard on ops actually applied since the last compact:
            # when many rows stay unsettled (live collab window), a
            # fresh compact can leave the bound above the watermark,
            # and re-compacting on every no-op flush (e.g. get_text)
            # would rebuild an identical table each call.
            self.compact()

    def _build_batch(self, chunk: list) -> OpBatch:
        B, PK = self.chunk_size, self.max_prop_pairs
        op_type = np.full(B, OP_NOOP, np.int32)
        pos1 = np.zeros(B, np.int32)
        pos2 = np.zeros(B, np.int32)
        seq = np.zeros(B, np.int32)
        ref = np.zeros(B, np.int32)
        client = np.full(B, NO_CLIENT, np.int32)
        buf = np.zeros(B, np.int32)
        ilen = np.zeros(B, np.int32)
        pkeys = np.full((B, PK), NO_KEY, np.int32)
        pvals = np.full((B, PK), PROP_ABSENT, np.int32)
        for i, (t, p1, p2, s, r, c, b, ln, ks, vs, _msn) in enumerate(chunk):
            op_type[i], pos1[i], pos2[i] = t, p1, p2
            seq[i], ref[i], client[i], buf[i], ilen[i] = s, r, c, b, ln
            for j, (k, v) in enumerate(zip(ks, vs)):
                pkeys[i, j], pvals[i, j] = k, v
        return OpBatch(
            op_type=jnp.asarray(op_type),
            pos1=jnp.asarray(pos1),
            pos2=jnp.asarray(pos2),
            seq=jnp.asarray(seq),
            ref_seq=jnp.asarray(ref),
            client=jnp.asarray(client),
            buf_start=jnp.asarray(buf),
            ins_len=jnp.asarray(ilen),
            prop_keys=jnp.asarray(pkeys),
            prop_vals=jnp.asarray(pvals),
        )

    # --------------------------------------------------------- capacity

    def _ensure_capacity(self) -> None:
        needed = self._host_rows_upper_bound() + 2 * self.chunk_size + 8
        if needed <= self.capacity:
            return
        new_cap = self.capacity
        while new_cap < needed:
            new_cap *= 2
        self._grow(new_cap)

    def _host_rows_upper_bound(self) -> int:
        return self._pending_rows_bound

    def _grow(self, new_cap: int) -> None:
        self.table = grow_table(self.table, self.capacity, new_cap)
        self.capacity = new_cap

    # ------------------------------------------------------- compaction

    def compact(self) -> None:
        """Zamboni + settled-run coalescing over a rewritten arena.

        Safe because any future op's refSeq ≥ MSN (deli nacks stale
        refSeqs, deli/lambda.ts:967): a tombstone with removal ≤ MSN is
        SKIP for every future perspective, and a settled row
        (ins_seq ≤ MSN, not removed) is fully VISIBLE for every future
        perspective — so runs of settled rows with identical props are
        indistinguishable from a single loaded row.
        """
        t = jax.tree_util.tree_map(np.asarray, self.table)
        n = int(t.n_rows)
        text = self.arena.snapshot()

        new_rows: List[tuple] = []  # (text, ins_seq, ins_client, rem_seq, rem_clients, props)
        run_parts: List[str] = []
        run_props: Optional[np.ndarray] = None

        def flush_run():
            nonlocal run_parts, run_props
            if run_parts:
                new_rows.append(
                    ("".join(run_parts), UNIVERSAL_SEQ, NO_CLIENT, None, None, run_props)
                )
                run_parts = []
                run_props = None

        for i in range(n):
            rem = int(t.rem_seq[i])
            removed = rem != NOT_REMOVED
            if removed and rem <= self._applied_min_seq:
                continue  # zamboni: tombstone below the window
            seg_text = text[int(t.buf_start[i]) : int(t.buf_start[i]) + int(t.length[i])]
            settled = (not removed) and int(t.ins_seq[i]) <= self._applied_min_seq
            if settled:
                if run_props is not None and not np.array_equal(run_props, t.props[i]):
                    flush_run()
                run_props = t.props[i].copy()
                run_parts.append(seg_text)
            else:
                flush_run()
                new_rows.append(
                    (
                        seg_text,
                        int(t.ins_seq[i]),
                        int(t.ins_client[i]),
                        rem if removed else None,
                        t.rem_clients[i].copy(),
                        t.props[i].copy(),
                    )
                )
        flush_run()

        # Rebuild arena + table.
        m = len(new_rows)
        cap = self.capacity
        while cap // 2 >= max(m + 2 * self.chunk_size + 8, 64) and cap > 64:
            cap //= 2
        buf_start = np.zeros(cap, np.int32)
        length = np.zeros(cap, np.int32)
        ins_seq = np.zeros(cap, np.int32)
        ins_client = np.full(cap, NO_CLIENT, np.int32)
        rem_seq = np.full(cap, NOT_REMOVED, np.int32)
        rem_clients = np.full((cap, self.n_removers), NO_CLIENT, np.int32)
        props = np.full((cap, self.n_prop_keys), PROP_ABSENT, np.int32)
        parts: List[str] = []
        off = 0
        for i, (seg_text, iseq, iclient, rseq, rclients, prow) in enumerate(new_rows):
            buf_start[i] = off
            length[i] = len(seg_text)
            ins_seq[i] = iseq
            ins_client[i] = iclient
            if rseq is not None:
                rem_seq[i] = rseq
                rem_clients[i] = rclients
            if prow is not None:
                props[i] = prow
            parts.append(seg_text)
            off += len(seg_text)
        self.arena = TextArena("".join(parts))
        self.capacity = cap
        # Encoded-but-unapplied ops still hold offsets into the old
        # arena; re-append their text to the new arena and remap.
        if self._encoded:
            remapped = []
            for row in self._encoded:
                if row[0] == OP_INSERT and row[7] > 0:
                    new_off = self.arena.append(text[row[6] : row[6] + row[7]])
                    row = row[:6] + (new_off,) + row[7:]
                remapped.append(row)
            self._encoded = remapped
        err = int(t.error)
        self.table = SegmentTable(
            n_rows=jnp.int32(m),
            buf_start=jnp.asarray(buf_start),
            length=jnp.asarray(length),
            ins_seq=jnp.asarray(ins_seq),
            ins_client=jnp.asarray(ins_client),
            rem_seq=jnp.asarray(rem_seq),
            rem_clients=jnp.asarray(rem_clients),
            props=jnp.asarray(props),
            error=jnp.int32(err),
        )
        self._pending_rows_bound = m + 2 * len(self._encoded)
        self._applied_since_compact = False

    # ------------------------------------------------------------ output

    def check_errors(self) -> None:
        raise_kernel_errors(int(self.table.error))

    def _host_table(self):
        return jax.tree_util.tree_map(np.asarray, self.table)

    def get_text(self) -> str:
        self._flush_chunks(final=True)
        t = self._host_table()
        text = self.arena.snapshot()
        n = int(t.n_rows)
        parts = [
            text[int(t.buf_start[i]) : int(t.buf_start[i]) + int(t.length[i])]
            for i in range(n)
            if int(t.rem_seq[i]) == NOT_REMOVED
        ]
        return "".join(parts)

    def annotated_spans(self) -> List[Tuple[str, Optional[dict]]]:
        self._flush_chunks(final=True)
        t = self._host_table()
        text = self.arena.snapshot()
        out = []
        for i in range(int(t.n_rows)):
            if int(t.rem_seq[i]) == NOT_REMOVED:
                seg = text[int(t.buf_start[i]) : int(t.buf_start[i]) + int(t.length[i])]
                out.append((seg, self.props.decode_row(np.asarray(t.props[i]))))
        return out


class EncoderState:
    """Minimal op-encoder state for non-KernelReplica consumers (the
    overlay replicas): a text arena + prop interner + the encode
    accumulators `encode_op` writes into."""

    def __init__(self, arena: TextArena, props: PropInterner,
                 max_prop_pairs: int):
        self.arena = arena
        self.props = props
        self.max_prop_pairs = max_prop_pairs
        self._encoded: List[tuple] = []
        self._pending_rows_bound = 0


def encode_op(state, op: MergeTreeOp, msg: SequencedMessage) -> None:
    """Encode one sequenced op into columnar rows
    ``(type, pos1, pos2, seq, ref, client, buf, len, keys, vals, msn)``
    appended to ``state._encoded``. `state` is a KernelReplica or an
    EncoderState (anything with arena/props/max_prop_pairs and the two
    accumulators). Prop lists wider than max_prop_pairs split into
    follow-up annotate rows at the same perspective."""
    if isinstance(op, GroupOp):
        for sub in op.ops:
            encode_op(state, sub, msg)
        return
    seq, ref, cid = msg.sequence_number, msg.ref_seq, msg.client_id
    msn = msg.minimum_sequence_number
    pk = state.max_prop_pairs
    keys: List[int] = []
    vals: List[int] = []
    if isinstance(op, InsertOp):
        if op.seg is not None and not isinstance(op.seg, str):
            raise TypeError(
                "KernelReplica is a text engine; item sequences use "
                "ItemKernelReplica semantics (not yet vectorized)"
            )
        text = op.text if op.seg is None else op.seg
        off = state.arena.append(text)
        if op.props:
            for k, v in op.props.items():
                keys.append(state.props.key_id(k))
                vals.append(state.props.value_id(v))
        if len(keys) > pk:
            # Insert with the first PK props, then annotate the
            # inserted range with the rest at the same perspective
            # (at (ref, cid) after the insert, [pos, pos+len) covers
            # exactly the new segment).
            state._encoded.append(
                (OP_INSERT, op.pos, 0, seq, ref, cid, off, len(text),
                 keys[:pk], vals[:pk], msn)
            )
            state._pending_rows_bound += 2
            for i in range(pk, len(keys), pk):
                state._encoded.append(
                    (OP_ANNOTATE, op.pos, op.pos + len(text), seq, ref,
                     cid, 0, 0, keys[i:i + pk], vals[i:i + pk], msn)
                )
                state._pending_rows_bound += 2
            return
        row = (OP_INSERT, op.pos, 0, seq, ref, cid, off, len(text),
               keys, vals, msn)
    elif isinstance(op, RemoveOp):
        row = (OP_REMOVE, op.start, op.end, seq, ref, cid, 0, 0,
               keys, vals, msn)
    elif isinstance(op, AnnotateOp):
        for k, v in op.props.items():
            keys.append(state.props.key_id(k))
            vals.append(state.props.value_id(v))
        if len(keys) > pk:
            # Split into several annotate ops at the same perspective
            # (equivalent: same range, same seq stamps).
            for i in range(0, len(keys), pk):
                state._encoded.append(
                    (OP_ANNOTATE, op.start, op.end, seq, ref, cid, 0, 0,
                     keys[i:i + pk], vals[i:i + pk], msn)
                )
                state._pending_rows_bound += 2
            return
        row = (OP_ANNOTATE, op.start, op.end, seq, ref, cid, 0, 0,
               keys, vals, msn)
    else:
        raise TypeError(f"unknown op {op!r}")
    state._encoded.append(row)
    state._pending_rows_bound += 2
