"""Scalar merge-tree engine: the semantics oracle.

A pointer-free re-implementation of the reference merge-tree's
conflict-resolution semantics over a flat, document-ordered segment list.
It is deliberately simple and slow (O(n) per op) — its job is to be
*obviously correct* so the vectorized JAX kernels
(fluidframework_tpu/ops/mergetree_kernel.py) can be differentially
tested against it, mirroring how the reference fuzz farms
(packages/dds/merge-tree/src/test/client.conflictFarm.spec.ts) assert
replica convergence.

Semantics sources (reference file:line):
- Visibility of a segment at a perspective (refSeq, clientId):
  mergeTree.ts:916 `nodeLength` (remote path) and mergeTree.ts:613
  `localNetLength` (local path). Three outcomes: SKIP (`undefined` —
  tombstone excluded even from tie-breaks), ZERO (invisible but
  participates in tie-breaks), VISIBLE.
- Insert placement + concurrency tie-break: mergeTree.ts:1740
  `insertingWalk` with mergeTree.ts:1719 `breakTie` — the new segment is
  placed before an existing zero-position segment iff
  effective(newSeq) > effective(segSeq), where a new local pending op
  has effective seq +inf and an existing local pending segment +inf-1.
- Range walks (remove/annotate) visit only segments with visible
  length > 0 at the op's perspective: mergeTree.ts `nodeMap` (skips
  len undefined or 0), after splitting at the range boundaries
  (`ensureIntervalBoundary`).
- Overlapping removes keep the earliest sequenced removedSeq and
  accumulate removing client ids: mergeTree.ts:1960 `markRangeRemoved`.
- Acking local ops: mergeTree.ts:1283 `ackPendingSegment` (FIFO pending
  segment groups).
- Annotate conflict resolution: segmentPropertiesManager.ts
  `addProperties` — pending local key updates shadow remote writes until
  acked; `null` deletes a key.
- Zamboni (tombstone collection below the MSN): zamboni.ts:19.
"""

from __future__ import annotations

import enum
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..protocol.constants import (
    EFF_SEQ_EXISTING_LOCAL,
    EFF_SEQ_NEW_LOCAL,
    NON_COLLAB_CLIENT,
    UNASSIGNED_SEQ,
    UNIVERSAL_SEQ,
)
from ..protocol.mergetree_ops import (
    AnnotateOp,
    GroupOp,
    InsertOp,
    MergeTreeDeltaType,
    MergeTreeOp,
    RemoveOp,
)
from ..protocol.messages import DocumentMessage, MessageType, SequencedMessage




class VisCategory(enum.IntEnum):
    SKIP = 0  # excluded from walks entirely (tombstone at/before perspective)
    ZERO = 1  # zero visible length; participates in insert tie-breaks
    VISIBLE = 2


@dataclass(eq=False)
class Segment:
    """One run of content with its merge metadata (reference ISegment,
    mergeTreeNodes.ts:126)."""

    content: Any  # str for text; tuple/list for item sequences
    seq: int  # UNASSIGNED_SEQ while a local insert is pending
    client_id: int
    local_seq: Optional[int] = None
    removed_seq: Optional[int] = None  # None=not removed; UNASSIGNED_SEQ=pending
    local_removed_seq: Optional[int] = None
    removed_clients: List[int] = field(default_factory=list)
    props: Optional[Dict[str, Any]] = None
    # pending local annotate counts per key (segmentPropertiesManager.ts)
    pending_props: Optional[Dict[str, int]] = None
    # pending local op groups this segment belongs to (reference:
    # ISegment.segmentGroups; splitAt copies membership so an ack reaches
    # both halves of a split pending segment).
    groups: List[Any] = field(default_factory=list)
    # local reference positions anchored on this segment (reference
    # ISegment.localRefs, localReference.ts LocalReferenceCollection).
    refs: List["LocalReference"] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.content)

    def split(self, offset: int) -> "Segment":
        """Split self at offset; self keeps [:offset], returns the tail
        (inherits all merge metadata — reference BaseSegment.splitAt)."""
        assert 0 < offset < len(self.content)
        tail = Segment(
            content=self.content[offset:],
            seq=self.seq,
            client_id=self.client_id,
            local_seq=self.local_seq,
            removed_seq=self.removed_seq,
            local_removed_seq=self.local_removed_seq,
            removed_clients=list(self.removed_clients),
            props=dict(self.props) if self.props is not None else None,
            pending_props=dict(self.pending_props) if self.pending_props else None,
            groups=list(self.groups),
        )
        self.content = self.content[:offset]
        for grp in tail.groups:
            grp.segments.append(tail)
        # References at/after the split point move to the tail
        # (localReference.ts LocalReferenceCollection.split).
        moved = [r for r in self.refs if r.offset >= offset]
        self.refs = [r for r in self.refs if r.offset < offset]
        for r in moved:
            r.segment = tail
            r.offset -= offset
        tail.refs = moved
        return tail


@dataclass(eq=False)
class LocalReference:
    """A position anchored to a segment + offset that tracks edits
    (reference LocalReferencePosition,
    packages/dds/merge-tree/src/localReference.ts). `segment is None`
    means the reference points at the end of the document. When the
    anchor segment is removed, resolution *slides* the position to the
    nearest surviving position (SlideOnRemove semantics). `after`
    marks an after-side anchor (reference Side.After): the reference
    denotes the position one past its anchor character while that
    character is visible, and collapses to the slid position once it
    is not."""

    segment: Optional[Segment]
    offset: int = 0
    after: bool = False

    def detach(self) -> None:
        if self.segment is not None and self in self.segment.refs:
            self.segment.refs.remove(self)
        self.segment = None
        self.offset = 0


def _eff_seq(seq: int) -> int:
    """An existing segment's effective seq for tie-break comparisons
    (reference mergeTree.ts:1719 breakTie): a local pending segment
    compares just below a new local op."""
    if seq == UNASSIGNED_SEQ:
        return EFF_SEQ_EXISTING_LOCAL
    return seq


_MISSING = object()  # annotate rollback: key absent before the op


@dataclass(eq=False)
class _PendingGroup:
    """One local op's segment group awaiting ack (reference SegmentGroup)."""

    kind: MergeTreeDeltaType
    segments: List[Segment] = field(default_factory=list)
    props: Optional[Dict[str, Any]] = None  # for annotate acks
    local_seq: Optional[int] = None
    # Per-segment prior prop values (aligned with `segments`), captured
    # by local annotates to make rollback exact (mergeTree.ts:2057).
    prevs: Optional[List[Dict[str, Any]]] = None


class MergeTreeEngine:
    """A single replica's merge state: a document-ordered segment list.

    `local_client_id` is the id this replica submits ops as
    (NON_COLLAB_CLIENT for a passive/replay replica, e.g. the
    server-side summarizer view).
    """

    def __init__(self, local_client_id: int = NON_COLLAB_CLIENT):
        self.segments: List[Segment] = []
        self.local_client_id = local_client_id
        self.collaborating = local_client_id != NON_COLLAB_CLIENT
        self.current_seq = 0
        self.min_seq = 0
        self.local_seq = 0
        self.pending: deque[_PendingGroup] = deque()
        self.zamboni_enabled = True
        # Bumped on structural changes that no (current_seq, local_seq)
        # pair captures (rollback restores state without advancing
        # either) — position-index caches key on it.
        self.structure_version = 0

    # ---------------------------------------------------------------- load

    def load(self, content: Any, props: Optional[dict] = None) -> None:
        """Initialize from summary content (seq = UniversalSequenceNumber,
        reference mergeTree.ts reloadFromSegments)."""
        if len(content) > 0:
            self.segments.append(
                Segment(
                    content=content,
                    seq=UNIVERSAL_SEQ,
                    client_id=NON_COLLAB_CLIENT,
                    props=dict(props) if props else None,
                )
            )

    # ---------------------------------------------------------- visibility

    def _vis(self, seg: Segment, ref_seq: int, client_id: int) -> Tuple[VisCategory, int]:
        """Visibility of `seg` at perspective (ref_seq, client_id).

        Mirrors mergeTree.ts:916 nodeLength. Returns (category, visible
        length)."""
        removed = seg.removed_seq is not None
        if client_id == self.local_client_id and self.collaborating:
            # Local perspective (localNetLength, mergeTree.ts:613): the
            # local replica has applied every sequenced op plus its own
            # pending ones, so any removal (acked or pending) hides the
            # segment; tombstones at/below the MSN are zamboni-eligible
            # and must be skipped entirely.
            if removed:
                norm = (
                    float("inf")
                    if seg.removed_seq == UNASSIGNED_SEQ
                    else seg.removed_seq
                )
                if norm > self.min_seq:
                    return (VisCategory.ZERO, 0)
                return (VisCategory.SKIP, 0)
            return (VisCategory.VISIBLE, len(seg))

        # Remote perspective.
        if removed and seg.removed_seq != UNASSIGNED_SEQ and seg.removed_seq <= ref_seq:
            # Tombstone at this perspective: may not exist on other
            # replicas — excluded from all decisions.
            return (VisCategory.SKIP, 0)
        if seg.client_id == client_id or (
            seg.seq != UNASSIGNED_SEQ and seg.seq <= ref_seq
        ):
            # Insert visible at this perspective.
            if removed and client_id in seg.removed_clients:
                return (VisCategory.ZERO, 0)
            return (VisCategory.VISIBLE, len(seg))
        # Insert not visible.
        if removed and seg.removed_seq != UNASSIGNED_SEQ:
            # Inserted and (remotely) removed, both unseen by this
            # client: will never exist for it.
            return (VisCategory.SKIP, 0)
        return (VisCategory.ZERO, 0)

    def visible_length(self, ref_seq: int, client_id: int) -> int:
        return sum(self._vis(s, ref_seq, client_id)[1] for s in self.segments)

    # ------------------------------------------------------------- insert

    def insert(
        self,
        pos: int,
        content: Any,
        ref_seq: int,
        client_id: int,
        seq: int,
        props: Optional[dict] = None,
    ) -> Segment:
        """Insert `content` at visible position `pos` of perspective
        (ref_seq, client_id), with op sequence number `seq`
        (UNASSIGNED_SEQ for a pending local op).

        Placement mirrors insertingWalk + breakTie (mergeTree.ts:1740,
        :1719): walk document order accumulating visible lengths; land
        strictly inside a VISIBLE segment -> split it; at a boundary,
        place the new segment before the first non-SKIP segment whose
        effective seq is lower than the op's.
        """
        eff_new = EFF_SEQ_NEW_LOCAL if seq == UNASSIGNED_SEQ else seq
        local_seq = None
        if seq == UNASSIGNED_SEQ:
            self.local_seq += 1
            local_seq = self.local_seq
        # None-valued insert props are absent (the null-deletes
        # convention applies uniformly; keeps parity with the kernel's
        # dictionary encoding where PROP_DELETE never materializes).
        clean_props = (
            {k: v for k, v in props.items() if v is not None} if props else None
        )
        new_seg = Segment(
            content=content,
            seq=seq,
            client_id=client_id,
            local_seq=local_seq,
            props=clean_props or None,
        )

        remaining = pos
        insert_at = len(self.segments)  # default: append at end
        for i, seg in enumerate(self.segments):
            cat, length = self._vis(seg, ref_seq, client_id)
            if cat == VisCategory.SKIP:
                continue
            if remaining < length:
                # Lands inside or immediately before a VISIBLE segment.
                # At its position 0 the tie-break always favors the new
                # op (a visible segment's seq is <= refSeq < newSeq; an
                # existing local pending segment yields to a new local).
                if remaining == 0:
                    insert_at = i
                else:
                    tail = seg.split(remaining)
                    self.segments.insert(i + 1, tail)
                    self.structure_version += 1
                    insert_at = i + 1
                break
            if remaining == 0 and length == 0:
                # breakTie (mergeTree.ts:1719): place before iff the new
                # op's effective seq is strictly greater than the
                # segment's (new local = INT32_MAX beats existing local
                # = INT32_MAX - 1 beats any sequenced seq).
                if eff_new > _eff_seq(seg.seq):
                    insert_at = i
                    break
                continue
            remaining -= length
        else:
            if remaining > 0:
                raise ValueError(
                    f"insert pos {pos} beyond visible length at perspective "
                    f"({ref_seq},{client_id})"
                )
            insert_at = len(self.segments)

        self.segments.insert(insert_at, new_seg)
        self.structure_version += 1

        if seq == UNASSIGNED_SEQ:
            grp = _PendingGroup(kind=MergeTreeDeltaType.INSERT, local_seq=local_seq)
            grp.segments.append(new_seg)
            new_seg.groups.append(grp)
            self.pending.append(grp)
        return new_seg

    # ------------------------------------------------------------- remove

    def _ensure_boundary(self, pos: int, ref_seq: int, client_id: int) -> None:
        """Split a VISIBLE segment so visible position `pos` is a segment
        boundary (reference ensureIntervalBoundary, mergeTree.ts:1706)."""
        remaining = pos
        for i, seg in enumerate(self.segments):
            cat, length = self._vis(seg, ref_seq, client_id)
            if cat == VisCategory.SKIP:
                continue
            if remaining < length:
                if remaining > 0:
                    tail = seg.split(remaining)
                    self.segments.insert(i + 1, tail)
                    self.structure_version += 1
                return
            remaining -= length

    def remove_range(
        self, start: int, end: int, ref_seq: int, client_id: int, seq: int
    ) -> List[Segment]:
        """Mark [start, end) removed at perspective (ref_seq, client_id).

        Mirrors markRangeRemoved (mergeTree.ts:1960): only segments with
        visible length > 0 at the perspective are marked; overlapping
        removes keep the earliest sequenced removedSeq; a local pending
        remove overtaken by a remote one puts the remote client at the
        head of the removing-client list.
        """
        assert end > start >= 0
        self._ensure_boundary(start, ref_seq, client_id)
        self._ensure_boundary(end, ref_seq, client_id)

        local_seq = None
        if seq == UNASSIGNED_SEQ:
            self.local_seq += 1
            local_seq = self.local_seq

        marked: List[Segment] = []
        marked_refs: List[tuple] = []  # (segment, index) for sliding
        pos = 0
        for seg_i, seg in enumerate(self.segments):
            if pos >= end:
                break
            cat, length = self._vis(seg, ref_seq, client_id)
            if cat == VisCategory.SKIP or length == 0:
                continue
            if pos >= start:  # boundary splits guarantee full containment
                if seg.refs:
                    marked_refs.append((seg, seg_i))
                if seg.removed_seq is not None:
                    if seg.removed_seq == UNASSIGNED_SEQ:
                        # Our pending local remove lost the race: the
                        # remote remover goes to the head of the list and
                        # its seq becomes the removal seq.
                        seg.removed_clients.insert(0, client_id)
                        seg.removed_seq = seq
                    else:
                        # Overlapping sequenced removes: keep earliest.
                        seg.removed_clients.append(client_id)
                else:
                    seg.removed_seq = seq
                    seg.removed_clients = [client_id]
                    seg.local_removed_seq = local_seq
                marked.append(seg)
            pos += length

        if seq == UNASSIGNED_SEQ:
            grp = _PendingGroup(kind=MergeTreeDeltaType.REMOVE, local_seq=local_seq)
            # Only segments newly removed by us are pending-acked.
            for s in marked:
                if s.removed_seq == UNASSIGNED_SEQ:
                    grp.segments.append(s)
                    s.groups.append(grp)
            self.pending.append(grp)
        else:
            # SEQUENCED removal: slide references off the tombstones NOW
            # (SlideOnRemove, localReference.ts). Every replica executes
            # this at the same point in the total order, when the
            # visible neighborhood is convergent — sliding later (at
            # zamboni) would race replica-local pending inserts adjacent
            # to the tombstone and anchor different characters.
            for s, i in marked_refs:
                if s.refs:
                    self._slide_refs_off(s, hint_index=i)
        return marked

    def _slide_refs_off(
        self, seg: Segment, hint_index: Optional[int] = None
    ) -> None:
        """Move `seg`'s references to the start of the next segment
        that is neither acked-removed nor a replica-local pending
        insert; document end if none. The target set is exactly the
        segments every replica agrees exist at this total-order point:
        pending-REMOVED segments are included (alive on other
        replicas; when their removal sequences, every replica —
        including this one — re-slides them together), pending local
        INSERTS are excluded (they exist only here). This keeps
        fully-acked replicas convergent."""
        refs, seg.refs = seg.refs, []
        if not refs:
            return
        # A slide can move a reference PAST pending-local segments
        # (excluded targets), inverting its stable order relative to
        # references anchored on them — order-keyed consumers (the
        # interval index) repair when this version changes.
        self.slide_version = getattr(self, "slide_version", 0) + 1
        if hint_index is not None and (
            hint_index < len(self.segments)
            and self.segments[hint_index] is seg
        ):
            i = hint_index
        else:
            try:
                i = self.segments.index(seg)
            except ValueError:
                i = len(self.segments)
        target: Optional[Segment] = None
        for s in self.segments[i + 1:]:
            if (
                s.removed_seq != UNASSIGNED_SEQ
                and s.removed_seq is not None
            ):
                continue  # acked tombstone: gone everywhere
            if s.seq == UNASSIGNED_SEQ or len(s.content) == 0:
                continue  # pending local insert: exists only here
            target = s
            break
        for r in refs:
            r.segment = target
            r.offset = 0
            # "after X" with X gone collapses to X's old spot — the
            # slid-to segment's start, not one past it.
            r.after = False
            if target is not None:
                target.refs.append(r)

    # ----------------------------------------------------------- annotate

    def annotate_range(
        self,
        start: int,
        end: int,
        props: Dict[str, Any],
        ref_seq: int,
        client_id: int,
        seq: int,
    ) -> None:
        """Set properties on [start, end) at the op's perspective.

        Conflict rule (segmentPropertiesManager.ts addProperties): a
        remote write to a key with pending local updates is ignored
        (the local value will win when sequenced); `None` deletes.
        """
        assert end > start >= 0
        self._ensure_boundary(start, ref_seq, client_id)
        self._ensure_boundary(end, ref_seq, client_id)
        is_local = seq == UNASSIGNED_SEQ
        if is_local:
            self.local_seq += 1

        pending_segs: List[Segment] = []
        prevs: List[Dict[str, Any]] = []
        pos = 0
        for seg in self.segments:
            if pos >= end:
                break
            cat, length = self._vis(seg, ref_seq, client_id)
            if cat == VisCategory.SKIP or length == 0:
                continue
            if pos >= start:
                if seg.props is None:
                    seg.props = {}
                prev: Dict[str, Any] = {}
                for key, value in props.items():
                    if is_local:
                        prev[key] = seg.props.get(key, _MISSING)
                        if seg.pending_props is None:
                            seg.pending_props = {}
                        seg.pending_props[key] = seg.pending_props.get(key, 0) + 1
                        _set_prop(seg.props, key, value)
                    else:
                        if seg.pending_props and seg.pending_props.get(key):
                            continue  # shadowed by pending local write
                        _set_prop(seg.props, key, value)
                pending_segs.append(seg)
                prevs.append(prev)
            pos += length

        if is_local:
            grp = _PendingGroup(
                kind=MergeTreeDeltaType.ANNOTATE,
                props=dict(props),
                local_seq=self.local_seq,
                prevs=prevs,
            )
            for s in pending_segs:
                grp.segments.append(s)
                s.groups.append(grp)
            self.pending.append(grp)

    # ----------------------------------------------------------------- ack

    def ack(self, seq: int) -> None:
        """Ack the oldest pending local op with its assigned sequence
        number (reference ackPendingSegment, mergeTree.ts:1283)."""
        grp = self.pending.popleft()
        for seg in grp.segments:
            try:
                seg.groups.remove(grp)
            except ValueError:
                pass
        if grp.kind == MergeTreeDeltaType.INSERT:
            for seg in grp.segments:
                seg.seq = seq
                seg.local_seq = None
        elif grp.kind == MergeTreeDeltaType.REMOVE:
            for seg in grp.segments:
                if seg.removed_seq == UNASSIGNED_SEQ:
                    seg.removed_seq = seq
                # else: an overlapping remote remove was sequenced first
                # and already owns removed_seq (keep earliest).
                seg.local_removed_seq = None
                # The removal is now sequenced: slide references off the
                # tombstone at this total-order point (SlideOnRemove —
                # see remove_range's sequenced branch).
                if seg.refs:
                    self._slide_refs_off(seg)
        elif grp.kind == MergeTreeDeltaType.ANNOTATE:
            for seg in grp.segments:
                if seg.pending_props:
                    for key in grp.props or {}:
                        cnt = seg.pending_props.get(key)
                        if cnt:
                            if cnt == 1:
                                del seg.pending_props[key]
                            else:
                                seg.pending_props[key] = cnt - 1

    # ------------------------------------------------------------ rollback

    def rollback(self, grp: "_PendingGroup") -> None:
        """Roll back the MOST RECENT pending local op (reference
        MergeTree.rollback, mergeTree.ts:2057 — the orderSequentially
        abort path, which unwinds in LIFO order before any other op
        can interleave).

        - insert: the pending segments are physically dropped (no
          other replica ever saw them); references slide forward to
          the next survivor, as in zamboni collection;
        - remove: the pending removal marks are cleared;
        - annotate: prior values (captured at apply) are restored and
          the pending-write shadow counts decremented.
        """
        assert self.pending and self.pending[-1] is grp, (
            "rollback out of order: only the newest pending op can roll back"
        )
        self.structure_version += 1
        self.pending.pop()
        for s in grp.segments:
            s.groups = [g for g in s.groups if g is not grp]
        if grp.kind == MergeTreeDeltaType.INSERT:
            dead = {id(s) for s in grp.segments}
            kept: List[Segment] = []
            orphaned: List[LocalReference] = []
            for s in self.segments:
                if id(s) in dead:
                    orphaned.extend(s.refs)
                    s.refs = []
                else:
                    if orphaned:
                        for r in orphaned:
                            r.segment = s
                            r.offset = 0
                            s.refs.append(r)
                        orphaned = []
                    kept.append(s)
            for r in orphaned:
                r.segment = None
                r.offset = 0
            self.segments = kept
        elif grp.kind == MergeTreeDeltaType.REMOVE:
            for s in grp.segments:
                if s.removed_seq == UNASSIGNED_SEQ:
                    s.removed_seq = None
                    s.local_removed_seq = None
                    s.removed_clients = []
        else:  # ANNOTATE
            for s, prev in zip(grp.segments, grp.prevs or []):
                for key, prior in prev.items():
                    if prior is _MISSING:
                        if s.props is not None:
                            s.props.pop(key, None)
                    else:
                        if s.props is None:
                            s.props = {}
                        s.props[key] = prior
                    cnt = (s.pending_props or {}).get(key)
                    if cnt:
                        if cnt == 1:
                            del s.pending_props[key]
                        else:
                            s.pending_props[key] = cnt - 1

    # ------------------------------------------------- reconnect / rebase

    def _group_index(self, seg: Segment, kind: "MergeTreeDeltaType"):
        for g in seg.groups:
            if g.kind == kind:
                try:
                    return list(self.pending).index(g)
                except ValueError:
                    return None
        return None

    def _reg_vis_len(self, seg: Segment, idx: int) -> int:
        """Visible length of `seg` at the perspective a regenerated op
        (pending-FIFO position `idx`) will be applied at by remote
        replicas: everything sequenced plus our earlier pending groups
        (they sequence first), excluding our later pending state."""
        if seg.seq == UNASSIGNED_SEQ:
            gi = self._group_index(seg, MergeTreeDeltaType.INSERT)
            if gi is None or gi >= idx:
                return 0  # not yet sequenced when this op applies
        if seg.removed_seq is not None:
            if seg.removed_seq != UNASSIGNED_SEQ:
                return 0  # sequenced removal: tombstone at any future refSeq
            gi = self._group_index(seg, MergeTreeDeltaType.REMOVE)
            if gi is not None and gi < idx:
                return 0  # earlier pending remove sequences first
        return len(seg)

    def regenerate_pending(
        self, grps: List["_PendingGroup"], original: "MergeTreeOp"
    ) -> "Tuple[Optional[MergeTreeOp], List[_PendingGroup]]":
        """Rebase the pending local op backed by `grps` for
        resubmission after reconnect (reference
        Client.regeneratePendingOp / normalizeSegmentsOnRebase,
        client.ts:917). `grps` is every pending group backing the one
        wire message being resubmitted: one group for a first-time
        resubmit, several when a previous reconnect already split a
        range op into per-segment groups.

        Returns ``(op, groups)`` where `groups` are the pending groups
        backing the returned op, **in sub-op order** (len == number of
        sub-ops; a GroupOp of N ops is backed by N groups, so its
        single sequenced ack pops one group per sub-op). Callers MUST
        store `groups` — not the stale input — as the resubmitted
        message's local metadata, or a second reconnect will misread
        the stale group's absence from the pending FIFO as "already
        sequenced" and silently drop the op.

        Returns ``(None, [])`` if nothing remains to resubmit (the
        input groups are dropped from the FIFO in that case).
        """
        ops: List[MergeTreeOp] = []
        out_groups: List[_PendingGroup] = []
        for grp in grps:
            if all(g is not grp for g in self.pending):
                continue  # this piece already sequenced during catch-up
            sub_ops, sub_groups = self._regenerate_one(grp, original)
            ops.extend(sub_ops)
            out_groups.extend(sub_groups)
        if not ops:
            return None, []
        if len(ops) == 1:
            return ops[0], out_groups
        return GroupOp(ops=ops), out_groups

    def _regenerate_one(
        self, grp: "_PendingGroup", original: "MergeTreeOp"
    ) -> "Tuple[List[MergeTreeOp], List[_PendingGroup]]":
        order = list(self.pending)
        idx = order.index(grp)
        seg_pos = {id(s): i for i, s in enumerate(self.segments)}
        segs = sorted(
            [s for s in grp.segments if id(s) in seg_pos],
            key=lambda s: seg_pos[id(s)],
        )
        # Segments may have been stamped under a previous connection's
        # client id; the op resubmits under the current identity.
        for s in segs:
            s.client_id = self.local_client_id

        def base_pos(target: Segment) -> int:
            total = 0
            for s in self.segments:
                if s is target:
                    return total
                total += self._reg_vis_len(s, idx)
            raise AssertionError("pending segment not in segment list")

        if grp.kind == MergeTreeDeltaType.INSERT:
            if not segs:
                self.pending.remove(grp)
                return [], []
            text_parts = [s.content for s in segs]
            content = (
                "".join(text_parts)
                if isinstance(text_parts[0], str)
                else [x for part in text_parts for x in part]
            )
            props = original.props if isinstance(original, InsertOp) else None
            pos = base_pos(segs[0])
            if isinstance(content, str):
                return [InsertOp(pos=pos, text=content, props=props)], [grp]
            return [InsertOp(pos=pos, seg=content, props=props)], [grp]

        # A segment whose removal has already *sequenced* (a remote
        # remove overtook our pending one) is a tombstone for every
        # future perspective: the regenerated remove/annotate must not
        # cite it, or receivers would hit unrelated visible content.
        segs = [
            s for s in segs
            if not (s.removed_seq is not None and s.removed_seq != UNASSIGNED_SEQ)
        ]
        if not segs:
            self.pending.remove(grp)
            return [], []

        # Split the group: one per-segment group in place of the original.
        at = idx
        self.pending.remove(grp)
        new_groups = []
        for s in segs:
            g = _PendingGroup(kind=grp.kind, props=grp.props, local_seq=grp.local_seq)
            g.segments.append(s)
            s.groups = [x for x in s.groups if x is not grp] + [g]
            new_groups.append(g)
        for offset, g in enumerate(new_groups):
            self.pending.insert(at + offset, g)

        ops: List[MergeTreeOp] = []
        removed_before = 0
        for s in segs:
            start = base_pos(s) - removed_before
            end = start + len(s)
            if grp.kind == MergeTreeDeltaType.REMOVE:
                ops.append(RemoveOp(start=start, end=end))
                removed_before += len(s)
            else:
                ops.append(
                    AnnotateOp(start=start, end=end, props=dict(grp.props or {}))
                )
        return ops, new_groups

    # --------------------------------------------------- local references

    def verify_invariants(self) -> None:
        """Exhaustive structural verification (opt-in, the role of the
        reference's PartialSequenceLengths verifier option,
        partialLengths.ts:336): raises AssertionError on any violated
        invariant. O(segments * pending) — test/debug use only."""
        seg_ids = {id(s) for s in self.segments}
        assert self.min_seq <= self.current_seq, "minSeq above currentSeq"
        for i, s in enumerate(self.segments):
            assert len(s) > 0, f"segment {i}: empty content"
            if s.removed_seq is None:
                assert not s.removed_clients, f"segment {i}: removers without removal"
            else:
                if s.removed_seq == UNASSIGNED_SEQ:
                    assert s.local_removed_seq is not None or s.groups, (
                        f"segment {i}: pending removal without local state"
                    )
                else:
                    assert s.removed_clients, f"segment {i}: removal without removers"
                    assert s.removed_seq >= s.seq or s.seq == UNASSIGNED_SEQ, (
                        f"segment {i}: removed before inserted"
                    )
            if s.seq == UNASSIGNED_SEQ:
                assert s.client_id == self.local_client_id, (
                    f"segment {i}: pending insert by foreign client {s.client_id}"
                )
            for g in s.groups:
                assert any(g is p for p in self.pending), (
                    f"segment {i}: group not in pending FIFO"
                )
            for r in s.refs:
                assert r.segment is s, f"segment {i}: foreign ref"
                assert 0 <= r.offset <= len(s), f"segment {i}: ref offset oob"
        for g in self.pending:
            for s in g.segments:
                assert id(s) in seg_ids, "pending group cites a ghost segment"
        # Cross-check: the visible length at the local head must equal
        # the materialized text length (an INDEPENDENT computation:
        # get_text walks removal state, visible_length walks the
        # perspective predicate).
        assert self.visible_length(
            self.current_seq, self.local_client_id
        ) == len(self.get_text()), "visible length != materialized text"
        # And perspectives are monotone: content visible at the MSN
        # perspective can never exceed the head perspective plus
        # pending local growth.
        head = self.visible_length(self.current_seq, self.local_client_id)
        for s in self.segments:
            cat, ln = self._vis(s, self.current_seq, self.local_client_id)
            assert ln <= len(s), "visibility length exceeds content"
        assert head >= 0

    def anchor_at(
        self, pos: int, ref_seq: int, client_id: int,
        after: bool = False,
    ) -> LocalReference:
        """Anchor a reference at visible position `pos` of perspective
        (ref_seq, client_id) (reference createLocalReferencePosition,
        client.ts / mergeTree.ts). pos == visible length anchors the
        document end (segment None). `after` marks an after-side
        anchor (cleared if the anchor immediately slides)."""
        remaining = pos
        for seg in self.segments:
            cat, length = self._vis(seg, ref_seq, client_id)
            if cat == VisCategory.SKIP or length == 0:
                continue
            if remaining < length:
                ref = LocalReference(
                    segment=seg, offset=remaining, after=after
                )
                seg.refs.append(ref)
                if (
                    seg.removed_seq is not None
                    and seg.removed_seq != UNASSIGNED_SEQ
                ):
                    # The char is visible at the op's perspective but
                    # its removal ALREADY sequenced — the slide pass
                    # for that removal has run, so slide now (every
                    # replica anchoring after the removal in total
                    # order does the same; ones that anchored before
                    # it slid at the removal). No reference may sit on
                    # an acked tombstone.
                    self._slide_refs_off(seg)
                return ref
            remaining -= length
        if remaining > 0:
            raise ValueError(f"anchor pos {pos} beyond visible length")
        return LocalReference(segment=None)

    def local_position(self, ref: LocalReference) -> int:
        """Resolve a reference to a visible position at the local
        perspective, sliding forward off removed segments
        (SlideOnRemove, localReference.ts)."""
        return self._resolve_ref(ref, honor_after=False)

    def resolve_reference(self, ref: LocalReference) -> int:
        """`local_position` honoring the reference's after-side: one
        past the anchor character while it is visible, collapsed to
        the slid position once it is not (Side.After resolution)."""
        return self._resolve_ref(ref, honor_after=True)

    def _resolve_ref(self, ref: LocalReference, honor_after: bool) -> int:
        if ref.segment is None:
            return self.visible_length(self.current_seq, self.local_client_id)
        pos = 0
        for seg in self.segments:
            cat, length = self._vis(seg, self.current_seq, self.local_client_id)
            if seg is ref.segment:
                if cat == VisCategory.VISIBLE:
                    p = pos + min(ref.offset, length)
                    if honor_after and ref.after:
                        p += 1
                    return p
                return pos  # removed anchor: slide to nearest survivor
            if cat != VisCategory.SKIP:
                pos += length
        # Anchor segment no longer tracked (shouldn't happen: zamboni
        # re-anchors); treat as end.
        return pos

    # ------------------------------------------------------------ windows

    def update_min_seq(self, min_seq: int) -> None:
        """Advance the MSN and run zamboni: physically drop tombstones
        whose removal is at/below the MSN (zamboni.ts:19). References on
        collected segments slide to the next surviving segment."""
        assert min_seq >= self.min_seq
        self.min_seq = min_seq
        if not self.zamboni_enabled:
            return
        kept: List[Segment] = []
        orphaned: List[LocalReference] = []
        for s in self.segments:
            dead = (
                s.removed_seq is not None
                and s.removed_seq != UNASSIGNED_SEQ
                and s.removed_seq <= min_seq
            )
            if dead:
                orphaned.extend(s.refs)
                s.refs = []
            else:
                if orphaned:
                    # Slide orphans to the front of the next survivor.
                    for r in orphaned:
                        r.segment = s
                        r.offset = 0
                        s.refs.append(r)
                    orphaned = []
                kept.append(s)
        for r in orphaned:  # removed tail: anchor to document end
            r.segment = None
            r.offset = 0
        self.segments = kept
        self.structure_version += 1

    # ------------------------------------------------------------- output

    def get_text(self) -> str:
        """Concatenated visible text from the local perspective.
        Item-content engines (e.g. permutation vectors) use get_items()."""
        parts = []
        for seg in self.segments:
            if seg.removed_seq is None:
                if not isinstance(seg.content, str):
                    raise TypeError("non-text engine: use get_items()")
                parts.append(seg.content)
        return "".join(parts)

    def get_items(self) -> List[Any]:
        out: List[Any] = []
        for seg in self.segments:
            if seg.removed_seq is None:
                out.extend(seg.content)
        return out

    def enable_attribution(self) -> None:
        """Parity seam with the native engine's attribution tracking.
        The oracle never coalesces segments, so per-position insert
        attribution is fully derived from segment metadata (key =
        insert seq; UNASSIGNED while pending; 0 for loaded content) —
        enabling is a no-op flag."""
        self._track_attr = True

    def attribution_spans(self) -> List[Tuple[int, int]]:
        """(run_length, attribution key) runs over the visible
        document, adjacent equal keys merged — must match the native
        engine's hm_attr_spans bit-for-bit (attributionCollection.ts
        role; farm-gated)."""
        out: List[Tuple[int, int]] = []
        for s in self.segments:
            if s.removed_seq is not None or len(s.content) == 0:
                continue
            if s.client_id == NON_COLLAB_CLIENT:
                key = 0
            else:
                key = s.seq
            if out and out[-1][1] == key:
                out[-1] = (out[-1][0] + len(s.content), key)
            else:
                out.append((len(s.content), key))
        return out

    def annotated_spans(self) -> List[Tuple[Any, Optional[dict]]]:
        """(content, props) for each visible segment — for convergence
        assertions that include annotations."""
        return [
            (s.content, dict(s.props) if s.props else None)
            for s in self.segments
            if s.removed_seq is None
        ]


def _set_prop(props: Dict[str, Any], key: str, value: Any) -> None:
    if value is None:
        props.pop(key, None)
    else:
        props[key] = value


def apply_remote_op(
    engine: MergeTreeEngine,
    op: MergeTreeOp,
    ref_seq: int,
    client_id: int,
    seq: int,
) -> None:
    """Apply a sequenced remote op at its perspective (the routing of
    reference Client.applyRemoteOp, client.ts:802)."""
    if isinstance(op, GroupOp):
        for sub in op.ops:
            apply_remote_op(engine, sub, ref_seq, client_id, seq)
        return
    if isinstance(op, InsertOp):
        content = op.text if op.seg is None else op.seg
        engine.insert(op.pos, content, ref_seq, client_id, seq, props=op.props)
    elif isinstance(op, RemoveOp):
        engine.remove_range(op.start, op.end, ref_seq, client_id, seq)
    elif isinstance(op, AnnotateOp):
        engine.annotate_range(op.start, op.end, op.props, ref_seq, client_id, seq)
    else:
        raise TypeError(f"unknown op {op!r}")


def replay_passive(stream, initial: Any = "",
                   on_message=None) -> MergeTreeEngine:
    """Replay a totally ordered SequencedMessage stream into a fresh
    passive replica (the server-side summarizer view; also the scalar
    oracle for the vectorized kernel's replay path). `on_message(i,
    engine)` runs after each message — staged-digest tools hook here
    so they replay with EXACTLY these semantics."""
    engine = MergeTreeEngine()
    if len(initial) > 0:
        engine.load(initial)
    for i, msg in enumerate(stream):
        if msg.type == MessageType.OP and msg.contents is not None:
            apply_remote_op(
                engine, msg.contents, msg.ref_seq, msg.client_id,
                msg.sequence_number,
            )
        engine.current_seq = msg.sequence_number
        engine.update_min_seq(max(engine.min_seq, msg.minimum_sequence_number))
        if on_message is not None:
            on_message(i, engine)
    return engine


class CollabClient:
    """A collaborating replica: local edits + sequenced-stream application.

    Mirrors the role of merge-tree `Client` (reference
    packages/dds/merge-tree/src/client.ts:98): local ops are applied
    optimistically and queued; `apply_msg` (client.ts:858) routes a
    sequenced message either to the ack path (own op) or the remote
    apply path, then advances the collaboration window.
    """

    def __init__(self, client_id: int, initial: str = "",
                 engine: str = "auto"):
        """`engine` picks the merge engine implementation: "auto"
        (native C++ hostmerge when available — the production
        interactive path), "native", or "python" (this module's
        oracle; tests that introspect `engine.segments` need it)."""
        if engine not in ("auto", "native", "python"):
            raise ValueError(f"unknown engine {engine!r}")
        self.client_id = client_id
        if engine == "python":
            self.engine = MergeTreeEngine(local_client_id=client_id)
        else:
            from .native_engine import make_merge_engine

            self.engine = make_merge_engine(client_id, prefer_native=True)
            if engine == "native" and isinstance(
                self.engine, MergeTreeEngine
            ):
                raise RuntimeError("native engine unavailable")
        if initial:
            self.engine.load(initial)
        self.client_seq = 0

    # ------------------------------------------------------- local edits

    def _make_msg(self, op: MergeTreeOp) -> DocumentMessage:
        self.client_seq += 1
        return DocumentMessage(
            client_seq=self.client_seq,
            ref_seq=self.engine.current_seq,
            type=MessageType.OP,
            contents=op,
        )

    def insert_local(self, pos: int, content: Any, props: Optional[dict] = None) -> DocumentMessage:
        self.engine.insert(
            pos,
            content,
            self.engine.current_seq,
            self.client_id,
            UNASSIGNED_SEQ,
            props=props,
        )
        if isinstance(content, str):
            return self._make_msg(InsertOp(pos=pos, text=content, props=props))
        return self._make_msg(InsertOp(pos=pos, seg=list(content), props=props))

    def remove_local(self, start: int, end: int) -> DocumentMessage:
        self.engine.remove_range(
            start, end, self.engine.current_seq, self.client_id, UNASSIGNED_SEQ
        )
        return self._make_msg(RemoveOp(start=start, end=end))

    def annotate_local(self, start: int, end: int, props: dict) -> DocumentMessage:
        self.engine.annotate_range(
            start, end, props, self.engine.current_seq, self.client_id, UNASSIGNED_SEQ
        )
        return self._make_msg(AnnotateOp(start=start, end=end, props=dict(props)))

    # --------------------------------------------------- sequenced input

    def apply_msg(self, msg: SequencedMessage) -> None:
        # Non-op messages (join/leave/noop/summarize...) only advance the
        # collaboration window (reference client.ts:858 applyMsg switch).
        if msg.type == MessageType.OP:
            op = msg.contents
            if msg.client_id == self.client_id:
                self._ack_op(op, msg.sequence_number)
            else:
                self._apply_remote(op, msg)
        self.engine.current_seq = msg.sequence_number
        self.engine.update_min_seq(
            max(self.engine.min_seq, msg.minimum_sequence_number)
        )

    def apply_msgs(self, msgs) -> None:
        """Apply a run of sequenced messages; one native batch call
        when the engine supports it (hm_apply_batch), else the
        per-message loop. Identical semantics either way."""
        batch = getattr(self.engine, "apply_sequenced_batch", None)
        if batch is not None:
            batch(msgs)
            return
        for m in msgs:
            self.apply_msg(m)

    def _ack_op(self, op: MergeTreeOp, seq: int) -> None:
        if isinstance(op, GroupOp):
            for sub in op.ops:
                self.engine.ack(seq)
            return
        self.engine.ack(seq)

    def _apply_remote(self, op: MergeTreeOp, msg: SequencedMessage) -> None:
        apply_remote_op(
            self.engine, op, msg.ref_seq, msg.client_id, msg.sequence_number
        )

    # ----------------------------------------------------------- queries

    def get_text(self) -> str:
        return self.engine.get_text()

    def visible_length(self) -> int:
        """Local visible length without materializing text (O(segments)
        and allocation-free on the native engine)."""
        return self.engine.visible_length(
            self.engine.current_seq, self.engine.local_client_id
        )

    @property
    def current_seq(self) -> int:
        return self.engine.current_seq
