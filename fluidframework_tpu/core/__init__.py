"""Scalar reference implementations (the differential-testing oracles).

`mergetree.py` is a straight, correct, pointer-free implementation of the
reference's merge-tree conflict-resolution semantics
(packages/dds/merge-tree/src/mergeTree.ts). Every TPU kernel in
`fluidframework_tpu.ops` is validated bit-identically against it on
seeded multi-client farms (mirroring the role of the reference's
mergeTreeOperationRunner.ts harness).
"""

from .mergetree import (
    Segment,
    MergeTreeEngine,
    CollabClient,
    VisCategory,
)

__all__ = ["Segment", "MergeTreeEngine", "CollabClient", "VisCategory"]
