"""Overlay replay engine: the O(collab window) TPU fast path.

`OverlayDeviceReplica` plays the same role as
`core.columnar_replay.ColumnarReplica` (consume a pre-decoded columnar
op stream, converge on the final document state) but drives the
overlay pallas kernel (`ops.overlay_pallas`): the device table holds
only UNSETTLED rows — per-op kernel work scales with the collaboration
window (a few thousand rows) instead of the table capacity (131k),
which is the reference's O(log n) B-tree + partial-lengths bound
(mergeTree.ts:1397, partialLengths.ts:256) re-expressed for the VPU.

Settled content never occupies device memory as rows: each per-chunk
fold appends its settled/dropped rows to a preallocated HBM record log
(one `dynamic_update_slice`, donated/in-place), and the host
reconstructs the settled text+props once, AFTER the timed region, by
replaying the log epoch-by-epoch (`reconstruct_settled`) — the
snapshot role, off the hot path, like the reference's snapshot write
(snapshotV1.ts:30). This also removes the round-2 VMEM scale cliff:
document length is unbounded by the window table; only the collab
window itself must fit (ERR_CAPACITY flags if it doesn't).

The steady-state loop performs ZERO host<->device transfers and no
blocking syncs: the (NOOP-padded) stream uploads once, each chunk is
one `replay_chunk_step` dispatch, and errors ride the table scalar,
checked at the end.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.mergetree_kernel import (
    NO_CLIENT,
    NO_KEY,
    NOT_REMOVED,
    OP_NOOP,
    PROP_ABSENT,
    PROP_DELETE,
    OpBatch,
    raise_kernel_errors,
)
from ..ops.overlay_pallas import (
    REC_DROP_SPAN,
    REC_NONE,
    REC_SETTLE_SPAN,
    REC_SETTLE_TEXT,
    OverlayTable,
    make_overlay_table,
    replay_chunk_step,
    replay_fused,
)
from ..ops.overlay_ref import (
    SETTLED_BASE,
    OverlayDoc,
    OverlayReplica,
    merge_span_props,
)
from ..testing.synthetic import ColumnarStream


def reconstruct_settled(
    initial_text: np.ndarray,
    stream_text: np.ndarray,
    log: np.ndarray,
    counts: List[int],
    n_prop_keys: int,
    initial_props: Optional[np.ndarray] = None,
    initial_attr: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Replay the fold log into the final settled (text, props, attr).

    Each epoch's records are in storage (== coordinate) order with
    anchors in that epoch's settled space — exactly the walk
    `overlay_ref.OverlayDoc.fold` performs in-place; here it runs once
    per epoch over the logged rows instead (same codes, same
    PROP_DELETE tombstone semantics; `attr` carries each settled
    position's insert-attribution key, record column 4).

    `initial_props`/`initial_attr` seed the settled props/attr arrays
    (defaults: all-absent / zero) — the INCREMENTAL form
    `core.overlay_fold.OverlayFoldReplica` applies per emission round,
    where the initial settled state carries real props from earlier
    rounds instead of a fresh load."""
    KK = n_prop_keys
    settled_t = np.asarray(initial_text, np.int32)
    settled_p = (
        np.asarray(initial_props, np.int32).copy()
        if initial_props is not None
        else np.full((len(settled_t), KK), PROP_ABSENT, np.int32)
    )
    settled_a = (
        np.asarray(initial_attr, np.int32).copy()
        if initial_attr is not None
        else np.zeros(len(settled_t), np.int32)
    )
    off = 0
    for cnt in counts:
        recs = log[off: off + cnt]
        off += cnt
        if cnt == 0:
            continue
        pieces_t: List[np.ndarray] = []
        pieces_p: List[np.ndarray] = []
        pieces_a: List[np.ndarray] = []
        cursor = 0
        for r in recs:
            a = int(r[0])
            code = int(r[1])
            b = int(r[2])
            ln = int(r[3])
            iseq = int(r[4])
            props = r[5:]
            pieces_t.append(settled_t[cursor:a])
            pieces_p.append(settled_p[cursor:a])
            pieces_a.append(settled_a[cursor:a])
            cursor = a
            if code == REC_SETTLE_TEXT:
                pieces_t.append(stream_text[b: b + ln])
                row = props.copy()
                row[row == PROP_DELETE] = PROP_ABSENT
                pieces_p.append(np.broadcast_to(row, (ln, KK)).copy())
                pieces_a.append(np.full(ln, iseq, np.int32))
            elif code == REC_DROP_SPAN:
                cursor = a + ln
            elif code == REC_SETTLE_SPAN:
                pieces_t.append(settled_t[a: a + ln])
                pieces_p.append(
                    merge_span_props(settled_p[a: a + ln], props)
                )
                pieces_a.append(settled_a[a: a + ln])
                cursor = a + ln
            elif code == REC_NONE:
                pass  # dropped text row: reconstructs to nothing
            else:
                raise ValueError(f"bad fold-log code {code}")
        pieces_t.append(settled_t[cursor:])
        pieces_p.append(settled_p[cursor:])
        pieces_a.append(settled_a[cursor:])
        settled_t = np.concatenate(pieces_t) if pieces_t else (
            np.zeros(0, np.int32)
        )
        settled_p = (
            np.concatenate(pieces_p)
            if pieces_p else np.zeros((0, KK), np.int32)
        )
        settled_a = (
            np.concatenate(pieces_a)
            if pieces_a else np.zeros(0, np.int32)
        )
    return settled_t, settled_p, settled_a


@functools.lru_cache(maxsize=None)
def _stream_step_fn(B: int, interpret: bool, n_ops_seg: int,
                    n_chunks_seg: int, shapes: tuple):
    """ONE cached jitted executable per segment shape: unpack the
    packed host->device transfer + the whole fused replay — one
    dispatch per segment rides the wire, and the big carries (table,
    log, counts) are donated so XLA updates them in place. Cached at
    module level so fresh replicas (bench repeats) reuse the compiled
    executable."""

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(table, log, counts, dev, epoch0):
        offs = [0]
        for k in shapes:
            offs.append(offs[-1] + n_ops_seg * max(k, 1))
        fields = []
        for k, o0, o1 in zip(shapes, offs, offs[1:]):
            f = dev[o0:o1]
            if k:
                f = f.reshape(n_ops_seg, k)
            fields.append(f)
        batch = OpBatch(*fields)
        msns = dev[offs[-1]: offs[-1] + n_chunks_seg]
        return replay_fused(
            table, batch, log, counts, msns, B, interpret,
            epoch0=epoch0,
        )

    return step


class OverlayDeviceReplica:
    """Device-resident overlay replica driven by columnar op arrays.

    Same output surface as `ColumnarReplica` / `OverlayReplica`
    (get_text / annotated_spans / check_errors) so the digest gates
    compare all engines directly. `interpret=True` runs the pallas
    kernel through the interpreter so CPU tests gate it bit-for-bit.
    """

    def __init__(
        self,
        stream: ColumnarStream,
        initial_len: int = 0,
        chunk_size: int = 2048,
        window: int = 8192,
        n_removers: int = 4,
        n_prop_keys: int = 8,
        interpret: bool = False,
        log_cap: Optional[int] = None,
    ):
        self.stream = stream
        self.chunk_size = chunk_size
        self.window = window
        self.n_removers = n_removers
        self.n_prop_keys = n_prop_keys
        self.interpret = interpret
        self.initial_len = initial_len

        n = len(stream)
        self.n_chunks = -(-n // chunk_size) if n else 0
        # Every row ever created folds (or survives) exactly once; ~3
        # rows/op (insert + split tails / gap spans) bounds the log.
        self.log_cap = log_cap or (3 * n + 4 * window)
        self.table = make_overlay_table(
            window, n_removers, n_prop_keys, settled_len=initial_len
        )
        self.log = jnp.zeros((self.log_cap, 5 + n_prop_keys), jnp.int32)
        self.counts = jnp.zeros(max(self.n_chunks, 1), jnp.int32)
        self.cursor = jnp.int32(0)
        self.chunks_done = 0
        self._doc: Optional[OverlayDoc] = None
        self._dev: Optional[OpBatch] = None

    # -------------------------------------------------------------- replay

    def prepare(self) -> None:
        """Upload the (NOOP-padded) op stream and per-chunk MSN
        schedule to the device — the load phase, outside the timed
        replay region (the reference replay tool likewise pre-parses
        recorded op files before its timed loop,
        packages/tools/replay-tool/src/replayMessages.ts)."""
        if getattr(self, "_dev", None) is not None:
            return
        self.prepare_host()
        self._dev = OpBatch(*(jnp.asarray(a) for a in self._host))
        self._msn_by_chunk = jnp.asarray(self._host_msn)

    def prepare_host(self) -> None:
        """Decode the stream into padded HOST arrays only (the
        streaming-ingress load phase: nothing touches the device; the
        replay itself feeds segments in)."""
        if getattr(self, "_host", None) is not None:
            return
        s = self.stream
        n = len(s)
        B = self.chunk_size
        pad = self.n_chunks * B

        def up(a: np.ndarray, fill: int = 0) -> np.ndarray:
            out = np.full(pad, fill, np.int32)
            out[:n] = a
            return out

        self._host = OpBatch(
            op_type=up(s.op_type, OP_NOOP),
            pos1=up(s.pos1), pos2=up(s.pos2),
            seq=up(s.seq), ref_seq=up(s.ref_seq),
            client=up(s.client, NO_CLIENT),
            buf_start=up(s.buf_start), ins_len=up(s.ins_len),
            prop_keys=up(s.prop_key, NO_KEY)[:, None],
            prop_vals=up(s.prop_val, PROP_ABSENT)[:, None],
        )
        # Applied MSN at each chunk's end (the fold perspective).
        ends = np.minimum(np.arange(1, self.n_chunks + 1) * B, n) - 1
        self._host_msn = s.min_seq[ends].astype(np.int32)

    def replay_streaming(self, n_segments: int = 8) -> None:
        """Replay with INGEST IN THE LOOP: the op stream lives on the
        host and feeds the device segment by segment, each segment's
        transfer (async `jax.device_put`) overlapping the previous
        segment's fused replay — the alfred→deli→merge pipeline
        running concurrently end-to-end (SURVEY §2.6 row 4;
        localOrderer.ts:245 pipelines per-doc over Kafka the same
        way) instead of the pre-staged load phase."""
        self.prepare_host()
        if not self.n_chunks:
            return
        n_segments = max(1, min(n_segments, self.n_chunks))
        seg_chunks = -(-self.n_chunks // n_segments)
        B = self.chunk_size

        def seg_slice(si: int):
            lo_c = si * seg_chunks
            hi_c = min(lo_c + seg_chunks, self.n_chunks)
            lo, hi = lo_c * B, hi_c * B
            # ONE packed transfer per segment (a tunneled backend pays
            # per-transfer latency; 10 small puts would serialize).
            packed = np.concatenate(
                [np.ascontiguousarray(a[lo:hi]).reshape(-1)
                 for a in self._host]
                + [self._host_msn[lo_c:hi_c]]
            ).astype(np.int32)
            return lo_c, hi - lo, hi_c - lo_c, jax.device_put(packed)

        shapes = tuple(
            (a.shape[1] if a.ndim > 1 else 0) for a in self._host
        )

        n_live = -(-self.n_chunks // seg_chunks)
        nxt = seg_slice(0)
        for si in range(n_live):
            lo_c, n_ops_seg, n_chunks_seg, dev = nxt
            if si + 1 < n_live:
                nxt = seg_slice(si + 1)  # async: overlaps the replay
            step = _stream_step_fn(
                B, self.interpret, n_ops_seg, n_chunks_seg, shapes
            )
            self.table, self.log, self.counts, self.cursor = step(
                self.table, self.log, self.counts, dev,
                jnp.int32(lo_c),
            )
        self.chunks_done = self.n_chunks
        self._doc = None

    def replay(self, limit_chunks: Optional[int] = None) -> None:
        """Replay the stream. Full replays run as ONE fused device
        dispatch (`replay_fused`); `limit_chunks` runs the incremental
        per-chunk form instead (compile warm-up with identical shapes
        — share the same stream)."""
        self.prepare()
        if limit_chunks is None and self.n_chunks:
            self.table, self.log, self.counts, self.cursor = replay_fused(
                self.table, self._dev, self.log, self.counts,
                self._msn_by_chunk, self.chunk_size, self.interpret,
            )
            self.chunks_done = self.n_chunks
            self._doc = None
            return
        for ci in range(self.n_chunks):
            if limit_chunks is not None and ci >= limit_chunks:
                break
            self.table, self.log, self.counts, self.cursor = (
                replay_chunk_step(
                    self.table, self._dev, jnp.int32(ci * self.chunk_size),
                    self.chunk_size, self._msn_by_chunk[ci], self.log,
                    self.counts, self.cursor, jnp.int32(ci),
                    self.interpret,
                )
            )
            self.chunks_done = ci + 1
        self._doc = None

    # ------------------------------------------------------------- output

    def check_errors(self) -> None:
        raise_kernel_errors(int(self.table.error))

    def _materialize(self) -> OverlayDoc:
        """Pull the table + fold log once and rebuild the final
        overlay document host-side (off the timed path)."""
        if self._doc is not None:
            return self._doc
        cursor = int(self.cursor)
        if cursor + self.window > self.log_cap:
            raise RuntimeError(
                f"fold log overflow ({cursor} + {self.window} rows > "
                f"cap {self.log_cap}); raise log_cap"
            )
        counts = np.asarray(self.counts)[: self.chunks_done].tolist()
        log = np.asarray(self.log[:cursor])
        settled_t, settled_p, settled_a = reconstruct_settled(
            self.stream.text[: self.initial_len], self.stream.text,
            log, counts, self.n_prop_keys,
        )
        doc = OverlayDoc(settled_t, self.n_removers, self.n_prop_keys)
        doc.settled_props = settled_p
        doc.settled_attr = settled_a
        t = self.table
        m = int(t.n_rows)
        doc.anchor = np.asarray(t.anchor[:m])
        doc.buf = np.asarray(t.buf_start[:m])
        doc.length = np.asarray(t.length[:m])
        doc.iseq = np.asarray(t.ins_seq[:m])
        doc.iclient = np.asarray(t.ins_client[:m])
        doc.rseq = np.asarray(t.rem_seq[:m])
        doc.rcl = np.asarray(t.rem_clients[:m])
        doc.props = np.asarray(t.props[:m])
        doc.error = int(t.error)
        stream_text = np.asarray(self.stream.text, np.int32)

        def row_text(i: int) -> np.ndarray:
            b = int(doc.buf[i])
            ln = int(doc.length[i])
            if b >= SETTLED_BASE:
                a = b - SETTLED_BASE
                return doc.settled_text[a: a + ln]
            return stream_text[b: b + ln]

        doc._row_text = row_text  # type: ignore[assignment]
        self._doc = doc
        return doc

    def _shim(self) -> OverlayReplica:
        shim = OverlayReplica.__new__(OverlayReplica)
        shim.doc = self._materialize()
        shim.stream = self.stream
        return shim

    def get_text(self) -> str:
        return OverlayReplica.get_text(self._shim())

    def annotated_spans(self):
        return OverlayReplica.annotated_spans(self._shim())

    def attribution_spans(self):
        """(run_length, insert-attribution key) runs over the visible
        document — settled keys ride the fold log's ins_seq column,
        unsettled rows derive theirs from the table's ins_seq."""
        return OverlayReplica.attribution_spans(self._shim())

    def verify_invariants(self) -> None:
        self._materialize().verify_invariants()


def stack_replicas(reps: List["OverlayDeviceReplica"]):
    """Stack prepared replicas into the leading-docs-axis input layout
    of `parallel.mesh.sharded_overlay_replay`:
    ``(tables, ops, logs, counts, msn_by_chunk)``."""
    stack = lambda *xs: jnp.stack(xs)
    return (
        jax.tree_util.tree_map(stack, *[r.table for r in reps]),
        jax.tree_util.tree_map(stack, *[r._dev for r in reps]),
        jnp.stack([r.log for r in reps]),
        jnp.stack([r.counts for r in reps]),
        jnp.stack([r._msn_by_chunk for r in reps]),
    )


def restore_shard(
    rep: "OverlayDeviceReplica", out_tables, out_logs, out_counts,
    cursors, d: int,
) -> "OverlayDeviceReplica":
    """Load document `d`'s sharded-replay outputs into `rep` so its
    host-side readout (get_text / annotated_spans / check_errors)
    reflects the mesh run."""
    rep.table = jax.tree_util.tree_map(lambda a: a[d], out_tables)
    rep.log = out_logs[d]
    rep.counts = out_counts[d]
    rep.cursor = cursors[d]
    rep.chunks_done = rep.n_chunks
    rep._doc = None
    return rep


class OverlayKernelMessageReplica:
    """SequencedMessage-driven overlay DEVICE replica: the pallas
    overlay kernel behind the same message surface as
    `overlay_ref.OverlayMessageReplica`, so the farm differential
    tests (real concurrency: lagging refSeqs, tie-breaks, overlapping
    removes, multi-pair annotations) gate the KERNEL bit-for-bit
    against the scalar oracle. Reuses `KernelReplica`'s op encoder
    (text arena + prop interner)."""

    def __init__(self, initial: str = "", chunk_size: int = 64,
                 window: int = 1024, n_removers: int = 4,
                 n_prop_keys: int = 8, max_prop_pairs: int = 4,
                 interpret: bool = True):
        from .kernel_replica import PropInterner, TextArena

        self.arena = TextArena("")
        self.props = PropInterner(n_prop_keys)
        self.chunk_size = chunk_size
        self.window = window
        self.n_removers = n_removers
        self.n_prop_keys = n_prop_keys
        self.max_prop_pairs = max_prop_pairs
        self.interpret = interpret
        self.initial = initial
        self._initial_np = np.asarray([ord(c) for c in initial], np.int32)
        self.table = make_overlay_table(
            window, n_removers, n_prop_keys, settled_len=len(initial)
        )
        self._rows: List[tuple] = []
        self._epochs: List[Tuple[np.ndarray, int]] = []
        self._doc: Optional[OverlayDoc] = None

    def apply_messages(self, msgs) -> None:
        from .kernel_replica import EncoderState, encode_op
        from ..protocol.messages import MessageType

        enc = EncoderState(self.arena, self.props, self.max_prop_pairs)
        msn = 0
        for msg in msgs:
            if msg.type == MessageType.OP and msg.contents is not None:
                encode_op(enc, msg.contents, msg)
                self._rows.extend(enc._encoded)
                if enc._encoded:
                    msn = enc._encoded[-1][10]
                enc._encoded = []
            else:
                msn = max(msn, msg.minimum_sequence_number)
            while len(self._rows) >= self.chunk_size:
                self._flush(self._rows[: self.chunk_size])
                self._rows = self._rows[self.chunk_size:]
        if self._rows:
            self._flush(self._rows)
            self._rows = []
        else:
            self._fold_only(msn)
        self._doc = None

    def _flush(self, rows: List[tuple]) -> None:
        from ..ops.overlay_pallas import fold_device, overlay_apply_chunk

        B = self.chunk_size
        PK = self.max_prop_pairs
        cols = {
            "op_type": (OP_NOOP, 0), "pos1": (0, 1), "pos2": (0, 2),
            "seq": (0, 3), "ref_seq": (0, 4), "client": (NO_CLIENT, 5),
            "buf_start": (0, 6), "ins_len": (0, 7),
        }
        arrs = {}
        for name, (fill, j) in cols.items():
            a = np.full(B, fill, np.int32)
            a[: len(rows)] = [r[j] for r in rows]
            arrs[name] = jnp.asarray(a)
        pk = np.full((B, PK), NO_KEY, np.int32)
        pv = np.full((B, PK), PROP_ABSENT, np.int32)
        for i, r in enumerate(rows):
            ks, vs = r[8], r[9]
            pk[i, : len(ks)] = ks
            pv[i, : len(vs)] = vs
        batch = OpBatch(
            prop_keys=jnp.asarray(pk), prop_vals=jnp.asarray(pv), **arrs
        )
        self.table = overlay_apply_chunk(
            self.table, batch, self.interpret
        )
        msn = rows[-1][10]
        self.table, records, n_rec = fold_device(
            self.table, jnp.int32(msn)
        )
        self._epochs.append((np.asarray(records), int(n_rec)))

    def _fold_only(self, msn: int) -> None:
        from ..ops.overlay_pallas import fold_device

        self.table, records, n_rec = fold_device(
            self.table, jnp.int32(msn)
        )
        self._epochs.append((np.asarray(records), int(n_rec)))

    # ------------------------------------------------------------- output

    def check_errors(self) -> None:
        raise_kernel_errors(int(self.table.error))

    def _materialize(self) -> OverlayDoc:
        if self._doc is not None:
            return self._doc
        arena_text = np.asarray(
            [ord(c) for c in self.arena.snapshot()], np.int32
        )
        counts = [n for _, n in self._epochs]
        log = (
            np.concatenate([r[:n] for r, n in self._epochs])
            if self._epochs else np.zeros((0, 5 + self.n_prop_keys),
                                          np.int32)
        )
        settled_t, settled_p, settled_a = reconstruct_settled(
            self._initial_np, arena_text, log, counts, self.n_prop_keys
        )
        doc = OverlayDoc(settled_t, self.n_removers, self.n_prop_keys)
        doc.settled_props = settled_p
        doc.settled_attr = settled_a
        t = self.table
        m = int(t.n_rows)
        doc.anchor = np.asarray(t.anchor[:m])
        doc.buf = np.asarray(t.buf_start[:m])
        doc.length = np.asarray(t.length[:m])
        doc.iseq = np.asarray(t.ins_seq[:m])
        doc.iclient = np.asarray(t.ins_client[:m])
        doc.rseq = np.asarray(t.rem_seq[:m])
        doc.rcl = np.asarray(t.rem_clients[:m])
        doc.props = np.asarray(t.props[:m])
        doc.error = int(t.error)

        def row_text(i: int) -> np.ndarray:
            b = int(doc.buf[i])
            ln = int(doc.length[i])
            if b >= SETTLED_BASE:
                a = b - SETTLED_BASE
                return doc.settled_text[a: a + ln]
            return arena_text[b: b + ln]

        doc._row_text = row_text  # type: ignore[assignment]
        self._doc = doc
        return doc

    def verify_invariants(self) -> None:
        self._materialize().verify_invariants()

    def _doc_order(self):
        shim = OverlayReplica.__new__(OverlayReplica)
        shim.doc = self._materialize()
        return OverlayReplica._doc_order(shim)

    def get_text(self) -> str:
        return "".join(
            "".join(map(chr, t)) for t, _ in self._doc_order()
        )

    def annotated_spans(self):
        spans: List[Tuple[str, Optional[dict]]] = []
        for text, props in self._doc_order():
            for j in range(len(text)):
                row = np.asarray(props[j])
                p = self.props.decode_row(
                    np.where(row == PROP_DELETE, PROP_ABSENT, row)
                )
                spans.append((chr(int(text[j])), p))
        return spans
