"""ctypes adapter for the native host merge engine (hostmerge.cpp).

`NativeMergeEngine` exposes the surface of `core.mergetree
.MergeTreeEngine` that interactive clients use — insert/remove/
annotate (local pending or sequenced remote), ack, MSN window +
zamboni, perspective queries, reconnect regeneration — backed by the
C++ segment list. Semantics are a faithful port of the oracle
(differentially farm-tested, tests/test_native_engine.py); the win is
the ~100x constant factor on the per-op document walks that dominate
the interactive path (BENCH_DETAIL configs 1/3).

Property keys/values are interned to int32 on this side (`None`
encodes as the PROP_DELETE sentinel, matching the reference's
null-deletes convention); content items are int32 (codepoints for
text, handles for permutation vectors).

`make_merge_engine()` picks native when the compiler/library is
available and falls back to the Python oracle engine otherwise, the
same convention as the content store (server/castore.py).
"""

from __future__ import annotations

import ctypes
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..native import load_hostmerge
from ..protocol.constants import NON_COLLAB_CLIENT, UNASSIGNED_SEQ
from ..protocol.mergetree_ops import (
    AnnotateOp,
    GroupOp,
    InsertOp,
    MergeTreeDeltaType,
    MergeTreeOp,
    RemoveOp,
)

PROP_DELETE = -2  # interned encoding of None (must match hostmerge.cpp)

_I32 = ctypes.c_int32


def _arr(values) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(values, np.int32))


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(_I32))


class _PropCoder:
    """Bidirectional key/value <-> int32 interning (unbounded; the
    kernel-side PropInterner is capacity-bound by KK, this one serves
    the host engine)."""

    def __init__(self):
        self._key2id: Dict[str, int] = {}
        self._keys: List[str] = []
        self._val2id: Dict[Any, int] = {}
        self._vals: List[Any] = []

    def key_id(self, key: str) -> int:
        kid = self._key2id.get(key)
        if kid is None:
            kid = len(self._keys)
            self._key2id[key] = kid
            self._keys.append(key)
        return kid

    def val_id(self, value: Any) -> int:
        if value is None:
            return PROP_DELETE
        vid = self._val2id.get(value)
        if vid is None:
            vid = len(self._vals)
            self._val2id[value] = vid
            self._vals.append(value)
        return vid

    def encode(self, props: Optional[dict]) -> Tuple[np.ndarray, np.ndarray]:
        if not props:
            return _arr([]), _arr([])
        keys = [self.key_id(k) for k in props]
        vals = [self.val_id(v) for v in props.values()]
        return _arr(keys), _arr(vals)

    def decode(self, pairs) -> Optional[dict]:
        out = {}
        for k, v in pairs:
            out[self._keys[k]] = self._vals[v]
        return out or None


class _PendingView:
    """Read-only view of the C++ pending FIFO exposing the bits
    callers use (`pending[-1]` as op metadata, truthiness, length)."""

    def __init__(self, eng: "NativeMergeEngine"):
        self._eng = eng

    def __len__(self) -> int:
        return int(self._eng._lib.hm_pending_count(self._eng._ptr))

    def __bool__(self) -> bool:
        return len(self) > 0

    def __getitem__(self, idx: int) -> int:
        if idx != -1:
            raise IndexError("pending view exposes [-1] only")
        gid = int(self._eng._lib.hm_pending_last_id(self._eng._ptr))
        if gid < 0:
            raise IndexError("no pending ops")
        return gid


class NativeMergeEngine:
    """C++-backed merge engine with the MergeTreeEngine surface used
    by CollabClient and PermutationVector."""

    # Staging buffers shrink per-op ctypes marshalling: content/prop
    # arrays are copied into preallocated numpy buffers whose pointers
    # are cached once (numpy's .ctypes.data_as costs ~10us per call).
    _STAGE = 1 << 16

    def __init__(self, local_client_id: int = NON_COLLAB_CLIENT,
                 lib: Optional[ctypes.CDLL] = None):
        self._lib = lib or load_hostmerge()
        if self._lib is None:
            raise RuntimeError("hostmerge library unavailable")
        self._ptr = ctypes.c_void_p(self._lib.hm_new(local_client_id))
        self._props = _PropCoder()
        self._is_text = True
        self._content_buf = np.empty(self._STAGE, np.int32)
        self._content_ptr = _ptr(self._content_buf)
        self._pk_buf = np.empty(64, np.int32)
        self._pk_ptr = _ptr(self._pk_buf)
        self._pv_buf = np.empty(64, np.int32)
        self._pv_ptr = _ptr(self._pv_buf)

    def __del__(self):
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr and getattr(self, "_lib", None) is not None:
            self._lib.hm_free(ptr)

    # ------------------------------------------------------ attributes

    @property
    def local_client_id(self) -> int:
        return int(self._lib.hm_local_client(self._ptr))

    @local_client_id.setter
    def local_client_id(self, cid: int) -> None:
        self._lib.hm_set_identity(
            self._ptr, cid, int(self._lib.hm_collaborating(self._ptr))
        )

    @property
    def collaborating(self) -> bool:
        return bool(self._lib.hm_collaborating(self._ptr))

    @collaborating.setter
    def collaborating(self, v: bool) -> None:
        self._lib.hm_set_identity(
            self._ptr, self.local_client_id, int(bool(v))
        )

    @property
    def current_seq(self) -> int:
        return int(self._lib.hm_current_seq(self._ptr))

    @current_seq.setter
    def current_seq(self, v: int) -> None:
        self._lib.hm_set_current_seq(self._ptr, v)

    @property
    def min_seq(self) -> int:
        return int(self._lib.hm_min_seq(self._ptr))

    @min_seq.setter
    def min_seq(self, v: int) -> None:
        self._lib.hm_set_min_seq(self._ptr, v)

    @property
    def pending(self) -> _PendingView:
        return _PendingView(self)

    # ------------------------------------------------------ mutations

    def _stage_content(self, content: Any) -> int:
        """Copy content items into the staging buffer; returns count."""
        n = len(content)
        if n > len(self._content_buf):
            self._content_buf = np.empty(
                max(n, 2 * len(self._content_buf)), np.int32
            )
            self._content_ptr = _ptr(self._content_buf)
        if isinstance(content, str):
            self._is_text = True
            if n:
                self._content_buf[:n] = np.frombuffer(
                    content.encode("utf-32-le"), np.int32
                )
            return n
        self._is_text = False
        self._content_buf[:n] = content
        return n

    def _stage_props(self, props: Optional[dict]) -> int:
        if not props:
            return 0
        coder = self._props
        for i, (k, v) in enumerate(props.items()):
            self._pk_buf[i] = coder.key_id(k)
            self._pv_buf[i] = coder.val_id(v)
        return len(props)

    def load(self, content: Any, props: Optional[dict] = None) -> None:
        n = self._stage_content(content)
        if props:
            raise NotImplementedError("native load with props")
        self._lib.hm_load(self._ptr, self._content_ptr, n)

    def insert(self, pos: int, content: Any, ref_seq: int, client_id: int,
               seq: int, props: Optional[dict] = None) -> None:
        n = self._stage_content(content)
        clean = (
            {k: v for k, v in props.items() if v is not None}
            if props else None
        )
        nk = self._stage_props(clean)
        rc = self._lib.hm_insert(
            self._ptr, pos, self._content_ptr, n, ref_seq, client_id,
            seq, self._pk_ptr, self._pv_ptr, nk,
        )
        if rc != 0:
            raise ValueError(
                f"insert pos {pos} beyond visible length at perspective "
                f"({ref_seq},{client_id})"
            )

    def remove_range(self, start: int, end: int, ref_seq: int,
                     client_id: int, seq: int) -> None:
        rc = self._lib.hm_remove(
            self._ptr, start, end, ref_seq, client_id, seq
        )
        if rc != 0:
            raise AssertionError(f"bad remove range [{start},{end})")

    def annotate_range(self, start: int, end: int, props: Dict[str, Any],
                       ref_seq: int, client_id: int, seq: int) -> None:
        nk = self._stage_props(props)
        rc = self._lib.hm_annotate(
            self._ptr, start, end, self._pk_ptr, self._pv_ptr, nk,
            ref_seq, client_id, seq,
        )
        if rc != 0:
            raise AssertionError(f"bad annotate range [{start},{end})")

    def ack(self, seq: int) -> None:
        if self._lib.hm_ack(self._ptr, seq) != 0:
            raise IndexError("ack with empty pending FIFO")

    def update_min_seq(self, min_seq: int) -> None:
        # Monotone by construction on every call path (callers pass
        # max(min_seq, msn)); the C++ zamboni is idempotent regardless.
        self._lib.hm_update_min_seq(self._ptr, min_seq)

    def apply_sequenced(self, msg) -> None:
        """Apply one remote `SequencedMessage` (passive-replica path:
        route by op type, advance current_seq and the MSN window —
        the replay_passive loop's per-message body)."""
        op = msg.contents
        if isinstance(op, InsertOp):
            self.insert(op.pos, op.text, msg.ref_seq, msg.client_id,
                        msg.sequence_number)
        elif isinstance(op, RemoveOp):
            self.remove_range(op.start, op.end, msg.ref_seq,
                              msg.client_id, msg.sequence_number)
        elif isinstance(op, AnnotateOp):
            self.annotate_range(op.start, op.end, op.props, msg.ref_seq,
                                msg.client_id, msg.sequence_number)
        else:
            raise TypeError(f"unsupported sequenced op {type(op)!r}")
        self.current_seq = msg.sequence_number
        self.update_min_seq(
            max(self.min_seq, msg.minimum_sequence_number)
        )

    def apply_sequenced_batch(self, msgs) -> None:
        """Apply a run of `SequencedMessage`s in ONE native call
        (hm_apply_batch — the client.ts:858 applyMsg loop with the
        Python/ctypes frame cost paid per BATCH, not per message).
        Own-client messages ack the pending FIFO, remote ops apply at
        their perspectives; the MSN advances once at batch end, which
        is semantics-preserving (zamboni timing never changes visible
        state; min_seq only enters visibility on the local-perspective
        read path, which no remote apply or ack touches)."""
        from ..protocol.messages import MessageType

        kind: List[int] = []
        pos1: List[int] = []
        pos2: List[int] = []
        ref: List[int] = []
        cli: List[int] = []
        seq: List[int] = []
        aoff: List[int] = []
        alen: List[int] = []
        chunks: List[str] = []
        items_mode = False
        item_chunks: List[List[int]] = []
        pk: List[int] = []
        pv: List[int] = []
        poff: List[int] = [0]
        coder = self._props
        local = self.local_client_id
        final_msn = self.min_seq
        cursor = 0

        def row(k, p1=0, p2=0, r=0, c=0, s=0, ao=0, al=0):
            kind.append(k)
            pos1.append(p1)
            pos2.append(p2)
            ref.append(r)
            cli.append(c)
            seq.append(s)
            aoff.append(ao)
            alen.append(al)
            poff.append(len(pk))

        for msg in msgs:
            if msg.minimum_sequence_number > final_msn:
                final_msn = msg.minimum_sequence_number
            sq = msg.sequence_number
            if msg.type != MessageType.OP or msg.contents is None:
                row(4, s=sq)
                continue
            ops = (
                msg.contents.ops
                if isinstance(msg.contents, GroupOp)
                else (msg.contents,)
            )
            for op in ops:
                if msg.client_id == local:
                    row(3, s=sq)
                elif isinstance(op, InsertOp):
                    if op.text is not None:
                        content_len = len(op.text)
                        chunks.append(op.text)
                    else:
                        content_len = len(op.seg)
                        items_mode = True
                        item_chunks.append(list(op.seg))
                    if op.props:
                        for k, v in op.props.items():
                            if v is None:
                                continue
                            pk.append(coder.key_id(k))
                            pv.append(coder.val_id(v))
                    row(0, p1=op.pos, r=msg.ref_seq, c=msg.client_id,
                        s=sq, ao=cursor, al=content_len)
                    cursor += content_len
                elif isinstance(op, RemoveOp):
                    row(1, p1=op.start, p2=op.end, r=msg.ref_seq,
                        c=msg.client_id, s=sq)
                elif isinstance(op, AnnotateOp):
                    for k, v in op.props.items():
                        pk.append(coder.key_id(k))
                        pv.append(coder.val_id(v))
                    row(2, p1=op.start, p2=op.end, r=msg.ref_seq,
                        c=msg.client_id, s=sq)
                else:
                    raise TypeError(f"unsupported op {type(op)!r}")

        if chunks:
            self._is_text = True
        if items_mode:
            if chunks:
                raise TypeError("mixed str/item inserts in one batch")
            arena = _arr([x for ch in item_chunks for x in ch])
            self._is_text = False
        else:
            joined = "".join(chunks)
            arena = (
                np.frombuffer(joined.encode("utf-32-le"), np.int32)
                if joined else _arr([])
            )
        rc = self._lib.hm_apply_batch(
            self._ptr, len(kind), _ptr(_arr(kind)), _ptr(_arr(pos1)),
            _ptr(_arr(pos2)), _ptr(_arr(ref)), _ptr(_arr(cli)),
            _ptr(_arr(seq)), _ptr(np.ascontiguousarray(arena)),
            _ptr(_arr(aoff)), _ptr(_arr(alen)), _ptr(_arr(pk)),
            _ptr(_arr(pv)), _ptr(_arr(poff)), final_msn,
        )
        if rc != 0:
            raise ValueError(
                f"apply_sequenced_batch failed at row {-rc - 1} "
                f"(kind {kind[-rc - 1]}, seq {seq[-rc - 1]})"
            )

    def pack_settled(self) -> None:
        """Merge adjacent fully-settled same-props segments (the
        zamboni.ts:19 packParent role; run length capped in C++).
        PASSIVE replicas only: pending local groups may hold pointers
        into merged-away tails."""
        if len(self.pending):
            raise RuntimeError(
                "pack_settled on an engine with pending local ops"
            )
        self._lib.hm_pack_settled(self._ptr)

    def verify_invariants(self) -> None:
        """Exhaustive structural verification in the C++ engine (the
        MergeTreeEngine.verify_invariants role; violation codes are
        documented at hostmerge.cpp hm_verify)."""
        code = int(self._lib.hm_verify(self._ptr))
        assert code == 0, f"native engine invariant violation #{code}"

    # -------------------------------------------------------- queries

    def visible_length(self, ref_seq: int, client_id: int) -> int:
        return int(
            self._lib.hm_visible_length(self._ptr, ref_seq, client_id)
        )

    def _items(self) -> np.ndarray:
        n = int(self._lib.hm_get_items(self._ptr, None, 0))
        out = np.empty(max(n, 1), np.int32)
        self._lib.hm_get_items(self._ptr, _ptr(out), n)
        return out[:n]

    def get_text(self) -> str:
        if not self._is_text:
            raise TypeError("non-text engine: use get_items()")
        return "".join(map(chr, self._items()))

    def get_items(self) -> List[int]:
        return self._items().tolist()

    def item_at(self, pos: int, ref_seq: int, client_id: int) -> int:
        v = int(self._lib.hm_item_at(self._ptr, pos, ref_seq, client_id))
        if v < 0:
            raise IndexError(f"position {pos} beyond visible length")
        return v

    def position_of_item(self, item: int, ref_seq: int,
                         client_id: int) -> Optional[int]:
        v = int(self._lib.hm_position_of_item(
            self._ptr, item, ref_seq, client_id
        ))
        return None if v < 0 else v

    def enable_attribution(self) -> None:
        """Track per-position insert attribution (attribution key =
        insert seq; the attributionCollection.ts/attributionPolicy.ts
        role). Existing content backfills: loaded text to key 0,
        sequenced segments to their seq, pending locals assigned on
        ack. Runs survive splits, zamboni and settled-run packing."""
        self._lib.hm_enable_attr(self._ptr)

    def attribution_spans(self) -> List[Tuple[int, int]]:
        """(run_length, attribution key) runs over the visible
        document, adjacent equal keys merged."""
        n = int(self._lib.hm_attr_spans(self._ptr, None, 0))
        buf = np.empty(max(n, 1), np.int32)
        self._lib.hm_attr_spans(self._ptr, _ptr(buf), n)
        out: List[Tuple[int, int]] = []
        for i in range(0, n, 2):
            ln, key = int(buf[i]), int(buf[i + 1])
            if out and out[-1][1] == key:
                out[-1] = (out[-1][0] + ln, key)
            else:
                out.append((ln, key))
        return out

    def annotated_spans(self) -> List[Tuple[Any, Optional[dict]]]:
        n = int(self._lib.hm_spans(self._ptr, None, 0))
        buf = np.empty(max(n, 1), np.int32)
        self._lib.hm_spans(self._ptr, _ptr(buf), n)
        out: List[Tuple[Any, Optional[dict]]] = []
        i = 0
        while i < n:
            ln = int(buf[i]); i += 1
            items = buf[i: i + ln]; i += ln
            np_ = int(buf[i]); i += 1
            pairs = [
                (int(buf[i + 2 * j]), int(buf[i + 2 * j + 1]))
                for j in range(np_)
            ]
            i += 2 * np_
            content: Any = (
                "".join(map(chr, items)) if self._is_text else items.tolist()
            )
            out.append((content, self._props.decode(pairs)))
        return out

    # ---------------------------------------------- reconnect / rebase

    def regenerate_pending(
        self, grps: List[int], original: MergeTreeOp
    ) -> Tuple[Optional[MergeTreeOp], List[int]]:
        """Rebase pending local ops for resubmission after reconnect
        (contract of MergeTreeEngine.regenerate_pending; `grps` are
        native group ids)."""
        gids = _arr(grps)
        # Regeneration MUTATES the pending FIFO (group splitting), so
        # the buffer is sized up front: each sub-op costs 5 header
        # ints, sub-op count is bounded by the segment count, and
        # insert payloads by the total content.
        cap = (
            5 * (int(self._lib.hm_segment_count(self._ptr)) + len(gids) + 1)
            + int(self._lib.hm_content_total(self._ptr))
        )
        buf = np.empty(cap, np.int32)
        n = int(self._lib.hm_regenerate(self._ptr, _ptr(gids), len(gids),
                                        _ptr(buf), cap))
        if n < 0:
            raise KeyError(f"unknown pending group in {grps}")
        assert n <= cap
        ops: List[MergeTreeOp] = []
        out_groups: List[int] = []
        i = 0
        ins_props = original.props if isinstance(original, InsertOp) else None
        while i < n:
            kind, gid, a, b = (int(buf[i]), int(buf[i + 1]), int(buf[i + 2]),
                               int(buf[i + 3]))
            ni = int(buf[i + 4])
            items = buf[i + 5: i + 5 + ni]
            i += 5 + ni
            out_groups.append(gid)
            if kind == MergeTreeDeltaType.INSERT:
                if self._is_text:
                    ops.append(InsertOp(
                        pos=a, text="".join(map(chr, items)),
                        props=ins_props,
                    ))
                else:
                    ops.append(InsertOp(
                        pos=a, seg=items.tolist(), props=ins_props
                    ))
            elif kind == MergeTreeDeltaType.REMOVE:
                ops.append(RemoveOp(start=a, end=b))
            else:
                pn = int(self._lib.hm_group_props(self._ptr, gid, None, 0))
                pbuf = np.empty(max(pn, 1), np.int32)
                self._lib.hm_group_props(self._ptr, gid, _ptr(pbuf), pn)
                pairs = [
                    (int(pbuf[2 * j]), int(pbuf[2 * j + 1]))
                    for j in range(pn // 2)
                ]
                ops.append(AnnotateOp(
                    start=a, end=b, props=self._props.decode(pairs) or {}
                ))
        if not ops:
            return None, []
        if len(ops) == 1:
            return ops[0], out_groups
        return GroupOp(ops=ops), out_groups


def native_available() -> bool:
    return load_hostmerge() is not None


def make_merge_engine(local_client_id: int = NON_COLLAB_CLIENT,
                      prefer_native: bool = True):
    """Native engine when available, Python oracle engine otherwise."""
    if prefer_native and native_available():
        return NativeMergeEngine(local_client_id)
    from .mergetree import MergeTreeEngine

    return MergeTreeEngine(local_client_id=local_client_id)
