"""Overlay merge-tree: numpy reference semantics for the O(window) engine.

The round-2 row-model kernels (ops/mergetree_kernel.py scan form,
ops/mergetree_pallas.py chunk form) pay O(capacity) vector work per op
because EVERY segment row — settled or not — lives in the kernel
table. But settled rows (insert seq <= MSN, not removed, or removed
<= MSN) are indistinguishable to every future perspective: any op's
refSeq >= MSN (deli nacks stale refSeqs), so settled-visible text is
visible to all of them and settled-removed text to none. The overlay
model exploits this the way the reference's B-tree + partial-lengths
cache bounds per-op work to O(log n) (mergeTree.ts:1397 insertSegments,
partialLengths.ts:256): per-op work scales with the COLLAB WINDOW, not
the document.

Representation
--------------
- Settled content is a virtual coordinate space ``[0, S)`` — NO rows.
  Its text/props live off-kernel (host arrays here; an append-only
  fold log on device). Un-materialized settled text is visible to
  every perspective by construction.
- The overlay holds rows only for state the window still needs:
    * TEXT rows — unsettled inserts. ``anchor`` = the settled
      coordinate the row sits before (a point; consumes no settled
      space). ``buf`` addresses an insert arena.
    * SPAN rows — unsettled removes/annotates COVERING settled text.
      ``anchor`` = first covered coordinate; the row consumes settled
      space ``[anchor, anchor+len)``. ``buf = SETTLED_BASE + anchor``
      (kept in sync through splits/folds). Created lazily ("gap
      materialization") when a range op covers settled coordinates.
- Storage order == document order. Invariants: anchors are
  non-decreasing; span rows are disjoint in coordinates; no row is
  anchored strictly inside a span row's range (splits enforce this).

Position resolution
-------------------
``delta_j = vis_len_j - consume_j`` (consume = len for span rows else
0). Visible prefix before row j at a perspective:
``pre(j) = anchor_j + cumsum_excl(delta)(j)`` and total visible length
``= S + sum(delta)`` — the partial-lengths role as one prefix sum over
the window.

Fold (settle-merge; the zamboni role, zamboni.ts:19)
----------------------------------------------------
At a sync point with applied MSN m:
- rows removed at/below m DROP; span rows among them excise their
  coordinates from settled space;
- live text rows with ins_seq <= m become settled text at their
  anchor;
- live span rows fold unconditionally (annotations are write-only:
  no visibility predicate ever reads props), merging their props into
  settled props per key (PROP_DELETE cells clear);
- surviving rows re-anchor by the prefix sums of excised/inserted
  lengths (storage order == coordinate order makes both plain
  cumsums).

Property cells in SPAN rows use PROP_DELETE as an explicit tombstone
(a delete of a settled prop must fold as a delete); TEXT rows are
authoritative for their own text, so deletes store PROP_ABSENT as in
the row model.

This module is the executable semantic spec: pure numpy, one op at a
time, dynamically sized arrays. It is differentially tested against
the scalar oracle (core/mergetree.py) and gates the pallas overlay
kernel bit-for-bit. ops/overlay_pallas.py is the TPU execution of
exactly these semantics.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..protocol.constants import NO_CLIENT
from .mergetree_kernel import (
    ERR_BAD_POS,
    ERR_REMOVERS,
    NOT_REMOVED,
    OP_ANNOTATE,
    OP_INSERT,
    OP_REMOVE,
    PROP_ABSENT,
    PROP_DELETE,
)

SETTLED_BASE = 1 << 30  # buf encoding for span rows: SETTLED_BASE + coord


def merge_span_props(seg_p: np.ndarray, row_p: np.ndarray) -> np.ndarray:
    """Resolve a span row's prop cells over a settled-props slice:
    PROP_DELETE tombstones clear the key, PROP_ABSENT leaves it, any
    other value overwrites. The ONE definition of span-prop
    resolution — used by fold, read-out, and log reconstruction."""
    out = seg_p.copy()
    for k in range(seg_p.shape[1]):
        if row_p[k] == PROP_DELETE:
            out[:, k] = PROP_ABSENT
        elif row_p[k] != PROP_ABSENT:
            out[:, k] = row_p[k]
    return out


class OverlayDoc:
    """Numpy reference overlay document (dynamic arrays, one op/call)."""

    def __init__(self, settled_text: np.ndarray, n_removers: int = 4,
                 n_prop_keys: int = 8):
        self.KR = n_removers
        self.KK = n_prop_keys
        # Settled state (host-side; the device engine keeps only S and
        # reconstructs text/props from the fold log).
        self.settled_text = np.asarray(settled_text, np.int32).copy()
        self.settled_props = np.full(
            (len(settled_text), n_prop_keys), PROP_ABSENT, np.int32
        )
        # Per-position insert-attribution keys (insert seq; 0 for
        # loaded content) — the attributionCollection.ts role carried
        # through folds (unsettled rows derive theirs from iseq).
        self.settled_attr = np.zeros(len(settled_text), np.int32)
        self.S = len(settled_text)
        # Overlay rows (length-n arrays, storage order == doc order).
        self.anchor = np.zeros(0, np.int32)
        self.buf = np.zeros(0, np.int32)
        self.length = np.zeros(0, np.int32)
        self.iseq = np.zeros(0, np.int32)
        self.iclient = np.zeros(0, np.int32)
        self.rseq = np.zeros(0, np.int32)
        self.rcl = np.zeros((0, n_removers), np.int32)
        self.props = np.zeros((0, n_prop_keys), np.int32)
        self.error = 0
        # Peak overlay occupancy (capacity planning for the kernel).
        self.peak_rows = 0
        self.max_gaps_per_op = 0

    # ------------------------------------------------------------ helpers

    @property
    def n(self) -> int:
        return len(self.anchor)

    def _is_span(self) -> np.ndarray:
        return self.buf >= SETTLED_BASE

    def _consume(self) -> np.ndarray:
        return np.where(self._is_span(), self.length, 0)

    def _visibility(self, ref_seq: int, client: int):
        """Per-row (skip, vis_len) at a perspective — the
        mergeTree.ts:916 nodeLength predicate, identical to
        mergetree_kernel._visibility minus the live mask."""
        removed = self.rseq != NOT_REMOVED
        tomb = removed & (self.rseq <= ref_seq)
        ins_vis = (self.iclient == client) | (self.iseq <= ref_seq)
        among = (self.rcl == client).any(axis=1) if self.n else np.zeros(0, bool)
        skip = tomb | (removed & ~ins_vis)
        visible = ~skip & ins_vis & ~(removed & among)
        vis_len = np.where(visible, self.length, 0)
        return skip, vis_len

    def _pre(self, vis_len: np.ndarray):
        delta = vis_len - self._consume()
        cum = np.cumsum(delta) - delta
        return self.anchor + cum, int(delta.sum())

    def _insert_row(self, at: int, anchor, buf, length, iseq, iclient,
                    rseq, rcl_row=None, props_row=None) -> None:
        def ins(a, v):
            return np.insert(a, at, v, axis=0)

        self.anchor = ins(self.anchor, anchor)
        self.buf = ins(self.buf, buf)
        self.length = ins(self.length, length)
        self.iseq = ins(self.iseq, iseq)
        self.iclient = ins(self.iclient, iclient)
        self.rseq = ins(self.rseq, rseq)
        self.rcl = ins(
            self.rcl,
            rcl_row if rcl_row is not None
            else np.full(self.KR, NO_CLIENT, np.int32),
        )
        self.props = ins(
            self.props,
            props_row if props_row is not None
            else np.full(self.KK, PROP_ABSENT, np.int32),
        )
        self.peak_rows = max(self.peak_rows, self.n)

    def _split(self, pos: int, ref_seq: int, client: int) -> None:
        """Boundary split (ensureIntervalBoundary, mergeTree.ts:1706):
        if visible position `pos` falls strictly inside a row, split it.
        Span-row tails advance their anchor with the offset (the tail
        covers later coordinates); text-row tails keep the anchor (both
        halves sit at the same point)."""
        skip, vis = self._visibility(ref_seq, client)
        pre, _ = self._pre(vis)
        inside = ~skip & (pre < pos) & (pre + vis > pos)
        if not inside.any():
            return
        j = int(np.argmax(inside))
        off = pos - int(pre[j])
        span = bool(self._is_span()[j])
        self._insert_row(
            j + 1,
            self.anchor[j] + (off if span else 0),
            self.buf[j] + off,
            self.length[j] - off,
            self.iseq[j], self.iclient[j], self.rseq[j],
            self.rcl[j].copy(), self.props[j].copy(),
        )
        self.length[j] = off

    def _coord_of(self, pos: int, pre: np.ndarray, delta_sum: int) -> int:
        """Settled coordinate of visible position `pos` (assumes any
        row strictly containing `pos` was already split)."""
        cand = pre >= pos
        if cand.any():
            j = int(np.argmax(cand))
            return int(self.anchor[j]) - (int(pre[j]) - pos)
        return pos - delta_sum

    # ------------------------------------------------------------- apply

    def apply(self, op_type: int, pos1: int, pos2: int, seq: int,
              ref_seq: int, client: int, buf_start: int, ins_len: int,
              prop_keys, prop_vals) -> None:
        if op_type == OP_INSERT:
            self._apply_insert(pos1, seq, ref_seq, client, buf_start,
                               ins_len, prop_keys, prop_vals)
        elif op_type in (OP_REMOVE, OP_ANNOTATE):
            self._apply_range(op_type, pos1, pos2, seq, ref_seq, client,
                              prop_keys, prop_vals)
        # NOOP: nothing.

    def _apply_insert(self, pos1, seq, ref_seq, client, buf_start,
                      ins_len, prop_keys, prop_vals) -> None:
        self._split(pos1, ref_seq, client)
        skip, vis = self._visibility(ref_seq, client)
        pre, delta_sum = self._pre(vis)
        total = self.S + delta_sum
        # Landing (insertingWalk + breakTie, mergeTree.ts:1740,:1719):
        # pre > pos1 means visible settled text intervenes — land
        # before that row regardless of tie-breaks; at pre == pos1 the
        # row-model walk applies (walk past skip rows and
        # zero-visibility rows that win the tie).
        land = (pre > pos1) | (
            (pre == pos1) & ~skip & ((vis > 0) | (seq > self.iseq))
        )
        if land.any():
            j = int(np.argmax(land))
            anchor_new = int(self.anchor[j]) - (int(pre[j]) - pos1)
        else:
            j = self.n
            if pos1 > total:
                self.error |= ERR_BAD_POS
            anchor_new = min(pos1 - delta_sum, self.S)
        props_row = np.full(self.KK, PROP_ABSENT, np.int32)
        for k, v in zip(prop_keys, prop_vals):
            if k >= 0:
                props_row[k] = PROP_ABSENT if v == PROP_DELETE else v
        self._insert_row(
            j, anchor_new, buf_start, ins_len, seq, client,
            NOT_REMOVED, None, props_row,
        )

    def _apply_range(self, op_type, pos1, pos2, seq, ref_seq, client,
                     prop_keys, prop_vals) -> None:
        self._split(pos1, ref_seq, client)
        self._split(pos2, ref_seq, client)
        skip, vis = self._visibility(ref_seq, client)
        pre, delta_sum = self._pre(vis)
        total = self.S + delta_sum
        if pos2 > total:
            self.error |= ERR_BAD_POS
        c1 = self._coord_of(pos1, pre, delta_sum)
        c2 = self._coord_of(pos2, pre, delta_sum)

        # Gap materialization: implicit settled coordinates covered by
        # [c1, c2) become span rows, one per storage gap (gap k sits
        # before row k; text anchors bound gaps, so materialized rows
        # never contain a foreign anchor strictly inside).
        consume = self._consume()
        glo = np.concatenate([[0], self.anchor + consume]).astype(np.int64)
        ghi = np.concatenate([self.anchor, [self.S]]).astype(np.int64)
        lo = np.maximum(glo, c1)
        hi = np.minimum(ghi, c2)
        mat = np.nonzero(lo < hi)[0]
        self.max_gaps_per_op = max(self.max_gaps_per_op, len(mat))
        for k in mat[::-1]:  # descending: indices stay valid
            self._insert_row(
                int(k), int(lo[k]), SETTLED_BASE + int(lo[k]),
                int(hi[k] - lo[k]), 0, NO_CLIENT, NOT_REMOVED,
            )

        # Covered-range updates (markRangeRemoved mergeTree.ts:1960 /
        # annotateRange :1895), identical to the row-model kernel.
        skip, vis = self._visibility(ref_seq, client)
        pre, _ = self._pre(vis)
        covered = ~skip & (vis > 0) & (pre >= pos1) & (pre + vis <= pos2)
        if op_type == OP_REMOVE:
            already = self.rseq != NOT_REMOVED
            upd = covered
            self.rseq = np.where(upd & ~already, seq, self.rseq)
            free = self.rcl == NO_CLIENT
            first_free = np.argmax(free, axis=1) if self.n else np.zeros(0, int)
            no_free = ~free.any(axis=1) if self.n else np.zeros(0, bool)
            slot = np.where(already, first_free, 0)
            write = upd & ~(already & no_free)
            for i in np.nonzero(write)[0]:
                self.rcl[i, slot[i]] = client
            if (upd & already & no_free).any():
                self.error |= ERR_REMOVERS
        else:  # annotate: last writer wins; deletes tombstone on spans
            is_span = self._is_span()
            for k, v in zip(prop_keys, prop_vals):
                if k < 0:
                    continue
                idx = np.nonzero(covered)[0]
                for i in idx:
                    if v == PROP_DELETE:
                        self.props[i, k] = (
                            PROP_DELETE if is_span[i] else PROP_ABSENT
                        )
                    else:
                        self.props[i, k] = v

    # -------------------------------------------------------------- fold

    def fold(self, msn: int) -> None:
        """Settle-merge under applied MSN `msn` (see module docstring)."""
        if self.n == 0:
            return
        removed = self.rseq != NOT_REMOVED
        is_span = self._is_span()
        drop = removed & (self.rseq <= msn)
        settle_text = ~removed & ~is_span & (self.iseq <= msn)
        settle_span = ~removed & is_span
        folding = drop | settle_text | settle_span
        if not folding.any():
            return

        exc_len = np.where(drop & is_span, self.length, 0)
        ins_len = np.where(settle_text, self.length, 0)
        exc_before = np.cumsum(exc_len) - exc_len
        ins_before = np.cumsum(ins_len) - ins_len

        # Rebuild settled text/props/attr in coordinate (== storage)
        # order.
        pieces_t: List[np.ndarray] = []
        pieces_p: List[np.ndarray] = []
        pieces_a: List[np.ndarray] = []
        cursor = 0

        def take_settled(upto: int) -> None:
            nonlocal cursor
            pieces_t.append(self.settled_text[cursor:upto])
            pieces_p.append(self.settled_props[cursor:upto])
            pieces_a.append(self.settled_attr[cursor:upto])
            cursor = upto

        for i in np.nonzero(folding)[0]:
            a = int(self.anchor[i])
            ln = int(self.length[i])
            if settle_text[i]:
                take_settled(a)
                pieces_t.append(self._row_text(i))
                pieces_p.append(np.broadcast_to(
                    self._fold_props_row(i, text_row=True), (ln, self.KK)
                ).copy())
                pieces_a.append(np.full(ln, self.iseq[i], np.int32))
            elif drop[i] and is_span[i]:
                take_settled(a)
                cursor = a + ln  # excise
            elif settle_span[i]:
                take_settled(a)
                pieces_t.append(self.settled_text[a: a + ln])
                pieces_p.append(merge_span_props(
                    self.settled_props[a: a + ln], self.props[i]
                ))
                pieces_a.append(self.settled_attr[a: a + ln])
                cursor = a + ln
            # drop & text row: nothing to do (just removed from overlay)
        take_settled(self.S)
        self.settled_text = np.concatenate(pieces_t) if pieces_t else (
            np.zeros(0, np.int32)
        )
        self.settled_props = np.concatenate(pieces_p) if pieces_p else (
            np.zeros((0, self.KK), np.int32)
        )
        self.settled_attr = np.concatenate(pieces_a) if pieces_a else (
            np.zeros(0, np.int32)
        )
        self.S = len(self.settled_text)

        keep = ~folding
        new_anchor = self.anchor - exc_before + ins_before
        self.anchor = new_anchor[keep].astype(np.int32)
        self.buf = np.where(
            is_span, SETTLED_BASE + new_anchor, self.buf
        )[keep].astype(np.int32)
        self.length = self.length[keep]
        self.iseq = self.iseq[keep]
        self.iclient = self.iclient[keep]
        self.rseq = self.rseq[keep]
        self.rcl = self.rcl[keep]
        self.props = self.props[keep]

    def _row_text(self, i: int) -> np.ndarray:
        """Codepoints of row i (overridden by the replica to resolve
        arena offsets; span rows read settled coordinates)."""
        if self.buf[i] >= SETTLED_BASE:
            a = int(self.buf[i]) - SETTLED_BASE
            return self.settled_text[a: a + int(self.length[i])]
        raise NotImplementedError("text rows need an arena resolver")

    def _fold_props_row(self, i: int, text_row: bool) -> np.ndarray:
        row = self.props[i].copy()
        if text_row:
            # Text rows are authoritative: ABSENT means absent.
            row[row == PROP_DELETE] = PROP_ABSENT
        return row

    # ----------------------------------------------------- verification

    def verify_invariants(self) -> None:
        """Structural invariants of the overlay representation (the
        partialLengths.ts:336 verifier role for this engine)."""
        assert (self.length > 0).all(), "zero/negative-length row"
        is_span = self._is_span()
        consume = self._consume()
        # Anchors non-decreasing; spans disjoint; anchors within bounds.
        end = self.anchor + consume
        assert (self.anchor >= 0).all() and (end <= self.S).all(), (
            "anchor out of settled range"
        )
        if self.n > 1:
            assert (self.anchor[1:] >= end[:-1]).all(), (
                "anchor order / span overlap violation"
            )
        # Span buf encoding stays in sync with anchors.
        assert (
            self.buf[is_span] - SETTLED_BASE == self.anchor[is_span]
        ).all(), "span buf/anchor desync"
        # Removal bookkeeping mirrors the row model.
        removed = self.rseq != NOT_REMOVED
        has_removers = (self.rcl != NO_CLIENT).any(axis=1)
        assert (removed == has_removers).all(), "removal/remover mismatch"
        # Span rows are settled content: universal insert identity.
        assert (self.iseq[is_span] == 0).all(), "span row with insert seq"


class OverlayMessageReplica:
    """SequencedMessage-driven overlay replica: the overlay engine
    behind the same message surface as `core.kernel_replica
    .KernelReplica`, so the farm differential tests (real concurrency:
    lagging refSeqs, tie-breaks, overlapping removes) gate the overlay
    semantics against the scalar oracle."""

    def __init__(self, initial: str = "", fold_interval: int = 64,
                 n_removers: int = 4, n_prop_keys: int = 8,
                 max_prop_pairs: int = 4):
        from ..core.kernel_replica import PropInterner, TextArena

        self.arena = TextArena("")
        self.props = PropInterner(n_prop_keys)
        self.fold_interval = fold_interval
        self.max_prop_pairs = max_prop_pairs
        doc = OverlayDoc(
            np.asarray([ord(c) for c in initial], np.int32),
            n_removers, n_prop_keys,
        )

        def row_text(i: int) -> np.ndarray:
            b = int(doc.buf[i])
            ln = int(doc.length[i])
            if b >= SETTLED_BASE:
                a = b - SETTLED_BASE
                return doc.settled_text[a: a + ln]
            txt = self.arena.snapshot()[b: b + ln]
            return np.asarray([ord(c) for c in txt], np.int32)

        doc._row_text = row_text  # type: ignore[assignment]
        self.doc = doc
        self._since_fold = 0
        self._msn = 0

    def apply_messages(self, msgs) -> None:
        from ..core.kernel_replica import EncoderState, encode_op
        from ..protocol.messages import MessageType

        enc = EncoderState(self.arena, self.props, self.max_prop_pairs)
        for msg in msgs:
            if msg.type == MessageType.OP and msg.contents is not None:
                encode_op(enc, msg.contents, msg)
                for row in enc._encoded:
                    (t, p1, p2, s, r, c, b, ln, ks, vs, msn) = row
                    self.doc.apply(t, p1, p2, s, r, c, b, ln, ks, vs)
                    self._msn = msn
                enc._encoded = []
                self._since_fold += 1
                if self._since_fold >= self.fold_interval:
                    self.doc.fold(self._msn)
                    self._since_fold = 0
            else:
                self._msn = max(self._msn, msg.minimum_sequence_number)
        self.doc.fold(self._msn)

    def check_errors(self) -> None:
        from .mergetree_kernel import raise_kernel_errors

        raise_kernel_errors(self.doc.error)

    def _doc_order(self):
        return OverlayReplica._doc_order(self)  # type: ignore[arg-type]

    def get_text(self) -> str:
        return "".join(
            "".join(map(chr, t)) for t, _ in self._doc_order()
        )

    def annotated_spans(self) -> List[Tuple[str, Optional[dict]]]:
        spans: List[Tuple[str, Optional[dict]]] = []
        for text, props in self._doc_order():
            for j in range(len(text)):
                row = np.asarray(props[j])
                p = self.props.decode_row(
                    np.where(row == PROP_DELETE, PROP_ABSENT, row)
                )
                spans.append((chr(int(text[j])), p))
        return spans


class OverlayReplica:
    """Stream-driven overlay replica (numpy reference engine).

    Consumes a `testing.synthetic.ColumnarStream` like
    `core.columnar_replay.ColumnarReplica`, folding every
    `fold_interval` ops. Exposes get_text()/annotated_spans() for
    digest comparison. Text rows resolve through the stream arena
    (offsets are rebased by STREAM_BASE like columnar_replay) or the
    initial document text.
    """

    def __init__(self, stream, initial_len: int = 0,
                 fold_interval: int = 2048, n_removers: int = 4,
                 n_prop_keys: int = 8):
        self.stream = stream
        self.fold_interval = fold_interval
        doc = OverlayDoc(
            np.asarray(stream.text[:initial_len], np.int32),
            n_removers, n_prop_keys,
        )
        stream_text = np.asarray(stream.text, np.int32)

        def row_text(i: int) -> np.ndarray:
            b = int(doc.buf[i])
            ln = int(doc.length[i])
            if b >= SETTLED_BASE:
                a = b - SETTLED_BASE
                return doc.settled_text[a: a + ln]
            return stream_text[b: b + ln]

        doc._row_text = row_text  # type: ignore[assignment]
        self.doc = doc

    def replay(self) -> None:
        s = self.stream
        d = self.doc
        n = len(s)
        for i in range(n):
            d.apply(
                int(s.op_type[i]), int(s.pos1[i]), int(s.pos2[i]),
                int(s.seq[i]), int(s.ref_seq[i]), int(s.client[i]),
                int(s.buf_start[i]), int(s.ins_len[i]),
                [int(s.prop_key[i])], [int(s.prop_val[i])],
            )
            if (i + 1) % self.fold_interval == 0 or i + 1 == n:
                d.fold(int(s.min_seq[i]))

    def check_errors(self) -> None:
        from .mergetree_kernel import raise_kernel_errors

        raise_kernel_errors(self.doc.error)

    # ------------------------------------------------------------ output

    def attribution_spans(self) -> List[Tuple[int, int]]:
        """(run_length, attribution key) runs over the visible
        document, adjacent equal keys merged — same surface as the
        scalar/native engines' attribution_spans (farm-gated); keys
        are insert seqs, 0 for initial content, carried through folds
        by `OverlayDoc.settled_attr`."""
        d = self.doc
        keys: List[np.ndarray] = []
        cursor = 0
        is_span = d._is_span()
        for i in range(d.n):
            a = int(d.anchor[i])
            if a > cursor:
                keys.append(d.settled_attr[cursor:a])
                cursor = a
            if int(d.rseq[i]) != NOT_REMOVED:
                if is_span[i]:
                    cursor = a + int(d.length[i])
                continue
            ln = int(d.length[i])
            if is_span[i]:
                keys.append(d.settled_attr[a: a + ln])
                cursor = a + ln
            else:
                keys.append(np.full(ln, int(d.iseq[i]), np.int32))
        keys.append(d.settled_attr[cursor:])
        out: List[Tuple[int, int]] = []
        for arr in keys:
            for k in np.asarray(arr).tolist():
                if out and out[-1][1] == k:
                    out[-1] = (out[-1][0] + 1, k)
                else:
                    out.append((1, k))
        return out

    def _doc_order(self) -> List[Tuple[np.ndarray, Optional[np.ndarray]]]:
        """(codepoints, per-char props | None) pieces in doc order:
        implicit settled gaps interleaved with visible overlay rows."""
        d = self.doc
        out: List[Tuple[np.ndarray, Optional[np.ndarray]]] = []
        cursor = 0
        is_span = d._is_span()
        for i in range(d.n):
            a = int(d.anchor[i])
            if a > cursor:
                out.append((
                    d.settled_text[cursor:a], d.settled_props[cursor:a]
                ))
                cursor = a
            if int(d.rseq[i]) != NOT_REMOVED:
                if is_span[i]:
                    cursor = a + int(d.length[i])
                continue
            ln = int(d.length[i])
            if is_span[i]:
                out.append((
                    d.settled_text[a: a + ln],
                    merge_span_props(d.settled_props[a: a + ln], d.props[i]),
                ))
                cursor = a + ln
            else:
                row_p = d.props[i].copy()
                row_p[row_p == PROP_DELETE] = PROP_ABSENT
                out.append((
                    d._row_text(i),
                    np.broadcast_to(row_p, (ln, d.KK)),
                ))
        if cursor < d.S:
            out.append((d.settled_text[cursor:], d.settled_props[cursor:]))
        return out

    def get_text(self) -> str:
        return "".join(
            "".join(map(chr, t)) for t, _ in self._doc_order()
        )

    def annotated_spans(self) -> List[Tuple[str, Optional[dict]]]:
        """Per-char span list in the synthetic stream's key naming
        (k<idx>), the same surface ColumnarReplica exposes for
        digest comparison."""
        spans: List[Tuple[str, Optional[dict]]] = []
        for text, props in self._doc_order():
            for j in range(len(text)):
                p = {
                    f"k{k}": int(props[j, k])
                    for k in range(self.doc.KK)
                    if props[j, k] != PROP_ABSENT
                }
                spans.append((chr(int(text[j])), p or None))
        return spans
