"""Pallas TPU kernel for the overlay merge-tree: O(collab window)/op.

Device execution of exactly the semantics specified by
`ops.overlay_ref.OverlayDoc` (the numpy executable spec; see that
module's docstring for the representation and its invariants). The
round-2 chunk kernel (ops/mergetree_pallas.py) keeps EVERY segment row
in VMEM and pays ~10 full-table vector passes per op — O(capacity) =
131k rows of work per op no matter how small the live collaboration
window is. Here the VMEM table holds ONLY unsettled rows (a few
thousand on the bench mix); settled content is a virtual coordinate
space represented by one scalar ``S`` whose text/props live off-kernel
in an append-only fold log. Per-op vector work scales with the window,
the way the reference bounds per-op work to O(log n) with its B-tree +
partial-lengths cache (mergeTree.ts:1397 insertSegments,
partialLengths.ts:256).

Execution shape, per chunk of B sequenced ops:

1. `_overlay_chunk_kernel` (pallas): the overlay columns live in VMEM
   as (W/128, 128) int32 tiles for the whole chunk; a `fori_loop`
   applies ops back-to-back with pure vector-domain bodies (one-hot
   masks, log-doubling cumsums, masked suffix shifts — the idioms
   proven in mergetree_pallas.py). Op-type branches use `pl.when` on
   SMEM scalars so inserts skip range work and vice versa. The one
   per-op vector->scalar crossing is the gap-materialization count of
   range ops (a dynamic `fori_loop` inserts exactly that many span
   rows; see overlay_ref.py "gap materialization").
2. `fold_device` (plain XLA): the settle-merge (overlay_ref.fold /
   the zamboni role, zamboni.ts:19). Folding rows leave the table
   (payload sorts, not gathers — an XLA gather lowers to ~100ns/elem
   on TPU, see ops/zamboni.py), survivors re-anchor by prefix sums,
   and the folded rows are emitted as a dense record block.
3. `replay_chunk_step` (one jit): kernel + fold + append of the fold
   records into a preallocated HBM log (`lax.dynamic_update_slice`,
   donated so XLA updates in place). The host replay loop performs
   zero device syncs; `core.overlay_replay.OverlayDeviceReplica`
   reconstructs the settled document from the log after the timed
   region.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..protocol.constants import NO_CLIENT
from .mergetree_kernel import (
    ERR_BAD_POS,
    ERR_CAPACITY,
    ERR_REMOVERS,
    NO_KEY,
    NOT_REMOVED,
    OP_ANNOTATE,
    OP_INSERT,
    OP_REMOVE,
    OpBatch,
    PROP_ABSENT,
    PROP_DELETE,
)
from .mergetree_pallas import (
    LANES,
    _flat_idx,
    _roll1_flat,
    _row_idx,
)
from .overlay_ref import SETTLED_BASE
from .zamboni import _pack_partition

# Fold-record type codes (column 1 of a log record).
REC_NONE = 0  # dropped text row: nothing to reconstruct (kept in the
#               block so one stable partition serves table + records)
REC_SETTLE_TEXT = 1  # unsettled insert becomes settled text at anchor
REC_DROP_SPAN = 2  # settled coords [anchor, anchor+len) excised
REC_SETTLE_SPAN = 3  # props merge into settled [anchor, anchor+len)


class OverlayTable(NamedTuple):
    """Device overlay state: unsettled rows + the settled length."""

    n_rows: jnp.ndarray  # int32 scalar
    anchor: jnp.ndarray  # int32[W] settled coordinate the row sits at
    buf_start: jnp.ndarray  # int32[W]; >= SETTLED_BASE marks span rows
    length: jnp.ndarray  # int32[W]
    ins_seq: jnp.ndarray  # int32[W] (0 for span rows)
    ins_client: jnp.ndarray  # int32[W]
    rem_seq: jnp.ndarray  # int32[W] (NOT_REMOVED if live)
    rem_clients: jnp.ndarray  # int32[W, KR]
    props: jnp.ndarray  # int32[W, KK]
    settled_len: jnp.ndarray  # int32 scalar: S
    error: jnp.ndarray  # int32 scalar ERR_* flags


def make_overlay_table(
    window: int, n_removers: int = 4, n_prop_keys: int = 8,
    settled_len: int = 0,
) -> OverlayTable:
    return OverlayTable(
        n_rows=jnp.int32(0),
        anchor=jnp.zeros(window, jnp.int32),
        buf_start=jnp.zeros(window, jnp.int32),
        length=jnp.zeros(window, jnp.int32),
        ins_seq=jnp.zeros(window, jnp.int32),
        ins_client=jnp.full(window, NO_CLIENT, jnp.int32),
        rem_seq=jnp.full(window, NOT_REMOVED, jnp.int32),
        rem_clients=jnp.full((window, n_removers), NO_CLIENT, jnp.int32),
        props=jnp.full((window, n_prop_keys), PROP_ABSENT, jnp.int32),
        settled_len=jnp.int32(settled_len),
        error=jnp.int32(0),
    )


def _overlay_chunk_kernel(
    # scalars / op columns (SMEM)
    nrows_in_ref, err_in_ref, nops_ref, s_ref,
    op_type_ref, pos1_ref, pos2_ref, seq_ref, client_ref,
    buf_ref, ilen_ref, pkey_ref, pval_ref, ref_seq_ref,
    # table columns in (VMEM)
    t_anchor_in, t_buf_in, t_len_in, t_iseq_in, t_iclient_in, t_rseq_in,
    t_rcl_in, t_props_in,
    # table columns out (VMEM) + scalars out (SMEM)
    t_anchor, t_buf, t_len, t_iseq, t_iclient, t_rseq, t_rcl, t_props,
    nrows_out_ref, err_out_ref,
    # scratch: stacked table + gap staging (VMEM), scalars (SMEM)
    T, G, nlive_ref, err_ref,
):
    """FUSED per-op form (round 4). Semantics identical to the round-3
    kernel / overlay_ref.OverlayDoc.apply (differential farm gates);
    the execution shape is redesigned for the serial-latency bound the
    round-3 profile exposed (per-op cost was ~window-independent —
    dominated by the NUMBER of dependent small vector ops, not data):

    - ONE perspective pass per op (visibility + prefix scan), with
      ``pre``/``vis``/``skip`` kept as scratch COLUMNS of the stacked
      table so split fixups and the covered phase never recompute the
      scan (the incremental-partial-lengths role, partialLengths.ts:256).
    - All landing/split indices move to the SCALAR domain via full
      reductions (jnp.min over one-hot masks) instead of mask cumsums
      + vector broadcasts.
    - The whole table is ONE stacked (C, W8, 128) tensor; a segment
      split + row insert is one or two dest-based masked rolls of the
      full stack (insertingWalk's memmove, mergeTree.ts:1740) — a few
      big instructions instead of ~20 per-column roll sequences.
    - Rows live in a packed prefix tracked by an SMEM ``n_live``
      scalar (no live column; capacity checks are scalar compares).
    """
    KR = t_rcl_in.shape[0]
    KK = t_props_in.shape[0]
    B = pos1_ref.shape[0]
    PK = pkey_ref.shape[0] // B
    shape = t_len_in.shape
    window = shape[0] * LANES
    flat = _flat_idx(shape)
    S = s_ref[0]
    W = jnp.int32(window)
    IMIN = jnp.int32(-2147483647)

    # Stacked column layout.
    A_, B_, L_, IS_, IC_, RS_ = 0, 1, 2, 3, 4, 5
    RC0 = 6
    PP0 = RC0 + KR
    PRE_ = PP0 + KK
    VIS_ = PRE_ + 1

    T[A_] = t_anchor_in[...]
    T[B_] = t_buf_in[...]
    T[L_] = t_len_in[...]
    T[IS_] = t_iseq_in[...]
    T[IC_] = t_iclient_in[...]
    T[RS_] = t_rseq_in[...]
    for k in range(KR):
        T[RC0 + k] = t_rcl_in[k]
    for k in range(KK):
        T[PP0 + k] = t_props_in[k]
    T[PRE_] = jnp.zeros(shape, jnp.int32)
    T[VIS_] = jnp.zeros(shape, jnp.int32)
    nlive_ref[0] = nrows_in_ref[0]
    err_ref[0] = err_in_ref[0]

    lane1 = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    # Upper-triangular ones: the lane-inclusive prefix sum becomes ONE
    # MXU matmul (v @ U) instead of a log2(128)-step roll chain. Exact:
    # every partial sum is an integer below 2^24 (document length bound
    # 2^23), representable in f32; HIGHEST precision avoids the bf16
    # fast path. Hoisted out of the op loop.
    U_tri = (
        jax.lax.broadcasted_iota(jnp.int32, (LANES, LANES), 0)
        <= jax.lax.broadcasted_iota(jnp.int32, (LANES, LANES), 1)
    ).astype(jnp.float32)
    row_i = _row_idx(shape)

    def cumsum_and_total(v):
        """(exclusive flat prefix sum, grand total) of int32 tiles."""
        inc = jax.lax.dot(
            v.astype(jnp.float32), U_tri,
            precision=jax.lax.Precision.HIGHEST,
        ).astype(jnp.int32)
        totals = jnp.broadcast_to(inc[:, LANES - 1:], shape)
        s = 1
        rt = totals
        while s < shape[0]:
            rt = rt + jnp.where(row_i >= s, pltpu.roll(rt, s, 0), 0)
            s *= 2
        row_excl = jnp.where(row_i > 0, pltpu.roll(rt, 1, 0), 0)
        return (inc - v) + row_excl, rt[shape[0] - 1, 0]

    def at(ci, j):
        """Scalar value of stacked column `ci` at flat row `j`: one
        dynamic-sublane (1, LANES) load + a lane-only reduce — far
        cheaper than a full-window masked reduce. `j` is clamped;
        out-of-range results are selected away by callers."""
        jc = jnp.minimum(j, W - 1)
        row = T[ci, pl.ds(jc // LANES, 1), :]
        return jnp.max(jnp.where(lane1 == jc % LANES, row, IMIN))

    def at_g(gref, ci, j):
        row = gref[ci, pl.ds(j // LANES, 1), :]
        return jnp.max(jnp.where(lane1 == j % LANES, row, IMIN))

    def first_idx(mask):
        """Index of the first set row, or W when none."""
        return jnp.min(jnp.where(mask, flat, W))

    def roll_from(thr):
        """Dest-based masked roll of the WHOLE stack: row j takes row
        j-1 for j >= thr (insertingWalk's memmove as ~4 wide ops).
        Row max(thr,1)-1 keeps its value; the opened slot holds a
        stale copy the caller overwrites. thr >= W: full no-op mask
        (callers pl.when-guard to skip the work entirely)."""
        v = T[...]
        w = pltpu.roll(v, 1, 2)
        carry = pltpu.roll(w, 1, 1)
        lane = jax.lax.broadcasted_iota(jnp.int32, v.shape, 2)
        rolled = jnp.where(lane == 0, carry, w)
        T[...] = jnp.where(flat[None] >= thr, rolled, v)

    def vis_pass(r, c):
        """The ONE perspective pass (overlay_ref._visibility + _pre;
        mergeTree.ts:916 nodeLength, partialLengths.ts:256): writes
        pre/vis scratch columns, returns (skip, dsum). Note vis > 0
        implies ~skip, so downstream phases that only touch visible
        rows never need skip."""
        nl = nlive_ref[0]
        live = flat < nl
        rseq = T[RS_]
        removed = rseq != NOT_REMOVED
        tomb = removed & (rseq <= r)
        ins_vis = (T[IC_] == c) | (T[IS_] <= r)
        among = jnp.any(T[RC0:PP0] == c, axis=0)
        skip = (~live) | tomb | (removed & ~ins_vis)
        visible = (~skip) & ins_vis & ~(removed & among)
        vis = jnp.where(visible, T[L_], 0)
        is_span = T[B_] >= SETTLED_BASE
        consume = jnp.where(live & is_span, T[L_], 0)
        delta = vis - consume
        excl, dsum = cumsum_and_total(delta)
        T[PRE_] = T[A_] + excl
        T[VIS_] = vis
        return skip, dsum

    def clear_new_row(ohn):
        """Remover/prop columns of a freshly opened slot."""
        oh3 = ohn[None]
        T[RC0:PP0] = jnp.where(oh3, NO_CLIENT, T[RC0:PP0])
        T[PP0:PRE_] = jnp.where(oh3, PROP_ABSENT, T[PP0:PRE_])

    def set1(ci, oh, val):
        T[ci] = jnp.where(oh, val, T[ci])

    def body(i, _):
        otype = op_type_ref[i]
        pos1 = pos1_ref[i]
        pos2 = pos2_ref[i]
        oseq = seq_ref[i]
        orefseq = ref_seq_ref[i]
        oclient = client_ref[i]
        obuf = buf_ref[i]
        oilen = ilen_ref[i]

        is_ins = otype == OP_INSERT
        is_rem = otype == OP_REMOVE
        is_ann = otype == OP_ANNOTATE
        is_range = is_rem | is_ann

        @pl.when(is_ins)
        def _():
            # Landing (overlay_ref._apply_insert / insertingWalk +
            # breakTie, mergeTree.ts:1740,:1719) fused with the
            # boundary split: both indices resolve in pre-split
            # coordinates from the single perspective pass. An inside
            # row (pre < pos < pre+vis) always precedes every landing
            # row (pre >= pos; pre is non-decreasing), so ONE reduce
            # finds whichever applies, and the row's scalars serve
            # both cases.
            skip, dsum = vis_pass(orefseq, oclient)
            nl = nlive_ref[0]
            live = flat < nl
            pre = T[PRE_]
            vis = T[VIS_]
            total = S + dsum
            inside = (pre < pos1) & (pre + vis > pos1)
            land = live & (
                (pre > pos1)
                | ((pre == pos1) & (~skip)
                   & ((vis > 0) | (oseq > T[IS_])))
            )
            j0 = first_idx(inside | land)
            preX = at(PRE_, j0)
            visX = at(VIS_, j0)
            ancX = at(A_, j0)
            bufX = at(B_, j0)
            has_split = (j0 < W) & (preX < pos1) & (preX + visX > pos1)
            land_dead = j0 >= nl
            j_l = jnp.minimum(j0, nl)
            span_s = bufX >= SETTLED_BASE
            off = pos1 - preX
            A_nosplit = jnp.where(
                land_dead, jnp.minimum(pos1 - dsum, S),
                ancX - (preX - pos1),
            )
            Aval = jnp.where(
                has_split, ancX + jnp.where(span_s, off, 0), A_nosplit
            )
            t1 = jnp.where(has_split, j0 + 1, j_l)
            n_new = jnp.where(has_split, 2, 1)
            err_ref[0] = err_ref[0] | jnp.where(
                (~has_split) & land_dead & (total < pos1),
                ERR_BAD_POS, 0,
            ) | jnp.where(nl + n_new > W, ERR_CAPACITY, 0)
            roll_from(t1)

            @pl.when(has_split)
            def _():
                roll_from(t1)
                oh_h = flat == (t1 - 1)  # head (row j_s)
                set1(L_, oh_h, off)
                set1(VIS_, oh_h, off)
                oh_t = flat == (t1 + 1)  # tail (raw copy of j_s)
                set1(B_, oh_t, T[B_] + off)
                set1(L_, oh_t, T[L_] - off)

                @pl.when(span_s)
                def _():
                    set1(A_, oh_t, T[A_] + off)

                set1(PRE_, oh_t, pos1)
                set1(VIS_, oh_t, T[VIS_] - off)

            ohn = flat == t1
            set1(A_, ohn, Aval)
            set1(B_, ohn, obuf)
            set1(L_, ohn, oilen)
            set1(IS_, ohn, oseq)
            set1(IC_, ohn, oclient)
            set1(RS_, ohn, NOT_REMOVED)
            clear_new_row(ohn)
            for p in range(PK):
                pkey = pkey_ref[p * B + i]
                pval = pval_ref[p * B + i]

                @pl.when(pkey != NO_KEY)
                def _(pkey=pkey, pval=pval):
                    v = jnp.where(
                        pval == PROP_DELETE, PROP_ABSENT, pval
                    )
                    for k in range(KK):
                        @pl.when(pkey == k)
                        def _(k=k, v=v):
                            set1(PP0 + k, ohn, v)
            set1(PRE_, ohn, pos1)
            set1(VIS_, ohn, oilen)
            nlive_ref[0] = nl + n_new

        @pl.when(is_range)
        def _():
            # Both boundary splits resolve in pre-split coordinates
            # from one perspective pass, then compose as two
            # dest-based rolls (ensureIntervalBoundary,
            # mergeTree.ts:1706).
            skip, dsum = vis_pass(orefseq, oclient)
            nl = nlive_ref[0]
            live = flat < nl
            pre = T[PRE_]
            vis = T[VIS_]
            total = S + dsum
            err_ref[0] = err_ref[0] | jnp.where(
                total < pos2, ERR_BAD_POS, 0
            )
            inside1 = (pre < pos1) & (pre + vis > pos1)
            inside2 = (pre < pos2) & (pre + vis > pos2)
            j1 = first_idx(inside1)
            j2 = first_idx(inside2)
            has1 = j1 < W
            has2 = j2 < W
            pre1 = at(PRE_, j1)
            anc1 = at(A_, j1)
            buf1 = at(B_, j1)
            pre2 = at(PRE_, j2)
            anc2 = at(A_, j2)
            buf2 = at(B_, j2)
            off1 = pos1 - pre1
            off2 = pos2 - pre2
            span1 = buf1 >= SETTLED_BASE
            span2 = buf2 >= SETTLED_BASE
            # Settled coordinates of the range ends, resolved from the
            # PRE-split state: a split's tail has pre == pos exactly,
            # so c = tail anchor; otherwise the first live row with
            # pre >= pos (unchanged by the splits) anchors the
            # coordinate, falling back past the live rows.
            jc1 = first_idx(live & (pre >= pos1))
            jc2 = first_idx(live & (pre >= pos2))
            c1_nos = jnp.where(
                jc1 < W, at(A_, jc1) - (at(PRE_, jc1) - pos1),
                pos1 - dsum,
            )
            c2_nos = jnp.where(
                jc2 < W, at(A_, jc2) - (at(PRE_, jc2) - pos2),
                pos2 - dsum,
            )
            c1 = jnp.where(
                has1, anc1 + jnp.where(span1, off1, 0), c1_nos
            )
            c2 = jnp.where(
                has2, anc2 + jnp.where(span2, off2, 0), c2_nos
            )
            r1 = jnp.where(
                has1, j1 + 1, jnp.where(has2, j2 + 1, W)
            )
            err_ref[0] = err_ref[0] | jnp.where(
                nl + has1.astype(jnp.int32) + has2.astype(jnp.int32)
                > W,
                ERR_CAPACITY, 0,
            )

            @pl.when(has1 | has2)
            def _():
                roll_from(r1)

            @pl.when(has1 & has2)
            def _():
                roll_from(j2 + 2)

            @pl.when(has1)
            def _():
                oh_h = flat == j1
                set1(L_, oh_h, off1)
                set1(VIS_, oh_h, off1)
                oh_t = flat == (j1 + 1)
                set1(B_, oh_t, T[B_] + off1)
                set1(L_, oh_t, T[L_] - off1)

                @pl.when(span1)
                def _():
                    set1(A_, oh_t, T[A_] + off1)

                set1(PRE_, oh_t, pos1)
                set1(VIS_, oh_t, T[VIS_] - off1)

            @pl.when(has2)
            def _():
                d2 = j2 + has1.astype(jnp.int32)
                base = jnp.where(has1 & (j1 == j2), off1, 0)
                oh_d = flat == d2
                set1(L_, oh_d, off2 - base)
                set1(VIS_, oh_d, off2 - base)
                # tail2 is ALWAYS a raw copy of the ORIGINAL row j2
                # (untouched by split1 fixups), so adjust by off2
                # against the original even when j1 == j2.
                oh_t = flat == (d2 + 1)
                set1(B_, oh_t, T[B_] + off2)
                set1(L_, oh_t, T[L_] - off2)

                @pl.when(span2)
                def _():
                    set1(A_, oh_t, T[A_] + off2)

                set1(PRE_, oh_t, pos2)
                set1(VIS_, oh_t, T[VIS_] - off2)

            nlive_ref[0] = (
                nl + has1.astype(jnp.int32) + has2.astype(jnp.int32)
            )

            # ---- gap materialization (overlay_ref "gap
            # materialization"): lazily create span rows for settled
            # coords the range covers. Per-gap bounds stage through
            # the G scratch so the loop's scalars are cheap row loads.
            def gaps():
                nl = nlive_ref[0]
                live = flat < nl
                is_span = T[B_] >= SETTLED_BASE
                consume = jnp.where(live & is_span, T[L_], 0)
                end = T[A_] + consume
                glo = jnp.where(flat == 0, 0, _roll1_flat(end))
                ghi = jnp.where(live, T[A_], S)
                prev_live = (flat == 0) | (
                    _roll1_flat(live.astype(jnp.int32)) > 0
                )
                gapvalid = live | prev_live
                lo = jnp.maximum(glo, c1)
                hi = jnp.minimum(ghi, c2)
                G[0] = lo
                G[1] = hi
                G[2] = ghi
                return gapvalid & (lo < hi)

            n_mat = jnp.sum(gaps().astype(jnp.int32))

            def gap_body(_, carry):
                mat = gaps()
                nl = nlive_ref[0]
                j = first_idx(mat)
                loJ = at_g(G, 0, j)
                hiJ = at_g(G, 1, j)
                ghiJ = at_g(G, 2, j)
                # Visible prefix of the new span row: the displaced
                # row's prefix minus the settled run [loJ, ghiJ) that
                # still sits between them (gap after the live rows:
                # against the grand total).
                preJ = at(PRE_, j)
                pre_new = jnp.where(
                    j < nl, preJ, S + dsum
                ) - (ghiJ - loJ)
                err_ref[0] = err_ref[0] | jnp.where(
                    nl + 1 > W, ERR_CAPACITY, 0
                )
                roll_from(j)
                oh = flat == j
                set1(A_, oh, loJ)
                set1(B_, oh, SETTLED_BASE + loJ)
                set1(L_, oh, hiJ - loJ)
                set1(IS_, oh, 0)
                set1(IC_, oh, NO_CLIENT)
                set1(RS_, oh, NOT_REMOVED)
                clear_new_row(oh)
                set1(PRE_, oh, pre_new)
                set1(VIS_, oh, hiJ - loJ)
                nlive_ref[0] = nl + 1
                return carry

            lax.fori_loop(0, n_mat, gap_body, 0)

            # ---- covered-range updates (markRangeRemoved
            # mergeTree.ts:1960 / annotateRange :1895) straight off
            # the maintained columns — no rescan (vis > 0 already
            # implies the row is live, unskipped and visible).
            pre = T[PRE_]
            vis = T[VIS_]
            covered = (
                (vis > 0) & (pre >= pos1) & (pre + vis <= pos2)
                & (flat < nlive_ref[0])
            )

            @pl.when(is_rem)
            def _():
                rcl = T[RC0:PP0]
                already = T[RS_] != NOT_REMOVED
                set1(RS_, covered & ~already, oseq)
                iota_k = jax.lax.broadcasted_iota(
                    jnp.int32, rcl.shape, 0
                )
                first_free = jnp.min(
                    jnp.where(rcl == NO_CLIENT, iota_k, KR), axis=0
                )
                no_free = first_free == KR
                slot = jnp.where(already, first_free, 0)
                write = covered & ~(already & no_free)
                T[RC0:PP0] = jnp.where(
                    write[None] & (iota_k == slot[None]), oclient, rcl
                )
                err_ref[0] = err_ref[0] | jnp.where(
                    jnp.any(covered & already & no_free),
                    ERR_REMOVERS, 0,
                )

            @pl.when(is_ann)
            def _():
                # Last writer wins; a delete tombstones on span rows
                # (it must fold as a delete of the settled prop) but
                # clears on text rows (they are authoritative).
                is_span = T[B_] >= SETTLED_BASE
                for p in range(PK):
                    pkey = pkey_ref[p * B + i]
                    pval = pval_ref[p * B + i]
                    newv = jnp.where(
                        pval == PROP_DELETE,
                        jnp.where(is_span, PROP_DELETE, PROP_ABSENT),
                        jnp.broadcast_to(pval, shape),
                    )
                    for k in range(KK):
                        @pl.when(pkey == k)
                        def _(k=k, newv=newv):
                            set1(PP0 + k, covered, newv)

        return 0

    lax.fori_loop(0, nops_ref[0], body, 0)

    t_anchor[...] = T[A_]
    t_buf[...] = T[B_]
    t_len[...] = T[L_]
    t_iseq[...] = T[IS_]
    t_iclient[...] = T[IC_]
    t_rseq[...] = T[RS_]
    for k in range(KR):
        t_rcl[k] = T[RC0 + k]
    for k in range(KK):
        t_props[k] = T[PP0 + k]
    nrows_out_ref[0] = nlive_ref[0]
    err_out_ref[0] = err_ref[0]


def _to_tiles(v):
    return v.reshape(-1, LANES)


@functools.partial(jax.jit, static_argnums=(2,))
def overlay_apply_chunk(table: OverlayTable, ops: OpBatch,
                        interpret: bool = False) -> OverlayTable:
    """Apply a chunk of sequenced ops (ascending seq order) to the
    overlay in ONE pallas kernel invocation. Bit-identical to
    `overlay_ref.OverlayDoc.apply` run op-by-op (differentially gated
    by tests/test_overlay_pallas.py)."""
    window = table.length.shape[0]
    KR = table.rem_clients.shape[1]
    KK = table.props.shape[1]
    B = ops.pos1.shape[0]
    PK = ops.prop_keys.shape[1]
    assert window % (8 * LANES) == 0, "window must be a multiple of 1024"

    tile_in = [
        _to_tiles(table.anchor), _to_tiles(table.buf_start),
        _to_tiles(table.length), _to_tiles(table.ins_seq),
        _to_tiles(table.ins_client), _to_tiles(table.rem_seq),
        jnp.moveaxis(table.rem_clients, 1, 0).reshape(KR, -1, LANES),
        jnp.moveaxis(table.props, 1, 0).reshape(KK, -1, LANES),
    ]
    op_in = [
        ops.op_type, ops.pos1, ops.pos2, ops.seq, ops.client,
        ops.buf_start, ops.ins_len,
        jnp.moveaxis(ops.prop_keys, 1, 0).reshape(PK * B),
        jnp.moveaxis(ops.prop_vals, 1, 0).reshape(PK * B),
        ops.ref_seq,
    ]

    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)
    W8 = window // LANES
    out_shapes = (
        jax.ShapeDtypeStruct((W8, LANES), jnp.int32),  # anchor
        jax.ShapeDtypeStruct((W8, LANES), jnp.int32),  # buf
        jax.ShapeDtypeStruct((W8, LANES), jnp.int32),  # len
        jax.ShapeDtypeStruct((W8, LANES), jnp.int32),  # ins_seq
        jax.ShapeDtypeStruct((W8, LANES), jnp.int32),  # ins_client
        jax.ShapeDtypeStruct((W8, LANES), jnp.int32),  # rem_seq
        jax.ShapeDtypeStruct((KR, W8, LANES), jnp.int32),
        jax.ShapeDtypeStruct((KK, W8, LANES), jnp.int32),
        jax.ShapeDtypeStruct((1,), jnp.int32),  # n_rows
        jax.ShapeDtypeStruct((1,), jnp.int32),  # error
    )
    C = 8 + KR + KK  # 6 scalar cols + rcl + props + pre/vis
    outs = pl.pallas_call(
        _overlay_chunk_kernel,
        out_shape=out_shapes,
        in_specs=[smem()] * 14 + [vmem()] * 8,
        out_specs=tuple([vmem()] * 8 + [smem(), smem()]),
        scratch_shapes=[
            pltpu.VMEM((C, W8, LANES), jnp.int32),  # stacked table
            pltpu.VMEM((3, W8, LANES), jnp.int32),  # gap lo/hi/ghi
            pltpu.SMEM((1,), jnp.int32),  # n_live
            pltpu.SMEM((1,), jnp.int32),  # error flags
        ],
        interpret=interpret,
    )(
        jnp.reshape(table.n_rows, (1,)), jnp.reshape(table.error, (1,)),
        jnp.asarray([B], jnp.int32),
        jnp.reshape(table.settled_len, (1,)),
        *op_in, *tile_in,
    )
    (anchor, buf, length, iseq, iclient, rseq, rcl, props, nrows,
     err) = outs
    return OverlayTable(
        n_rows=nrows[0],
        anchor=anchor.reshape(-1),
        buf_start=buf.reshape(-1),
        length=length.reshape(-1),
        ins_seq=iseq.reshape(-1),
        ins_client=iclient.reshape(-1),
        rem_seq=rseq.reshape(-1),
        rem_clients=jnp.moveaxis(rcl.reshape(KR, -1), 0, 1),
        props=jnp.moveaxis(props.reshape(KK, -1), 0, 1),
        settled_len=table.settled_len,
        error=err[0],
    )


@jax.jit
def fold_device(table: OverlayTable, msn: jnp.ndarray):
    """Settle-merge under applied MSN `msn` (overlay_ref.fold; the
    zamboni role, zamboni.ts:19) as one XLA dispatch.

    Returns ``(table', records, n_rec)``: ONE stable binary partition
    (log-shift compaction, `_pack_partition` — no sort network, no
    gathers) packs surviving rows to the front (re-anchored) and the
    folding rows to the back; because the partition is stable, the
    back IS the fold-record block in storage (== coordinate) order.
    Records are ``(W, 5+KK)`` columns ``[anchor, code, buf, len,
    ins_seq, props...]`` with pre-fold anchors (ins_seq carries the
    per-position insert-attribution key into the settled state — the
    attributionCollection.ts role); ``code == REC_NONE`` rows
    (dropped text) reconstruct to nothing but stay in the block so
    one partition serves both outputs.
    """
    W = table.length.shape[0]
    KR = table.rem_clients.shape[1]
    KK = table.props.shape[1]
    idx = jnp.arange(W, dtype=jnp.int32)
    live = idx < table.n_rows
    is_span = live & (table.buf_start >= SETTLED_BASE)
    removed = live & (table.rem_seq != NOT_REMOVED)
    drop = removed & (table.rem_seq <= msn)
    settle_text = live & ~removed & ~is_span & (table.ins_seq <= msn)
    settle_span = live & ~removed & is_span
    folding = drop | settle_text | settle_span

    exc = jnp.where(drop & is_span, table.length, 0)
    ins = jnp.where(settle_text, table.length, 0)
    exc_b = jnp.cumsum(exc) - exc
    ins_b = jnp.cumsum(ins) - ins
    new_anchor = (table.anchor - exc_b + ins_b).astype(jnp.int32)
    new_s = table.settled_len + jnp.sum(ins) - jnp.sum(exc)

    keep = live & ~folding
    n_new = jnp.sum(keep.astype(jnp.int32))
    n_rec = jnp.sum(folding.astype(jnp.int32))
    new_buf = jnp.where(is_span, SETTLED_BASE + new_anchor,
                        table.buf_start)
    code = jnp.where(
        settle_text, REC_SETTLE_TEXT,
        jnp.where(drop & is_span, REC_DROP_SPAN,
                  jnp.where(settle_span, REC_SETTLE_SPAN, 0)),
    ).astype(jnp.int32)
    cols = (
        new_anchor, new_buf, table.length, table.ins_seq,
        table.ins_client, table.rem_seq,
        *(table.rem_clients[:, k] for k in range(KR)),
        *(table.props[:, k] for k in range(KK)),
        table.anchor, code,
    )
    packed = _pack_partition(~keep, cols)
    valid = idx < n_new

    def fill(a, f):
        return jnp.where(valid, a, f)

    out = OverlayTable(
        n_rows=n_new,
        anchor=fill(packed[0], 0),
        buf_start=fill(packed[1], 0),
        length=fill(packed[2], 0),
        ins_seq=fill(packed[3], 0),
        ins_client=fill(packed[4], NO_CLIENT),
        rem_seq=fill(packed[5], NOT_REMOVED),
        rem_clients=jnp.where(
            valid[:, None], jnp.stack(packed[6:6 + KR], axis=1), NO_CLIENT
        ),
        props=jnp.where(
            valid[:, None], jnp.stack(packed[6 + KR:6 + KR + KK], axis=1),
            PROP_ABSENT,
        ),
        settled_len=new_s.astype(jnp.int32),
        error=table.error,
    )

    # The back of the partition holds the folding rows in storage
    # order (stable), directly followed by dead rows; rotate them to
    # the front of the record block for the log append.
    old_anchor_p = packed[6 + KR + KK]
    code_p = packed[6 + KR + KK + 1]
    rec_cols = (old_anchor_p, code_p, packed[1], packed[2], packed[3],
                *packed[6 + KR:6 + KR + KK])
    records = jnp.roll(jnp.stack(rec_cols, axis=1), -n_new, axis=0)
    return out, records, n_rec


@functools.partial(
    jax.jit, static_argnums=(5, 6), donate_argnums=(0, 2, 3)
)
def replay_fused(
    table: OverlayTable, stream_ops: OpBatch, log, counts, msn_by_chunk,
    chunk: int, interpret: bool = False, epoch0=0,
):
    """The WHOLE replay as one dispatch: `lax.fori_loop` over chunks,
    each iteration = pallas apply + XLA fold + log append, all
    device-resident (stream, msn schedule, log, table ride the loop
    carry; XLA keeps the donated log in place). One host->device
    dispatch replaces ~n/chunk of them — the host loop and its
    per-chunk scalar uploads are the dominant cost once the kernel is
    O(window), so fusing is worth ~10x wall-clock on a tunneled TPU.

    `msn_by_chunk[ci]` is the applied MSN at chunk ci's end (the fold
    perspective). Returns ``(table, log, counts, cursor)``.

    `epoch0` (streaming ingress): this call replays a SEGMENT of a
    larger stream whose global chunk numbering starts at `epoch0`;
    counts index globally and the log cursor carries in/out through
    `counts`'s prior entries (the caller threads table/log/counts
    across segment calls while the next segment's host->device
    transfer overlaps this one's compute)."""
    n_chunks = msn_by_chunk.shape[0]
    epoch0 = jnp.asarray(epoch0, jnp.int32)
    # Resume the log cursor where earlier segments left it (the mask
    # is all-false at epoch0 == 0, so a fresh replay starts at 0).
    cursor0 = jnp.sum(
        counts * (jnp.arange(counts.shape[0]) < epoch0)
    ).astype(jnp.int32)

    def step(ci, carry):
        table, log, counts, cursor = carry
        table, log, counts, cursor = _chunk_step_body(
            table, stream_ops, ci * chunk, chunk, msn_by_chunk[ci],
            log, counts, cursor, epoch0 + ci, interpret,
        )
        return (table, log, counts, cursor)

    return lax.fori_loop(
        0, n_chunks, step, (table, log, counts, cursor0)
    )


def _chunk_step_body(
    table, stream_ops, lo, chunk, msn, log, counts, cursor, epoch,
    interpret,
):
    """One steady-state replay step, fully device-side: slice ops
    [lo, lo+chunk) from the device-resident stream, run the pallas
    chunk kernel, fold at the chunk boundary, and append the fold
    records to the HBM log (donated: XLA updates in place).

    Returns ``(table', log', counts', cursor')``; ``counts[epoch]``
    records this epoch's record count so the host can reconstruct the
    settled document epoch-by-epoch after the run."""
    sl = lambda a: lax.dynamic_slice_in_dim(a, lo, chunk, axis=0)
    batch = OpBatch(
        op_type=sl(stream_ops.op_type), pos1=sl(stream_ops.pos1),
        pos2=sl(stream_ops.pos2), seq=sl(stream_ops.seq),
        ref_seq=sl(stream_ops.ref_seq), client=sl(stream_ops.client),
        buf_start=sl(stream_ops.buf_start),
        ins_len=sl(stream_ops.ins_len),
        prop_keys=sl(stream_ops.prop_keys),
        prop_vals=sl(stream_ops.prop_vals),
    )
    table = overlay_apply_chunk(table, batch, interpret)
    table, records, n_rec = fold_device(table, msn)
    log = lax.dynamic_update_slice(
        log, records, (cursor, jnp.int32(0))
    )
    counts = counts.at[epoch].set(n_rec)
    return table, log, counts, cursor + n_rec


@functools.partial(
    jax.jit, static_argnums=(3, 9), donate_argnums=(0, 5, 6)
)
def replay_chunk_step(
    table: OverlayTable, stream_ops: OpBatch, lo, chunk: int,
    msn, log, counts, cursor, epoch, interpret: bool = False,
):
    """One replay step as its own dispatch (the incremental form:
    warm-up with `limit_chunks`, message-driven replicas, tests).
    `replay_fused` runs the same body for the whole stream in one
    dispatch."""
    return _chunk_step_body(
        table, stream_ops, lo, chunk, msn, log, counts, cursor, epoch,
        interpret,
    )
