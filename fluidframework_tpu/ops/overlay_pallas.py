"""Pallas TPU kernel for the overlay merge-tree: O(collab window)/op.

Device execution of exactly the semantics specified by
`ops.overlay_ref.OverlayDoc` (the numpy executable spec; see that
module's docstring for the representation and its invariants). The
round-2 chunk kernel (ops/mergetree_pallas.py) keeps EVERY segment row
in VMEM and pays ~10 full-table vector passes per op — O(capacity) =
131k rows of work per op no matter how small the live collaboration
window is. Here the VMEM table holds ONLY unsettled rows (a few
thousand on the bench mix); settled content is a virtual coordinate
space represented by one scalar ``S`` whose text/props live off-kernel
in an append-only fold log. Per-op vector work scales with the window,
the way the reference bounds per-op work to O(log n) with its B-tree +
partial-lengths cache (mergeTree.ts:1397 insertSegments,
partialLengths.ts:256).

Execution shape, per chunk of B sequenced ops:

1. `_overlay_chunk_kernel` (pallas): the overlay columns live in VMEM
   as (W/128, 128) int32 tiles for the whole chunk; a `fori_loop`
   applies ops back-to-back with pure vector-domain bodies (one-hot
   masks, log-doubling cumsums, masked suffix shifts — the idioms
   proven in mergetree_pallas.py). Op-type branches use `pl.when` on
   SMEM scalars so inserts skip range work and vice versa. The one
   per-op vector->scalar crossing is the gap-materialization count of
   range ops (a dynamic `fori_loop` inserts exactly that many span
   rows; see overlay_ref.py "gap materialization").
2. `fold_device` (plain XLA): the settle-merge (overlay_ref.fold /
   the zamboni role, zamboni.ts:19). Folding rows leave the table
   (payload sorts, not gathers — an XLA gather lowers to ~100ns/elem
   on TPU, see ops/zamboni.py), survivors re-anchor by prefix sums,
   and the folded rows are emitted as a dense record block.
3. `replay_chunk_step` (one jit): kernel + fold + append of the fold
   records into a preallocated HBM log (`lax.dynamic_update_slice`,
   donated so XLA updates in place). The host replay loop performs
   zero device syncs; `core.overlay_replay.OverlayDeviceReplica`
   reconstructs the settled document from the log after the timed
   region.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..protocol.constants import NO_CLIENT
from .mergetree_kernel import (
    ERR_BAD_POS,
    ERR_CAPACITY,
    ERR_REMOVERS,
    NO_KEY,
    NOT_REMOVED,
    OP_ANNOTATE,
    OP_INSERT,
    OP_REMOVE,
    OpBatch,
    PROP_ABSENT,
    PROP_DELETE,
)
from .mergetree_pallas import (
    LANES,
    _allreduce_sum,
    _cumsum_excl,
    _flat_idx,
    _roll1_flat,
)
from .overlay_ref import SETTLED_BASE
from .zamboni import _pack_sort

# Fold-record type codes (column 1 of a log record).
REC_SETTLE_TEXT = 1  # unsettled insert becomes settled text at anchor
REC_DROP_SPAN = 2  # settled coords [anchor, anchor+len) excised
REC_SETTLE_SPAN = 3  # props merge into settled [anchor, anchor+len)


class OverlayTable(NamedTuple):
    """Device overlay state: unsettled rows + the settled length."""

    n_rows: jnp.ndarray  # int32 scalar
    anchor: jnp.ndarray  # int32[W] settled coordinate the row sits at
    buf_start: jnp.ndarray  # int32[W]; >= SETTLED_BASE marks span rows
    length: jnp.ndarray  # int32[W]
    ins_seq: jnp.ndarray  # int32[W] (0 for span rows)
    ins_client: jnp.ndarray  # int32[W]
    rem_seq: jnp.ndarray  # int32[W] (NOT_REMOVED if live)
    rem_clients: jnp.ndarray  # int32[W, KR]
    props: jnp.ndarray  # int32[W, KK]
    settled_len: jnp.ndarray  # int32 scalar: S
    error: jnp.ndarray  # int32 scalar ERR_* flags


def make_overlay_table(
    window: int, n_removers: int = 4, n_prop_keys: int = 8,
    settled_len: int = 0,
) -> OverlayTable:
    return OverlayTable(
        n_rows=jnp.int32(0),
        anchor=jnp.zeros(window, jnp.int32),
        buf_start=jnp.zeros(window, jnp.int32),
        length=jnp.zeros(window, jnp.int32),
        ins_seq=jnp.zeros(window, jnp.int32),
        ins_client=jnp.full(window, NO_CLIENT, jnp.int32),
        rem_seq=jnp.full(window, NOT_REMOVED, jnp.int32),
        rem_clients=jnp.full((window, n_removers), NO_CLIENT, jnp.int32),
        props=jnp.full((window, n_prop_keys), PROP_ABSENT, jnp.int32),
        settled_len=jnp.int32(settled_len),
        error=jnp.int32(0),
    )


def _overlay_chunk_kernel(
    # scalars / op columns (SMEM)
    nrows_in_ref, err_in_ref, nops_ref, s_ref,
    op_type_ref, pos1_ref, pos2_ref, seq_ref, client_ref,
    buf_ref, ilen_ref, pkey_ref, pval_ref, ref_seq_ref,
    # table columns in (VMEM)
    t_anchor_in, t_buf_in, t_len_in, t_iseq_in, t_iclient_in, t_rseq_in,
    t_rcl_in, t_props_in,
    # table columns out (VMEM) + scalars out (SMEM)
    t_anchor, t_buf, t_len, t_iseq, t_iclient, t_rseq, t_rcl, t_props,
    nrows_out_ref, err_out_ref,
    # scratch (VMEM)
    t_live, t_err,
):
    KR = t_rcl_in.shape[0]
    KK = t_props_in.shape[0]
    B = pos1_ref.shape[0]
    PK = pkey_ref.shape[0] // B
    shape = t_len_in.shape
    window = shape[0] * LANES
    flat = _flat_idx(shape)
    last = flat == (window - 1)
    S = s_ref[0]

    t_anchor[...] = t_anchor_in[...]
    t_buf[...] = t_buf_in[...]
    t_len[...] = t_len_in[...]
    t_iseq[...] = t_iseq_in[...]
    t_iclient[...] = t_iclient_in[...]
    t_rseq[...] = t_rseq_in[...]
    t_rcl[...] = t_rcl_in[...]
    t_props[...] = t_props_in[...]
    t_live[...] = jnp.where(flat < nrows_in_ref[0], 1, 0)
    t_err[...] = jnp.where(flat == 0, err_in_ref[0], 0)

    def visibility(ref_seq, client):
        """(skip, vis_len) at a perspective — overlay_ref._visibility
        (mergeTree.ts:916 nodeLength) plus the dead-row mask."""
        live = t_live[...] > 0
        rseq = t_rseq[...]
        removed = rseq != NOT_REMOVED
        tomb = removed & (rseq <= ref_seq)
        ins_vis = (t_iclient[...] == client) | (t_iseq[...] <= ref_seq)
        among = t_rcl[0] == client
        for k in range(1, KR):
            among = among | (t_rcl[k] == client)
        skip = (~live) | tomb | (removed & ~ins_vis)
        visible = (~skip) & ins_vis & ~(removed & among)
        vis_len = jnp.where(visible, t_len[...], 0)
        return skip, vis_len

    def consume():
        """Settled coords a row occupies (span rows only; dead masked)."""
        live = t_live[...] > 0
        is_span = t_buf[...] >= SETTLED_BASE
        return jnp.where(live & is_span, t_len[...], 0)

    def pre_delta(vis_len):
        """Visible prefix before each row + the delta grand total (as a
        broadcast tile): overlay_ref._pre — one prefix sum over the
        WINDOW plays the partialLengths.ts:256 role for the whole
        settled document."""
        delta = vis_len - consume()
        pre = t_anchor[...] + _cumsum_excl(delta)
        dsum = _allreduce_sum(delta)
        return pre, dsum

    def shift_cols(keep):
        """Suffix shift opening one row at the first ~keep (vectorized
        memmove); flags ERR_CAPACITY if a live last row falls off."""
        t_err[...] = t_err[...] | jnp.where(
            last & (t_live[...] > 0) & ~keep, ERR_CAPACITY, 0
        )
        for ref in (t_anchor, t_buf, t_len, t_iseq, t_iclient, t_rseq,
                    t_live):
            v = ref[...]
            ref[...] = jnp.where(keep, v, _roll1_flat(v))
        for k in range(KR):
            v = t_rcl[k]
            t_rcl[k] = jnp.where(keep, v, _roll1_flat(v))
        for k in range(KK):
            v = t_props[k]
            t_props[k] = jnp.where(keep, v, _roll1_flat(v))

    def split_at(pos, orefseq, oclient):
        """Boundary split (overlay_ref._split / ensureIntervalBoundary,
        mergeTree.ts:1706): span tails advance their anchor with the
        offset; text tails keep theirs (both halves at one point)."""
        skip, vis = visibility(orefseq, oclient)
        delta = vis - consume()
        prefix = t_anchor[...] + _cumsum_excl(delta)
        inside = (
            (~skip) & (prefix < pos) & (prefix + vis > pos)
        ).astype(jnp.int32)
        after = _cumsum_excl(inside)
        keep = after == 0
        shift_cols(keep)
        at = (~keep) & (_roll1_flat(keep.astype(jnp.int32)) > 0)
        at = at & (flat > 0)
        off = pos - _roll1_flat(prefix)
        is_span_tail = t_buf[...] >= SETTLED_BASE
        t_anchor[...] = jnp.where(
            at & is_span_tail, t_anchor[...] + off, t_anchor[...]
        )
        t_buf[...] = jnp.where(at, t_buf[...] + off, t_buf[...])
        t_len[...] = jnp.where(at, t_len[...] - off, t_len[...])
        t_len[...] = jnp.where(inside > 0, pos - prefix, t_len[...])

    def body(i, _):
        otype = op_type_ref[i]
        pos1 = pos1_ref[i]
        pos2 = pos2_ref[i]
        oseq = seq_ref[i]
        orefseq = ref_seq_ref[i]
        oclient = client_ref[i]
        obuf = buf_ref[i]
        oilen = ilen_ref[i]

        is_ins = otype == OP_INSERT
        is_rem = otype == OP_REMOVE
        is_ann = otype == OP_ANNOTATE
        is_range = is_rem | is_ann

        @pl.when(is_ins | is_range)
        def _():
            split_at(pos1, orefseq, oclient)

        @pl.when(is_ins)
        def _():
            # Landing (overlay_ref._apply_insert / insertingWalk +
            # breakTie, mergeTree.ts:1740,:1719). pre > pos1 means
            # visible SETTLED text intervenes — land before that row
            # regardless of tie-breaks (the overlay-specific clause);
            # at pre == pos1 the row-model walk applies.
            skip, vis = visibility(orefseq, oclient)
            pre, dsum = pre_delta(vis)
            live_pre = t_live[...] > 0
            total = S + dsum
            land_real = live_pre & (
                (pre > pos1)
                | ((pre == pos1) & (~skip)
                   & ((vis > 0) | (oseq > t_iseq[...])))
            )
            land_all = land_real | ~live_pre
            landi = land_all.astype(jnp.int32)
            open_excl = _cumsum_excl(landi)
            ft = land_all & (open_excl == 0)  # one-hot landing row
            # New-row anchor, evaluated pre-shift at the landing index.
            A = jnp.where(
                land_real,
                t_anchor[...] - (pre - pos1),
                jnp.minimum(pos1 - dsum, S),
            )
            keep = (open_excl + landi) == 0
            shift_cols(keep)
            t_err[...] = t_err[...] | jnp.where(
                ft & ~live_pre & (total < pos1), ERR_BAD_POS, 0
            )
            t_anchor[...] = jnp.where(ft, A, t_anchor[...])
            t_buf[...] = jnp.where(ft, obuf, t_buf[...])
            t_len[...] = jnp.where(ft, oilen, t_len[...])
            t_iseq[...] = jnp.where(ft, oseq, t_iseq[...])
            t_iclient[...] = jnp.where(ft, oclient, t_iclient[...])
            t_rseq[...] = jnp.where(ft, NOT_REMOVED, t_rseq[...])
            t_live[...] = jnp.where(ft, 1, t_live[...])
            for k in range(KR):
                t_rcl[k] = jnp.where(ft, NO_CLIENT, t_rcl[k])
            for k in range(KK):
                newv = jnp.int32(PROP_ABSENT)
                for p in range(PK):
                    pkey = pkey_ref[p * B + i]
                    pval = pval_ref[p * B + i]
                    v = jnp.where(pval == PROP_DELETE, PROP_ABSENT, pval)
                    newv = jnp.where(pkey == k, v, newv)
                t_props[k] = jnp.where(ft, newv, t_props[k])

        @pl.when(is_range)
        def _():
            split_at(pos2, orefseq, oclient)
            skip, vis = visibility(orefseq, oclient)
            pre, dsum = pre_delta(vis)
            total = S + dsum
            t_err[...] = t_err[...] | jnp.where(
                total < pos2, ERR_BAD_POS, 0
            )

            def coord_of(pos):
                """Settled coordinate of visible position `pos`
                (overlay_ref._coord_of; rows containing `pos` were
                split). Broadcast tile, vector-domain only."""
                live = t_live[...] > 0
                cand = live & (pre >= pos)
                oh = cand & (_cumsum_excl(cand.astype(jnp.int32)) == 0)
                val = _allreduce_sum(
                    jnp.where(oh, t_anchor[...] - (pre - pos), 0)
                )
                has = _allreduce_sum(oh.astype(jnp.int32)) > 0
                return jnp.where(has, val, pos - dsum)

            c1 = coord_of(pos1)
            c2 = coord_of(pos2)

            def gaps():
                """Mask of storage gaps (gap k sits before row k) whose
                settled coords intersect [c1, c2) — the rows to
                materialize (overlay_ref "gap materialization")."""
                live = t_live[...] > 0
                end = t_anchor[...] + consume()
                glo = jnp.where(flat == 0, 0, _roll1_flat(end))
                ghi = jnp.where(live, t_anchor[...], S)
                prev_live = (flat == 0) | (_roll1_flat(t_live[...]) > 0)
                gapvalid = live | prev_live
                lo = jnp.maximum(glo, c1)
                hi = jnp.minimum(ghi, c2)
                return (gapvalid & (lo < hi), lo, hi)

            mat0, _, _ = gaps()
            # The one per-op vector->scalar crossing: how many span
            # rows this range op must materialize (usually 0-2; each
            # materialization removes exactly one gap, so the count is
            # stable across iterations).
            n_mat = jnp.sum(mat0.astype(jnp.int32))

            def gap_body(_, carry):
                mat, lo, hi = gaps()
                mi = mat.astype(jnp.int32)
                oh = mat & (_cumsum_excl(mi) == 0)
                ohi = oh.astype(jnp.int32)
                keep = (_cumsum_excl(ohi) + ohi) == 0
                shift_cols(keep)
                t_anchor[...] = jnp.where(oh, lo, t_anchor[...])
                t_buf[...] = jnp.where(oh, SETTLED_BASE + lo, t_buf[...])
                t_len[...] = jnp.where(oh, hi - lo, t_len[...])
                t_iseq[...] = jnp.where(oh, 0, t_iseq[...])
                t_iclient[...] = jnp.where(oh, NO_CLIENT, t_iclient[...])
                t_rseq[...] = jnp.where(oh, NOT_REMOVED, t_rseq[...])
                t_live[...] = jnp.where(oh, 1, t_live[...])
                for k in range(KR):
                    t_rcl[k] = jnp.where(oh, NO_CLIENT, t_rcl[k])
                for k in range(KK):
                    t_props[k] = jnp.where(oh, PROP_ABSENT, t_props[k])
                return carry

            lax.fori_loop(0, n_mat, gap_body, 0)

            # Covered-range updates (markRangeRemoved mergeTree.ts:1960
            # / annotateRange :1895), visibility recomputed after the
            # splits and materializations.
            skip, vis = visibility(orefseq, oclient)
            delta = vis - consume()
            prefix = t_anchor[...] + _cumsum_excl(delta)
            covered = (
                (~skip) & (vis > 0) & (prefix >= pos1)
                & (prefix + vis <= pos2)
            )

            @pl.when(is_rem)
            def _():
                already = t_rseq[...] != NOT_REMOVED
                t_rseq[...] = jnp.where(
                    covered & ~already, oseq, t_rseq[...]
                )
                first_free = jnp.full(shape, KR, jnp.int32)
                for k in range(KR - 1, -1, -1):
                    first_free = jnp.where(
                        t_rcl[k] == NO_CLIENT, k, first_free
                    )
                no_free = first_free == KR
                slot = jnp.where(already, first_free, 0)
                write = covered & ~(already & no_free)
                for k in range(KR):
                    t_rcl[k] = jnp.where(
                        write & (slot == k), oclient, t_rcl[k]
                    )
                t_err[...] = t_err[...] | jnp.where(
                    covered & already & no_free, ERR_REMOVERS, 0
                )

            @pl.when(is_ann)
            def _():
                # Last writer wins; a delete tombstones on span rows
                # (it must fold as a delete of the settled prop) but
                # clears on text rows (they are authoritative).
                is_span = t_buf[...] >= SETTLED_BASE
                for p in range(PK):
                    pkey = pkey_ref[p * B + i]
                    pval = pval_ref[p * B + i]
                    valid = pkey != NO_KEY
                    newv = jnp.where(
                        pval == PROP_DELETE,
                        jnp.where(is_span, PROP_DELETE, PROP_ABSENT),
                        jnp.broadcast_to(pval, shape),
                    )
                    for k in range(KK):
                        t_props[k] = jnp.where(
                            covered & valid & (pkey == k), newv,
                            t_props[k],
                        )

        return 0

    lax.fori_loop(0, nops_ref[0], body, 0)

    nrows_out_ref[0] = jnp.sum(t_live[...])
    err = t_err[...]
    s = 1
    while s < LANES:
        err = err | pltpu.roll(err, s, 1)
        s *= 2
    s = 1
    while s < err.shape[0]:
        err = err | pltpu.roll(err, s, 0)
        s *= 2
    err_out_ref[0] = jnp.max(err)


def _to_tiles(v):
    return v.reshape(-1, LANES)


@functools.partial(jax.jit, static_argnums=(2,))
def overlay_apply_chunk(table: OverlayTable, ops: OpBatch,
                        interpret: bool = False) -> OverlayTable:
    """Apply a chunk of sequenced ops (ascending seq order) to the
    overlay in ONE pallas kernel invocation. Bit-identical to
    `overlay_ref.OverlayDoc.apply` run op-by-op (differentially gated
    by tests/test_overlay_pallas.py)."""
    window = table.length.shape[0]
    KR = table.rem_clients.shape[1]
    KK = table.props.shape[1]
    B = ops.pos1.shape[0]
    PK = ops.prop_keys.shape[1]
    assert window % (8 * LANES) == 0, "window must be a multiple of 1024"

    tile_in = [
        _to_tiles(table.anchor), _to_tiles(table.buf_start),
        _to_tiles(table.length), _to_tiles(table.ins_seq),
        _to_tiles(table.ins_client), _to_tiles(table.rem_seq),
        jnp.moveaxis(table.rem_clients, 1, 0).reshape(KR, -1, LANES),
        jnp.moveaxis(table.props, 1, 0).reshape(KK, -1, LANES),
    ]
    op_in = [
        ops.op_type, ops.pos1, ops.pos2, ops.seq, ops.client,
        ops.buf_start, ops.ins_len,
        jnp.moveaxis(ops.prop_keys, 1, 0).reshape(PK * B),
        jnp.moveaxis(ops.prop_vals, 1, 0).reshape(PK * B),
        ops.ref_seq,
    ]

    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)
    W8 = window // LANES
    out_shapes = (
        jax.ShapeDtypeStruct((W8, LANES), jnp.int32),  # anchor
        jax.ShapeDtypeStruct((W8, LANES), jnp.int32),  # buf
        jax.ShapeDtypeStruct((W8, LANES), jnp.int32),  # len
        jax.ShapeDtypeStruct((W8, LANES), jnp.int32),  # ins_seq
        jax.ShapeDtypeStruct((W8, LANES), jnp.int32),  # ins_client
        jax.ShapeDtypeStruct((W8, LANES), jnp.int32),  # rem_seq
        jax.ShapeDtypeStruct((KR, W8, LANES), jnp.int32),
        jax.ShapeDtypeStruct((KK, W8, LANES), jnp.int32),
        jax.ShapeDtypeStruct((1,), jnp.int32),  # n_rows
        jax.ShapeDtypeStruct((1,), jnp.int32),  # error
    )
    outs = pl.pallas_call(
        _overlay_chunk_kernel,
        out_shape=out_shapes,
        in_specs=[smem()] * 14 + [vmem()] * 8,
        out_specs=tuple([vmem()] * 8 + [smem(), smem()]),
        scratch_shapes=[
            pltpu.VMEM((W8, LANES), jnp.int32),  # live column
            pltpu.VMEM((W8, LANES), jnp.int32),  # error accumulator
        ],
        interpret=interpret,
    )(
        jnp.reshape(table.n_rows, (1,)), jnp.reshape(table.error, (1,)),
        jnp.asarray([B], jnp.int32),
        jnp.reshape(table.settled_len, (1,)),
        *op_in, *tile_in,
    )
    (anchor, buf, length, iseq, iclient, rseq, rcl, props, nrows,
     err) = outs
    return OverlayTable(
        n_rows=nrows[0],
        anchor=anchor.reshape(-1),
        buf_start=buf.reshape(-1),
        length=length.reshape(-1),
        ins_seq=iseq.reshape(-1),
        ins_client=iclient.reshape(-1),
        rem_seq=rseq.reshape(-1),
        rem_clients=jnp.moveaxis(rcl.reshape(KR, -1), 0, 1),
        props=jnp.moveaxis(props.reshape(KK, -1), 0, 1),
        settled_len=table.settled_len,
        error=err[0],
    )


@jax.jit
def fold_device(table: OverlayTable, msn: jnp.ndarray):
    """Settle-merge under applied MSN `msn` (overlay_ref.fold; the
    zamboni role, zamboni.ts:19) as one XLA dispatch.

    Returns ``(table', records, n_rec)``: surviving rows re-anchored
    and packed to the front (stable payload sort — no gathers, see
    module docstring), plus the folded rows as a dense ``(W, 4+KK)``
    record block in storage (== coordinate) order: columns
    ``[anchor, code, buf, len, props...]`` with pre-fold anchors, for
    the host-side settled-state reconstruction.
    """
    W = table.length.shape[0]
    KR = table.rem_clients.shape[1]
    KK = table.props.shape[1]
    idx = jnp.arange(W, dtype=jnp.int32)
    live = idx < table.n_rows
    is_span = live & (table.buf_start >= SETTLED_BASE)
    removed = live & (table.rem_seq != NOT_REMOVED)
    drop = removed & (table.rem_seq <= msn)
    settle_text = live & ~removed & ~is_span & (table.ins_seq <= msn)
    settle_span = live & ~removed & is_span
    folding = drop | settle_text | settle_span

    exc = jnp.where(drop & is_span, table.length, 0)
    ins = jnp.where(settle_text, table.length, 0)
    exc_b = jnp.cumsum(exc) - exc
    ins_b = jnp.cumsum(ins) - ins
    new_anchor = (table.anchor - exc_b + ins_b).astype(jnp.int32)
    new_s = table.settled_len + jnp.sum(ins) - jnp.sum(exc)

    keep = live & ~folding
    n_new = jnp.sum(keep.astype(jnp.int32))
    new_buf = jnp.where(is_span, SETTLED_BASE + new_anchor,
                        table.buf_start)
    cols = (
        new_anchor, new_buf, table.length, table.ins_seq,
        table.ins_client, table.rem_seq,
        *(table.rem_clients[:, k] for k in range(KR)),
        *(table.props[:, k] for k in range(KK)),
    )
    packed = _pack_sort(jnp.where(keep, 0, 1).astype(jnp.int32), cols)
    valid = idx < n_new

    def fill(a, f):
        return jnp.where(valid, a, f)

    out = OverlayTable(
        n_rows=n_new,
        anchor=fill(packed[0], 0),
        buf_start=fill(packed[1], 0),
        length=fill(packed[2], 0),
        ins_seq=fill(packed[3], 0),
        ins_client=fill(packed[4], NO_CLIENT),
        rem_seq=fill(packed[5], NOT_REMOVED),
        rem_clients=jnp.where(
            valid[:, None], jnp.stack(packed[6:6 + KR], axis=1), NO_CLIENT
        ),
        props=jnp.where(
            valid[:, None], jnp.stack(packed[6 + KR:], axis=1), PROP_ABSENT
        ),
        settled_len=new_s.astype(jnp.int32),
        error=table.error,
    )

    code = jnp.where(
        settle_text, REC_SETTLE_TEXT,
        jnp.where(drop & is_span, REC_DROP_SPAN,
                  jnp.where(settle_span, REC_SETTLE_SPAN, 0)),
    ).astype(jnp.int32)
    recmask = code > 0  # dropped text rows reconstruct to nothing
    n_rec = jnp.sum(recmask.astype(jnp.int32))
    rcols = (
        table.anchor, code, table.buf_start, table.length,
        *(table.props[:, k] for k in range(KK)),
    )
    rpacked = _pack_sort(
        jnp.where(recmask, 0, 1).astype(jnp.int32), rcols
    )
    records = jnp.stack(rpacked, axis=1)  # (W, 4+KK)
    return out, records, n_rec


@functools.partial(
    jax.jit, static_argnums=(5, 6), donate_argnums=(0, 2, 3)
)
def replay_fused(
    table: OverlayTable, stream_ops: OpBatch, log, counts, msn_by_chunk,
    chunk: int, interpret: bool = False,
):
    """The WHOLE replay as one dispatch: `lax.fori_loop` over chunks,
    each iteration = pallas apply + XLA fold + log append, all
    device-resident (stream, msn schedule, log, table ride the loop
    carry; XLA keeps the donated log in place). One host->device
    dispatch replaces ~n/chunk of them — the host loop and its
    per-chunk scalar uploads are the dominant cost once the kernel is
    O(window), so fusing is worth ~10x wall-clock on a tunneled TPU.

    `msn_by_chunk[ci]` is the applied MSN at chunk ci's end (the fold
    perspective). Returns ``(table, log, counts, cursor)``."""
    n_chunks = msn_by_chunk.shape[0]

    def step(ci, carry):
        table, log, counts, cursor = carry
        table, log, counts, cursor = _chunk_step_body(
            table, stream_ops, ci * chunk, chunk, msn_by_chunk[ci],
            log, counts, cursor, ci, interpret,
        )
        return (table, log, counts, cursor)

    return lax.fori_loop(
        0, n_chunks, step, (table, log, counts, jnp.int32(0))
    )


def _chunk_step_body(
    table, stream_ops, lo, chunk, msn, log, counts, cursor, epoch,
    interpret,
):
    """One steady-state replay step, fully device-side: slice ops
    [lo, lo+chunk) from the device-resident stream, run the pallas
    chunk kernel, fold at the chunk boundary, and append the fold
    records to the HBM log (donated: XLA updates in place).

    Returns ``(table', log', counts', cursor')``; ``counts[epoch]``
    records this epoch's record count so the host can reconstruct the
    settled document epoch-by-epoch after the run."""
    sl = lambda a: lax.dynamic_slice_in_dim(a, lo, chunk, axis=0)
    batch = OpBatch(
        op_type=sl(stream_ops.op_type), pos1=sl(stream_ops.pos1),
        pos2=sl(stream_ops.pos2), seq=sl(stream_ops.seq),
        ref_seq=sl(stream_ops.ref_seq), client=sl(stream_ops.client),
        buf_start=sl(stream_ops.buf_start),
        ins_len=sl(stream_ops.ins_len),
        prop_keys=sl(stream_ops.prop_keys),
        prop_vals=sl(stream_ops.prop_vals),
    )
    table = overlay_apply_chunk(table, batch, interpret)
    table, records, n_rec = fold_device(table, msn)
    log = lax.dynamic_update_slice(
        log, records, (cursor, jnp.int32(0))
    )
    counts = counts.at[epoch].set(n_rec)
    return table, log, counts, cursor + n_rec


@functools.partial(
    jax.jit, static_argnums=(3, 9), donate_argnums=(0, 5, 6)
)
def replay_chunk_step(
    table: OverlayTable, stream_ops: OpBatch, lo, chunk: int,
    msn, log, counts, cursor, epoch, interpret: bool = False,
):
    """One replay step as its own dispatch (the incremental form:
    warm-up with `limit_chunks`, message-driven replicas, tests).
    `replay_fused` runs the same body for the whole stream in one
    dispatch."""
    return _chunk_step_body(
        table, stream_ops, lo, chunk, msn, log, counts, cursor, epoch,
        interpret,
    )
