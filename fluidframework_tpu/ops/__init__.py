"""Vectorized (JAX/XLA) kernels: the TPU execution backend.

Each kernel has a scalar oracle elsewhere in the package and is
differentially tested against it:

- mergetree_kernel: batched merge-tree op application
  (oracle: fluidframework_tpu.core.mergetree.MergeTreeEngine)
- sequencer_kernel: batched document sequencing / MSN
  (oracle: fluidframework_tpu.server.sequencer.DocumentSequencer)
"""
