"""Pallas TPU kernel for batched merge-tree op application.

Semantics are identical to `ops.mergetree_kernel._apply_one` (the
XLA-scan form of reference mergeTree.ts:1397/:1960/:1895 — see that
module's docstring for the semantic mapping); what changes is the
execution shape, twice over:

1. The scan form dispatches ~40 small XLA ops per sequenced op; on
   real hardware per-op cost is dominated by that dispatch chain
   (~175µs/op, nearly independent of table size — measured round 2).
   Here the WHOLE chunk runs inside ONE pallas kernel: the segment
   table lives in VMEM as (C/128, 128) int32 tiles for the entire
   batch and a `fori_loop` applies ops back-to-back.
2. Within the loop, the body is pure VECTOR-domain code: there are
   ZERO vector→scalar reductions per op (a VPU→SREG crossing costs
   ~µs in pipeline stalls; a first draft with ~40 reductions/op ran
   at 126µs/op). Scalar positions ("first row where...") are kept as
   one-hot masks; suffix shifts use cumulative-mask keeps; the row
   count is replaced by a `live` 0/1 column; error flags accumulate
   in a vector tile, OR-reduced once at kernel end.

Layout: every logical int32[C] table column is a (C//128, 128) tile
array; flattened row-major index == document order. 2D columns
(rem_clients[C, KR], props[C, KK]) are stored as KR/KK separate tile
arrays stacked on a leading static axis. Op columns ride in SMEM
(per-op dynamic scalar reads; the values are only ever used as vector
splats, which is the cheap crossing direction).

In-kernel primitives (rolls + masked selects, the VPU idiom):
- `_cumsum_excl`: exclusive prefix sum over flattened order via
  log-doubling along lanes then sublanes (the PartialSequenceLengths
  role, partialLengths.ts:256).
- `_allreduce_sum`: unmasked doubling — every element ends up holding
  the grand total (an "any/total" broadcast without leaving the VPU).
- `_roll1_flat`: flattened-order roll by one row.

The public wrapper `apply_chunk` matches `apply_op_batch`'s contract
(same SegmentTable/OpBatch pytrees) so the differential oracle gate
(tests/test_kernel_vs_oracle.py) runs against both kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..protocol.constants import INT32_MAX
from .mergetree_kernel import (
    ERR_BAD_POS,
    ERR_CAPACITY,
    ERR_REMOVERS,
    NO_CLIENT,
    NO_KEY,
    NOT_REMOVED,
    OP_ANNOTATE,
    OP_INSERT,
    OP_REMOVE,
    OpBatch,
    PROP_ABSENT,
    PROP_DELETE,
    SegmentTable,
)

LANES = 128


def _lane_idx(shape):
    return jax.lax.broadcasted_iota(jnp.int32, shape, 1)


def _row_idx(shape):
    return jax.lax.broadcasted_iota(jnp.int32, shape, 0)


def _flat_idx(shape):
    return _row_idx(shape) * LANES + _lane_idx(shape)


def _cumsum_excl(v):
    """Exclusive prefix sum over flattened (row-major) order."""
    shape = v.shape
    lane = _lane_idx(shape)
    row = _row_idx(shape)
    s = 1
    acc = v
    while s < LANES:  # inclusive along lanes (wrap masked off)
        acc = acc + jnp.where(lane >= s, pltpu.roll(acc, s, 1), 0)
        s *= 2
    totals = jnp.broadcast_to(acc[:, LANES - 1 :], shape)
    s = 1
    rt = totals
    while s < shape[0]:  # inclusive row-total cascade
        rt = rt + jnp.where(row >= s, pltpu.roll(rt, s, 0), 0)
        s *= 2
    row_excl = jnp.where(row > 0, pltpu.roll(rt, 1, 0), 0)
    return acc - v + row_excl


def _allreduce_sum(v):
    """Every element := sum of all elements (stays in vector domain)."""
    s = 1
    acc = v
    while s < LANES:
        acc = acc + pltpu.roll(acc, s, 1)
        s *= 2
    s = 1
    while s < v.shape[0]:
        acc = acc + pltpu.roll(acc, s, 0)
        s *= 2
    return acc


def _roll1_flat(v):
    """w[i] = v[i-1] in flattened order (w[0] = v[C-1], masked off by
    callers)."""
    w = pltpu.roll(v, 1, 1)
    carry = pltpu.roll(w, 1, 0)
    return jnp.where(_lane_idx(v.shape) == 0, carry, w)


def _mergetree_chunk_kernel(
    parts,  # static profiling/bisection knob; sections: 'splits' =
    #   the pos1 boundary split, 'insert' = the merged pos2-split +
    #   landing pass, 'covered' = range updates. Partial tuples are
    #   for TIMING only — they do not produce semantically complete
    #   states (e.g. 'covered' without 'insert' skips the pos2 split).
    # scalars / op columns (SMEM)
    nrows_in_ref, err_in_ref, nops_ref,
    op_type_ref, pos1_ref, pos2_ref, seq_ref, client_ref,
    buf_ref, ilen_ref, pkey_ref, pval_ref, ref_seq_ref,
    # table columns in (VMEM)
    t_buf_in, t_len_in, t_iseq_in, t_iclient_in, t_rseq_in,
    t_rcl_in, t_props_in,
    # table columns out (VMEM) + scalars out (SMEM)
    t_buf, t_len, t_iseq, t_iclient, t_rseq, t_rcl, t_props,
    nrows_out_ref, err_out_ref,
    # scratch (VMEM)
    t_live, t_err,
):
    KR = t_rcl_in.shape[0]
    KK = t_props_in.shape[0]
    B = pos1_ref.shape[0]
    PK = pkey_ref.shape[0] // B
    shape = t_len_in.shape
    capacity = shape[0] * LANES
    flat = _flat_idx(shape)
    last = flat == (capacity - 1)

    t_buf[...] = t_buf_in[...]
    t_len[...] = t_len_in[...]
    t_iseq[...] = t_iseq_in[...]
    t_iclient[...] = t_iclient_in[...]
    t_rseq[...] = t_rseq_in[...]
    t_rcl[...] = t_rcl_in[...]
    t_props[...] = t_props_in[...]
    t_live[...] = jnp.where(flat < nrows_in_ref[0], 1, 0)
    t_err[...] = jnp.where(flat == 0, err_in_ref[0], 0)

    def visibility(ref_seq, client):
        """(skip, vis_len) at a perspective — mergeTree.ts:916
        nodeLength (same predicate as mergetree_kernel._visibility)."""
        live = t_live[...] > 0
        rseq = t_rseq[...]
        removed = rseq != NOT_REMOVED
        tomb = removed & (rseq <= ref_seq)
        ins_vis = (t_iclient[...] == client) | (t_iseq[...] <= ref_seq)
        among = t_rcl[0] == client
        for k in range(1, KR):
            among = among | (t_rcl[k] == client)
        skip = (~live) | tomb | (removed & ~ins_vis)
        visible = (~skip) & ins_vis & ~(removed & among)
        vis_len = jnp.where(visible, t_len[...], 0)
        return skip, vis_len

    def shift_cols(keep):
        """Suffix shift: col[i] = col[i] if keep[i] else col[i-1]
        (vectorized memmove opening one row at the first ~keep).
        Flags ERR_CAPACITY if a live last row falls off the end."""
        t_err[...] = t_err[...] | jnp.where(
            last & (t_live[...] > 0) & ~keep, ERR_CAPACITY, 0
        )
        for ref in (t_buf, t_len, t_iseq, t_iclient, t_rseq, t_live):
            v = ref[...]
            ref[...] = jnp.where(keep, v, _roll1_flat(v))
        for k in range(KR):
            v = t_rcl[k]
            t_rcl[k] = jnp.where(keep, v, _roll1_flat(v))
        for k in range(KK):
            v = t_props[k]
            t_props[k] = jnp.where(keep, v, _roll1_flat(v))

    def split_at(pos, enable, orefseq, oclient):
        """Masked boundary split (ensureIntervalBoundary,
        mergeTree.ts:1706), vector-only: `inside` is a one-hot mask of
        the row strictly containing visible position `pos`; the tail
        inherits every field through the shift itself, then gets its
        span offset fixed up."""
        skip, vis_len = visibility(orefseq, oclient)
        prefix = _cumsum_excl(vis_len)
        inside = (
            (~skip) & (prefix < pos) & (prefix + vis_len > pos) & enable
        ).astype(jnp.int32)
        after = _cumsum_excl(inside)  # 1 for i > j_split
        keep = after == 0
        shift_cols(keep)
        split_fixup(keep, prefix, pos, inside)

    def split_fixup(keep, prefix, pos, inside, gate=None):
        """Post-shift boundary-split repairs: the tail row (first
        ~keep; inherits every field through the shift) gets its span
        offset advanced, and the head row truncates to the split
        offset. `gate` optionally restricts the tail mask (the merged
        pass gates on is_range)."""
        at = (~keep) & (_roll1_flat(keep.astype(jnp.int32)) > 0)
        at = at & (flat > 0)  # keep[0] is always True; guard the wrap
        if gate is not None:
            at = at & jnp.broadcast_to(gate, shape)
        off = pos - _roll1_flat(prefix)  # at tail pos: pos - prefix[j]
        t_buf[...] = jnp.where(at, t_buf[...] + off, t_buf[...])
        t_len[...] = jnp.where(at, t_len[...] - off, t_len[...])
        # Head truncation (head row index is unchanged by the shift).
        t_len[...] = jnp.where(inside > 0, pos - prefix, t_len[...])

    def body(i, _):
        otype = op_type_ref[i]
        pos1 = pos1_ref[i]
        pos2 = pos2_ref[i]
        oseq = seq_ref[i]
        orefseq = ref_seq_ref[i]
        oclient = client_ref[i]
        obuf = buf_ref[i]
        oilen = ilen_ref[i]

        is_ins = otype == OP_INSERT
        is_rem = otype == OP_REMOVE
        is_ann = otype == OP_ANNOTATE
        is_range = is_rem | is_ann

        if 'splits' in parts:
            split_at(pos1, is_ins | is_range, orefseq, oclient)

        # ---- merged structural pass: the pos2 boundary split (range
        # ops) and the insert landing shift (insert ops) are mutually
        # exclusive by op type, so ONE suffix shift serves both —
        # saving a full 19-column shift per op vs doing them serially.
        if 'insert' not in parts:
            return 0
        skip, vis_len = visibility(orefseq, oclient)
        prefix = _cumsum_excl(vis_len)
        total = _allreduce_sum(vis_len)
        live_pre = t_live[...] > 0
        # (a) pos2 split row (ensureIntervalBoundary for the range end).
        inside2 = (
            (~skip) & (prefix < pos2) & (prefix + vis_len > pos2) & is_range
        ).astype(jnp.int32)
        # (b) insert landing (insertingWalk + breakTie,
        # mergeTree.ts:1740,:1719): first row at/after pos1 that is
        # visible content or loses the tie-break; first non-live row is
        # the virtual end boundary.
        land = (
            (~skip) & (prefix >= pos1)
            & ((vis_len > 0) | (oseq > t_iseq[...]))
        ) | ~live_pre
        land = land & is_ins
        landi = land.astype(jnp.int32)
        open_excl = _cumsum_excl(inside2 + landi)
        ft = land & (open_excl == 0)  # one-hot landing row
        # keep[i]: split2 keeps i <= j2 (tail opens AFTER the inside
        # row); insert keeps i < landing (new row opens AT it).
        keep = (open_excl + landi) == 0
        shift_cols(keep)
        # Split-tail fixes (only when a range op split at pos2).
        split_fixup(keep, prefix, pos2, inside2, gate=is_range)
        # pos beyond visible length and no real landing row: flagged
        # exactly like the scan kernel (ERR_BAD_POS).
        t_err[...] = t_err[...] | jnp.where(
            ft & ~live_pre & (total < pos1), ERR_BAD_POS, 0
        )
        t_buf[...] = jnp.where(ft, obuf, t_buf[...])
        t_len[...] = jnp.where(ft, oilen, t_len[...])
        t_iseq[...] = jnp.where(ft, oseq, t_iseq[...])
        t_iclient[...] = jnp.where(ft, oclient, t_iclient[...])
        t_rseq[...] = jnp.where(ft, NOT_REMOVED, t_rseq[...])
        t_live[...] = jnp.where(ft, 1, t_live[...])
        for k in range(KR):
            t_rcl[k] = jnp.where(ft, NO_CLIENT, t_rcl[k])
        for k in range(KK):
            newv = jnp.int32(PROP_ABSENT)
            for p in range(PK):
                pkey = pkey_ref[p * B + i]
                pval = pval_ref[p * B + i]
                v = jnp.where(pval == PROP_DELETE, PROP_ABSENT, pval)
                newv = jnp.where(pkey == k, v, newv)
            t_props[k] = jnp.where(ft, newv, t_props[k])

        if 'covered' not in parts:
            return 0
        # ---- covered-range updates (markRangeRemoved mergeTree.ts:1960
        # / annotateRange :1895), visibility recomputed post-shift.
        skip, vis_len = visibility(orefseq, oclient)
        prefix = _cumsum_excl(vis_len)
        covered = (
            (~skip) & (vis_len > 0) & (prefix >= pos1)
            & (prefix + vis_len <= pos2)
        )
        t_err[...] = t_err[...] | jnp.where(
            is_range & (_allreduce_sum(vis_len) < pos2), ERR_BAD_POS, 0
        )

        # Remove: earliest sequenced rem_seq wins; removing client
        # appended at the first free slot.
        upd_rem = covered & is_rem
        already = t_rseq[...] != NOT_REMOVED
        t_rseq[...] = jnp.where(upd_rem & ~already, oseq, t_rseq[...])
        first_free = jnp.full(shape, KR, jnp.int32)
        for k in range(KR - 1, -1, -1):
            first_free = jnp.where(t_rcl[k] == NO_CLIENT, k, first_free)
        no_free = first_free == KR
        slot = jnp.where(already, first_free, 0)
        write = upd_rem & ~(already & no_free)
        for k in range(KR):
            t_rcl[k] = jnp.where(write & (slot == k), oclient, t_rcl[k])
        t_err[...] = t_err[...] | jnp.where(
            upd_rem & already & no_free, ERR_REMOVERS, 0
        )

        # Annotate: last writer wins, PROP_DELETE clears.
        upd_ann = covered & is_ann
        for p in range(PK):
            pkey = pkey_ref[p * B + i]
            pval = pval_ref[p * B + i]
            valid = pkey != NO_KEY
            newv = jnp.where(pval == PROP_DELETE, PROP_ABSENT, pval)
            for k in range(KK):
                t_props[k] = jnp.where(
                    upd_ann & valid & (pkey == k), newv, t_props[k]
                )
        return 0

    jax.lax.fori_loop(0, nops_ref[0], body, 0)

    # Single vector→scalar crossing per kernel: n_rows and the OR of
    # the error tile (per-bit max == bitwise OR for flag words).
    nrows_out_ref[0] = jnp.sum(t_live[...])
    err = t_err[...]
    s = 1
    while s < LANES:
        err = err | pltpu.roll(err, s, 1)
        s *= 2
    s = 1
    while s < err.shape[0]:
        err = err | pltpu.roll(err, s, 0)
        s *= 2
    err_out_ref[0] = jnp.max(err)


def _to_tiles(v):
    """int32[C] -> int32[C//128, 128] (row-major == doc order)."""
    return v.reshape(-1, LANES)


@functools.partial(jax.jit, static_argnums=(3, 4), donate_argnums=(0,))
def apply_chunk_at(table: SegmentTable, stream_ops: OpBatch, lo,
                   chunk: int, interpret: bool = False) -> SegmentTable:
    """Apply ops [lo, lo+chunk) of a device-resident op stream.

    The whole (NOOP-padded) stream is uploaded to the device ONCE;
    each chunk is a dynamic slice taken on device, so the steady-state
    replay loop performs zero host→device transfers (each transfer
    pays a full round trip on a tunneled TPU — uploading per chunk
    measured ~100x slower than the kernel itself)."""
    sl = lambda a: jax.lax.dynamic_slice_in_dim(a, lo, chunk, axis=0)
    batch = OpBatch(
        op_type=sl(stream_ops.op_type), pos1=sl(stream_ops.pos1),
        pos2=sl(stream_ops.pos2), seq=sl(stream_ops.seq),
        ref_seq=sl(stream_ops.ref_seq), client=sl(stream_ops.client),
        buf_start=sl(stream_ops.buf_start), ins_len=sl(stream_ops.ins_len),
        prop_keys=sl(stream_ops.prop_keys), prop_vals=sl(stream_ops.prop_vals),
    )
    return apply_chunk(table, batch, interpret)


@functools.partial(jax.jit, static_argnums=(2, 3))
def apply_chunk(table: SegmentTable, ops: OpBatch, interpret: bool = False,
                parts: tuple = ('splits', 'insert', 'covered')
                ) -> SegmentTable:
    """Apply a chunk of sequenced ops (ascending seq order) in ONE
    pallas kernel invocation. Drop-in equivalent of
    `mergetree_kernel.apply_op_batch` (bit-identical results; gated by
    the same differential tests)."""
    capacity = table.length.shape[0]
    KR = table.rem_clients.shape[1]
    KK = table.props.shape[1]
    B = ops.pos1.shape[0]
    PK = ops.prop_keys.shape[1]
    assert capacity % (8 * LANES) == 0, "capacity must be a multiple of 1024"

    n_ops = jnp.asarray([B], jnp.int32)

    tile_in = [
        _to_tiles(table.buf_start), _to_tiles(table.length),
        _to_tiles(table.ins_seq), _to_tiles(table.ins_client),
        _to_tiles(table.rem_seq),
        # [C, K] -> [K, C//128, 128]
        jnp.moveaxis(table.rem_clients, 1, 0).reshape(KR, -1, LANES),
        jnp.moveaxis(table.props, 1, 0).reshape(KK, -1, LANES),
    ]
    # Op columns ride in SMEM as flat [B] arrays: per-op dynamic
    # scalar reads, used only as vector splats.
    op_in = [
        ops.op_type, ops.pos1, ops.pos2, ops.seq, ops.client,
        ops.buf_start, ops.ins_len,
        # [B, PK] -> [PK * B] (key p of op i at p * B + i)
        jnp.moveaxis(ops.prop_keys, 1, 0).reshape(PK * B),
        jnp.moveaxis(ops.prop_vals, 1, 0).reshape(PK * B),
        ops.ref_seq,
    ]

    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)
    C8 = capacity // LANES
    out_shapes = (
        jax.ShapeDtypeStruct((C8, LANES), jnp.int32),  # buf
        jax.ShapeDtypeStruct((C8, LANES), jnp.int32),  # len
        jax.ShapeDtypeStruct((C8, LANES), jnp.int32),  # ins_seq
        jax.ShapeDtypeStruct((C8, LANES), jnp.int32),  # ins_client
        jax.ShapeDtypeStruct((C8, LANES), jnp.int32),  # rem_seq
        jax.ShapeDtypeStruct((KR, C8, LANES), jnp.int32),
        jax.ShapeDtypeStruct((KK, C8, LANES), jnp.int32),
        jax.ShapeDtypeStruct((1,), jnp.int32),  # n_rows
        jax.ShapeDtypeStruct((1,), jnp.int32),  # error
    )
    outs = pl.pallas_call(
        functools.partial(_mergetree_chunk_kernel, parts),
        out_shape=out_shapes,
        in_specs=[smem()] * 13 + [vmem()] * 7,
        out_specs=tuple([vmem()] * 7 + [smem(), smem()]),
        scratch_shapes=[
            pltpu.VMEM((C8, LANES), jnp.int32),  # live column
            pltpu.VMEM((C8, LANES), jnp.int32),  # error accumulator
        ],
        interpret=interpret,
    )(
        jnp.reshape(table.n_rows, (1,)), jnp.reshape(table.error, (1,)),
        n_ops, *op_in, *tile_in,
    )
    (buf, length, iseq, iclient, rseq, rcl, props, nrows, err) = outs
    return SegmentTable(
        n_rows=nrows[0],
        buf_start=buf.reshape(-1),
        length=length.reshape(-1),
        ins_seq=iseq.reshape(-1),
        ins_client=iclient.reshape(-1),
        rem_seq=rseq.reshape(-1),
        rem_clients=jnp.moveaxis(rcl.reshape(KR, -1), 0, 1),
        props=jnp.moveaxis(props.reshape(KK, -1), 0, 1),
        error=err[0],
    )
