"""Vectorized merge-tree kernel: batched sequenced-op application.

The TPU-native re-expression of the reference merge-tree hot path
(packages/dds/merge-tree/src/mergeTree.ts:1397 insertSegments, :1960
markRangeRemoved, :1895 annotateRange, position resolution via
partialLengths.ts) as a structure-of-arrays segment table plus
`lax.scan` over a totally ordered op batch.

Design (SURVEY.md §7):

- The segment list is held *physically in document order* in fixed-
  capacity int32 arrays (`SegmentTable`); rows `[0, n_rows)` are live.
  There are no pointers: the reference's B-tree exists only to make
  per-op position resolution O(log n) on a scalar CPU. On TPU we
  resolve positions with an O(n) vector prefix-sum per op — the whole
  table is touched with full lanes, which is the shape XLA/VPU wants.
- Visibility of a segment at a perspective (refSeq, clientId) is the
  closed-form predicate of reference mergeTree.ts:916 `nodeLength`,
  computed as a mask over all rows at once.
- Inserts/splits shift the suffix of the table by 1-2 rows via a
  single gather (`rows[src]`), i.e. a vectorized memmove.
- Characters are never seen by the kernel: segments carry
  `(buf_start, length)` spans into a host-side text arena, so the
  kernel is pure int32 table manipulation. Property annotations are
  dictionary-encoded host-side (key→column, value→int id).

Semantics notes / scope:

- This kernel implements the *sequenced replay* path: every op it sees
  has an assigned sequence number and ops arrive in ascending seq
  order (the totally ordered stream every replica converges on —
  SURVEY.md §3.3). Local pending ops (UNASSIGNED_SEQ) and the
  ack/rebase paths stay host-side in core/mergetree.py, mirroring the
  reference's split between the hot remote-apply loop and the rare
  reconnect machinery (client.ts:917).
- Insert tie-breaks (mergeTree.ts:1719 breakTie) reduce to
  `op_seq > row_ins_seq` because all rows are sequenced; equal seqs
  occur for flattened group ops and break toward "walk past", exactly
  as the reference's strict `>`.

Differential gate: tests/test_kernel_vs_oracle.py replays seeded farm
streams through this kernel and the scalar oracle and asserts
bit-identical text + annotations.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..protocol.constants import INT32_MAX, NO_CLIENT

# Sentinels (int32 table encoding).
NOT_REMOVED = INT32_MAX  # rem_seq value for live segments
PROP_ABSENT = -1  # props cell: key not set on this segment
PROP_DELETE = -2  # op value: delete the key (reference: null prop value)
NO_KEY = -1  # op key slot unused

# Op type codes (match protocol.mergetree_ops.MergeTreeDeltaType).
OP_INSERT = 0
OP_REMOVE = 1
OP_ANNOTATE = 2
OP_NOOP = 3

# Error bit flags accumulated in SegmentTable.error.
ERR_CAPACITY = 1  # segment table overflow
ERR_BAD_POS = 2  # op position beyond visible length
ERR_REMOVERS = 4  # more concurrent removers than KR slots


class SegmentTable(NamedTuple):
    """SoA segment table for one document replica (rows in doc order)."""

    n_rows: jnp.ndarray  # int32 scalar
    buf_start: jnp.ndarray  # int32[S] offset into the host text arena
    length: jnp.ndarray  # int32[S]
    ins_seq: jnp.ndarray  # int32[S] (UNIVERSAL_SEQ=0 for loaded content)
    ins_client: jnp.ndarray  # int32[S]
    rem_seq: jnp.ndarray  # int32[S] (NOT_REMOVED if live)
    rem_clients: jnp.ndarray  # int32[S, KR] (NO_CLIENT padding)
    props: jnp.ndarray  # int32[S, KK] (PROP_ABSENT default)
    error: jnp.ndarray  # int32 scalar, ERR_* bit flags


class OpBatch(NamedTuple):
    """A chunk of sequenced ops in ascending sequence-number order."""

    op_type: jnp.ndarray  # int32[B]
    pos1: jnp.ndarray  # int32[B] insert pos / range start
    pos2: jnp.ndarray  # int32[B] range end (exclusive)
    seq: jnp.ndarray  # int32[B]
    ref_seq: jnp.ndarray  # int32[B]
    client: jnp.ndarray  # int32[B]
    buf_start: jnp.ndarray  # int32[B] arena offset of inserted text
    ins_len: jnp.ndarray  # int32[B]
    prop_keys: jnp.ndarray  # int32[B, PK] (NO_KEY padding)
    prop_vals: jnp.ndarray  # int32[B, PK]


def grow_table(table: SegmentTable, old_cap: int, new_cap: int) -> SegmentTable:
    """Pad a table to a larger static capacity (realloc outside jit)."""
    pad = new_cap - old_cap

    def pad1(a, fill):
        return jnp.concatenate([a, jnp.full((pad,) + a.shape[1:], fill, a.dtype)])

    return table._replace(
        buf_start=pad1(table.buf_start, 0),
        length=pad1(table.length, 0),
        ins_seq=pad1(table.ins_seq, 0),
        ins_client=pad1(table.ins_client, NO_CLIENT),
        rem_seq=pad1(table.rem_seq, NOT_REMOVED),
        rem_clients=pad1(table.rem_clients, NO_CLIENT),
        props=pad1(table.props, PROP_ABSENT),
    )


def raise_kernel_errors(error: int) -> None:
    """Raise if any ERR_* bit is set in an error-flag word."""
    problems = []
    if error & ERR_CAPACITY:
        problems.append("segment table capacity overflow")
    if error & ERR_BAD_POS:
        problems.append("op position beyond visible length")
    if error & ERR_REMOVERS:
        problems.append("removing-client slots exhausted")
    if problems:
        raise RuntimeError("kernel error: " + "; ".join(problems))


def make_table(capacity: int, n_removers: int, n_prop_keys: int) -> SegmentTable:
    """An empty table with static shapes (S, KR, KK)."""
    return SegmentTable(
        n_rows=jnp.int32(0),
        buf_start=jnp.zeros(capacity, jnp.int32),
        length=jnp.zeros(capacity, jnp.int32),
        ins_seq=jnp.zeros(capacity, jnp.int32),
        ins_client=jnp.full(capacity, NO_CLIENT, jnp.int32),
        rem_seq=jnp.full(capacity, NOT_REMOVED, jnp.int32),
        rem_clients=jnp.full((capacity, n_removers), NO_CLIENT, jnp.int32),
        props=jnp.full((capacity, n_prop_keys), PROP_ABSENT, jnp.int32),
        error=jnp.int32(0),
    )


# --------------------------------------------------------------------------
# Visibility (reference mergeTree.ts:916 nodeLength, remote perspective)
# --------------------------------------------------------------------------


def _visibility(table: SegmentTable, ref_seq, client):
    """Per-row (skip, vis_len) at perspective (ref_seq, client).

    skip: excluded from walks and tie-breaks entirely (tombstone at the
    perspective, or insert+remove both unseen → the segment will never
    exist for this client).
    vis_len: visible length (0 for zero-visibility rows that still
    participate in insert tie-breaks).
    """
    capacity = table.length.shape[0]
    live = jnp.arange(capacity, dtype=jnp.int32) < table.n_rows
    removed = table.rem_seq != NOT_REMOVED
    tomb = removed & (table.rem_seq <= ref_seq)
    ins_vis = (table.ins_client == client) | (table.ins_seq <= ref_seq)
    among_removers = jnp.any(table.rem_clients == client, axis=1)
    skip = (~live) | tomb | (removed & ~ins_vis)
    visible = (~skip) & ins_vis & ~(removed & among_removers)
    vis_len = jnp.where(visible, table.length, 0)
    return skip, vis_len


def _prefix(vis_len):
    """Exclusive prefix sum of visible lengths (the role of the
    reference's PartialSequenceLengths cache, partialLengths.ts:256 —
    recomputed as a scan instead of maintained incrementally)."""
    return jnp.cumsum(vis_len) - vis_len


# --------------------------------------------------------------------------
# Table edits (gather-based row shifts)
# --------------------------------------------------------------------------


def _shift_rows(table: SegmentTable, at: jnp.ndarray, shift: jnp.ndarray) -> SegmentTable:
    """Open `shift` ∈ {0, 1} empty rows at index `at` by shifting the
    suffix rightward (vectorized memmove); `at >= capacity` or
    `shift == 0` is an identity. Row `at` keeps a stale value — the
    caller overwrites it.

    Implemented as a static roll + elementwise select rather than a
    dynamic gather: general gathers lower to scalar-core loops on TPU,
    while roll is a concat of static slices and the select is pure VPU
    work. There is deliberately NO control flow here (or anywhere in
    the op-apply path): a masked no-op pass is far cheaper on TPU than
    per-op `lax.cond` dispatch inside the scan."""
    capacity = table.length.shape[0]
    j = jnp.arange(capacity, dtype=jnp.int32)
    keep = (j < at) | (shift == 0)

    def g(a):
        moved = jnp.roll(a, 1, axis=0)
        if a.ndim == 1:
            return jnp.where(keep, a, moved)
        return jnp.where(keep[:, None], a, moved)

    return table._replace(
        buf_start=g(table.buf_start),
        length=g(table.length),
        ins_seq=g(table.ins_seq),
        ins_client=g(table.ins_client),
        rem_seq=g(table.rem_seq),
        rem_clients=g(table.rem_clients),
        props=g(table.props),
        n_rows=table.n_rows + shift,
        error=table.error
        | jnp.where(table.n_rows + shift > capacity, ERR_CAPACITY, 0).astype(jnp.int32),
    )


def _write_row(table: SegmentTable, at, buf_start, length, ins_seq, ins_client,
               rem_seq, rem_clients_row, props_row) -> SegmentTable:
    """Overwrite row `at` with the given field values."""
    capacity = table.length.shape[0]
    here = jnp.arange(capacity, dtype=jnp.int32) == at

    def w(a, v):
        if a.ndim == 1:
            return jnp.where(here, v, a)
        return jnp.where(here[:, None], v[None, :], a)

    return table._replace(
        buf_start=w(table.buf_start, buf_start),
        length=w(table.length, length),
        ins_seq=w(table.ins_seq, ins_seq),
        ins_client=w(table.ins_client, ins_client),
        rem_seq=w(table.rem_seq, rem_seq),
        rem_clients=w(table.rem_clients, rem_clients_row),
        props=w(table.props, props_row),
    )


def _op_props_row(op: OpBatch, n_prop_keys: int):
    """Dictionary-encoded props carried by an op, as a props row
    (PROP_DELETE values become 'absent' for newly inserted segments)."""
    row = jnp.full(n_prop_keys, PROP_ABSENT, jnp.int32)
    vals = jnp.where(op.prop_vals == PROP_DELETE, PROP_ABSENT, op.prop_vals)
    keys = jnp.where(op.prop_keys == NO_KEY, n_prop_keys, op.prop_keys)  # drop
    return row.at[keys].set(vals, mode="drop")


def _split_at(table: SegmentTable, pos, ref_seq, client, enable) -> SegmentTable:
    """Masked ensure-boundary (reference ensureIntervalBoundary,
    mergeTree.ts:1706): if `enable` and visible position `pos` falls
    strictly inside a row, split that row. Straight-line masked code —
    when no split is needed every write is a no-op pass."""
    capacity = table.length.shape[0]
    skip, vis_len = _visibility(table, ref_seq, client)
    prefix = _prefix(vis_len)
    inside = (~skip) & (prefix < pos) & (prefix + vis_len > pos)
    found = jnp.any(inside) & enable
    idx = jnp.argmax(inside).astype(jnp.int32)  # garbage unless found
    off = pos - prefix[idx]
    at = jnp.where(found, idx + 1, jnp.int32(capacity))

    # Snapshot the split row's fields before shifting.
    head = (table.buf_start[idx], table.length[idx], table.ins_seq[idx],
            table.ins_client[idx], table.rem_seq[idx], table.rem_clients[idx],
            table.props[idx])

    t = _shift_rows(table, at, jnp.where(found, 1, 0).astype(jnp.int32))
    # Tail inherits all merge metadata (reference BaseSegment.splitAt);
    # `at >= capacity` makes this a no-op.
    t = _write_row(t, at, head[0] + off, head[1] - off, head[2], head[3],
                   head[4], head[5], head[6])
    # Truncate the head row (drop-mode scatter is a no-op when masked).
    head_at = jnp.where(found, idx, jnp.int32(capacity))
    return t._replace(length=t.length.at[head_at].set(off, mode="drop"))


# --------------------------------------------------------------------------
# Op application — one fully unconditional (masked) step
# --------------------------------------------------------------------------


def _apply_one(table: SegmentTable, op: OpBatch) -> SegmentTable:
    """Apply one sequenced op of any type as straight-line masked code.

    The reference dispatches per op type (client.ts:802 applyRemoteOp →
    insert/remove/annotate walks). On TPU, per-op control flow
    (`lax.cond`/`lax.switch` inside the scan) costs more than the work
    it saves, so every step runs the same fixed passes with masks:

      1. boundary split at pos1 (insert, remove, annotate)
      2. boundary split at pos2 (remove, annotate)
      3. shift+write of the new segment row (insert; reference
         insertingWalk + breakTie, mergeTree.ts:1740,:1719 — after the
         pos1 split the landing site is always a row boundary)
      4. masked field updates over the covered range (remove: rem_seq /
         rem_clients per markRangeRemoved mergeTree.ts:1960; annotate:
         dictionary-encoded props per annotateRange mergeTree.ts:1895)
    """
    capacity = table.length.shape[0]
    n_prop_keys = table.props.shape[1]
    is_ins = op.op_type == OP_INSERT
    is_rem = op.op_type == OP_REMOVE
    is_ann = op.op_type == OP_ANNOTATE
    is_range = is_rem | is_ann

    # 1-2. Boundary splits.
    t = _split_at(table, op.pos1, op.ref_seq, op.client, is_ins | is_range)
    t = _split_at(t, op.pos2, op.ref_seq, op.client, is_range)

    # 3. Insert landing + shift + write.
    skip, vis_len = _visibility(t, op.ref_seq, op.client)
    prefix = _prefix(vis_len)
    total = jnp.sum(vis_len)
    # First non-skip row at/after pos1 that is either visible content or
    # a zero-visibility row losing the tie-break to this op (strict >,
    # reference breakTie mergeTree.ts:1719).
    land = (~skip) & (prefix >= op.pos1) & ((vis_len > 0) | (op.seq > t.ins_seq))
    land_found = jnp.any(land)
    insert_at = jnp.where(land_found, jnp.argmax(land).astype(jnp.int32), t.n_rows)
    at = jnp.where(is_ins, insert_at, jnp.int32(capacity))
    t = _shift_rows(t, at, jnp.where(is_ins, 1, 0).astype(jnp.int32))
    t = _write_row(
        t, at, op.buf_start, op.ins_len, op.seq, op.client,
        jnp.int32(NOT_REMOVED),
        jnp.full(t.rem_clients.shape[1], NO_CLIENT, jnp.int32),
        _op_props_row(op, n_prop_keys),
    )
    bad = is_ins & (~land_found) & (op.pos1 > total)

    # 4. Covered-range updates (visibility recomputed after the shift).
    skip, vis_len = _visibility(t, op.ref_seq, op.client)
    prefix = _prefix(vis_len)
    covered = (
        (~skip) & (vis_len > 0) & (prefix >= op.pos1)
        & (prefix + vis_len <= op.pos2)
    )
    bad = bad | (is_range & (op.pos2 > jnp.sum(vis_len)))

    # Remove: overlapping removes keep the earliest sequenced rem_seq
    # and append the removing client at the first free slot.
    upd_rem = covered & is_rem
    already = t.rem_seq != NOT_REMOVED
    new_rem_seq = jnp.where(upd_rem & ~already, op.seq, t.rem_seq)
    n_removers = t.rem_clients.shape[1]
    free = t.rem_clients == NO_CLIENT
    first_free = jnp.argmax(free, axis=1).astype(jnp.int32)
    no_free = ~jnp.any(free, axis=1)
    slot = jnp.where(already, first_free, 0)
    write = upd_rem & ~(already & no_free)
    slot_onehot = jnp.arange(n_removers, dtype=jnp.int32)[None, :] == slot[:, None]
    new_rem_clients = jnp.where(write[:, None] & slot_onehot, op.client, t.rem_clients)
    overflow = jnp.any(upd_rem & already & no_free)

    # Annotate: last writer wins, PROP_DELETE clears (sequenced-path
    # semantics of segmentPropertiesManager addProperties).
    upd_ann = covered & is_ann
    props = t.props
    for p in range(op.prop_keys.shape[0]):  # PK is a small static width
        key = op.prop_keys[p]
        val = op.prop_vals[p]
        valid = key != NO_KEY
        col = jnp.arange(n_prop_keys, dtype=jnp.int32) == key
        newv = jnp.where(val == PROP_DELETE, PROP_ABSENT, val)
        props = jnp.where(valid & upd_ann[:, None] & col[None, :], newv, props)

    return t._replace(
        rem_seq=new_rem_seq,
        rem_clients=new_rem_clients,
        props=props,
        error=t.error
        | jnp.where(bad, ERR_BAD_POS, 0).astype(jnp.int32)
        | jnp.where(overflow, ERR_REMOVERS, 0).astype(jnp.int32),
    )


def apply_op_batch(table: SegmentTable, ops: OpBatch) -> SegmentTable:
    """Apply a chunk of sequenced ops in order (lax.scan over the batch).

    This is the jit unit: the whole chunk runs as one XLA computation;
    per-op work is a handful of O(capacity) vector passes."""

    def step(t, op):
        return _apply_one(t, op), None

    table, _ = lax.scan(step, table, ops)
    return table


@functools.partial(jax.jit, donate_argnums=0)
def apply_op_batch_jit(table: SegmentTable, ops: OpBatch) -> SegmentTable:
    return apply_op_batch(table, ops)


# vmap over a leading document axis: the data-parallel form used by the
# multi-document benchmarks and the pjit/shard_map multi-chip path
# (documents are embarrassingly parallel — SURVEY.md §2.6 row 1).
apply_op_batch_docs = jax.vmap(apply_op_batch)


@functools.partial(jax.jit, donate_argnums=0)
def apply_op_batch_docs_jit(tables: SegmentTable, ops: OpBatch) -> SegmentTable:
    return apply_op_batch_docs(tables, ops)


def verify_table_invariants(host_table: dict, capacity: int) -> None:
    """Exhaustive host-side verification of an unpacked SegmentTable
    (the partialLengths.ts:336 verifier role for the kernel path):
    raises AssertionError on violations. Test/debug opt-in."""
    import numpy as np

    n = host_table["n_rows"]
    assert 0 <= n <= capacity, f"n_rows {n} out of range"
    length = host_table["length"][:n]
    rem_seq = host_table["rem_seq"][:n]
    rem_clients = host_table["rem_clients"][:n]
    ins_seq = host_table["ins_seq"][:n]
    assert (length > 0).all(), "zero/negative-length live row"
    removed = rem_seq != NOT_REMOVED
    has_removers = (rem_clients != NO_CLIENT).any(axis=1)
    assert (removed == has_removers).all(), "removal/remover mismatch"
    # Remover slots fill left-to-right (first-free-slot append).
    free = rem_clients == NO_CLIENT
    first_free = np.argmax(free, axis=1)
    for k in range(rem_clients.shape[1]):
        after_free = free.any(axis=1) & (k > first_free)
        bad = after_free & (rem_clients[:, k] != NO_CLIENT)
        assert not bad.any(), "remover slot gap"
    assert (rem_seq[removed] >= ins_seq[removed]).all(), (
        "removed before inserted"
    )
