"""Batched multi-document sequencer kernel (the deli hot loop on TPU).

The reference sequencer (server/routerlicious/packages/lambdas/src/deli/
lambda.ts:818 `ticket`) is per-document serial scalar code: stamp
sequence numbers, track per-client reference sequence numbers in a heap
(clientSeqManager.ts:22), maintain MSN = min over connected clients'
refSeqs, and nack invalid submissions (stale refSeq lambda.ts:967,
out-of-order clientSeq, unknown client).

TPU-native re-expression (BASELINE.md config 5 — 10k docs x 64
clients): documents are the data-parallel axis (`vmap`), the op batch
is a `lax.scan`, and each scan step does the per-document work as
O(max_clients) vector ops — so one step processes *every* document's
next op in lockstep with D*C lanes of VPU work. The per-client "heap"
becomes a dense refSeq row per document; MSN is a masked min-reduce
(the reduction the reference maintains incrementally with a heap).

Scalar oracle: fluidframework_tpu/server/sequencer.py
(DocumentSequencer). Differential gate: tests/test_sequencer_kernel.py
drives both with identical random traffic and asserts identical stamps,
nack codes, and MSNs.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..protocol.constants import INT32_MAX

# Submission kinds (SeqBatch.kind).
SUB_OP = 0  # ordinary client message (op/noop/...): validate + stamp
SUB_JOIN = 1  # client join: admit into the MSN set, stamp a join message
SUB_LEAVE = 2  # client leave: evict, stamp a leave message
SUB_PAD = 3  # padding: no effect, no stamp

# Nack codes (0 = accepted). Values match server/sequencer.py.
ACCEPT = 0
NACK_STALE_REFSEQ = 400
NACK_UNKNOWN_CLIENT = 403
NACK_FUTURE_REFSEQ = 416
NACK_OUT_OF_ORDER = 422


class SequencerState(NamedTuple):
    """Per-document sequencer state, documents on the leading axis.

    The dense [D, C] client table replaces the reference's per-doc heap
    (clientSeqManager.ts:22); slot index = client id within the doc.
    """

    seq: jnp.ndarray  # int32[D] last assigned sequence number
    min_seq: jnp.ndarray  # int32[D] minimum sequence number (MSN)
    connected: jnp.ndarray  # bool[D, C]
    ref_seq: jnp.ndarray  # int32[D, C] last seen refSeq per client
    client_seq: jnp.ndarray  # int32[D, C] last accepted clientSeq per client


class SeqBatch(NamedTuple):
    """A batch of submissions: one column per scan step, [D, B]."""

    kind: jnp.ndarray  # int32[D, B] SUB_*
    client: jnp.ndarray  # int32[D, B] client slot in [0, C)
    client_seq: jnp.ndarray  # int32[D, B]
    ref_seq: jnp.ndarray  # int32[D, B]


class SeqResult(NamedTuple):
    """Per-submission verdicts, [D, B]."""

    seq: jnp.ndarray  # int32: assigned sequence number (0 if not stamped)
    min_seq: jnp.ndarray  # int32: MSN as of this submission
    nack: jnp.ndarray  # int32: ACCEPT or NACK_* code


def make_state(n_docs: int, max_clients: int) -> SequencerState:
    return SequencerState(
        seq=jnp.zeros(n_docs, jnp.int32),
        min_seq=jnp.zeros(n_docs, jnp.int32),
        connected=jnp.zeros((n_docs, max_clients), jnp.bool_),
        ref_seq=jnp.zeros((n_docs, max_clients), jnp.int32),
        client_seq=jnp.zeros((n_docs, max_clients), jnp.int32),
    )


def _step_one_doc(state: SequencerState, kind, client, client_seq, ref_seq):
    """Process one submission for one document (vmapped over docs).

    All fields here are per-document scalars / [C] rows; straight-line
    masked code (no control flow) mirroring DocumentSequencer.sequence
    and deli ticket() (lambda.ts:818).
    """
    n_clients = state.connected.shape[0]
    slot = jnp.clip(client, 0, n_clients - 1)
    onehot = jnp.arange(n_clients, dtype=jnp.int32) == slot

    is_op = kind == SUB_OP
    is_join = kind == SUB_JOIN
    is_leave = kind == SUB_LEAVE

    known = state.connected[slot]
    # Validation ladder (first failing rule wins), reference order in
    # DocumentSequencer.sequence: unknown -> stale -> future -> gap.
    nack = jnp.where(
        is_op & ~known,
        NACK_UNKNOWN_CLIENT,
        jnp.where(
            is_op & (ref_seq < state.min_seq),
            NACK_STALE_REFSEQ,
            jnp.where(
                is_op & (ref_seq > state.seq),
                NACK_FUTURE_REFSEQ,
                jnp.where(
                    is_op & (client_seq != state.client_seq[slot] + 1),
                    NACK_OUT_OF_ORDER,
                    ACCEPT,
                ),
            ),
        ),
    ).astype(jnp.int32)

    ok_op = is_op & (nack == ACCEPT)
    # leave of an unknown client stamps nothing (oracle returns None).
    ok_leave = is_leave & known
    stamped = ok_op | is_join | ok_leave

    new_seq = state.seq + stamped.astype(jnp.int32)

    # Client-table updates.
    connected = jnp.where(
        onehot & is_join, True, jnp.where(onehot & ok_leave, False, state.connected)
    )
    # join admits at ref_seq = head seq *before* its own stamp
    # (oracle join(): ref_seq=self.seq then _stamp increments).
    new_ref = jnp.where(is_join, state.seq, ref_seq)
    ref_row = jnp.where(onehot & (ok_op | is_join), new_ref, state.ref_seq)
    cseq_row = jnp.where(
        onehot & is_join,
        0,
        jnp.where(onehot & ok_op, client_seq, state.client_seq),
    )

    # MSN: min over connected clients' refSeqs; empty set trails the
    # head; monotone (oracle _update_msn). Recomputed only when a
    # message is stamped, matching the oracle's call sites.
    masked = jnp.where(connected, ref_row, INT32_MAX)
    any_conn = jnp.any(connected)
    candidate = jnp.where(any_conn, jnp.min(masked), new_seq)
    new_min = jnp.where(stamped, jnp.maximum(state.min_seq, candidate), state.min_seq)

    out = SeqResult(
        seq=jnp.where(stamped, new_seq, 0).astype(jnp.int32),
        min_seq=new_min.astype(jnp.int32),
        nack=nack,
    )
    return (
        SequencerState(
            seq=new_seq.astype(jnp.int32),
            min_seq=new_min.astype(jnp.int32),
            connected=connected,
            ref_seq=ref_row.astype(jnp.int32),
            client_seq=cseq_row.astype(jnp.int32),
        ),
        out,
    )


def sequence_batch(state: SequencerState, batch: SeqBatch):
    """Sequence a [D, B] submission batch: scan over B, vmap over D.

    Returns (new_state, SeqResult[D, B])."""
    step = jax.vmap(_step_one_doc)

    def body(st, col):
        kind, client, client_seq, ref_seq = col
        return step(st, kind, client, client_seq, ref_seq)

    cols = (
        jnp.swapaxes(batch.kind, 0, 1),
        jnp.swapaxes(batch.client, 0, 1),
        jnp.swapaxes(batch.client_seq, 0, 1),
        jnp.swapaxes(batch.ref_seq, 0, 1),
    )
    new_state, out = lax.scan(body, state, cols)
    # out fields are [B, D] -> [D, B]
    return new_state, SeqResult(*(jnp.swapaxes(a, 0, 1) for a in out))


@functools.partial(jax.jit, donate_argnums=0)
def sequence_batch_jit(state: SequencerState, batch: SeqBatch):
    return sequence_batch(state, batch)
