"""Batched multi-document sequencer kernel (the deli hot loop on TPU).

The reference sequencer (server/routerlicious/packages/lambdas/src/deli/
lambda.ts:818 `ticket`) is per-document serial scalar code: stamp
sequence numbers, track per-client reference sequence numbers in a heap
(clientSeqManager.ts:22), maintain MSN = min over connected clients'
refSeqs, and nack invalid submissions (stale refSeq lambda.ts:967,
out-of-order clientSeq, unknown client).

TPU-native re-expression (BASELINE.md config 5 — 10k docs x 64
clients): documents are the data-parallel axis (`vmap`), the op batch
is a `lax.scan`, and each scan step does the per-document work as
O(max_clients) vector ops — so one step processes *every* document's
next op in lockstep with D*C lanes of VPU work. The per-client "heap"
becomes a dense refSeq row per document; MSN is a masked min-reduce
(the reduction the reference maintains incrementally with a heap).

Scalar oracle: fluidframework_tpu/server/sequencer.py
(DocumentSequencer). Differential gate: tests/test_sequencer_kernel.py
drives both with identical random traffic and asserts identical stamps,
nack codes, and MSNs.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..protocol.constants import INT32_MAX

# Submission kinds (SeqBatch.kind).
SUB_OP = 0  # ordinary client message (op/noop/...): validate + stamp
SUB_JOIN = 1  # client join: admit into the MSN set, stamp a join message
SUB_LEAVE = 2  # client leave: evict, stamp a leave message
SUB_PAD = 3  # padding: no effect, no stamp
SUB_SYSTEM = 4  # server-originated control: stamp unconditionally,
#                 bypassing client validation (deli's system-message
#                 path — summary ack/nack from scribe)

# Boxcar group sentinel (the `groups` batch column): submissions with
# group >= 0 belong to an atomic boxcar; -1 means standalone.
NO_GROUP = -1

# Nack codes (0 = accepted). Values match server/sequencer.py.
ACCEPT = 0
NACK_STALE_REFSEQ = 400
NACK_UNKNOWN_CLIENT = 403
NACK_FUTURE_REFSEQ = 416
NACK_OUT_OF_ORDER = 422


class SequencerState(NamedTuple):
    """Per-document sequencer state, documents on the leading axis.

    The dense [D, C] client table replaces the reference's per-doc heap
    (clientSeqManager.ts:22); slot index = client id within the doc.
    """

    seq: jnp.ndarray  # int32[D] last assigned sequence number
    min_seq: jnp.ndarray  # int32[D] minimum sequence number (MSN)
    connected: jnp.ndarray  # bool[D, C]
    ref_seq: jnp.ndarray  # int32[D, C] last seen refSeq per client
    client_seq: jnp.ndarray  # int32[D, C] last accepted clientSeq per client


class SeqBatch(NamedTuple):
    """A batch of submissions: one column per scan step, [D, B]."""

    kind: jnp.ndarray  # int32[D, B] SUB_*
    client: jnp.ndarray  # int32[D, B] client slot in [0, C)
    client_seq: jnp.ndarray  # int32[D, B]
    ref_seq: jnp.ndarray  # int32[D, B]


class SeqResult(NamedTuple):
    """Per-submission verdicts, [D, B]."""

    seq: jnp.ndarray  # int32: assigned sequence number (0 if not stamped)
    min_seq: jnp.ndarray  # int32: MSN as of this submission
    nack: jnp.ndarray  # int32: ACCEPT or NACK_* code
    # bool: submission was masked out with no stamp AND no nack — the
    # tail of an aborted boxcar (scalar `_handle` breaks out of the
    # batch after a nack) or a deduped resubmission (DeliRole's
    # at-least-once ingress dedup drops it silently).
    skipped: jnp.ndarray


def make_state(n_docs: int, max_clients: int) -> SequencerState:
    return SequencerState(
        seq=jnp.zeros(n_docs, jnp.int32),
        min_seq=jnp.zeros(n_docs, jnp.int32),
        connected=jnp.zeros((n_docs, max_clients), jnp.bool_),
        ref_seq=jnp.zeros((n_docs, max_clients), jnp.int32),
        client_seq=jnp.zeros((n_docs, max_clients), jnp.int32),
    )


def grow_state(state: SequencerState, n_docs: int = None,
               n_clients: int = None) -> SequencerState:
    """Zero-pad the packed state to [n_docs, n_clients] (dynamic
    doc-slot / client-slot growth; new rows are empty documents)."""
    d, c = state.connected.shape
    nd = d if n_docs is None else max(d, n_docs)
    nc = c if n_clients is None else max(c, n_clients)
    if (nd, nc) == (d, c):
        return state
    pad1 = ((0, nd - d),)
    pad2 = ((0, nd - d), (0, nc - c))
    return SequencerState(
        seq=jnp.pad(state.seq, pad1),
        min_seq=jnp.pad(state.min_seq, pad1),
        connected=jnp.pad(state.connected, pad2),
        ref_seq=jnp.pad(state.ref_seq, pad2),
        client_seq=jnp.pad(state.client_seq, pad2),
    )


def _step_one_doc(state: SequencerState, aborted, kind, client, client_seq,
                  ref_seq, group, *, dedup: bool = False):
    """Process one submission for one document (vmapped over docs).

    All fields here are per-document scalars / [C] rows; straight-line
    masked code (no control flow) mirroring DocumentSequencer.sequence
    and deli ticket() (lambda.ts:818). `aborted` is the batch-local
    boxcar-abort tracker: the group id whose remaining submissions are
    masked out (a nack aborts the REST of its boxcar, the `_handle`
    break semantics).
    """
    n_clients = state.connected.shape[0]
    slot = jnp.clip(client, 0, n_clients - 1)
    onehot = jnp.arange(n_clients, dtype=jnp.int32) == slot

    is_join = kind == SUB_JOIN
    is_leave = kind == SUB_LEAVE
    is_sys = kind == SUB_SYSTEM

    known = state.connected[slot]
    in_box = group >= 0
    box_dead = in_box & (group == aborted)
    if dedup:
        # Resubmission dedup (DeliRole's idempotent-producer role): a
        # clientSeq at or below the last accepted one is dropped
        # silently — checked BEFORE the nack ladder, so a stale
        # resubmission never pollutes the stream with spurious nacks.
        dup = (kind == SUB_OP) & known & (client_seq <= state.client_seq[slot])
    else:
        dup = jnp.zeros((), jnp.bool_)
    skipped = box_dead | dup
    is_op = (kind == SUB_OP) & ~skipped

    # Validation ladder (first failing rule wins), reference order in
    # DocumentSequencer.sequence: unknown -> stale -> future -> gap.
    nack = jnp.where(
        is_op & ~known,
        NACK_UNKNOWN_CLIENT,
        jnp.where(
            is_op & (ref_seq < state.min_seq),
            NACK_STALE_REFSEQ,
            jnp.where(
                is_op & (ref_seq > state.seq),
                NACK_FUTURE_REFSEQ,
                jnp.where(
                    is_op & (client_seq != state.client_seq[slot] + 1),
                    NACK_OUT_OF_ORDER,
                    ACCEPT,
                ),
            ),
        ),
    ).astype(jnp.int32)

    ok_op = is_op & (nack == ACCEPT)
    live = ~box_dead
    do_join = is_join & live
    # leave of an unknown client stamps nothing (oracle returns None).
    ok_leave = is_leave & known & live
    do_sys = is_sys & live
    stamped = ok_op | do_join | ok_leave | do_sys

    new_seq = state.seq + stamped.astype(jnp.int32)

    # Client-table updates (system stamps bypass the table entirely).
    connected = jnp.where(
        onehot & do_join, True, jnp.where(onehot & ok_leave, False, state.connected)
    )
    # join admits at ref_seq = head seq *before* its own stamp
    # (oracle join(): ref_seq=self.seq then _stamp increments).
    new_ref = jnp.where(do_join, state.seq, ref_seq)
    ref_row = jnp.where(onehot & (ok_op | do_join), new_ref, state.ref_seq)
    cseq_row = jnp.where(
        onehot & do_join,
        0,
        jnp.where(onehot & ok_op, client_seq, state.client_seq),
    )

    # MSN: min over connected clients' refSeqs; empty set trails the
    # head; monotone (oracle _update_msn). Recomputed only when a
    # message is stamped, matching the oracle's call sites.
    masked = jnp.where(connected, ref_row, INT32_MAX)
    any_conn = jnp.any(connected)
    candidate = jnp.where(any_conn, jnp.min(masked), new_seq)
    new_min = jnp.where(stamped, jnp.maximum(state.min_seq, candidate), state.min_seq)

    # A nack aborts the rest of its boxcar (nack is only ever nonzero
    # for live ops, so this can't retrigger inside a dead group).
    new_aborted = jnp.where(in_box & (nack != ACCEPT), group, aborted)

    out = SeqResult(
        seq=jnp.where(stamped, new_seq, 0).astype(jnp.int32),
        min_seq=new_min.astype(jnp.int32),
        nack=nack,
        skipped=skipped,
    )
    return (
        SequencerState(
            seq=new_seq.astype(jnp.int32),
            min_seq=new_min.astype(jnp.int32),
            connected=connected,
            ref_seq=ref_row.astype(jnp.int32),
            client_seq=cseq_row.astype(jnp.int32),
        ),
        new_aborted.astype(jnp.int32),
        out,
    )


def _sequence_batch_impl(state: SequencerState, aborted, batch: SeqBatch,
                         groups, dedup: bool):
    step = jax.vmap(functools.partial(_step_one_doc, dedup=dedup))

    def body(carry, col):
        st, ab = carry
        kind, client, client_seq, ref_seq, group = col
        st2, ab2, out = step(st, ab, kind, client, client_seq,
                             ref_seq, group)
        return (st2, ab2), out

    cols = (
        jnp.swapaxes(batch.kind, 0, 1),
        jnp.swapaxes(batch.client, 0, 1),
        jnp.swapaxes(batch.client_seq, 0, 1),
        jnp.swapaxes(batch.ref_seq, 0, 1),
        jnp.swapaxes(groups, 0, 1),
    )
    (new_state, new_aborted), out = lax.scan(body, (state, aborted), cols)
    # out fields are [B, D] -> [D, B]
    return new_state, new_aborted, SeqResult(
        *(jnp.swapaxes(a, 0, 1) for a in out)
    )


def pack_submissions(slot, kind, client, client_seq, ref_seq, groups,
                     n_docs: int, max_cols: int):
    """Pack PRE-COLUMNIZED 1-D submission arrays into dense ``[D, B]``
    kernel chunks (host-side, vectorized numpy).

    Inputs are six equal-length 1-D arrays — one entry per submission,
    in stream order — exactly the shape the columnar op-log codec
    (`protocol.record_batch`) hands over, so the live pipeline feeds
    the kernel without ever materializing per-record Python tuples.
    Per-doc column index = the submission's rank within its document
    (stable argsort + cumulative count keeps per-doc order == record
    order); documents whose rank exceeds `max_cols` spill into further
    chunks (the boxcar-abort tracker threads across them).

    Yields ``(sel, sl, ic, kind2, client2, cseq2, ref2, grp2)`` per
    chunk: `sel` indexes the original arrays (slice or bool mask),
    ``[sl, ic]`` gathers that chunk's verdicts out of the kernel's
    ``[D, B]`` result, and the five dense int32 arrays are the
    `SeqBatch` + groups input."""
    slot = np.asarray(slot, np.int64)
    n = slot.shape[0]
    if n == 0:
        return
    kind = np.asarray(kind)
    client = np.asarray(client)
    client_seq = np.asarray(client_seq)
    ref_seq = np.asarray(ref_seq)
    groups = np.asarray(groups)
    ar = np.arange(n)
    order = np.argsort(slot, kind="stable")
    ss = slot[order]
    first = np.empty(n, bool)
    first[0] = True
    first[1:] = ss[1:] != ss[:-1]
    col_sorted = ar - np.maximum.accumulate(np.where(first, ar, 0))
    col = np.empty(n, np.int64)
    col[order] = col_sorted
    n_chunks = int(col.max()) // max_cols + 1
    for k in range(n_chunks):
        if n_chunks == 1:
            sel = slice(None)
            sl, ic = slot, col
        else:
            sel = (col // max_cols) == k
            sl, ic = slot[sel], col[sel] - k * max_cols
        b = 8
        top = int(ic.max()) + 1
        while b < top:
            b <<= 1
        kind2 = np.full((n_docs, b), SUB_PAD, np.int32)
        client2 = np.zeros((n_docs, b), np.int32)
        cseq2 = np.zeros((n_docs, b), np.int32)
        ref2 = np.zeros((n_docs, b), np.int32)
        grp2 = np.full((n_docs, b), NO_GROUP, np.int32)
        kind2[sl, ic] = kind[sel]
        client2[sl, ic] = client[sel]
        cseq2[sl, ic] = client_seq[sel]
        ref2[sl, ic] = ref_seq[sel]
        grp2[sl, ic] = groups[sel]
        yield sel, sl, ic, kind2, client2, cseq2, ref2, grp2


# The span decomposition every columnar ingest/emit path shares: a
# homogeneous run vectorizes (one `add_columns` call, one verdict
# slice, one blob-heap memcpy), category boundaries fall back to
# per-record handling without losing stream order. Defined next to the
# codec (it is pure numpy over codec columns, and jax-free consumers —
# the fused durable+broadcast hop — use it too); re-exported here
# beside `pack_submissions` because kernel callers treat it as part of
# the packing toolkit.
from ..protocol.record_batch import mask_runs  # noqa: E402,F401


def no_aborts(n_docs: int):
    """A fresh boxcar-abort tracker ([D], no group aborted)."""
    return jnp.full((n_docs,), -2, jnp.int32)


def sequence_batch(state: SequencerState, batch: SeqBatch, groups=None,
                   dedup: bool = False):
    """Sequence a [D, B] submission batch: scan over B, vmap over D.

    `groups` (int32[D, B], optional) assigns submissions to atomic
    boxcars: a nack masks out the rest of that group (NO_GROUP = -1 =
    standalone). `dedup` enables silent resubmission dedup (the
    at-least-once-ingress DeliRole semantics).

    Returns (new_state, SeqResult[D, B])."""
    if groups is None:
        groups = jnp.full(batch.kind.shape, NO_GROUP, jnp.int32)
    new_state, _, out = _sequence_batch_impl(
        state, no_aborts(state.seq.shape[0]), batch, groups, dedup
    )
    return new_state, out


@functools.partial(jax.jit, donate_argnums=0)
def sequence_batch_jit(state: SequencerState, batch: SeqBatch):
    return sequence_batch(state, batch)


@functools.partial(jax.jit, donate_argnums=(0, 1), static_argnums=4)
def _sequence_batch_grouped_jit(state, aborted, batch, groups, dedup):
    return _sequence_batch_impl(state, aborted, batch, groups, dedup)


_SHARDED_FN_CACHE: dict = {}


def sharded_sequence_fn(mesh, dedup: bool = False, axis: str = "docs"):
    """Compile the grouped sequencer scan data-parallel over `mesh`.

    Documents are embarrassingly parallel here — verdicts, boxcar
    aborts, and resubmission dedup are all per-doc state — so the
    whole `[D, C]` pool shards over a 1-D device mesh with
    ``PartitionSpec(axis)`` on every per-doc array (state rows, the
    `[D, B]` batch columns, the groups plane, the abort tracker) and
    ZERO cross-device collectives inside the scan: each device runs
    the identical vmap-over-local-docs / scan-over-B body on its slice
    of the doc axis. `D` must be a multiple of ``mesh.size`` (the pool
    keeps it so). Returns a jitted
    ``fn(state, aborted, batch, groups) -> (state', aborted', SeqResult)``
    with the same donation contract as `sequence_batch_grouped`; the
    caller threads `aborted'` across a pump's chunks exactly as in the
    single-device path, so boxcar groups may still span chunks.

    Compiled callables cache process-wide per (mesh, dedup, axis) —
    paired with `parallel.mesh.shared_docs_mesh`, every pool/bench in
    a process shares one jit cache instead of re-tracing per instance.
    """
    key = (mesh, bool(dedup), axis)
    cached = _SHARDED_FN_CACHE.get(key)
    if cached is not None:
        return cached
    from ..utils.jax_compat import shard_map_compat

    docs = jax.sharding.PartitionSpec(axis)
    state_specs = SequencerState(
        seq=docs, min_seq=docs, connected=docs, ref_seq=docs,
        client_seq=docs,
    )
    batch_specs = SeqBatch(
        kind=docs, client=docs, client_seq=docs, ref_seq=docs,
    )
    res_specs = SeqResult(seq=docs, min_seq=docs, nack=docs, skipped=docs)

    def local(state, aborted, batch, groups):
        return _sequence_batch_impl(state, aborted, batch, groups, dedup)

    fn = shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(state_specs, docs, batch_specs, docs),
        out_specs=(state_specs, docs, res_specs),
        check=False,
    )
    jitted = jax.jit(fn, donate_argnums=(0, 1))
    _SHARDED_FN_CACHE[key] = jitted
    return jitted


def sequence_batch_grouped(state: SequencerState, batch: SeqBatch, groups,
                           dedup: bool = False, aborted=None):
    """Jitted entry for the live deli pipeline: boxcar groups + optional
    resubmission dedup. `aborted` (from `no_aborts` or a previous
    chunk's return) threads the abort tracker across the chunks of one
    pump, so boxcars MAY span chunk boundaries (group ids must be
    unique per doc per pump). Donates (consumes) the input state and
    tracker; returns (new_state, new_aborted, SeqResult)."""
    if aborted is None:
        aborted = no_aborts(state.seq.shape[0])
    return _sequence_batch_grouped_jit(state, aborted, batch, groups,
                                       bool(dedup))
