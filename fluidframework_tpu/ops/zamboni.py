"""Device-side zamboni: segment-table compaction as one XLA dispatch.

The reference's zamboni (packages/dds/merge-tree/src/zamboni.ts:19)
collects segments whose removal has passed below the minimum sequence
number and merges adjacent settled segments. Round 1 did this host-side
(core/columnar_replay.py compact()), costing a device→host→device
round trip per compaction — ~500 round trips over the 1M-op replay.

This version never leaves the device and never touches text:

1. tombstone drop — rows removed at/below the MSN can never be seen
   by any future perspective; a mask + prefix-sum + gather packs the
   survivors (stable, preserving document order);
2. adjacency coalescing — consecutive *settled* rows (insert seq ≤
   MSN, not removed) with identical props whose text spans are
   CONTIGUOUS IN THE ARENA (prev.buf_start + prev.length ==
   next.buf_start) merge into one row. Contiguity replaces the host
   version's text re-gather: split pieces are contiguous by
   construction, and consecutive same-client inserts usually are, so
   most of the coalescing survives without moving a single byte.

Everything is masks, cumsums, and two gathers over [C] arrays —
standard XLA, so it runs on any backend (tests exercise it on CPU)
and costs ~one kernel dispatch on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..protocol.constants import NO_CLIENT
from .mergetree_kernel import (
    NOT_REMOVED,
    PROP_ABSENT,
    SegmentTable,
)

STREAM_BASE = 1 << 28  # stream-arena offsets start here (columnar_replay)


@jax.jit
def zamboni_device(table: SegmentTable, min_seq: jnp.ndarray) -> SegmentTable:
    """Compact `table` under applied MSN `min_seq` (int32 scalar).

    Returns a table with identical visible semantics for every
    perspective with ref_seq >= min_seq (the only ones that can still
    occur): dropped rows were invisible to all of them; coalesced rows
    were identically visible to all of them.
    """
    C = table.length.shape[0]
    idx = jnp.arange(C, dtype=jnp.int32)
    live = idx < table.n_rows
    removed = table.rem_seq != NOT_REMOVED

    # ---- 1. tombstone drop (stable pack of survivors)
    keep = live & ~(removed & (table.rem_seq <= min_seq))
    pos = jnp.cumsum(keep.astype(jnp.int32)) - keep  # dest of each kept row
    n_keep = jnp.sum(keep.astype(jnp.int32))
    # src[d] = source row of destination d (scatter the inverse map).
    src = jnp.full(C, C - 1, jnp.int32).at[
        jnp.where(keep, pos, C)
    ].set(idx, mode="drop")
    packed_valid = idx < n_keep

    def pack(a, fill):
        g = a[src]
        if a.ndim == 1:
            return jnp.where(packed_valid, g, fill)
        return jnp.where(packed_valid[:, None], g, fill)

    buf = pack(table.buf_start, 0)
    length = pack(table.length, 0)
    iseq = pack(table.ins_seq, 0)
    iclient = pack(table.ins_client, NO_CLIENT)
    rseq = pack(table.rem_seq, NOT_REMOVED)
    rcl = pack(table.rem_clients, NO_CLIENT)
    props = pack(table.props, PROP_ABSENT)

    # ---- 2. adjacency coalescing of settled runs
    settled = packed_valid & (rseq == NOT_REMOVED) & (iseq <= min_seq)
    prev_settled = jnp.concatenate([jnp.zeros(1, bool), settled[:-1]])
    prev_end = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), (buf + length)[:-1]]
    )
    same_props = jnp.concatenate(
        [jnp.zeros(1, bool), jnp.all(props[1:] == props[:-1], axis=1)]
    )
    merge_into_prev = (
        settled & prev_settled & same_props & (prev_end == buf)
    )
    start = packed_valid & ~merge_into_prev
    run_id = jnp.cumsum(start.astype(jnp.int32)) - 1  # 0-based run index
    m = jnp.sum(start.astype(jnp.int32))
    run_len = jax.ops.segment_sum(
        jnp.where(packed_valid, length, 0), run_id, num_segments=C
    ).astype(jnp.int32)
    # Gather each run's first row to its final position.
    src2 = jnp.full(C, C - 1, jnp.int32).at[
        jnp.where(start, run_id, C)
    ].set(idx, mode="drop")
    final_valid = idx < m

    def take(a, fill):
        g = a[src2]
        if a.ndim == 1:
            return jnp.where(final_valid, g, fill)
        return jnp.where(final_valid[:, None], g, fill)

    return SegmentTable(
        n_rows=m,
        buf_start=take(buf, 0),
        length=jnp.where(final_valid, run_len, 0),
        ins_seq=take(iseq, 0),
        ins_client=take(iclient, NO_CLIENT),
        rem_seq=take(rseq, NOT_REMOVED),
        rem_clients=take(rcl, NO_CLIENT),
        props=take(props, PROP_ABSENT),
        error=table.error,
    )


def _pack_sort(key, cols):
    """Stable-sort `cols` (tuple of int32[C] arrays) by int32 `key`."""
    out = jax.lax.sort((key,) + tuple(cols), num_keys=1, is_stable=True)
    return out[1:]


def _pack_partition(drop, cols):
    """Stable binary partition: rows with ``drop == False`` pack to the
    front, dropped rows to the back, both preserving order — the
    result of ``_pack_sort(drop ? 1 : 0, cols)`` without the sort
    network. A 0/1 key needs only a monotone variable shift: keepers
    move DOWN by (# dropped before them), dropped rows move UP by
    (# keepers after them); both shifts are 1-Lipschitz in the row
    index, so applying them bit-by-bit (log2 W masked rolls per
    direction) never collides. A ridden original-index column guards
    each pull (a slot qualifies as a source only if its element's
    already-applied low shift bits land it exactly there), so stale
    copies left behind by earlier moves can never be re-pulled.

    ~2x log2(W) fused select/roll passes over the stacked columns
    replaces lax.sort's ~log^2(W) compare-exchange stages — the fold
    runs per chunk, so this is on the replay's critical path.
    """
    W = drop.shape[0]
    idx = jnp.arange(W, dtype=jnp.int32)
    di = drop.astype(jnp.int32)
    keep = 1 - di
    s_down = jnp.cumsum(di) - di
    ka_up = jnp.sum(keep) - jnp.cumsum(keep)
    n_keep = jnp.sum(keep)
    base = jnp.stack(cols, 0)

    def compact(stack, flag, shift, down):
        st = jnp.concatenate(
            [stack, flag[None], shift[None], idx[None]], 0
        )
        b = 1
        while b < W:
            if down:
                src = jnp.roll(st, -b, axis=1)
                src_pos = idx + b
                valid = src_pos < W
                at_pos = src[-1] - (src[-2] % b) == src_pos
            else:
                src = jnp.roll(st, b, axis=1)
                src_pos = idx - b
                valid = src_pos >= 0
                at_pos = src[-1] + (src[-2] % b) == src_pos
            pull = (
                valid & (src[-3] > 0) & ((src[-2] & b) > 0) & at_pos
            )
            st = jnp.where(pull[None], src, st)
            b <<= 1
        return st[: stack.shape[0]]

    front = compact(base, keep, s_down, down=True)
    back = compact(base, di, ka_up, down=False)
    out = jnp.where((idx < n_keep)[None], front, back)
    return tuple(out)


@jax.jit
def compact_gather_text(
    table: SegmentTable,
    min_seq: jnp.ndarray,
    doc_arena: jnp.ndarray,
    stream_text: jnp.ndarray,
):
    """Full compaction WITH device-side text re-gather, gather-free.

    Interleaved multi-client editing leaves doc-order neighbors far
    apart in the arenas, so pure adjacency coalescing stalls and the
    row count grows with the document. The round-1 fix was a host
    compaction that re-gathers all live text contiguously; this is
    that compaction as ONE device dispatch — built ONLY from sorts,
    scatters, and cumsums, because on TPU an XLA gather of N elements
    lowers to an elementwise loop (~100ns/element measured: a 1M-
    element gather costs ~100ms, while payload sorts and scatters of
    the same size are ~fast vector ops):

    1. tombstone drop: stable payload-sort by a kept-first key packs
       surviving rows to the front (no inverse-permutation gather);
    2. text move: each surviving span [buf, buf+len) must land at its
       new contiguous offset. dest(e) = e + delta with delta piecewise
       constant per span, so scatter +/-delta EVENTS at span
       boundaries, cumsum them into a per-element delta over the
       source arena, and SCATTER source elements to their
       destinations (out-of-span elements get a poison delta and drop)
       — the classic event-sweep trick, one pass per source region
       (doc arena / stream text);
    3. coalescing: every settled neighbor pair with equal props now
       merges (text is contiguous by construction). Run lengths come
       from prefix-sum differences at run starts (no segment_sum,
       which scatter-adds per element); a second payload-sort packs
       run starts to the front.

    `doc_arena` addresses codepoints in [0, STREAM_BASE);
    `stream_text` holds immutable op-inserted text addressed from
    STREAM_BASE (core/columnar_replay.py's dual-region scheme).
    Callers size `doc_arena` at initial_len + len(stream_text), which
    no live document can exceed.

    Returns ``(table, new_doc_arena)``.
    """
    C = table.length.shape[0]
    A = doc_arena.shape[0]
    S = stream_text.shape[0]
    KR = table.rem_clients.shape[1]
    KK = table.props.shape[1]
    idx = jnp.arange(C, dtype=jnp.int32)
    live = idx < table.n_rows
    removed = table.rem_seq != NOT_REMOVED

    # ---- 1. tombstone drop: stable kept-first payload sort
    keep = live & ~(removed & (table.rem_seq <= min_seq))
    n_keep = jnp.sum(keep.astype(jnp.int32))
    key = jnp.where(keep, 0, 1).astype(jnp.int32)
    cols = (
        table.buf_start, table.length, table.ins_seq, table.ins_client,
        table.rem_seq,
        *(table.rem_clients[:, k] for k in range(KR)),
        *(table.props[:, k] for k in range(KK)),
    )
    packed = _pack_sort(key, cols)
    buf, length, iseq, iclient, rseq = packed[:5]
    rcl = packed[5:5 + KR]
    props = packed[5 + KR:]
    valid = idx < n_keep
    length = jnp.where(valid, length, 0)

    # ---- 2. text move (event sweep + element scatter, no gathers)
    new_off = jnp.cumsum(length) - length
    total = jnp.sum(length)

    def sweep_region(region_len, base, arena_vals, out):
        """Scatter this region's surviving spans into `out` at their
        destinations. `base` rebases buf into region coordinates."""
        DEAD = jnp.int32(A + region_len + 2)
        in_region = valid & (buf >= base) & (buf < base + region_len)
        rbuf = buf - base
        delta = new_off - rbuf
        ev_at = jnp.where(in_region, rbuf, region_len + 1)
        ev = jnp.zeros(region_len + 2, jnp.int32).at[ev_at].add(
            delta - DEAD, mode="drop"
        )
        ev_end = jnp.where(in_region, rbuf + length, region_len + 1)
        ev = ev.at[ev_end].add(DEAD - delta, mode="drop")
        delta_per_elem = DEAD + jnp.cumsum(ev)[:region_len]
        e = jnp.arange(region_len, dtype=jnp.int32)
        dest = e + delta_per_elem  # >= A for out-of-span elements
        return out.at[dest].set(arena_vals, mode="drop")

    new_arena = jnp.zeros(A, jnp.int32)
    new_arena = sweep_region(A, 0, doc_arena, new_arena)
    new_arena = sweep_region(S, STREAM_BASE, stream_text, new_arena)
    buf = new_off  # every surviving span now lives contiguously

    # ---- 3. maximal coalescing (arena adjacency holds by construction)
    settled = valid & (rseq == NOT_REMOVED) & (iseq <= min_seq)
    prev_settled = jnp.concatenate([jnp.zeros(1, bool), settled[:-1]])
    props_m = jnp.stack(props, axis=1)
    same_props = jnp.concatenate(
        [jnp.zeros(1, bool), jnp.all(props_m[1:] == props_m[:-1], axis=1)]
    )
    start = valid & ~(settled & prev_settled & same_props)
    m = jnp.sum(start.astype(jnp.int32))
    key2 = jnp.where(start, 0, 1).astype(jnp.int32)
    packed2 = _pack_sort(
        key2,
        (buf, iseq, iclient, rseq, *rcl, *props, new_off),
    )
    fbuf, fiseq, ficlient, frseq = packed2[:4]
    frcl = packed2[4:4 + KR]
    fprops = packed2[4 + KR:4 + KR + KK]
    f_off = packed2[-1]
    final_valid = idx < m
    # Run length = next run's text offset - this run's (runs are
    # contiguous in the new arena).
    next_off = jnp.concatenate([f_off[1:], jnp.zeros(1, jnp.int32)])
    next_off = jnp.where(idx == m - 1, total, next_off)
    run_len = jnp.where(final_valid, next_off - f_off, 0)

    out = SegmentTable(
        n_rows=m,
        buf_start=jnp.where(final_valid, fbuf, 0),
        length=run_len,
        ins_seq=jnp.where(final_valid, fiseq, 0),
        ins_client=jnp.where(final_valid, ficlient, NO_CLIENT),
        rem_seq=jnp.where(final_valid, frseq, NOT_REMOVED),
        rem_clients=jnp.where(
            final_valid[:, None], jnp.stack(frcl, axis=1), NO_CLIENT
        ),
        props=jnp.where(
            final_valid[:, None], jnp.stack(fprops, axis=1), PROP_ABSENT
        ),
        error=table.error,
    )
    return out, new_arena
