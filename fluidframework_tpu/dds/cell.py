"""SharedCell: a single shared LWW value.

Reference packages/dds/cell/src/cell.ts:58. Same pending-local
shadowing as the map kernel, over exactly one slot.
"""

from __future__ import annotations

import json
from typing import Any

from ..protocol.messages import SequencedMessage
from ..runtime.channel import ChannelFactory, ChannelStorage
from ..runtime.shared_object import SharedObject
from ..runtime.summary import SummaryTreeBuilder


class SharedCell(SharedObject):
    def initialize_local_core(self) -> None:
        self._value: Any = None
        self._empty = True
        self._pending = 0

    def get(self) -> Any:
        return self._value

    @property
    def is_empty(self) -> bool:
        return self._empty

    def set(self, value: Any) -> None:
        md = {"prev": self._value, "empty": self._empty}
        self._value = value
        self._empty = False
        self._pending += 1
        self.submit_local_message({"type": "setCell", "value": value}, md)
        self.emit("valueChanged", value, True)

    def delete(self) -> None:
        md = {"prev": self._value, "empty": self._empty}
        self._value = None
        self._empty = True
        self._pending += 1
        self.submit_local_message({"type": "deleteCell"}, md)
        self.emit("delete", True)

    def rollback(self, content: Any, local_metadata: Any) -> None:
        self._value = local_metadata["prev"]
        self._empty = local_metadata["empty"]
        self._pending -= 1

    def process_core(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        op = msg.contents
        if local:
            self._pending -= 1
            return
        if self._pending > 0:
            return  # pending local write wins (cell.ts processCore)
        if op["type"] == "setCell":
            self._value = op["value"]
            self._empty = False
            self.emit("valueChanged", self._value, False)
        else:
            self._value = None
            self._empty = True
            self.emit("delete", False)

    def apply_stashed_op(self, content: Any) -> Any:
        if content["type"] == "setCell":
            self.set(content["value"])
        else:
            self.delete()
        return None

    def summarize_core(self):
        return (
            SummaryTreeBuilder()
            .add_json_blob("header", {"value": self._value, "empty": self._empty})
            .summary
        )

    def load_core(self, storage: ChannelStorage) -> None:
        self.initialize_local_core()
        data = json.loads(storage.read("header"))
        self._value = data["value"]
        self._empty = data["empty"]


class CellFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/cell"
    channel_class = SharedCell
