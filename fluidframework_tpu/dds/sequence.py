"""SharedString and the sequence DDS family over the merge-tree engine.

Mirrors packages/dds/sequence: `SharedSegmentSequence`
(src/sequence.ts:112, processCore :620) binds a merge-tree replica
(core.mergetree.MergeTreeEngine — the reference's Client, client.ts:98)
behind the channel seam; `SharedString` (src/sharedString.ts:169) is
its text specialization; `IntervalCollection`
(src/intervalCollection.ts:1436) stores anchored ranges whose endpoints
are merge-tree local references that slide on remove.

Channel op encoding (`contents` of the channel-level message):
- {"kind": "seq", "op": <MergeTreeOp>} — merge-tree delta
- {"kind": "intervals", "collection": name, "op": {...}} — interval ops

The high-throughput sequenced-replay path for this DDS is the TPU
kernel (ops.mergetree_kernel via core.columnar_replay); this class is
the interactive collaborating replica (local edits, acks, references),
host-side by design like the reference's Client.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..core.mergetree import LocalReference, MergeTreeEngine, apply_remote_op
from ..protocol.constants import NON_COLLAB_CLIENT, UNASSIGNED_SEQ, UNIVERSAL_SEQ
from ..protocol.mergetree_ops import (
    AnnotateOp,
    GroupOp,
    InsertOp,
    MergeTreeOp,
    RemoveOp,
    op_from_json,
    op_to_json,
)
from ..protocol.messages import SequencedMessage
from ..runtime.channel import ChannelFactory, ChannelStorage
from ..runtime.shared_object import SharedObject
from ..runtime.summary import SummaryTreeBuilder


@dataclass
class Marker:
    """An atomic length-1 non-text segment (reference Marker,
    mergeTreeNodes.ts:557): an anchor/boundary with properties."""

    ref_type: int = 0
    props: Optional[dict] = None

    def __len__(self) -> int:
        return 1

    def __getitem__(self, i):  # slicing never splits a length-1 segment
        return self


class SharedSegmentSequence(SharedObject):
    """Base sequence DDS (reference SharedSegmentSequence,
    sequence.ts:112)."""

    def initialize_local_core(self) -> None:
        self.engine = MergeTreeEngine(local_client_id=NON_COLLAB_CLIENT)
        self._collections: Dict[str, IntervalCollection] = {}

    def on_connected(self) -> None:
        # Adopt the session identity: local ops now ride the pending/ack
        # path (reference Client.startOrUpdateCollaboration).
        cid = self.runtime.client_id
        assert cid is not None
        self.engine.local_client_id = cid
        self.engine.collaborating = True
        self.engine.current_seq = self.runtime.container.current_seq

    # ------------------------------------------------------------ queries

    def get_length(self) -> int:
        return self.engine.visible_length(
            self.engine.current_seq, self.engine.local_client_id
        )

    # -------------------------------------------------------- local edits

    def _submit_seq_op(self, op: MergeTreeOp) -> None:
        # Local metadata = the engine's pending group for this op, so
        # the reconnect path can rebase (regeneratePendingOp).
        grp = self.engine.pending[-1] if self.engine.pending else None
        self.submit_local_message({"kind": "seq", "op": op}, grp)

    def rollback(self, content: Any, local_metadata: Any) -> None:
        """Undo a just-applied local sequence op (orderSequentially
        abort; reference revertSharedStringRevertibles path over
        MergeTree.rollback, mergeTree.ts:2057). `local_metadata` is
        the op's pending group."""
        if content.get("kind") != "seq" or local_metadata is None:
            raise NotImplementedError(
                "rollback supports sequence ops with pending metadata"
            )
        grps = (
            local_metadata
            if isinstance(local_metadata, list) else [local_metadata]
        )
        for grp in reversed(grps):
            self.engine.rollback(grp)

    def resubmit(self, content: Any, local_metadata: Any) -> None:
        """Reconnect replay: rebase the pending op against current
        state before resubmitting (reference reSubmitCore →
        Client.regeneratePendingOp, client.ts:917).

        `local_metadata` is the pending group backing the message, or
        the *list* of groups a previous reconnect's regeneration split
        it into; the resubmitted message's metadata is always the
        replacement group list returned by `regenerate_pending`, so
        membership checks stay valid across repeated reconnects."""
        if not (isinstance(content, dict) and content.get("kind") == "seq"):
            self.submit_local_message(content, local_metadata)
            return
        grps = local_metadata if isinstance(local_metadata, list) else (
            [] if local_metadata is None else [local_metadata]
        )
        op = content["op"]
        if isinstance(op, dict):
            op = op_from_json(op)
        # regenerate_pending skips groups no longer pending (sequenced
        # during catch-up) and returns (None, []) when nothing remains.
        regenerated, new_groups = self.engine.regenerate_pending(grps, op)
        if regenerated is not None:
            self.submit_local_message(
                {"kind": "seq", "op": regenerated}, new_groups
            )

    def _local_perspective(self):
        return self.engine.current_seq, self.engine.local_client_id

    def _insert(self, pos: int, content: Any, props: Optional[dict]):
        if self.engine.collaborating:
            seg = self.engine.insert(
                pos, content, self.engine.current_seq,
                self.engine.local_client_id, UNASSIGNED_SEQ, props=props,
            )
        else:  # detached: applies as pre-collaboration content
            return self.engine.insert(
                pos, content, UNIVERSAL_SEQ, NON_COLLAB_CLIENT,
                UNIVERSAL_SEQ, props=props,
            )
        if isinstance(content, str):
            op = InsertOp(pos=pos, text=content, props=props)
        else:
            op = InsertOp(pos=pos, seg=content, props=props)
        self._submit_seq_op(op)
        self.emit("sequenceDelta", op, True)
        return seg

    def remove_range(self, start: int, end: int) -> None:
        if self.engine.collaborating:
            self.engine.remove_range(
                start, end, self.engine.current_seq,
                self.engine.local_client_id, UNASSIGNED_SEQ,
            )
            self._submit_seq_op(RemoveOp(start=start, end=end))
            self.emit("sequenceDelta", RemoveOp(start=start, end=end), True)
        else:
            self.engine.remove_range(
                start, end, UNIVERSAL_SEQ, NON_COLLAB_CLIENT, UNIVERSAL_SEQ
            )

    def annotate_range(self, start: int, end: int, props: dict) -> None:
        if self.engine.collaborating:
            self.engine.annotate_range(
                start, end, props, self.engine.current_seq,
                self.engine.local_client_id, UNASSIGNED_SEQ,
            )
            self._submit_seq_op(AnnotateOp(start=start, end=end, props=dict(props)))
            self.emit("sequenceDelta", AnnotateOp(start=start, end=end, props=props), True)
        else:
            self.engine.annotate_range(
                start, end, props, UNIVERSAL_SEQ, NON_COLLAB_CLIENT, UNIVERSAL_SEQ
            )

    # ---------------------------------------------------- inbound routing

    def process_core(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        contents = msg.contents
        kind = contents["kind"]
        if kind == "seq":
            op = contents["op"]
            if isinstance(op, dict):  # wire-decoded form
                op = op_from_json(op)
            if local:
                self._ack(op, msg.sequence_number)
            else:
                apply_remote_op(
                    self.engine, op, msg.ref_seq, msg.client_id,
                    msg.sequence_number,
                )
                self.emit("sequenceDelta", op, False)
        elif kind == "intervals":
            coll = self.get_interval_collection(contents["collection"])
            coll._process(contents["op"], msg, local)
        else:  # pragma: no cover
            raise ValueError(f"unknown sequence op kind {kind!r}")
        # Advance the collaboration window (Client.applyMsg tail,
        # client.ts:877).
        self.engine.current_seq = msg.sequence_number
        self.engine.update_min_seq(
            max(self.engine.min_seq, msg.minimum_sequence_number)
        )

    def _ack(self, op: MergeTreeOp, seq: int) -> None:
        if isinstance(op, GroupOp):
            for _ in op.ops:
                self.engine.ack(seq)
        else:
            self.engine.ack(seq)

    def apply_stashed_op(self, content: Any) -> Any:
        if content["kind"] == "intervals":
            # Re-apply as a fresh pending local interval op. The
            # original id is safe to keep: it embeds the stashed
            # session's client id (collision-free with this session's
            # fresh ids) and never sequenced anywhere.
            coll = self.get_interval_collection(content["collection"])
            iop = content["op"]
            kind = iop["type"]
            if kind == "add":
                ss = iop.get("startSide", SIDE_BEFORE)
                es = iop.get("endSide", SIDE_BEFORE)
                # Sides must survive rehydration: the resubmitted op
                # carries them, so the local anchors must match what
                # every remote replica will anchor.
                s_ref, e_ref = coll._anchor_local(
                    iop["start"], iop["end"], ss, es
                )
                coll._set_interval(iop["id"], SequenceInterval(
                    iop["id"], s_ref, e_ref, dict(iop.get("props") or {}),
                    start_side=ss, end_side=es,
                ))
                coll._pending[iop["id"]] = coll._pending.get(iop["id"], 0) + 1
                coll._submit(dict(iop))
            elif kind == "change":
                if iop["id"] in coll.intervals:
                    coll.change(iop["id"], iop["start"], iop["end"])
            elif kind == "delete":
                coll.remove_interval_by_id(iop["id"])
            return None
        op = content["op"]
        if isinstance(op, dict):
            op = op_from_json(op)
        # Re-apply as a fresh pending local op (client.ts:831
        # applyStashedOp): positions were recorded at the stashed
        # session's perspective which the rehydrated state reproduces.
        if isinstance(op, InsertOp):
            self._insert(op.pos, op.text if op.seg is None else op.seg, op.props)
        elif isinstance(op, RemoveOp):
            self.remove_range(op.start, op.end)
        elif isinstance(op, AnnotateOp):
            self.annotate_range(op.start, op.end, op.props)
        return None

    # --------------------------------------------------------- intervals

    def get_interval_collection(self, name: str) -> "IntervalCollection":
        if name not in self._collections:
            self._collections[name] = IntervalCollection(self, name)
        return self._collections[name]

    # --------------------------------------------------------- summaries

    def summarize_core(self):
        """Chunked segment snapshot (reference SnapshotV1 header +
        body chunks, snapshotV1.ts:30; chunk size :37). Segments inside
        the collab window persist their merge info
        (IJSONSegmentWithMergeInfo, snapshotChunks.ts:48)."""
        header = {
            "currentSeq": self.engine.current_seq,
            "minSeq": self.engine.min_seq,
            "intervals": {
                name: coll._to_serializable()
                for name, coll in self._collections.items()
            },
        }
        segs = []
        for s in self.engine.segments:
            row: Dict[str, Any] = {}
            if isinstance(s.content, Marker):
                row["marker"] = {"refType": s.content.ref_type, "props": s.content.props}
            elif isinstance(s.content, str):
                row["text"] = s.content
            else:
                row["items"] = list(s.content)
            if s.props:
                row["props"] = dict(s.props)
            # Merge info for unsettled segments (in collab window).
            if s.seq not in (UNIVERSAL_SEQ,) or s.removed_seq is not None:
                row["seq"] = s.seq
                row["client"] = s.client_id
                if s.removed_seq is not None:
                    row["removedSeq"] = s.removed_seq
                    row["removedClients"] = list(s.removed_clients)
            segs.append(row)
        builder = SummaryTreeBuilder().add_json_blob("header", header)
        chunk_size = 10_000  # snapshotV1.ts:37
        chunk, chunks, size = [], [], 0
        for row in segs:
            chunk.append(row)
            size += len(row.get("text", "x"))
            if size >= chunk_size:
                chunks.append(chunk)
                chunk, size = [], 0
        if chunk or not chunks:
            chunks.append(chunk)
        for i, c in enumerate(chunks):
            builder.add_json_blob(f"body_{i}", c)
        builder.add_json_blob("chunkCount", len(chunks))
        return builder.summary

    def load_core(self, storage: ChannelStorage) -> None:
        self.initialize_local_core()
        header = json.loads(storage.read("header"))
        self.engine.current_seq = header["currentSeq"]
        self.engine.min_seq = header["minSeq"]
        from ..core.mergetree import Segment

        n_chunks = json.loads(storage.read("chunkCount"))
        for i in range(n_chunks):
            for row in json.loads(storage.read(f"body_{i}")):
                if "marker" in row:
                    content: Any = Marker(
                        ref_type=row["marker"]["refType"],
                        props=row["marker"]["props"],
                    )
                elif "text" in row:
                    content = row["text"]
                else:
                    content = list(row["items"])
                seg = Segment(
                    content=content,
                    seq=row.get("seq", UNIVERSAL_SEQ),
                    client_id=row.get("client", NON_COLLAB_CLIENT),
                    props=row.get("props"),
                )
                if "removedSeq" in row:
                    seg.removed_seq = row["removedSeq"]
                    seg.removed_clients = list(row["removedClients"])
                self.engine.segments.append(seg)
        for name, data in header.get("intervals", {}).items():
            coll = self.get_interval_collection(name)
            coll._load(data)


class SharedString(SharedSegmentSequence):
    """Collaborative text (reference SharedString, sharedString.ts)."""

    def insert_text(self, pos: int, text: str, props: Optional[dict] = None):
        return self._insert(pos, text, props)

    def remove_text(self, start: int, end: int) -> None:
        self.remove_range(start, end)

    def insert_marker(self, pos: int, ref_type: int = 0,
                      props: Optional[dict] = None) -> None:
        self._insert(pos, Marker(ref_type=ref_type, props=props), None)

    def get_text(self) -> str:
        parts = []
        for seg in self.engine.segments:
            if seg.removed_seq is None and isinstance(seg.content, str):
                parts.append(seg.content)
        return "".join(parts)

    def get_markers(self) -> List[Marker]:
        return [
            s.content
            for s in self.engine.segments
            if s.removed_seq is None and isinstance(s.content, Marker)
        ]

    def annotated_spans(self):
        return self.engine.annotated_spans()

    # ----------------------------------------------------- attribution

    def enable_attribution(self) -> None:
        """Track per-position insert attribution (attribution key =
        insert seq; attributionPolicy.ts role). Resolve keys to
        {client, timestamp} through a `framework.attributor.Attributor`
        observing the same op stream."""
        self.engine.enable_attribution()

    def attribution_spans(self):
        """(run_length, attribution key) runs over the visible text."""
        return self.engine.attribution_spans()

    def attribution_at(self, pos: int) -> int:
        """Attribution key of the character at visible position `pos`
        (0 = initial content, UNASSIGNED_SEQ = pending local)."""
        off = pos
        for ln, key in self.engine.attribution_spans():
            if off < ln:
                return key
            off -= ln
        raise IndexError(f"position {pos} beyond visible length")


class StringFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/mergeTree"
    channel_class = SharedString


class SequenceFactory(StringFactory):
    """Alias factory matching the reference's SharedString factory id."""


# ---------------------------------------------------------------------------
# Interval collections
# ---------------------------------------------------------------------------


SIDE_BEFORE = "before"
SIDE_AFTER = "after"


@dataclass
class SequenceInterval:
    """An anchored range (reference SequenceInterval,
    intervalCollection.ts:404): endpoints are merge-tree local
    references that slide on remove.

    Endpoint SIDEDNESS (reference Side/stickiness,
    sequencePlace.ts / intervalCollection.ts): a `before` endpoint
    anchors to the character AT the position, so concurrent inserts
    at the boundary push it along (the interval expands); an `after`
    endpoint anchors to the PREVIOUS character and resolves one past
    it, so boundary inserts land outside (the interval does not
    expand). (start=before, end=after) is "full stickiness" for
    exclusive-end ranges."""

    interval_id: str
    start_ref: LocalReference
    end_ref: LocalReference
    props: Dict[str, Any] = field(default_factory=dict)
    start_side: str = SIDE_BEFORE
    end_side: str = SIDE_BEFORE

    def bounds(self, engine: MergeTreeEngine):
        # After-ness lives on the references themselves (set at anchor
        # time, cleared when a removal slides them), so degraded
        # anchors (after at position 0) and slid anchors resolve
        # correctly; the declared sides only drive (re)anchoring.
        return (
            engine.resolve_reference(self.start_ref),
            engine.resolve_reference(self.end_ref),
        )


class _IntervalIndex:
    """INCREMENTAL augmented interval index (the
    findOverlappingIntervals role, intervalCollection.ts:958 backed
    by the reference's IntervalTree over LocalReferencePositions).

    The key insight the reference exploits: anchored references keep
    a STABLE total order under every sequence edit — segments never
    reorder, splits preserve (segment, offset) order, and slides are
    monotone — so an index sorted by reference order NEVER needs
    maintenance when the sequence changes. Rows sort by the start
    reference's stable order with a prefix-max of end references (the
    tree augment), also by stable order, so it stays a valid
    prefix-max forever. Sequence edits cost ZERO index work; interval
    add/change/delete costs one O(n) array splice + suffix-max
    refresh; queries resolve only the O(log n) probed endpoints plus
    the candidate walk — never all n (the former design re-resolved
    and re-sorted every endpoint on each engine version bump)."""

    def __init__(self):
        self.rows: List[SequenceInterval] = []  # sorted by start ref
        self.maxend: List[LocalReference] = []  # prefix max (stable order)
        self._ord_cache: dict = {}
        self._ord_version: Optional[tuple] = None
        self._slide_seen: int = -1

    # ------------------------------------------------------ stable order

    def _ordinals(self, engine) -> dict:
        """id(segment) -> document ordinal, cached per engine
        structure version (one O(S) pass amortized over a mutation
        burst instead of an O(S) list scan PER key comparison)."""
        ver = (
            getattr(engine, "structure_version", None),
            len(engine.segments),
        )
        if self._ord_version != ver:
            self._ord_cache = {
                id(seg): i for i, seg in enumerate(engine.segments)
            }
            self._ord_version = ver
        return self._ord_cache

    def _stable_key(self, ref, engine):
        """Total order on references that future edits preserve:
        (segment document index, offset, after). End-of-document
        references order after everything."""
        if ref.segment is None:
            return (1 << 60, 0, 0)
        si = self._ordinals(engine).get(id(ref.segment), 1 << 60)
        return (si, ref.offset, 1 if ref.after else 0)

    # -------------------------------------------------------- mutation

    def insert(self, iv: "SequenceInterval", engine) -> None:
        key = self._stable_key(iv.start_ref, engine)
        lo, hi = 0, len(self.rows)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._stable_key(self.rows[mid].start_ref, engine) <= key:
                lo = mid + 1
            else:
                hi = mid
        self.rows.insert(lo, iv)
        self._refresh_maxend(lo, engine)

    def remove(self, iid: str, engine) -> None:
        for i, r in enumerate(self.rows):
            if r.interval_id == iid:
                del self.rows[i]
                self._refresh_maxend(i, engine)
                return

    def _refresh_maxend(self, i: int, engine) -> None:
        """Recompute the prefix-max suffix from row i (stable-order
        comparisons, so the prefix-max stays valid under all later
        sequence edits)."""
        del self.maxend[i:]
        m = self.maxend[-1] if self.maxend else None
        m_key = (
            self._stable_key(m, engine) if m is not None
            else (-1, -1, -1)
        )
        for r in self.rows[i:]:
            k = self._stable_key(r.end_ref, engine)
            if k >= m_key:
                m, m_key = r.end_ref, k
            self.maxend.append(m)

    def _repair_after_slides(self, engine) -> None:
        """Reference slides are order-stable EXCEPT when a slide
        skips pending-local segments (excluded slide targets),
        carrying a reference past ones anchored on them. When the
        engine's slide version changes, verify sortedness by stable
        key (O(n) cached-ordinal comparisons, zero resolutions) and
        re-sort + rebuild the prefix-max only if actually violated."""
        ver = getattr(engine, "slide_version", 0)
        if ver == self._slide_seen:
            return
        self._slide_seen = ver
        keys = [self._stable_key(r.start_ref, engine) for r in self.rows]
        if all(keys[i] <= keys[i + 1] for i in range(len(keys) - 1)):
            # Order intact; the prefix-max may still be stale (an END
            # reference slid): rebuild it (cheap, key-only).
            self._refresh_maxend(0, engine)
            return
        self.rows = [
            r for _, r in sorted(
                zip(keys, self.rows), key=lambda t: t[0]
            )
        ]
        self._refresh_maxend(0, engine)

    # ----------------------------------------------------------- query

    def query(self, start: int, end: int, engine) -> List[str]:
        """Ids of intervals [s, e] with s <= end and e >= start, in
        start order. Stable order implies resolved positions are
        monotone over the arrays, so both bounds binary-search with
        O(log n) resolutions; maxend prunes whole prefixes whose
        intervals all end before `start`."""
        self._repair_after_slides(engine)
        pos = engine.resolve_reference
        # hi: first row whose start resolves past `end`.
        lo_, hi_ = 0, len(self.rows)
        while lo_ < hi_:
            mid = (lo_ + hi_) // 2
            if pos(self.rows[mid].start_ref) <= end:
                lo_ = mid + 1
            else:
                hi_ = mid
        hi = lo_
        # lo: first row whose prefix-max end reaches `start`.
        lo_, hi2 = 0, hi
        while lo_ < hi2:
            mid = (lo_ + hi2) // 2
            if pos(self.maxend[mid]) < start:
                lo_ = mid + 1
            else:
                hi2 = mid
        out: List[str] = []
        for r in self.rows[lo_:hi]:
            if pos(r.end_ref) >= start:
                out.append(r.interval_id)
        return out


class IntervalCollection:
    """A named set of intervals over one sequence (reference
    IntervalCollection, intervalCollection.ts:1436).

    Conflict policy: whole-interval last-writer-wins with
    pending-local shadowing (the defaultMap kernel the reference
    stores interval values in, dds/sequence/src/defaultMap.ts).
    """

    def __init__(self, sequence: SharedSegmentSequence, name: str):
        self.sequence = sequence
        self.name = name
        self.intervals: Dict[str, SequenceInterval] = {}
        self._pending: Dict[str, int] = {}
        self._pending_props: Dict[Tuple[str, str], int] = {}
        self._next_local_id = 0
        self._index = _IntervalIndex()

    # Every interval-set mutation flows through these two, keeping the
    # incremental index in lock-step with the dict.

    def _set_interval(self, iid: str, iv: "SequenceInterval") -> None:
        eng = self.sequence.engine
        if iid in self.intervals:
            self._index.remove(iid, eng)
        self.intervals[iid] = iv
        self._index.insert(iv, eng)

    def _drop_interval(self, iid: str):
        iv = self.intervals.pop(iid, None)
        if iv is not None:
            self._index.remove(iid, self.sequence.engine)
        return iv

    # ----------------------------------------------------------- local API

    def _submit(self, op: dict) -> None:
        self.sequence.submit_local_message(
            {"kind": "intervals", "collection": self.name, "op": op}
        )

    def _anchor(self, pos: int, side: str, ref_seq: int, cid: int):
        """Anchor one endpoint honoring its side: `after` anchors to
        the previous character with the reference's after flag set
        (resolution adds 1 back while the char is visible), so
        boundary inserts land outside the interval; position 0
        degrades to `before` (there is no previous character)."""
        eng = self.sequence.engine
        if side == SIDE_AFTER and pos > 0:
            return eng.anchor_at(pos - 1, ref_seq, cid, after=True)
        return eng.anchor_at(pos, ref_seq, cid)

    def _anchor_local(self, start: int, end: int,
                      start_side: str = SIDE_BEFORE,
                      end_side: str = SIDE_BEFORE):
        eng = self.sequence.engine
        ref_seq, cid = eng.current_seq, eng.local_client_id
        return (
            self._anchor(start, start_side, ref_seq, cid),
            self._anchor(end, end_side, ref_seq, cid),
        )

    def add(self, start: int, end: int, props: Optional[dict] = None,
            start_side: str = SIDE_BEFORE,
            end_side: str = SIDE_BEFORE) -> SequenceInterval:
        self._next_local_id += 1
        iid = f"{self.sequence.engine.local_client_id}-{self._next_local_id}"
        s_ref, e_ref = self._anchor_local(start, end, start_side, end_side)
        iv = SequenceInterval(
            iid, s_ref, e_ref, dict(props or {}),
            start_side=start_side, end_side=end_side,
        )
        self._set_interval(iid, iv)
        self._pending[iid] = self._pending.get(iid, 0) + 1
        self._submit(
            {"type": "add", "id": iid, "start": start, "end": end,
             "props": props or {}, "startSide": start_side,
             "endSide": end_side}
        )
        return iv

    def change(self, iid: str, start: int, end: int) -> None:
        iv = self.intervals[iid]
        iv.start_ref.detach()
        iv.end_ref.detach()
        iv.start_ref, iv.end_ref = self._anchor_local(
            start, end, iv.start_side, iv.end_side
        )
        self._set_interval(iid, iv)  # endpoints moved: re-place in index
        self._pending[iid] = self._pending.get(iid, 0) + 1
        self._submit({"type": "change", "id": iid, "start": start, "end": end})

    def change_properties(self, iid: str, props: Dict[str, Any]) -> None:
        """Per-KEY last-writer-wins property merge with pending-local
        shadowing (the reference's propertyManager on intervals /
        defaultMap kernel semantics): `None` deletes a key."""
        iv = self.intervals[iid]
        for k, v in props.items():
            if v is None:
                iv.props.pop(k, None)
            else:
                iv.props[k] = v
            pk = (iid, k)
            self._pending_props[pk] = self._pending_props.get(pk, 0) + 1
        self._submit({"type": "props", "id": iid, "props": dict(props)})

    def remove_interval_by_id(self, iid: str) -> None:
        iv = self._drop_interval(iid)
        if iv is not None:
            iv.start_ref.detach()
            iv.end_ref.detach()
        self._pending[iid] = self._pending.get(iid, 0) + 1
        self._submit({"type": "delete", "id": iid})

    def get_interval_by_id(self, iid: str) -> Optional[SequenceInterval]:
        return self.intervals.get(iid)

    def __iter__(self) -> Iterator[SequenceInterval]:
        return iter(self.intervals.values())

    def __len__(self) -> int:
        return len(self.intervals)

    # -------------------------------------------------------------- queries

    def find_overlapping_intervals(
        self, start: int, end: int
    ) -> List[SequenceInterval]:
        """Intervals whose resolved range [s, e] intersects
        [start, end] (findOverlappingIntervals,
        intervalCollection.ts:958,2312), via the lazily rebuilt
        sorted-endpoint index — O(log n + candidates) per query
        between mutations, not an O(n) interval scan."""
        eng = self.sequence.engine
        # Every index row id is in the dict by construction
        # (_set_interval/_drop_interval keep them in lock-step); a
        # KeyError here means the invariant broke — surface it loudly.
        return [
            self.intervals[iid]
            for iid in self._index.query(start, end, eng)
        ]

    # -------------------------------------------------------------- apply

    def _process(self, op: dict, msg: SequencedMessage, local: bool) -> None:
        iid = op["id"]
        kind = op["type"]
        if kind == "props":
            self._process_props(op, local)
            return
        if local:
            n = self._pending.get(iid, 0) - 1
            if n <= 0:
                self._pending.pop(iid, None)
            else:
                self._pending[iid] = n
            return
        if self._pending.get(iid, 0) > 0:
            return  # pending local change shadows the remote one
        eng = self.sequence.engine
        if kind == "delete":
            iv = self._drop_interval(iid)
            if iv is not None:
                iv.start_ref.detach()
                iv.end_ref.detach()
            return
        # Anchor at the op's perspective — every replica resolves the
        # same segments (merge-tree remote-perspective contract) —
        # honoring the interval's endpoint sides.
        if kind == "add":
            ss = op.get("startSide", SIDE_BEFORE)
            es = op.get("endSide", SIDE_BEFORE)
        else:
            iv0 = self.intervals.get(iid)
            ss = iv0.start_side if iv0 is not None else SIDE_BEFORE
            es = iv0.end_side if iv0 is not None else SIDE_BEFORE
        rs, cid = msg.ref_seq, msg.client_id
        s_ref = self._anchor(op["start"], ss, rs, cid)
        e_ref = self._anchor(op["end"], es, rs, cid)
        if kind == "add":
            self._set_interval(iid, SequenceInterval(
                iid, s_ref, e_ref, dict(op.get("props") or {}),
                start_side=ss, end_side=es,
            ))
        elif kind == "change":
            iv = self.intervals.get(iid)
            if iv is None:
                s_ref.detach()
                e_ref.detach()
                return
            iv.start_ref.detach()
            iv.end_ref.detach()
            iv.start_ref, iv.end_ref = s_ref, e_ref
            self._set_interval(iid, iv)  # endpoints moved: re-place

    def _process_props(self, op: dict, local: bool) -> None:
        """Per-key LWW with pending shadowing; sequenced remote writes
        on keys with outstanding local writes are shadowed (the local
        value rewins when its own op sequences)."""
        iid = op["id"]
        if local:
            for k in op["props"]:
                pk = (iid, k)
                n = self._pending_props.get(pk, 0) - 1
                if n <= 0:
                    self._pending_props.pop(pk, None)
                else:
                    self._pending_props[pk] = n
            return
        iv = self.intervals.get(iid)
        if iv is None:
            return
        for k, v in op["props"].items():
            if self._pending_props.get((iid, k), 0) > 0:
                continue
            if v is None:
                iv.props.pop(k, None)
            else:
                iv.props[k] = v

    # ---------------------------------------------------------- summaries

    def _to_serializable(self) -> list:
        # Store LOGICAL endpoint positions (bounds), not raw anchor
        # positions: _load re-applies the side adjustment when it
        # re-anchors, so storing anchors would shift after-endpoints
        # by one on every summarize/load cycle.
        eng = self.sequence.engine
        rows = []
        for iv in self.intervals.values():
            s, e = iv.bounds(eng)
            rows.append(
                {
                    "id": iv.interval_id,
                    "start": s,
                    "end": e,
                    "props": iv.props,
                    "startSide": iv.start_side,
                    "endSide": iv.end_side,
                }
            )
        return rows

    def _load(self, data: list) -> None:
        eng = self.sequence.engine
        for row in data:
            ss = row.get("startSide", SIDE_BEFORE)
            es = row.get("endSide", SIDE_BEFORE)
            s_ref = self._anchor(
                row["start"], ss, eng.current_seq, eng.local_client_id
            )
            e_ref = self._anchor(
                row["end"], es, eng.current_seq, eng.local_client_id
            )
            self._set_interval(row["id"], SequenceInterval(
                row["id"], s_ref, e_ref, dict(row.get("props") or {}),
                start_side=ss, end_side=es,
            ))
