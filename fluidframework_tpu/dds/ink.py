"""SharedInk: append-only ink strokes.

Reference packages/dds/ink/src/ink.ts:103: strokes are created with a
pen and extended point-by-point; all ops commute per-stroke (points
append in sequence order), so there is no conflict policy beyond the
total order.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ..protocol.messages import SequencedMessage
from ..runtime.channel import ChannelFactory, ChannelStorage
from ..runtime.shared_object import SharedObject
from ..runtime.summary import SummaryTreeBuilder


class SharedInk(SharedObject):
    def initialize_local_core(self) -> None:
        self.strokes: Dict[str, dict] = {}  # id -> {"pen", "points"}
        self._order: List[str] = []
        self._next_local = 0

    def create_stroke(self, pen: Optional[dict] = None) -> str:
        self._next_local += 1
        stroke_id = f"{self.runtime.client_id or 'detached'}-{self._next_local}"
        self._apply_create(stroke_id, pen or {})
        self.submit_local_message(
            {"type": "createStroke", "id": stroke_id, "pen": pen or {}}
        )
        return stroke_id

    def append_point(self, stroke_id: str, x: float, y: float,
                     pressure: float = 1.0) -> None:
        point = {"x": x, "y": y, "pressure": pressure}
        self.strokes[stroke_id]["points"].append(point)
        self.submit_local_message(
            {"type": "stylus", "id": stroke_id, "point": point}
        )

    def get_stroke(self, stroke_id: str) -> dict:
        return self.strokes[stroke_id]

    def get_strokes(self) -> List[dict]:
        return [self.strokes[s] for s in self._order]

    def _apply_create(self, stroke_id: str, pen: dict) -> None:
        if stroke_id not in self.strokes:
            self.strokes[stroke_id] = {"id": stroke_id, "pen": pen, "points": []}
            self._order.append(stroke_id)

    def process_core(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        if local:
            return  # applied optimistically (all ink ops commute)
        op = msg.contents
        if op["type"] == "createStroke":
            self._apply_create(op["id"], op["pen"])
        elif op["type"] == "stylus":
            if op["id"] in self.strokes:
                self.strokes[op["id"]]["points"].append(op["point"])
        self.emit("ink", op)

    def apply_stashed_op(self, content: Any) -> Any:
        op = content
        if op["type"] == "createStroke":
            self._apply_create(op["id"], op["pen"])
            self.submit_local_message(op)
        else:
            self.append_point(op["id"], **op["point"])
        return None

    def summarize_core(self):
        return (
            SummaryTreeBuilder()
            .add_json_blob(
                "header",
                {"order": self._order, "strokes": self.strokes},
            )
            .summary
        )

    def load_core(self, storage: ChannelStorage) -> None:
        self.initialize_local_core()
        data = json.loads(storage.read("header"))
        self._order = data["order"]
        self.strokes = data["strokes"]


class InkFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/ink"
    channel_class = SharedInk
