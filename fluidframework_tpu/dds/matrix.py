"""SharedMatrix: a collaborative 2D grid.

Mirrors packages/dds/matrix (src/matrix.ts:80): the row order and the
column order are each a *merge-tree replica* over opaque handles
(`PermutationVector extends Client`, src/permutationvector.ts:151) —
inserting/removing rows or columns is a sequence insert/remove, reusing
all of the merge-tree's conflict resolution; cells live in a sparse
store keyed by (row_handle, col_handle) (src/sparsearray2d.ts:57) so
cell values survive row/column moves without rewrites.

Handles are replica-local storage names (each replica allocates its
own); convergence is judged on the (position → value) mapping, exactly
as the reference.

setCell conflict policy: last sequenced writer wins with pending-local
shadowing per cell (reference matrix conflict-resolution; the
productSet/bspSet machinery for undo-aware set semantics is not yet
ported — see framework undo-redo task).

Wire ops (`contents`):
- {"type": "insertRows"/"removeRows"/"insertCols"/"removeCols",
   "pos": p, "count": n}
- {"type": "setCell", "row": r, "col": c, "value": v}  (positions at
   the sender's perspective)
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from ..core.mergetree import MergeTreeEngine, Segment, VisCategory
from ..protocol.constants import NON_COLLAB_CLIENT, UNASSIGNED_SEQ, UNIVERSAL_SEQ
from ..protocol.messages import SequencedMessage
from ..runtime.channel import ChannelFactory, ChannelStorage
from ..runtime.shared_object import SharedObject
from ..runtime.summary import SummaryTreeBuilder


class PermutationVector:
    """One axis's order: a merge-tree over handle items
    (reference PermutationVector, permutationvector.ts:151). Runs on
    the native C++ engine when available (core/native_engine.py — the
    interactive hot path, BENCH_DETAIL config 3), falling back to the
    Python oracle engine."""

    def __init__(self):
        from ..core.native_engine import make_merge_engine

        self.engine = make_merge_engine(NON_COLLAB_CLIENT)
        self._next_handle = 0

    def alloc(self, count: int) -> List[int]:
        out = list(range(self._next_handle, self._next_handle + count))
        self._next_handle += count
        return out

    # ---- perspective-resolved queries

    def handle_at(self, pos: int, ref_seq: int, client_id: int) -> int:
        """The handle at visible position `pos` of a perspective."""
        eng = self.engine
        if hasattr(eng, "item_at"):
            return eng.item_at(pos, ref_seq, client_id)
        remaining = pos
        for seg in eng.segments:
            cat, length = eng._vis(seg, ref_seq, client_id)
            if cat == VisCategory.SKIP or length == 0:
                continue
            if remaining < length:
                return seg.content[remaining]
            remaining -= length
        raise IndexError(f"position {pos} beyond visible length")

    def local_handle_at(self, pos: int) -> int:
        return self.handle_at(
            pos, self.engine.current_seq, self.engine.local_client_id
        )

    def length(self) -> int:
        return self.engine.visible_length(
            self.engine.current_seq, self.engine.local_client_id
        )

    def handles(self) -> List[int]:
        return self.engine.get_items()

    def position_of_handle(self, handle: int) -> Optional[int]:
        """Current local visible position of a handle, or None if its
        row/col is no longer visible."""
        eng = self.engine
        if hasattr(eng, "position_of_item"):
            return eng.position_of_item(
                handle, eng.current_seq, eng.local_client_id
            )
        pos = 0
        for seg in eng.segments:
            cat, length = eng._vis(
                seg, eng.current_seq, eng.local_client_id
            )
            if cat == VisCategory.SKIP or length == 0:
                continue
            if handle in seg.content:
                return pos + seg.content.index(handle)
            pos += length
        return None


class SharedMatrix(SharedObject):
    def initialize_local_core(self) -> None:
        self.rows = PermutationVector()
        self.cols = PermutationVector()
        self._cells: Dict[Tuple[int, int], Any] = {}
        self._pending_cells: Dict[Tuple[int, int], int] = {}

    def on_connected(self) -> None:
        cid = self.runtime.client_id
        for pv in (self.rows, self.cols):
            pv.engine.local_client_id = cid
            pv.engine.collaborating = True
            pv.engine.current_seq = self.runtime.container.current_seq

    # --------------------------------------------------------------- shape

    @property
    def row_count(self) -> int:
        return self.rows.length()

    @property
    def col_count(self) -> int:
        return self.cols.length()

    def _axis_insert(self, pv: PermutationVector, pos: int, count: int, op_type: str) -> None:
        handles = pv.alloc(count)
        eng = pv.engine
        if eng.collaborating:
            eng.insert(pos, handles, eng.current_seq, eng.local_client_id, UNASSIGNED_SEQ)
            self.submit_local_message(
                {"type": op_type, "pos": pos, "count": count},
                {"axis": "rows" if pv is self.rows else "cols",
                 "group": eng.pending[-1]},
            )
        else:
            eng.insert(pos, handles, UNIVERSAL_SEQ, NON_COLLAB_CLIENT, UNIVERSAL_SEQ)
        self.emit(
            "localAxisInsert",
            "rows" if pv is self.rows else "cols", handles,
        )

    def _axis_remove(self, pv: PermutationVector, pos: int, count: int, op_type: str) -> None:
        axis = "rows" if pv is self.rows else "cols"
        # Capture for undo (the productSet/bspSet role: removed
        # region's identity + cell payload) — one pass over the cell
        # map, not O(count x other-axis).
        handles = [pv.local_handle_at(p) for p in range(pos, pos + count)]
        hs = set(handles)
        hi = 0 if pv is self.rows else 1
        cells = {k: v for k, v in self._cells.items() if k[hi] in hs}
        eng = pv.engine
        if eng.collaborating:
            eng.remove_range(pos, pos + count, eng.current_seq, eng.local_client_id, UNASSIGNED_SEQ)
            self.submit_local_message(
                {"type": op_type, "pos": pos, "count": count},
                {"axis": axis, "group": eng.pending[-1]},
            )
        else:
            eng.remove_range(pos, pos + count, UNIVERSAL_SEQ, NON_COLLAB_CLIENT, UNIVERSAL_SEQ)
        self.emit("localAxisRemove", axis, pos, handles, cells)

    def insert_rows(self, pos: int, count: int = 1) -> None:
        self._axis_insert(self.rows, pos, count, "insertRows")

    def remove_rows(self, pos: int, count: int = 1) -> None:
        self._axis_remove(self.rows, pos, count, "removeRows")

    def insert_cols(self, pos: int, count: int = 1) -> None:
        self._axis_insert(self.cols, pos, count, "insertCols")

    def remove_cols(self, pos: int, count: int = 1) -> None:
        self._axis_remove(self.cols, pos, count, "removeCols")

    # --------------------------------------------------------------- cells

    def get_cell(self, row: int, col: int) -> Any:
        key = (self.rows.local_handle_at(row), self.cols.local_handle_at(col))
        return self._cells.get(key)

    def set_cell(self, row: int, col: int, value: Any) -> None:
        key = (self.rows.local_handle_at(row), self.cols.local_handle_at(col))
        had = key in self._cells
        prev = self._cells.get(key)
        self._cells[key] = value
        if self.rows.engine.collaborating:
            self._pending_cells[key] = self._pending_cells.get(key, 0) + 1
            self.submit_local_message(
                {"type": "setCell", "row": row, "col": col, "value": value},
                {"key": key},
            )
        self.emit("localCellSet", key, had, prev)
        self.emit("cellChanged", row, col, True)

    def set_cell_by_handle(self, key, value: Any) -> None:
        """Set a cell addressed by its stable (row, col) HANDLES —
        the undo path's addressing, immune to concurrent permutation.
        No-op if either handle's row/col is no longer visible (the
        cell died with its axis; reference matrix undo skips too)."""
        r = self.rows.position_of_handle(key[0])
        c = self.cols.position_of_handle(key[1])
        if r is None or c is None:
            return
        self.set_cell(r, c, value)

    def to_dense(self) -> List[List[Any]]:
        """The visible grid (row-major), for assertions and export."""
        rh = self.rows.handles()
        ch = self.cols.handles()
        return [[self._cells.get((r, c)) for c in ch] for r in rh]

    # --------------------------------------------------------------- apply

    def process_core(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        op = msg.contents
        kind = op["type"]
        if kind == "setCell":
            if local:
                key = local_metadata["key"]
                n = self._pending_cells.get(key, 0) - 1
                if n <= 0:
                    self._pending_cells.pop(key, None)
                else:
                    self._pending_cells[key] = n
            else:
                key = (
                    self.rows.handle_at(op["row"], msg.ref_seq, msg.client_id),
                    self.cols.handle_at(op["col"], msg.ref_seq, msg.client_id),
                )
                if self._pending_cells.get(key, 0) == 0:
                    self._cells[key] = op["value"]
                    # Event positions are RECEIVER-local (the sender's
                    # row/col indices mean nothing at this replica) —
                    # and resolving them costs two engine walks, so
                    # only do it when someone is listening.
                    if self._listeners.get("cellChanged"):
                        r = self.rows.position_of_handle(key[0])
                        c = self.cols.position_of_handle(key[1])
                        if r is not None and c is not None:
                            self.emit("cellChanged", r, c, False)
        else:
            pv = self.rows if "Rows" in kind else self.cols
            eng = pv.engine
            if local:
                eng.ack(msg.sequence_number)
            elif kind.startswith("insert"):
                eng.insert(
                    op["pos"], pv.alloc(op["count"]), msg.ref_seq,
                    msg.client_id, msg.sequence_number,
                )
            else:
                eng.remove_range(
                    op["pos"], op["pos"] + op["count"], msg.ref_seq,
                    msg.client_id, msg.sequence_number,
                )
        # Advance both axes' collaboration windows (the MSN advance —
        # which runs zamboni — only when it actually moved).
        seq = msg.sequence_number
        msn = msg.minimum_sequence_number
        for pv in (self.rows, self.cols):
            eng = pv.engine
            eng.current_seq = seq
            if msn > eng.min_seq:
                eng.update_min_seq(msn)

    def resubmit(self, content: Any, local_metadata: Any) -> None:
        """Reconnect replay with rebase: structural ops regenerate
        their positions from their pending merge-tree groups (the
        sequence DDS's regeneratePendingOp applied per axis); setCell
        re-targets by handle at the current perspective (dropped if the
        row/col has since been removed)."""
        op = content
        kind = op["type"]
        if kind == "setCell":
            key = local_metadata["key"]
            r = self.rows.position_of_handle(key[0])
            c = self.cols.position_of_handle(key[1])
            if r is None or c is None:
                # Target row/col is gone: the write is moot; clear the
                # pending shadow it held.
                n = self._pending_cells.get(key, 0) - 1
                if n <= 0:
                    self._pending_cells.pop(key, None)
                else:
                    self._pending_cells[key] = n
                return
            self.submit_local_message(
                {"type": "setCell", "row": r, "col": c, "value": op["value"]},
                local_metadata,
            )
            return
        pv = self.rows if local_metadata["axis"] == "rows" else self.cols
        grps = local_metadata["group"]
        grps = grps if isinstance(grps, list) else [grps]
        from ..protocol.mergetree_ops import GroupOp, InsertOp, RemoveOp

        regenerated, new_groups = pv.engine.regenerate_pending(
            grps,
            InsertOp(pos=op["pos"]) if kind.startswith("insert")
            else RemoveOp(start=op["pos"], end=op["pos"] + op["count"]),
        )
        if regenerated is None:
            return
        subs = regenerated.ops if isinstance(regenerated, GroupOp) else [regenerated]
        # Each regenerated sub-op submits as its own message (each pops
        # one per-segment pending group on ack), carrying ITS OWN
        # replacement group as metadata so a second reconnect can find
        # it in the pending FIFO (stale-group metadata silently dropped
        # resubmissions — advisor finding, round 1).
        for sub, g in zip(subs, new_groups):
            if isinstance(sub, InsertOp):
                mop = {"type": kind, "pos": sub.pos, "count": len(sub.seg or sub.text)}
            else:
                mop = {"type": kind, "pos": sub.start, "count": sub.end - sub.start}
            self.submit_local_message(
                mop, {"axis": local_metadata["axis"], "group": g}
            )

    def apply_stashed_op(self, content: Any) -> Any:
        op = content
        kind = op["type"]
        if kind == "setCell":
            self.set_cell(op["row"], op["col"], op["value"])
        elif kind == "insertRows":
            self.insert_rows(op["pos"], op["count"])
        elif kind == "removeRows":
            self.remove_rows(op["pos"], op["count"])
        elif kind == "insertCols":
            self.insert_cols(op["pos"], op["count"])
        elif kind == "removeCols":
            self.remove_cols(op["pos"], op["count"])
        return None

    # ----------------------------------------------------------- summaries

    def summarize_core(self):
        """Positional snapshot of the visible grid (reference matrix
        snapshot: permutation vectors + cell payload). Unsettled merge
        metadata inside the collab window is not persisted — summaries
        are taken on quiescent replicas (ContainerRuntime refuses dirty
        summarize)."""
        dense = self.to_dense()
        cells = [
            [r, c, row_vals[c]]
            for r, row_vals in enumerate(dense)
            for c in range(len(row_vals))
            if row_vals[c] is not None
        ]
        header = {
            "rowCount": self.row_count,
            "colCount": self.col_count,
            "currentSeq": self.rows.engine.current_seq,
            "minSeq": self.rows.engine.min_seq,
        }
        return (
            SummaryTreeBuilder()
            .add_json_blob("header", header)
            .add_json_blob("cells", cells)
            .summary
        )

    def load_core(self, storage: ChannelStorage) -> None:
        self.initialize_local_core()
        header = json.loads(storage.read("header"))
        for pv, n in ((self.rows, header["rowCount"]), (self.cols, header["colCount"])):
            pv.engine.current_seq = header["currentSeq"]
            pv.engine.min_seq = header["minSeq"]
            if n:
                pv.engine.load(pv.alloc(n))
        rh, ch = self.rows.handles(), self.cols.handles()
        for r, c, v in json.loads(storage.read("cells")):
            self._cells[(rh[r], ch[c])] = v


class MatrixFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/sharedmatrix"
    channel_class = SharedMatrix
