"""SharedMap and SharedDirectory: last-writer-wins key-value DDSes.

Mirrors packages/dds/map: `MapKernel` (src/mapKernel.ts:130) owns the
op apply / pending-ack bookkeeping shared by `SharedMap` (src/map.ts:92)
and each subdirectory of `SharedDirectory` (src/directory.ts:324).

Conflict policy (mapKernel.ts processMessageForKey/Clear):
- a remote write to a key with pending local writes is ignored — the
  local value rides a later sequence number and wins;
- a remote clear wipes the data but re-applies pending local values;
- acking a local op just decrements its pending count (the value was
  applied optimistically at submit time).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterator, Optional

from ..protocol.messages import SequencedMessage
from ..runtime.channel import ChannelFactory, ChannelStorage
from ..runtime.shared_object import SharedObject
from ..runtime.summary import SummaryTreeBuilder

_DELETE = object()  # pending-value sentinel: local delete in flight


class MapKernel:
    """Op apply + pending bookkeeping for one key-space
    (reference MapKernel, mapKernel.ts:130)."""

    def __init__(self, submit_fn):
        self._submit = submit_fn
        self.data: Dict[str, Any] = {}
        self._pending_keys: Dict[str, int] = {}
        self._pending_values: Dict[str, Any] = {}
        self._pending_clears = 0

    # ----------------------------------------------------------- local API

    def get(self, key: str, default: Any = None) -> Any:
        return self.data.get(key, default)

    def has(self, key: str) -> bool:
        return key in self.data

    def set(self, key: str, value: Any) -> None:
        md = self._undo_record(key)
        self.data[key] = value
        self._track_pending(key, value)
        self._submit({"type": "set", "key": key, "value": value}, md)

    def delete(self, key: str) -> bool:
        md = self._undo_record(key)
        existed = key in self.data
        self.data.pop(key, None)
        self._track_pending(key, _DELETE)
        self._submit({"type": "delete", "key": key}, md)
        return existed

    def clear(self) -> None:
        # Pending bookkeeping survives a local clear: earlier local ops
        # are still in flight and their echoes must find their counts
        # (mapKernel.ts keeps pendingKeys across clear).
        md = {"data": dict(self.data)}
        self.data.clear()
        self._pending_clears += 1
        self._submit({"type": "clear"}, md)

    def _undo_record(self, key: str) -> dict:
        return {
            "exists": key in self.data,
            "prev": self.data.get(key),
            "had_pending": key in self._pending_values,
            "prev_pending": self._pending_values.get(key),
        }

    def keys(self) -> Iterator[str]:
        return iter(self.data)

    def __len__(self) -> int:
        return len(self.data)

    def _track_pending(self, key: str, value: Any) -> None:
        self._pending_keys[key] = self._pending_keys.get(key, 0) + 1
        self._pending_values[key] = value

    # -------------------------------------------------------------- apply

    def process(self, op: dict, local: bool) -> None:
        kind = op["type"]
        if local:
            # Ack: the optimistic apply already happened at submit.
            if kind == "clear":
                self._pending_clears -= 1
            else:
                key = op["key"]
                n = self._pending_keys.get(key, 0) - 1
                if n <= 0:
                    self._pending_keys.pop(key, None)
                    self._pending_values.pop(key, None)
                else:
                    self._pending_keys[key] = n
            return
        if kind == "clear":
            # Remote clear wipes, then pending local values re-apply
            # (they ride later sequence numbers — mapKernel
            # processClearMessage).
            self.data.clear()
            for key, val in self._pending_values.items():
                if val is not _DELETE:
                    self.data[key] = val
            return
        key = op["key"]
        if self._pending_clears > 0 or self._pending_keys.get(key, 0) > 0:
            return  # shadowed by pending local state
        if kind == "set":
            self.data[key] = op["value"]
        elif kind == "delete":
            self.data.pop(key, None)

    def rollback(self, op: dict, md: Any) -> None:
        """Undo a just-submitted local op (orderSequentially abort,
        containerRuntime.ts:1996 → mapKernel rollback)."""
        kind = op["type"]
        if kind == "clear":
            self.data = dict(md["data"])
            self._pending_clears -= 1
            return
        key = op["key"]
        if md["exists"]:
            self.data[key] = md["prev"]
        else:
            self.data.pop(key, None)
        n = self._pending_keys.get(key, 0) - 1
        if n <= 0:
            self._pending_keys.pop(key, None)
            self._pending_values.pop(key, None)
        else:
            self._pending_keys[key] = n
            if md["had_pending"]:
                self._pending_values[key] = md["prev_pending"]

    # ---------------------------------------------------------- summaries

    def to_serializable(self) -> Dict[str, Any]:
        return dict(self.data)

    def load(self, data: Dict[str, Any]) -> None:
        self.data = dict(data)


class SharedMap(SharedObject):
    """LWW key-value DDS (reference SharedMap, map.ts:92)."""

    def initialize_local_core(self) -> None:
        self.kernel = MapKernel(self.submit_local_message)

    # Public API mirrors ISharedMap.
    def get(self, key: str, default: Any = None) -> Any:
        return self.kernel.get(key, default)

    def set(self, key: str, value: Any) -> "SharedMap":
        self.kernel.set(key, value)
        self.emit("valueChanged", key, True)
        return self

    def has(self, key: str) -> bool:
        return self.kernel.has(key)

    def delete(self, key: str) -> bool:
        out = self.kernel.delete(key)
        self.emit("valueChanged", key, True)
        return out

    def clear(self) -> None:
        self.kernel.clear()
        self.emit("clear", True)

    def keys(self):
        return self.kernel.keys()

    def items(self):
        return self.kernel.data.items()

    def __len__(self) -> int:
        return len(self.kernel)

    # Channel seam obligations.
    def process_core(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        self.kernel.process(msg.contents, local)
        if not local:
            key = msg.contents.get("key") if isinstance(msg.contents, dict) else None
            self.emit("valueChanged", key, False)

    def rollback(self, content: Any, local_metadata: Any) -> None:
        self.kernel.rollback(content, local_metadata)

    def apply_stashed_op(self, content: Any) -> Any:
        op = content
        if op["type"] == "set":
            self.kernel.set(op["key"], op["value"])
        elif op["type"] == "delete":
            self.kernel.delete(op["key"])
        elif op["type"] == "clear":
            self.kernel.clear()
        return None

    def summarize_core(self):
        return (
            SummaryTreeBuilder()
            .add_json_blob("header", self.kernel.to_serializable())
            .summary
        )

    def load_core(self, storage: ChannelStorage) -> None:
        self.kernel = MapKernel(self.submit_local_message)
        self.kernel.load(json.loads(storage.read("header")))


class MapFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/map"
    channel_class = SharedMap


# ---------------------------------------------------------------------------
# SharedDirectory
# ---------------------------------------------------------------------------


class SubDirectory:
    """One node of the directory tree (reference SubDirectory,
    directory.ts:1244): a MapKernel for its keys + named children."""

    def __init__(self, shared: "SharedDirectory", path: str):
        self._shared = shared
        self.path = path  # absolute, "/" for root
        self.kernel = MapKernel(
            lambda op, md=None: shared._submit_storage_op(path, op, md)
        )
        self.subdirs: Dict[str, "SubDirectory"] = {}

    # key ops
    def get(self, key: str, default: Any = None) -> Any:
        return self.kernel.get(key, default)

    def set(self, key: str, value: Any) -> "SubDirectory":
        self.kernel.set(key, value)
        return self

    def has(self, key: str) -> bool:
        return self.kernel.has(key)

    def delete(self, key: str) -> bool:
        return self.kernel.delete(key)

    def clear(self) -> None:
        self.kernel.clear()

    def keys(self):
        return self.kernel.keys()

    def __len__(self) -> int:
        return len(self.kernel)

    # subdirectory ops
    def create_subdirectory(self, name: str) -> "SubDirectory":
        sub = self.subdirs.get(name)
        if sub is None:
            sub = self._create_child(name)
            self._shared._submit_subdir_op(
                {"type": "createSubDirectory", "path": self.path, "subdirName": name}
            )
        return sub

    def delete_subdirectory(self, name: str) -> bool:
        existed = name in self.subdirs
        removed = self.subdirs.pop(name, None)
        # The removed subtree rides as local metadata so a rollback
        # (orderSequentially abort) can reattach it intact.
        self._shared._submit_subdir_op(
            {"type": "deleteSubDirectory", "path": self.path, "subdirName": name},
            removed,
        )
        return existed

    def get_subdirectory(self, name: str) -> Optional["SubDirectory"]:
        return self.subdirs.get(name)

    def _create_child(self, name: str) -> "SubDirectory":
        child_path = self.path.rstrip("/") + "/" + name
        sub = SubDirectory(self._shared, child_path)
        self.subdirs[name] = sub
        return sub

    # summary form
    def to_serializable(self) -> dict:
        return {
            "storage": self.kernel.to_serializable(),
            "subdirectories": {
                name: sub.to_serializable() for name, sub in self.subdirs.items()
            },
        }

    def load(self, data: dict) -> None:
        self.kernel.load(data.get("storage", {}))
        for name, sub_data in data.get("subdirectories", {}).items():
            self._create_child(name).load(sub_data)


class SharedDirectory(SharedObject):
    """Hierarchical LWW key-value DDS (reference SharedDirectory,
    directory.ts:324). Ops carry the absolute subdirectory path."""

    def initialize_local_core(self) -> None:
        self.root = SubDirectory(self, "/")

    # Root-level convenience API (ISharedDirectory extends IDirectory).
    def get(self, key: str, default: Any = None) -> Any:
        return self.root.get(key, default)

    def set(self, key: str, value: Any) -> "SharedDirectory":
        self.root.set(key, value)
        return self

    def has(self, key: str) -> bool:
        return self.root.has(key)

    def delete(self, key: str) -> bool:
        return self.root.delete(key)

    def keys(self):
        return self.root.keys()

    def create_subdirectory(self, name: str) -> SubDirectory:
        return self.root.create_subdirectory(name)

    def get_subdirectory(self, name: str) -> Optional[SubDirectory]:
        return self.root.get_subdirectory(name)

    def get_working_directory(self, path: str) -> Optional[SubDirectory]:
        node = self.root
        for part in path.strip("/").split("/"):
            if not part:
                continue
            node = node.get_subdirectory(part)
            if node is None:
                return None
        return node

    # op plumbing
    def _submit_storage_op(self, path: str, op: dict, md: Any = None) -> None:
        self.submit_local_message({**op, "path": path}, md)

    def _submit_subdir_op(self, op: dict, local_metadata=None) -> None:
        self.submit_local_message(op, local_metadata)

    def _resolve(self, path: str, create: bool = False) -> Optional[SubDirectory]:
        node = self.root
        for part in path.strip("/").split("/"):
            if not part:
                continue
            nxt = node.get_subdirectory(part)
            if nxt is None:
                if not create:
                    return None
                nxt = node._create_child(part)
            node = nxt
        return node

    def process_core(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        op = msg.contents
        kind = op["type"]
        if kind == "createSubDirectory":
            if not local:
                parent = self._resolve(op["path"], create=True)
                if parent is not None and op["subdirName"] not in parent.subdirs:
                    parent._create_child(op["subdirName"])
            return
        if kind == "deleteSubDirectory":
            if not local:
                parent = self._resolve(op["path"])
                if parent is not None:
                    parent.subdirs.pop(op["subdirName"], None)
            return
        node = self._resolve(op["path"], create=not local)
        if node is not None:
            node.kernel.process(op, local)

    def apply_stashed_op(self, content: Any) -> Any:
        op = dict(content)
        kind = op["type"]
        if kind == "createSubDirectory":
            parent = self._resolve(op["path"], create=True)
            parent.create_subdirectory(op["subdirName"])
        elif kind == "deleteSubDirectory":
            parent = self._resolve(op["path"], create=True)
            parent.delete_subdirectory(op["subdirName"])
        else:
            node = self._resolve(op["path"], create=True)
            if kind == "set":
                node.set(op["key"], op["value"])
            elif kind == "delete":
                node.delete(op["key"])
            elif kind == "clear":
                node.clear()
        return None

    def rollback(self, content: Any, local_metadata: Any) -> None:
        op = content
        kind = op["type"]
        if kind == "createSubDirectory":
            parent = self._resolve(op["path"])
            if parent is not None:
                parent.subdirs.pop(op["subdirName"], None)
        elif kind == "deleteSubDirectory":
            # Reattach the subtree captured at submit time (it kept
            # its kernels and children; nothing observed the gap).
            parent = self._resolve(op["path"])
            if parent is not None and local_metadata is not None:
                parent.subdirs[op["subdirName"]] = local_metadata
        else:
            node = self._resolve(op["path"])
            if node is not None:
                node.kernel.rollback(op, local_metadata)

    def summarize_core(self):
        return (
            SummaryTreeBuilder()
            .add_json_blob("header", self.root.to_serializable())
            .summary
        )

    def load_core(self, storage: ChannelStorage) -> None:
        self.root = SubDirectory(self, "/")
        self.root.load(json.loads(storage.read("header")))


class DirectoryFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/directory"
    channel_class = SharedDirectory
