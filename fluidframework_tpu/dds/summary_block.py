"""SharedSummaryBlock: op-free, summary-only data.

Reference packages/dds/shared-summary-block/src/sharedSummaryBlock.ts:38:
values are written before attach (or by the summarizing client) and
travel exclusively via summaries — the DDS submits no ops.
"""

from __future__ import annotations

import json
from typing import Any, Dict

from ..protocol.messages import SequencedMessage
from ..runtime.channel import ChannelFactory, ChannelStorage
from ..runtime.shared_object import SharedObject
from ..runtime.summary import SummaryTreeBuilder


class SharedSummaryBlock(SharedObject):
    def initialize_local_core(self) -> None:
        self.data: Dict[str, Any] = {}

    def get(self, key: str) -> Any:
        return self.data.get(key)

    def set(self, key: str, value: Any) -> None:
        # No op submission: state persists only through summaries.
        self.data[key] = value

    def process_core(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        raise RuntimeError("SharedSummaryBlock does not process ops")

    def apply_stashed_op(self, content: Any) -> Any:
        raise RuntimeError("SharedSummaryBlock has no ops to stash")

    def summarize_core(self):
        return SummaryTreeBuilder().add_json_blob("header", self.data).summary

    def load_core(self, storage: ChannelStorage) -> None:
        self.data = json.loads(storage.read("header"))


class SummaryBlockFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/shared-summary-block"
    channel_class = SharedSummaryBlock
