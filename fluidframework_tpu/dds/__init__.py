"""Distributed data structures (the reference's packages/dds/*).

Every DDS subclasses `runtime.SharedObject` and registers a
`ChannelFactory` behind the channel seam. Conflict policy per family:

- map/directory/cell: last-writer-wins with pending-local shadowing
  (packages/dds/map/src/mapKernel.ts:130)
- counter: commutative increments (packages/dds/counter)
- sequence (SharedString): merge-tree CRDT (packages/dds/merge-tree →
  core.mergetree + ops.mergetree_kernel)
- matrix: two permutation merge-trees + sparse cell store
  (packages/dds/matrix)
- consensus family: server-ack gated (ordered-collection,
  register-collection, task-manager, pact-map)
"""

from .map import MapFactory, SharedMap, DirectoryFactory, SharedDirectory
from .cell import CellFactory, SharedCell
from .counter import CounterFactory, SharedCounter
from .consensus import (
    READ_ATOMIC,
    READ_LWW,
    ConsensusQueue,
    ConsensusQueueFactory,
    ConsensusRegisterCollection,
    PactMap,
    PactMapFactory,
    RegisterCollectionFactory,
    TaskManager,
    TaskManagerFactory,
)
from .ink import InkFactory, SharedInk
from .matrix import MatrixFactory, SharedMatrix
from .summary_block import SharedSummaryBlock, SummaryBlockFactory
from .sequence import (
    IntervalCollection,
    Marker,
    SequenceInterval,
    SharedSegmentSequence,
    SharedString,
    StringFactory,
)

__all__ = [
    "READ_ATOMIC",
    "READ_LWW",
    "CellFactory",
    "ConsensusQueue",
    "ConsensusQueueFactory",
    "ConsensusRegisterCollection",
    "CounterFactory",
    "InkFactory",
    "PactMap",
    "PactMapFactory",
    "RegisterCollectionFactory",
    "SharedInk",
    "SharedSummaryBlock",
    "SummaryBlockFactory",
    "TaskManager",
    "TaskManagerFactory",
    "DirectoryFactory",
    "IntervalCollection",
    "MapFactory",
    "Marker",
    "MatrixFactory",
    "SharedMatrix",
    "SequenceInterval",
    "SharedCell",
    "SharedCounter",
    "SharedDirectory",
    "SharedMap",
    "SharedSegmentSequence",
    "SharedString",
    "StringFactory",
]
