"""Consensus-family DDSes: server-ack-gated coordination structures.

These DDSes derive their guarantees from the total order itself (a
claim is yours iff *your* op sequences first) plus quorum membership
(leases release when their holder leaves). Reference packages:

- `ConsensusQueue` (dds/ordered-collection/src/consensusQueue.ts:37):
  distributed work queue with acquire/complete/release leases.
- `ConsensusRegisterCollection`
  (dds/register-collection/src/consensusRegisterCollection.ts:95):
  versioned registers with Atomic / LocalWriterWins read policies.
- `TaskManager` (dds/task-manager/src/taskManager.ts:150): per-task
  volunteer queues; the head holds the lock.
- `PactMap` (dds/pact-map/src/pactMap.ts:159): write-once keys that
  commit when every connected client has seen them (MSN passes the
  set's sequence number — the quorum-proposal commit rule).

Quorum-leave cleanup is deterministic: every replica folds protocol
messages at the same stream position (ContainerRuntime._process_one),
so lease releases happen identically everywhere.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional

from ..protocol.messages import SequencedMessage
from ..runtime.channel import ChannelFactory, ChannelStorage
from ..runtime.shared_object import SharedObject
from ..runtime.summary import SummaryTreeBuilder


class _QuorumWatcher(SharedObject):
    """Base for DDSes that react to quorum membership changes."""

    def on_connected(self) -> None:
        quorum = self.runtime.container.protocol.quorum
        if getattr(self, "_watching", None) is not quorum:
            self._watching = quorum
            quorum.on("removeMember", self._on_member_left)

    def _on_member_left(self, client_id: int) -> None:  # pragma: no cover
        pass


# ---------------------------------------------------------------------------
# ConsensusQueue
# ---------------------------------------------------------------------------


class ConsensusQueue(_QuorumWatcher):
    """FIFO queue with acquire leases (consensusQueue.ts:37).

    acquire(): submits an acquire op; when it sequences and the queue
    is non-empty, the head value is leased to the acquiring client.
    complete(id) removes it permanently; release(id) returns it to the
    head. A leaseholder's departure releases its leases.
    """

    def initialize_local_core(self) -> None:
        self.queue: List[dict] = []  # {"id": n, "value": v}
        self.in_flight: Dict[int, dict] = {}  # id -> {"value", "client"}
        self._next_id = 0
        self._acquire_callbacks: List[Callable[[Optional[dict]], None]] = []

    def add(self, value: Any) -> None:
        self.submit_local_message({"type": "add", "value": value})

    def acquire(self, callback: Optional[Callable[[Optional[dict]], None]] = None) -> None:
        """Request the queue head; `callback(item_or_None)` fires when
        our acquire op sequences (the server-ack contract)."""
        self._acquire_callbacks.append(callback or (lambda item: None))
        self.submit_local_message({"type": "acquire"})

    def complete(self, item_id: int) -> None:
        self.submit_local_message({"type": "complete", "id": item_id})

    def release(self, item_id: int) -> None:
        self.submit_local_message({"type": "release", "id": item_id})

    def process_core(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        op = msg.contents
        kind = op["type"]
        if kind == "add":
            self.queue.append({"id": self._next_id, "value": op["value"]})
            self._next_id += 1
        elif kind == "acquire":
            item = self.queue.pop(0) if self.queue else None
            if item is not None:
                self.in_flight[item["id"]] = {
                    "value": item["value"],
                    "client": msg.client_id,
                }
            if local:
                cb = self._acquire_callbacks.pop(0)
                cb(dict(item) if item else None)
            self.emit("acquired", item, msg.client_id)
        elif kind == "complete":
            self.in_flight.pop(op["id"], None)
        elif kind == "release":
            entry = self.in_flight.pop(op["id"], None)
            if entry is not None:
                self.queue.insert(0, {"id": op["id"], "value": entry["value"]})

    def _on_member_left(self, client_id: int) -> None:
        # Leases die with their holder (localOrderSequentially in the
        # reference releases on quorum leave).
        for item_id in sorted(
            [i for i, e in self.in_flight.items() if e["client"] == client_id]
        ):
            entry = self.in_flight.pop(item_id)
            self.queue.insert(0, {"id": item_id, "value": entry["value"]})

    def apply_stashed_op(self, content: Any) -> Any:
        self.submit_local_message(content)
        return None

    def summarize_core(self):
        return (
            SummaryTreeBuilder()
            .add_json_blob(
                "header",
                {"queue": self.queue, "nextId": self._next_id,
                 "inFlight": [[k, v] for k, v in self.in_flight.items()]},
            )
            .summary
        )

    def load_core(self, storage: ChannelStorage) -> None:
        self.initialize_local_core()
        data = json.loads(storage.read("header"))
        self.queue = data["queue"]
        self._next_id = data["nextId"]
        self.in_flight = {int(k): v for k, v in data["inFlight"]}


class ConsensusQueueFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/consensus-queue"
    channel_class = ConsensusQueue


# ---------------------------------------------------------------------------
# ConsensusRegisterCollection
# ---------------------------------------------------------------------------

READ_ATOMIC = "Atomic"
READ_LWW = "LocalWriterWins"


class ConsensusRegisterCollection(_QuorumWatcher):
    """Versioned registers (consensusRegisterCollection.ts:95): a write
    supersedes exactly the versions its author had seen (version seq <=
    write refSeq); concurrent writes coexist as versions. Atomic read =
    earliest surviving version; LWW read = latest."""

    def initialize_local_core(self) -> None:
        # key -> [{"value", "seq", "client"}] in sequence order
        self.registers: Dict[str, List[dict]] = {}

    def write(self, key: str, value: Any) -> None:
        self.submit_local_message({"type": "write", "key": key, "value": value})

    def read(self, key: str, policy: str = READ_ATOMIC) -> Any:
        versions = self.registers.get(key)
        if not versions:
            return None
        return versions[0 if policy == READ_ATOMIC else -1]["value"]

    def read_versions(self, key: str) -> List[Any]:
        return [v["value"] for v in self.registers.get(key, [])]

    def keys(self):
        return self.registers.keys()

    def process_core(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        op = msg.contents
        if op["type"] != "write":
            return
        key = op["key"]
        versions = self.registers.setdefault(key, [])
        # Supersede everything the writer had seen.
        versions[:] = [v for v in versions if v["seq"] > msg.ref_seq]
        versions.append(
            {"value": op["value"], "seq": msg.sequence_number, "client": msg.client_id}
        )
        self.emit("atomicChanged" if len(versions) == 1 else "versionChanged", key)

    def apply_stashed_op(self, content: Any) -> Any:
        self.submit_local_message(content)
        return None

    def summarize_core(self):
        return SummaryTreeBuilder().add_json_blob("header", self.registers).summary

    def load_core(self, storage: ChannelStorage) -> None:
        self.registers = json.loads(storage.read("header"))


class RegisterCollectionFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/consensus-register-collection"
    channel_class = ConsensusRegisterCollection


# ---------------------------------------------------------------------------
# TaskManager
# ---------------------------------------------------------------------------


class TaskManager(_QuorumWatcher):
    """Distributed task locks via volunteer queues (taskManager.ts:150).
    The queue head holds the lock; abandoning or leaving passes it."""

    def initialize_local_core(self) -> None:
        self.queues: Dict[str, List[int]] = {}  # task id -> client queue

    def volunteer_for_task(self, task_id: str) -> None:
        self.submit_local_message({"type": "volunteer", "taskId": task_id})

    def abandon(self, task_id: str) -> None:
        self.submit_local_message({"type": "abandon", "taskId": task_id})

    def assigned_client(self, task_id: str) -> Optional[int]:
        q = self.queues.get(task_id)
        return q[0] if q else None

    def assigned(self, task_id: str) -> bool:
        cid = self.runtime.client_id
        return cid is not None and self.assigned_client(task_id) == cid

    def queued(self, task_id: str) -> bool:
        cid = self.runtime.client_id
        return cid is not None and cid in self.queues.get(task_id, [])

    def process_core(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        op = msg.contents
        q = self.queues.setdefault(op["taskId"], [])
        if op["type"] == "volunteer":
            if msg.client_id not in q:
                q.append(msg.client_id)
        elif op["type"] == "abandon":
            if msg.client_id in q:
                was_head = q[0] == msg.client_id
                q.remove(msg.client_id)
                if was_head and q:
                    self.emit("assigned", op["taskId"], q[0])
        self.emit("queueChanged", op["taskId"])

    def _on_member_left(self, client_id: int) -> None:
        for task_id, q in self.queues.items():
            if client_id in q:
                was_head = q[0] == client_id
                q.remove(client_id)
                if was_head and q:
                    self.emit("assigned", task_id, q[0])

    def apply_stashed_op(self, content: Any) -> Any:
        self.submit_local_message(content)
        return None

    def summarize_core(self):
        # Volunteer queues are session state: clients re-volunteer on
        # load (the reference persists nothing for connected clients).
        return SummaryTreeBuilder().add_json_blob("header", {}).summary

    def load_core(self, storage: ChannelStorage) -> None:
        self.initialize_local_core()


class TaskManagerFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/task-manager"
    channel_class = TaskManager


# ---------------------------------------------------------------------------
# PactMap
# ---------------------------------------------------------------------------


class PactMap(_QuorumWatcher):
    """Write-once keys committed by unanimous observation
    (pactMap.ts:159): a set becomes the key's pact once the MSN passes
    its sequence number; competing concurrent sets lose to the first
    sequenced."""

    def initialize_local_core(self) -> None:
        self.values: Dict[str, Any] = {}  # committed pacts
        self.pending_pacts: Dict[str, dict] = {}  # key -> {"value","seq"}

    def set(self, key: str, value: Any) -> None:
        self.submit_local_message({"type": "set", "key": key, "value": value})

    def get(self, key: str) -> Any:
        return self.values.get(key)

    def get_pending(self, key: str) -> Any:
        p = self.pending_pacts.get(key)
        return p["value"] if p else None

    def has(self, key: str) -> bool:
        return key in self.values

    def process_core(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        op = msg.contents
        if op["type"] == "set":
            key = op["key"]
            if key not in self.values and key not in self.pending_pacts:
                self.pending_pacts[key] = {
                    "value": op["value"], "seq": msg.sequence_number,
                }
            # else: a pact exists or is forming — later sets lose.
        self._commit_ready(msg.minimum_sequence_number)

    def _commit_ready(self, msn: int) -> None:
        ready = [k for k, p in self.pending_pacts.items() if p["seq"] <= msn]
        for key in ready:
            self.values[key] = self.pending_pacts.pop(key)["value"]
            self.emit("pact", key, self.values[key])

    def apply_stashed_op(self, content: Any) -> Any:
        self.submit_local_message(content)
        return None

    def summarize_core(self):
        return (
            SummaryTreeBuilder()
            .add_json_blob(
                "header", {"values": self.values, "pending": self.pending_pacts}
            )
            .summary
        )

    def load_core(self, storage: ChannelStorage) -> None:
        data = json.loads(storage.read("header"))
        self.values = data["values"]
        self.pending_pacts = data["pending"]


class PactMapFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/pact-map"
    channel_class = PactMap
