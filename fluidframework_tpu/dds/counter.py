"""SharedCounter: commutative shared increments.

Reference packages/dds/counter/src/counter.ts:84. Increments commute,
so there is no conflict policy: every replica sums every increment;
a local increment is applied optimistically and skipped on its
sequenced echo.
"""

from __future__ import annotations

import json
from typing import Any

from ..protocol.messages import SequencedMessage
from ..runtime.channel import ChannelFactory, ChannelStorage
from ..runtime.shared_object import SharedObject
from ..runtime.summary import SummaryTreeBuilder


class SharedCounter(SharedObject):
    def initialize_local_core(self) -> None:
        self._value = 0

    @property
    def value(self) -> int:
        return self._value

    def increment(self, amount: int = 1) -> None:
        if not isinstance(amount, int):
            raise TypeError("SharedCounter increments must be integers")
        self._value += amount
        self.submit_local_message({"type": "increment", "incrementAmount": amount})
        self.emit("incremented", amount, self._value)

    def process_core(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        if local:
            return  # already applied optimistically
        amount = msg.contents["incrementAmount"]
        self._value += amount
        self.emit("incremented", amount, self._value)

    def rollback(self, content: Any, local_metadata: Any) -> None:
        self._value -= content["incrementAmount"]

    def apply_stashed_op(self, content: Any) -> Any:
        self.increment(content["incrementAmount"])
        return None

    def summarize_core(self):
        return SummaryTreeBuilder().add_json_blob("header", {"value": self._value}).summary

    def load_core(self, storage: ChannelStorage) -> None:
        self._value = json.loads(storage.read("header"))["value"]


class CounterFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/counter"
    channel_class = SharedCounter
