"""Seeded multi-client convergence farms.

The workhorse consistency test, after the reference's conflict farm
(packages/dds/merge-tree/src/test/client.conflictFarm.spec.ts +
mergeTreeOperationRunner.ts): a round consists of each client applying
random local ops *before* seeing each other's (maximal concurrency),
then the sequencer's totally ordered stream is drained to everyone and
all replicas must agree exactly.
"""

from __future__ import annotations

import random
import string
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..core.mergetree import CollabClient
from ..protocol.messages import DocumentMessage, SequencedMessage
from ..server.sequencer import DocumentSequencer


@dataclass
class FarmConfig:
    num_clients: int = 4
    rounds: int = 20
    ops_per_client_per_round: int = 4
    seed: int = 0
    # Run MergeTreeEngine.verify_invariants on every replica each
    # round (the exhaustive partialLengths.ts:336 verifier; slow).
    verify_invariants_every: int = 0
    insert_weight: float = 0.5
    remove_weight: float = 0.3
    annotate_weight: float = 0.2
    max_insert_len: int = 6
    annotate_keys: Tuple[str, ...] = ("bold", "color", "size")
    initial_text: str = "hello world"
    check_annotations: bool = True
    # Convergence-assert cadence: every round by default (the farm's
    # correctness role); throughput configs raise it so the measured
    # region is the client/sequencer path, not O(doc) text pulls.
    check_every: int = 1
    # Annotate ops carry 1..len(annotate_keys) keys per op (PK>1
    # coverage for the kernels' prop-pair loops).
    multi_key_annotates: bool = False


def random_op_for(
    client: CollabClient, rng: random.Random, cfg: FarmConfig
) -> Optional[DocumentMessage]:
    """One random local op on `client` (insert/remove/annotate mix)."""
    length = client.visible_length()
    r = rng.random()
    total = cfg.insert_weight + cfg.remove_weight + cfg.annotate_weight
    r *= total
    if r < cfg.insert_weight or length == 0:
        pos = rng.randint(0, length)
        n = rng.randint(1, cfg.max_insert_len)
        text = "".join(rng.choices(string.ascii_lowercase, k=n))
        return client.insert_local(pos, text)
    r -= cfg.insert_weight
    start = rng.randint(0, length - 1)
    end = rng.randint(start + 1, min(length, start + 8))
    if r < cfg.remove_weight:
        return client.remove_local(start, end)
    if cfg.multi_key_annotates:
        n_keys = rng.randint(1, len(cfg.annotate_keys))
        keys = rng.sample(list(cfg.annotate_keys), n_keys)
    else:
        keys = [rng.choice(cfg.annotate_keys)]
    props = {
        k: rng.choice([rng.randint(0, 9), "x", None]) for k in keys
    }
    return client.annotate_local(start, end, props)


@dataclass
class FarmResult:
    final_text: str
    stream: List[SequencedMessage]
    clients: List[CollabClient]


def run_sharedstring_farm(cfg: FarmConfig) -> FarmResult:
    """Run the farm; assert convergence each round. Returns the final
    text plus the full sequenced stream (for passive/kernel replays)."""
    rng = random.Random(cfg.seed)
    seqr = DocumentSequencer("farm")
    clients: List[CollabClient] = []
    stream: List[SequencedMessage] = []
    for i in range(cfg.num_clients):
        cid = i + 1
        stream.append(seqr.join(cid))
        clients.append(CollabClient(cid, initial=cfg.initial_text))
    # Join messages consumed sequence numbers; align every window.
    for cl in clients:
        cl.engine.current_seq = seqr.seq

    for rnd in range(cfg.rounds):
        # Phase 1: everyone edits locally without seeing each other.
        submissions: List[Tuple[int, DocumentMessage]] = []
        for c in clients:
            for _ in range(cfg.ops_per_client_per_round):
                msg = random_op_for(c, rng, cfg)
                if msg is not None:
                    submissions.append((c.client_id, msg))
        # Phase 2: sequence in a shuffled interleaving.
        # (Per-client order must be preserved — deli enforces clientSeq
        # contiguity — so shuffle by merging per-client queues.)
        per_client = {c.client_id: [] for c in clients}
        for cid, m in submissions:
            per_client[cid].append(m)
        sequenced: List[SequencedMessage] = []
        while any(per_client.values()):
            cid = rng.choice([c for c, q in per_client.items() if q])
            out = seqr.sequence(cid, per_client[cid].pop(0))
            assert isinstance(out, SequencedMessage), f"unexpected nack {out}"
            sequenced.append(out)
        # Phase 3: drain to all clients in total order (clients are
        # independent, so each takes the round as one batched apply).
        stream.extend(sequenced)
        for c in clients:
            c.apply_msgs(sequenced)
        # Phase 4: convergence.
        if (rnd + 1) % cfg.check_every and rnd + 1 != cfg.rounds:
            continue
        texts = [c.get_text() for c in clients]
        assert all(t == texts[0] for t in texts), (
            f"round {rnd}: divergent texts (seed {cfg.seed}):\n"
            + "\n".join(f"  client {c.client_id}: {t!r}" for c, t in zip(clients, texts))
        )
        if cfg.check_annotations:
            spans = [char_spans(c.engine.annotated_spans()) for c in clients]
            assert all(s == spans[0] for s in spans), (
                f"round {rnd}: divergent annotations (seed {cfg.seed})"
            )
        if (
            cfg.verify_invariants_every
            and (rnd + 1) % cfg.verify_invariants_every == 0
        ):
            for c in clients:
                c.engine.verify_invariants()
    return FarmResult(
        final_text=clients[0].get_text(), stream=stream, clients=clients
    )


def char_spans(annotated_spans):
    """Character-wise (char, props) stream from (content, props) spans —
    segment boundaries may legitimately differ across replicas;
    per-character state may not."""
    out = []
    for content, props in annotated_spans:
        norm = tuple(sorted(props.items())) if props else ()
        for ch in content:
            out.append((ch, norm))
    return out
