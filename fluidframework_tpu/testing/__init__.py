"""Test harnesses: seeded multi-client op farms and mock plumbing.

Mirrors the roles of the reference's test-runtime-utils mocks
(packages/runtime/test-runtime-utils/src/mocks.ts) and the merge-tree
farm runner (packages/dds/merge-tree/src/test/mergeTreeOperationRunner.ts):
drive N collaborating clients with a seeded random op mix through an
in-proc sequencer, interleaving delivery, and assert all replicas
converge to identical state.
"""

from .farm import FarmConfig, run_sharedstring_farm, random_op_for
from .chaos import (
    ChaosConfig,
    ChaosResult,
    run_chaos,
    stream_digest,
)
from .scenarios import run_scenario_suite

__all__ = [
    "ChaosConfig",
    "ChaosResult",
    "FarmConfig",
    "random_op_for",
    "run_chaos",
    "run_scenario_suite",
    "run_sharedstring_farm",
    "stream_digest",
]
