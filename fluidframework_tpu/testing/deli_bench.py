"""End-to-end deli pipeline bench: raw topic in → stamped deltas out.

Measures the LIVE ordering pipeline (BASELINE config 5's 10k docs x 64
clients shape), not the naked kernel: records are read from a durable
`SharedFileTopic` raw topic (JSON parse included), ticketed, and the
stamped/nacked records written to a durable deltas topic — the exact
datapath the supervised farm's deli role runs (`server.supervisor`),
including its checkpoint policy (time/byte cadence by default; the
seed's every-step policy is measured alongside as the ROADMAP item (b)
comparison), minus lease upkeep only. The report attaches a per-stage
wall-time breakdown (poll/parse, process+kernel, append, checkpoint)
and the run's checkpoint write/byte counters from `utils.metrics`.

Three variants over the identical pre-built workload:

- ``kernel``        — `deli_kernel.KernelDeliRole`: columnar pack →
  vmap'd device kernel → one `append_many` per pump.
- ``scalar``        — `supervisor.DeliRole` with the per-pump
  `append_many` flush (this PR's batched-scalar fix).
- ``scalar_seed``   — `supervisor.DeliRole` with the seed pipeline's
  per-record `SharedFileTopic.append` (one lock + fsync per record).
  This is the baseline `vs_baseline` is computed against; since one
  fsync per record makes full-workload runs take hours by design, it
  is measured on a bounded prefix of the same stream
  (`seed_records`), processed identically.

A correctness gate runs first: kernel and batched-scalar deltas topics
must carry bit-identical stamps, nack codes, and MSNs (reason text
exempt) before any number is reported.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple


def build_pipeline_workload(n_docs: int, n_clients: int,
                            ops_per_client: int, seed: int = 5,
                            doc_names: Optional[List[str]] = None
                            ) -> List[dict]:
    """Deterministic raw-topic stream, round-robin across docs (every
    pump carries many documents — the data-parallel axis the kernel
    batches over). Each client's join rides immediately before its
    first op, so ANY prefix of the stream carries the same join:op mix
    as the whole — the bounded seed-baseline measurement then rates
    the same workload shape the full runs do. `doc_names` overrides
    the default ``doc{d}`` naming (the shard bench passes
    partition-balanced names)."""
    import random

    rng = random.Random(seed)
    docs = doc_names if doc_names is not None else [
        f"doc{d}" for d in range(n_docs)
    ]
    recs: List[dict] = []
    for i in range(ops_per_client):
        for c in range(1, n_clients + 1):
            for doc in docs:
                if i == 0:
                    recs.append({"kind": "join", "doc": doc, "client": c})
                recs.append({
                    "kind": "op", "doc": doc, "client": c,
                    "clientSeq": i + 1, "refSeq": 0,
                    "contents": {"v": rng.randint(0, 999), "i": i},
                })
    return recs


def _make_role(impl: str, scratch: str, log_format: str = "json",
               deli_devices: Optional[int] = None):
    if impl == "kernel":
        from ..server.deli_kernel import KernelDeliRole

        return KernelDeliRole(scratch, owner=f"bench-{impl}",
                              ttl_s=3600.0, log_format=log_format,
                              deli_devices=deli_devices)
    from ..server.supervisor import DeliRole

    return DeliRole(scratch, owner=f"bench-{impl}", ttl_s=3600.0,
                    log_format=log_format)


def run_pipeline(impl: str, raw_path: str, out_dir: str,
                 batch: int = 8192, per_record_append: bool = False,
                 max_records: Optional[int] = None,
                 checkpoint_mode: Optional[str] = "cadence",
                 log_format: str = "json",
                 deli_devices: Optional[int] = None) -> dict:
    """Drive one deli variant raw-topic-in → deltas-topic-out.

    `checkpoint_mode` selects the farm's checkpoint policy inside the
    timed region: "cadence" (time/byte-based, `_Role.maybe_checkpoint`
    — the production default), "pump" (one fenced checkpoint per pump,
    the seed's every-step behavior), or None (no checkpoints).

    `log_format` selects the topic wire form for BOTH ends: "json"
    (JSONL lines) or "columnar" (binary record batches — the kernel
    role then ingests raw `RecordBatch` frames and passes op contents
    through as pre-encoded blobs, zero per-record JSON on the wire).

    Returns {"seconds", "records", "outputs", "out_path", "stages",
    "metrics"} — `stages` is the per-stage wall-time breakdown (poll/
    parse, process+kernel, append, checkpoint) and `metrics` the run's
    checkpoint counters from an isolated registry."""
    from ..server.columnar_log import make_tail_reader, make_topic
    from ..utils import metrics as _metrics

    raw = make_topic(raw_path, log_format)
    out_path = os.path.join(out_dir, f"deltas-{impl}-{log_format}"
                            + ("-seed" if per_record_append else "") + ".jsonl")
    if os.path.exists(out_path):
        os.remove(out_path)
        for side in (".fence", ".clen"):
            if os.path.exists(out_path + side):
                os.remove(out_path + side)
    deltas = make_topic(out_path, log_format)
    # Isolated registry: this run's checkpoint/pump/codec/fsync
    # counters are not polluted by (and do not pollute) other runs in
    # the process. The registry stays swapped in for the whole timed
    # loop so the emit-side evidence (encode-columns records, topic
    # fsyncs) lands here too.
    from fluidframework_tpu.protocol.record_batch import count_records

    reg = _metrics.MetricsRegistry()
    prev_reg = _metrics.set_registry(reg)
    try:
        role = _make_role(impl, os.path.join(out_dir, f"scratch-{impl}"),
                          log_format, deli_devices)
        # The bench drives the role datapath directly (no lease loop);
        # bind a fence so fenced checkpoint writes work.
        role.fence = 1
        reader = make_tail_reader(raw)
        # The kernel role's columnar fast path: whole RecordBatch
        # frames (max_records runs keep the exact per-record cap).
        use_batches = (role.ingest_batches and max_records is None
                       and hasattr(reader, "poll_batches"))
        n_records = 0
        n_out = 0
        t_poll = t_proc = t_append = t_ckpt = 0.0
        t0 = time.perf_counter()
        while True:
            cap = batch
            if max_records is not None:
                cap = min(cap, max_records - n_records)
                if cap <= 0:
                    break
            t1 = time.perf_counter()
            if use_batches:
                units = reader.poll_batches(cap)
                entries = None
                moved = sum(u[2].n if u[0] == "batch" else 1
                            for u in units)
            else:
                entries = reader.poll(cap)
                moved = len(entries)
            t2 = time.perf_counter()
            t_poll += t2 - t1
            if not moved:
                break
            out: List[dict] = []
            if use_batches:
                for u in units:
                    if u[0] == "batch":
                        role.process_batch(u[1], u[2], out)
                    else:
                        role.process(u[1], u[2], out)
            else:
                for line_idx, rec in entries:
                    role.process(line_idx, rec, out)
            role.flush_batch(out)
            t3 = time.perf_counter()
            t_proc += t3 - t2
            if per_record_append:
                for r in out:  # the seed pipeline: one lock+fsync each
                    role._ckpt_pending_bytes += deltas.append(r)
            else:
                role._ckpt_pending_bytes += deltas.append_many(out)
            t4 = time.perf_counter()
            t_append += t4 - t3
            role.offset = reader.next_line
            if checkpoint_mode is not None:
                role._ckpt_dirty = True
                if checkpoint_mode == "pump":
                    role.checkpoint()
                else:
                    role.maybe_checkpoint()
                t_ckpt += time.perf_counter() - t4
            n_records += moved
            n_out += count_records(out)
        seconds = time.perf_counter() - t0
    finally:
        _metrics.set_registry(prev_reg)
    ckpt = {
        "writes": int(reg.counter(
            "checkpoint_writes_total", role="deli").value),
        "bytes": int(reg.counter(
            "checkpoint_bytes_total", role="deli").value),
        "seconds": round(t_ckpt, 4),
        "mode": checkpoint_mode,
    }
    # Emit-side codec evidence (the pre-columnized emission tentpole):
    # how many output records rode `encode_columns` (zero per-record
    # classification) and the run's topic-fsync floor per record.
    fsyncs = int(reg.counter("topic_fsyncs_total", kind="topic").value)
    emit = {
        "codec_encode_columns_records": int(reg.counter(
            "codec_encode_columns_total", codec="columnar").value),
        "topic_fsyncs": fsyncs,
        "fsyncs_per_record": round(fsyncs / max(1, n_records), 6),
    }
    return {"seconds": seconds, "records": n_records, "outputs": n_out,
            "out_path": out_path,
            "stages": {
                "poll_parse_s": round(t_poll, 4),
                "process_kernel_s": round(t_proc, 4),
                "append_s": round(t_append, 4),
                "checkpoint_s": round(t_ckpt, 4),
            },
            "metrics": {"checkpoint": ckpt, "emit": emit}}


def _read_canonical(path: str) -> List[dict]:
    # ColumnarFileTopic reads BOTH wire forms (JSON lines and binary
    # frames), so one reader canonicalizes every variant's output.
    from ..server.columnar_log import ColumnarFileTopic

    return [
        {k: v for k, v in r.items() if k != "reason"}
        for r in ColumnarFileTopic(path).read_from(0)
    ]


def run_pipeline_bench(n_docs: int = 10_000, n_clients: int = 64,
                       ops_per_client: int = 1, seed_records: int = 400,
                       batch: int = 16384, work_dir: Optional[str] = None,
                       keep: bool = False,
                       deli_devices: Optional[int] = None) -> dict:
    """The full comparison: build the workload once, gate kernel vs
    batched-scalar for bit-identity, time all three variants, and
    report the standard one-line JSON fields."""
    from ..server.queue import SharedFileTopic

    scratch = work_dir or tempfile.mkdtemp(prefix="deli-bench-")
    os.makedirs(scratch, exist_ok=True)
    try:
        workload = build_pipeline_workload(n_docs, n_clients, ops_per_client)
        raw_path = os.path.join(scratch, "rawdeltas.jsonl")
        if os.path.exists(raw_path):
            os.remove(raw_path)
        raw = SharedFileTopic(raw_path)
        raw.append_many(workload)
        # The SAME workload as a columnar record-batch log, framed in
        # pump-sized batches (the boxcarred ingress shape).
        from ..server.columnar_log import make_topic

        raw_col_path = os.path.join(scratch, "rawdeltas-col.jsonl")
        for stale in (raw_col_path, raw_col_path + ".clen",
                      raw_col_path + ".fence"):
            if os.path.exists(stale):
                os.remove(stale)
        raw_col = make_topic(raw_col_path, "columnar")
        for lo in range(0, len(workload), batch):
            raw_col.append_many(workload[lo:lo + batch])

        # Kernel warm-up (the standard bench contract: the timed region
        # never compiles — one untimed full run compiles every jit
        # shape the real run uses; the scalar path has nothing to
        # compile and gets no warm-up). `deli_devices` shards the
        # kernel runs' doc pool across a device mesh.
        run_pipeline("kernel", raw_path, scratch, batch=batch,
                     deli_devices=deli_devices)
        kern = run_pipeline("kernel", raw_path, scratch, batch=batch,
                            deli_devices=deli_devices)
        scal = run_pipeline("scalar", raw_path, scratch, batch=batch)
        # The columnar op-log twins (ROADMAP (a)): identical records,
        # binary record-batch topics on both ends.
        kern_col = run_pipeline("kernel", raw_col_path, scratch,
                                batch=batch, log_format="columnar",
                                deli_devices=deli_devices)
        scal_col = run_pipeline("scalar", raw_col_path, scratch,
                                batch=batch, log_format="columnar")

        # Correctness gate: bit-identical stamps/nacks/MSNs across
        # every (impl x log_format) variant.
        a = _read_canonical(kern["out_path"])
        for other in (scal, kern_col, scal_col):
            b = _read_canonical(other["out_path"])
            if a != b:
                n = sum(1 for x, y in zip(a, b) if x != y)                     + abs(len(a) - len(b))
                raise AssertionError(
                    f"deltas diverge across variants at "
                    f"{other['out_path']} ({n} records differ; "
                    f"{len(a)} vs {len(b)})"
                )

        # ROADMAP item (b) evidence: the same kernel run with the
        # seed's every-step checkpoint policy — the checkpoint
        # counters show the cadence win (writes/bytes collapse).
        kern_every = run_pipeline("kernel", raw_path, scratch,
                                  batch=batch, checkpoint_mode="pump",
                                  deli_devices=deli_devices)

        seed_run = run_pipeline(
            "scalar", raw_path, scratch, batch=batch,
            per_record_append=True, checkpoint_mode="pump",
            max_records=min(seed_records, len(workload)),
        )

        kernel_ops = kern["records"] / kern["seconds"]
        scalar_ops = scal["records"] / scal["seconds"]
        col_ops = kern_col["records"] / kern_col["seconds"]
        col_scalar_ops = scal_col["records"] / scal_col["seconds"]
        seed_ops = seed_run["records"] / seed_run["seconds"]
        every_ops = kern_every["records"] / kern_every["seconds"]
        return {
            "metric": "deli_pipeline_raw_to_deltas",
            "docs": n_docs, "clients_per_doc": n_clients,
            "n_devices": int(deli_devices or 1),
            "records": len(workload), "stamped": kern["outputs"],
            "ops_per_sec": round(kernel_ops, 1),
            "scalar_batched_ops_per_sec": round(scalar_ops, 1),
            "scalar_seed_ops_per_sec": round(seed_ops, 1),
            "seed_records_measured": seed_run["records"],
            "vs_baseline": round(kernel_ops / seed_ops, 2),
            "vs_scalar_batched": round(kernel_ops / scalar_ops, 2),
            # Columnar op-log (ROADMAP (a)/(d)): the SAME pipeline over
            # binary record-batch topics — the end-to-end number where
            # the kernel win finally survives the wire.
            "columnar_ops_per_sec": round(col_ops, 1),
            "columnar_scalar_ops_per_sec": round(col_scalar_ops, 1),
            "columnar_vs_json_log": round(col_ops / kernel_ops, 2),
            "columnar_vs_scalar_batched_json": round(
                col_ops / scalar_ops, 2
            ),
            "columnar_stage_breakdown": kern_col["stages"],
            # Emit-side evidence: records through `encode_columns`
            # (the pre-columnized emission — per-record Python
            # eliminated on the columnar kernel path) and the
            # fsyncs-per-record floor of the columnar run.
            "columnar_emit_codec": kern_col["metrics"]["emit"],
            # Per-stage wall-time breakdown of the timed kernel run
            # (where a sequenced record's time goes inside the pump).
            "stage_breakdown": kern["stages"],
            # Checkpoint cadence (ROADMAP (b)): time/byte-based vs the
            # seed's every-step policy, counters from utils.metrics.
            "ckpt_cadence": kern["metrics"]["checkpoint"],
            "ckpt_every_pump": kern_every["metrics"]["checkpoint"],
            "ckpt_every_pump_ops_per_sec": round(every_ops, 1),
            "vs_ckpt_every_pump": round(kernel_ops / every_ops, 2),
            "gate": "bit-identical",
            "unit": "records/s",
        }
    finally:
        if not keep and work_dir is None:
            shutil.rmtree(scratch, ignore_errors=True)


# ---------------------------------------------------------------------------
# multi-device scaling bench (config7_multichip's engine)
# ---------------------------------------------------------------------------


def _multichip_workload(n_docs: int, ops_per_doc: int, n_clients: int):
    """Deterministic [D, B] kernel submissions, identical for every
    device count (the bit-identity gate compares verdict digests
    across topologies, so the workload must not depend on N): clients
    1..C pre-admitted, per-client FIFO clientSeq, a sprinkle of
    unknown-client ops (client 0 = never admitted) so the nack path is
    inside the digest too."""
    import numpy as np

    rng = np.random.default_rng(7)
    client = rng.integers(1, n_clients + 1,
                          (n_docs, ops_per_doc)).astype(np.int32)
    # ~2% unknown-client submissions -> deterministic nacks.
    client[rng.random((n_docs, ops_per_doc)) < 0.02] = 0
    kind = np.full((n_docs, ops_per_doc), 0, np.int32)  # SUB_OP
    cseq = np.zeros((n_docs, ops_per_doc), np.int32)
    counts = np.zeros((n_docs, n_clients + 1), np.int32)
    rows = np.arange(n_docs)
    for j in range(ops_per_doc):
        c = client[:, j]
        counts[rows, c] += 1
        cseq[:, j] = counts[rows, c]
    ref = np.zeros((n_docs, ops_per_doc), np.int32)
    return kind, client, cseq, ref


def _multichip_child_main() -> None:
    """Subprocess entry for one device count (the XLA forced-host flag
    only acts before the first jax import — hence one process per N):
    compile untimed (warm-up cost reported as `warmup_s`), then run
    `repeats` timed passes of the full [D, B] sequencer batch over the
    N-device mesh and report one DONE json line with the verdict
    digest the parent gates bit-identity on. The mesh and compiled
    kernel are the PROCESS-WIDE shared objects
    (`parallel.mesh.shared_docs_mesh` +
    `sequencer_kernel.sharded_sequence_fn`'s cache), so every repeat
    reuses one mesh/device set."""
    import sys

    n_devices = int(sys.argv[1])
    n_docs, ops_per_doc, n_clients, repeats = (
        int(a) for a in sys.argv[2:6]
    )
    # Optional 2-D device-plane spec ("DxM"): the sequencer then runs
    # on the plane's 1-D docs-axis SLICE (`DevicePlane.seq_mesh(0)`) —
    # the config15 form where the model axis exists in the process
    # (forced docs*model devices) but ordering tiles one column of it.
    plane_spec = sys.argv[6] if len(sys.argv) > 6 else ""
    import hashlib

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops import sequencer_kernel as _sk

    kind, client, cseq, ref = _multichip_workload(
        n_docs, ops_per_doc, n_clients
    )
    from ..server.deli_kernel import _pow2

    groups = np.full((n_docs, ops_per_doc), _sk.NO_GROUP, np.int32)
    admitted = np.zeros((n_docs, _pow2(n_clients + 1, lo=2)), bool)
    admitted[:, 1:n_clients + 1] = True

    mesh = None
    if plane_spec:
        from ..parallel.device_plane import shared_plane, \
            parse_plane_spec

        mesh = shared_plane(*parse_plane_spec(plane_spec)).seq_mesh(0)
    elif n_devices > 1:
        from ..parallel.mesh import shared_docs_mesh

        mesh = shared_docs_mesh(n_devices)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        sh = NamedSharding(mesh, PartitionSpec("docs"))
        fn = _sk.sharded_sequence_fn(mesh)

        def place(state):
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sh), state
            )
    else:
        fn = None

        def place(state):
            return state

    batch = _sk.SeqBatch(
        kind=jnp.asarray(kind), client=jnp.asarray(client),
        client_seq=jnp.asarray(cseq), ref_seq=jnp.asarray(ref),
    )
    jgroups = jnp.asarray(groups)

    def one_pass():
        state = place(_sk.make_state(
            n_docs, admitted.shape[1]
        )._replace(connected=jnp.asarray(admitted)))
        if fn is not None:
            state, _, res = fn(
                state, _sk.no_aborts(n_docs), batch, jgroups
            )
        else:
            state, _, res = _sk.sequence_batch_grouped(
                state, batch, jgroups
            )
        jax.block_until_ready(res.seq)
        return res

    t0 = time.perf_counter()
    res = one_pass()  # compile + first run, untimed
    warmup_s = time.perf_counter() - t0
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        res = one_pass()
        best = min(best, time.perf_counter() - t0)
    h = hashlib.sha256()
    for a in (res.seq, res.min_seq, res.nack):
        h.update(np.ascontiguousarray(jax.device_get(a)).tobytes())
    ops = n_docs * ops_per_doc
    print("DONE " + json.dumps({
        "n_devices": n_devices,
        "plane": plane_spec or None,
        "platform": jax.devices()[0].platform,
        "visible_devices": len(jax.devices()),
        "seconds": round(best, 6),
        "warmup_s": round(warmup_s, 4),
        "ops": ops,
        "ops_per_sec": round(ops / best, 1),
        "digest": h.hexdigest(),
    }), flush=True)


def run_multichip_bench(devices: Tuple[int, ...] = (1, 4, 8),
                        n_docs: int = 4096, ops_per_doc: int = 64,
                        n_clients: int = 8, repeats: int = 3) -> dict:
    """Aggregate sequencer ops/s across device counts, bit-identity
    gated: the SAME [D, B] workload is sequenced under every N in
    `devices` (one subprocess per N — real accelerator devices when
    the host has them, otherwise N forced virtual host CPU devices,
    `utils.devices`), and every topology's verdict digest must equal
    the single-device one before any number is reported.

    The report carries per-N `warmup_s` (compile + first pass — the
    cost each fresh process pays before the mesh/kernel caches make
    repeats free) and `forced_host` so a reader can tell real-chip
    scaling from the CPU-CI correctness fallback. Scaling judgment
    lives in `tools/bench_configs.config7_multichip`, which skips the
    ratio assert LOUDLY when `utils.devices.parity_skip_reason` says
    the host cannot measure it honestly."""
    import math

    from ..server.deli_kernel import _mul_of
    from ..utils.devices import run_forced_host_subprocess, \
        visible_devices

    # Every child shards the doc axis over its own device count, and
    # the digest gate compares verdicts across ALL of them — so round
    # the doc count ONCE to a multiple of every requested N (the lcm),
    # not per child, or a non-divisible count crashes the device_put
    # and a per-N round would un-compare the workloads.
    n_docs = _mul_of(n_docs, math.lcm(*(int(n) for n in devices)))
    platform, available = visible_devices()
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    code = ("from fluidframework_tpu.testing.deli_bench import "
            "_multichip_child_main; _multichip_child_main()")
    runs: List[dict] = []
    for n in devices:
        forced = platform in ("cpu", "none") or available < n
        res = run_forced_host_subprocess(
            code, n, cwd=repo,
            argv=[str(n), str(n_docs), str(ops_per_doc),
                  str(n_clients), str(repeats)],
            env=None if forced else dict(os.environ),
        )
        done = [l for l in res.stdout.splitlines()
                if l.startswith("DONE ")]
        assert done, res.stdout[-800:]
        child = json.loads(done[0][5:])
        child["forced_host"] = forced
        runs.append(child)
    # Correctness gate: every topology computed the identical stream.
    digests = {r["digest"] for r in runs}
    assert len(digests) == 1, (
        f"sequencer verdicts diverge across device counts: "
        f"{[(r['n_devices'], r['digest'][:16]) for r in runs]}"
    )
    by_n = {r["n_devices"]: r for r in runs}
    base = min(by_n)
    peak = max(by_n)
    return {
        "metric": "deli_multichip_scaling",
        "docs": n_docs, "ops_per_doc": ops_per_doc,
        "clients_per_doc": n_clients,
        "n_devices": peak,
        "runs": runs,
        "speedup": round(
            by_n[peak]["ops_per_sec"] / by_n[base]["ops_per_sec"], 2
        ),
        "speedup_axis": f"{peak}_vs_{base}_devices",
        "cores": os.cpu_count(),
        "gate": "bit-identical across device counts",
        "unit": "submissions/s",
    }


# ---------------------------------------------------------------------------
# device plane: one 2-D mesh for sequencing AND summary folds
# ---------------------------------------------------------------------------


def fold_parity_skip_reason() -> Optional[str]:
    """None when the overlay-vs-kernel fold speedup can be measured
    honestly on this host (the overlay-pallas kernel actually lowers —
    a real TPU); else the loud-skip reason. Interpreter-mode timing
    measures the pallas interpreter, not the engine, so the
    BENCH_r04/r05 ~38x replay advantage is unmeasurable on CPU CI —
    the digest bit-identity gates still run there."""
    from ..core.overlay_fold import overlay_available

    if overlay_available(False):
        return None
    return (
        "overlay-pallas cannot lower on this host (no TPU backend): "
        "interpreter-mode timing measures the interpreter, not the "
        "engine — the fold-backend speedup is not honestly measurable"
    )


def run_fold_backend_bench(n_docs: int = 4, ops_per_doc: int = 1500,
                           summary_ops: Optional[int] = None,
                           n_clients: int = 4, seed: int = 40,
                           device_plane: Optional[str] = None,
                           repeats: int = 2) -> dict:
    """Kernel vs overlay summarizer fold over IDENTICAL streams — the
    config15 engine. Each backend runs the summarizer's exact
    emission loop (boot-from-rows, encode, stacked fold across docs,
    canonical serialization, rebuild — the restart path every
    cadence) over `n_docs` deterministic merge-tree streams; the
    canonical rows of EVERY emission must be byte-identical across
    backends (the content-addressed no-fork contract) before any
    number is reported, and ``fold_backend_speedup`` =
    kernel_time / overlay_time. On hosts where pallas cannot lower
    the overlay runs the INTERPRETER (`parity_skip_reason` names why
    the speedup is then unmeasurable; the digest gate still ran).
    `device_plane` stacks both backends' fold dispatches over the 2-D
    plane (resolvable in-process — forced host devices or a real
    slice)."""
    import hashlib

    from ..core.overlay_fold import (
        boot_overlay,
        fold_jobs_overlay,
        overlay_available,
    )
    from ..parallel.device_plane import resolve_plane
    from ..server.summarizer import (
        _boot_mergetree,
        _canonical_rows,
        _encode_fold,
        _fold_jobs,
    )

    summary_ops = int(summary_ops or max(64, ops_per_doc // 8))
    plane = resolve_plane(device_plane)
    interpret = not overlay_available(False)
    streams = {
        f"doc{i}": build_mergetree_stream(
            ops_per_doc, n_clients=n_clients, seed=seed + i,
            doc=f"doc{i}",
        )
        for i in range(n_docs)
    }
    rec_len = max(len(r) for r in streams.values())

    def one_run(backend: str):
        def boot(rows, msn):
            if backend == "overlay":
                return boot_overlay(rows, msn, interpret=interpret)
            return _boot_mergetree(rows, msn)

        reps: Dict[str, Any] = {}
        state: Dict[str, tuple] = {d: ([], 0) for d in streams}
        msn_run: Dict[str, int] = {d: 0 for d in streams}
        digests: List[str] = []
        t0 = time.perf_counter()
        for lo in range(0, rec_len, summary_ops):
            jobs = []
            triggers = []
            for doc, recs in streams.items():
                take = recs[lo: lo + summary_ops]
                if not take:
                    continue
                rows, base_msn = state[doc]
                rep = reps.get(doc)
                if rep is None:
                    rep = reps[doc] = boot(rows, base_msn)
                _encode_fold(rep, take)
                msn_run[doc] = max(
                    msn_run[doc], max(r["msn"] for r in take)
                )
                jobs.append((rep, take))
                triggers.append((doc, rep, msn_run[doc]))
            if not jobs:
                continue
            if backend == "overlay":
                fold_jobs_overlay(jobs, plane=plane,
                                  interpret=interpret)
            else:
                _fold_jobs(jobs, plane=plane)
            for doc, rep, msn in triggers:
                rows = (rep.canonical_rows(msn) if backend == "overlay"
                        else _canonical_rows(rep, msn))
                digests.append(hashlib.sha256(
                    json.dumps(rows, sort_keys=True).encode()
                ).hexdigest())
                state[doc] = (rows, msn)
                reps[doc] = boot(rows, msn)
        return time.perf_counter() - t0, digests

    results = {}
    for backend in ("kernel", "overlay"):
        warm, dig0 = one_run(backend)  # compile + first pass, untimed
        best = float("inf")
        digs = dig0
        for _ in range(max(1, repeats)):
            t, digs = one_run(backend)
            best = min(best, t)
        assert digs == dig0, f"{backend} fold is not deterministic"
        results[backend] = {"seconds": round(best, 4),
                            "warmup_s": round(warm, 4),
                            "digests": digs}
    kd = results["kernel"].pop("digests")
    od = results["overlay"].pop("digests")
    # The gate that ALWAYS runs: blob bytes (canonical rows) identical
    # across backends at every emission point.
    assert kd == od, (
        f"fold backends DIVERGED: {sum(a != b for a, b in zip(kd, od))}"
        f"/{len(kd)} emissions differ"
    )
    speedup = results["kernel"]["seconds"] / max(
        results["overlay"]["seconds"], 1e-9
    )
    return {
        "metric": "summary_fold_backend",
        "docs": n_docs, "ops_per_doc": ops_per_doc,
        "summary_ops": summary_ops, "emissions": len(kd),
        "kernel": results["kernel"], "overlay": results["overlay"],
        "fold_backend_speedup": round(speedup, 2),
        "interpret": interpret,
        "plane": plane.spec() if plane is not None else None,
        "parity_skip_reason": fold_parity_skip_reason(),
        "gate": ("canonical rows bit-identical across fold backends "
                 "at every emission"),
        "unit": "x (kernel_s / overlay_s)",
    }


def _fold_backend_child_main() -> None:
    """Subprocess entry for the fold-backend bench under a forced
    device grid (the plane needs docs*model devices, which only exist
    if the XLA flag preceded the first jax import)."""
    import sys

    n_docs, ops_per_doc, summary_ops, n_clients, repeats = (
        int(a) for a in sys.argv[1:6]
    )
    plane = sys.argv[6] if len(sys.argv) > 6 and sys.argv[6] else None
    res = run_fold_backend_bench(
        n_docs=n_docs, ops_per_doc=ops_per_doc,
        summary_ops=summary_ops or None, n_clients=n_clients,
        device_plane=plane, repeats=repeats,
    )
    print("DONE " + json.dumps(res), flush=True)


def run_device_plane_bench(plane: str = "2x2", n_docs: int = 2048,
                           ops_per_doc: int = 64, n_clients: int = 8,
                           repeats: int = 3, fold_docs: int = 4,
                           fold_ops: int = 1500,
                           fold_summary_ops: Optional[int] = None
                           ) -> dict:
    """The 2-D device-plane composition bench (config15's engine):

    - SEQUENCER on the plane's docs-axis slice vs single-device — the
      same [D, B] workload, verdict digests bit-identical (the
      config7 gate extended to the 2-D layout: the model axis exists
      in the child process, ordering tiles one column of it);
    - SUMMARIZER fold backends stacked over the whole plane — kernel
      vs overlay, canonical rows bit-identical at every emission,
      ``fold_backend_speedup`` reported (honestly measurable only
      where pallas lowers — `fold_parity_skip_reason`).

    One subprocess per leg so the forced-device grid exists before
    the first jax import; real chips are used when present."""
    from ..parallel.device_plane import parse_plane_spec
    from ..server.deli_kernel import _mul_of
    from ..utils.devices import run_forced_host_subprocess, \
        visible_devices

    d, m = parse_plane_spec(plane)
    spec = f"{d}x{m}"
    # Every leg shares one workload; the plane leg shards docs over
    # `d` devices, so a d*m multiple covers every divisibility need.
    n_docs = _mul_of(n_docs, d * m)
    platform, available = visible_devices()
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    seq_code = ("from fluidframework_tpu.testing.deli_bench import "
                "_multichip_child_main; _multichip_child_main()")
    runs: List[dict] = []
    # ALL legs run under the SAME device grid (docs*model forced host
    # devices on emulation hosts): 1 device of it, the classic 1-D
    # docs mesh over `d` of it, and the plane's docs-axis slice.
    # Forcing the grid only into the plane leg would bill the others
    # the whole host's threadpool while the slice pays the
    # per-virtual-device split — a ratio of the emulation artifact,
    # not the sharding. On real accelerator hosts env passes through
    # untouched. The 1-D leg is the PRESERVATION comparator: the 2-D
    # layout must not lose what the 1-D mesh measures on this host.
    forced = platform in ("cpu", "none") or available < d * m
    for child_spec, n_dev in (("", 1), ("", d), (spec, d * m)):
        res = run_forced_host_subprocess(
            seq_code, d * m, cwd=repo,
            argv=[str(n_dev), str(n_docs), str(ops_per_doc),
                  str(n_clients), str(repeats), child_spec],
            env=None if forced else dict(os.environ),
        )
        done = [l for l in res.stdout.splitlines()
                if l.startswith("DONE ")]
        assert done, res.stdout[-800:]
        child = json.loads(done[0][5:])
        child["forced_host"] = forced
        runs.append(child)
    digests = {r["digest"] for r in runs}
    assert len(digests) == 1, (
        f"sequencer verdicts diverge across 1-dev / 1-D mesh / plane "
        f"slice: {[(r['n_devices'], r['digest'][:16]) for r in runs]}"
    )
    seq_speedup = round(
        runs[2]["ops_per_sec"] / runs[0]["ops_per_sec"], 2
    )
    oned_speedup = round(
        runs[1]["ops_per_sec"] / runs[0]["ops_per_sec"], 2
    )
    fold_code = ("from fluidframework_tpu.testing.deli_bench import "
                 "_fold_backend_child_main; _fold_backend_child_main()")
    res = run_forced_host_subprocess(
        fold_code, d * m, cwd=repo,
        argv=[str(fold_docs), str(fold_ops),
              str(fold_summary_ops or 0), "4", "2", spec],
        timeout_s=1800.0,
        env=None if forced else dict(os.environ),
    )
    done = [l for l in res.stdout.splitlines() if l.startswith("DONE ")]
    assert done, res.stdout[-800:]
    fold = json.loads(done[0][5:])
    return {
        "metric": "device_plane",
        "plane": spec,
        "docs": n_docs, "ops_per_doc": ops_per_doc,
        "sequencer": {"runs": runs, "speedup": seq_speedup,
                      "oned_speedup": oned_speedup,
                      "forced_host": forced,
                      "speedup_axis": f"plane_{spec}_vs_1_device"},
        "fold": fold,
        "fold_backend_speedup": fold["fold_backend_speedup"],
        "parity_skip_reason": fold["parity_skip_reason"],
        "cores": os.cpu_count(),
        "gate": ("sequencer digests bit-identical 1-dev vs plane "
                 "slice; fold canonical rows bit-identical across "
                 "backends"),
        "unit": "x (kernel_s / overlay_s)",
    }


# ---------------------------------------------------------------------------
# sharded-fabric scaling bench (config6_shard_scaling's engine)
# ---------------------------------------------------------------------------


def _shard_child_main() -> None:
    """Subprocess entry for one bench shard: warm up untimed (imports +
    jit compile — the cost reported as `warmup_s`, what a fresh
    process pays before the process-wide mesh/jit caches make further
    runs free), announce READY, wait for the go-file barrier, then
    run the timed partition drain and report one DONE json line."""
    import sys

    raw_path, out_dir, impl, log_format, batch_s, go_path = sys.argv[1:7]
    warm_dir = os.path.join(out_dir, "warm")
    os.makedirs(warm_dir, exist_ok=True)
    t0 = time.perf_counter()
    run_pipeline(impl, raw_path, warm_dir, batch=int(batch_s),
                 log_format=log_format)
    warmup_s = time.perf_counter() - t0
    print("READY", flush=True)
    while not os.path.exists(go_path):
        time.sleep(0.005)
    res = run_pipeline(impl, raw_path, out_dir, batch=int(batch_s),
                       log_format=log_format)
    print("DONE " + json.dumps({
        "seconds": res["seconds"], "records": res["records"],
        "outputs": res["outputs"], "out_path": res["out_path"],
        "warmup_s": round(warmup_s, 4),
    }), flush=True)


def _canonical_by_doc(paths: List[str]) -> Dict[str, List[dict]]:
    """Merged per-doc, seq-sorted canonical streams across partition
    output topics — the form sharded and single-partition runs are
    compared in (a doc lives in exactly one partition, so per-doc
    streams merge without interleaving questions)."""
    per_doc: Dict[str, List[dict]] = {}
    for path in paths:
        for rec in _read_canonical(path):
            if rec.get("kind") == "op":
                # inOff/inSrc are per-partition transport bookkeeping
                # (input line offsets differ across shardings, and a
                # ranged successor tags absorbed records with their
                # source) — the same exclusion canonical_record
                # applies.
                per_doc.setdefault(rec["doc"], []).append(
                    {k: v for k, v in rec.items()
                     if k not in ("inOff", "inSrc")}
                )
    for v in per_doc.values():
        v.sort(key=lambda r: r["seq"])
    return per_doc


def run_shard_bench(n_docs: int = 2048, n_clients: int = 8,
                    ops_per_client: int = 2,
                    partitions: Tuple[int, ...] = (1, 4),
                    batch: int = 8192, deli_impl: str = "kernel",
                    log_format: str = "columnar",
                    work_dir: Optional[str] = None,
                    keep: bool = False) -> dict:
    """Aggregate-throughput scaling of the sharded ordering fabric:
    the SAME workload (partition-balanced doc names) drained through P
    parallel partition pipelines — one OS process per partition, the
    exact `run_pipeline` datapath the single-partition bench times —
    for each P in `partitions`. Children warm up untimed (imports, jit)
    behind a READY/go barrier, so the timed window is pure drain.

    Aggregate ops/s per P = total records / slowest partition's drain
    (the fabric is only as done as its last shard). The bit-identity
    gate extends the four-way single-partition gate ACROSS partitions:
    every P's merged per-doc canonical stream must equal the first
    P's, record for record."""
    import subprocess
    import sys

    scratch = work_dir or tempfile.mkdtemp(prefix="shard-bench-")
    os.makedirs(scratch, exist_ok=True)
    try:
        from ..server.columnar_log import make_topic
        from ..server.queue import record_partition
        from ..server.shard_fabric import spread_doc_names

        max_p = max(partitions)
        docs = spread_doc_names(n_docs, max_p)
        workload = build_pipeline_workload(
            n_docs, n_clients, ops_per_client, doc_names=docs
        )
        runs: Dict[int, dict] = {}
        reference: Optional[Dict[str, List[dict]]] = None
        for P in partitions:
            pdir = os.path.join(scratch, f"P{P}")
            os.makedirs(pdir, exist_ok=True)
            shards: List[List[dict]] = [[] for _ in range(P)]
            for rec in workload:
                shards[record_partition(rec, P)].append(rec)
            raw_paths = []
            for p in range(P):
                raw_path = os.path.join(pdir, f"raw-p{p}.jsonl")
                for stale in (raw_path, raw_path + ".clen",
                              raw_path + ".fence"):
                    if os.path.exists(stale):
                        os.remove(stale)
                topic = make_topic(raw_path, log_format)
                for lo in range(0, len(shards[p]), batch):
                    topic.append_many(shards[p][lo:lo + batch])
                raw_paths.append(raw_path)
            go_path = os.path.join(pdir, "go")
            procs = []
            children = []
            try:
                for p in range(P):
                    out_dir = os.path.join(pdir, f"out-p{p}")
                    os.makedirs(out_dir, exist_ok=True)
                    procs.append(subprocess.Popen(
                        [sys.executable, "-c",
                         "from fluidframework_tpu.testing.deli_bench "
                         "import _shard_child_main; _shard_child_main()",
                         raw_paths[p], out_dir, deli_impl, log_format,
                         str(batch), go_path],
                        stdout=subprocess.PIPE, text=True,
                        env=dict(os.environ, JAX_PLATFORMS="cpu"),
                    ))
                for proc in procs:
                    line = (proc.stdout.readline() or "").strip()
                    assert line == "READY", f"shard child failed: {line!r}"
                with open(go_path, "w") as f:
                    f.write("go")
                for proc in procs:
                    out, _ = proc.communicate(timeout=600)
                    assert proc.returncode == 0, out[-800:]
                    done = [l for l in out.splitlines()
                            if l.startswith("DONE ")]
                    assert done, out[-800:]
                    children.append(json.loads(done[0][5:]))
            finally:
                # A failure above (bad READY, crash, timeout) must not
                # orphan siblings spinning on the go-file poll forever.
                for proc in procs:
                    if proc.poll() is None:
                        proc.kill()
                        proc.wait(timeout=10)
            total = sum(c["records"] for c in children)
            wall = max(c["seconds"] for c in children)
            merged = _canonical_by_doc([c["out_path"] for c in children])
            if reference is None:
                reference = merged
            else:
                assert merged == reference, (
                    f"sharded deltas diverge from the "
                    f"{partitions[0]}-partition reference at P={P}"
                )
            runs[P] = {
                "partitions": P, "records": total,
                "aggregate_ops_per_sec": round(total / wall, 1),
                "slowest_partition_s": round(wall, 4),
                "per_partition_records": [c["records"] for c in children],
                # Warm-up cost per shard child (imports + jit compile,
                # untimed behind the READY barrier): each subprocess
                # re-initializes JAX — one process-wide mesh/jit-cache
                # reuse only helps WITHIN a child (warm run + timed run
                # share it); this notes what the per-process split
                # still costs.
                "warmup_s_per_partition": [
                    c.get("warmup_s") for c in children
                ],
            }
        base = min(partitions)
        peak = max(partitions)
        ratio = (runs[peak]["aggregate_ops_per_sec"]
                 / runs[base]["aggregate_ops_per_sec"])
        return {
            "metric": "shard_fabric_scaling",
            "deli_impl": deli_impl, "log_format": log_format,
            "docs": n_docs, "clients_per_doc": n_clients,
            "records": len(workload),
            "runs": [runs[p] for p in partitions],
            "speedup": round(ratio, 2),
            "speedup_axis": f"{peak}_vs_{base}_partitions",
            "cores": os.cpu_count(),
            "gate": "bit-identical across partitions",
            "unit": "records/s",
        }
    finally:
        if not keep and work_dir is None:
            shutil.rmtree(scratch, ignore_errors=True)


def run_rebalance_bench(n_docs: int = 10_000, n_clients: int = 64,
                        ops_per_client: int = 1, n_ranges: int = 4,
                        n_workers: int = 2, deli_impl: str = "kernel",
                        log_format: str = "columnar",
                        ttl_s: float = 0.75, feed_batch: int = 4096,
                        timeout_s: float = 900.0,
                        work_dir: Optional[str] = None) -> dict:
    """Cost of a LIVE topology change: the same workload drained
    through the ELASTIC fabric (`server.shard_fabric`, hash-range
    leases) twice — once on a steady topology, once with a range
    SPLIT committed mid-stream (final fenced checkpoint, epoch bump,
    children absorb the parent's tail while the router re-routes).
    Aggregate ops/s of the split run over the steady run is the
    rebalance cost; the CONVERGENCE gate always runs — both variants'
    merged canonical per-doc streams must be identical with contiguous
    seqs (N changing mid-run must be invisible in the order)."""
    import shutil as _shutil

    from ..server.queue import RangeLeaseStore
    from ..server.shard_fabric import (
        ShardFabricSupervisor,
        ShardRouter,
        spread_doc_names,
    )

    docs = spread_doc_names(n_docs, n_ranges)
    workload = build_pipeline_workload(
        n_docs, n_clients, ops_per_client, doc_names=docs
    )
    expected = len(workload)  # every join + valid op stamps exactly once
    scratch = work_dir or tempfile.mkdtemp(prefix="rebalance-bench-")
    runs: Dict[str, dict] = {}
    reference: Optional[Dict[str, List[dict]]] = None
    try:
        for variant in ("steady", "split"):
            vdir = os.path.join(scratch, variant)
            os.makedirs(vdir, exist_ok=True)
            router = ShardRouter(vdir, n_ranges, log_format,
                                 elastic=True)
            sup = ShardFabricSupervisor(
                vdir, n_workers=n_workers, n_partitions=n_ranges,
                ttl_s=ttl_s, deli_impl=deli_impl,
                log_format=log_format, elastic=True,
            ).start()
            split_cmd = None
            ops_count = 0
            reader = router.merged_reader()
            t0 = time.time()
            try:
                fed = 0
                deadline = t0 + timeout_s
                while time.time() < deadline:
                    sup.poll_once()
                    if fed < len(workload):
                        router.append(workload[fed:fed + feed_batch])
                        fed += feed_batch
                        if (variant == "split" and split_cmd is None
                                and fed >= len(workload) // 2):
                            split_cmd = sup.request_split()
                    ops_count += sum(
                        1 for r in reader.poll()
                        if isinstance(r, dict) and r.get("kind") == "op"
                    )
                    if fed >= len(workload) and ops_count >= expected:
                        break
                    if fed >= len(workload):
                        time.sleep(0.01)
                elapsed = time.time() - t0
            finally:
                sup.stop()
            assert ops_count >= expected, (
                f"{variant}: drained {ops_count}/{expected} within "
                f"{timeout_s}s"
            )
            epoch = RangeLeaseStore(vdir, "__bench__").read_topology()[
                "epoch"
            ]
            if variant == "split":
                assert split_cmd is not None and epoch > 1, (
                    f"split never committed (epoch {epoch})"
                )
            merged = _canonical_by_doc([
                os.path.join(vdir, "topics", f"{name}.jsonl")
                for name in router.deltas_topic_names()
            ])
            for doc, recs in merged.items():
                seqs = [r["seq"] for r in recs]
                assert seqs == list(range(1, len(seqs) + 1)), (
                    f"{variant}: {doc} seqs not contiguous across the "
                    f"rebalance"
                )
            if reference is None:
                reference = merged
            else:
                assert merged == reference, (
                    "split-run stream diverges from the steady run"
                )
            runs[variant] = {
                "variant": variant, "seconds": round(elapsed, 3),
                "ops_per_sec": round(expected / elapsed, 1),
                "epoch": epoch,
            }
        cost_pct = (1.0 - runs["split"]["ops_per_sec"]
                    / runs["steady"]["ops_per_sec"]) * 100.0
        return {
            "metric": "elastic_rebalance",
            "deli_impl": deli_impl, "log_format": log_format,
            "docs": n_docs, "clients_per_doc": n_clients,
            "records": expected, "ranges": n_ranges,
            "workers": n_workers,
            "runs": [runs["steady"], runs["split"]],
            "split_cost_pct": round(cost_pct, 2),
            "cores": os.cpu_count(),
            "gate": "bit-identical steady vs mid-run split",
            "unit": "records/s",
        }
    finally:
        if work_dir is None:
            _shutil.rmtree(scratch, ignore_errors=True)


# ---------------------------------------------------------------------------
# summary catch-up bench (config10_catchup's engine)
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# fused durable+broadcast hop bench (the per-hop fsync floor)
# ---------------------------------------------------------------------------


def _snap_counter(snap: dict, name: str, **labels) -> float:
    """Sum one counter family across a heartbeat metrics snapshot."""
    total = 0.0
    for c in snap.get("counters", ()):
        if c.get("name") != name:
            continue
        lbl = c.get("labels") or {}
        if all(lbl.get(k) == v for k, v in labels.items()):
            total += float(c.get("value", 0))
    return total


def run_hop_bench(n_docs: int = 64, n_clients: int = 8,
                  ops_per_client: int = 4,
                  log_format: str = "columnar",
                  deli_impl: str = "kernel",
                  timeout_s: float = 180.0) -> dict:
    """Classic vs FUSED downstream topology over ONE pre-staged
    workload: records cross deli → durable → broadcast either through
    the split {scriptorium, broadcaster} pair (two consumers — two
    process wakes and two fsyncs per batch on the hop pair) or through
    the fused `ScriptoriumBroadcasterRole` (one consumer — one wake,
    ~one fsync: the broadcast leg appends unfsynced and recovery
    regenerates it). Reports each topology's drain throughput and the
    hop pair's fsyncs-per-record (read from the children's heartbeat
    metrics — the `topic_fsyncs_total` evidence), and GATES
    bit-identity: both topologies must produce identical durable and
    broadcast streams."""
    from ..server.columnar_log import make_topic
    from ..server.supervisor import ServiceSupervisor

    workload = build_pipeline_workload(n_docs, n_clients, ops_per_client)
    expected = len(workload)  # every join/op in this workload stamps
    per_mode: Dict[str, dict] = {}
    streams: Dict[str, tuple] = {}
    for mode in ("split", "fused"):
        shared = tempfile.mkdtemp(prefix=f"hop-bench-{mode}-")
        sup = ServiceSupervisor(
            shared, roles=("deli", "scriptorium", "broadcaster"),
            ttl_s=2.0, heartbeat_timeout_s=20.0, batch=4096,
            deli_impl=deli_impl, log_format=log_format,
            fused_hop=(mode == "fused"), hb_interval_s=0.2,
        ).start()
        try:
            topics = {
                name: make_topic(
                    os.path.join(shared, "topics", f"{name}.jsonl"),
                    log_format,
                )
                for name in ("rawdeltas", "durable", "broadcast")
            }
            t0 = time.perf_counter()
            for lo in range(0, expected, 4096):
                topics["rawdeltas"].append_many(workload[lo:lo + 4096])
            deadline = time.time() + timeout_s
            dur = bc = []
            while time.time() < deadline:
                sup.poll_once()
                dur = topics["durable"].read_from(0)
                bc = topics["broadcast"].read_from(0)
                if len(dur) >= expected and len(bc) >= expected:
                    break
                time.sleep(0.02)
            else:
                raise AssertionError(
                    f"hop bench ({mode}) never drained: "
                    f"{len(dur)}/{len(bc)} of {expected}"
                )
            seconds = time.perf_counter() - t0
            time.sleep(0.5)  # one post-drain throttled heartbeat each
            snaps = sup.child_metrics()
        finally:
            sup.stop()
            shutil.rmtree(shared, ignore_errors=True)
        hop_fsyncs = sum(
            _snap_counter(snaps[r], "topic_fsyncs_total", kind="topic")
            for r in snaps if r != "deli"
        )
        per_mode[mode] = {
            "seconds": round(seconds, 3),
            "ops_per_sec": round(expected / seconds, 1),
            "hop_pair_fsyncs": int(hop_fsyncs),
            "hop_pair_fsyncs_per_record": round(
                hop_fsyncs / expected, 4
            ),
            "downstream_consumers": len(snaps) - 1,
            "emit_columns_records": int(sum(
                _snap_counter(snaps[r], "codec_encode_columns_total")
                for r in snaps
            )),
        }
        streams[mode] = (dur, bc)
    # Bit-identity gate: the fused hop must carry EXACTLY the split
    # pair's records, in order, on both legs.
    assert streams["split"][0] == streams["fused"][0], (
        "durable streams diverge between split and fused topologies"
    )
    assert streams["split"][1] == streams["fused"][1], (
        "broadcast streams diverge between split and fused topologies"
    )
    split_f = per_mode["split"]["hop_pair_fsyncs"]
    fused_f = per_mode["fused"]["hop_pair_fsyncs"]
    return {
        "metric": "fused_hop_farm",
        "records": expected,
        "log_format": log_format,
        "deli_impl": deli_impl,
        "split": per_mode["split"],
        "fused": per_mode["fused"],
        "hop_fsync_reduction": round(split_f / max(1, fused_f), 2),
        "fused_vs_split_ops": round(
            per_mode["fused"]["ops_per_sec"]
            / per_mode["split"]["ops_per_sec"], 2
        ),
        "gate": "bit-identical",
        "unit": "fsyncs/record",
    }


def run_ingress_bench(n_docs: int = 2000, n_clients: int = 16,
                      ops_per_client: int = 2, n_partitions: int = 2,
                      log_format: str = "json",
                      overload_backlog: int = 64,
                      overload_records: int = 1200) -> dict:
    """The front-door guard's engine (bench_configs
    ``config12_front_door``): admission cost + the overload episode.

    Phase 1 — ADMISSION: the config-5-shape workload (auth ON, per-doc
    signed tokens) driven through an in-proc `IngressRole` vs the bare
    `ShardRouter` append the pre-front-door edge used, vs the batched
    scalar deli sequencing the same stream. `admission_overhead_pct`
    is the end-to-end cost in the farm's PIPELINED topology (stages in
    separate processes): zero while admission outruns sequencing, the
    bottleneck slowdown once it doesn't — the number config12 holds
    under 5%. The serial view (extra hop + checks as a fraction of
    sequencing work) is reported as `serial_overhead_pct`.

    Phase 2 — OVERLOAD: a single-partition storm fed faster than a
    deliberately slow deli drains, with a small backlog budget: the
    rawdeltas backlog must stay BOUNDED (budget + one in-flight batch
    per refresh lag) while throttle nacks flow, and once the feeder
    stops, retried submits drain and sequence EXACTLY once — overload
    degrades visibly, it never grows the log unboundedly or loses an
    acknowledged record. Both phases gate correctness before any
    number is reported."""
    from ..server.columnar_log import make_topic
    from ..server.ingress import IngressRole, write_tenants
    from ..server.riddler import sign_token
    from ..server.shard_fabric import ShardRouter, spread_doc_names
    from ..server.supervisor import DeliRole, _topic_path

    scratch = tempfile.mkdtemp(prefix="ingress-bench-")
    try:
        docs = spread_doc_names(n_docs, n_partitions)
        workload = build_pipeline_workload(
            n_docs, n_clients, ops_per_client, doc_names=docs
        )
        n = len(workload)
        # --- phase 1: admission throughput -------------------------
        # Session auth (the alfred connection shape): one auth record
        # per (doc, client) opens the session, the op stream rides
        # BARE — per-record admission is a session probe, not an HMAC.
        key = "bench-key"
        tokens = {d: sign_token(key, "t0", d, ["doc:write"],
                                lifetime_s=24 * 3600.0) for d in docs}
        auth_recs = [
            {"kind": "auth", "doc": d, "client": c, "tenant": "t0",
             "token": tokens[d]}
            for d in docs for c in range(1, n_clients + 1)
        ]
        def timed_admission(root: str) -> float:
            d = os.path.join(scratch, root)
            write_tenants(d, {"t0": key})
            t = make_topic(os.path.join(d, "topics", "ingress.jsonl"),
                           log_format)
            t.append_many(auth_recs)
            ing = IngressRole(d, "bench-ingress", ttl_s=3600.0,
                              batch=8192, log_format=log_format,
                              n_partitions=n_partitions)
            while ing.step() > 0:
                pass  # session setup: connect-time cost, untimed
            for i in range(0, n, 8192):
                t.append_many(workload[i:i + 8192])
            t0 = time.perf_counter()
            while ing.step() > 0:
                pass
            dt = time.perf_counter() - t0
            # Everything valid must be admitted — the correctness
            # gate before any number.
            admitted = sum(ing._routed.values())
            assert admitted == n, f"admitted {admitted}/{n}"
            return dt

        def timed_sequencing(root: str) -> float:
            d = os.path.join(scratch, root)
            raw = make_topic(_topic_path(d, "rawdeltas"), log_format)
            for i in range(0, n, 8192):
                raw.append_many(workload[i:i + 8192])
            deli = DeliRole(d, "bench-deli", ttl_s=3600.0,
                            batch=8192, log_format=log_format)
            t0 = time.perf_counter()
            while deli.step() > 0:
                pass
            return time.perf_counter() - t0

        # Best of two per loop: the two rates sit close by design
        # (both are one read+transform+append pass), so scheduler
        # noise would otherwise dominate the overhead ratio.
        t_ing = min(timed_admission("adm1"), timed_admission("adm2"))
        t_seq = min(timed_sequencing("seq1"), timed_sequencing("seq2"))
        # Bare routing baseline (the old ingress edge).
        route_dir = os.path.join(scratch, "route")
        router = ShardRouter(route_dir, n_partitions, log_format)
        t0 = time.perf_counter()
        for i in range(0, n, 8192):
            router.append(workload[i:i + 8192])
        t_route = time.perf_counter() - t0
        # Overhead in the farm's PIPELINED topology: stages run as
        # separate processes, so the front door costs end-to-end
        # throughput only where admission becomes the new bottleneck —
        # overhead = how much slower min(admission, sequencing) runs
        # than sequencing alone. The SERIAL view (the extra hop +
        # checks as a fraction of sequencing work) rides alongside as
        # `serial_overhead_pct`.
        adm_rate = n / max(1e-9, t_ing)
        seq_rate = n / max(1e-9, t_seq)
        overhead_pct = max(
            0.0, seq_rate / min(adm_rate, seq_rate) - 1.0
        ) * 100
        serial_overhead_pct = \
            max(0.0, t_ing - t_route) / max(1e-9, t_seq) * 100
        # --- phase 2: overload ------------------------------------
        ov_dir = os.path.join(scratch, "ov")
        ov_ing = IngressRole(
            ov_dir, "ov-ingress", ttl_s=3600.0, batch=64,
            log_format=log_format, backlog_max=overload_backlog,
            backlog_poll_s=0.0,  # exact backlog per record: the bound
            #                      is then budget + one admit batch
            retry_after_s=0.01,
        )
        ov_deli = DeliRole(ov_dir, "ov-deli", ttl_s=3600.0, batch=16,
                           log_format=log_format)
        ov_topic = make_topic(
            os.path.join(ov_dir, "topics", "ingress.jsonl"), log_format
        )
        raw_topic = make_topic(
            _topic_path(ov_dir, "rawdeltas"), log_format
        )
        storm = [{"kind": "op", "doc": "hotdoc", "client": 1,
                  "clientSeq": i + 1, "refSeq": 0, "contents": {"i": i}}
                 for i in range(overload_records)]
        storm.insert(0, {"kind": "join", "doc": "hotdoc", "client": 1})
        max_backlog = 0
        fed = 0
        while fed < len(storm):
            chunk = storm[fed:fed + 64]
            fed += len(chunk)
            ov_topic.append_many(chunk)
            ov_ing.step()   # admits up to the gate, throttle-nacks past
            ov_deli.step()  # drains slower than the feed by design
            entries, total = raw_topic.read_entries(0)
            max_backlog = max(max_backlog, total - ov_deli.offset)
        budget = overload_backlog + 64  # + one admit batch of slack
        assert max_backlog <= budget, (
            f"overload backlog {max_backlog} burst past the bound "
            f"{budget} (backlog_max={overload_backlog})"
        )
        nacks_topic = make_topic(
            os.path.join(ov_dir, "topics", "nacks.jsonl"), log_format
        )
        throttled = [r for r in nacks_topic.read_from(0)
                     if isinstance(r, dict) and str(
                         r.get("reason", "")).startswith("backpressure")]
        assert throttled, "overload produced no throttle nacks"
        # Retry-and-converge (the real client contract): resubmit the
        # remaining tail in ascending clientSeq windows until the
        # whole storm is sequenced. Admission gates admit PREFIXES of
        # an ascending batch (the backlog estimate is monotone within
        # one pump), so per-client order survives the retries and the
        # deli's dedup silences every duplicate copy.
        retries = 0
        deadline = time.time() + 120.0
        deltas_topic = make_topic(
            _topic_path(ov_dir, "deltas"), log_format
        )
        ops: List[dict] = []
        while time.time() < deadline:
            ops = [r for r in deltas_topic.read_from(0)
                   if isinstance(r, dict) and r.get("kind") == "op"
                   and r.get("type") == "op"]
            if len(ops) >= overload_records:
                break
            frontier = max((r["clientSeq"] for r in ops), default=0)
            window = [r for r in storm if r["kind"] == "op"
                      and frontier < r["clientSeq"] <= frontier + 64]
            retries += len(window)
            ov_topic.append_many(window)
            ov_ing.step()
            ov_deli.step()
        keys = [(r["doc"], r["client"], r["clientSeq"]) for r in ops]
        assert len(ops) == overload_records and \
            len(set(keys)) == overload_records, (
                f"overload storm did not converge exactly-once: "
                f"{len(ops)} ops, {len(set(keys))} unique"
            )
        return {
            "metric": "ingress_front_door",
            "records": n,
            "partitions": n_partitions,
            "log_format": log_format,
            "ops_per_sec": round(n / t_ing, 1),  # admission (headline)
            "route_ops_per_sec": round(n / t_route, 1),
            "sequencing_ops_per_sec": round(n / t_seq, 1),
            "admission_overhead_pct": round(overhead_pct, 2),
            "serial_overhead_pct": round(serial_overhead_pct, 2),
            "overload": {
                "records": overload_records,
                "backlog_max": overload_backlog,
                "max_backlog_seen": int(max_backlog),
                "backlog_bound": budget,
                "throttle_nacks": len(throttled),
                "retries": retries,
                "sequenced_exactly_once": True,
            },
            "gate": ("all valid records admitted; overload backlog "
                     "bounded with visible throttle nacks; storm "
                     "retried to exactly-once convergence"),
            "unit": "records/s",
        }
    finally:
        shutil.rmtree(scratch, ignore_errors=True)


def build_mergetree_stream(n_ops: int, n_clients: int = 4,
                           seed: int = 10, doc: str = "doc0",
                           window: int = 64,
                           target_len: int = 400) -> List[dict]:
    """A deterministic SEQUENCED deltas stream of merge-tree wire ops:
    joins, then `n_ops` sequential insert/remove/annotate ops whose
    positions are valid at their refSeq (= seq-1) perspective, with
    the msn trailing by `window` (so summaries stay window-bounded)
    and document length hovering around `target_len` (so per-op kernel
    cost — O(live rows) — is flat and the log-length axis isolates
    replay cost, the thing summaries remove). A PREFIX of the stream
    is itself a valid stream, so one build serves every swept log
    length."""
    import random
    import string

    rng = random.Random(seed)
    recs: List[dict] = []
    seq = 0
    for c in range(1, n_clients + 1):
        seq += 1
        recs.append({"kind": "op", "doc": doc, "seq": seq, "msn": 0,
                     "client": c, "clientSeq": 0, "refSeq": seq - 1,
                     "type": "join", "contents": c})
    length = 0
    cseq = {c: 0 for c in range(1, n_clients + 1)}
    for _ in range(n_ops):
        c = rng.randint(1, n_clients)
        seq += 1
        cseq[c] += 1
        msn = max(0, seq - window)
        r = rng.random()
        p_ins = 0.45 if length < target_len else 0.25
        if length == 0 or r < p_ins:
            pos = rng.randint(0, length)
            text = "".join(
                rng.choices(string.ascii_lowercase, k=rng.randint(1, 6))
            )
            contents: dict = {"type": 0, "pos1": pos, "seg": text}
            length += len(text)
        elif r < p_ins + 0.35:
            a = rng.randint(0, length - 1)
            b = min(length, a + rng.randint(1, 6))
            contents = {"type": 1, "pos1": a, "pos2": b}
            length -= b - a
        else:
            a = rng.randint(0, length - 1)
            b = min(length, a + rng.randint(1, 8))
            contents = {"type": 2, "pos1": a, "pos2": b,
                        "props": {rng.choice(["bold", "color", "size"]):
                                  rng.choice([1, 2, "x", None])}}
        recs.append({"kind": "op", "doc": doc, "seq": seq, "msn": msn,
                     "client": c, "clientSeq": cseq[c],
                     "refSeq": seq - 1, "type": "op",
                     "contents": contents})
    return recs


def _drive_summarizer(shared: str, log_format: str,
                      summary_ops: int, batch: int = 4096) -> dict:
    """Run the summarizer ROLE datapath (deltas → summaries + blobs)
    to quiescence over an already-written deltas topic — the exact
    fold/emit path the supervised child runs, minus lease upkeep (the
    `run_pipeline` pattern)."""
    from ..server.columnar_log import make_tail_reader, make_topic
    from ..server.summarizer import SummarizerRole

    deltas = make_topic(
        os.path.join(shared, "topics", "deltas.jsonl"), log_format
    )
    role = SummarizerRole(shared, owner="bench-summ", ttl_s=3600.0,
                          log_format=log_format,
                          summary_ops=summary_ops)
    role.fence = 1
    reader = make_tail_reader(deltas)
    # The counter is process-global (shared registry labels): report
    # THIS run's delta, not the cumulative across swept lengths.
    summ0 = int(role._m_summaries.value)
    n = 0
    t0 = time.perf_counter()
    while True:
        entries = reader.poll(batch)
        if not entries:
            break
        out: List[dict] = []
        for line_idx, rec in entries:
            role.process(line_idx, rec, out)
        role.flush_batch(out)
        if out:
            role.out_topic.append_many(out, fence=1, owner="bench-summ")
        role.offset = reader.next_line
        n += len(entries)
    return {"seconds": time.perf_counter() - t0, "records": n,
            "summaries": int(role._m_summaries.value) - summ0}


def run_fanout_bench(n_records: int = 2000, n_subscribers: int = 200,
                     batch: int = 256,
                     work_dir: Optional[str] = None) -> dict:
    """Broadcast fan-out through the doorbell-woken read front end
    (`socket_service.FarmTailPusher`): N subscribed readers on one
    partition's broadcast tail, aggregate deliveries/s — the
    hundreds-of-subscribed-clients shape of the read-heavy workload."""
    from ..server.queue import SharedFileTopic
    from ..server.socket_service import FarmTailPusher

    scratch = work_dir or tempfile.mkdtemp(
        prefix="fanout-bench-",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    try:
        path = os.path.join(scratch, "topics", "broadcast.jsonl")
        topic = SharedFileTopic(path)
        pusher = FarmTailPusher(path, "json").start()
        import threading

        got = [0] * n_subscribers
        done = threading.Event()

        def sub(i):
            def fn(recs):
                got[i] += len(recs)
                if got[i] >= n_records and all(
                    g >= n_records for g in got
                ):
                    done.set()
            return fn

        for i in range(n_subscribers):
            pusher.subscribe("doc0", sub(i))
        recs = [{"kind": "op", "doc": "doc0", "seq": i + 1, "msn": 0,
                 "client": 1, "clientSeq": i + 1, "refSeq": 0,
                 "type": "op", "contents": {"i": i}}
                for i in range(n_records)]
        t0 = time.perf_counter()
        for lo in range(0, n_records, batch):
            topic.append_many(recs[lo:lo + batch])
        assert done.wait(timeout=120.0), (
            f"fan-out never completed: {min(got)}/{n_records} at the "
            f"slowest subscriber"
        )
        elapsed = time.perf_counter() - t0
        pusher.stop()
        total = n_records * n_subscribers
        return {
            "records": n_records, "subscribers": n_subscribers,
            "seconds": round(elapsed, 4),
            "deliveries_per_sec": round(total / elapsed, 1),
        }
    finally:
        if work_dir is None:
            shutil.rmtree(scratch, ignore_errors=True)


def run_catchup_bench(log_lengths: Tuple[int, ...] = (10_000, 30_000,
                                                      100_000),
                      summary_ops: int = 2000, n_clients: int = 4,
                      n_subscribers: int = 200,
                      log_format: str = "json",
                      work_dir: Optional[str] = None) -> dict:
    """Cold-join latency vs log length, with and without summaries —
    the read side the summary service exists for.

    For each swept log length L (prefixes of ONE deterministic
    merge-tree stream): write the deltas topic, run the summarizer
    role datapath over it, then measure a cold join both ways —
    full-log replay through the merge-tree kernel
    (`summarizer.SummaryReplica(None)`, what every joiner paid before
    this service) vs nearest summary + op tail
    (`summarizer.read_catchup` + blob boot). The CORRECTNESS gate
    always runs: both joins must land on the identical document-state
    digest at every L. Headline: `speedup` (full replay / summary
    join at the largest L) and `join_flatness` (summary-join time at
    max L over min L — flat means ~1). A broadcast fan-out leg
    (`run_fanout_bench`) rides along."""
    from ..server.columnar_log import make_topic
    from ..server.summarizer import (
        SummaryReplica,
        open_summary_store,
        read_catchup,
    )

    scratch = work_dir or tempfile.mkdtemp(prefix="catchup-bench-")
    try:
        lengths = tuple(sorted(set(int(x) for x in log_lengths)))
        # A scaled-down sweep (BD_SCALE/BC_SCALE) must still produce a
        # summary at the SMALLEST length, or the correctness gate has
        # nothing to check and the run crashes where config10 promises
        # a loud skip — clamp the cadence so every swept length emits
        # several (full scale: 2000 < 10000//4, unchanged).
        summary_ops = max(16, min(int(summary_ops), lengths[0] // 4))
        stream = build_mergetree_stream(max(lengths),
                                        n_clients=n_clients)
        joins = n_clients  # the join records ride ahead of the ops
        # Warm-up: one full untimed mini-cycle (summarize + cold
        # replay + summary boot) so the timed region never compiles —
        # the boot path jits its own table shapes, not just the cold
        # replay's (the standard bench contract).
        warm_L = min(1024, lengths[0])
        warm_dir = os.path.join(scratch, "warm")
        os.makedirs(os.path.join(warm_dir, "topics"), exist_ok=True)
        warm_prefix = stream[: joins + warm_L]
        make_topic(
            os.path.join(warm_dir, "topics", "deltas.jsonl"), log_format
        ).append_many(warm_prefix)
        _drive_summarizer(warm_dir, log_format,
                          max(64, min(summary_ops, warm_L // 2)))
        warm = SummaryReplica(None)
        warm.apply_records(warm_prefix)
        wcu = read_catchup(warm_dir, "doc0", log_format,
                           store=open_summary_store(warm_dir))
        wboot = SummaryReplica(wcu["blob"]) if wcu["blob"] else \
            SummaryReplica(None)
        wboot.apply_records(wcu["ops"])
        runs: List[dict] = []
        for L in lengths:
            ldir = os.path.join(scratch, f"L{L}")
            os.makedirs(os.path.join(ldir, "topics"), exist_ok=True)
            prefix = stream[: joins + L]
            deltas = make_topic(
                os.path.join(ldir, "topics", "deltas.jsonl"), log_format
            )
            for lo in range(0, len(prefix), 16384):
                deltas.append_many(prefix[lo:lo + 16384])
            summ = _drive_summarizer(ldir, log_format, summary_ops)
            store = open_summary_store(ldir)

            t0 = time.perf_counter()
            cold = SummaryReplica(None)
            cold.apply_records(prefix)
            cold_s = time.perf_counter() - t0

            t0 = time.perf_counter()
            cu = read_catchup(ldir, "doc0", log_format, store=store)
            boot = SummaryReplica(cu["blob"]) if cu["blob"] else \
                SummaryReplica(None)
            boot.apply_records(cu["ops"])
            warm_s = time.perf_counter() - t0

            # Correctness gate (ALWAYS): identical document state.
            assert cu["manifest"] is not None, f"no summary at L={L}"
            assert boot.state_digest() == cold.state_digest(), (
                f"summary+tail boot diverges from full replay at L={L}"
            )
            runs.append({
                "log_len": L,
                "full_replay_ms": round(cold_s * 1000.0, 2),
                "summary_join_ms": round(warm_s * 1000.0, 2),
                "speedup": round(cold_s / warm_s, 2),
                "summary_seq": cu["manifest"]["seq"],
                "tail_ops": len(cu["ops"]),
                "blob_bytes": cu["manifest"]["bytes"],
                "summarize_s": round(summ["seconds"], 3),
                "summaries": summ["summaries"],
            })
        lo, hi = runs[0], runs[-1]
        fanout = run_fanout_bench(n_subscribers=n_subscribers)
        return {
            "metric": "summary_catchup",
            "log_format": log_format,
            "summary_ops": summary_ops,
            "runs": runs,
            "speedup": hi["speedup"],
            "speedup_axis": f"full_replay_vs_summary_join_at_"
                            f"{hi['log_len']}_ops",
            "join_flatness": round(
                hi["summary_join_ms"] / max(1e-9, lo["summary_join_ms"]),
                2,
            ),
            "fanout": fanout,
            "cores": os.cpu_count(),
            "gate": "summary+tail boot bit-identical to full replay "
                    "at every length",
            "unit": "ratio",
        }
    finally:
        if work_dir is None:
            shutil.rmtree(scratch, ignore_errors=True)


# ---------------------------------------------------------------------------
# open-loop latency SLO bench (config9_latency's engine)
# ---------------------------------------------------------------------------


def _exact_quantile(sorted_vals: List[float], q: float) -> float:
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(q * (len(sorted_vals) - 1)))]


def _span_quantiles(samples: List[float]) -> dict:
    s = sorted(samples)
    return {"count": len(s),
            "mean": round(sum(s) / len(s), 3),
            "p50": round(_exact_quantile(s, 0.5), 3),
            "p95": round(_exact_quantile(s, 0.95), 3),
            "p99": round(_exact_quantile(s, 0.99), 3)}


def _run_latency_variant(shared: str, doorbell: bool, rate_hz: float,
                         duration_s: float, n_docs: int, n_clients: int,
                         ttl_s: float, timeout_s: float,
                         fused_hop: bool = False) -> dict:
    """One open-loop run against the supervised farm: fixed-rate
    submits (never waiting on completion — OPEN loop, so a backlogged
    pipeline shows up as latency, not as a silently slower load), wire
    traces on, spans read back off the broadcast/durable tails.
    `fused_hop` collapses scriptorium+broadcaster into the fused
    consumer — same topics, one fewer wake in the path — so the
    open-loop p99 delta of the fused hop is measurable at the same
    load (ROADMAP item-1 follow-up c)."""
    from ..server.queue import SharedFileTopic, TailReader
    from ..server.supervisor import ServiceSupervisor
    from ..utils import metrics as _metrics

    sup = ServiceSupervisor(
        shared, roles=("deli", "scriptorium", "broadcaster"),
        ttl_s=ttl_s, fused_hop=fused_hop,
        child_env={"FLUID_TRACE_WIRE": "1",
                   "FLUID_DOORBELL": "1" if doorbell else "0"},
        # Heartbeat throttle for BOTH variants (identical treatment):
        # a trace-mode registry snapshot per record is pure tail
        # latency, and liveness only needs ~Hz.
        hb_interval_s=0.1,
    ).start()
    try:
        raw = SharedFileTopic(os.path.join(shared, "topics",
                                           "rawdeltas.jsonl"))
        bc_reader = TailReader(SharedFileTopic(
            os.path.join(shared, "topics", "broadcast.jsonl")))
        dur_reader = TailReader(SharedFileTopic(
            os.path.join(shared, "topics", "durable.jsonl")))
        docs = [f"doc{d}" for d in range(n_docs)]
        raw.append_many([
            {"kind": "join", "doc": doc, "client": c}
            for doc in docs for c in range(1, n_clients + 1)
        ])
        # Warm: every join sequenced and broadcast (the pipeline is
        # live end to end before the timed window opens).
        want_joins = n_docs * n_clients
        bcast: List[dict] = []
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            sup.poll_once()
            bcast.extend(v for _, v in bc_reader.poll())
            if sum(1 for r in bcast
                   if isinstance(r, dict) and r.get("kind") == "op"
                   ) >= want_joins:
                break
            time.sleep(0.01)
        else:
            raise AssertionError(
                f"farm never came live: {len(bcast)} broadcast records "
                f"for {want_joins} joins"
            )
        bcast.clear()
        # Steady open-loop window, after a short PACED lead-in at the
        # same rate (cold paths — first checkpoint, instrument
        # creation, first bell registration — are start-up cost, not
        # steady-state SLO; the lead-in ops are still span-verified
        # below, just excluded from the quantiles).
        lead_in = 8
        total = max(16, int(rate_hz * duration_s)) + lead_in
        cseq = {(d, c): 0 for d in docs for c in range(1, n_clients + 1)}
        keys = sorted(cseq)
        t0 = time.perf_counter()
        last_sup = 0.0
        lead_keys = set()
        for i in range(total):
            tick = t0 + i / rate_hz
            while True:
                now = time.perf_counter()
                if now >= tick:
                    break
                # Drain tails while waiting so the bench process never
                # bursts; the spans themselves come from wire stamps,
                # not from observation time.
                bcast.extend(v for _, v in bc_reader.poll())
                if now - last_sup > 0.2:
                    sup.poll_once()
                    last_sup = now
                time.sleep(min(0.002, tick - now))
            d, c = keys[i % len(keys)]
            cseq[(d, c)] += 1
            if i < lead_in:
                lead_keys.add((d, c, cseq[(d, c)]))
            raw.append_many([{
                "kind": "op", "doc": d, "client": c,
                "clientSeq": cseq[(d, c)], "refSeq": 0,
                "contents": {"i": i}, "tr_sub": time.time(),
            }])
        # Drain: every submitted op must reach broadcast.
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            bcast.extend(v for _, v in bc_reader.poll())
            n_ops = sum(1 for r in bcast
                        if isinstance(r, dict) and r.get("kind") == "op"
                        and "sub" in (r.get("tr") or {}))
            if n_ops >= total:
                break
            sup.poll_once()
            time.sleep(0.005)
        durable = [v for _, v in dur_reader.poll()]
        # Children heartbeat on a throttle (hb_interval_s): give every
        # role one post-drain heartbeat so the collected snapshots
        # include the final ops.
        time.sleep(0.35)
        metrics_reg = sup.collect_metrics()
        slow_ops = sup.child_slow_ops()
    finally:
        sup.stop()

    # ----- trace/quantile correctness assertions (run on EVERY host)
    ops = [r for r in bcast if isinstance(r, dict)
           and r.get("kind") == "op" and "sub" in (r.get("tr") or {})]
    seen = [(r["doc"], r["client"], r["clientSeq"]) for r in ops]
    assert len(seen) == len(set(seen)), "duplicate ops in broadcast"
    assert len(seen) == total, (
        f"open-loop drain incomplete: {len(seen)}/{total} ops reached "
        f"broadcast within {timeout_s}s"
    )
    sub_stamp, sub_bc = [], []
    all_sub_stamp, all_sub_bc = [], []
    for r in ops:
        tr = r["tr"]
        assert tr["sub"] <= tr["stamp"] <= tr["bc"], (
            f"non-monotone span {tr}"
        )
        ss = (tr["stamp"] - tr["sub"]) * 1000.0
        all_sub_stamp.append(ss)
        all_sub_bc.append((tr["bc"] - tr["sub"]) * 1000.0)
        if (r["doc"], r["client"], r["clientSeq"]) in lead_keys:
            continue  # verified, but start-up cost: no quantile
        sub_stamp.append(ss)
        sub_bc.append((tr["bc"] - tr["sub"]) * 1000.0)
    assert len(sub_bc) == total - len(lead_keys)
    for r in durable:
        tr = r.get("tr") if isinstance(r, dict) else None
        if isinstance(tr, dict) and "dur" in tr and "stamp" in tr:
            assert tr["stamp"] <= tr["dur"], f"non-monotone span {tr}"
    # The child-reported histogram must be exactly the bench-side
    # distribution: the deli stamps tr["stamp"] and observes its
    # submit_to_stamp histogram from ONE clock read, so rebuilding the
    # histogram from the wire spans must reproduce the child's bucket
    # counts — the end-to-end proof that quantile estimates summarize
    # the real per-op traces.
    reb = _metrics.MetricsRegistry()
    h_local = reb.histogram("op_stage_ms", stage="submit_to_stamp")
    for v in all_sub_stamp:  # the child saw the lead-in ops too
        h_local.observe(v)
    h_child = metrics_reg.histogram("op_stage_ms", stage="submit_to_stamp")
    assert h_child.counts == h_local.counts and \
        h_child.count == h_local.count, (
            "child-reported submit_to_stamp histogram diverges from "
            "the wire spans"
        )
    # Bucket-interpolated estimate lands in the same (or adjacent,
    # for an exact-bound landing) bucket as the exact sample quantile.
    from bisect import bisect_left
    snap_h = [h for h in metrics_reg.snapshot()["histograms"]
              if h["name"] == "op_stage_ms"
              and h["labels"].get("stage") == "submit_to_broadcast"]
    if snap_h:
        est = _metrics.histogram_quantile(snap_h[0], 0.99)
        exact = _exact_quantile(sorted(all_sub_bc), 0.99)
        bounds = snap_h[0]["buckets"]
        if est != float("inf"):
            assert abs(bisect_left(bounds, est)
                       - bisect_left(bounds, exact)) <= 1, (
                f"interpolated p99 {est} not in the exact p99 "
                f"{exact}'s bucket"
            )
    return {
        "doorbell": doorbell,
        "fused_hop": fused_hop,
        "records": total,
        "lead_in": lead_in,
        "rate_hz": rate_hz,
        "submit_to_stamp_ms": _span_quantiles(sub_stamp),
        "submit_to_broadcast_ms": _span_quantiles(sub_bc),
        "slow_ops": slow_ops[:5],
    }


def wake_jitter_probe(n: int = 450, rate_hz: float = 150.0) -> dict:
    """The host's EVENT-WAKE honesty probe: one minimal bell-driven
    relay hop (producer process → FIFO doorbell → relay process) at
    the bench rate, p50/p99 of append→relayed latency. On bare metal
    a select() wake costs tens of microseconds; an oversubscribed VM
    parks idle vCPUs and a wake can cost ~10ms at the tail — a host
    property that puts a hard floor under ANY event-driven pipeline's
    p99, poll stack or no poll stack. `config9_latency` skips its
    ratio assert (loudly) when this probe's p99 says the floor is too
    high to measure a 3x improvement honestly."""
    import subprocess
    import sys

    from ..server.queue import SharedFileTopic, TailReader

    scratch = tempfile.mkdtemp(
        prefix="wake-probe-",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    relay_src = (
        "import sys, time\n"
        f"sys.path.insert(0, {repo!r})\n"
        "from fluidframework_tpu.server.queue import (\n"
        "    SharedFileTopic, TopicDoorbell, TailReader)\n"
        "src = SharedFileTopic(sys.argv[1])\n"
        "dst = SharedFileTopic(sys.argv[2])\n"
        "bell = TopicDoorbell(src.path)\n"
        "r = TailReader(src)\n"
        "print('READY', flush=True)\n"
        "while True:\n"
        "    vals = [v for _, v in r.poll()]\n"
        "    if vals:\n"
        "        now = time.time()\n"
        "        dst.append_many([{**v, 'hop': now} for v in vals])\n"
        "    else:\n"
        "        bell.wait(0.05)\n"
    )
    a = os.path.join(scratch, "a.jsonl")
    b = os.path.join(scratch, "b.jsonl")
    proc = subprocess.Popen([sys.executable, "-c", relay_src, a, b],
                            stdout=subprocess.PIPE, text=True)
    try:
        assert (proc.stdout.readline() or "").strip() == "READY"
        src = SharedFileTopic(a)
        out = TailReader(SharedFileTopic(b))
        time.sleep(0.2)
        recs: List[dict] = []
        t0 = time.perf_counter()
        for i in range(n):
            tick = t0 + i / rate_hz
            while time.perf_counter() < tick:
                recs.extend(v for _, v in out.poll())
                time.sleep(0.001)
            src.append_many([{"i": i, "ts": time.time()}])
        deadline = time.time() + 15
        while time.time() < deadline and len(recs) < n:
            recs.extend(v for _, v in out.poll())
            time.sleep(0.002)
    finally:
        proc.kill()
        proc.wait(timeout=10)
        shutil.rmtree(scratch, ignore_errors=True)
    lat = sorted((r["hop"] - r["ts"]) * 1000.0 for r in recs)
    assert len(lat) >= n * 0.95, f"probe relay lost records: {len(lat)}/{n}"
    return {
        "samples": len(lat),
        "p50": round(_exact_quantile(lat, 0.5), 3),
        "p99": round(_exact_quantile(lat, 0.99), 3),
        "max": round(lat[-1], 3),
    }


def run_latency_bench(rate_hz: float = 150.0, duration_s: float = 4.0,
                      n_docs: int = 2, n_clients: int = 2,
                      ttl_s: float = 0.75, timeout_s: float = 60.0,
                      attempts: int = 2,
                      work_dir: Optional[str] = None,
                      fused_hop: bool = False) -> dict:
    """Submit→stamp→durable→broadcast latency SLO of the supervised
    farm under a steady OPEN-loop load (fixed rate, never waiting on
    completion), doorbells ON vs the polling baseline at the same
    load. Exact per-op spans come off the wire traces
    (FLUID_TRACE_WIRE); the trace/quantile correctness assertions run
    inside every variant regardless of host size — the
    p99-improvement judgment lives in `bench_configs.config9_latency`
    (loud skip under 4 cores, where the ratio measures the scheduler).

    With `fused_hop`, a THIRD variant runs the fused
    durable+broadcast consumer (doorbells on, same load): the
    open-loop p99 delta of one fewer wake+fsync in the path, reported
    as `fused_vs_split_p99` / `fused_p99_ms` (ROADMAP item-1
    follow-up c — config9 records it in its MEASURED section and the
    bench_trend ledger).

    Scratch defaults to tmpfs (/dev/shm) when present: the bench
    measures the POLL-INTERVAL stack, and on a slow/network filesystem
    the per-hop fsync floor would cap the measurable ratio no matter
    how the consumers wake — the disk is not the thing under test."""
    scratch = work_dir or tempfile.mkdtemp(
        prefix="latency-bench-",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    try:
        variants = [("poll", False, False), ("doorbell", True, False)]
        if fused_hop:
            variants.append(("fused", True, True))
        runs = {}
        for name, doorbell, fused in variants:
            # Best-of-N per variant (the config5_metrics_overhead
            # pattern): a virtualized host's wake-from-idle jitter
            # lands ~10ms stalls on ~1% of EVENT wakes in an unlucky
            # run — real, but not the poll stack this bench measures.
            # Every attempt still runs the full correctness contract.
            best = None
            for k in range(max(1, attempts)):
                vdir = os.path.join(scratch, f"{name}-{k}")
                os.makedirs(vdir, exist_ok=True)
                res = _run_latency_variant(
                    vdir, doorbell, rate_hz, duration_s, n_docs,
                    n_clients, ttl_s, timeout_s, fused_hop=fused,
                )
                if (best is None
                        or res["submit_to_broadcast_ms"]["p99"]
                        < best["submit_to_broadcast_ms"]["p99"]):
                    best = res
            runs[name] = best
        imp = {
            q: round(runs["poll"]["submit_to_broadcast_ms"][q]
                     / max(1e-9,
                           runs["doorbell"]["submit_to_broadcast_ms"][q]),
                     2)
            for q in ("p50", "p99")
        }
        out = {
            "metric": "latency_slo_open_loop",
            "rate_hz": rate_hz,
            "records_per_variant": runs["poll"]["records"],
            "docs": n_docs, "clients_per_doc": n_clients,
            "runs": [runs[name] for name, _d, _f in variants],
            "p50_improvement": imp["p50"],
            "p99_improvement": imp["p99"],
            "cores": os.cpu_count(),
            "gate": ("per-op spans exactly-once + monotone; child "
                     "histograms == wire spans"),
            "unit": "ms",
        }
        if fused_hop:
            split_p99 = runs["doorbell"]["submit_to_broadcast_ms"]["p99"]
            fused_p99 = runs["fused"]["submit_to_broadcast_ms"]["p99"]
            out["fused_p99_ms"] = fused_p99
            out["fused_p50_ms"] = \
                runs["fused"]["submit_to_broadcast_ms"]["p50"]
            out["fused_vs_split_p99"] = round(
                split_p99 / max(1e-9, fused_p99), 2
            )
        return out
    finally:
        if work_dir is None:
            shutil.rmtree(scratch, ignore_errors=True)


def main() -> None:  # CLI twin: tools/bench_deli.py
    scale = float(os.environ.get("BD_SCALE", "1.0"))
    if os.environ.get("BD_DEVICES"):
        # Multi-device scaling mode (tools/bench_deli.py --devices):
        # aggregate sequencer ops/s per device count, bit-identity
        # gated across topologies. BD_DEVICES is a comma list of
        # device counts (default "1,4,8").
        devs = tuple(
            int(d) for d in os.environ["BD_DEVICES"].split(",") if d
        )
        res = run_multichip_bench(
            devices=devs or (1, 4, 8),
            n_docs=max(8, int(int(os.environ.get("BD_DOCS", "4096"))
                              * scale)),
            ops_per_doc=int(os.environ.get("BD_OPS_PER_DOC", "64")),
            n_clients=int(os.environ.get("BD_CLIENTS", "8")),
            repeats=int(os.environ.get("BD_REPEATS", "3")),
        )
        print(json.dumps(res))
        return
    if os.environ.get("BD_DEVICE_PLANE"):
        # 2-D device-plane mode (tools/bench_deli.py --device-plane):
        # sequencer on the plane's docs slice vs single-device +
        # kernel-vs-overlay summarizer fold stacked over the whole
        # plane, both digest-gated (bench_configs
        # config15_device_plane's engine). BD_DEVICE_PLANE is the
        # "DOCSxMODEL" spec (default "2x2").
        spec = os.environ["BD_DEVICE_PLANE"]
        res = run_device_plane_bench(
            plane=spec if "x" in spec else "2x2",
            n_docs=max(8, int(int(os.environ.get("BD_DOCS", "2048"))
                              * scale)),
            ops_per_doc=int(os.environ.get("BD_OPS_PER_DOC", "64")),
            n_clients=int(os.environ.get("BD_CLIENTS", "8")),
            repeats=int(os.environ.get("BD_REPEATS", "3")),
            fold_docs=int(os.environ.get("BD_FOLD_DOCS", "4")),
            fold_ops=max(64, int(int(os.environ.get("BD_FOLD_OPS",
                                                    "1500"))
                                 * scale)),
        )
        print(json.dumps(res))
        return
    if os.environ.get("BD_SCENARIOS"):
        # Traffic-profile scenario mode (tools/bench_deli.py
        # --scenarios): the four open-loop scenario primitives —
        # hot-doc storm, reconnect stampede, read swarm, tenant mix —
        # each with /slo quantiles, slow-op spans, and a convergence
        # digest (bench_configs config13_scenarios' engine lives in
        # testing.scenarios; this is the standalone CLI twin).
        from .scenarios import run_scenario_suite

        res = run_scenario_suite(
            scale=scale,
            deli_impl=os.environ.get("BD_IMPL", "scalar"),
            log_format=os.environ.get("BD_LOG_FORMAT", "json"),
            swarm_sessions=int(os.environ.get("BD_SESSIONS",
                                              "100000")),
        )
        print(json.dumps(res))
        return
    if os.environ.get("BD_LATENCY"):
        # Open-loop latency SLO mode (tools/bench_deli.py --latency):
        # p50/p99 submit→broadcast under a steady fixed-rate load,
        # doorbells vs the polling baseline (bench_configs
        # config9_latency's engine).
        res = run_latency_bench(
            rate_hz=float(os.environ.get("BD_RATE_HZ", "150")),
            duration_s=float(os.environ.get("BD_DURATION_S", "4"))
            * scale,
            n_docs=int(os.environ.get("BD_DOCS", "2")),
            n_clients=int(os.environ.get("BD_CLIENTS", "2")),
            fused_hop=bool(os.environ.get("BD_FUSED_HOP")),
        )
        print(json.dumps(res))
        return
    if os.environ.get("BD_CATCHUP"):
        # Summary catch-up mode (tools/bench_deli.py --catchup):
        # cold-join latency vs log length with/without summaries plus
        # the broadcast fan-out leg (bench_configs config10_catchup's
        # engine). BD_LOG_LENGTHS is a comma list (default
        # "10000,30000,100000", scaled by BD_SCALE).
        lens = tuple(
            max(512, int(int(x) * scale)) for x in os.environ.get(
                "BD_LOG_LENGTHS", "10000,30000,100000"
            ).split(",") if x
        )
        res = run_catchup_bench(
            log_lengths=lens,
            summary_ops=int(os.environ.get("BD_SUMMARY_OPS", "2000")),
            n_subscribers=int(os.environ.get("BD_SUBSCRIBERS", "200")),
            log_format=os.environ.get("BD_LOG_FORMAT", "json"),
        )
        print(json.dumps(res))
        return
    if os.environ.get("BD_INGRESS"):
        # Front-door mode (tools/bench_deli.py --ingress): admission
        # throughput + the bounded-backlog overload episode
        # (bench_configs config12_front_door's engine).
        res = run_ingress_bench(
            n_docs=max(8, int(int(os.environ.get("BD_DOCS", "2000"))
                              * scale)),
            n_clients=int(os.environ.get("BD_CLIENTS", "16")),
            ops_per_client=int(os.environ.get("BD_OPS", "2")),
            n_partitions=int(os.environ.get("BD_PARTITIONS", "2")),
            log_format=os.environ.get("BD_LOG_FORMAT", "json"),
        )
        print(json.dumps(res))
        return
    if os.environ.get("BD_HOPS"):
        # Fused-hop mode (tools/bench_deli.py --hops): classic vs
        # fused durable+broadcast consumer topology — drain rate,
        # hop-pair fsyncs per record, bit-identity gated.
        res = run_hop_bench(
            n_docs=max(8, int(int(os.environ.get("BD_DOCS", "64"))
                              * scale)),
            n_clients=int(os.environ.get("BD_CLIENTS", "8")),
            ops_per_client=int(os.environ.get("BD_OPS", "4")),
            log_format=os.environ.get("BD_LOG_FORMAT", "columnar"),
            deli_impl=os.environ.get("BD_IMPL", "kernel"),
        )
        print(json.dumps(res))
        return
    if os.environ.get("BD_REBALANCE"):
        # Elastic-rebalance mode: mid-run split cost vs steady
        # topology, convergence-gated (bench_configs config8 twin).
        res = run_rebalance_bench(
            n_docs=max(8, int(int(os.environ.get("BD_DOCS", "10000"))
                              * scale)),
            n_clients=int(os.environ.get("BD_CLIENTS", "64")),
            ops_per_client=int(os.environ.get("BD_OPS", "1")),
            n_ranges=int(os.environ.get("BD_PARTITIONS", "4")),
            deli_impl=os.environ.get("BD_IMPL", "kernel"),
            log_format=os.environ.get("BD_LOG_FORMAT", "columnar"),
        )
        print(json.dumps(res))
        return
    if os.environ.get("BD_SHARD"):
        # Shard-scaling mode (tools/bench_deli.py --shard): aggregate
        # ops/s of the P-partition fabric vs single-partition, gated
        # bit-identical across partitions. BD_PARTITIONS is a comma
        # list of partition counts (default "1,4").
        parts = tuple(
            int(p) for p in
            os.environ.get("BD_PARTITIONS", "1,4").split(",") if p
        )
        res = run_shard_bench(
            n_docs=max(8, int(int(os.environ.get("BD_DOCS", "2048"))
                              * scale)),
            n_clients=int(os.environ.get("BD_CLIENTS", "8")),
            ops_per_client=int(os.environ.get("BD_OPS", "2")),
            partitions=parts,
            batch=int(os.environ.get("BD_BATCH", "8192")),
            deli_impl=os.environ.get("BD_IMPL", "kernel"),
            log_format=os.environ.get("BD_LOG_FORMAT", "columnar"),
        )
        print(json.dumps(res))
        return
    res = run_pipeline_bench(
        n_docs=max(8, int(int(os.environ.get("BD_DOCS", "10000")) * scale)),
        n_clients=int(os.environ.get("BD_CLIENTS", "64")),
        ops_per_client=int(os.environ.get("BD_OPS", "1")),
        seed_records=int(os.environ.get("BD_SEED_RECORDS", "400")),
        batch=int(os.environ.get("BD_BATCH", "16384")),
    )
    print(json.dumps(res))


if __name__ == "__main__":
    main()
