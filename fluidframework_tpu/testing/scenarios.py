"""Traffic-profile scenario layer: skewed, bursty, REAL-shaped load.

Every bench in-tree so far spreads load evenly over many docs; real
Fluid traffic is the opposite (SURVEY §S0: production load is
dominated by joins and reads, and alfred exists precisely to absorb
storms). This module composes OPEN-LOOP scenario primitives on top of
the supervised farm / `deli_bench` machinery so the skewed shapes are
first-class, guarded workloads:

- **hot-doc storm** (`run_hotdoc_storm`) — one viral document with
  thousands of writers plus a cold background mix, driven open-loop
  through the supervised farm. Stresses the sequencer's per-doc
  client table (the kernel deli's `[D, C]` pool COLUMN axis) and the
  MSN math of a huge collaborator set; reports hot-vs-cold
  submit→broadcast quantiles separately, because a storm's tail and
  the background's tail are different SLOs.
- **reconnect stampede** (`run_reconnect_stampede`) — a simulated
  network partition heals and thousands of sessions catch up
  SIMULTANEOUSLY through the summary path (`summarizer.read_catchup`
  + `SummaryReplica` boot, PR 10): the read-amplification burst a
  real outage recovery produces. Every session must land the
  identical manifest/blob/tail, and summary+tail boots must stay
  bit-identical to a cold full-log replay.
- **read-mostly swarm** (`run_read_swarm`) — 100k+ subscribed
  sessions fanning out through `FarmReadServer`'s doorbell-woken
  pusher (a handful of them as REAL TCP sessions over the framed
  wire protocol, the rest as in-proc subscriber sessions — scaled
  honestly, with a LOUD skip on the throughput evidence below the
  100k-session/core bar). A session that misses one record fails the
  run: fan-out cannot pass by dropping work.
- **tenant-skewed mix** (`run_tenant_mix`) — a zipf-shaped tenant mix
  riding the PR 12 ingress token buckets: one hot tenant over its
  rate budget must be throttled (visible 429 nacks billed to IT and
  only it) while the cold tenants' traffic flows untouched, and the
  throttled tail retries to exactly-once convergence.

The scenario CONTRACT (every primitive, every scale):

- **Open loop** — load is offered on a fixed schedule (or all at
  once, for the stampede/swarm) and NEVER waits on completion; a
  backlogged pipeline shows up as latency, not as a silently gentler
  load.
- **`/slo` quantiles** — each run returns an `slo` body
  (`utils.metrics.slo_summary` form: per-stage `op_stage_ms`
  histograms reduced to count/mean/p50/p95/p99, plus the `ingress_*`
  admission counters where a front door is in play).
- **Slow-op evidence** — each run returns `slow_ops`, the flight-
  recorder spans of its slowest operations (farm scenarios from the
  broadcaster-fed process recorder via the supervisor's merged
  `/traces` channel; read-side scenarios from a scenario-scoped
  recorder fed with per-session spans).
- **Convergence digest** — each run ends in a digest gate proving no
  work was dropped: exactly-once keys + contiguous seqs for write
  scenarios, single-valued catch-up signatures / complete per-session
  delivery for read scenarios. A scenario cannot pass by shedding
  its own load.

`run_scenario_suite` bundles all four at a common scale — the engine
behind `tools/bench_configs.config13_scenarios` and
`tools/bench_deli.py --scenarios`, whose per-scenario p99s feed the
`bench_trend` ledger (lower-is-better `scenario_p99_ms` lines).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import shutil
import socket
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from .chaos import sequence_integrity, stream_digest
from .deli_bench import _span_quantiles

__all__ = [
    "run_hotdoc_storm",
    "run_read_swarm",
    "run_reconnect_stampede",
    "run_scenario_suite",
    "run_tenant_mix",
    "run_week_of_traffic",
    "scenario_p99s",
]


def _slo_p99(slo: dict, stage: str) -> Optional[float]:
    """The p99 of one `op_stage_ms` stage out of an /slo body."""
    for h in slo.get("histograms", ()):
        if h["name"] == "op_stage_ms" and \
                h.get("labels", {}).get("stage") == stage:
            return h.get("p99")
    return None


def _slowest(recorder, top: int = 5) -> List[dict]:
    """The recorder's spans slowest-first (the /traces convention)."""
    return sorted(recorder.snapshot(),
                  key=lambda s: -float(s.get("e2e_ms", 0.0)))[:top]


def _fresh_metrics():
    """(registry, recorder, restore_fn): scenario-scoped metrics + a
    scenario-scoped flight recorder, swapped in process-wide so role
    code constructed inside the scenario feeds THEM, not the suite's
    shared instruments — the bench-isolation pattern `run_pipeline`
    uses, extended to the recorder."""
    from ..utils import metrics as M

    reg = M.MetricsRegistry()
    # Fast-arming rolling gate: a scaled-down scenario has tens of
    # observations, and the production defaults (arm at 32, refresh
    # every 32) would leave the evidence buffer empty — same policy,
    # shorter warm-up.
    rec = M.FlightRecorder(min_samples=8)
    rec.RECALC_EVERY = 8
    prev_reg = M.set_registry(reg)
    prev_rec = M.set_flight_recorder(rec)

    def restore():
        M.set_registry(prev_reg)
        M.set_flight_recorder(prev_rec)

    return reg, rec, restore


# ---------------------------------------------------------------------------
# hot-doc storm
# ---------------------------------------------------------------------------


def run_hotdoc_storm(n_writers: int = 2000, cold_docs: int = 32,
                     cold_clients: int = 2, rate_hz: float = 300.0,
                     duration_s: float = 4.0, hot_fraction: float = 0.9,
                     deli_impl: str = "scalar", log_format: str = "json",
                     ttl_s: float = 0.75, timeout_s: float = 120.0,
                     seed: int = 13,
                     hb_timeout_s: Optional[float] = None,
                     work_dir: Optional[str] = None) -> dict:
    """One viral document, `n_writers` writers, a cold background mix
    — open-loop through the supervised farm (fused durable+broadcast
    hop, wire traces on). The hot doc concentrates `hot_fraction` of
    the offered ops on ONE per-doc client table, which is exactly the
    axis even load never stresses: the kernel deli's `[D, C]` pool
    must widen its client-column axis for one row, and the MSN is a
    min over thousands of collaborators instead of a handful.

    Gates (always): every offered op broadcast exactly once, seqs
    contiguous per doc, spans monotone, /slo quantiles present,
    slow-op spans recorded. Returns hot/cold/combined quantiles —
    the storm's tail and the background's tail are separate numbers."""
    scratch = work_dir or tempfile.mkdtemp(
        prefix="storm-",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    try:
        return _hotdoc_storm_run(
            scratch, n_writers, cold_docs, cold_clients, rate_hz,
            duration_s, hot_fraction, deli_impl, log_format, ttl_s,
            timeout_s, seed, hb_timeout_s,
        )
    finally:
        # Unconditional (failure paths too): the scratch lives on
        # tmpfs, and a run that failed its gates must not leave a
        # 2000-writer run's topics pinned in RAM.
        if work_dir is None:
            shutil.rmtree(scratch, ignore_errors=True)


def _hotdoc_storm_run(scratch: str, n_writers: int, cold_docs: int,
                      cold_clients: int, rate_hz: float,
                      duration_s: float, hot_fraction: float,
                      deli_impl: str, log_format: str, ttl_s: float,
                      timeout_s: float, seed: int,
                      hb_timeout_s: Optional[float] = None) -> dict:
    from ..server.supervisor import ServiceSupervisor

    rng = random.Random(seed)
    sup = ServiceSupervisor(
        scratch, roles=("deli", "scriptorium", "broadcaster"),
        ttl_s=ttl_s, fused_hop=True, deli_impl=deli_impl,
        log_format=log_format,
        # The WEDGE bar (chaos kills still surface via process exit):
        # a kernel deli compiling its first full-width [D, C, B] pump
        # on a small host is silent for tens of seconds — killing it
        # mid-compile restarts the same compile forever.
        heartbeat_timeout_s=hb_timeout_s if hb_timeout_s else 2.0,
        # FLUID_TRACE_SLOW_MS=0: the children's flight recorders keep
        # every span (ring-bounded) instead of waiting for the rolling
        # p99 to arm — a short scaled run must still produce /traces
        # evidence.
        child_env={"FLUID_TRACE_WIRE": "1", "FLUID_DOORBELL": "1",
                   "FLUID_TRACE_SLOW_MS": "0"},
        hb_interval_s=0.1,
    ).start()
    try:
        # Topics in the FARM's wire format: a columnar run feeds
        # binary record-batch frames and tails the broadcast leg with
        # the frame-aware reader (SharedFileTopic would write JSONL
        # into a columnar pipeline and parse none of its output).
        from ..server.columnar_log import make_tail_reader, make_topic

        raw = make_topic(
            os.path.join(scratch, "topics", "rawdeltas.jsonl"),
            log_format,
        )
        bc_reader = make_tail_reader(make_topic(
            os.path.join(scratch, "topics", "broadcast.jsonl"),
            log_format,
        ))
        hot_doc = "hotdoc"
        colds = [(f"cold{d}", c) for d in range(cold_docs)
                 for c in range(1, cold_clients + 1)]
        joins = [{"kind": "join", "doc": hot_doc, "client": w}
                 for w in range(1, n_writers + 1)]
        joins += [{"kind": "join", "doc": d, "client": c}
                  for d, c in colds]
        for lo in range(0, len(joins), 4096):
            raw.append_many(joins[lo:lo + 4096])
        # Warm: the whole collaborator set joined and broadcast before
        # the timed window opens (the storm measures steady state, not
        # the connect burst — that burst is the stampede's job).
        want = len(joins)
        bcast: List[dict] = []
        # Running counters folded at append time — a full rescan of
        # the accumulated list per poll tick would be O(n²) over the
        # run (the swarm's crossing-counter rule, applied here).
        n_op = 0        # broadcast records with kind == "op"
        n_traced = 0    # ...that carry the tr.sub submit stamp

        def take() -> None:
            nonlocal n_op, n_traced
            for _, v in bc_reader.poll():
                bcast.append(v)
                if isinstance(v, dict) and v.get("kind") == "op":
                    n_op += 1
                    if "sub" in (v.get("tr") or {}):
                        n_traced += 1

        deadline = time.time() + timeout_s
        while time.time() < deadline:
            sup.poll_once()
            take()
            if n_op >= want:
                break
            time.sleep(0.01)
        else:
            raise AssertionError(
                f"storm farm never came live: {len(bcast)} broadcast "
                f"records for {want} joins"
            )
        # (The join prefix stays in `bcast`: the integrity gate below
        # checks seqs 1..N per doc, and a doc's stream starts with its
        # joins. The op-drain condition keys on tr.sub, which joins
        # never carry, so nothing double-counts.)
        # Open-loop storm: fixed-rate offered load, hot_fraction of
        # picks landing on the viral doc. The feeder NEVER waits on
        # completion — while pacing it only drains tails and polls
        # the supervisor, so a backlogged pipeline reads as latency.
        total = max(128, int(rate_hz * duration_s))
        hot_cseq = {w: 0 for w in range(1, n_writers + 1)}
        cold_cseq = {k: 0 for k in colds}
        hot_sent = 0
        behind_ticks = 0
        t0 = time.perf_counter()
        last_sup = 0.0
        for i in range(total):
            tick = t0 + i / rate_hz
            now = time.perf_counter()
            if now > tick + 1.0 / rate_hz:
                behind_ticks += 1
            while True:
                now = time.perf_counter()
                if now >= tick:
                    break
                take()
                if now - last_sup > 0.2:
                    sup.poll_once()
                    last_sup = now
                time.sleep(min(0.002, tick - now))
            if rng.random() < hot_fraction:
                w = 1 + (hot_sent % n_writers)
                hot_sent += 1
                hot_cseq[w] += 1
                doc, client, cseq = hot_doc, w, hot_cseq[w]
            else:
                k = colds[i % len(colds)]
                cold_cseq[k] += 1
                doc, client, cseq = k[0], k[1], cold_cseq[k]
            raw.append_many([{
                "kind": "op", "doc": doc, "client": client,
                "clientSeq": cseq, "refSeq": 0,
                "contents": {"i": i}, "tr_sub": time.time(),
            }])
        feed_wall_s = time.perf_counter() - t0
        # Drain: every offered op must reach broadcast (bounded).
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            take()
            if n_traced >= total:
                break
            sup.poll_once()
            time.sleep(0.005)
        time.sleep(0.35)  # one post-drain throttled heartbeat
        slo = _collect_slo(sup)
        slow_ops = sup.child_slow_ops()
    finally:
        sup.stop()

    ops = [r for r in bcast if isinstance(r, dict)
           and r.get("kind") == "op" and "sub" in (r.get("tr") or {})]
    keys = [(r["doc"], r["client"], r["clientSeq"]) for r in ops]
    assert len(keys) == len(set(keys)), "duplicate ops in broadcast"
    assert len(keys) == total, (
        f"storm dropped work: {len(keys)}/{total} offered ops reached "
        f"broadcast within {timeout_s}s"
    )
    all_ops = [r for r in bcast if isinstance(r, dict)
               and r.get("kind") == "op"]  # joins + ops: seqs 1..N
    dups, skips = sequence_integrity(all_ops)
    assert dups == 0 and skips == 0, (
        f"storm stream integrity violated: dups={dups} skips={skips}"
    )
    hot_ms, cold_ms = [], []
    for r in ops:
        tr = r["tr"]
        assert tr["sub"] <= tr["stamp"] <= tr["bc"], \
            f"non-monotone span {tr}"
        ms = (tr["bc"] - tr["sub"]) * 1000.0
        (hot_ms if r["doc"] == hot_doc else cold_ms).append(ms)
    assert hot_ms, "storm produced no hot-doc ops"
    combined = _span_quantiles(hot_ms + cold_ms)
    assert _slo_p99(slo, "submit_to_broadcast") is not None, (
        "storm /slo carries no submit_to_broadcast quantiles"
    )
    assert slow_ops, "storm recorded no slow-op spans"
    return {
        "scenario": "hotdoc_storm",
        "open_loop": True,
        "records": total,
        "writers": n_writers,
        "hot_ops": len(hot_ms),
        "cold_ops": len(cold_ms),
        "hot_fraction": hot_fraction,
        "rate_hz": rate_hz,
        "feed_wall_s": round(feed_wall_s, 3),
        "behind_ticks": behind_ticks,
        "hot_submit_to_broadcast_ms": _span_quantiles(hot_ms),
        "cold_submit_to_broadcast_ms": (
            _span_quantiles(cold_ms) if cold_ms else None
        ),
        "submit_to_broadcast_ms": combined,
        "scenario_p99_ms": combined["p99"],
        "digest": stream_digest(all_ops),
        "slo": slo,
        "slow_ops": slow_ops[:5],
        "gate": ("exactly-once + contiguous seqs + monotone spans; "
                 "slo + slow-op evidence present"),
    }


def _collect_slo(sup) -> dict:
    """The farm's /slo body off the supervisor's merged child
    heartbeats (exactly what `monitor.MetricsServer` would serve)."""
    from ..utils.metrics import slo_summary

    return slo_summary(sup.collect_metrics().snapshot())


# ---------------------------------------------------------------------------
# reconnect stampede
# ---------------------------------------------------------------------------


def _drive_ranged_summarizers(shared: str, log_format: str,
                              summary_ops: int, topo: dict) -> int:
    """Drive one RANGED summarizer per live topology range to
    quiescence over already-written ``deltas-{rid}`` topics — the
    per-range elastic summary surface (`_drive_summarizer`'s fabric
    twin; the supervised form is `ShardWorker(elastic=True,
    summarize=True)`). Returns the manifest count."""
    from ..server.columnar_log import make_tail_reader, make_topic
    from ..server.shard_fabric import ranged_role_class
    from ..server.summarizer import SummarizerRole

    emitted = 0
    for entry in topo["ranges"]:
        cls = ranged_role_class(SummarizerRole, entry, topo["epoch"])
        role = cls(shared, owner=f"stampede-{entry['rid']}",
                   ttl_s=3600.0, log_format=log_format,
                   summary_ops=summary_ops)
        role.fence = 1
        reader = make_tail_reader(make_topic(
            os.path.join(shared, "topics",
                         f"{role.in_topic_name}.jsonl"),
            log_format,
        ))
        while True:
            entries = reader.poll(4096)
            if not entries:
                break
            out: List[dict] = []
            for line_idx, rec in entries:
                role.process(line_idx, rec, out)
            role.flush_batch(out)
            if out:
                role.out_topic.append_many(out, fence=1,
                                           owner=role.owner)
                emitted += len(out)
            role.offset = reader.next_line
    return emitted


def run_reconnect_stampede(n_sessions: int = 2000, log_len: int = 20000,
                           n_clients: int = 4, summary_ops: int = 1000,
                           boot_checks: int = 3, threads: int = 16,
                           log_format: str = "json",
                           elastic_ranges: int = 0,
                           work_dir: Optional[str] = None) -> dict:
    """A partition heals: `n_sessions` clients that were offline for
    the whole log catch up SIMULTANEOUSLY through the summary path.
    Each session pays the real server-side work (`read_catchup`:
    manifest lookup + blob fetch + O(tail) backward scan) against ONE
    shared `SummaryIndex`/store — the read-amplification burst of an
    outage recovery, started behind a barrier so the stampede is
    genuinely concurrent.

    Gates (always): `boot_checks` full `SummaryReplica` boots
    bit-identical to a cold full-log replay (the PR 10 contract under
    stampede conditions), and every session's catch-up SIGNATURE
    (manifest seq/handle, tail key range) single-valued — a stampede
    cannot pass by handing different clients different states.

    `elastic_ranges` >= 2 runs the PER-RANGE elastic summary variant
    (PR 13 follow-up b over PR 14's elastic summarizer): the stream
    splits into hash-range ``deltas-{rid}`` topics, one RANGED
    summarizer serves each range, and every stampeding session reads
    through the MERGED `SummaryIndex` over the per-range
    ``summaries-{rid}`` topics — the same single-signature gate must
    hold across the fabric-shaped surface, plus a background doc per
    other range proving the merged index resolves them all."""
    from ..server.columnar_log import make_topic
    from ..server.queue import RangeLeaseStore, range_for_doc
    from ..server.summarizer import (
        SummaryIndex,
        SummaryReplica,
        open_summary_store,
        read_catchup,
    )
    from .deli_bench import _drive_summarizer, build_mergetree_stream

    scratch = work_dir or tempfile.mkdtemp(prefix="stampede-")
    reg, recorder, restore = _fresh_metrics()
    elastic = int(elastic_ranges) >= 2
    try:
        summary_ops = max(16, min(int(summary_ops), log_len // 4))
        stream = build_mergetree_stream(log_len, n_clients=n_clients)
        os.makedirs(os.path.join(scratch, "topics"), exist_ok=True)
        hot_deltas_topic = "deltas"
        if elastic:
            topo = RangeLeaseStore(scratch, "stampede").ensure_topology(
                int(elastic_ranges)
            )
            # The hot doc lands in ITS range's topic; one background
            # doc per OTHER range keeps every summaries-{rid} topic
            # live, so the merged index demonstrably resolves across
            # the whole per-range surface.
            hot_rid = range_for_doc(topo, "doc0")["rid"]
            hot_deltas_topic = f"deltas-{hot_rid}"
            by_topic: Dict[str, List[dict]] = {hot_deltas_topic: stream}
            bg_digests: Dict[str, str] = {}
            bg_i = 0
            for entry in topo["ranges"]:
                if entry["rid"] == hot_rid:
                    continue
                # Find a doc hashing into this range (bounded probe).
                doc = None
                for k in range(10000):
                    cand = f"bg{bg_i}-{k}"
                    if range_for_doc(topo, cand)["rid"] == entry["rid"]:
                        doc = cand
                        break
                if doc is None:
                    continue
                bg_i += 1
                bg = build_mergetree_stream(
                    max(64, summary_ops * 2), n_clients=2,
                    seed=90 + bg_i, doc=doc,
                )
                by_topic.setdefault(
                    f"deltas-{entry['rid']}", []
                ).extend(bg)
                cold_bg = SummaryReplica(None)
                cold_bg.apply_records(bg)
                bg_digests[doc] = cold_bg.state_digest()
            for tname, recs in by_topic.items():
                t = make_topic(
                    os.path.join(scratch, "topics", f"{tname}.jsonl"),
                    log_format,
                )
                for lo in range(0, len(recs), 16384):
                    t.append_many(recs[lo:lo + 16384])
            _drive_ranged_summarizers(scratch, log_format,
                                      summary_ops, topo)
            store = open_summary_store(scratch)
            index = SummaryIndex(scratch, log_format, topics=[
                f"summaries-{e['rid']}" for e in topo["ranges"]
            ])
        else:
            deltas = make_topic(
                os.path.join(scratch, "topics", "deltas.jsonl"),
                log_format,
            )
            for lo in range(0, len(stream), 16384):
                deltas.append_many(stream[lo:lo + 16384])
            _drive_summarizer(scratch, log_format, summary_ops)
            store = open_summary_store(scratch)
            index = SummaryIndex(scratch, log_format)

        # Boot-equivalence gate (+ jit warm-up for the boot path).
        cold = SummaryReplica(None)
        cold.apply_records(stream)
        cold_digest = cold.state_digest()
        for _ in range(max(1, boot_checks)):
            cu = read_catchup(scratch, "doc0", log_format,
                              index=index, store=store,
                              deltas_topic=hot_deltas_topic)
            assert cu["manifest"] is not None, "no summary emitted"
            boot = SummaryReplica(cu["blob"])
            boot.apply_records(cu["ops"])
            assert boot.state_digest() == cold_digest, (
                "summary+tail boot diverged from cold replay under "
                "stampede conditions"
            )
        if elastic:
            # The merged per-range surface resolves EVERY range's
            # docs, not just the hot one.
            for doc, want in bg_digests.items():
                rid = range_for_doc(topo, doc)["rid"]
                cu = read_catchup(scratch, doc, log_format,
                                  index=index, store=store,
                                  deltas_topic=f"deltas-{rid}")
                assert cu["manifest"] is not None, (
                    f"merged index missed {doc}'s range summary"
                )
                boot = SummaryReplica(cu["blob"])
                boot.apply_records(cu["ops"])
                assert boot.state_digest() == want, (
                    f"per-range boot diverged for {doc}"
                )

        # The stampede proper: all sessions released at once.
        h_catchup = reg.histogram("op_stage_ms", stage="read_catchup")
        lat_ms: List[float] = [0.0] * n_sessions
        sigs: List[Optional[str]] = [None] * n_sessions
        errors: List[str] = []
        barrier = threading.Barrier(min(threads, n_sessions) + 1)
        next_session = [0]
        lock = threading.Lock()

        def session_sig(cu: dict) -> str:
            man = cu["manifest"]
            ops = cu["ops"]
            payload = json.dumps([
                man["seq"], man["handle"], len(ops),
                ops[0]["seq"] if ops else None,
                ops[-1]["seq"] if ops else None,
            ])
            return hashlib.sha256(payload.encode()).hexdigest()

        def worker():
            try:
                barrier.wait(timeout=60)
            except threading.BrokenBarrierError:
                return
            while True:
                with lock:
                    i = next_session[0]
                    if i >= n_sessions:
                        return
                    next_session[0] = i + 1
                try:
                    t0 = time.perf_counter()
                    cu = read_catchup(scratch, "doc0", log_format,
                                      index=index, store=store,
                                      deltas_topic=hot_deltas_topic)
                    ms = (time.perf_counter() - t0) * 1000.0
                    lat_ms[i] = ms
                    sigs[i] = session_sig(cu)
                    h_catchup.observe(ms)
                    if recorder.note(ms):
                        recorder.add(ms, {"session": i,
                                          "stage": "read_catchup"})
                except Exception as exc:  # surfaced as a gate failure
                    with lock:
                        errors.append(f"session {i}: {exc!r}")
                    return

        pool = [threading.Thread(target=worker, daemon=True)
                for _ in range(min(threads, n_sessions))]
        for t in pool:
            t.start()
        t0 = time.perf_counter()
        barrier.wait(timeout=60)  # the partition heals HERE
        for t in pool:
            t.join(timeout=600)
        wall = time.perf_counter() - t0
        assert not errors, f"stampede sessions failed: {errors[:3]}"
        assert all(s is not None for s in sigs), "sessions incomplete"
        assert len(set(sigs)) == 1, (
            f"stampede diverged: {len(set(sigs))} distinct catch-up "
            f"signatures across {n_sessions} sessions"
        )
        from ..utils.metrics import slo_summary

        slo = slo_summary(reg.snapshot())
        q = _span_quantiles(lat_ms)
        return {
            "scenario": "reconnect_stampede",
            "open_loop": True,  # all sessions offered at once
            "sessions": n_sessions,
            "log_len": log_len,
            "elastic_ranges": int(elastic_ranges) if elastic else 0,
            "summary_seq": cu["manifest"]["seq"],
            "tail_ops": len(cu["ops"]),
            "wall_s": round(wall, 3),
            "catchups_per_sec": round(n_sessions / wall, 1),
            "catchup_ms": q,
            "scenario_p99_ms": q["p99"],
            "boots_bit_identical": True,
            "digest": sigs[0],
            "slo": slo,
            "slow_ops": _slowest(recorder),
            "gate": ("summary+tail boots == cold replay; one catch-up "
                     "signature across every session"),
        }
    finally:
        restore()
        if work_dir is None:
            shutil.rmtree(scratch, ignore_errors=True)


# ---------------------------------------------------------------------------
# read-mostly swarm
# ---------------------------------------------------------------------------


def run_read_swarm(n_sessions: int = 100_000, n_docs: int = 4,
                   n_records: int = 64, n_tcp: int = 8,
                   feed_batch: int = 32, session_bar: int = 100_000,
                   min_cores: int = 4, timeout_s: float = 180.0,
                   work_dir: Optional[str] = None) -> dict:
    """`n_sessions` subscribed read sessions fanning out through
    `FarmReadServer` — the joins-and-reads shape production traffic
    actually has. `n_tcp` of them are REAL TCP sessions over the
    framed wire protocol (subscribe + live push, per-record latency
    observed against the append stamp); the rest are in-proc
    subscriber sessions on the same doorbell-woken pusher, which is
    the honest way to reach 100k sessions on one box without
    measuring the kernel's fd table instead of the fan-out path.

    Convergence gate (always): EVERY session — TCP and in-proc —
    receives its doc's `n_records` records exactly, and the TCP
    sessions' streams are seq-contiguous; a swarm cannot pass by
    dropping a subscriber. The throughput evidence (deliveries/s) is
    recorded-not-gated below the `session_bar`/`min_cores` honesty
    bar, with a LOUD skip naming why."""
    from ..server.framing import read_frame, write_frame
    from ..server.queue import SharedFileTopic
    from ..server.socket_service import FarmReadServer

    scratch = work_dir or tempfile.mkdtemp(
        prefix="swarm-",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    reg, recorder, restore = _fresh_metrics()
    n_tcp = min(n_tcp, n_sessions)
    srv = None
    tcp_socks: List[socket.socket] = []
    torn_down = [False]

    def _teardown():
        # Idempotent: runs inline on the happy path (server down
        # before the asserts) AND from the finally, so a failed
        # subscribe or a timed-out fan-out can never leak the server
        # thread / sockets past the scratch rmtree.
        if torn_down[0]:
            return
        torn_down[0] = True
        for s in tcp_socks:
            try:
                s.close()
            except OSError:
                pass
        if srv is not None:
            srv.stop()

    try:
        topic = SharedFileTopic(
            os.path.join(scratch, "topics", "broadcast.jsonl")
        )
        srv = FarmReadServer(scratch).start()
        h_push = reg.histogram("op_stage_ms", stage="broadcast_to_push")
        docs = [f"doc{d}" for d in range(n_docs)]
        n_light = n_sessions - n_tcp
        counts = [0] * n_light
        done_light = threading.Event()
        # Crossing counter, not an all() scan: at 100k sessions an
        # O(sessions) completion check per delivery callback would be
        # O(sessions²) and the swarm would measure the checker. The
        # pusher delivers from ONE thread, so the decrement is
        # race-free by construction.
        pending = [n_light]

        def light_session(i: int):
            def fn(recs):
                before = counts[i]
                counts[i] = before + sum(
                    1 for r in recs if r.get("kind") == "op"
                )
                if before < n_records <= counts[i]:
                    pending[0] -= 1
                    if pending[0] == 0:
                        done_light.set()
            return fn

        for i in range(n_light):
            srv.pusher.subscribe(docs[i % n_docs], light_session(i))
        if not n_light:
            done_light.set()

        # Real TCP sessions: framed subscribe + push-reader threads.
        tcp_counts = [0] * n_tcp
        tcp_seq_ok = [True] * n_tcp
        tcp_threads: List[threading.Thread] = []
        tcp_done = threading.Event()

        def tcp_reader(i: int, rf):
            last = 0
            while tcp_counts[i] < n_records:
                try:
                    frame = read_frame(rf)
                except (OSError, ValueError, ConnectionError):
                    return
                if frame is None:
                    return
                if frame.get("event") != "recs":
                    continue
                now = time.time()
                for r in frame["recs"]:
                    if r.get("kind") != "op":
                        continue
                    tcp_counts[i] += 1
                    if int(r["seq"]) != last + 1:
                        tcp_seq_ok[i] = False
                    last = int(r["seq"])
                    ts = r.get("ts")
                    if isinstance(ts, (int, float)):
                        ms = (now - ts) * 1000.0
                        h_push.observe(ms)
                        if recorder.note(ms):
                            recorder.add(ms, {
                                "session": f"tcp{i}",
                                "doc": r.get("doc"),
                                "seq": r.get("seq"),
                                "stage": "broadcast_to_push",
                            })
            if all(c >= n_records for c in tcp_counts):
                tcp_done.set()

        for i in range(n_tcp):
            s = socket.create_connection((srv.host, srv.port),
                                         timeout=30)
            tcp_socks.append(s)
            wf, rf = s.makefile("wb"), s.makefile("rb")
            write_frame(wf, {"id": 1, "cmd": "subscribe",
                             "docId": docs[i % n_docs]})
            resp = read_frame(rf)
            assert resp and "result" in resp, f"subscribe failed: {resp}"
            th = threading.Thread(target=tcp_reader, args=(i, rf),
                                  daemon=True)
            th.start()
            tcp_threads.append(th)
        if not n_tcp:
            tcp_done.set()

        # Feed: n_records per doc, batched, append-stamped so the TCP
        # sessions measure broadcast→push latency off the wire.
        t0 = time.perf_counter()
        for lo in range(0, n_records, feed_batch):
            hi = min(n_records, lo + feed_batch)
            for doc in docs:
                topic.append_many([
                    {"kind": "op", "doc": doc, "seq": s + 1, "msn": 0,
                     "client": 1, "clientSeq": s + 1, "refSeq": 0,
                     "type": "op", "contents": {"i": s},
                     "ts": time.time()}
                    for s in range(lo, hi)
                ])
        ok = done_light.wait(timeout=timeout_s) and \
            tcp_done.wait(timeout=timeout_s)
        wall = time.perf_counter() - t0
        _teardown()
        assert ok, (
            f"swarm fan-out incomplete within {timeout_s}s: slowest "
            f"in-proc session {min(counts) if counts else n_records}"
            f"/{n_records}, tcp {tcp_counts}"
        )
        assert all(c == n_records for c in counts), (
            "an in-proc session saw duplicated records"
        )
        assert all(c == n_records for c in tcp_counts) and \
            all(tcp_seq_ok), (
                f"tcp sessions incomplete or out of order: "
                f"{tcp_counts} seq_ok={tcp_seq_ok}"
            )
        from ..utils.metrics import slo_summary

        slo = slo_summary(reg.snapshot())
        total = n_sessions * n_records
        p99 = _slo_p99(slo, "broadcast_to_push")
        result: Dict[str, Any] = {
            "scenario": "read_swarm",
            "open_loop": True,  # feed never waits on delivery
            "sessions": n_sessions,
            "tcp_sessions": n_tcp,
            "docs": n_docs,
            "records_per_doc": n_records,
            "deliveries": total,
            "wall_s": round(wall, 3),
            "deliveries_per_sec": round(total / wall, 1),
            "push_ms": slo,
            "scenario_p99_ms": p99,
            "digest": hashlib.sha256(json.dumps(
                [n_sessions, n_records, sorted(set(counts)),
                 tcp_counts]).encode()).hexdigest(),
            "slo": slo,
            "slow_ops": _slowest(recorder),
            "gate": ("every session delivered exactly n_records; tcp "
                     "streams seq-contiguous"),
        }
        cores = os.cpu_count() or 1
        if n_sessions < session_bar or cores < min_cores:
            why = (f"{n_sessions} sessions < the {session_bar}-session "
                   f"bar" if n_sessions < session_bar
                   else f"host has {cores} cores < {min_cores}")
            result["skipped"] = (
                f"swarm throughput recorded-not-gated: {why}; the "
                f"fan-out convergence gate ran on every session"
            )
            import sys

            print(f"SKIP read_swarm throughput evidence: "
                  f"{result['skipped']}", file=sys.stderr)
        return result
    finally:
        _teardown()  # failure paths: server down BEFORE the rmtree
        restore()
        if work_dir is None:
            shutil.rmtree(scratch, ignore_errors=True)


# ---------------------------------------------------------------------------
# tenant-skewed mix
# ---------------------------------------------------------------------------


def run_tenant_mix(n_tenants: int = 8, records: int = 4000,
                   hot_share: float = 0.7, rate_hz: float = 400.0,
                   rate_limit: float = 150.0, n_partitions: int = 2,
                   log_format: str = "json", timeout_s: float = 120.0,
                   seed: int = 17,
                   work_dir: Optional[str] = None) -> dict:
    """A zipf-shaped tenant mix through the PR 12 front door: tenant
    ``t0`` offers `hot_share` of a `rate_hz` open-loop stream — well
    over the per-tenant `rate_limit` token bucket — while the cold
    tenants split the rest, each far under it. The bucket must bill
    the hot tenant and ONLY the hot tenant: visible 429 rate-nacks on
    t0's docs, zero on anyone else's, and the throttled tail retried
    to exactly-once convergence (the real client contract).

    Wire traces are ON, so the run also proves the `admit_to_stamp`
    stage end-to-end: the front door stamps ``tr_adm``, the deli folds
    it into the trace dict and observes the stage, and the /slo body
    carries both the quantiles and the `ingress_*` refusal counters."""
    from ..server.columnar_log import make_topic
    from ..server.ingress import IngressRole, write_tenants
    from ..server.riddler import sign_token
    from ..server.supervisor import DeliRole, partitioned_role_class
    from ..utils.metrics import slo_summary

    scratch = work_dir or tempfile.mkdtemp(prefix="tenant-mix-")
    reg, recorder, restore = _fresh_metrics()
    prev_trace = os.environ.get("FLUID_TRACE_WIRE")
    os.environ["FLUID_TRACE_WIRE"] = "1"
    rng = random.Random(seed)
    try:
        tenants = {f"t{i}": f"mix-key-{i}" for i in range(n_tenants)}
        write_tenants(scratch, tenants)
        # One doc per tenant (names spread across partitions by the
        # consistent hash as-is; skew is the POINT here, not balance).
        docs = {t: f"{t}-doc" for t in tenants}
        doc_tenant = {d: t for t, d in docs.items()}
        tokens = {
            t: sign_token(k, t, docs[t], ["doc:write"],
                          lifetime_s=24 * 3600.0)
            for t, k in tenants.items()
        }
        ing = IngressRole(
            scratch, "mix-ingress", ttl_s=3600.0, batch=8192,
            log_format=log_format, n_partitions=n_partitions,
            rate_limit=rate_limit,
            # Half-second bucket depth: the default 2x-rate burst
            # would absorb a whole scaled run before the hot tenant
            # ever hit the sustained limit the scenario is about.
            rate_burst=max(1.0, rate_limit / 2.0),
        )
        delis = [
            partitioned_role_class(DeliRole, k)(
                scratch, f"mix-deli-p{k}", ttl_s=3600.0, batch=8192,
                log_format=log_format,
            )
            for k in range(n_partitions)
        ] if n_partitions > 1 else [
            DeliRole(scratch, "mix-deli", ttl_s=3600.0, batch=8192,
                     log_format=log_format)
        ]
        ing_topic = make_topic(
            os.path.join(scratch, "topics", "ingress.jsonl"), log_format
        )
        nacks_topic = make_topic(
            os.path.join(scratch, "topics", "nacks.jsonl"), log_format
        )
        # Sessions open first (the alfred connection shape): ops then
        # ride bare and inherit their (doc, client) session.
        ing_topic.append_many([
            {"kind": "auth", "doc": docs[t], "client": 1, "tenant": t,
             "token": tokens[t]}
            for t in tenants
        ])
        # Joins ride the front door too (session-authed, one bucket
        # token each): a client must be in the doc's collaborator set
        # before its first op or the deli nacks the whole stream.
        ing_topic.append_many([
            {"kind": "join", "doc": docs[t], "client": 1}
            for t in tenants
        ])
        while ing.step() > 0:
            pass

        # The offered mix: hot_share of picks on t0, the rest spread
        # over the cold tenants — contiguous clientSeq per tenant.
        cold = [t for t in tenants if t != "t0"]
        cseq = {t: 0 for t in tenants}
        plan: List[dict] = []
        for i in range(records):
            t = "t0" if rng.random() < hot_share else \
                cold[i % len(cold)]
            cseq[t] += 1
            plan.append({"kind": "op", "doc": docs[t], "client": 1,
                         "clientSeq": cseq[t], "refSeq": 0,
                         "contents": {"i": i}})
        offered = {t: cseq[t] for t in tenants}

        def pump():
            ing.step()
            for d in delis:
                d.step()

        # Open-loop feed at rate_hz (small batches so the bucket sees
        # a stream, not one burst); the feeder never waits on
        # sequencing — it pumps the roles only while pacing.
        t0 = time.perf_counter()
        step = max(1, int(rate_hz / 50))  # ~50 appends/s
        i = 0
        while i < len(plan):
            tick = t0 + i / rate_hz
            while time.perf_counter() < tick:
                pump()
                time.sleep(0.001)
            ing_topic.append_many(plan[i:i + step])
            i += step
            pump()
        feed_wall_s = time.perf_counter() - t0

        # Retry-and-converge: resubmit each nacked client-tail (both
        # ingress throttle nacks and any deli order nacks a gate flip
        # let through) until every offered op is sequenced once.
        deltas = [
            make_topic(os.path.join(
                scratch, "topics",
                f"deltas-p{k}.jsonl" if n_partitions > 1
                else "deltas.jsonl",
            ), log_format)
            for k in range(max(1, n_partitions))
        ]

        # Incremental drains (TailReader cursors, never a from-zero
        # re-read per pass — a from-zero scan would be O(records²)
        # over the retry window): `ops`/`every`/`seen` accumulate, and
        # nack triggers (ingress throttles AND deli order-nacks, which
        # land on the deltas topics) collect into pending_tails as
        # they arrive.
        from ..server.columnar_log import make_tail_reader

        seq_readers = [make_tail_reader(t, 0) for t in deltas]
        nack_reader = make_tail_reader(nacks_topic, 0)
        ops: List[dict] = []
        every: List[dict] = []
        seen: set = set()
        pending_tails: Dict[str, int] = {}

        def note_nack(r: Any) -> None:
            if isinstance(r, dict) and r.get("kind") == "nack" \
                    and r.get("doc") in doc_tenant:
                c = int(r.get("clientSeq") or 0)
                d = r["doc"]
                pending_tails[d] = min(pending_tails.get(d, c), c)

        def drain() -> None:
            for rd in seq_readers:
                for _i, r in rd.poll():
                    if not isinstance(r, dict):
                        continue
                    if r.get("kind") == "op":
                        every.append(r)
                        if r.get("type") == "op":
                            ops.append(r)
                            seen.add((r["doc"], r["clientSeq"]))
                    else:
                        note_nack(r)
            for _i, r in nack_reader.poll():
                note_nack(r)

        retries = 0
        last_retry = 0.0
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            pump()
            drain()
            if len(ops) >= records:
                break
            if time.time() - last_retry < 0.1:
                # Pace the resubmissions to the bucket's refill, or
                # every pass re-offers the whole throttled tail and
                # the nack log measures the retry loop, not the mix.
                time.sleep(0.002)
                continue
            last_retry = time.time()
            # Nacked tails: lowest nacked clientSeq per doc since the
            # last retry, resubmit everything unsequenced from there
            # (per-doc = per-tenant here). A re-throttled resubmit
            # produces fresh nacks, which re-trigger the next pass.
            tails, pending_tails = pending_tails, {}
            batch = [p for p in plan
                     if p["doc"] in tails
                     and p["clientSeq"] >= tails[p["doc"]]
                     and (p["doc"], p["clientSeq"]) not in seen]
            if batch:
                retries += len(batch)
                ing_topic.append_many(batch)
            time.sleep(0.002)

        # Convergence digest: every offered op exactly once, contents
        # intact, per-doc seqs contiguous.
        keys = [(r["doc"], r["clientSeq"]) for r in ops]
        assert len(ops) == records and len(set(keys)) == records, (
            f"tenant mix did not converge exactly-once: {len(ops)} "
            f"ops, {len(set(keys))} unique of {records}"
        )
        want = {(p["doc"], p["clientSeq"]):
                p["contents"] for p in plan}
        for r in ops:
            assert want[(r["doc"], r["clientSeq"])] == r["contents"], (
                f"contents corrupted for {r['doc']}#{r['clientSeq']}"
            )
        dups, skips = sequence_integrity(every)
        assert dups == 0 and skips == 0
        # Throttle taxonomy: rate nacks exist and bill ONLY t0.
        rate_nacks: Dict[str, int] = {}
        for r in nacks_topic.read_from(0):
            if isinstance(r, dict) and r.get("kind") == "nack" and \
                    str(r.get("reason", "")).startswith("rate:"):
                t = doc_tenant.get(r.get("doc"), "?")
                rate_nacks[t] = rate_nacks.get(t, 0) + 1
        assert rate_nacks.get("t0"), (
            "hot tenant was never throttled — the mix exercised no "
            "token bucket"
        )
        assert set(rate_nacks) == {"t0"}, (
            f"cold tenants were throttled too: {rate_nacks} (the "
            f"bucket must bill the hot tenant only)"
        )
        # Admission-stage evidence: adm stamps rode the wire and the
        # deli observed admit_to_stamp; feed the slowest admissions to
        # the scenario recorder as its slow-op spans.
        adm_ms: List[float] = []
        for r in ops:
            tr = r.get("tr")
            if isinstance(tr, dict) and "adm" in tr and "stamp" in tr:
                assert tr["adm"] <= tr["stamp"], f"adm > stamp: {tr}"
                ms = (tr["stamp"] - tr["adm"]) * 1000.0
                adm_ms.append(ms)
                if recorder.note(ms):
                    recorder.add(ms, {
                        "doc": r.get("doc"), "seq": r.get("seq"),
                        "stage": "admit_to_stamp",
                    })
        assert adm_ms, "no admit_to_stamp spans rode the wire"
        slo = slo_summary(reg.snapshot())
        assert _slo_p99(slo, "admit_to_stamp") is not None, (
            "/slo carries no admit_to_stamp quantiles"
        )
        assert any(c["name"] == "ingress_nacks_total"
                   for c in slo.get("counters", ())), (
            "/slo carries no ingress refusal counters"
        )
        q = _span_quantiles(adm_ms)
        return {
            "scenario": "tenant_mix",
            "open_loop": True,
            "records": records,
            "tenants": n_tenants,
            "hot_share": hot_share,
            "offered_per_tenant": offered,
            "rate_hz": rate_hz,
            "rate_limit": rate_limit,
            "feed_wall_s": round(feed_wall_s, 3),
            "throttle_nacks": rate_nacks,
            "retries": retries,
            "admit_to_stamp_ms": q,
            "scenario_p99_ms": q["p99"],
            "digest": stream_digest(ops),
            "slo": slo,
            "slow_ops": _slowest(recorder),
            "gate": ("exactly-once after retries; rate nacks bill the "
                     "hot tenant only; admit_to_stamp + ingress "
                     "counters in /slo"),
        }
    finally:
        if prev_trace is None:
            os.environ.pop("FLUID_TRACE_WIRE", None)
        else:
            os.environ["FLUID_TRACE_WIRE"] = prev_trace
        restore()
        if work_dir is None:
            shutil.rmtree(scratch, ignore_errors=True)


# ---------------------------------------------------------------------------
# week-of-traffic churn (the retention plane's gate)
# ---------------------------------------------------------------------------


def run_week_of_traffic(cycles: int = 4, hot_writers: int = 12,
                        cold_docs: int = 2, cold_clients: int = 2,
                        ops_per_writer: int = 30,
                        summary_ops: int = 64, rate_hz: float = 500.0,
                        stampede_sessions: int = 16,
                        swarm_sessions: int = 48,
                        deli_impl: str = "scalar",
                        retention: bool = True,
                        keep_tail: int = 256,
                        hwm_slack: float = 1.35,
                        timeout_s: float = 300.0,
                        work_dir: Optional[str] = None) -> dict:
    """The MIXED week-of-traffic shape (ROADMAP 4 follow-up (c)):
    storm + stampede + swarm CONCURRENTLY, compressed into `cycles`
    generations of churning collaborators — and the retention plane's
    churn gate (ROADMAP 3 / ISSUE 14 acceptance).

    Each cycle, a FRESH band of writers joins (one viral hot doc takes
    most of the load, a cold background mix the rest), streams
    bounded merge-tree edits open-loop at `rate_hz`, and LEAVES — the
    collab window closes, so summaries settle to state-sized blobs.
    While the cycle streams, `swarm_sessions` subscribed read sessions
    ride the broadcast push (every one must see every record of its
    doc), and a `stampede_sessions`-strong reconnect burst hits the
    summary catch-up path mid-run (one signature across the burst).

    With `retention=True` the farm runs the SIXTH role
    (`server.retention.RetentionRole`, columnar log, fused
    durable+broadcast hop): deltas/rawdeltas/durable/broadcast all
    truncate behind the summary epoch and unreferenced castore blobs
    sweep. The gate:

    - **bounded disk** — the on-disk high-water mark (op logs +
      castore) stops growing after the first retention cycle:
      ``max(usage[2:]) <= hwm_slack * usage[1]``;
    - **bit-identity** — a LIVE client's accumulated stream, a COLD
      boot from the newest summary + tail, and a LONG-OFFLINE
      reconnector (last saw cycle 0; its op gap is partially
      reclaimed, so it must REBOOT from the summary, not replay)
      all converge to one `state_digest` per doc, with zero
      duplicate/skipped seqs.

    Returns the per-cycle usage table and ``retention_disk_mb`` (the
    steady-state high-water mark, the bench_trend lower-is-better
    ledger line)."""
    if retention and cycles < 3:
        raise ValueError(
            "retention=True needs cycles >= 3: the bounded-disk gate "
            "compares the high-water mark of cycles AFTER the first "
            "retention cycle against cycle 1 — with fewer cycles "
            "there is nothing to compare and the gate is vacuous"
        )
    from ..server.columnar_log import make_tail_reader, make_topic
    from ..server.retention import disk_usage
    from ..server.socket_service import FarmReadServer
    from ..server.summarizer import (
        SummaryIndex,
        SummaryReplica,
        open_summary_store,
        read_catchup,
    )
    from ..server.supervisor import ServiceSupervisor, canonical_record

    scratch = work_dir or tempfile.mkdtemp(
        prefix="week-",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    log_format = "columnar"
    srv = None
    sup = None
    try:
        sup = ServiceSupervisor(
            scratch,
            roles=("deli", "scriptorium", "broadcaster", "scribe",
                   "summarizer"),
            fused_hop=True, deli_impl=deli_impl,
            log_format=log_format, summary_ops=summary_ops,
            retention=retention, ttl_s=0.75, hb_interval_s=0.1,
            # A loaded CI box stalls children past the default 2s
            # staleness bar without any real fault; a spurious restart
            # mid-cycle stalls retention and races the disk sample.
            # Restart-on-crash still works — this only widens the
            # wedged-child detector.
            heartbeat_timeout_s=6.0,
            retention_env={
                "FLUID_RETENTION_INTERVAL": "0.25",
                "FLUID_RETENTION_MIN_BYTES": "4096",
                # Spare tail: live pushers/readers are structurally
                # ahead of every cut (scaled with the workload — it
                # must stay well under one cycle's record count or
                # nothing ever qualifies).
                "FLUID_RETENTION_KEEP_TAIL": str(int(keep_tail)),
                "FLUID_RETENTION_GRACE": "1.0",
                # Every growth surface, metadata included: the op
                # logs, plus the manifest topic (superseded manifests
                # beyond the keep depth) and the retention topic's own
                # commit history (only the newest commit per topic is
                # ever read again).
                "FLUID_RETENTION_TOPICS":
                    "deltas,rawdeltas,durable,broadcast,"
                    "summaries,retention",
            } if retention else None,
        ).start()
        raw = make_topic(
            os.path.join(scratch, "topics", "rawdeltas.jsonl"),
            log_format,
        )
        broadcast = make_topic(
            os.path.join(scratch, "topics", "broadcast.jsonl"),
            log_format,
        )
        retention_topic = make_topic(
            os.path.join(scratch, "topics", "retention.jsonl"),
            log_format,
        ) if retention else None
        docs = ["hotdoc"] + [f"cold{i}" for i in range(cold_docs)]
        # The LIVE client: an incremental broadcast tail accumulated
        # across the whole run (per-doc canonical records) — what any
        # connected session would have seen.
        bc_reader = make_tail_reader(broadcast, 0)
        live: Dict[str, List[dict]] = {d: [] for d in docs}

        def drain_live() -> None:
            for _i, r in bc_reader.poll():
                if isinstance(r, dict) and r.get("kind") == "op" \
                        and r.get("doc") in live:
                    live[r["doc"]].append(canonical_record(r))

        # The SWARM: in-proc subscribed sessions on the farm's read
        # front end (same doorbell-woken pusher real TCP rides).
        srv = FarmReadServer(scratch, log_format=log_format)
        srv.start()
        swarm_counts = [0] * swarm_sessions
        swarm_docs = [docs[i % len(docs)] for i in range(swarm_sessions)]

        def swarm_session(i: int):
            def fn(recs):
                swarm_counts[i] += sum(
                    1 for r in recs if r.get("kind") == "op"
                )
            return fn

        for i in range(swarm_sessions):
            srv.pusher.subscribe(swarm_docs[i], swarm_session(i))

        # Feeder model: feed order == sequence order (one feeder, one
        # raw topic), so refSeq can track the head exactly and the
        # text length model is exact — bounded merge-tree docs whose
        # canonical rows (and therefore blobs) stay O(state).
        head = {d: 0 for d in docs}  # per-doc fed-record count == seq
        text_len = {d: 0 for d in docs}

        def reader_lag() -> int:
            """How far the slowest UNTRACKED broadcast reader (the
            live tail, the swarm's pusher) trails the fed head, in
            records. Retention spares only `keep_tail` records behind
            its scan head for these readers — no checkpoint tracks
            them — and a reader lapped past a cut silently resumes at
            the truncation base (records between are gone, failing
            the convergence gates minutes later and doc-load-
            dependent). Joins/leaves sequence as records too, so the
            fed head is directly comparable to delivered counts."""
            total = sum(head.values())
            live_lag = total - sum(len(live[d]) for d in docs)
            swarm_lag = max(
                (head[swarm_docs[i]] - swarm_counts[i]
                 for i in range(swarm_sessions)),
                default=0,
            )
            return max(live_lag, swarm_lag)

        def feed(recs: List[dict]) -> None:
            # Backpressure (retention runs only): pace the feed so no
            # untracked reader falls further behind than HALF the
            # keep_tail spare — the cut can then never lap a live
            # reader by construction, however asymmetrically a loaded
            # host schedules the parent's reader threads against the
            # retention child. Bounded wait: a wedged farm surfaces
            # as the cycle-drain assertion, not a silent hang here.
            if retention:
                limit = time.time() + 30.0
                while reader_lag() > keep_tail // 2 and \
                        time.time() < limit:
                    pump(0.002)
            for r in recs:
                head[r["doc"]] += 1
            raw.append_many(recs)

        def mt_op(doc: str, i: int) -> dict:
            if text_len[doc] >= 120:
                k = 60
                text_len[doc] -= k
                return {"type": 1, "pos1": 0, "pos2": k}
            seg = f"w{i % 97:02d}"
            text_len[doc] += len(seg)
            return {"type": 0, "pos1": 0, "seg": seg}

        def pump(dt: float = 0.0) -> None:
            sup.poll_once()
            drain_live()
            if dt:
                time.sleep(dt)

        usage: List[int] = []
        stampede_sigs: List[set] = []
        reconnect_seen = 0  # the long-offline client's last seq (hot)
        truncs_seen = 0
        activity_seen = 0  # retention records-ever-appended high water
        for cycle in range(cycles):
            deadline = time.time() + timeout_s
            base_id = 1000 * (cycle + 1)
            hot = [base_id + w for w in range(hot_writers)]
            colds = [(f"cold{d}", base_id + w)
                     for d in range(cold_docs)
                     for w in range(cold_clients)]
            feed([{"kind": "join", "doc": "hotdoc", "client": c,
                   "refSeq": head["hotdoc"]} for c in hot])
            feed([{"kind": "join", "doc": d, "client": c,
                   "refSeq": head[d]} for d, c in colds])
            # Open-loop-paced edit stream: hot writers round-robin on
            # the viral doc, cold writers on the background docs.
            plan: List[tuple] = []
            for i in range(ops_per_writer):
                for w in hot:
                    plan.append(("hotdoc", w, i + 1))
                for d, c in colds:
                    plan.append((d, c, i + 1))
            t0 = time.perf_counter()
            for j, (doc, client, cseq) in enumerate(plan):
                tick = t0 + j / rate_hz
                while time.perf_counter() < tick:
                    pump(0.001)
                feed([{"kind": "op", "doc": doc, "client": client,
                       "clientSeq": cseq, "refSeq": head[doc],
                       "contents": mt_op(doc, j)}])
                if j % 16 == 0:
                    pump()
            # Churn: the whole generation LEAVES — the collab window
            # closes behind it, summaries settle, blobs stay bounded.
            feed([{"kind": "leave", "doc": "hotdoc", "client": c}
                  for c in hot])
            feed([{"kind": "leave", "doc": d, "client": c}
                  for d, c in colds])
            # Every record of the cycle must reach the live tail
            # (joins/leaves sequence too, so the target is the head).
            while time.time() < deadline:
                pump(0.005)
                if all(len(live[d]) >= head[d] for d in docs):
                    break
            else:
                raise AssertionError(
                    f"cycle {cycle} never drained: "
                    f"{ {d: len(live[d]) for d in docs} } of "
                    f"{ {d: head[d] for d in docs} }"
                )
            if cycle == 0:
                # The long-offline reconnector saw exactly cycle 0.
                reconnect_seen = max(
                    int(r["seq"]) for r in live["hotdoc"]
                )
            # Mid-run reconnect STAMPEDE through the summary path
            # (after cycle 1 a summary provably exists). Quiesce the
            # summarizer first — a manifest landing MID-burst would
            # legitimately split the signatures.
            if cycle >= 1:
                from ..server.queue import FencedCheckpointStore

                ck = FencedCheckpointStore(
                    os.path.join(scratch, "checkpoints")
                )

                def summ_offset() -> int:
                    env = ck.load("summarizer")
                    try:
                        return int(((env or {}).get("state") or {})
                                   .get("offset", 0))
                    except (TypeError, ValueError):
                        return 0

                total = sum(head.values())
                while summ_offset() < total and \
                        time.time() < deadline:
                    pump(0.02)
                idx = SummaryIndex(scratch, log_format)
                store = open_summary_store(scratch)
                idx.poll()
                last_man = idx.nearest("hotdoc")
                stable_t = time.time()
                while time.time() - stable_t < 0.8 and \
                        time.time() < deadline:
                    pump(0.05)
                    idx.poll()
                    cur = idx.nearest("hotdoc")
                    if (cur or {}).get("handle") != \
                            (last_man or {}).get("handle"):
                        last_man, stable_t = cur, time.time()
                sigs: List[Optional[str]] = [None] * stampede_sessions
                errs: List[str] = []

                def catchup_session(i: int) -> None:
                    try:
                        cu = read_catchup(scratch, "hotdoc", log_format,
                                          index=idx, store=store)
                        man = cu["manifest"]
                        sigs[i] = json.dumps([
                            man["seq"] if man else None,
                            man["handle"] if man else None,
                            len(cu["ops"]),
                        ])
                    except Exception as exc:  # gate failure, surfaced
                        errs.append(repr(exc))

                pool = [threading.Thread(target=catchup_session,
                                         args=(i,), daemon=True)
                        for i in range(stampede_sessions)]
                for t in pool:
                    t.start()
                for t in pool:
                    t.join(timeout=120)
                assert not errs, f"stampede failed: {errs[:3]}"
                assert all(s is not None for s in sigs)
                stampede_sigs.append(set(sigs))
                assert len(stampede_sigs[-1]) == 1, (
                    f"stampede diverged in cycle {cycle}: "
                    f"{stampede_sigs[-1]}"
                )
            # Let the retention plane SETTLE before sampling disk:
            # wait for the truncate-commit stream to go quiet (~4
            # retention intervals with nothing new — the reclaimable
            # prefix is cut incrementally, so breaking on the first
            # commit would race the rest), then one GC grace beat.
            if retention:
                # Progress target first: rawdeltas reclaims up to the
                # deli's checkpoint (= the head), so its base reaching
                # head - keep_tail (frame-granular slack) proves the
                # plane worked through THIS cycle — a restarted child
                # mid-cycle just makes the wait longer, not the sample
                # wrong.
                # (margin: keep_tail spare + frame granularity + the
                # min-reclaim-bytes hysteresis, in records)
                target = max(0, sum(head.values()) - 2 * keep_tail - 256)
                wait_until = time.time() + 60.0
                while time.time() < wait_until:
                    pump(0.01)
                    if raw.base_offsets()[0] >= target:
                        break
                # Then commit quiescence: the reclaimable prefix cuts
                # incrementally, so sample only once the commit stream
                # goes quiet. Activity is RECORDS EVER APPENDED
                # (base + visible) — the retention topic prunes its
                # own commit history, so a visible-commit count can
                # DROP below a prior cycle's and freeze the fast
                # break; records-ever-appended is monotone under
                # self-pruning.
                last_n = -1
                stable_t = time.time()
                wait_until = time.time() + 45.0
                while time.time() < wait_until:
                    pump(0.01)
                    recs = retention_topic.read_from(0)
                    n = retention_topic.base_offsets()[0] + len(recs)
                    # Visible commits only bound the stat from below
                    # after a self-prune; the newest commit per topic
                    # always survives, so the gate stays nonzero.
                    truncs_seen = max(truncs_seen, sum(
                        1 for r in recs
                        if isinstance(r, dict)
                        and r.get("kind") == "truncate"
                    ))
                    if n != last_n:
                        last_n, stable_t = n, time.time()
                    elif time.time() - stable_t >= 1.0 and \
                            n > activity_seen:
                        break
                    elif time.time() - stable_t >= 6.0:
                        break  # nothing reclaimable this cycle
                activity_seen = max(activity_seen, last_n)
                time.sleep(1.2)  # one GC grace beat
                pump()
            usage.append(disk_usage(scratch)["total_bytes"])
        # ------------------------------------------------ final gates
        # Swarm completeness: every subscribed session saw every op of
        # its doc (subscriptions predate the first record).
        for i in range(swarm_sessions):
            # Joins/leaves sequence as kind=="op" records too, so each
            # session's complete view is its doc's HEAD count.
            want = head[swarm_docs[i]]
            got = swarm_counts[i]
            lim = time.time() + 30.0
            while got < want and time.time() < lim:
                pump(0.01)
                got = swarm_counts[i]
            assert got == want, (
                f"swarm session {i} ({swarm_docs[i]}): {got}/{want} "
                f"records delivered"
            )
        # Sequence integrity + tri-view bit-identity per doc.
        dups, skips = sequence_integrity(
            [r for d in docs for r in live[d]]
        )
        assert dups == 0 and skips == 0, f"dups={dups} skips={skips}"
        store = open_summary_store(scratch)
        digests: Dict[str, str] = {}
        for d in docs:
            cu = read_catchup(scratch, d, log_format, store=store)
            assert cu["manifest"] is not None, f"no summary for {d}"
            boot = SummaryReplica(cu["blob"])
            boot.apply_records(cu["ops"])
            live_rep = SummaryReplica(None)
            live_rep.apply_records(live[d])
            assert boot.state_digest() == live_rep.state_digest(), (
                f"cold-from-summary boot diverged from the live "
                f"client on {d}"
            )
            digests[d] = boot.state_digest()
        # The long-offline reconnector: its gap is (partially)
        # reclaimed, so the farm MUST answer with a summary reboot —
        # newest manifest past its last seen seq — not a gap replay.
        recon = srv.catchup("hotdoc", from_seq=reconnect_seen)
        assert recon["rebase"] and recon["blob"] is not None, (
            "long-offline reconnect did not reboot from a summary"
        )
        assert recon["manifest"]["seq"] > reconnect_seen
        rboot = SummaryReplica(recon["blob"])
        rboot.apply_records(recon["ops"])
        assert rboot.state_digest() == digests["hotdoc"], (
            "reconnector diverged after summary reboot"
        )
        # Bounded disk: the high-water mark stops growing after the
        # first retention cycle.
        result: Dict[str, Any] = {
            "scenario": "week_of_traffic",
            "open_loop": True,
            "cycles": cycles,
            "records": sum(head.values()),
            "hot_writers_per_cycle": hot_writers,
            "swarm_sessions": swarm_sessions,
            "stampede_sessions": stampede_sessions,
            "retention": retention,
            "disk_bytes_per_cycle": usage,
            "truncations": truncs_seen,
            "digest": hashlib.sha256(json.dumps(
                sorted(digests.items())).encode()).hexdigest(),
            "gate": ("disk hwm bounded after first retention cycle; "
                     "live == cold-from-summary == reconnector "
                     "bit-identical; swarm complete; zero dup/skip"),
        }
        if retention:
            assert truncs_seen > 0, "retention never truncated"
            hwm = max(usage[1:])
            result["retention_disk_mb"] = round(hwm / 1e6, 3)
            result["unit"] = "MB"
            assert max(usage[2:]) <= \
                hwm_slack * usage[1], (
                    f"disk high-water mark kept growing after the "
                    f"first retention cycle: {usage} "
                    f"(slack {hwm_slack})"
                )
        else:
            result["disk_mb_unbounded"] = round(max(usage) / 1e6, 3)
        return result
    finally:
        if srv is not None:
            srv.stop()
        if sup is not None:
            sup.stop()
        if work_dir is None:
            shutil.rmtree(scratch, ignore_errors=True)


# ---------------------------------------------------------------------------
# the suite
# ---------------------------------------------------------------------------


def scenario_p99s(suite: dict) -> Dict[str, Optional[float]]:
    """{scenario: p99_ms} off a `run_scenario_suite` result — the
    numbers the bench_trend ledger guards (lower is better)."""
    return {
        name: suite[name].get("scenario_p99_ms")
        for name in ("storm", "stampede", "swarm", "tenant_mix")
        if isinstance(suite.get(name), dict)
    }


def run_scenario_suite(scale: float = 1.0, deli_impl: str = "scalar",
                       log_format: str = "json",
                       swarm_sessions: int = 100_000,
                       stampede_elastic_ranges: int = 0,
                       work_dir: Optional[str] = None) -> dict:
    """All four scenario primitives at a common `scale` (1.0 = the
    full shapes: 2k-writer storm, 2k-session stampede, 100k-session
    swarm, 4k-record tenant mix). Every scenario's convergence and
    evidence gates run at EVERY scale — the asserts live inside the
    primitives; a scaled-down suite still proves the contracts, it
    only shrinks the load. Throughput/p99 honesty is per scenario
    (the swarm loud-skips below its session/core bar; the ledger
    gating of p99s is `tools/bench_configs.config13_scenarios`'
    business)."""
    suite: Dict[str, Any] = {
        "metric": "scenario_suite",
        "scale": scale,
        "deli_impl": deli_impl,
        "log_format": log_format,
        "cores": os.cpu_count(),
    }
    suite["storm"] = run_hotdoc_storm(
        n_writers=max(16, int(2000 * scale)),
        cold_docs=max(2, int(32 * scale)),
        rate_hz=max(50.0, 300.0 * scale),
        duration_s=max(1.0, 4.0 * scale),
        deli_impl=deli_impl, log_format=log_format,
        work_dir=os.path.join(work_dir, "storm") if work_dir else None,
    )
    suite["stampede"] = run_reconnect_stampede(
        n_sessions=max(24, int(2000 * scale)),
        log_len=max(2048, int(20000 * scale)),
        log_format=log_format,
        # >= 2: the per-range elastic-summary variant (PR 13
        # follow-up b) — the burst reads through the MERGED
        # SummaryIndex over hash-range summaries-{rid} topics.
        elastic_ranges=stampede_elastic_ranges,
        work_dir=os.path.join(work_dir, "stampede")
        if work_dir else None,
    )
    suite["swarm"] = run_read_swarm(
        n_sessions=max(64, int(swarm_sessions * scale)),
        work_dir=os.path.join(work_dir, "swarm") if work_dir else None,
    )
    suite["tenant_mix"] = run_tenant_mix(
        records=max(180, int(4000 * scale)),
        rate_hz=max(120.0, 400.0 * scale),
        # ~2.8x headroom between the hot tenant's offered rate
        # (hot_share * rate_hz) and the bucket: a loaded CI box that
        # stretches the feed wall clock must still leave the hot
        # tenant demonstrably over its budget.
        rate_limit=max(30.0, 100.0 * scale),
        log_format=log_format,
        work_dir=os.path.join(work_dir, "mix") if work_dir else None,
    )
    suite["scenario_p99s"] = scenario_p99s(suite)
    suite["gate"] = (
        "per-scenario convergence digests + /slo + slow-op evidence "
        "(asserted inside each primitive)"
    )
    return suite
