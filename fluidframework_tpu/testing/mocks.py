"""Multi-client runtime harness for DDS unit tests.

Plays the role of the reference's `MockContainerRuntimeFactory` +
`MockFluidDataStoreRuntime`
(packages/runtime/test-runtime-utils/src/mocks.ts:206,392): N real
`ContainerRuntime`s share one in-proc `LocalOrderingService` in
deferred mode; `process_all()` is the analog of
`processAllMessages` — drain the totally ordered stream to every
replica. Unlike the reference mocks these are the *production* runtime
classes; only the ordering service is local (which mirrors how the
reference integration tests run real lambdas in-proc, SURVEY.md §4).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..runtime.channel import ChannelRegistry
from ..runtime.container_runtime import ContainerRuntime, FlushMode
from ..server.local_service import LocalOrderingService

DEFAULT_DATASTORE = "default"


class MultiClientHarness:
    """N container runtimes collaborating on one document in-proc."""

    def __init__(
        self,
        n_clients: int,
        registry: ChannelRegistry,
        doc_id: str = "doc",
        flush_mode: FlushMode = FlushMode.TURN_BASED,
        channel_types: Optional[Sequence[tuple]] = None,
    ):
        """`channel_types`: [(channel_id, type_name), ...] created on
        every client's default datastore before connecting (the mock
        pattern: each replica constructs its own instance of the same
        channel, reference mocks.ts usage throughout dds tests)."""
        self.service = LocalOrderingService(deferred=True)
        self.doc_id = doc_id
        self.runtimes: List[ContainerRuntime] = []
        for i in range(n_clients):
            rt = ContainerRuntime(registry, flush_mode=flush_mode)
            ds = rt.create_datastore(DEFAULT_DATASTORE)
            for cid, tname in channel_types or []:
                ds.create_channel(cid, tname)
            self.runtimes.append(rt)
        for i, rt in enumerate(self.runtimes):
            conn = self.service.connect(doc_id, client_id=i + 1)
            rt.connect(conn)
        self.process_all()  # drain joins so every replica's seq aligns

    def channel(self, client_index: int, channel_id: str):
        return self.runtimes[client_index].get_datastore(
            DEFAULT_DATASTORE
        ).get_channel(channel_id)

    def flush_all(self) -> None:
        for rt in self.runtimes:
            rt.flush()

    def process_all(self) -> int:
        """Flush every client's outbox, then drain the sequenced stream
        to all replicas (processAllMessages, mocks.ts:107)."""
        self.flush_all()
        n = self.service.process_all(self.doc_id)
        # flushing during processing can enqueue more (e.g. resubmits)
        while True:
            self.flush_all()
            more = self.service.process_all(self.doc_id)
            if not more:
                return n
            n += more

    @property
    def sequencer(self):
        return self.service.sequencers[self.doc_id]
