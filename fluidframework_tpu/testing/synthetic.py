"""Synthetic totally-ordered op streams in columnar form.

The replay benchmarks (BASELINE.md configs 1-2: mixed SharedString
insert/remove/annotate from many clients) need op streams far larger
than the Python-object message path can cheaply materialize. This
module generates streams directly in the columnar layout the kernel
consumes (see `fluidframework_tpu.ops.mergetree_kernel.OpBatch`),
mirroring how the reference's replay tool pre-parses recorded op files
before the timed replay (packages/tools/replay-tool/src/replayMessages.ts).

Every generated op is *valid*: positions are within the visible length
at the op's perspective.

Two generators:

- `generate_stream`: ops use ``ref_seq = seq - 1`` (each client has
  seen the whole prefix when it submits) — cheap to produce, but the
  timed path never resolves a lagging perspective.
- `generate_lagged_stream`: the HONEST concurrency workload and the
  headline bench stream. Each client's ``ref_seq`` trails the head by
  a random lag up to the collaboration window, the way the reference's
  operation runner interleaves clients that have not yet seen each
  other's ops (packages/dds/merge-tree/src/test/
  mergeTreeOperationRunner.ts): positions are drawn within the
  *visible length at that lagging perspective* (queried from the
  native C++ engine, which replays the stream as it is generated), so
  replay engines must execute real concurrent-perspective resolution
  — insert tie-breaks, invisible-segment skips, overlapping removes —
  on every lagged op (the partialLengths.ts:256 role).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List

import numpy as np

from ..ops.mergetree_kernel import NO_KEY, OP_ANNOTATE, OP_INSERT, OP_REMOVE
from ..protocol.messages import MessageType, SequencedMessage
from ..protocol.mergetree_ops import AnnotateOp, InsertOp, RemoveOp


@dataclass
class ColumnarStream:
    """A sequenced op stream as parallel numpy arrays (one row per op)."""

    op_type: np.ndarray  # int32[N]
    pos1: np.ndarray  # int32[N]
    pos2: np.ndarray  # int32[N]
    seq: np.ndarray  # int32[N]
    ref_seq: np.ndarray  # int32[N]
    client: np.ndarray  # int32[N]
    buf_start: np.ndarray  # int32[N] (offset into `text`)
    ins_len: np.ndarray  # int32[N]
    prop_key: np.ndarray  # int32[N] (NO_KEY when no annotation)
    prop_val: np.ndarray  # int32[N]
    min_seq: np.ndarray  # int32[N] MSN as of this op
    text: np.ndarray  # int32[S] codepoint arena for all inserted text

    def __len__(self) -> int:
        return len(self.op_type)

    # ---------------------------------------------------------- messages

    def as_messages(self, limit: int | None = None) -> Iterator[SequencedMessage]:
        """Object-form view (for the scalar oracle / object-path replay)."""
        n = len(self) if limit is None else min(limit, len(self))
        for i in range(n):
            t = int(self.op_type[i])
            if t == OP_INSERT:
                lo = int(self.buf_start[i])
                text = "".join(
                    map(chr, self.text[lo : lo + int(self.ins_len[i])])
                )
                op = InsertOp(pos=int(self.pos1[i]), text=text)
            elif t == OP_REMOVE:
                op = RemoveOp(start=int(self.pos1[i]), end=int(self.pos2[i]))
            else:
                op = AnnotateOp(
                    start=int(self.pos1[i]),
                    end=int(self.pos2[i]),
                    props={f"k{int(self.prop_key[i])}": int(self.prop_val[i])},
                )
            yield SequencedMessage(
                sequence_number=int(self.seq[i]),
                minimum_sequence_number=int(self.min_seq[i]),
                client_id=int(self.client[i]),
                client_seq=0,
                ref_seq=int(self.ref_seq[i]),
                type=MessageType.OP,
                contents=op,
            )


def generate_stream(
    n_ops: int,
    n_clients: int = 1024,
    seed: int = 0,
    window: int = 1024,
    insert_weight: float = 0.55,
    remove_weight: float = 0.25,
    annotate_weight: float = 0.20,
    max_insert_len: int = 8,
    max_range_len: int = 16,
    n_prop_keys: int = 8,
    n_prop_vals: int = 16,
    initial_len: int = 64,
) -> ColumnarStream:
    """Generate `n_ops` mixed ops from `n_clients` round-robin clients.

    The MSN trails the head by `window` (the collaboration-window size
    deli would maintain for caught-up clients), so replay engines can
    compact tombstones exactly as they would in a live session.
    """
    rng = np.random.default_rng(seed)
    # Pre-draw all randomness (keeps the Python loop light).
    type_u = rng.random(n_ops)
    pos_u = rng.random(n_ops)
    len_draw = rng.integers(1, max_insert_len + 1, n_ops).astype(np.int64)
    range_draw = rng.integers(1, max_range_len + 1, n_ops).astype(np.int64)
    keys = rng.integers(0, n_prop_keys, n_ops).astype(np.int32)
    vals = rng.integers(0, n_prop_vals, n_ops).astype(np.int32)
    codepoints = rng.integers(ord("a"), ord("z") + 1, int(np.sum(len_draw))).astype(
        np.int32
    )

    w_total = insert_weight + remove_weight + annotate_weight
    t_ins = insert_weight / w_total
    t_rem = t_ins + remove_weight / w_total

    op_type = np.empty(n_ops, np.int32)
    pos1 = np.empty(n_ops, np.int32)
    pos2 = np.zeros(n_ops, np.int32)
    buf_start = np.zeros(n_ops, np.int32)
    ins_len = np.zeros(n_ops, np.int32)
    prop_key = np.full(n_ops, NO_KEY, np.int32)
    prop_val = np.zeros(n_ops, np.int32)

    length = initial_len  # visible length before op i (ref_seq = seq-1 view)
    arena_off = initial_len
    for i in range(n_ops):
        u = type_u[i]
        if u < t_ins or length == 0:
            n = int(len_draw[i])
            op_type[i] = OP_INSERT
            pos1[i] = int(pos_u[i] * (length + 1))
            buf_start[i] = arena_off
            ins_len[i] = n
            arena_off += n
            length += n
        else:
            start = int(pos_u[i] * length)
            end = min(length, start + int(range_draw[i]))
            assert end > start  # pos_u < 1.0 and range_draw >= 1
            if u < t_rem:
                op_type[i] = OP_REMOVE
                length -= end - start
            else:
                op_type[i] = OP_ANNOTATE
                prop_key[i] = keys[i]
                prop_val[i] = vals[i]
            pos1[i] = start
            pos2[i] = end

    seq = np.arange(1, n_ops + 1, dtype=np.int32)
    initial_text = rng.integers(ord("a"), ord("z") + 1, initial_len).astype(np.int32)
    text = np.concatenate([initial_text, codepoints[: arena_off - initial_len]])
    return ColumnarStream(
        op_type=op_type,
        pos1=pos1,
        pos2=pos2,
        seq=seq,
        ref_seq=seq - 1,
        client=(np.arange(n_ops, dtype=np.int32) % n_clients) + 1,
        buf_start=buf_start,
        ins_len=ins_len,
        prop_key=prop_key,
        prop_val=prop_val,
        min_seq=np.maximum(0, seq - window).astype(np.int32),
        text=text,
    )


def generate_lagged_stream(
    n_ops: int,
    n_clients: int = 1024,
    seed: int = 0,
    window: int = 1024,
    lag_zero_frac: float = 0.35,
    insert_weight: float = 0.55,
    remove_weight: float = 0.25,
    annotate_weight: float = 0.20,
    max_insert_len: int = 8,
    max_range_len: int = 16,
    n_prop_keys: int = 8,
    n_prop_vals: int = 16,
    initial_len: int = 64,
    cache_dir: str | None = None,
) -> ColumnarStream:
    """Generate `n_ops` mixed ops whose refSeqs lag the head.

    Per op: `lag_zero_frac` of ops are caught up (``ref_seq = seq-1``,
    the well-synced client); the rest draw a lag uniform in
    ``[1, window-1]``, clamped so ``ref_seq >= MSN`` (deli nacks staler
    refSeqs, deli/lambda.ts:967) and per-client non-decreasing (a
    client cannot unsee ops). Positions are valid *in the emitting
    client's view*: the visible length at ``(ref_seq, client)`` is
    queried from the native C++ engine — which includes the client's
    own earlier ops and excludes concurrent ops it has not seen — so a
    replay engine resolving these ops performs genuine lagging-
    perspective work (insert tie-breaks against concurrent inserts,
    tombstone skips for unseen removes; mergeTree.ts:1740 insertingWalk
    at a non-head perspective).

    The generation-time engine replay makes this ~10x slower than
    `generate_stream`; pass `cache_dir` to memoize the arrays on disk
    keyed by all parameters.
    """
    import ctypes

    params = (
        n_ops, n_clients, seed, window, round(lag_zero_frac, 6),
        round(insert_weight, 6), round(remove_weight, 6),
        round(annotate_weight, 6), max_insert_len, max_range_len,
        n_prop_keys, n_prop_vals, initial_len,
    )
    cache_path = None
    if cache_dir:
        import hashlib
        import os

        key = hashlib.sha256(repr(params).encode()).hexdigest()[:16]
        cache_path = os.path.join(cache_dir, f"lagged_{key}.npz")
        if os.path.exists(cache_path):
            z = np.load(cache_path)
            return ColumnarStream(**{k: z[k] for k in z.files})

    from ..native import load_hostmerge
    from ..protocol.constants import NO_CLIENT

    lib = load_hostmerge()
    if lib is None:
        raise RuntimeError(
            "generate_lagged_stream needs the native hostmerge engine "
            "(no C++ compiler available)"
        )

    rng = np.random.default_rng(seed)
    type_u = rng.random(n_ops)
    pos_u = rng.random(n_ops)
    lag_u = rng.random(n_ops)
    lag_draw = rng.integers(1, max(window - 1, 1) + 1, n_ops)
    len_draw = rng.integers(1, max_insert_len + 1, n_ops).astype(np.int64)
    range_draw = rng.integers(1, max_range_len + 1, n_ops).astype(np.int64)
    keys = rng.integers(0, n_prop_keys, n_ops).astype(np.int32)
    vals = rng.integers(0, n_prop_vals, n_ops).astype(np.int32)
    arena = np.ascontiguousarray(
        rng.integers(
            ord("a"), ord("z") + 1, initial_len + int(np.sum(len_draw))
        ).astype(np.int32)
    )

    w_total = insert_weight + remove_weight + annotate_weight
    t_ins = insert_weight / w_total
    t_rem = t_ins + remove_weight / w_total

    op_type = np.empty(n_ops, np.int32)
    pos1 = np.empty(n_ops, np.int32)
    pos2 = np.zeros(n_ops, np.int32)
    ref_seq = np.empty(n_ops, np.int32)
    buf_start = np.zeros(n_ops, np.int32)
    ins_len = np.zeros(n_ops, np.int32)
    prop_key = np.full(n_ops, NO_KEY, np.int32)
    prop_val = np.zeros(n_ops, np.int32)
    last_ref = np.zeros(n_clients + 1, np.int32)

    # The generator's view oracle: a passive native replica with an
    # identity no stream client uses, so every op takes the remote
    # path (hostmerge.cpp vis()).
    h = ctypes.c_void_p(lib.hm_new(NO_CLIENT))
    try:
        ip = ctypes.POINTER(ctypes.c_int32)
        arena_p = arena.ctypes.data_as(ctypes.c_void_p).value
        isz = ctypes.sizeof(ctypes.c_int32)
        lib.hm_load(h, arena.ctypes.data_as(ip), initial_len)
        arena_off = initial_len
        hm_insert = lib.hm_insert
        hm_remove = lib.hm_remove
        hm_vislen = lib.hm_visible_length
        for i in range(n_ops):
            seq = i + 1
            c = (i % n_clients) + 1
            msn = seq - window
            if msn < 0:
                msn = 0
            if lag_u[i] < lag_zero_frac:
                r = seq - 1
            else:
                r = seq - 1 - int(lag_draw[i])
            if r < msn:
                r = msn
            lr = last_ref[c]
            if r < lr:
                r = int(lr)
            last_ref[c] = r
            ref_seq[i] = r
            L = hm_vislen(h, r, c)
            u = type_u[i]
            if u < t_ins or L == 0:
                n = int(len_draw[i])
                op_type[i] = OP_INSERT
                p = int(pos_u[i] * (L + 1))
                pos1[i] = p
                buf_start[i] = arena_off
                ins_len[i] = n
                rc = hm_insert(
                    h, p,
                    ctypes.cast(arena_p + arena_off * isz, ip),
                    n, r, c, seq, None, None, 0,
                )
                arena_off += n
            else:
                start = int(pos_u[i] * L)
                end = min(L, start + int(range_draw[i]))
                pos1[i] = start
                pos2[i] = end
                if u < t_rem:
                    op_type[i] = OP_REMOVE
                    rc = hm_remove(h, start, end, r, c, seq)
                else:
                    # Annotate never changes visible lengths; the view
                    # oracle can skip it.
                    op_type[i] = OP_ANNOTATE
                    prop_key[i] = keys[i]
                    prop_val[i] = vals[i]
                    rc = 0
            if rc != 0:
                raise AssertionError(
                    f"generator emitted invalid op at seq {seq}"
                )
            if (i & 255) == 255:
                lib.hm_set_current_seq(h, seq)
                lib.hm_update_min_seq(h, msn)
                # Passive replica: merge adjacent settled segments so
                # the per-op view walk stays O(collab window), not
                # O(total inserts) (zamboni.ts:19 packParent role).
                lib.hm_pack_settled(h)
    finally:
        lib.hm_free(h)

    seqs = np.arange(1, n_ops + 1, dtype=np.int32)
    stream = ColumnarStream(
        op_type=op_type,
        pos1=pos1,
        pos2=pos2,
        seq=seqs,
        ref_seq=ref_seq,
        client=(np.arange(n_ops, dtype=np.int32) % n_clients) + 1,
        buf_start=buf_start,
        ins_len=ins_len,
        prop_key=prop_key,
        prop_val=prop_val,
        min_seq=np.maximum(0, seqs - window).astype(np.int32),
        text=arena[:arena_off],
    )
    if cache_path:
        import os

        os.makedirs(cache_dir, exist_ok=True)
        tmp = f"{cache_path}.{os.getpid()}.tmp.npz"
        np.savez(
            tmp,
            **{
                f: getattr(stream, f)
                for f in stream.__dataclass_fields__
            },
        )
        os.replace(tmp, cache_path)
    return stream
