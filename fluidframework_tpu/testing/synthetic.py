"""Synthetic totally-ordered op streams in columnar form.

The replay benchmarks (BASELINE.md configs 1-2: mixed SharedString
insert/remove/annotate from many clients) need op streams far larger
than the Python-object message path can cheaply materialize. This
module generates streams directly in the columnar layout the kernel
consumes (see `fluidframework_tpu.ops.mergetree_kernel.OpBatch`),
mirroring how the reference's replay tool pre-parses recorded op files
before the timed replay (packages/tools/replay-tool/src/replayMessages.ts).

Every generated op is *valid*: positions are within the visible length
at the op's perspective. Ops use ``ref_seq = seq - 1`` (each client has
seen the whole prefix when it submits), so the visible length is
exactly the document length tracked by the generator. Concurrency
semantics (tie-breaks at lagging refSeqs) are exercised by the farm
streams in `fluidframework_tpu.testing.farm`, which remain the
correctness gate; this generator is the throughput workload.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List

import numpy as np

from ..ops.mergetree_kernel import NO_KEY, OP_ANNOTATE, OP_INSERT, OP_REMOVE
from ..protocol.messages import MessageType, SequencedMessage
from ..protocol.mergetree_ops import AnnotateOp, InsertOp, RemoveOp


@dataclass
class ColumnarStream:
    """A sequenced op stream as parallel numpy arrays (one row per op)."""

    op_type: np.ndarray  # int32[N]
    pos1: np.ndarray  # int32[N]
    pos2: np.ndarray  # int32[N]
    seq: np.ndarray  # int32[N]
    ref_seq: np.ndarray  # int32[N]
    client: np.ndarray  # int32[N]
    buf_start: np.ndarray  # int32[N] (offset into `text`)
    ins_len: np.ndarray  # int32[N]
    prop_key: np.ndarray  # int32[N] (NO_KEY when no annotation)
    prop_val: np.ndarray  # int32[N]
    min_seq: np.ndarray  # int32[N] MSN as of this op
    text: np.ndarray  # int32[S] codepoint arena for all inserted text

    def __len__(self) -> int:
        return len(self.op_type)

    # ---------------------------------------------------------- messages

    def as_messages(self, limit: int | None = None) -> Iterator[SequencedMessage]:
        """Object-form view (for the scalar oracle / object-path replay)."""
        n = len(self) if limit is None else min(limit, len(self))
        for i in range(n):
            t = int(self.op_type[i])
            if t == OP_INSERT:
                lo = int(self.buf_start[i])
                text = "".join(
                    map(chr, self.text[lo : lo + int(self.ins_len[i])])
                )
                op = InsertOp(pos=int(self.pos1[i]), text=text)
            elif t == OP_REMOVE:
                op = RemoveOp(start=int(self.pos1[i]), end=int(self.pos2[i]))
            else:
                op = AnnotateOp(
                    start=int(self.pos1[i]),
                    end=int(self.pos2[i]),
                    props={f"k{int(self.prop_key[i])}": int(self.prop_val[i])},
                )
            yield SequencedMessage(
                sequence_number=int(self.seq[i]),
                minimum_sequence_number=int(self.min_seq[i]),
                client_id=int(self.client[i]),
                client_seq=0,
                ref_seq=int(self.ref_seq[i]),
                type=MessageType.OP,
                contents=op,
            )


def generate_stream(
    n_ops: int,
    n_clients: int = 1024,
    seed: int = 0,
    window: int = 1024,
    insert_weight: float = 0.55,
    remove_weight: float = 0.25,
    annotate_weight: float = 0.20,
    max_insert_len: int = 8,
    max_range_len: int = 16,
    n_prop_keys: int = 8,
    n_prop_vals: int = 16,
    initial_len: int = 64,
) -> ColumnarStream:
    """Generate `n_ops` mixed ops from `n_clients` round-robin clients.

    The MSN trails the head by `window` (the collaboration-window size
    deli would maintain for caught-up clients), so replay engines can
    compact tombstones exactly as they would in a live session.
    """
    rng = np.random.default_rng(seed)
    # Pre-draw all randomness (keeps the Python loop light).
    type_u = rng.random(n_ops)
    pos_u = rng.random(n_ops)
    len_draw = rng.integers(1, max_insert_len + 1, n_ops).astype(np.int64)
    range_draw = rng.integers(1, max_range_len + 1, n_ops).astype(np.int64)
    keys = rng.integers(0, n_prop_keys, n_ops).astype(np.int32)
    vals = rng.integers(0, n_prop_vals, n_ops).astype(np.int32)
    codepoints = rng.integers(ord("a"), ord("z") + 1, int(np.sum(len_draw))).astype(
        np.int32
    )

    w_total = insert_weight + remove_weight + annotate_weight
    t_ins = insert_weight / w_total
    t_rem = t_ins + remove_weight / w_total

    op_type = np.empty(n_ops, np.int32)
    pos1 = np.empty(n_ops, np.int32)
    pos2 = np.zeros(n_ops, np.int32)
    buf_start = np.zeros(n_ops, np.int32)
    ins_len = np.zeros(n_ops, np.int32)
    prop_key = np.full(n_ops, NO_KEY, np.int32)
    prop_val = np.zeros(n_ops, np.int32)

    length = initial_len  # visible length before op i (ref_seq = seq-1 view)
    arena_off = initial_len
    for i in range(n_ops):
        u = type_u[i]
        if u < t_ins or length == 0:
            n = int(len_draw[i])
            op_type[i] = OP_INSERT
            pos1[i] = int(pos_u[i] * (length + 1))
            buf_start[i] = arena_off
            ins_len[i] = n
            arena_off += n
            length += n
        else:
            start = int(pos_u[i] * length)
            end = min(length, start + int(range_draw[i]))
            assert end > start  # pos_u < 1.0 and range_draw >= 1
            if u < t_rem:
                op_type[i] = OP_REMOVE
                length -= end - start
            else:
                op_type[i] = OP_ANNOTATE
                prop_key[i] = keys[i]
                prop_val[i] = vals[i]
            pos1[i] = start
            pos2[i] = end

    seq = np.arange(1, n_ops + 1, dtype=np.int32)
    initial_text = rng.integers(ord("a"), ord("z") + 1, initial_len).astype(np.int32)
    text = np.concatenate([initial_text, codepoints[: arena_off - initial_len]])
    return ColumnarStream(
        op_type=op_type,
        pos1=pos1,
        pos2=pos2,
        seq=seq,
        ref_seq=seq - 1,
        client=(np.arange(n_ops, dtype=np.int32) % n_clients) + 1,
        buf_start=buf_start,
        ins_len=ins_len,
        prop_key=prop_key,
        prop_val=prop_val,
        min_seq=np.maximum(0, seq - window).astype(np.int32),
        text=text,
    )
