"""Chaos-engineering harness for the supervised ordering farm.

The convergence claim ("identical deterministic replay of one totally
ordered stream", PAPER.md) is only worth what it survives. This module
composes the supervised multi-process pipeline
(`server.supervisor.ServiceSupervisor`) with seeded fault injection and
asserts the farm converges **bit-identical to the no-fault GOLDEN
digest with zero duplicate and zero skipped sequence numbers**.

Fault classes (all seeded — a failing run reproduces from its seed):

- ``kill``   — SIGKILL of each lambda role at randomized-but-seeded
  points in the stream; the supervisor restarts it and exactly-once
  recovery (fenced checkpoint + inOff output scan) must hold.
- ``torn``   — partial, newline-less junk appended to the shared
  topics under the append lock (a writer dying mid-write); consumers
  must neither crash nor mis-parse, and the next append seals the
  remnant.
- ``lease``  — expired-lease takeover: the sequencer is SIGSTOPped
  past its TTL, a usurper acquires its lease and binds the next fence,
  and the deposed owner's post-takeover writes (and a forged
  stale-fence write) are **demonstrably rejected** with `FencedError`.
- ``net``    — duplicated + delayed delivery on the broadcast edge: a
  flaky consumer re-delivers past records and defers others; the
  client-side gap/dedup guard (drop `seq <= last`, ranged refetch
  across a gap) must reconstruct the exact stream.
- ``client`` — client disconnect mid-batch: the feeder loses its ack
  and re-appends whole submission batches (at-least-once ingress);
  deli's resubmission dedup must keep the total order identical.

Elastic-fabric fault classes (``n_partitions > 1`` with the
hash-range topology, `server.shard_fabric` elastic mode — a topology
change is just another fault the fenced-handoff machinery must
survive):

- ``split``  — a live range split mid-run (mid-boxcar when
  boxcar_rate > 0): the owner writes its final fenced checkpoint,
  commits the next topology epoch, and the children absorb its tail
  exactly-once; the PRE-SPLIT owner's append with its old fence must
  be **demonstrably rejected** with `FencedError`.
- ``merge``  — the inverse: two adjacent ranges merge live; the
  survivor restores both parents' checkpoints and closes both gaps.
- ``disk``   — storage failure: ENOSPC injected on the workers'
  topic/checkpoint writes (plus an artificially stalled fsync
  episode); roles must degrade gracefully — bounded-retry jittered
  backoff, a ``degraded`` flag visible in worker heartbeats and
  `ShardFabricSupervisor.health()` — and recover with no lost
  acknowledged record once the fault clears.

The GOLDEN digest is produced by running the SAME production role code
(`DeliRole.process` / `ScribeRole.process`) in-process with no faults —
not a parallel reimplementation — so golden and chaotic runs can only
differ if a fault actually corrupted the pipeline.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import signal
import tempfile
import time
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Tuple

from ..server.columnar_log import make_tail_reader, make_topic
from ..server.queue import (
    FencedCheckpointStore,
    FencedError,
    LeaseManager,
    SharedFileTopic,
)
from ..server.supervisor import (
    PIPELINE_ROLES,
    DeliRole,
    ScribeRole,
    ServiceSupervisor,
    canonical_record,
)

FAULT_CLASSES = ("kill", "torn", "lease", "net", "client")
# Fault classes of the ELASTIC fabric only (hash-range topology):
ELASTIC_FAULTS = ("split", "merge", "disk")
ALL_FAULT_CLASSES = FAULT_CLASSES + ELASTIC_FAULTS
# Traffic-profile scenarios (`ChaosConfig.scenario`): the workload is
# reshaped so a SKEWED burst is in flight while the faults land —
# "hotdoc" weaves a contiguous storm block (one viral doc, a swarm of
# extra writers) into the middle of the stream and the seeded
# kill/split points are clamped INTO that window, so convergence
# proves the fenced-handoff machinery under the one load shape even
# benches never offer it (testing.scenarios has the open-loop,
# latency-measured twins; this is the fault-injection twin).
SCENARIO_PROFILES = ("hotdoc",)


@dataclass
class ChaosConfig:
    seed: int = 0
    faults: Tuple[str, ...] = FAULT_CLASSES
    n_docs: int = 2
    n_clients: int = 3
    ops_per_client: int = 40
    ttl_s: float = 0.5
    heartbeat_timeout_s: float = 3.0
    batch: int = 16
    kills_per_role: int = 1
    timeout_s: float = 120.0
    shared_dir: Optional[str] = None
    # Sequencer implementation under test: "scalar" or "kernel" (the
    # batched deli, server.deli_kernel). Golden always comes from the
    # scalar production path, so a kernel run converging proves the
    # batched pipeline bit-identical under faults.
    deli_impl: str = "scalar"
    # Topic wire form under test: "json" (JSONL lines) or "columnar"
    # (binary record-batch frames, server.columnar_log). Golden always
    # folds in-process, so a columnar run converging proves the binary
    # op-log bit-identical under the same faults.
    log_format: str = "json"
    # Fraction of interleave picks that ride a wire BOXCAR record
    # (several of one client's ops in one ingress record, sequenced
    # atomically — the ROADMAP (d) schema rev). 0 keeps the historical
    # per-op stream.
    boxcar_rate: float = 0.0
    # Sharded ordering fabric (server.shard_fabric): >1 runs the run
    # against `n_workers` lease-balanced shard workers over
    # `n_partitions` partition topic pairs instead of the classic
    # four-role farm. Faults then target WORKERS (kill) and PARTITION
    # leases (lease); "net" is rejected (no socket consumer to
    # dup/delay in the fabric runner); convergence still compares the
    # merged sequenced stream against the same single-partition
    # in-proc golden.
    n_partitions: int = 1
    n_workers: int = 2
    # Multi-device deli (kernel impl only): shard the kernel deli's
    # [D, C] doc-slot pool across N devices (forced virtual host
    # devices in the child processes — the CPU-CI emulation of an
    # N-chip slice). Golden still folds single-device in-proc, so a
    # converging run proves the SHARDED kernel bit-identical to the
    # single-device stream under the same faults.
    deli_devices: Optional[int] = None
    # 2-D device plane (parallel.device_plane, kernel impl only): ONE
    # docs x model mesh serving the kernel deli (its docs-axis slice)
    # AND the summarizer folds (the whole pool) — the children run
    # under docs*model forced virtual host devices. Golden still folds
    # single-device in-proc, so a converging run proves the
    # plane-sliced pipeline bit-identical under the same faults.
    device_plane: Optional[str] = None
    # Summarizer merge-tree fold engine ("kernel" | "overlay"): the
    # overlay-pallas backend runs through the INTERPRETER in the farm
    # children (FLUID_FOLD_INTERPRET=1 — the CPU-CI correctness mode),
    # and the summary-integrity gate then proves its blobs/handles
    # bit-identical to the kernel fold's and to cold scalar replay.
    fold_backend: Optional[str] = None
    # Elastic hash-range topology (server.shard_fabric elastic mode):
    # partitions are range leases that can split/merge LIVE. Implied
    # by the split/merge/disk fault classes; may be set explicitly to
    # run the classic fault set against the elastic fabric.
    elastic: bool = False
    # Wire tracing (supervisor.TRACE_WIRE_ENV) in the farm children:
    # per-stage timestamps ride a side "tr" key on the wire records
    # and the broadcaster feeds the slow-op flight recorder, so a
    # chaos report can attach the exact slowest ops it saw. Safe for
    # convergence — digests compare `canonical_record`, which never
    # sees "tr".
    trace_wire: bool = False
    # Summary service (`server.summarizer.SummarizerRole`): run the
    # summarizer as a fifth supervised role, include it in the kill
    # schedule, and gate the run on SUMMARY INTEGRITY too — every
    # (doc, seq) manifest emitted exactly once with one handle
    # (restarts never fork a summary), and the newest summary + op
    # tail booting bit-identical to a cold full-log replay
    # (`summarizer.state_digest`). Classic single-partition farm only.
    summarizer: bool = False
    summary_ops: int = 32
    # Retention plane (`server.retention.RetentionRole`): run the
    # SIXTH supervised role — summary-driven fenced op-log truncation
    # + castore GC — include it in the kill schedule, fire the SEEDED
    # kill-during-truncate and kill-during-GC fault points (the role
    # SIGKILLs itself between its fenced commit record and the
    # physical reclaim / mid-sweep; recovery must roll the cut forward
    # with zero dup/skip), and gate the run on RETENTION INTEGRITY:
    # at least one committed truncation actually reclaimed the deltas
    # prefix, both seeded kill points fired, and summary + tail still
    # boots bit-identical to a cold replay (read off the untruncated
    # durable topic). Requires summarizer=True and the columnar log
    # format (JSONL has no truncation header); classic farm only.
    retention: bool = False
    # Fused durable+broadcast hop (`supervisor.
    # ScriptoriumBroadcasterRole`): the scriptorium+broadcaster pair
    # collapses into ONE supervised consumer (durable leg fsynced,
    # broadcast leg unfsynced-but-recoverable). Kill faults then
    # target the fused role; convergence still reads the same durable
    # + broadcast topics, so a converging run proves the fused hop
    # bit-identical to the split pair under the same faults. Classic
    # single-partition farm only (the fabric has no downstream pair).
    fused_hop: bool = False
    # Supervised admission front door (`server.ingress.IngressRole`,
    # sharded runner only): the workload feeds the `ingress` topic
    # with signed tenant tokens instead of the router, the front door
    # joins the kill schedule, `bad_submits` seeded invalid records
    # (tampered token / oversized contents / unknown tenant) ride the
    # stream and must each be NACKED exactly once and NEVER sequenced,
    # and throttle-nacked valid submits are retried by the feeder
    # until admitted (the retry-and-converge client contract).
    ingress: bool = False
    bad_submits: int = 6
    # Overload episode knobs (ingress runs): per-tenant rate limit
    # (ops/s; 0 = off) and per-partition backlog budget (records;
    # 0 = off) exported to the ingress child via FLUID_INGRESS_*.
    ingress_rate: float = 0.0
    ingress_backlog: int = 0
    # Load-driven autoscaling (`shard_fabric.AutoscalePolicy`, implies
    # elastic): the fabric supervisor watches per-partition throughput
    # and stages policy-driven splits/merges itself; convergence then
    # ALSO requires the topology epoch to have actually moved — a
    # LOAD-driven split fired mid-stream and the stream stayed
    # bit-identical.
    autoscale: bool = False
    # Per-partition downstream stages (`ShardWorker(downstream=)`):
    # "fused" | "split". Convergence then ALSO requires the merged
    # durable legs to carry exactly the sequenced ops (bit-identical
    # digest, zero dup/skip) — a split mid-stream hands each range's
    # downstream legs over exactly-once.
    downstream: Optional[str] = None
    # Traffic-profile scenario (`SCENARIO_PROFILES`): "hotdoc" weaves
    # a contiguous viral-doc storm block (a swarm of extra writers on
    # docs[0]) into the middle of the workload and clamps the seeded
    # kill/split points INTO the storm window — the faults land while
    # the storm is in flight, and convergence must still be
    # bit-identical with zero dup/skip.
    scenario: Optional[str] = None


@dataclass
class ChaosResult:
    converged: bool
    digest: str
    golden_digest: str
    client_digest: Optional[str]
    scribe_ok: bool
    duplicate_seqs: int
    skipped_seqs: int
    fence_rejections: int
    restarts: Dict[str, int]
    events: List[str] = field(default_factory=list)
    detail: str = ""
    # Fault/recovery timeline: (unix_ts, event) across harness faults
    # and supervisor actions, time-ordered (chaos_run renders it).
    timeline: List[Tuple[float, str]] = field(default_factory=list)
    # Merged utils.metrics snapshot from every role's final heartbeat
    # (per-stage pump sizes, checkpoint bytes/durations, fence
    # rejections...) — `utils.metrics.format_report([metrics])` prints.
    metrics: Dict[str, Any] = field(default_factory=dict)
    # Disk-fault evidence: the degraded flag (worker heartbeat →
    # health()) was observed while the ENOSPC episode ran.
    degraded_seen: bool = False
    # Topology evidence: epochs observed committed during the run
    # (split/merge faults must actually move it).
    epochs: List[int] = field(default_factory=list)
    # Slow-op flight-recorder spans (trace_wire runs only): the exact
    # ops whose submit→broadcast latency crossed the rolling p99,
    # slowest first, with all stage timestamps — a tail-latency
    # regression report carries its evidence.
    slow_ops: List[dict] = field(default_factory=list)
    # Summary-service evidence (summarizer runs only): manifests seen,
    # and whether the integrity gate held — no (doc, seq) fork or
    # duplicate, and summary + tail boot == cold full replay.
    summaries_ok: bool = True
    summary_manifests: int = 0
    # Front-door evidence (ingress runs): nacks by reason, whether
    # every seeded bad submit was nacked-never-sequenced, and how many
    # throttle-nacked submits the feeder had to retry.
    ingress_nacks: Dict[str, int] = field(default_factory=dict)
    never_sequenced_ok: bool = True
    throttle_retries: int = 0
    # Autoscale evidence: policy-staged commands during the run.
    autoscale_actions: int = 0
    # Downstream evidence (downstream runs): the merged durable legs
    # matched the sequenced stream bit-identically.
    downstream_ok: bool = True
    # Retention evidence (retention runs): committed truncations
    # observed, the deltas base they advanced to, blobs the GC swept,
    # and whether the integrity gate held (commits rolled forward,
    # seeded kill points fired, summary+tail == cold durable replay).
    retention_ok: bool = True
    truncations: int = 0
    retention_base_records: int = 0
    gc_deleted: int = 0


# ---------------------------------------------------------------------------
# workload + golden
# ---------------------------------------------------------------------------


def build_workload(cfg: ChaosConfig) -> List[dict]:
    """Deterministic ingress stream: per-doc joins, then a seeded
    interleaving of each client's in-order op queue (per-client order
    preserved — deli enforces clientSeq contiguity)."""
    rng = random.Random(cfg.seed)
    if cfg.n_partitions > 1:
        # Partition-balanced doc names: small doc counts clump under
        # the consistent hash, and a one-partition "sharded" run would
        # prove nothing about cross-partition convergence.
        from ..server.shard_fabric import spread_doc_names

        docs = spread_doc_names(cfg.n_docs, cfg.n_partitions)
    else:
        docs = [f"doc{d}" for d in range(cfg.n_docs)]
    recs: List[dict] = []
    queues: Dict[Tuple[str, int], List[dict]] = {}
    for doc in docs:
        for c in range(1, cfg.n_clients + 1):
            recs.append({"kind": "join", "doc": doc, "client": c})
            queues[(doc, c)] = [
                {
                    "kind": "op", "doc": doc, "client": c,
                    "clientSeq": i + 1, "refSeq": 0,
                    # With a summarizer FOLD BACKEND under test the
                    # contents must decode as merge-tree wire ops or
                    # the engine under test never runs (generic docs
                    # take the "ops"-blob path). Prepend-inserts are
                    # valid at EVERY perspective (position 0 always
                    # exists), so the raw records stay valid however
                    # the deli interleaves and stamps them; the
                    # golden/scribe machinery treats contents
                    # opaquely either way.
                    "contents": (
                        {"type": 0, "pos1": 0,
                         "seg": f"{c}.{i};"}
                        if cfg.fold_backend is not None
                        else {"v": rng.randint(0, 999), "i": i}
                    ),
                }
                for i in range(cfg.ops_per_client)
            ]
    recs.extend(_interleave(rng, queues, cfg.boxcar_rate))
    if cfg.scenario == "hotdoc":
        # The storm block: a swarm of EXTRA writers (clients
        # n_clients+1 .. n_clients+S, well below the bad-submit id
        # space at 9000) piling onto docs[0], spliced in as one
        # contiguous run in the middle of the stream — a viral doc
        # going viral mid-run, while the background mix continues
        # around it. The runners detect the storm chunks by client id
        # and land their kill/split faults inside the window.
        block = _storm_block(cfg, rng, docs[0])
        mid = len(recs) // 3
        recs = recs[:mid] + block + recs[mid:]
    return recs


def _interleave(rng: random.Random,
                queues: Dict[Tuple[str, int], List[dict]],
                boxcar_rate: float = 0.0) -> List[dict]:
    """The seeded cross-client interleave both the base workload and
    the scenario storm block drain through: pick a live (doc, client)
    queue at random and pop its head — or wrap 2-4 of its ops into a
    wire boxcar at `boxcar_rate` — preserving per-client order. ONE
    helper, so the storm block can never silently diverge from the
    base workload's interleave shape."""
    recs: List[dict] = []
    keys = list(queues)
    while keys:
        k = rng.choice(keys)
        q = queues[k]
        if boxcar_rate and len(q) >= 2 and rng.random() < boxcar_rate:
            n = min(len(q), rng.randint(2, 4))
            ops = [q.pop(0) for _ in range(n)]
            recs.append({
                "kind": "boxcar", "doc": k[0], "client": k[1],
                "ops": [
                    {"clientSeq": o["clientSeq"], "refSeq": o["refSeq"],
                     "contents": o["contents"]}
                    for o in ops
                ],
            })
        else:
            recs.append(q.pop(0))
        if not q:
            keys.remove(k)
    return recs


def _storm_block(cfg: ChaosConfig, rng: random.Random,
                 hot_doc: str) -> List[dict]:
    """The hotdoc scenario's contiguous burst: `4 * n_clients` (min 6)
    storm writers join `hot_doc` and interleave their op queues — the
    same seeded-interleave shape as the base workload, concentrated on
    one document."""
    n_storm = max(6, 4 * cfg.n_clients)
    ops_each = max(2, cfg.ops_per_client // 2)
    clients = [cfg.n_clients + 1 + i for i in range(n_storm)]
    recs: List[dict] = [
        {"kind": "join", "doc": hot_doc, "client": c} for c in clients
    ]
    recs.extend(_interleave(rng, {
        (hot_doc, c): [
            {"kind": "op", "doc": hot_doc, "client": c,
             "clientSeq": i + 1, "refSeq": 0,
             "contents": {"storm": c, "i": i}}
            for i in range(ops_each)
        ]
        for c in clients
    }))
    return recs


def golden_stream(workload: List[dict], scratch_dir: str) -> List[dict]:
    """The no-fault sequenced stream, produced by the PRODUCTION deli
    code path run in-process (not a reimplementation)."""
    role = DeliRole(scratch_dir, owner="golden", ttl_s=3600.0)
    out: List[dict] = []
    for i, rec in enumerate(workload):
        role.process(i, rec, out)
    return [canonical_record(r) for r in out]


def golden_scribe_digests(stream: List[dict],
                          scratch_dir: str) -> Dict[str, str]:
    """Per-doc rolling digests from the PRODUCTION scribe fold."""
    role = ScribeRole(scratch_dir, owner="golden-scribe", ttl_s=3600.0)
    for i, rec in enumerate(stream):
        role.process(i, rec, [])
    return {d: st["digest"] for d, st in role.docs.items()}


def client_stream_digest(records: List[dict]) -> str:
    """SHA-256 over every (doc, client)'s seq-ordered op sequence —
    clientSeq, type and contents, but NOT the seq/msn assignment.
    The convergence form for OVERLOAD runs: throttle-nacked clients
    retry, which legitimately reorders the cross-client admission
    interleaving (and therefore the seq numbering) relative to the
    no-throttle golden — but every client's own stream must still
    land exactly once, in order, bit-identical in content. Used with
    `sequence_integrity` (zero dup/skip), this pins everything the
    front door is allowed to leave undetermined."""
    per: Dict[Tuple[str, Any], List[Tuple[int, list]]] = {}
    for r in records:
        rec = canonical_record(r)
        per.setdefault((rec["doc"], rec.get("client")), []).append(
            (int(rec.get("seq", 0)),
             [rec.get("clientSeq"), rec.get("type"),
              rec.get("contents")])
        )
    form = {
        f"{doc}\x00{client}": [v for _s, v in sorted(entries)]
        for (doc, client), entries in per.items()
    }
    payload = json.dumps(form, sort_keys=True, ensure_ascii=True)
    return hashlib.sha256(payload.encode()).hexdigest()


def stream_digest(records: List[dict]) -> str:
    """SHA-256 over the per-doc, seq-sorted canonical stream — the
    bit-identity form two runs are compared in."""
    per_doc: Dict[str, List[dict]] = {}
    for r in records:
        per_doc.setdefault(r["doc"], []).append(canonical_record(r))
    for v in per_doc.values():
        v.sort(key=lambda r: r["seq"])
    payload = json.dumps(per_doc, sort_keys=True, ensure_ascii=True)
    return hashlib.sha256(payload.encode()).hexdigest()


def sequence_integrity(records: List[dict]) -> Tuple[int, int]:
    """(duplicate_seqs, skipped_seqs) across all docs: every doc's
    sequence numbers must be exactly 1..N."""
    dups = skips = 0
    per_doc: Dict[str, List[int]] = {}
    for r in records:
        per_doc.setdefault(r["doc"], []).append(int(r["seq"]))
    for seqs in per_doc.values():
        dups += len(seqs) - len(set(seqs))
        uniq = sorted(set(seqs))
        # Seqs start at 1: a complete stream is exactly 1..max.
        skips += (uniq[-1] - len(uniq)) if uniq else 0
    return dups, skips


# ---------------------------------------------------------------------------
# fault injection pieces
# ---------------------------------------------------------------------------

TORN_FRAGMENT = b'{"torn": tru'  # can never parse; no trailing newline


def inject_torn_append(path: str) -> None:
    """Simulate a writer dying mid-append: raw partial line, no
    newline, written under the same append lock real writers use."""
    import fcntl

    with open(path, "ab") as f:
        fcntl.flock(f.fileno(), fcntl.LOCK_EX)
        try:
            f.write(TORN_FRAGMENT)
            f.flush()
        finally:
            fcntl.flock(f.fileno(), fcntl.LOCK_UN)


def consume_with_net_faults(topic: SharedFileTopic, rng: random.Random,
                            dup_rate: float = 0.1,
                            delay_rate: float = 0.1) -> List[dict]:
    """A flaky delivery edge over the broadcast feed: re-delivers past
    records (duplication) and defers others (delay → a visible gap at
    delivery time). The client applies the same guard the socket
    driver uses: drop ``seq <= last``, and close a gap with a ranged
    refetch from the feed (the ops_from(from, to) role)."""
    entries, _ = topic.read_entries(0)
    feed = [r for _, r in entries
            if isinstance(r, dict) and r.get("kind") == "op"]
    delivery: List[dict] = []
    deferred: List[Tuple[int, dict]] = []
    for i, rec in enumerate(feed):
        # Release any deferred record whose time has come.
        while deferred and deferred[0][0] <= i:
            delivery.append(deferred.pop(0)[1])
        r = rng.random()
        if r < delay_rate:
            deferred.append((i + rng.randint(2, 6), rec))
            continue
        delivery.append(rec)
        if r < delay_rate + dup_rate and delivery:
            delivery.append(rng.choice(delivery))  # re-delivery
    delivery.extend(rec for _, rec in deferred)

    by_key = {(r["doc"], int(r["seq"])): r for r in feed}
    view: Dict[str, List[dict]] = {}
    last: Dict[str, int] = {}
    for rec in delivery:
        doc, seq = rec["doc"], int(rec["seq"])
        cur = last.get(doc, 0)
        if seq <= cur:
            continue  # duplicate delivery
        if seq > cur + 1:
            # Gap: ranged refetch [cur+1, seq-1] from the feed (the
            # driver's ops_from(from_seq, to_seq) catch-up).
            for missing in range(cur + 1, seq):
                hit = by_key.get((doc, missing))
                if hit is not None:
                    view.setdefault(doc, []).append(hit)
            last[doc] = seq - 1
        view.setdefault(doc, []).append(rec)
        last[doc] = seq
    return [r for doc in sorted(view) for r in view[doc]]


# ---------------------------------------------------------------------------
# the run
# ---------------------------------------------------------------------------


def run_chaos(cfg: ChaosConfig) -> ChaosResult:
    """Run the chaos suite. With no `cfg.shared_dir`, a throwaway temp
    dir is used and removed on convergence (kept for post-mortem on
    divergence, named in `detail`); pass `shared_dir` to keep it."""
    if cfg.n_partitions > 1 and "net" in cfg.faults:
        # The sharded runner reads the merged partition topics directly
        # — there is no socket consumer to dup/delay, so accepting
        # "net" would print a convergence verdict for a fault that was
        # never exercised. Reject loudly instead of lying.
        raise ValueError(
            "fault class 'net' is not supported with n_partitions > 1 "
            "(no socket consumer in the fabric runner); drop it from "
            "faults or run single-partition"
        )
    if cfg.deli_devices is not None and cfg.deli_devices > 1 \
            and cfg.deli_impl != "kernel":
        # Loud, before any scratch state exists: a scalar farm has no
        # device axis, and silently running it would print a sharded
        # convergence verdict that exercised nothing.
        raise ValueError(
            f"deli_devices={cfg.deli_devices} needs deli_impl='kernel'"
            f"; got {cfg.deli_impl!r}"
        )
    if cfg.device_plane is not None:
        if cfg.deli_impl != "kernel":
            raise ValueError(
                f"device_plane={cfg.device_plane!r} needs "
                f"deli_impl='kernel'; got {cfg.deli_impl!r}"
            )
        if cfg.deli_devices is not None and cfg.deli_devices > 1:
            raise ValueError(
                "deli_devices and device_plane are exclusive (the "
                "plane's docs axis IS the deli's device slice)"
            )
        from ..parallel.device_plane import parse_plane_spec

        parse_plane_spec(cfg.device_plane)  # loud on a bad spec
    if cfg.fold_backend is not None:
        if cfg.fold_backend not in ("kernel", "overlay"):
            raise ValueError(
                f"fold_backend {cfg.fold_backend!r} not in "
                f"('kernel', 'overlay')"
            )
        if not cfg.summarizer:
            raise ValueError(
                "fold_backend is a summarizer knob: set "
                "summarizer=True (nothing else folds merge-trees)"
            )
    unknown = set(cfg.faults) - set(ALL_FAULT_CLASSES)
    if unknown:
        raise ValueError(f"unknown fault classes {sorted(unknown)}")
    if cfg.scenario is not None and cfg.scenario not in SCENARIO_PROFILES:
        raise ValueError(
            f"unknown scenario {cfg.scenario!r}; profiles: "
            f"{SCENARIO_PROFILES}"
        )
    if cfg.scenario and cfg.summarizer:
        # The summarizer gate's deterministic manifest-count formula
        # assumes the uniform per-doc record count; a storm block
        # breaks it. Reject loudly rather than print a summary verdict
        # computed against the wrong expectation.
        raise ValueError(
            "scenario workloads do not run with summarizer=True "
            "(the manifest-count gate assumes the uniform workload)"
        )
    if cfg.fused_hop and cfg.n_partitions > 1:
        # The fabric's workers run deli pipelines only — there is no
        # scriptorium/broadcaster pair to fuse, and accepting the flag
        # would print a fused-hop verdict nothing exercised.
        raise ValueError(
            "fused_hop=True runs on the classic single-partition farm "
            "(the sharded fabric has no downstream stage pair)"
        )
    if cfg.retention:
        # Retention truncates only SUMMARY-covered prefixes, and only
        # the columnar log has a truncation header; on the fabric the
        # retention role is a follow-up. Each a loud config error —
        # a run that silently skipped the plane would still print a
        # retention verdict.
        if not cfg.summarizer:
            raise ValueError(
                "retention=True needs summarizer=True (truncation "
                "only reclaims summary-covered records)"
            )
        if cfg.log_format != "columnar":
            raise ValueError(
                "retention=True needs log_format='columnar' (JSONL "
                "topics have no truncation header)"
            )
        if cfg.n_partitions > 1:
            raise ValueError(
                "retention=True runs on the classic single-partition "
                "farm (fabric retention: ROADMAP follow-up)"
            )
    if cfg.summarizer and cfg.n_partitions > 1:
        # The per-partition summarizer rides ShardWorker(summarize=)
        # on the STATIC fabric; the chaos gate for it is a follow-up —
        # accepting the flag here would print a summary-integrity
        # verdict the sharded runner never checked.
        raise ValueError(
            "summarizer=True runs on the classic single-partition "
            "farm (sharded summary gate: ROADMAP follow-up)"
        )
    if cfg.n_partitions <= 1 and (cfg.ingress or cfg.autoscale
                                  or cfg.downstream):
        # The front-door / autoscale / downstream axes all live on the
        # sharded fabric runner; accepting them single-partition would
        # print verdicts for machinery that never ran.
        raise ValueError(
            "ingress/autoscale/downstream need n_partitions > 1 "
            "(the sharded fabric runner)"
        )
    if cfg.autoscale and not (cfg.elastic or any(
            f in ELASTIC_FAULTS for f in cfg.faults)):
        cfg = replace(cfg, elastic=True)  # the policy splits ranges
    if cfg.downstream == "fused" and (cfg.elastic or cfg.autoscale):
        raise ValueError(
            "downstream='fused' is static-partition only "
            "(use 'split' with the elastic fabric)"
        )
    elastic_wanted = [f for f in cfg.faults if f in ELASTIC_FAULTS]
    if elastic_wanted and cfg.n_partitions <= 1:
        # split/merge/disk target the sharded fabric's workers and
        # topology; accepting them single-partition would print a
        # convergence verdict for a fault that never ran.
        raise ValueError(
            f"fault classes {elastic_wanted} need n_partitions > 1 "
            f"(the elastic sharded fabric)"
        )
    if elastic_wanted:
        cfg = replace(cfg, elastic=True)
    shared = cfg.shared_dir or tempfile.mkdtemp(prefix="chaos-")
    runner = _run_chaos_sharded if cfg.n_partitions > 1 else _run_chaos_in
    res = runner(cfg, shared)
    if cfg.shared_dir is None:
        if res.converged:
            import shutil

            shutil.rmtree(shared, ignore_errors=True)
        else:
            res.detail += f" [state kept for post-mortem: {shared}]"
    return res


def _feed_plan(cfg: ChaosConfig, rng: random.Random,
               workload: List[dict], kill_targets: Tuple[str, ...]):
    """The seeded feed/fault plan BOTH runners share (classic farm and
    sharded fabric — only the kill targets differ: role names vs
    worker slots). Returns ``(chunks, dup_after, kill_at, torn_at,
    lease_at)``:

    - `chunks`: seeded submission batches of the workload;
    - `dup_after` (`client` fault): chunk idx → later idx at which the
      chunk is re-appended in full (a client that lost its ack
      mid-batch resubmits everything — at-least-once ingress);
    - `kill_at` (`kill` fault): chunk idx → targets SIGKILLed there,
      each target `cfg.kills_per_role` times;
    - `torn_at` (`torn` fault): chunk indices for torn appends;
    - `lease_at` (`lease` fault): the takeover chunk index, or None."""
    chunks: List[List[dict]] = []
    i = 0
    while i < len(workload):
        n = rng.randint(1, 12)
        chunks.append(workload[i:i + n])
        i += n
    dup_after: Dict[int, int] = {}
    if "client" in cfg.faults:
        for idx in rng.sample(
            range(len(chunks)), max(1, len(chunks) // 10)
        ):
            dup_after[idx] = idx + rng.randint(1, 5)
    kill_at: Dict[int, List[str]] = {}
    if "kill" in cfg.faults:
        for target in kill_targets:
            for _ in range(cfg.kills_per_role):
                idx = rng.randint(len(chunks) // 5,
                                  max(1, len(chunks) - 2))
                kill_at.setdefault(idx, []).append(target)
    torn_at = (
        sorted(rng.sample(range(len(chunks)), min(3, len(chunks))))
        if "torn" in cfg.faults else []
    )
    lease_at = (
        rng.randint(len(chunks) // 3, max(1, 2 * len(chunks) // 3))
        if "lease" in cfg.faults else None
    )
    return chunks, dup_after, kill_at, torn_at, lease_at


def _trace_env() -> Dict[str, str]:
    """Child env for trace-wire chaos runs: wire stamps on, and the
    flight recorder pinned to a FIXED threshold (default 0 — keep
    every span, ring-bounded) so a short seeded run's /traces
    evidence does not depend on the rolling-p99 gate having armed.
    An operator's explicit FLUID_TRACE_SLOW_MS wins."""
    return {
        "FLUID_TRACE_WIRE": "1",
        "FLUID_TRACE_SLOW_MS": os.environ.get(
            "FLUID_TRACE_SLOW_MS", "0"
        ),
    }


def _storm_chunk_indices(cfg: ChaosConfig,
                         chunks: List[List[dict]]) -> List[int]:
    """Chunk indices carrying scenario-storm records (storm writers
    live in the client-id band between the base workload's clients and
    the bad-submit base at 9000)."""
    if not cfg.scenario:
        return []
    return [
        i for i, ch in enumerate(chunks)
        if any(isinstance(r, dict) and isinstance(r.get("client"), int)
               and cfg.n_clients < r["client"] < 9000 for r in ch)
    ]


def _clamp_faults_into_storm(cfg: ChaosConfig, rng: random.Random,
                             storm_idx: List[int],
                             kill_at: Dict[int, List[str]],
                             split_at: Optional[int],
                             ) -> Tuple[Dict[int, List[str]],
                                        Optional[int]]:
    """Scenario runs land their kill/split faults INSIDE the storm
    window (seeded picks over the storm chunks): 'a storm fires
    during a split/kill' is the whole point — faults scheduled after
    the burst drained would prove nothing about it."""
    if not storm_idx:
        return kill_at, split_at
    if kill_at:
        remapped: Dict[int, List[str]] = {}
        for targets in kill_at.values():
            for t in targets:
                remapped.setdefault(rng.choice(storm_idx), []).append(t)
        kill_at = remapped
    if split_at is not None:
        split_at = storm_idx[len(storm_idx) // 3]
    return kill_at, split_at


def _run_chaos_in(cfg: ChaosConfig, shared: str) -> ChaosResult:
    rng = random.Random(cfg.seed ^ 0x5EED)
    workload = build_workload(cfg)
    golden = golden_stream(workload, os.path.join(shared, "golden"))
    gdigest = stream_digest(golden)
    gscribe = golden_scribe_digests(golden, os.path.join(shared, "golden"))
    expected = len(golden)

    roles = PIPELINE_ROLES
    if cfg.fused_hop:
        from ..server.supervisor import FUSED_PIPELINE_ROLES

        roles = FUSED_PIPELINE_ROLES
    kill_targets = list(roles)
    if cfg.summarizer:
        # Fifth role: the summary service, killed like any other —
        # restarts must re-emit byte-identical manifests, never fork.
        kill_targets.append("summarizer")
        roles = tuple(roles) + ("summarizer",)
    if cfg.retention:
        # Sixth role: the retention plane — SIGKILLed like any other,
        # PLUS the seeded kill-during-truncate / kill-during-GC points
        # below (the role kills itself between its fenced commit and
        # the physical reclaim; recovery must roll the cut forward).
        kill_targets.append("retention")
    chunks, dup_after, kill_at, torn_at, lease_at = _feed_plan(
        cfg, rng, workload, tuple(kill_targets),
    )
    storm_idx = _storm_chunk_indices(cfg, chunks)
    kill_at, _ = _clamp_faults_into_storm(cfg, rng, storm_idx,
                                          kill_at, None)

    ret_fault = os.path.join(shared, "retention-fault.json")
    child_env: Dict[str, str] = dict(
        _trace_env() if cfg.trace_wire else {}
    )
    if cfg.retention:
        from ..server.retention import RETENTION_FAULT_ENV

        child_env[RETENTION_FAULT_ENV] = ret_fault
    hb_timeout = cfg.heartbeat_timeout_s
    if cfg.fold_backend == "overlay":
        # CPU-CI correctness mode: the overlay-pallas fold runs
        # through the interpreter in the summarizer child, so the
        # overlay path is actually EXERCISED (not silently fallen
        # back from) and the summary-integrity gate below proves its
        # blobs bit-identical.
        from ..server.summarizer import FOLD_INTERPRET_ENV

        child_env.setdefault(FOLD_INTERPRET_ENV, "1")
        # The interpreter's first fold compiles for tens of seconds
        # INSIDE flush_batch — a silent child, not a wedged one. A
        # 3s staleness bar would SIGKILL every summarizer mid-compile
        # forever (the restart pays the same compile); chaos kills
        # are still detected instantly via process exit, so widening
        # the WEDGE bar costs the run nothing it is testing.
        hb_timeout = max(hb_timeout, 120.0)
    sup = ServiceSupervisor(
        shared, roles=roles, ttl_s=cfg.ttl_s,
        heartbeat_timeout_s=hb_timeout, batch=cfg.batch,
        deli_impl=cfg.deli_impl, log_format=cfg.log_format,
        deli_devices=cfg.deli_devices,
        device_plane=cfg.device_plane,
        fold_backend=cfg.fold_backend,
        child_env=child_env or None,
        summary_ops=cfg.summary_ops if cfg.summarizer else None,
        fused_hop=cfg.fused_hop,
        retention=cfg.retention,
        retention_env={
            # Aggressive knobs so a short seeded run actually reclaims:
            # every covered frame qualifies, a tiny tail is spared,
            # and GC's grace is one beat.
            "FLUID_RETENTION_INTERVAL": "0.2",
            "FLUID_RETENTION_MIN_BYTES": "1",
            "FLUID_RETENTION_KEEP_TAIL": "4",
            "FLUID_RETENTION_GRACE": "0.5",
        } if cfg.retention else None,
    ).start()
    raw = make_topic(os.path.join(shared, "topics", "rawdeltas.jsonl"),
                     cfg.log_format)
    deltas_path = os.path.join(shared, "topics", "deltas.jsonl")
    durable = make_topic(os.path.join(shared, "topics", "durable.jsonl"),
                         cfg.log_format)
    broadcast = make_topic(
        os.path.join(shared, "topics", "broadcast.jsonl"), cfg.log_format
    )
    summaries = make_topic(
        os.path.join(shared, "topics", "summaries.jsonl"), cfg.log_format
    )
    # Deterministic manifest count: each doc's record count is fixed
    # by the workload (dup resubmissions dedup silently), so the
    # summarizer MUST emit exactly one manifest per cadence multiple
    # past the engine-decision point (the doc's first op, at count
    # n_clients + 1; earlier multiples — all-join prefixes — are
    # deterministically skipped) — however many times it was killed.
    per_doc = cfg.n_clients * (1 + cfg.ops_per_client)
    expected_manifests = (
        cfg.n_docs * (per_doc // cfg.summary_ops
                      - cfg.n_clients // cfg.summary_ops)
        if cfg.summarizer else 0
    )
    fence_rejections = 0
    events: List[str] = []
    timeline: List[Tuple[float, str]] = []

    def note(ev: str) -> None:
        events.append(ev)
        timeline.append((time.time(), ev))

    # Seeded retention kill points: armed one at a time from 1/3 of
    # the feed on — the NEXT time the role reaches the named point it
    # consumes the spec and SIGKILLs itself. Sequential (gc armed only
    # after truncate fired), so both points demonstrably fire.
    ret_points = ["truncate", "gc"] if cfg.retention else []
    ret_arm_at = max(1, len(chunks) // 3) if cfg.retention else None

    def pump_retention_faults() -> None:
        if not ret_points or os.path.exists(ret_fault):
            return
        point = ret_points.pop(0)
        tmp = ret_fault + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"point": point}, f)
        os.replace(tmp, ret_fault)
        note(f"chaos: retention kill armed at {point!r}")

    def retention_done() -> bool:
        if not cfg.retention:
            return True
        if ret_points or os.path.exists(ret_fault):
            return False
        deltas_t = make_topic(deltas_path, cfg.log_format)
        return deltas_t.base_offsets()[0] > 0

    try:
        if storm_idx:
            note(f"chaos: scenario {cfg.scenario!r} storm spans "
                 f"chunks {storm_idx[0]}..{storm_idx[-1]} "
                 f"(faults clamped inside)")
        fed_idx = 0
        pending_dups: Dict[int, List[dict]] = {}
        deadline = time.time() + cfg.timeout_s
        while time.time() < deadline:
            sup.poll_once()
            if ret_arm_at is not None and fed_idx >= ret_arm_at:
                pump_retention_faults()
            if fed_idx < len(chunks):
                if cfg.trace_wire:
                    # Stamp the submit instant at FEED time (the
                    # workload list stays pristine for the golden):
                    # the broadcaster then measures submit→broadcast
                    # e2e and feeds the slow-op recorder. Digest-safe:
                    # canonical_record never sees tr_sub.
                    now = time.time()
                    chunk = [{**r, "tr_sub": now}
                             for r in chunks[fed_idx]]
                else:
                    chunk = chunks[fed_idx]
                raw.append_many(chunk)
                if fed_idx in dup_after:
                    pending_dups.setdefault(
                        dup_after[fed_idx], []
                    ).extend(chunks[fed_idx])
                for rec in pending_dups.pop(fed_idx, []):
                    raw.append(rec)  # the lost-ack resubmission
                for role in kill_at.pop(fed_idx, []):
                    proc = sup.procs.get(role)
                    if proc is not None and proc.poll() is None:
                        proc.kill()
                        note(f"chaos: SIGKILL {role}")
                if torn_at and torn_at[0] == fed_idx:
                    torn_at.pop(0)
                    inject_torn_append(raw.path)
                    inject_torn_append(deltas_path)
                    note("chaos: torn append")
                if lease_at == fed_idx:
                    fence_rejections += _lease_takeover(
                        shared, sup, cfg, note
                    )
                fed_idx += 1
            # Drain any resubmissions scheduled past the last chunk.
            if fed_idx >= len(chunks) and pending_dups:
                for idx in sorted(pending_dups):
                    for rec in pending_dups.pop(idx, []):
                        raw.append(rec)
            ops = [r for r in durable.read_from(0)
                   if isinstance(r, dict) and r.get("kind") == "op"]
            bops = [r for r in broadcast.read_from(0)
                    if isinstance(r, dict) and r.get("kind") == "op"]
            if (fed_idx >= len(chunks) and not pending_dups
                    and len(ops) >= expected and len(bops) >= expected):
                if cfg.summarizer and sum(
                    1 for r in summaries.read_from(0)
                    if isinstance(r, dict) and r.get("kind") == "summary"
                ) < expected_manifests:
                    time.sleep(0.02)
                    continue  # the summary service must finish too
                if not retention_done():
                    time.sleep(0.02)
                    continue  # both kill points + a real reclaim first
                scr = FencedCheckpointStore(
                    os.path.join(shared, "checkpoints")
                ).load("scribe")
                total = sum(
                    int(st["count"]) for st in
                    ((scr or {}).get("state", {}).get("state", {}) or {})
                    .values()
                )
                if total >= expected:
                    break
            time.sleep(0.02)
    finally:
        sup.stop()

    ops = [r for r in durable.read_from(0)
           if isinstance(r, dict) and r.get("kind") == "op"]
    digest = stream_digest(ops)
    dups, skips = sequence_integrity(ops)
    client_digest = None
    if "net" in cfg.faults:
        client_view = consume_with_net_faults(
            broadcast, random.Random(cfg.seed ^ 0xDE1)
        )
        client_digest = stream_digest(client_view)
    scr = FencedCheckpointStore(
        os.path.join(shared, "checkpoints")
    ).load("scribe")
    live_scribe = {
        d: st["digest"] for d, st in
        ((scr or {}).get("state", {}).get("state", {}) or {}).items()
    }
    scribe_ok = live_scribe == gscribe
    # Summary-service integrity (summarizer runs): every (doc, seq)
    # manifest exactly once with exactly one handle (a kill between
    # blob put / manifest append / checkpoint must re-emit the SAME
    # summary, never fork or duplicate it), the deterministic cadence
    # count reached, and the newest summary + tail booting
    # bit-identical to a cold full-log replay.
    summaries_ok = True
    n_manifests = 0
    if cfg.summarizer:
        from ..server.summarizer import (
            SummaryReplica,
            open_summary_store,
            read_catchup,
        )

        mans = [r for r in summaries.read_from(0)
                if isinstance(r, dict) and r.get("kind") == "summary"]
        n_manifests = len(mans)
        by_key: Dict[Tuple[str, int], List[str]] = {}
        for m in mans:
            by_key.setdefault((m["doc"], m["seq"]), []).append(
                m["handle"]
            )
        summaries_ok = (
            n_manifests == expected_manifests
            and all(len(hs) == 1 for hs in by_key.values())
        )
        if summaries_ok and expected_manifests:
            # Cold-replay source: with retention ON the deltas prefix
            # is legitimately truncated, so the full stream comes off
            # the (untruncated) durable leg — same records, scriptorium
            # re-keyed, canonical fields intact.
            src_topic = durable if cfg.retention else make_topic(
                deltas_path, cfg.log_format
            )
            deltas_ops = [
                r for r in src_topic.read_from(0)
                if isinstance(r, dict) and r.get("kind") == "op"
            ]
            store = open_summary_store(shared)
            for doc in sorted({r["doc"] for r in deltas_ops}):
                cu = read_catchup(shared, doc, cfg.log_format,
                                  store=store)
                boot = SummaryReplica(cu["blob"])
                boot.apply_records(cu["ops"])
                cold = SummaryReplica(None)
                cold.apply_records(
                    [r for r in deltas_ops if r["doc"] == doc]
                )
                if boot.state_digest() != cold.state_digest():
                    summaries_ok = False
                    events.append(
                        f"summary+tail boot DIVERGED for {doc}"
                    )
                    break
    # Retention integrity (retention runs): >= 1 committed truncation,
    # every committed cut rolled forward (the topic base is at/past
    # the newest commit — the torn-truncate contract), and both seeded
    # kill points actually fired.
    retention_ok = True
    truncations = 0
    base_records = 0
    gc_deleted = 0
    if cfg.retention:
        rt = make_topic(
            os.path.join(shared, "topics", "retention.jsonl"),
            cfg.log_format,
        )
        commits = [r for r in rt.read_from(0) if isinstance(r, dict)]
        truncations = sum(1 for r in commits
                          if r.get("kind") == "truncate")
        gc_deleted = sum(int(r.get("deleted", 0)) for r in commits
                         if r.get("kind") == "gc")
        newest_cut = max(
            (int(r.get("records", 0)) for r in commits
             if r.get("kind") == "truncate"
             and r.get("topic") == "deltas"), default=0,
        )
        deltas_t = make_topic(deltas_path, cfg.log_format)
        base_records = deltas_t.base_offsets()[0]
        if newest_cut > base_records:
            # The final sup.stop() can SIGKILL retention INSIDE the
            # commit-then-reclaim window (commit durable, bytes not
            # yet reclaimed) — legal torn state whose contract is
            # recovery roll-forward, but no successor runs after
            # stop. Roll it forward here (idempotent, same as
            # `_recover_inner`): the gate then verifies the committed
            # cut actually applies instead of flaking on the window.
            try:
                deltas_t.truncate_prefix(newest_cut)
            except Exception as exc:  # noqa: BLE001 - gate evidence
                events.append(f"retention roll-forward failed: {exc}")
            base_records = deltas_t.base_offsets()[0]
        points_fired = not ret_points and not os.path.exists(ret_fault)
        retention_ok = (truncations > 0 and newest_cut > 0
                        and base_records >= newest_cut and points_fired)
        if not retention_ok:
            events.append(
                f"retention integrity FAILED: truncations={truncations}"
                f" newest_cut={newest_cut} base={base_records} "
                f"points_fired={points_fired}"
            )
    converged = (
        digest == gdigest and dups == 0 and skips == 0 and scribe_ok
        and summaries_ok and retention_ok
        and (client_digest in (None, gdigest))
        and ("lease" not in cfg.faults or fence_rejections > 0)
    )
    detail = (
        f"ops={len(ops)}/{expected} restarts={sup.restarts} "
        + (f"manifests={n_manifests}/{expected_manifests} "
           f"summaries_ok={summaries_ok} " if cfg.summarizer else "")
        + (f"truncations={truncations} base={base_records} "
           f"gc_deleted={gc_deleted} retention_ok={retention_ok} "
           if cfg.retention else "")
        + f"events={events + sup.events}"
    )
    # Observability artifacts: merge every role's final
    # heartbeat-reported metrics snapshot (the same channel the
    # supervisor's /metrics scrape uses) and time-sort the fault +
    # supervisor timeline. With a kept shared_dir, the per-role
    # snapshots also land in <dir>/metrics.jsonl for
    # tools/metrics_report.py.
    from ..utils.metrics import dump_snapshot_line, merge_snapshots

    role_snaps = sup.child_metrics()
    metrics = merge_snapshots(role_snaps.values()).snapshot()
    if cfg.shared_dir is not None:
        mpath = os.path.join(shared, "metrics.jsonl")
        for role, snap in role_snaps.items():
            dump_snapshot_line(mpath, snap, source=f"chaos-{role}")
    return ChaosResult(
        converged=converged, digest=digest, golden_digest=gdigest,
        client_digest=client_digest, scribe_ok=scribe_ok,
        duplicate_seqs=dups, skipped_seqs=skips,
        fence_rejections=fence_rejections, restarts=dict(sup.restarts),
        events=events + list(sup.events), detail=detail,
        timeline=sorted(timeline + sup.timeline), metrics=metrics,
        slow_ops=sup.child_slow_ops() if cfg.trace_wire else [],
        summaries_ok=summaries_ok, summary_manifests=n_manifests,
        retention_ok=retention_ok, truncations=truncations,
        retention_base_records=base_records, gc_deleted=gc_deleted,
    )


def _run_chaos_sharded(cfg: ChaosConfig, shared: str) -> ChaosResult:
    """The sharded-fabric twin of `_run_chaos_in`: the same seeded
    workload and in-proc single-partition golden, fed through the
    `ShardRouter` into `cfg.n_partitions` partition topic pairs served
    by `cfg.n_workers` supervised lease-balanced shard workers
    (`server.shard_fabric`). Faults target the fabric's own failure
    axes — SIGKILL of a worker mid-stream (its partitions' leases
    expire and peers/restarts take them over), torn appends on
    partition topics, and an expired-lease PARTITION takeover whose
    deposed owner is demonstrably fence-rejected. Convergence: the
    merged sequenced stream across every ``deltas-p{k}`` must be
    bit-identical to the golden with zero duplicate/skipped seqs —
    a rebalance mid-boxcar must be invisible in the order."""
    from ..server.queue import DISK_FAULT_ENV
    from ..server.shard_fabric import (
        AutoscalePolicy,
        ShardFabricSupervisor,
        ShardRouter,
    )

    rng = random.Random(cfg.seed ^ 0x5EED)
    workload = build_workload(cfg)
    golden = golden_stream(workload, os.path.join(shared, "golden"))
    gdigest = stream_digest(golden)
    expected = len(golden)

    kill_targets = [f"shard-w{w}" for w in range(cfg.n_workers)]
    if cfg.ingress:
        # The front door is supervised like everything else: kill it
        # mid-stream and its exactly-once recovery must neither drop
        # an admitted submit nor duplicate a nack.
        kill_targets.append("ingress")
    chunks, dup_after, kill_at, torn_at, lease_at = _feed_plan(
        cfg, rng, workload, tuple(kill_targets),
    )

    # Front-door fixtures: one tenant key (auth turns ON the moment
    # the tenants file exists), SESSION auth records per (doc, client)
    # fed up front — the alfred connection-auth shape: the workload's
    # op records then ride BARE (credential-free, columnar-schema) and
    # inherit their session — and `bad_submits` seeded invalid records
    # that must be nacked-never-sequenced. Bad clients live at >= 9000
    # so "never sequenced" is one scan of the merged stream.
    BAD_CLIENT_BASE = 9000
    tokens: Dict[str, str] = {}
    bad_records: List[dict] = []
    auth_records: List[dict] = []
    if cfg.ingress:
        from ..server.ingress import write_tenants
        from ..server.riddler import sign_token

        tenant_key = f"chaos-key-{cfg.seed}"
        write_tenants(shared, {"t0": tenant_key})

        def token_for(doc: str) -> str:
            tok = tokens.get(doc)
            if tok is None:
                tok = tokens[doc] = sign_token(
                    tenant_key, "t0", doc, ["doc:write"],
                    lifetime_s=24 * 3600.0,
                )
            return tok

        seen_sessions = set()
        for r in workload:
            key = (r["doc"], r["client"])
            if key not in seen_sessions:
                seen_sessions.add(key)
                auth_records.append({
                    "kind": "auth", "doc": r["doc"],
                    "client": r["client"], "tenant": "t0",
                    "token": token_for(r["doc"]),
                })
        docs = sorted({r["doc"] for r in workload})
        for i in range(cfg.bad_submits):
            doc = docs[i % len(docs)]
            flavor = i % 3
            rec = {"kind": "op", "doc": doc,
                   "client": BAD_CLIENT_BASE + i, "clientSeq": 1,
                   "refSeq": 0, "contents": {"bad": i},
                   "tenant": "t0", "token": token_for(doc)}
            if flavor == 0:  # tampered signature
                rec["token"] = rec["token"][:-6] + "aaaaaa"
            elif flavor == 1:
                # Oversized contents behind a VALID session (the cap
                # set below must be what rejects it, not auth).
                auth_records.append({
                    "kind": "auth", "doc": doc,
                    "client": BAD_CLIENT_BASE + i, "tenant": "t0",
                    "token": token_for(doc),
                })
                rec = {"kind": "op", "doc": doc,
                       "client": BAD_CLIENT_BASE + i, "clientSeq": 1,
                       "refSeq": 0,
                       "contents": {"bad": i, "pad": "x" * 8192}}
            else:  # unknown tenant
                rec["tenant"] = "nobody"
            bad_records.append(rec)

    # Elastic fault schedule (seeded like everything else): the split
    # lands in the FIRST half of the stream — mid-run, with boxcars in
    # flight when boxcar_rate > 0 — the merge in the second half (so
    # it can merge the split's children), the ENOSPC episode between.
    # Bounds are clamped lo <= hi so a degenerate tiny workload (one
    # or two chunks) still schedules the fault instead of crashing
    # randint with an empty range.
    def pick(lo: int, hi: int) -> int:
        lo = max(0, lo)
        # min() with the final chunk: the fault must actually FIRE
        # (fed_idx never exceeds len(chunks) - 1).
        return min(len(chunks) - 1, rng.randint(lo, max(lo, hi)))

    split_at = (pick(max(1, len(chunks) // 4), len(chunks) // 2)
                if "split" in cfg.faults else None)
    merge_at = (pick(2 * len(chunks) // 3, len(chunks) - 2)
                if "merge" in cfg.faults else None)
    disk_at = (pick(len(chunks) // 3, 2 * len(chunks) // 3)
               if "disk" in cfg.faults else None)
    stall_at = (min(len(chunks) - 1, disk_at + max(2, len(chunks) // 8))
                if disk_at is not None else None)
    storm_idx = _storm_chunk_indices(cfg, chunks)
    kill_at, split_at = _clamp_faults_into_storm(
        cfg, rng, storm_idx, kill_at, split_at,
    )

    # Children get the disk-fault spec path via their spawn env; the
    # harness's own appends (the router feed) stay clean.
    fault_spec = os.path.join(shared, "disk-fault.json")
    child_env = dict({DISK_FAULT_ENV: fault_spec}
                     if "disk" in cfg.faults else {})
    if cfg.trace_wire:
        child_env.update(_trace_env())
    if cfg.ingress:
        # Admission knobs for the front-door child: a contents cap the
        # seeded oversized submit violates, plus the overload episode's
        # rate/backlog budgets when the run asks for one.
        child_env["FLUID_INGRESS_MAX_BYTES"] = "4096"
        if cfg.ingress_rate:
            child_env["FLUID_INGRESS_RATE"] = str(cfg.ingress_rate)
        if cfg.ingress_backlog:
            child_env["FLUID_INGRESS_BACKLOG"] = str(cfg.ingress_backlog)
    child_env = child_env or None
    # Load-driven autoscaling: thresholds scaled for the harness's
    # small seeded workloads — the feed rate across a handful of
    # ranges must read as "hot" within a couple of lease TTLs, so a
    # POLICY-driven split demonstrably fires mid-stream.
    policy = AutoscalePolicy(
        split_rate=5.0, merge_rate=0.01,
        sustain_s=max(0.5, cfg.ttl_s),
        min_interval_s=max(2.0, 4 * cfg.ttl_s),
        max_ranges=cfg.n_partitions + 2,
    ) if cfg.autoscale else None
    sup = ShardFabricSupervisor(
        shared, n_workers=cfg.n_workers, n_partitions=cfg.n_partitions,
        ttl_s=cfg.ttl_s, heartbeat_timeout_s=cfg.heartbeat_timeout_s,
        batch=cfg.batch, deli_impl=cfg.deli_impl,
        log_format=cfg.log_format, deli_devices=cfg.deli_devices,
        device_plane=cfg.device_plane,
        elastic=cfg.elastic, child_env=child_env,
        ingress=cfg.ingress, downstream=cfg.downstream,
        autoscale=policy,
    ).start()
    router = ShardRouter(shared, cfg.n_partitions, cfg.log_format,
                         elastic=cfg.elastic)
    ing_topic = make_topic(
        os.path.join(shared, "topics", "ingress.jsonl"), cfg.log_format
    ) if cfg.ingress else None
    nacks_topic = make_topic(
        os.path.join(shared, "topics", "nacks.jsonl"), cfg.log_format
    ) if cfg.ingress else None

    def feed(records: List[dict]) -> None:
        """One ingress batch: through the front door when it is on
        (bare records — sessions carry the auth), straight through
        the router otherwise."""
        if ing_topic is not None:
            ing_topic.append_many(records)
        else:
            router.append(records)
    fence_rejections = 0
    degraded_seen = False
    epochs: List[int] = []
    events: List[str] = []
    timeline: List[Tuple[float, str]] = []

    def note(ev: str) -> None:
        events.append(ev)
        timeline.append((time.time(), ev))

    def note_epoch() -> None:
        topo = sup.topology()
        if topo is not None and topo["epoch"] not in epochs:
            epochs.append(topo["epoch"])

    def merged_ops() -> List[dict]:
        out: List[dict] = []
        for t in router.deltas_topics():
            out.extend(
                r for r in t.read_from(0)
                if isinstance(r, dict) and r.get("kind") == "op"
            )
        return out

    def merged_stage_ops(base: str) -> List[dict]:
        out: List[dict] = []
        for name in router.stage_topic_names(base):
            t = make_topic(
                os.path.join(shared, "topics", f"{name}.jsonl"),
                cfg.log_format,
            )
            out.extend(
                r for r in t.read_from(0)
                if isinstance(r, dict) and r.get("kind") == "op"
            )
        return out

    # Bad submits land at seeded chunk indices. Throttle-nacked VALID
    # submits follow the real client contract: a nack makes the client
    # resubmit its WHOLE remaining tail in order (per-client ascending
    # clientSeq — admission gates admit prefixes, so order survives
    # the retry; the deli's dedup silences every duplicate). Triggers
    # come from the ingress nacks topic (rate/backpressure) AND from
    # deli nacks in the sequenced stream (an out-of-order arrival a
    # gate flip let through), coalesced per client per pass.
    bad_at: Dict[int, List[dict]] = {}
    for rec in bad_records:
        bad_at.setdefault(rng.randint(0, max(0, len(chunks) - 2)),
                          []).append(rec)
    client_units: Dict[Tuple[str, int], List[Tuple[int, dict]]] = {}
    for rec in workload:
        ckey = (rec["doc"], rec["client"])
        if rec["kind"] == "op":
            cseq = rec["clientSeq"]
        elif rec["kind"] == "boxcar":
            cseq = rec["ops"][0]["clientSeq"]
        else:
            cseq = 0  # the join leads the client's unit stream
        client_units.setdefault(ckey, []).append((cseq, rec))
    for units in client_units.values():
        units.sort(key=lambda u: u[0])
    throttle_retries = 0
    nacks_cursor = 0
    deli_nack_readers: Dict[str, Any] = {}

    def resubmit_tails(tails: Dict[Tuple[str, int], int]) -> None:
        nonlocal throttle_retries
        batch: List[dict] = []
        for ckey, from_cseq in tails.items():
            batch.extend(rec for cseq, rec in client_units.get(ckey, ())
                         if cseq >= from_cseq)
        if batch:
            throttle_retries += len(batch)
            feed(batch)

    def retry_throttled() -> None:
        """One retry pass: gather NEW nack triggers, resubmit each
        affected client's tail once (from its lowest nacked seq)."""
        nonlocal nacks_cursor
        if nacks_topic is None:
            return
        tails: Dict[Tuple[str, int], int] = {}
        entries, _ = nacks_topic.read_entries(nacks_cursor)
        for i, r in entries:
            nacks_cursor = max(nacks_cursor, i + 1)
            if not (isinstance(r, dict) and r.get("kind") == "nack"):
                continue
            reason = (r.get("reason") or "")
            if not (reason.startswith("rate:")
                    or reason.startswith("backpressure:")):
                continue
            ckey = (r.get("doc"), r.get("client"))
            if ckey in client_units:
                cseq = int(r.get("clientSeq") or 0)
                tails[ckey] = min(tails.get(ckey, cseq), cseq)
        # Deli nacks (sequenced-stream rejections of out-of-order
        # arrivals): same tail resubmission, read INCREMENTALLY (a
        # from-zero re-read per 0.02s tick would be quadratic in
        # stream length). Only possible when an admission gate is
        # configured — a gate flip is the one thing that can reorder
        # a client's stream.
        if not (cfg.ingress_rate or cfg.ingress_backlog):
            resubmit_tails(tails)
            return
        for name in router.deltas_topic_names():
            reader = deli_nack_readers.get(name)
            if reader is None:
                reader = deli_nack_readers[name] = make_tail_reader(
                    make_topic(
                        os.path.join(shared, "topics",
                                     f"{name}.jsonl"),
                        cfg.log_format,
                    ), 0,
                )
            for _i, r in reader.poll():
                if isinstance(r, dict) and r.get("kind") == "nack":
                    ckey = (r.get("doc"), r.get("client"))
                    if ckey in client_units:
                        cseq = int(r.get("clientSeq") or 0)
                        tails[ckey] = min(tails.get(ckey, cseq), cseq)
        resubmit_tails(tails)

    try:
        note_epoch()
        if storm_idx:
            note(f"chaos: scenario {cfg.scenario!r} storm spans "
                 f"chunks {storm_idx[0]}..{storm_idx[-1]} "
                 f"(kill/split clamped inside)")
        if auth_records and ing_topic is not None:
            # Sessions open FIRST (clients connect before they
            # submit); an ingress kill replays them from the gap.
            ing_topic.append_many(auth_records)
        fed_idx = 0
        pending_dups: Dict[int, List[dict]] = {}
        deadline = time.time() + cfg.timeout_s
        # Autoscale runs pace the feed to ~2 chunks per lease TTL: the
        # policy needs two rate samples plus its sustain window to
        # fire, and the point is a LOAD-driven split landing MID-
        # stream — a burst-fed workload would drain before the loop
        # closes.
        feed_gap = cfg.ttl_s / 2 if cfg.autoscale else 0.0
        last_feed = 0.0
        while time.time() < deadline:
            sup.poll_once()
            retry_throttled()
            if cfg.autoscale:
                note_epoch()  # see the policy's epoch as it commits
            if fed_idx < len(chunks) and (
                    not feed_gap
                    or time.time() - last_feed >= feed_gap):
                last_feed = time.time()
                if cfg.trace_wire:
                    # Same feed-time submit stamp as the classic
                    # runner: the ranged delis stamp "tr" and observe
                    # per-partition submit_to_stamp quantiles into
                    # their worker heartbeats; with `downstream` the
                    # per-partition broadcaster stages feed the
                    # worker's flight recorder too, so sharded runs
                    # carry partition-tagged e2e spans (without a
                    # downstream stage there is no broadcast hop and
                    # the slow-op list stays empty).
                    now = time.time()
                    chunk = [{**r, "tr_sub": now}
                             for r in chunks[fed_idx]]
                else:
                    chunk = chunks[fed_idx]
                feed(chunk)
                for rec in bad_at.pop(fed_idx, []):
                    ing_topic.append_many([rec])  # pre-wrapped bad
                if fed_idx in dup_after:
                    pending_dups.setdefault(
                        dup_after[fed_idx], []
                    ).extend(chunks[fed_idx])
                for rec in pending_dups.pop(fed_idx, []):
                    feed([rec])  # the lost-ack resubmission
                for slot in kill_at.pop(fed_idx, []):
                    proc = sup.procs.get(slot)
                    if proc is not None and proc.poll() is None:
                        proc.kill()
                        note(f"chaos: SIGKILL {slot}")
                if torn_at and torn_at[0] == fed_idx:
                    torn_at.pop(0)
                    inject_torn_append(router.live_raw_topics()[0].path)
                    inject_torn_append(router.deltas_topics()[0].path)
                    if ing_topic is not None:
                        inject_torn_append(ing_topic.path)
                    note("chaos: torn append (p0)")
                if lease_at == fed_idx:
                    fence_rejections += _shard_lease_takeover(
                        shared, sup, cfg, note
                    )
                if split_at == fed_idx:
                    fence_rejections += _topology_split_fault(
                        shared, sup, cfg, note
                    )
                    note_epoch()
                if merge_at == fed_idx:
                    _topology_merge_fault(shared, sup, cfg, note)
                    note_epoch()
                if disk_at == fed_idx:
                    degraded_seen |= _disk_enospc_fault(
                        fault_spec, sup, cfg, note
                    )
                if stall_at == fed_idx and stall_at != disk_at:
                    _disk_stall_fault(fault_spec, cfg, note)
                fed_idx += 1
            if fed_idx >= len(chunks) and pending_dups:
                for idx in sorted(pending_dups):
                    for rec in pending_dups.pop(idx, []):
                        feed([rec])
            if (fed_idx >= len(chunks) and not pending_dups
                    and len(merged_ops()) >= expected
                    and (not cfg.autoscale or len(epochs) > 1)
                    and (not cfg.downstream
                         or (len(merged_stage_ops("durable"))
                             >= expected
                             and len(merged_stage_ops("broadcast"))
                             >= expected))):
                break
            time.sleep(0.02)
        note_epoch()
    finally:
        sup.stop()
        if os.path.exists(fault_spec):
            os.remove(fault_spec)

    ops = merged_ops()
    digest = stream_digest(ops)
    dups, skips = sequence_integrity(ops)
    # Front-door verdict: every seeded bad submit nacked EXACTLY once
    # (ingress exactly-once across its kill schedule), none of them
    # ever sequenced, and the nack taxonomy on the wire.
    ingress_nacks: Dict[str, int] = {}
    never_sequenced_ok = True
    ingress_ok = True
    if cfg.ingress:
        nk = [r for r in nacks_topic.read_from(0)
              if isinstance(r, dict) and r.get("kind") == "nack"]
        for r in nk:
            reason = (r.get("reason") or "?").split(":", 1)[0]
            ingress_nacks[reason] = ingress_nacks.get(reason, 0) + 1
        bad_nacks = [r for r in nk
                     if isinstance(r.get("client"), int)
                     and r["client"] >= BAD_CLIENT_BASE]
        never_sequenced_ok = not any(
            isinstance(op.get("client"), int)
            and op["client"] >= BAD_CLIENT_BASE for op in ops
        )
        ingress_ok = (len(bad_nacks) == len(bad_records)
                      and never_sequenced_ok)
    # Downstream verdict: the merged durable AND broadcast legs must
    # mirror the SEQUENCED stream exactly (bit-identical to the
    # merged deltas, zero dup/skip) — a mid-stream split handed each
    # range's legs over exactly-once or this digest forks.
    downstream_ok = True
    if cfg.downstream:
        for base in ("durable", "broadcast"):
            sops = merged_stage_ops(base)
            sdups, sskips = sequence_integrity(sops)
            if (stream_digest(sops) != digest or sdups or sskips):
                downstream_ok = False
                events.append(
                    f"downstream {base} leg DIVERGED "
                    f"({len(sops)}/{expected} dups={sdups} "
                    f"skips={sskips})"
                )
    autoscale_actions = (len(sup.autoscale.actions)
                         if sup.autoscale is not None else 0)
    # OVERLOAD runs converge in the order-free client-stream form:
    # throttle retries legitimately reorder the cross-client admission
    # interleaving (the seq assignment), so bit-identity holds per
    # client stream + zero dup/skip instead of per global interleave.
    overload_mode = bool(cfg.ingress_rate or cfg.ingress_backlog)
    order_ok = (
        client_stream_digest(ops) == client_stream_digest(golden)
        if overload_mode else digest == gdigest
    )
    converged = (
        order_ok and dups == 0 and skips == 0
        and len(ops) == expected
        and (("lease" not in cfg.faults and "split" not in cfg.faults)
             or fence_rejections > 0)
        and ("split" not in cfg.faults or len(epochs) > 1)
        and ("merge" not in cfg.faults or len(epochs) > 1)
        and ("disk" not in cfg.faults or degraded_seen)
        and ingress_ok and downstream_ok
        # A LOAD-driven topology change must actually have fired.
        and (not cfg.autoscale
             or (len(epochs) > 1 and autoscale_actions > 0))
    )
    detail = (
        f"ops={len(ops)}/{expected} partitions={cfg.n_partitions} "
        f"workers={cfg.n_workers} elastic={cfg.elastic} "
        f"epochs={epochs} degraded_seen={degraded_seen} "
        + (f"ingress_nacks={ingress_nacks} bad={len(bad_records)} "
           f"never_sequenced_ok={never_sequenced_ok} "
           f"throttle_retries={throttle_retries} "
           if cfg.ingress else "")
        + (f"autoscale_actions={autoscale_actions} "
           if cfg.autoscale else "")
        + (f"downstream_ok={downstream_ok} " if cfg.downstream else "")
        + f"restarts={sup.restarts} "
        f"owners={sup.partition_owners()} events={events + sup.events}"
    )
    from ..utils.metrics import dump_snapshot_line, merge_snapshots

    worker_snaps = sup.child_metrics()
    metrics = merge_snapshots(worker_snaps.values()).snapshot()
    if cfg.shared_dir is not None:
        mpath = os.path.join(shared, "metrics.jsonl")
        for slot, snap in worker_snaps.items():
            dump_snapshot_line(mpath, snap, source=f"chaos-{slot}")
    return ChaosResult(
        converged=converged, digest=digest, golden_digest=gdigest,
        client_digest=None, scribe_ok=True,
        duplicate_seqs=dups, skipped_seqs=skips,
        fence_rejections=fence_rejections, restarts=dict(sup.restarts),
        events=events + list(sup.events), detail=detail,
        timeline=sorted(timeline + sup.timeline), metrics=metrics,
        degraded_seen=degraded_seen, epochs=epochs,
        # With `downstream` stages the worker heartbeats carry
        # partition-tagged e2e spans (the per-partition broadcaster
        # feeds each worker's flight recorder); without them there is
        # no broadcast hop and the list is legitimately empty.
        slow_ops=sup.child_slow_ops() if cfg.trace_wire else [],
        ingress_nacks=ingress_nacks,
        never_sequenced_ok=never_sequenced_ok,
        throttle_retries=throttle_retries,
        autoscale_actions=autoscale_actions,
        downstream_ok=downstream_ok,
    )


def _topology_split_fault(shared: str, sup, cfg: ChaosConfig,
                          note) -> int:
    """The live-split fault: pick an OWNED range mid-run, capture its
    output topic's bound (fence, owner), stage the split command, wait
    for the owning worker to commit the next epoch, then PROVE the
    pre-split owner is deposed: its append with the old fence must
    raise `FencedError` once a child's higher fence binds. Returns
    demonstrated rejections."""
    from ..server.shard_fabric import range_lease_name

    topo = sup.topology()
    if topo is None:
        return 0
    target = None
    probe_deadline = time.time() + 24 * cfg.ttl_s
    while time.time() < probe_deadline and target is None:
        owners = sup.partition_owners()
        for e in sorted(topo["ranges"], key=lambda r: r["lo"]):
            if range_lease_name(e["rid"]) in owners:
                target = e
                break
        if target is None:
            sup.poll_once()
            time.sleep(cfg.ttl_s / 5)
    if target is None:
        note("chaos: split fault retired (no owned range)")
        return 0
    deltas = make_topic(
        os.path.join(shared, "topics", f"{target['deltas']}.jsonl"),
        cfg.log_format,
    )
    old_fence, old_owner = deltas.latest_fence()
    cmd = sup.request_split(rid=target["rid"])
    note(f"chaos: split requested on {target['rid']} (mid-run)")
    done_deadline = time.time() + 60 * cfg.ttl_s
    res = None
    while time.time() < done_deadline and res is None:
        sup.poll_once()
        res = sup.control_result(cmd)
        if res is None:
            time.sleep(cfg.ttl_s / 5)
    if res is None or res.get("error"):
        note(f"chaos: split did not complete ({res})")
        return 0
    note(f"chaos: split committed (epoch {res.get('epoch')})")
    rejections = 0
    if old_fence:
        # Wait for a child successor's higher fence to bind on the
        # parent's output topic, then replay the dead parent's write.
        bind_deadline = time.time() + 30 * cfg.ttl_s
        while time.time() < bind_deadline:
            cur, _ = deltas.latest_fence()
            if cur > old_fence:
                break
            sup.poll_once()
            time.sleep(cfg.ttl_s / 5)
        try:
            deltas.append_many(
                [{"kind": "op", "doc": "zombie", "seq": -1}],
                fence=old_fence, owner=old_owner,
            )
        except FencedError:
            rejections += 1
            note("chaos: PRE-SPLIT owner topic write REJECTED")
    return rejections


def _topology_merge_fault(shared: str, sup, cfg: ChaosConfig,
                          note) -> None:
    """The live-merge fault: merge two adjacent ranges mid-run —
    sibling children of an earlier split when present (the full
    round-trip), else the first adjacent pair."""
    topo = sup.topology()
    if topo is None or len(topo["ranges"]) < 2:
        note("chaos: merge fault retired (nothing to merge)")
        return
    ranges = sorted(topo["ranges"], key=lambda e: e["lo"])
    pair = None
    for a, b in zip(ranges, ranges[1:]):
        if a["preds"] and a["preds"] == b["preds"]:
            pair = (a, b)  # the split's children: the round-trip
            break
    if pair is None:
        pair = (ranges[0], ranges[1])
    cmd = sup.request_merge(pair[0]["rid"], pair[1]["rid"])
    note(f"chaos: merge requested {pair[0]['rid']}+{pair[1]['rid']}")
    done_deadline = time.time() + 60 * cfg.ttl_s
    res = None
    while time.time() < done_deadline and res is None:
        sup.poll_once()
        res = sup.control_result(cmd)
        if res is None:
            time.sleep(cfg.ttl_s / 5)
    note(f"chaos: merge result {res}")


def _disk_enospc_fault(fault_spec: str, sup, cfg: ChaosConfig,
                       note) -> bool:
    """The ENOSPC episode: children's durable writes (topic appends,
    checkpoints) start failing; the roles must enter bounded-retry
    backoff and flag themselves `degraded` — visible in `health()` —
    rather than corrupt or silently drop. The fault holds until the
    flag is OBSERVED (or a deadline passes), then clears; convergence
    after clearance proves no acknowledged record was lost. Returns
    whether degradation was observed."""
    tmp = fault_spec + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"mode": "enospc",
                   "kinds": ["topic", "checkpoint"]}, f)
    os.replace(tmp, fault_spec)
    note("chaos: ENOSPC injected on worker durable writes")
    degraded = False
    deadline = time.time() + 30 * cfg.ttl_s
    try:
        while time.time() < deadline:
            sup.poll_once()
            h = sup.health()
            if h.get("degraded_partitions"):
                degraded = True
                note(f"chaos: degraded visible in health(): "
                     f"{h['degraded_partitions']} "
                     f"(status={h['status']})")
                break
            time.sleep(cfg.ttl_s / 10)
    finally:
        os.remove(fault_spec)
        note("chaos: ENOSPC cleared")
    return degraded


def _disk_stall_fault(fault_spec: str, cfg: ChaosConfig, note) -> None:
    """The stalled-fsync episode: every durable write crawls for a
    beat. Liveness must hold (no restart storm — heartbeats continue
    between writes) and the order must not notice; the window is kept
    under the heartbeat timeout so a stall is degradation, not
    death."""
    stall_s = min(0.2, cfg.heartbeat_timeout_s / 8)
    tmp = fault_spec + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"mode": "stall", "stall_s": stall_s,
                   "kinds": ["topic", "checkpoint"]}, f)
    os.replace(tmp, fault_spec)
    note(f"chaos: fsync stall injected ({stall_s}s/write)")
    try:
        time.sleep(6 * stall_s)  # a few stalled writes land
    finally:
        os.remove(fault_spec)
        note("chaos: fsync stall cleared")


def _shard_lease_takeover(shared: str, sup, cfg: ChaosConfig,
                          note) -> int:
    """The fabric's expired-lease fault: SIGSTOP one shard worker past
    the lease TTL, usurp ONE of its partitions, bind the next fence on
    that partition's deltas topic + checkpoint, and prove the deposed
    owner's writes are REJECTED. The stopped worker's other partitions
    meanwhile expire and rebalance onto peers — the membership-change
    path under fault. Returns demonstrated fence rejections."""
    # A worker may transiently own nothing (mid-rebalance, just
    # restarted): poll for a live worker that demonstrably holds a
    # partition lease before staging the takeover. Generous window —
    # a deadline for a condition poll, not a sleep: under suite
    # contention a starved worker can take seconds to first sweep,
    # and an expired probe would retire the fault (rejections=0 fails
    # the run's lease gate).
    slot = proc = None
    victims: List[str] = []
    probe_deadline = time.time() + 24 * cfg.ttl_s
    while time.time() < probe_deadline and proc is None:
        owners = sup.partition_owners()
        for s in sup.roles:
            p = sup.procs.get(s)
            if p is None or p.poll() is not None:
                continue
            owner_id = f"{s}-g{sup.generation[s]}"
            victims = [name for name, o in owners.items()
                       if o == owner_id]
            if victims:
                slot, proc = s, p
                break
        if proc is None:
            sup.poll_once()
            time.sleep(cfg.ttl_s / 5)
    if proc is None or not victims:
        return 0
    # The lease name is "deli-<suffix>" in both topologies (p{k} or a
    # range id); the partition's output topic is "deltas-<suffix>".
    target = victims[0]
    deltas = make_topic(
        os.path.join(shared, "topics",
                     f"deltas-{target[len('deli-'):]}.jsonl"),
        cfg.log_format,
    )
    old_fence, old_owner = deltas.latest_fence()
    rejections = 0
    os.kill(proc.pid, signal.SIGSTOP)
    note(f"chaos: SIGSTOP {slot} (stale partition lease on {target})")
    zombie_alive = True

    def kill_zombie(why: str) -> None:
        nonlocal zombie_alive
        if not zombie_alive:
            return
        try:
            proc.kill()
            proc.wait(timeout=10)
        except OSError:
            pass
        zombie_alive = False
        note(f"chaos: zombie {slot} killed ({why})")

    try:
        usurper = LeaseManager(
            os.path.join(shared, "leases"), "chaos-usurper",
            ttl_s=cfg.ttl_s, claim_ttl_s=max(0.25, cfg.ttl_s / 2),
            # Elastic leases allocate from the fabric-wide counter;
            # the usurper must too, or its fence could tie a peer's.
            fence_scope="__fabric__" if cfg.elastic else None,
        )

        def acquire(deadline_s: float):
            deadline = time.time() + deadline_s
            while time.time() < deadline:
                f = usurper.try_acquire(target)
                if f is not None:
                    return f
                time.sleep(cfg.ttl_s / 5)
            return None

        fence = acquire(6 * cfg.ttl_s)
        if fence is None:
            kill_zombie("holding the lease claim")
            fence = acquire(6 * cfg.ttl_s)
        if fence is None:
            # Lost the takeover race: a live peer swept the expired
            # lease first (it polls its sweep as fast as we do). A
            # successor owner therefore EXISTS — the deposed owner's
            # rejection is still demonstrable once the successor's
            # higher fence is bound on the output topic.
            if not old_fence:
                return 0
            cur = 0
            bind_deadline = time.time() + 8 * cfg.ttl_s
            while time.time() < bind_deadline:
                cur, _ = deltas.latest_fence()
                if cur and cur > old_fence:
                    break
                time.sleep(cfg.ttl_s / 5)
            if not cur or cur <= old_fence:
                return 0
            note(f"chaos: takeover race lost to a live peer (fence "
                 f"{cur} bound); demonstrating deposed rejection")
            try:
                deltas.append_many(
                    [{"kind": "op", "doc": "zombie", "seq": -1}],
                    fence=old_fence, owner=old_owner,
                )
            except FencedError:
                rejections += 1
                note("chaos: deposed partition topic write REJECTED")
            return rejections
        note(f"chaos: usurper took {target} (fence {fence})")
        ckpt = FencedCheckpointStore(os.path.join(shared, "checkpoints"))
        env = ckpt.load(target)
        # The usurper can itself lose the partition mid-fault: blocking
        # on the zombie's write lock outlasts its own short lease, a
        # live worker retakes the partition with a higher fence, and
        # the usurper's bind is REJECTED — which demonstrates the very
        # write-path fencing this fault exists to prove, so count it
        # rather than crash the run.
        try:
            try:
                deltas.append_many([], fence=fence, owner="chaos-usurper",
                                   lock_timeout_s=2 * cfg.ttl_s)
                if env is not None:
                    ckpt.save(target, env["state"], fence=fence,
                              owner="chaos-usurper",
                              lock_timeout_s=2 * cfg.ttl_s)
            except TimeoutError:
                kill_zombie("holding a write lock")
                # Our lease may have expired while we were blocked;
                # refresh the fence before retrying the bind.
                refreshed = acquire(2 * cfg.ttl_s)
                if refreshed is not None:
                    fence = refreshed
                deltas.append_many([], fence=fence, owner="chaos-usurper")
                if env is not None:
                    ckpt.save(target, env["state"], fence=fence,
                              owner="chaos-usurper")
        except FencedError:
            rejections += 1
            note("chaos: usurper itself fence-REJECTED "
                 "(partition retaken mid-fault)")
        if old_fence:
            try:
                deltas.append_many(
                    [{"kind": "op", "doc": "zombie", "seq": -1}],
                    fence=old_fence, owner=old_owner,
                )
            except FencedError:
                rejections += 1
                note("chaos: deposed partition topic write REJECTED")
            if env is not None:
                try:
                    ckpt.save(target, env["state"], fence=old_fence,
                              owner=old_owner)
                except FencedError:
                    rejections += 1
                    note("chaos: deposed partition checkpoint REJECTED")
        usurper.release(target)
    finally:
        if zombie_alive:
            try:
                os.kill(proc.pid, signal.SIGCONT)
            except OSError:
                pass
            note(f"chaos: SIGCONT {slot}")
    return rejections


def _lease_takeover(shared: str, sup: ServiceSupervisor,
                    cfg: ChaosConfig, note) -> int:
    """The expired-lease fault: SIGSTOP the sequencer past its TTL, a
    usurper takes its lease and binds the next fence on the write
    paths, and the deposed owner's writes must be REJECTED. Returns
    the number of demonstrated fence rejections.

    The stopped zombie may be holding an append/checkpoint/claim flock
    at the moment it is stopped; the usurper therefore uses BOUNDED
    lock acquisition and, on timeout, has the zombie killed — exactly
    what the supervisor's stale-heartbeat detection does in production
    (kernel lock release on death then unblocks the successor)."""
    rejections = 0
    deli = sup.procs.get("deli")
    if deli is None or deli.poll() is not None:
        return 0
    deltas = make_topic(os.path.join(shared, "topics", "deltas.jsonl"),
                        cfg.log_format)
    old_fence, old_owner = deltas.latest_fence()
    os.kill(deli.pid, signal.SIGSTOP)
    note("chaos: SIGSTOP deli (stale lease)")
    zombie_alive = True

    def kill_zombie(why: str) -> None:
        nonlocal zombie_alive
        if not zombie_alive:
            return
        try:
            deli.kill()
            deli.wait(timeout=10)
        except OSError:
            pass
        zombie_alive = False
        note(f"chaos: zombie deli killed ({why})")

    try:
        usurper = LeaseManager(
            os.path.join(shared, "leases"), "chaos-usurper",
            ttl_s=cfg.ttl_s, claim_ttl_s=max(0.25, cfg.ttl_s / 2),
        )

        def acquire(deadline_s: float) -> Optional[int]:
            deadline = time.time() + deadline_s
            while time.time() < deadline:
                f = usurper.try_acquire("deli")
                if f is not None:
                    return f
                time.sleep(cfg.ttl_s / 5)
            return None

        fence = acquire(6 * cfg.ttl_s)
        if fence is None:
            # The zombie was stopped inside its arbitration claim; its
            # flock outlives SIGSTOP, so depose it the way the
            # supervisor would.
            kill_zombie("holding the lease claim")
            fence = acquire(6 * cfg.ttl_s)
        if fence is None:
            return 0
        note(f"chaos: usurper took deli lease (fence {fence})")
        # Bind the new fence on the write paths (an empty fenced append
        # gates without writing), exactly what a real successor's first
        # batch does — bounded, in case the zombie holds the lock.
        ckpt = FencedCheckpointStore(os.path.join(shared, "checkpoints"))
        env = ckpt.load("deli")
        # As in `_shard_lease_takeover`: killing the zombie lets the
        # supervisor restart it, and the fresh generation can rebind a
        # higher fence before our retry — the usurper being REJECTED
        # demonstrates the same write-path fencing, so count it.
        try:
            try:
                deltas.append_many([], fence=fence, owner="chaos-usurper",
                                   lock_timeout_s=2 * cfg.ttl_s)
                if env is not None:
                    ckpt.save("deli", env["state"], fence=fence,
                              owner="chaos-usurper",
                              lock_timeout_s=2 * cfg.ttl_s)
            except TimeoutError:
                kill_zombie("holding a write lock")
                refreshed = acquire(2 * cfg.ttl_s)
                if refreshed is not None:
                    fence = refreshed
                deltas.append_many([], fence=fence, owner="chaos-usurper")
                if env is not None:
                    ckpt.save("deli", env["state"], fence=fence,
                              owner="chaos-usurper")
        except FencedError:
            rejections += 1
            note("chaos: usurper itself fence-REJECTED "
                 "(lease retaken mid-fault)")
        # The deposed owner's write attempts — the exact calls the
        # stopped deli would make on resume — must be rejected.
        if old_fence:
            try:
                deltas.append_many(
                    [{"kind": "op", "doc": "zombie", "seq": -1}],
                    fence=old_fence, owner=old_owner,
                )
            except FencedError:
                rejections += 1
                note("chaos: deposed topic write REJECTED")
            if env is not None:
                try:
                    ckpt.save("deli", env["state"], fence=old_fence,
                              owner=old_owner)
                except FencedError:
                    rejections += 1
                    note("chaos: deposed checkpoint REJECTED")
        usurper.release("deli")
    finally:
        if zombie_alive:
            try:
                os.kill(deli.pid, signal.SIGCONT)
            except OSError:
                pass
            note("chaos: SIGCONT deli")
    return rejections
