"""Chaos-engineering harness for the supervised ordering farm.

The convergence claim ("identical deterministic replay of one totally
ordered stream", PAPER.md) is only worth what it survives. This module
composes the supervised multi-process pipeline
(`server.supervisor.ServiceSupervisor`) with seeded fault injection and
asserts the farm converges **bit-identical to the no-fault GOLDEN
digest with zero duplicate and zero skipped sequence numbers**.

Fault classes (all seeded — a failing run reproduces from its seed):

- ``kill``   — SIGKILL of each lambda role at randomized-but-seeded
  points in the stream; the supervisor restarts it and exactly-once
  recovery (fenced checkpoint + inOff output scan) must hold.
- ``torn``   — partial, newline-less junk appended to the shared
  topics under the append lock (a writer dying mid-write); consumers
  must neither crash nor mis-parse, and the next append seals the
  remnant.
- ``lease``  — expired-lease takeover: the sequencer is SIGSTOPped
  past its TTL, a usurper acquires its lease and binds the next fence,
  and the deposed owner's post-takeover writes (and a forged
  stale-fence write) are **demonstrably rejected** with `FencedError`.
- ``net``    — duplicated + delayed delivery on the broadcast edge: a
  flaky consumer re-delivers past records and defers others; the
  client-side gap/dedup guard (drop `seq <= last`, ranged refetch
  across a gap) must reconstruct the exact stream.
- ``client`` — client disconnect mid-batch: the feeder loses its ack
  and re-appends whole submission batches (at-least-once ingress);
  deli's resubmission dedup must keep the total order identical.

The GOLDEN digest is produced by running the SAME production role code
(`DeliRole.process` / `ScribeRole.process`) in-process with no faults —
not a parallel reimplementation — so golden and chaotic runs can only
differ if a fault actually corrupted the pipeline.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import signal
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..server.columnar_log import make_topic
from ..server.queue import (
    FencedCheckpointStore,
    FencedError,
    LeaseManager,
    SharedFileTopic,
)
from ..server.supervisor import (
    DeliRole,
    ScribeRole,
    ServiceSupervisor,
    canonical_record,
)

FAULT_CLASSES = ("kill", "torn", "lease", "net", "client")


@dataclass
class ChaosConfig:
    seed: int = 0
    faults: Tuple[str, ...] = FAULT_CLASSES
    n_docs: int = 2
    n_clients: int = 3
    ops_per_client: int = 40
    ttl_s: float = 0.5
    heartbeat_timeout_s: float = 3.0
    batch: int = 16
    kills_per_role: int = 1
    timeout_s: float = 120.0
    shared_dir: Optional[str] = None
    # Sequencer implementation under test: "scalar" or "kernel" (the
    # batched deli, server.deli_kernel). Golden always comes from the
    # scalar production path, so a kernel run converging proves the
    # batched pipeline bit-identical under faults.
    deli_impl: str = "scalar"
    # Topic wire form under test: "json" (JSONL lines) or "columnar"
    # (binary record-batch frames, server.columnar_log). Golden always
    # folds in-process, so a columnar run converging proves the binary
    # op-log bit-identical under the same faults.
    log_format: str = "json"
    # Fraction of interleave picks that ride a wire BOXCAR record
    # (several of one client's ops in one ingress record, sequenced
    # atomically — the ROADMAP (d) schema rev). 0 keeps the historical
    # per-op stream.
    boxcar_rate: float = 0.0


@dataclass
class ChaosResult:
    converged: bool
    digest: str
    golden_digest: str
    client_digest: Optional[str]
    scribe_ok: bool
    duplicate_seqs: int
    skipped_seqs: int
    fence_rejections: int
    restarts: Dict[str, int]
    events: List[str] = field(default_factory=list)
    detail: str = ""
    # Fault/recovery timeline: (unix_ts, event) across harness faults
    # and supervisor actions, time-ordered (chaos_run renders it).
    timeline: List[Tuple[float, str]] = field(default_factory=list)
    # Merged utils.metrics snapshot from every role's final heartbeat
    # (per-stage pump sizes, checkpoint bytes/durations, fence
    # rejections...) — `utils.metrics.format_report([metrics])` prints.
    metrics: Dict[str, Any] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# workload + golden
# ---------------------------------------------------------------------------


def build_workload(cfg: ChaosConfig) -> List[dict]:
    """Deterministic ingress stream: per-doc joins, then a seeded
    interleaving of each client's in-order op queue (per-client order
    preserved — deli enforces clientSeq contiguity)."""
    rng = random.Random(cfg.seed)
    docs = [f"doc{d}" for d in range(cfg.n_docs)]
    recs: List[dict] = []
    queues: Dict[Tuple[str, int], List[dict]] = {}
    for doc in docs:
        for c in range(1, cfg.n_clients + 1):
            recs.append({"kind": "join", "doc": doc, "client": c})
            queues[(doc, c)] = [
                {
                    "kind": "op", "doc": doc, "client": c,
                    "clientSeq": i + 1, "refSeq": 0,
                    "contents": {"v": rng.randint(0, 999), "i": i},
                }
                for i in range(cfg.ops_per_client)
            ]
    keys = list(queues)
    while keys:
        k = rng.choice(keys)
        q = queues[k]
        if cfg.boxcar_rate and len(q) >= 2 and rng.random() < cfg.boxcar_rate:
            n = min(len(q), rng.randint(2, 4))
            ops = [q.pop(0) for _ in range(n)]
            recs.append({
                "kind": "boxcar", "doc": k[0], "client": k[1],
                "ops": [
                    {"clientSeq": o["clientSeq"], "refSeq": o["refSeq"],
                     "contents": o["contents"]}
                    for o in ops
                ],
            })
        else:
            recs.append(q.pop(0))
        if not q:
            keys.remove(k)
    return recs


def golden_stream(workload: List[dict], scratch_dir: str) -> List[dict]:
    """The no-fault sequenced stream, produced by the PRODUCTION deli
    code path run in-process (not a reimplementation)."""
    role = DeliRole(scratch_dir, owner="golden", ttl_s=3600.0)
    out: List[dict] = []
    for i, rec in enumerate(workload):
        role.process(i, rec, out)
    return [canonical_record(r) for r in out]


def golden_scribe_digests(stream: List[dict],
                          scratch_dir: str) -> Dict[str, str]:
    """Per-doc rolling digests from the PRODUCTION scribe fold."""
    role = ScribeRole(scratch_dir, owner="golden-scribe", ttl_s=3600.0)
    for i, rec in enumerate(stream):
        role.process(i, rec, [])
    return {d: st["digest"] for d, st in role.docs.items()}


def stream_digest(records: List[dict]) -> str:
    """SHA-256 over the per-doc, seq-sorted canonical stream — the
    bit-identity form two runs are compared in."""
    per_doc: Dict[str, List[dict]] = {}
    for r in records:
        per_doc.setdefault(r["doc"], []).append(canonical_record(r))
    for v in per_doc.values():
        v.sort(key=lambda r: r["seq"])
    payload = json.dumps(per_doc, sort_keys=True, ensure_ascii=True)
    return hashlib.sha256(payload.encode()).hexdigest()


def sequence_integrity(records: List[dict]) -> Tuple[int, int]:
    """(duplicate_seqs, skipped_seqs) across all docs: every doc's
    sequence numbers must be exactly 1..N."""
    dups = skips = 0
    per_doc: Dict[str, List[int]] = {}
    for r in records:
        per_doc.setdefault(r["doc"], []).append(int(r["seq"]))
    for seqs in per_doc.values():
        dups += len(seqs) - len(set(seqs))
        uniq = sorted(set(seqs))
        # Seqs start at 1: a complete stream is exactly 1..max.
        skips += (uniq[-1] - len(uniq)) if uniq else 0
    return dups, skips


# ---------------------------------------------------------------------------
# fault injection pieces
# ---------------------------------------------------------------------------

TORN_FRAGMENT = b'{"torn": tru'  # can never parse; no trailing newline


def inject_torn_append(path: str) -> None:
    """Simulate a writer dying mid-append: raw partial line, no
    newline, written under the same append lock real writers use."""
    import fcntl

    with open(path, "ab") as f:
        fcntl.flock(f.fileno(), fcntl.LOCK_EX)
        try:
            f.write(TORN_FRAGMENT)
            f.flush()
        finally:
            fcntl.flock(f.fileno(), fcntl.LOCK_UN)


def consume_with_net_faults(topic: SharedFileTopic, rng: random.Random,
                            dup_rate: float = 0.1,
                            delay_rate: float = 0.1) -> List[dict]:
    """A flaky delivery edge over the broadcast feed: re-delivers past
    records (duplication) and defers others (delay → a visible gap at
    delivery time). The client applies the same guard the socket
    driver uses: drop ``seq <= last``, and close a gap with a ranged
    refetch from the feed (the ops_from(from, to) role)."""
    entries, _ = topic.read_entries(0)
    feed = [r for _, r in entries
            if isinstance(r, dict) and r.get("kind") == "op"]
    delivery: List[dict] = []
    deferred: List[Tuple[int, dict]] = []
    for i, rec in enumerate(feed):
        # Release any deferred record whose time has come.
        while deferred and deferred[0][0] <= i:
            delivery.append(deferred.pop(0)[1])
        r = rng.random()
        if r < delay_rate:
            deferred.append((i + rng.randint(2, 6), rec))
            continue
        delivery.append(rec)
        if r < delay_rate + dup_rate and delivery:
            delivery.append(rng.choice(delivery))  # re-delivery
    delivery.extend(rec for _, rec in deferred)

    by_key = {(r["doc"], int(r["seq"])): r for r in feed}
    view: Dict[str, List[dict]] = {}
    last: Dict[str, int] = {}
    for rec in delivery:
        doc, seq = rec["doc"], int(rec["seq"])
        cur = last.get(doc, 0)
        if seq <= cur:
            continue  # duplicate delivery
        if seq > cur + 1:
            # Gap: ranged refetch [cur+1, seq-1] from the feed (the
            # driver's ops_from(from_seq, to_seq) catch-up).
            for missing in range(cur + 1, seq):
                hit = by_key.get((doc, missing))
                if hit is not None:
                    view.setdefault(doc, []).append(hit)
            last[doc] = seq - 1
        view.setdefault(doc, []).append(rec)
        last[doc] = seq
    return [r for doc in sorted(view) for r in view[doc]]


# ---------------------------------------------------------------------------
# the run
# ---------------------------------------------------------------------------


def run_chaos(cfg: ChaosConfig) -> ChaosResult:
    """Run the chaos suite. With no `cfg.shared_dir`, a throwaway temp
    dir is used and removed on convergence (kept for post-mortem on
    divergence, named in `detail`); pass `shared_dir` to keep it."""
    shared = cfg.shared_dir or tempfile.mkdtemp(prefix="chaos-")
    res = _run_chaos_in(cfg, shared)
    if cfg.shared_dir is None:
        if res.converged:
            import shutil

            shutil.rmtree(shared, ignore_errors=True)
        else:
            res.detail += f" [state kept for post-mortem: {shared}]"
    return res


def _run_chaos_in(cfg: ChaosConfig, shared: str) -> ChaosResult:
    rng = random.Random(cfg.seed ^ 0x5EED)
    workload = build_workload(cfg)
    golden = golden_stream(workload, os.path.join(shared, "golden"))
    gdigest = stream_digest(golden)
    gscribe = golden_scribe_digests(golden, os.path.join(shared, "golden"))
    expected = len(golden)

    # Feed plan: seeded submission batches; with the `client` fault,
    # some batches are re-appended later in full (a client that lost
    # its ack mid-batch resubmits everything — at-least-once ingress).
    chunks: List[List[dict]] = []
    i = 0
    while i < len(workload):
        n = rng.randint(1, 12)
        chunks.append(workload[i:i + n])
        i += n
    dup_after: Dict[int, int] = {}
    if "client" in cfg.faults:
        for idx in rng.sample(
            range(len(chunks)), max(1, len(chunks) // 10)
        ):
            dup_after[idx] = idx + rng.randint(1, 5)

    # Kill plan: each role killed `kills_per_role` times at seeded
    # chunk indices.
    kill_at: Dict[int, List[str]] = {}
    if "kill" in cfg.faults:
        for role in ("deli", "scriptorium", "scribe", "broadcaster"):
            for _ in range(cfg.kills_per_role):
                idx = rng.randint(len(chunks) // 5,
                                  max(1, len(chunks) - 2))
                kill_at.setdefault(idx, []).append(role)
    torn_at = (
        sorted(rng.sample(range(len(chunks)), min(3, len(chunks))))
        if "torn" in cfg.faults else []
    )
    lease_at = (
        rng.randint(len(chunks) // 3, max(1, 2 * len(chunks) // 3))
        if "lease" in cfg.faults else None
    )

    sup = ServiceSupervisor(
        shared, ttl_s=cfg.ttl_s,
        heartbeat_timeout_s=cfg.heartbeat_timeout_s, batch=cfg.batch,
        deli_impl=cfg.deli_impl, log_format=cfg.log_format,
    ).start()
    raw = make_topic(os.path.join(shared, "topics", "rawdeltas.jsonl"),
                     cfg.log_format)
    deltas_path = os.path.join(shared, "topics", "deltas.jsonl")
    durable = make_topic(os.path.join(shared, "topics", "durable.jsonl"),
                         cfg.log_format)
    broadcast = make_topic(
        os.path.join(shared, "topics", "broadcast.jsonl"), cfg.log_format
    )
    fence_rejections = 0
    events: List[str] = []
    timeline: List[Tuple[float, str]] = []

    def note(ev: str) -> None:
        events.append(ev)
        timeline.append((time.time(), ev))

    try:
        fed_idx = 0
        pending_dups: Dict[int, List[dict]] = {}
        deadline = time.time() + cfg.timeout_s
        while time.time() < deadline:
            sup.poll_once()
            if fed_idx < len(chunks):
                raw.append_many(chunks[fed_idx])
                if fed_idx in dup_after:
                    pending_dups.setdefault(
                        dup_after[fed_idx], []
                    ).extend(chunks[fed_idx])
                for rec in pending_dups.pop(fed_idx, []):
                    raw.append(rec)  # the lost-ack resubmission
                for role in kill_at.pop(fed_idx, []):
                    proc = sup.procs.get(role)
                    if proc is not None and proc.poll() is None:
                        proc.kill()
                        note(f"chaos: SIGKILL {role}")
                if torn_at and torn_at[0] == fed_idx:
                    torn_at.pop(0)
                    inject_torn_append(raw.path)
                    inject_torn_append(deltas_path)
                    note("chaos: torn append")
                if lease_at == fed_idx:
                    fence_rejections += _lease_takeover(
                        shared, sup, cfg, note
                    )
                fed_idx += 1
            # Drain any resubmissions scheduled past the last chunk.
            if fed_idx >= len(chunks) and pending_dups:
                for idx in sorted(pending_dups):
                    for rec in pending_dups.pop(idx, []):
                        raw.append(rec)
            ops = [r for r in durable.read_from(0)
                   if isinstance(r, dict) and r.get("kind") == "op"]
            bops = [r for r in broadcast.read_from(0)
                    if isinstance(r, dict) and r.get("kind") == "op"]
            if (fed_idx >= len(chunks) and not pending_dups
                    and len(ops) >= expected and len(bops) >= expected):
                scr = FencedCheckpointStore(
                    os.path.join(shared, "checkpoints")
                ).load("scribe")
                total = sum(
                    int(st["count"]) for st in
                    ((scr or {}).get("state", {}).get("state", {}) or {})
                    .values()
                )
                if total >= expected:
                    break
            time.sleep(0.02)
    finally:
        sup.stop()

    ops = [r for r in durable.read_from(0)
           if isinstance(r, dict) and r.get("kind") == "op"]
    digest = stream_digest(ops)
    dups, skips = sequence_integrity(ops)
    client_digest = None
    if "net" in cfg.faults:
        client_view = consume_with_net_faults(
            broadcast, random.Random(cfg.seed ^ 0xDE1)
        )
        client_digest = stream_digest(client_view)
    scr = FencedCheckpointStore(
        os.path.join(shared, "checkpoints")
    ).load("scribe")
    live_scribe = {
        d: st["digest"] for d, st in
        ((scr or {}).get("state", {}).get("state", {}) or {}).items()
    }
    scribe_ok = live_scribe == gscribe
    converged = (
        digest == gdigest and dups == 0 and skips == 0 and scribe_ok
        and (client_digest in (None, gdigest))
        and ("lease" not in cfg.faults or fence_rejections > 0)
    )
    detail = (
        f"ops={len(ops)}/{expected} restarts={sup.restarts} "
        f"events={events + sup.events}"
    )
    # Observability artifacts: merge every role's final
    # heartbeat-reported metrics snapshot (the same channel the
    # supervisor's /metrics scrape uses) and time-sort the fault +
    # supervisor timeline. With a kept shared_dir, the per-role
    # snapshots also land in <dir>/metrics.jsonl for
    # tools/metrics_report.py.
    from ..utils.metrics import dump_snapshot_line, merge_snapshots

    role_snaps = sup.child_metrics()
    metrics = merge_snapshots(role_snaps.values()).snapshot()
    if cfg.shared_dir is not None:
        mpath = os.path.join(shared, "metrics.jsonl")
        for role, snap in role_snaps.items():
            dump_snapshot_line(mpath, snap, source=f"chaos-{role}")
    return ChaosResult(
        converged=converged, digest=digest, golden_digest=gdigest,
        client_digest=client_digest, scribe_ok=scribe_ok,
        duplicate_seqs=dups, skipped_seqs=skips,
        fence_rejections=fence_rejections, restarts=dict(sup.restarts),
        events=events + list(sup.events), detail=detail,
        timeline=sorted(timeline + sup.timeline), metrics=metrics,
    )


def _lease_takeover(shared: str, sup: ServiceSupervisor,
                    cfg: ChaosConfig, note) -> int:
    """The expired-lease fault: SIGSTOP the sequencer past its TTL, a
    usurper takes its lease and binds the next fence on the write
    paths, and the deposed owner's writes must be REJECTED. Returns
    the number of demonstrated fence rejections.

    The stopped zombie may be holding an append/checkpoint/claim flock
    at the moment it is stopped; the usurper therefore uses BOUNDED
    lock acquisition and, on timeout, has the zombie killed — exactly
    what the supervisor's stale-heartbeat detection does in production
    (kernel lock release on death then unblocks the successor)."""
    rejections = 0
    deli = sup.procs.get("deli")
    if deli is None or deli.poll() is not None:
        return 0
    deltas = make_topic(os.path.join(shared, "topics", "deltas.jsonl"),
                        cfg.log_format)
    old_fence, old_owner = deltas.latest_fence()
    os.kill(deli.pid, signal.SIGSTOP)
    note("chaos: SIGSTOP deli (stale lease)")
    zombie_alive = True

    def kill_zombie(why: str) -> None:
        nonlocal zombie_alive
        if not zombie_alive:
            return
        try:
            deli.kill()
            deli.wait(timeout=10)
        except OSError:
            pass
        zombie_alive = False
        note(f"chaos: zombie deli killed ({why})")

    try:
        usurper = LeaseManager(
            os.path.join(shared, "leases"), "chaos-usurper",
            ttl_s=cfg.ttl_s, claim_ttl_s=max(0.25, cfg.ttl_s / 2),
        )

        def acquire(deadline_s: float) -> Optional[int]:
            deadline = time.time() + deadline_s
            while time.time() < deadline:
                f = usurper.try_acquire("deli")
                if f is not None:
                    return f
                time.sleep(cfg.ttl_s / 5)
            return None

        fence = acquire(6 * cfg.ttl_s)
        if fence is None:
            # The zombie was stopped inside its arbitration claim; its
            # flock outlives SIGSTOP, so depose it the way the
            # supervisor would.
            kill_zombie("holding the lease claim")
            fence = acquire(6 * cfg.ttl_s)
        if fence is None:
            return 0
        note(f"chaos: usurper took deli lease (fence {fence})")
        # Bind the new fence on the write paths (an empty fenced append
        # gates without writing), exactly what a real successor's first
        # batch does — bounded, in case the zombie holds the lock.
        ckpt = FencedCheckpointStore(os.path.join(shared, "checkpoints"))
        env = ckpt.load("deli")
        try:
            deltas.append_many([], fence=fence, owner="chaos-usurper",
                               lock_timeout_s=2 * cfg.ttl_s)
            if env is not None:
                ckpt.save("deli", env["state"], fence=fence,
                          owner="chaos-usurper",
                          lock_timeout_s=2 * cfg.ttl_s)
        except TimeoutError:
            kill_zombie("holding a write lock")
            deltas.append_many([], fence=fence, owner="chaos-usurper")
            if env is not None:
                ckpt.save("deli", env["state"], fence=fence,
                          owner="chaos-usurper")
        # The deposed owner's write attempts — the exact calls the
        # stopped deli would make on resume — must be rejected.
        if old_fence:
            try:
                deltas.append_many(
                    [{"kind": "op", "doc": "zombie", "seq": -1}],
                    fence=old_fence, owner=old_owner,
                )
            except FencedError:
                rejections += 1
                note("chaos: deposed topic write REJECTED")
            if env is not None:
                try:
                    ckpt.save("deli", env["state"], fence=old_fence,
                              owner=old_owner)
                except FencedError:
                    rejections += 1
                    note("chaos: deposed checkpoint REJECTED")
        usurper.release("deli")
    finally:
        if zombie_alive:
            try:
                os.kill(deli.pid, signal.SIGCONT)
            except OSError:
                pass
            note("chaos: SIGCONT deli")
    return rejections
