"""Canonical document-state digests for cross-implementation identity.

Different replay engines segment the same document differently (the
scalar oracle keeps per-op segments, the kernel coalesces settled
runs), so raw segment lists are not comparable. `normalize_spans`
reduces any (content, props) span list to its canonical form —
maximal runs of identical props — which is a pure function of the
visible document state; `state_digest` hashes it. Used by the
full-stream bit-identity gate (bench.py vs GOLDEN.json — the north
star's "bit-identical final state" contract, BASELINE.json).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, List, Optional, Tuple


def normalize_spans(
    spans: List[Tuple[Any, Optional[dict]]]
) -> List[Tuple[str, Optional[dict]]]:
    """Merge adjacent spans with identical props; empty props == None.

    Content may be str or a list of items; everything is rendered to
    its text form (items joined) so engines that store codepoints and
    engines that store strings normalize identically.
    """
    out: List[Tuple[str, Optional[dict]]] = []
    for content, props in spans:
        if not isinstance(content, str):
            content = "".join(
                c if isinstance(c, str) else chr(c) for c in content
            )
        if not content:
            continue
        p = props or None
        if out and out[-1][1] == p:
            out[-1] = (out[-1][0] + content, p)
        else:
            out.append((content, p))
    return out


def state_digest(spans: List[Tuple[Any, Optional[dict]]]) -> str:
    """SHA-256 over the canonical span form."""
    norm = normalize_spans(spans)
    payload = json.dumps(
        [[t, p] for t, p in norm], sort_keys=True, ensure_ascii=True
    )
    return hashlib.sha256(payload.encode()).hexdigest()
