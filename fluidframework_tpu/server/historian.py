"""Historian: the caching tier in front of summary storage.

Mirrors the reference's historian service (server/historian — a Redis-
backed caching REST proxy in front of gitrest): content-addressed
blobs are IMMUTABLE, so they cache forever under an LRU budget; refs
(mutable head pointers) cache with explicit invalidation on writes
through this tier and a TTL against out-of-band writers. Every store
surface this repo uses (`server.castore.ContentAddressedStore`, the
native C++ store, the durable on-disk store) shares the same
put/get/contains/set_ref/get_ref/list_refs contract, so the historian
wraps any of them.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple


class HistorianCache:
    """LRU blob cache + TTL ref cache over a backing store.

    `blob_budget_bytes` bounds cached blob payloads (immutable:
    eviction only, never invalidation); `ref_ttl` bounds staleness for
    refs written by OTHER processes (writes through this historian
    invalidate immediately)."""

    def __init__(self, backing, blob_budget_bytes: int = 64 * 1024 * 1024,
                 ref_ttl: float = 1.0, name: str = "default"):
        """`name` labels this cache's metrics series (several
        historians in one process — e.g. a summary store next to a
        test fixture — must not fold into one gauge)."""
        self.backing = backing
        self.blob_budget = blob_budget_bytes
        self.ref_ttl = ref_ttl
        self._blobs: "OrderedDict[str, bytes]" = OrderedDict()
        self._blob_bytes = 0
        self._refs: Dict[str, Tuple[float, Optional[str]]] = {}
        self._lock = threading.RLock()
        self.hits = 0
        self.misses = 0
        from ..utils.metrics import get_registry

        m = get_registry()
        self._m_bytes = m.gauge("historian_blob_bytes", cache=name)
        self._m_blobs = m.gauge("historian_blobs", cache=name)
        self._m_hits = m.counter("historian_hits_total", cache=name)
        self._m_misses = m.counter("historian_misses_total", cache=name)
        self._m_evictions = m.counter(
            "historian_evictions_total", cache=name
        )

    # ------------------------------------------------------------- blobs

    def put(self, content) -> str:
        key = self.backing.put(content)
        if isinstance(content, str):
            content = content.encode()
        with self._lock:
            self._admit(key, bytes(content))
        return key

    def get(self, key: str) -> bytes:
        with self._lock:
            data = self._blobs.get(key)
            if data is not None:
                self._blobs.move_to_end(key)
                self.hits += 1
                self._m_hits.inc()
                return data
            self.misses += 1
            self._m_misses.inc()
        data = self.backing.get(key)
        with self._lock:
            self._admit(key, data)
        return data

    def contains(self, key: str) -> bool:
        with self._lock:
            if key in self._blobs:
                return True
        return self.backing.contains(key)

    def _admit(self, key: str, data: bytes) -> None:
        if key in self._blobs:
            self._blobs.move_to_end(key)
            return
        if len(data) > self.blob_budget:
            return  # never cache a blob bigger than the whole budget
        self._blobs[key] = data
        self._blob_bytes += len(data)
        while self._blob_bytes > self.blob_budget:
            _, old = self._blobs.popitem(last=False)
            self._blob_bytes -= len(old)
            self._m_evictions.inc()
        self._m_bytes.set(self._blob_bytes)
        self._m_blobs.set(len(self._blobs))

    # -------------------------------------------------------------- refs

    def set_ref(self, name: str, key: str) -> None:
        self.backing.set_ref(name, key)
        with self._lock:
            self._refs[name] = (time.monotonic(), key)

    def get_ref(self, name: str) -> Optional[str]:
        with self._lock:
            hit = self._refs.get(name)
            if hit is not None and time.monotonic() - hit[0] < self.ref_ttl:
                self.hits += 1
                self._m_hits.inc()
                return hit[1]
            self.misses += 1
            self._m_misses.inc()
        val = self.backing.get_ref(name)
        with self._lock:
            self._refs[name] = (time.monotonic(), val)
        return val

    def list_refs(self) -> List[str]:
        return self.backing.list_refs()  # enumeration stays authoritative

    # ------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "cached_blobs": len(self._blobs),
                "cached_bytes": self._blob_bytes,
                "cached_refs": len(self._refs),
            }
