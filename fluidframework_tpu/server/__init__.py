"""Ordering service (the "routerlicious" role, re-designed in-proc).

- `sequencer.py`: per-document total-order sequencer with MSN tracking —
  the role of the deli lambda (reference:
  server/routerlicious/packages/lambdas/src/deli/lambda.ts).
- `local_service.py`: in-process ordering service wiring sequencer ->
  connected clients, the role of LocalOrderer/LocalDeltaConnectionServer
  (reference: server/routerlicious/packages/memory-orderer/src/
  localOrderer.ts:95, local-server/src/localDeltaConnectionServer.ts:63).

The batched TPU counterpart (thousands of documents sequenced in one
kernel call) lives in fluidframework_tpu/ops/sequencer_kernel.py.
"""

from .sequencer import DocumentSequencer, NACK_STALE_REFSEQ
from .local_service import LocalOrderingService
from .castore import ContentAddressedStore
from .columnar_log import (
    ColumnarFileTopic,
    ColumnarTailReader,
    LOG_FORMATS,
    make_tail_reader,
    make_topic,
)
from .queue import (
    FencedCheckpointStore,
    FencedError,
    JournalConsumer,
    JournalProducer,
    LeaseManager,
    SharedFileConsumer,
    SharedFileProducer,
    SharedFileTopic,
    partition_of,
)
from .supervisor import ServiceSupervisor
from .shard_fabric import (
    AutoscalePolicy,
    ShardFabricSupervisor,
    ShardRouter,
    ShardWorker,
)
from .ingress import IngressRole, verify_nack, write_tenants
from .retention import RetentionRole, disk_usage
from .summarizer import (
    SummarizerRole,
    SummaryIndex,
    SummaryReplica,
    read_catchup,
    summarize_document,
)


def __getattr__(name):
    # Lazy: the kernel deli pulls in jax; scalar-only users (e.g. the
    # supervised farm's non-deli children) must not pay that import.
    if name in ("KernelDeliLambda", "KernelDeliRole"):
        from . import deli_kernel

        return getattr(deli_kernel, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
from .log import LogConsumer, LogTopic, MessageLog
from .lambdas import (
    BroadcasterLambda,
    DeliLambda,
    LocalServer,
    ScribeLambda,
    ScriptoriumLambda,
)

__all__ = [
    "AutoscalePolicy",
    "ColumnarFileTopic",
    "ColumnarTailReader",
    "FencedCheckpointStore",
    "FencedError",
    "LOG_FORMATS",
    "make_tail_reader",
    "make_topic",
    "JournalConsumer",
    "JournalProducer",
    "LeaseManager",
    "SharedFileConsumer",
    "SharedFileProducer",
    "SharedFileTopic",
    "partition_of",
    "BroadcasterLambda",
    "ContentAddressedStore",
    "DeliLambda",
    "KernelDeliLambda",
    "KernelDeliRole",
    "DocumentSequencer",
    "IngressRole",
    "LocalOrderingService",
    "LocalServer",
    "LogConsumer",
    "LogTopic",
    "MessageLog",
    "NACK_STALE_REFSEQ",
    "RetentionRole",
    "ScribeLambda",
    "ScriptoriumLambda",
    "ServiceSupervisor",
    "ShardFabricSupervisor",
    "ShardRouter",
    "ShardWorker",
    "SummarizerRole",
    "SummaryIndex",
    "SummaryReplica",
    "disk_usage",
    "read_catchup",
    "summarize_document",
    "verify_nack",
    "write_tenants",
]
