"""Ordering service (the "routerlicious" role, re-designed in-proc).

- `sequencer.py`: per-document total-order sequencer with MSN tracking —
  the role of the deli lambda (reference:
  server/routerlicious/packages/lambdas/src/deli/lambda.ts).
- `local_service.py`: in-process ordering service wiring sequencer ->
  connected clients, the role of LocalOrderer/LocalDeltaConnectionServer
  (reference: server/routerlicious/packages/memory-orderer/src/
  localOrderer.ts:95, local-server/src/localDeltaConnectionServer.ts:63).

The batched TPU counterpart (thousands of documents sequenced in one
kernel call) lives in fluidframework_tpu/ops/sequencer_kernel.py.
"""

from .sequencer import DocumentSequencer, NACK_STALE_REFSEQ
from .local_service import LocalOrderingService
from .castore import ContentAddressedStore
from .queue import (
    FencedCheckpointStore,
    FencedError,
    JournalConsumer,
    JournalProducer,
    LeaseManager,
    SharedFileConsumer,
    SharedFileProducer,
    SharedFileTopic,
    partition_of,
)
from .supervisor import ServiceSupervisor
from .log import LogConsumer, LogTopic, MessageLog
from .lambdas import (
    BroadcasterLambda,
    DeliLambda,
    LocalServer,
    ScribeLambda,
    ScriptoriumLambda,
)

__all__ = [
    "FencedCheckpointStore",
    "FencedError",
    "JournalConsumer",
    "JournalProducer",
    "LeaseManager",
    "SharedFileConsumer",
    "SharedFileProducer",
    "SharedFileTopic",
    "partition_of",
    "BroadcasterLambda",
    "ContentAddressedStore",
    "DeliLambda",
    "DocumentSequencer",
    "LocalOrderingService",
    "LocalServer",
    "LogConsumer",
    "LogTopic",
    "MessageLog",
    "NACK_STALE_REFSEQ",
    "ScribeLambda",
    "ScriptoriumLambda",
    "ServiceSupervisor",
]
