"""Self-healing service supervisor: the lambda pipeline as SUPERVISED
child processes with fenced, exactly-once recovery.

The reference deploys the routerlicious lambdas as separate pods under
an orchestrator (SURVEY.md §2.5's deployment topology): each lambda is
its own process consuming a Kafka topic, checkpointing to Mongo, and a
crashed pod is restarted to resume from its checkpoint under a new
ZooKeeper epoch. Round 5 had the lambda CLASSES but no topology —
everything ran in one interpreter on the happy path. This module is
that topology over the cross-process primitives in `server.queue`:

    rawdeltas.jsonl → deli → deltas.jsonl → { scriptorium → durable.jsonl
                                            , broadcaster → broadcast.jsonl
                                            , scribe      → (fold ckpt) }

- Every role runs as a child process (`python -m
  fluidframework_tpu.server.supervisor --role <r> ...`) holding a
  FENCED lease on its role (`server.queue.LeaseManager`), renewing it
  while alive and writing a liveness heartbeat each step.
- `ServiceSupervisor` launches the four roles, monitors child liveness
  (process exit + heartbeat staleness), and restarts a dead/stalled
  child with a fresh owner identity; the restarted child re-acquires
  the lease (waiting out the TTL), loads the last durable checkpoint,
  and resumes.
- **Exactly-once recovery**: a role crashing BETWEEN its output append
  and its checkpoint would classically replay the batch (at-least-once)
  — the partition-worker round punted that to consumer-side dedup.
  Here every output record carries the input line offset it was
  produced from (`inOff`); on recovery the role scans its output topic
  for the largest `inOff` already durable, deterministically reprocesses
  the checkpoint→`inOff` input gap WITHOUT emitting (rebuilding
  sequencer state — the paper's determinism doing the work), and only
  then resumes emitting. Output appends and checkpoint writes are both
  fenced, so a deposed owner (expired lease, SIGSTOP zombie) is
  rejected at the write path with `FencedError`, not merely asked to
  stand down.

`testing/chaos.py` + `tools/chaos_run.py` drive this farm under
injected faults (SIGKILL, torn appends, lease takeover, duplicated /
delayed delivery) and assert bit-identical convergence with the
no-fault golden digest.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from .columnar_log import (
    LOG_FORMATS,
    default_log_format,
    make_tail_reader,
    make_topic,
)
from .queue import (
    FencedCheckpointStore,
    FencedError,
    LeaseManager,
    SharedFileTopic,
    TailReader,
    TopicDoorbell,
    doorbells_enabled,
    partition_suffix,
    retry_durable,
)
from .sequencer import DocumentSequencer

__all__ = [
    "BroadcasterRole",
    "DELI_IMPLS",
    "DeliRole",
    "FUSED_PIPELINE_ROLES",
    "LOG_FORMATS",
    "PIPELINE_ROLES",
    "ROLES",
    "ScribeRole",
    "ScriptoriumBroadcasterRole",
    "ScriptoriumRole",
    "ServiceSupervisor",
    "canonical_record",
    "fused_roles",
    "partitioned_role_class",
    "resolve_role_class",
    "serve_role",
    "unwrap_ranged_state",
]

# The supervised farm: the classic four-lambda pipeline plus the
# summary service (`server.summarizer.SummarizerRole` — deltas →
# content-addressed summary blobs + a `summaries` manifest topic, the
# catch-up read side). PIPELINE_ROLES is the pre-summary four-stage
# core for callers that want the ordering path alone.
PIPELINE_ROLES = ("deli", "scriptorium", "scribe", "broadcaster")
ROLES = PIPELINE_ROLES + ("summarizer",)

EXIT_DEPOSED = 4  # lease renew failed: a successor owns the role
EXIT_FENCED = 3  # write-path fence rejection: we are a zombie

# Opt-in WIRE tracing for the supervised farm: with FLUID_TRACE_WIRE
# set, the deli stamps per-stage wall-clock timestamps into a "tr" dict
# on its output records and scriptorium/broadcaster extend it — the
# farm twin of the in-proc `SequencedMessage.traces`. Off by default:
# timestamps differ run to run, so any bit-identity comparison that
# keeps all record keys must run untraced. Digest/convergence forms are
# safe either way (`canonical_record` keeps a fixed key set that
# excludes "tr").
TRACE_WIRE_ENV = "FLUID_TRACE_WIRE"


def trace_wire_enabled() -> bool:
    return os.environ.get(TRACE_WIRE_ENV, "").lower() not in (
        "", "0", "off", "no"
    )


def _topic_path(shared_dir: str, name: str) -> str:
    return os.path.join(shared_dir, "topics", f"{name}.jsonl")


def unwrap_ranged_state(state: Any) -> Any:
    """Deli checkpoint states come in two shapes: the classic per-doc
    `DocumentSequencer` map, and the elastic fabric's ranged envelope
    (``{"__ranged__": 1, "docs": {...}, "preds": {...}}`` — per-doc
    map plus predecessor catch-up cursors, `server.shard_fabric`).
    Every deli restore path unwraps through here, so a checkpoint
    written by a ranged role stays restorable by ANY frontend (scalar,
    kernel, in-proc) — the cursors only mean something to a ranged
    successor, the doc states mean the same thing everywhere."""
    if (isinstance(state, dict) and state.get("__ranged__")
            and "docs" in state):
        return state.get("docs") or {}
    return state


def canonical_record(rec: dict) -> dict:
    """A sequenced record minus transport bookkeeping (`inOff`, worker
    tags) — the form digests and convergence checks compare."""
    return {
        k: rec[k]
        for k in ("kind", "doc", "seq", "msn", "client", "clientSeq",
                  "refSeq", "type", "contents")
        if k in rec
    }


# ---------------------------------------------------------------------------
# Roles
# ---------------------------------------------------------------------------


class _Role:
    """One supervised lambda: fenced lease + heartbeat + exactly-once
    consume/transform/append loop over shared file topics."""

    name: str = ""
    in_topic_name: str = ""
    out_topic_name: Optional[str] = None
    # Roles that ingest columnar `RecordBatch` frames whole (the deli
    # family) set this; everyone else reads decoded records.
    ingest_batches: bool = False
    # Sharded-fabric identity (`partitioned_role_class`): the partition
    # this role instance owns, and the base role name its metrics are
    # labeled with. None = the classic single-partition farm.
    partition: Optional[int] = None
    role_base: Optional[str] = None
    # Set True around a flush whose output records will be
    # POST-PROCESSED as wire dicts (the ranged fabric's predecessor
    # drains tag `inSrc` onto each record): columnar-emitting roles
    # (the kernel deli) then fall back to per-record dict emission for
    # that flush. Recovery and wire tracing force the dict path on
    # their own flags.
    _dict_emit: bool = False
    # LOGICAL input-topic byte position at the START of the batch being
    # processed (captured off the incremental reader before each poll;
    # None during recovery replay and predecessor drains, where no such
    # anchor exists). The summarizer stamps it into its manifests as
    # ``byteOff`` — a hard lower bound for the catch-up tail seek,
    # stable under op-log truncation.
    _in_pos: Optional[int] = None

    def _metric_labels(self) -> Dict[str, str]:
        """Metric label set: single-partition roles keep the historic
        {role: name}; partitioned roles label {role: base, partition: k}
        so the supervisor scrape can aggregate across the fabric while
        per-partition series stay distinguishable."""
        if self.partition is None:
            return {"role": self.name}
        return {"role": self.role_base or self.name,
                "partition": str(self.partition)}

    def __init__(self, shared_dir: str, owner: str, ttl_s: float = 1.0,
                 batch: int = 512, ckpt_interval_s: float = 0.25,
                 ckpt_bytes: int = 256 * 1024,
                 log_format: Optional[str] = None,
                 ckpt_duty: float = 0.2):
        """`ckpt_interval_s` / `ckpt_bytes`: checkpoint cadence —
        a checkpoint is written when EITHER bound is crossed since the
        last one (ROADMAP item (b): the seed checkpointed every step,
        and at 10k-doc scale the per-step JSON snapshot dwarfs the
        batch). Correctness is cadence-independent: exactly-once
        recovery scans the output topic for the durable `inOff` prefix
        and silently replays the checkpoint→prefix gap, however wide.
        `ckpt_interval_s=0` restores every-step checkpointing.

        `log_format` ("json" | "columnar", default env
        ``FLUID_LOG_FORMAT``) picks the topic wire form: JSONL lines or
        binary record batches (`server.columnar_log`). Columnar
        readers parse both, so a JSONL farm may UPGRADE to columnar
        across a restart and resume the same topics mid-stream (the
        reverse needs drained topics — JSON readers cannot parse
        frames).

        `ckpt_duty` is the checkpoint-STORM guard: once state grows to
        where one snapshot costs S seconds (a 10k-doc deli checkpoint
        runs to tens of MB), a cadence that fires every pump would
        spend most of the wall clock checkpointing — so a snapshot
        costing S runs at most every ``S / ckpt_duty`` seconds,
        bounding checkpoint work to that fraction of wall time however
        large the state gets. Recovery granularity widens with it;
        correctness does not (the inOff scan replays any gap).
        Explicit every-step mode (``ckpt_interval_s=0``) bypasses the
        guard."""
        self.shared_dir = shared_dir
        self.owner = owner
        self.batch = batch
        self.ckpt_interval_s = ckpt_interval_s
        self.ckpt_bytes = ckpt_bytes
        self.ckpt_duty = ckpt_duty
        self.log_format = default_log_format(log_format)
        self.leases = LeaseManager(
            os.path.join(shared_dir, "leases"), owner, ttl_s,
            claim_ttl_s=max(0.25, ttl_s / 2),
        )
        self.ckpt = FencedCheckpointStore(
            os.path.join(shared_dir, "checkpoints")
        )
        self.in_topic = make_topic(
            _topic_path(shared_dir, self.in_topic_name), self.log_format
        )
        self.out_topic = (
            make_topic(_topic_path(shared_dir, self.out_topic_name),
                       self.log_format)
            if self.out_topic_name else None
        )
        self.fence: Optional[int] = None
        self.offset = 0
        # Storage degradation flag: True while a durable write (topic
        # append, checkpoint) is inside its bounded-retry backoff
        # budget (ENOSPC, stalled volume). Rides the heartbeat so the
        # supervisor's health surface can show a limping-but-live
        # role; cleared by the next durable write that lands.
        self.degraded = False
        self._reader: Optional[TailReader] = None
        self._last_renew = 0.0
        # Event-driven idle: instead of sleeping the poll interval
        # blind, the idle branch waits on the input topic's doorbell
        # (queue.TopicDoorbell) with the SAME bounded timeout — an
        # append wakes the role immediately, and a missed ring only
        # costs the old poll latency. Created lazily on first idle so
        # bench-driven roles (which never idle) register no FIFO.
        self._bell: Optional[TopicDoorbell] = None
        self._doorbell_ok = doorbells_enabled()
        # Wire tracing (off by default — see TRACE_WIRE_ENV) and the
        # per-stage histogram cache it feeds. `_recovering` gates the
        # OBSERVATION side off during recovery's silent replay:
        # replayed records would otherwise be observed a second time,
        # with a "latency" that spans the crash — phantom multi-second
        # slow ops in the very evidence surface this exists for.
        self.trace_wire = trace_wire_enabled()
        self._recovering = False
        self._stage_hists: Dict[str, Any] = {}
        self._hb_path = os.path.join(shared_dir, "hb", f"{self.name}.json")
        os.makedirs(os.path.dirname(self._hb_path), exist_ok=True)
        # Checkpoint-cadence state + role metrics. The registry is
        # per-process; `heartbeat()` snapshots it into the hb file so
        # the supervisor can merge children's metrics for /metrics.
        self._ckpt_dirty = False
        self._ckpt_last_t = time.time()
        self._ckpt_last_s = 0.0
        self._ckpt_pending_bytes = 0
        self._hb_t = 0.0
        from ..utils.metrics import get_registry

        self.metrics = get_registry()
        m = self.metrics
        labels = self._metric_labels()
        self._m_pump = m.histogram(
            "role_pump_records",
            buckets=(1, 4, 16, 64, 256, 1024, 4096, 16384),
            **labels,
        )
        self._m_records = m.counter("role_records_total", **labels)
        self._m_ckpt_writes = m.counter(
            "checkpoint_writes_total", **labels
        )
        self._m_ckpt_bytes = m.counter(
            "checkpoint_bytes_total", **labels
        )
        self._m_ckpt_ms = m.histogram("checkpoint_ms", **labels)
        self._m_fenced = m.counter("fence_rejections_total", **labels)
        self._m_disk_retries = m.counter("disk_retries_total", **labels)
        self._m_degraded = m.gauge("role_degraded", **labels)

    # ------------------------------------------------------------ state

    def snapshot_state(self) -> Any:
        return None

    def restore_state(self, state: Any) -> None:
        pass

    def process(self, line_idx: int, rec: Any,
                out: List[dict]) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def flush_batch(self, out: List[dict]) -> None:
        """End-of-batch hook: batching roles (the kernel deli) buffer
        in `process` and emit here; scalar roles emit per record."""

    def _append_outputs(self, out: List[dict]) -> int:
        """The fenced durable output append for one step's batch
        (fused roles extend it to several topics — each leg wraps its
        OWN retry budget, so a retried leg can never re-append a leg
        that already landed). Returns bytes written."""
        return self._durable(lambda: self.out_topic.append_many(
            out, fence=self.fence, owner=self.owner
        ))

    def _absorb_predecessors(self) -> None:
        """Recovery hook between the output fence bind and the
        own-topic durable scan: the elastic fabric's ranged roles
        (`shard_fabric._RangedMixin`) absorb their predecessor ranges'
        tails here. Classic roles have no predecessors."""

    # -------------------------------------------------------- doorbells

    def doorbell(self) -> Optional[TopicDoorbell]:
        """This role's input-topic doorbell (created lazily; None when
        doorbells are disabled or the FIFO cannot be made — the caller
        then falls back to the plain poll sleep)."""
        if not self._doorbell_ok:
            return None
        if self._bell is None:
            try:
                self._bell = TopicDoorbell(self.in_topic.path)
            except OSError:
                self._doorbell_ok = False
                return None
        return self._bell

    def close_doorbell(self) -> None:
        """Release the FIFO (a worker dropping a deposed partition
        role must not leave its bell absorbing rings forever)."""
        if self._bell is not None:
            self._bell.close()
            self._bell = None

    # With a live bell the idle timeout stretches to this (still
    # bounded — the poll fallback): rings are retained in the FIFO
    # even while the role is mid-step, so the only append a wait can
    # "miss" predates the bell's creation, and that one costs at most
    # this. Meanwhile idle churn (a heartbeat write per poll tick)
    # drops ~5x, which is itself tail latency on a contended host.
    bell_wait_s: float = 0.05

    def _idle_wait(self, timeout_s: float) -> None:
        """The idle quantum: event wake on new input, bounded by the
        poll fallback that keeps every correctness property
        doorbell-independent."""
        if timeout_s <= 0:
            return
        bell = self.doorbell()
        if bell is None:
            time.sleep(timeout_s)
        else:
            bell.wait(max(timeout_s, self.bell_wait_s))

    def _observe_stage(self, stage: str, ms: float) -> None:
        """Fold one wire-trace stage latency into `op_stage_ms` (the
        same histogram family the in-proc pipeline feeds; instruments
        cached per stage). Partitioned/ranged roles label the series
        with their partition too — the worker heartbeat then carries
        per-partition stage histograms, the supervisor scrape merges
        them, and the `_q` quantile gauges come out labeled
        ``{partition=k}`` (the per-range p99 the autoscale policy's
        `p99_per_partition` trigger reads). Classic single-partition
        roles keep the historic label set."""
        h = self._stage_hists.get(stage)
        if h is None:
            labels = {"stage": stage}
            if self.partition is not None:
                labels["partition"] = str(self.partition)
            h = self._stage_hists[stage] = self.metrics.histogram(
                "op_stage_ms", **labels
            )
        h.observe(ms)

    # -------------------------------------------------------- lifecycle

    # Minimum seconds between heartbeat file writes (0 = every call —
    # the classic farm's liveness contract, where THIS file is what the
    # supervisor watches). The shard fabric raises it on its embedded
    # roles: worker-level heartbeats are the fabric's liveness/metrics
    # channel, so per-partition role heartbeats would otherwise be
    # O(partitions) registry-snapshot writes per pump that nothing
    # reads.
    hb_interval_s: float = 0.0

    def heartbeat(self, force: bool = False) -> None:
        now = time.time()
        if (not force and self.hb_interval_s > 0
                and now - self._hb_t < self.hb_interval_s):
            return
        self._hb_t = now
        tmp = self._hb_path + f".tmp.{os.getpid()}"
        hb = {
            "pid": os.getpid(), "owner": self.owner, "t": time.time(),
            "fence": self.fence, "offset": self.offset,
            "degraded": self.degraded,
            # Metrics report UP through the existing heartbeat
            # channel: the supervisor merges these snapshots into
            # its /metrics registry (per-process registries, one
            # explicit merge point).
            "metrics": self.metrics.snapshot(),
        }
        if self.trace_wire:
            # Slow-op flight-recorder spans ride the same channel (the
            # supervisor's /traces merges them); only in wire-trace
            # mode — nothing feeds the recorder otherwise.
            from ..utils.metrics import get_flight_recorder

            spans = get_flight_recorder().snapshot()
            if spans:
                hb["slow_ops"] = spans
        with open(tmp, "w") as f:
            json.dump(hb, f)
        os.replace(tmp, self._hb_path)

    def _durable(self, fn):
        """Run one durable write under the storage-fault budget:
        bounded-retry jittered backoff on OSError (ENOSPC, EIO, a
        stalled volume), flagging the role `degraded` — and force-
        heartbeating, so liveness AND the flag stay visible while it
        waits — for as long as the retry budget lasts. A write that
        lands clears the flag; a spent budget re-raises (hard-fail:
        the record was never acknowledged, so the supervisor restart
        loses nothing). `FencedError` passes straight through — a
        deposed writer must die, not loop."""
        def note(attempt, exc, delay):
            self.degraded = True
            self._m_degraded.set(1.0)
            self._m_disk_retries.inc()
            self.heartbeat(force=True)  # export the flag while limping

        out = retry_durable(fn, on_retry=note)
        if self.degraded:
            self.degraded = False
            self._m_degraded.set(0.0)
            self.heartbeat(force=True)  # recovery is news too
        return out

    def _renew_or_die(self, now: Optional[float] = None) -> None:
        """Lease upkeep (every ttl/3): a failed renewal means a
        successor owns the role — stand down loudly. ONE helper for
        every pump path (base step, ranged step, predecessor drains)
        so deposed handling can never fork."""
        now = time.time() if now is None else now
        if now - self._last_renew <= self.leases.ttl_s / 3:
            return
        if not self.leases.renew(self.name):
            print(f"DEPOSED {self.name} {self.owner}", flush=True)
            raise SystemExit(EXIT_DEPOSED)
        self._last_renew = now

    def _recover(self) -> None:
        """Resume from the durable checkpoint, then close the
        append-vs-checkpoint crash window: deterministically reprocess
        (silently) every input whose output is already durable."""
        self._recovering = True
        try:
            self._recover_inner()
        finally:
            self._recovering = False

    def _recover_inner(self) -> None:
        env = self.ckpt.load(self.name)
        self.offset = 0
        if env is not None:
            st = env["state"]
            self.offset = int(st.get("offset", 0))
            self.restore_state(st.get("state"))
        else:
            self.restore_state(None)
        if self.out_topic is None:
            return
        # Bind our fence on the output topic BEFORE scanning it: from
        # this append on, a deposed predecessor's in-flight batch is
        # rejected (FencedError), so the scan below sees the final
        # durable prefix and no zombie write can land after it — the
        # write-path half of the takeover contract.
        self._durable(lambda: self.out_topic.append_many(
            [], fence=self.fence, owner=self.owner
        ))
        # Ranged successors absorb their predecessors' tails HERE —
        # after our fence is bound, before the own-topic scan: a doc's
        # own-topic records always postdate its predecessor records,
        # so this is the per-document input order (no-op otherwise).
        self._absorb_predecessors()
        done_counts = self._durable_done_counts(self.out_topic)
        if not done_counts:
            return
        max_done = max(done_counts)
        gap, next_off = self.in_topic.read_entries(self.offset)
        sink: List[dict] = []
        for line_idx, rec in gap:
            if line_idx > max_done:
                next_off = line_idx
                break
            self.process(line_idx, rec, sink)  # silent: already durable
        else:
            next_off = max(self.offset, max_done + 1, next_off)
        self.flush_batch(sink)  # batching roles rebuild state here
        # Re-emit the missing tail of max_done's outputs, if the crash
        # clipped them: deterministic replay regenerates the exact
        # records, so emitting from the durable count onward completes
        # the input without duplicating its prefix.
        tail = [r for r in sink if r.get("inOff") == max_done]
        tail = tail[done_counts[max_done]:]
        if tail:
            self._durable(lambda: self.out_topic.append_many(
                tail, fence=self.fence, owner=self.owner
            ))
        self.offset = next_off
        self._reader = None  # re-anchor the tail at the new offset
        # The replayed records MUST match what is already on disk —
        # that is the determinism claim this service rests on.
        # (Checked cheaply: counts; the chaos harness checks digests.)
        self.checkpoint()

    def _durable_done_counts(self, topic) -> Dict[int, int]:
        """Durable outputs per input offset on `topic`: one input may
        emit SEVERAL outputs (a wire boxcar), and a crash mid-append
        can leave a durable PREFIX of them — outputs land in input
        order, so only the LAST durable input (max over the keys) can
        be partial; everything below it is complete. Records tagged
        `inSrc` live in a PREDECESSOR's offset space (a ranged
        successor's absorbed catch-up, server.shard_fabric) — their
        inOff would collide with ours, so the predecessor scan owns
        them, not this one."""
        entries, _ = topic.read_entries(0)
        done: Dict[int, int] = {}
        for _, r in entries:
            if (isinstance(r, dict) and r.get("inSrc") is None
                    and r.get("inOff", -1) >= self.offset):
                off = r["inOff"]
                done[off] = done.get(off, 0) + 1
        return done

    def checkpoint(self) -> None:
        t0 = time.perf_counter()
        n_bytes = self._durable(lambda: self.ckpt.save(
            self.name,
            {"offset": self.offset, "state": self.snapshot_state()},
            fence=self.fence, owner=self.owner,
        ))
        self._m_ckpt_writes.inc()
        self._m_ckpt_bytes.inc(n_bytes)
        self._ckpt_last_s = time.perf_counter() - t0
        self._m_ckpt_ms.observe(self._ckpt_last_s * 1000.0)
        self._ckpt_dirty = False
        self._ckpt_pending_bytes = 0
        self._ckpt_last_t = time.time()

    def maybe_checkpoint(self) -> bool:
        """Write a checkpoint iff the cadence says so (dirty AND the
        time or byte bound crossed), subject to the checkpoint-storm
        guard: a snapshot whose last write cost S seconds runs at most
        every ``S / ckpt_duty`` seconds, so huge states cannot turn
        the cadence into a wall-clock sink (the 10k-doc deli snapshot
        is tens of MB — every-pump writes would dominate the pipeline
        end-to-end). Returns whether one was written."""
        if not self._ckpt_dirty:
            return False
        now = time.time()
        if (self._ckpt_pending_bytes < self.ckpt_bytes
                and now - self._ckpt_last_t < self.ckpt_interval_s):
            return False
        if (self.ckpt_interval_s > 0 and self.ckpt_duty > 0
                and self._ckpt_last_s > 0
                and now - self._ckpt_last_t
                < self._ckpt_last_s / self.ckpt_duty):
            # Storm guard (ckpt_interval_s=0 — every-step mode — and
            # ckpt_duty=0 — guard disabled — both bypass it).
            return False
        self.checkpoint()
        return True

    def step(self, idle_sleep: float = 0.01) -> int:
        """One supervision quantum: lease upkeep, one input batch,
        fenced append + checkpoint, heartbeat. Returns records moved."""
        now = time.time()
        if self.fence is None:
            fence = self.leases.try_acquire(self.name)
            self.heartbeat()
            if fence is None:
                time.sleep(idle_sleep)
                return 0
            self.fence = fence
            self._last_renew = now
            self._recover()
        else:
            self._renew_or_die(now)
        # Micro-batch cap (threaded into the read): a deep input
        # backlog yields between steps, so lease renewal + heartbeat
        # stay live no matter how far behind the role is. The tail is
        # read incrementally (TailReader) — re-reading the whole topic
        # per step is O(topic²) over a role's lifetime.
        if self._reader is None or self._reader.next_line != self.offset:
            self._reader = make_tail_reader(self.in_topic, self.offset)
        # Batch-start input byte anchor (see `_in_pos`): every record
        # of the coming poll sits at/after this logical position.
        self._in_pos = getattr(self._reader, "_pos", None)
        out: List[dict] = []
        moved = 0
        if self.ingest_batches and hasattr(self._reader, "poll_batches"):
            # Columnar zero-decode path: whole RecordBatch frames go to
            # process_batch; stray decoded records (a migrated JSONL
            # history) take the per-record path.
            for unit in self._reader.poll_batches(self.batch):
                if unit[0] == "batch":
                    moved += unit[2].n
                    self.process_batch(unit[1], unit[2], out)
                else:
                    moved += 1
                    self.process(unit[1], unit[2], out)
        else:
            entries = self._reader.poll(self.batch)
            moved = len(entries)
            for line_idx, rec in entries:
                self.process(line_idx, rec, out)
        next_off = self._reader.next_line
        if not moved:
            if next_off != self.offset:
                self.offset = next_off  # junk-only progress still counts
                self._ckpt_dirty = True
            try:
                # Idle flush: progress folded since the last
                # checkpoint goes durable once the interval elapses
                # (a quiescent stream must not pin state in memory).
                self.maybe_checkpoint()
            except FencedError as exc:
                self._m_fenced.inc()
                self.heartbeat(force=True)  # export the rejection before dying
                print(f"FENCED {self.name} {self.owner}: {exc}", flush=True)
                raise SystemExit(EXIT_FENCED)
            self.heartbeat()
            self._idle_wait(idle_sleep)
            return 0
        self.flush_batch(out)
        try:
            if self.out_topic is not None:
                # Append THEN checkpoint; the recovery scan makes the
                # crash window between them exactly-once, whatever the
                # checkpoint cadence. Durable = retried under the
                # storage-fault budget (degraded, not dead, through a
                # transient ENOSPC).
                self._ckpt_pending_bytes += self._append_outputs(out)
            self.offset = next_off
            self._ckpt_dirty = True
            self.maybe_checkpoint()
        except FencedError as exc:
            self._m_fenced.inc()
            self.heartbeat(force=True)  # export the rejection before dying
            print(f"FENCED {self.name} {self.owner}: {exc}", flush=True)
            raise SystemExit(EXIT_FENCED)
        self._m_pump.observe(moved)
        self._m_records.inc(moved)
        self.heartbeat()
        return moved


class DeliRole(_Role):
    """The sequencer lambda: rawdeltas → deltas, one DocumentSequencer
    per document, resubmission dedup by (client, clientSeq).

    Over a columnar op-log the role ingests whole `RecordBatch` frames
    (`process_batch`): int fields come straight off the codec columns,
    doc ids from the batch dictionary, and standalone ops' `contents`
    stay pre-encoded JSON blobs end to end when the out topic is
    columnar too — the scalar twin of `KernelDeliRole`'s zero-JSON
    ingest (ROADMAP PR-4 follow-up: the per-record lazy `record(i)`
    decode was the last JSON cost on the scalar-on-columnar path)."""

    name = "deli"
    in_topic_name = "rawdeltas"
    out_topic_name = "deltas"
    ingest_batches = True  # _Role.step feeds RecordBatch frames whole

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.sequencers: Dict[str, DocumentSequencer] = {}
        # Blob pass-through is only legal when the output topic can
        # carry raw JSON bytes (a columnar sibling); a JSON out topic
        # needs decoded values for its json.dumps.
        from .columnar_log import ColumnarFileTopic

        self.out_columnar = isinstance(self.out_topic, ColumnarFileTopic)

    def snapshot_state(self) -> Any:
        return {d: s.checkpoint() for d, s in self.sequencers.items()}

    def restore_state(self, state: Any) -> None:
        state = unwrap_ranged_state(state)
        self.sequencers = {
            d: DocumentSequencer.restore(s) for d, s in (state or {}).items()
        }

    def _doc(self, doc_id: str) -> DocumentSequencer:
        if doc_id not in self.sequencers:
            self.sequencers[doc_id] = DocumentSequencer(doc_id)
        return self.sequencers[doc_id]

    def process(self, line_idx: int, rec: Any, out: List[dict]) -> None:
        if not isinstance(rec, dict) or "doc" not in rec:
            return  # foreign/junk record: consume and move on
        doc = self._doc(rec["doc"])
        kind = rec.get("kind")
        if kind == "join":
            if rec["client"] in doc.clients:
                return  # duplicate join (at-least-once ingress)
            msg = doc.join(rec["client"])
            out.append(self._wire(rec["doc"], msg, line_idx))
            return
        if kind == "leave":
            msg = doc.leave(rec["client"])
            if msg is not None:
                out.append(self._wire(rec["doc"], msg, line_idx))
            return
        if kind == "boxcar":
            # Wire schema rev (ROADMAP (d)): one ingress record carries
            # a whole client batch, ticketed back-to-back so it
            # sequences ATOMICALLY — a nack aborts the rest of the
            # boxcar (matching the in-proc `lambdas` semantics and the
            # kernel's group-abort machinery), while resubmission dedup
            # stays per-op and silent (a re-appended boxcar vanishes
            # without polluting the order).
            client = int(rec["client"])
            for op in rec.get("ops") or []:
                if not self._ticket_wire(
                    doc, rec["doc"], client, int(op["clientSeq"]),
                    int(op.get("refSeq", 0)), op.get("contents"),
                    line_idx, out, sub_ts=rec.get("tr_sub"),
                    adm_ts=rec.get("tr_adm"),
                ):
                    break
            return
        if kind != "op":
            return
        self._ticket_wire(
            doc, rec["doc"], int(rec["client"]), int(rec["clientSeq"]),
            int(rec.get("refSeq", 0)), rec.get("contents"), line_idx, out,
            sub_ts=rec.get("tr_sub"), adm_ts=rec.get("tr_adm"),
        )

    def process_batch(self, start_line: int, batch: Any,
                      out: List[dict]) -> None:
        """Columnar ingest: ticket one `RecordBatch` (records numbered
        start_line..start_line+n-1) reusing the already-decoded codec
        columns — no per-record dict build, no lazy full-record JSON
        decode; op contents ride as raw blobs when the out topic is
        columnar (the kernel role's pass-through rule)."""
        import json as _json

        from ..protocol import record_batch as _rb

        rb = batch
        kinds = rb.kind.tolist()
        doci = rb.doc_idx.tolist()
        clients = rb.client.tolist()
        cseqs = rb.client_seq.tolist()
        refs = rb.ref_seq.tolist()
        docs = rb.docs
        passthrough = self.out_columnar
        for i in range(rb.n):
            k = kinds[i]
            if k == _rb.K_RAW_OP:
                doc_id = docs[doci[i]]
                contents: Any = _rb.JsonBlob(rb.blob(i))
                if not passthrough:
                    contents = contents.value
                self._ticket_wire(
                    self._doc(doc_id), doc_id, clients[i], cseqs[i],
                    refs[i], contents, start_line + i, out,
                )
            elif k == _rb.K_RAW_JOIN:
                doc = self._doc(docs[doci[i]])
                if clients[i] in doc.clients:
                    continue  # duplicate join (at-least-once ingress)
                out.append(self._wire(
                    docs[doci[i]], doc.join(clients[i]), start_line + i
                ))
            elif k == _rb.K_RAW_LEAVE:
                msg = self._doc(docs[doci[i]]).leave(clients[i])
                if msg is not None:
                    out.append(self._wire(
                        docs[doci[i]], msg, start_line + i
                    ))
            elif k == _rb.K_RAW_BOXCAR:
                doc_id = docs[doci[i]]
                doc = self._doc(doc_id)
                # v2 frames hand per-op contents as raw-blob handles
                # (no once-per-boxcar JSON decode); v1 as plain values.
                for cseq, ref, contents in rb.boxcar(i):
                    if not passthrough and isinstance(
                            contents, _rb.JsonBlob):
                        contents = contents.value
                    if not self._ticket_wire(
                        doc, doc_id, clients[i], cseq, ref, contents,
                        start_line + i, out,
                    ):
                        break  # nack aborts the rest of the boxcar
            else:
                # Generic / foreign record inside the frame: decode
                # this one record and route it the legacy way.
                self.process(start_line + i, rb.record(i), out)

    def _ticket_wire(self, doc: DocumentSequencer, doc_id: str,
                     client: int, client_seq: int, ref_seq: int,
                     contents: Any, line_idx: int,
                     out: List[dict], sub_ts: Optional[float] = None,
                     adm_ts: Optional[float] = None) -> bool:
        """Ticket one wire op; returns False on a nack (the boxcar
        abort signal). Deduped resubmissions return True silently."""
        state = doc.clients.get(client)
        if state is not None and client_seq <= state.client_seq:
            # Resubmission dedup (the idempotent-producer role): a
            # client that lost its ack mid-batch re-appends the whole
            # batch; everything already sequenced is dropped HERE, so
            # the deltas stream carries each op exactly once and no
            # out-of-order nacks pollute the total order.
            return True
        from ..protocol.messages import DocumentMessage, NackMessage

        res = doc.sequence(client, DocumentMessage(
            client_seq=client_seq, ref_seq=ref_seq, contents=contents,
        ))
        if isinstance(res, NackMessage):
            out.append({
                "kind": "nack", "doc": doc_id, "client": client,
                "clientSeq": res.client_seq, "code": res.code,
                "reason": res.reason, "inOff": line_idx,
            })
            return False
        out.append(self._wire(doc_id, res, line_idx, sub_ts=sub_ts,
                              adm_ts=adm_ts))
        return True

    def _wire(self, doc_id: str, msg, line_idx: int,
              sub_ts: Optional[float] = None,
              adm_ts: Optional[float] = None) -> dict:
        # Timestamps deliberately excluded from the CANONICAL keys:
        # the stream must be a pure function of the input order (the
        # bit-identity contract). In wire-trace mode the stamp rides
        # the side "tr" dict, which canonical_record/digests never see
        # — one clock read serves both the record stamp and the
        # submit_to_stamp histogram so the two surfaces agree exactly.
        rec = {
            "kind": "op", "doc": doc_id, "seq": msg.sequence_number,
            "msn": msg.minimum_sequence_number, "client": msg.client_id,
            "clientSeq": msg.client_seq, "refSeq": msg.ref_seq,
            "type": msg.type.value, "contents": msg.contents,
            "inOff": line_idx,
        }
        if self.trace_wire:
            now = time.time()
            tr = {"stamp": now}
            if isinstance(sub_ts, (int, float)):
                tr["sub"] = sub_ts
                if not self._recovering:
                    # Recovery's silent replay regenerates records it
                    # never emits (plus the genuinely-missing tail,
                    # first stamped now) — observing those would
                    # double-count with crash-spanning durations.
                    self._observe_stage(
                        "submit_to_stamp", (now - sub_ts) * 1000.0
                    )
            if isinstance(adm_ts, (int, float)):
                # The front door's admission stamp (`tr_adm`, one
                # clock read inside `IngressRole.process`): the SAME
                # `now` that stamps this record measures
                # admit_to_stamp, and the same recovery gate keeps
                # replayed records from being observed twice (the
                # trace_stage_once contract every stage follows).
                tr["adm"] = adm_ts
                if not self._recovering:
                    self._observe_stage(
                        "admit_to_stamp", (now - adm_ts) * 1000.0
                    )
            rec["tr"] = tr
        return rec


class ScriptoriumRole(_Role):
    """Durable op log: deltas → durable.jsonl (the Mongo deltas
    collection role). Stateless 1:1 map; exactly-once comes entirely
    from the inOff fast-forward."""

    name = "scriptorium"
    in_topic_name = "deltas"
    out_topic_name = "durable"

    def process(self, line_idx: int, rec: Any, out: List[dict]) -> None:
        if not isinstance(rec, dict) or rec.get("kind") != "op":
            return
        # `inOff`/`inSrc` are the UPSTREAM stage's transport
        # bookkeeping (the deli's input offsets, the elastic fabric's
        # pred-drain tags): stripped here and re-keyed to THIS stage's
        # input offset, so the downstream exactly-once scan reads its
        # own offset space.
        rec2 = {**{k: v for k, v in rec.items()
                   if k not in ("inOff", "inSrc")},
                "inOff": line_idx}
        tr = rec.get("tr")
        if self.trace_wire and isinstance(tr, dict):
            now = time.time()
            rec2["tr"] = {**tr, "dur": now}
            stamp = tr.get("stamp")
            if isinstance(stamp, (int, float)) and not self._recovering:
                # Silent replay re-processes already-durable records;
                # observing them again would skew /slo with
                # crash-spanning durations.
                self._observe_stage(
                    "stamp_to_durable", (now - stamp) * 1000.0
                )
        out.append(rec2)


class BroadcasterRole(_Role):
    """Fan-out feed: deltas → broadcast.jsonl, which connected clients
    tail (the socket push edge). Delivery to clients is at-least-once
    by nature — the chaos harness's delayed/duplicated delivery faults
    live on the consumer side of this topic."""

    name = "broadcaster"
    in_topic_name = "deltas"
    out_topic_name = "broadcast"

    def process(self, line_idx: int, rec: Any, out: List[dict]) -> None:
        if not isinstance(rec, dict) or rec.get("kind") not in (
            "op", "nack"
        ):
            return
        rec2 = {**{k: v for k, v in rec.items()
                   if k not in ("inOff", "inSrc")},
                "inOff": line_idx}
        tr = rec.get("tr")
        if self.trace_wire and isinstance(tr, dict):
            now = time.time()
            rec2["tr"] = {**tr, "bc": now}
            if self._recovering:
                # Silent replay: already-observed records must not be
                # re-observed (crash-spanning durations) nor fed to
                # the flight recorder as phantom slow ops.
                out.append(rec2)
                return
            stamp = tr.get("stamp")
            if isinstance(stamp, (int, float)):
                self._observe_stage(
                    "stamp_to_broadcast", (now - stamp) * 1000.0
                )
            sub = tr.get("sub")
            if isinstance(sub, (int, float)):
                # The farm's end-to-end stage AND the slow-op flight
                # recorder: a tail observation beyond the rolling p99
                # (or fixed threshold) keeps its full span — the exact
                # slow op a regression report needs attached.
                e2e = (now - sub) * 1000.0
                self._observe_stage("submit_to_broadcast", e2e)
                from ..utils.metrics import get_flight_recorder

                fr = get_flight_recorder()
                if fr.note(e2e):
                    span = {
                        "doc": rec.get("doc"), "seq": rec.get("seq"),
                        "client": rec.get("client"),
                        "clientSeq": rec.get("clientSeq"),
                        "stages": rec2["tr"],
                    }
                    if self.partition is not None:
                        # Fabric runs: the span names its partition so
                        # the supervisor's merged /traces can pin a
                        # tail regression to the hot range.
                        span["partition"] = str(self.partition)
                    fr.add(e2e, span)
        out.append(rec2)


class ScriptoriumBroadcasterRole(_Role):
    """The FUSED durable+broadcast hop: ONE supervised consumer plays
    both `ScriptoriumRole` and `BroadcasterRole`, so a record crosses
    deltas → durable → broadcast for one topic read, one process wake
    and ~one fsync per batch instead of one of each PER STAGE (the
    per-hop floor PR 9's open-loop bench exposed). The wire contract
    is unchanged — `durable` and `broadcast` carry exactly the records
    the split roles wrote — only the consumer topology fuses.

    - The durable leg keeps its fsync; the broadcast leg appends
      UNFSYNCED (`append_many(fsync=False)`): broadcast is a DERIVED
      feed, deterministically regenerable from the durable deltas
      stream, and recovery's per-topic durable-prefix scan re-emits
      anything the page cache lost — exactly-once holds leg by leg.
    - On columnar topics the transform is a frame PASS-THROUGH:
      K_SEQ_OP / K_NACK rows re-emit as `ColumnarRecords` slices with
      only the inOff column rewritten — no decode, no re-encode, blob
      bytes ride untouched (`record_batch.ColumnarRecords.from_batch`).
    - Recovery generalizes the single-topic contract: the fence binds
      on BOTH topics, each topic's durable prefix scans independently,
      the gap replays silently once, and each topic gets exactly its
      missing suffix re-emitted (the durable leg appends first in
      steady state, so the broadcast leg is the one that usually
      trails a crash).

    In wire-trace mode one clock read stamps both `dur` and `bc` and
    feeds the same stage histograms + slow-op flight recorder the
    split roles fed."""

    name = "scriptorium_broadcaster"
    in_topic_name = "deltas"
    out_topic_name = "durable"
    # The second output leg (partitioned_role_class suffixes it along
    # with the in/out pair, so a per-partition fused consumer reads
    # deltas-p{k} and writes durable-p{k} + broadcast-p{k}).
    bc_topic_name = "broadcast"
    ingest_batches = True  # columnar pass-through wants whole frames

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.bc_topic = make_topic(
            _topic_path(self.shared_dir, self.bc_topic_name),
            self.log_format,
        )
        self._bc_out: List[Any] = []
        from .columnar_log import ColumnarFileTopic

        self.out_columnar = isinstance(self.out_topic, ColumnarFileTopic)

    # ------------------------------------------------------------- pump

    def process(self, line_idx: int, rec: Any, out: List[dict]) -> None:
        if not isinstance(rec, dict) or rec.get("kind") not in (
            "op", "nack"
        ):
            return
        rec2 = {**{k: v for k, v in rec.items()
                   if k not in ("inOff", "inSrc")},
                "inOff": line_idx}
        tr = rec.get("tr")
        if self.trace_wire and isinstance(tr, dict):
            now = time.time()
            # One clock read serves both stage stamps (the fused hop
            # IS one instant) and every observation below.
            rec2["tr"] = {**tr, "dur": now, "bc": now}
            if not self._recovering:
                stamp = tr.get("stamp")
                if isinstance(stamp, (int, float)):
                    ms = (now - stamp) * 1000.0
                    self._observe_stage("stamp_to_durable", ms)
                    self._observe_stage("stamp_to_broadcast", ms)
                sub = tr.get("sub")
                if isinstance(sub, (int, float)):
                    e2e = (now - sub) * 1000.0
                    self._observe_stage("submit_to_broadcast", e2e)
                    from ..utils.metrics import get_flight_recorder

                    fr = get_flight_recorder()
                    if fr.note(e2e):
                        span = {
                            "doc": rec.get("doc"), "seq": rec.get("seq"),
                            "client": rec.get("client"),
                            "clientSeq": rec.get("clientSeq"),
                            "stages": rec2["tr"],
                        }
                        if self.partition is not None:
                            span["partition"] = str(self.partition)
                        fr.add(e2e, span)
        if rec.get("kind") == "op":
            out.append(rec2)
        # Broadcast carries ops AND nacks; the very same dict object
        # rides both legs (no per-leg rebuild).
        self._bc_out.append(rec2)

    def process_batch(self, start_line: int, batch: Any,
                      out: List[dict]) -> None:
        """Columnar ingest: pass K_SEQ_OP/K_NACK spans through as
        column slices (durable takes the seq-ops, broadcast takes
        both), decode only generic strays — in stream order, so the
        spliced output frames carry records exactly where the split
        roles would have."""
        if (not self.out_columnar or self.trace_wire
                or self._recovering or self._dict_emit):
            for i in range(batch.n):
                self.process(start_line + i, batch.record(i), out)
            return
        import numpy as np

        from ..protocol import record_batch as _rb

        n = batch.n
        if n == 0:
            return
        kind = batch.kind
        is_pass = (kind == _rb.K_SEQ_OP) | (kind == _rb.K_NACK)
        for run_pass, lo, hi in _rb.mask_runs(is_pass):
            if not run_pass:
                for i in range(lo, hi):
                    self.process(start_line + i, batch.record(i), out)
                continue
            rows = np.arange(lo, hi)
            offs = np.arange(start_line + lo, start_line + hi,
                             dtype=np.int64)
            self._bc_out.append(
                _rb.ColumnarRecords.from_batch(batch, rows, offs)
            )
            ops = kind[lo:hi] == _rb.K_SEQ_OP
            if ops.all():
                out.append(self._bc_out[-1])  # same object, both legs
            elif ops.any():
                out.append(_rb.ColumnarRecords.from_batch(
                    batch, rows[ops], offs[ops]
                ))

    def _append_outputs(self, out: List[Any]) -> int:
        # Durable first (fsync, the base append), broadcast second
        # (unfsynced): a crash between the legs leaves broadcast
        # trailing, which recovery's per-topic scan closes. Each leg
        # owns its retry budget — a retry must never re-append the leg
        # that already landed.
        n = super()._append_outputs(out)
        bc, self._bc_out = self._bc_out, []
        n += self._durable(lambda: self.bc_topic.append_many(
            bc, fence=self.fence, owner=self.owner, fsync=False
        ))
        return n

    # --------------------------------------------------------- recovery

    def _recover_inner(self) -> None:
        env = self.ckpt.load(self.name)
        self.offset = 0
        if env is not None:
            st = env["state"]
            self.offset = int(st.get("offset", 0))
            self.restore_state(st.get("state"))
        else:
            self.restore_state(None)
        self._bc_out = []
        # Bind our fence on BOTH output topics before scanning either:
        # a deposed predecessor's in-flight append to either leg is
        # rejected from here on.
        self._durable(lambda: self.out_topic.append_many(
            [], fence=self.fence, owner=self.owner
        ))
        self._durable(lambda: self.bc_topic.append_many(
            [], fence=self.fence, owner=self.owner
        ))
        done_d = self._durable_done_counts(self.out_topic)
        done_b = self._durable_done_counts(self.bc_topic)
        if not done_d and not done_b:
            return
        max_done = max(list(done_d) + list(done_b))
        gap, next_off = self.in_topic.read_entries(self.offset)
        sink: List[dict] = []
        for line_idx, rec in gap:
            if line_idx > max_done:
                next_off = line_idx
                break
            self.process(line_idx, rec, sink)  # silent: already durable
        else:
            next_off = max(self.offset, max_done + 1, next_off)
        self.flush_batch(sink)
        bc_sink, self._bc_out = self._bc_out, []
        # Per-leg tail: everything past that leg's own durable prefix
        # (its max_done's clipped suffix, plus whole inputs the other
        # leg reached first). Records sit in `snk` in input order, so
        # the concatenation preserves stream order.
        for topic, snk, done, fs in (
            (self.out_topic, sink, done_d, True),
            (self.bc_topic, bc_sink, done_b, False),
        ):
            if done:
                md = max(done)
                tail = [r for r in snk if r.get("inOff") == md]
                tail = tail[done.get(md, 0):]
                tail += [r for r in snk if r.get("inOff", -1) > md]
            else:
                tail = list(snk)
            if tail:
                self._durable(lambda t=topic, x=tail, f=fs:
                              t.append_many(x, fence=self.fence,
                                            owner=self.owner, fsync=f))
        self.offset = next_off
        self._reader = None  # re-anchor the tail at the new offset
        self.checkpoint()


class ScribeRole(_Role):
    """Protocol-state folder: deltas → per-doc rolling digest + head
    seq (the scribe/summary role). Its output IS its checkpoint, and
    state+offset commit in one atomic fenced write, so recovery is
    trivially exactly-once."""

    name = "scribe"
    in_topic_name = "deltas"
    out_topic_name = None

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.docs: Dict[str, dict] = {}

    def snapshot_state(self) -> Any:
        return self.docs

    def restore_state(self, state: Any) -> None:
        self.docs = dict(state or {})

    def process(self, line_idx: int, rec: Any, out: List[dict]) -> None:
        if not isinstance(rec, dict) or rec.get("kind") != "op":
            return
        st = self.docs.setdefault(
            rec["doc"], {"seq": 0, "count": 0, "digest": ""}
        )
        payload = json.dumps(
            [st["digest"], canonical_record(rec)], sort_keys=True
        )
        st["digest"] = hashlib.sha256(payload.encode()).hexdigest()
        st["seq"] = max(int(st["seq"]), int(rec["seq"]))
        st["count"] = int(st["count"]) + 1


ROLE_CLASSES = {
    cls.name: cls
    for cls in (DeliRole, ScriptoriumRole, ScribeRole, BroadcasterRole,
                ScriptoriumBroadcasterRole)
}

DELI_IMPLS = ("scalar", "kernel")


def fused_roles(roles: Tuple[str, ...]) -> Tuple[str, ...]:
    """`roles` with the scriptorium+broadcaster pair collapsed into
    the fused durable+broadcast consumer (order preserved, the fused
    role at the first of the pair's positions)."""
    out: List[str] = []
    for r in roles:
        if r in ("scriptorium", "broadcaster"):
            if ScriptoriumBroadcasterRole.name not in out:
                out.append(ScriptoriumBroadcasterRole.name)
        else:
            out.append(r)
    return tuple(out)


FUSED_PIPELINE_ROLES = fused_roles(PIPELINE_ROLES)


def resolve_role_class(role: str, deli_impl: str = "scalar"):
    """Role name -> class; `deli_impl="kernel"` swaps the sequencer for
    the device-batched `deli_kernel.KernelDeliRole` (imported lazily so
    scalar farms never pay the jax import). The summarizer resolves
    lazily too — its merge-tree fold engine only imports jax when a
    doc's contents actually decode as merge-tree ops."""
    if role == "deli" and deli_impl == "kernel":
        from .deli_kernel import KernelDeliRole

        return KernelDeliRole
    if role == "summarizer":
        from .summarizer import SummarizerRole

        return SummarizerRole
    if role == "ingress":
        from .ingress import IngressRole

        return IngressRole
    if role == "retention":
        from .retention import RetentionRole

        return RetentionRole
    return ROLE_CLASSES[role]


def partitioned_role_class(base: type, partition: int) -> type:
    """The sharded-fabric form of a role class: same code, partition-
    suffixed identity. Lease key, heartbeat file, checkpoint key and
    topic pair all become per-partition (`deli-p3` over
    `rawdeltas-p3` → `deltas-p3`), so N partitions of one role are N
    independent fenced exactly-once pipelines over disjoint slices of
    the document space (`server.shard_fabric` owns the slicing)."""
    p = int(partition)
    if p < 0:
        raise ValueError(f"partition must be >= 0, got {partition}")
    attrs = {
        "name": partition_suffix(base.name, p),
        "in_topic_name": partition_suffix(base.in_topic_name, p),
        "out_topic_name": (
            partition_suffix(base.out_topic_name, p)
            if base.out_topic_name else None
        ),
        "partition": p,
        "role_base": base.name,
    }
    # A second output leg (the fused durable+broadcast consumer)
    # partitions along with the primary pair.
    if getattr(base, "bc_topic_name", None):
        attrs["bc_topic_name"] = partition_suffix(base.bc_topic_name, p)
    return type(f"{base.__name__}P{p}", (base,), attrs)


def serve_role(shared_dir: str, role: str, owner: str,
               ttl_s: float = 1.0, batch: int = 512,
               deli_impl: str = "scalar",
               ckpt_interval_s: float = 0.25,
               ckpt_bytes: int = 256 * 1024,
               log_format: Optional[str] = None,
               ckpt_duty: float = 0.2,
               partition: Optional[int] = None,
               deli_devices: Optional[int] = None,
               hb_interval_s: Optional[float] = None,
               summary_ops: Optional[int] = None,
               ingress_partitions: Optional[int] = None,
               ingress_elastic: bool = False,
               device_plane: Optional[str] = None,
               fold_backend: Optional[str] = None) -> None:
    """Child-process entry: run one role until killed/deposed/fenced.
    With `partition`, the role serves that partition's topic pair under
    its partition-suffixed lease (one pinned shard of the fabric —
    `shard_fabric.ShardWorker` is the lease-balanced multi-partition
    form). `deli_devices=N` shards the kernel deli's doc-slot pool
    across an N-device mesh (`--deli-devices`; kernel impl only —
    the scalar deli has no device axis, so asking is a config error).
    `summary_ops` sets the summarizer's emission cadence (summarizer
    role only; env ``FLUID_SUMMARY_OPS`` is the process-wide form).
    `device_plane` ("DOCSxMODEL", `parallel.device_plane`) serves the
    kernel deli on the plane's 1-D docs slice and lays the
    summarizer's folds over the whole 2-D pool; `fold_backend`
    ("kernel"|"overlay") picks the summarizer's merge-tree fold
    engine (``FLUID_FOLD_BACKEND`` is the process-wide form)."""
    if deli_devices is not None and deli_devices > 1 and (
            role != "deli" or deli_impl != "kernel"):
        raise ValueError(
            f"deli_devices={deli_devices} needs role=deli with "
            f"deli_impl='kernel' (got role={role!r}, impl={deli_impl!r})"
        )
    if device_plane is not None and (
            role not in ("deli", "summarizer")
            or (role == "deli" and deli_impl != "kernel")):
        raise ValueError(
            f"device_plane={device_plane!r} serves the kernel deli "
            f"and the summarizer (got role={role!r}, "
            f"impl={deli_impl!r})"
        )
    if fold_backend is not None and role != "summarizer":
        raise ValueError(
            f"fold_backend={fold_backend!r} is a summarizer knob "
            f"(got role={role!r})"
        )
    if summary_ops is not None and role != "summarizer":
        raise ValueError(
            f"summary_ops={summary_ops} is a summarizer knob "
            f"(got role={role!r})"
        )
    if (ingress_partitions is not None or ingress_elastic) \
            and role != "ingress":
        raise ValueError(
            f"ingress_partitions/ingress_elastic are ingress knobs "
            f"(got role={role!r})"
        )
    cls = resolve_role_class(role, deli_impl)
    if partition is not None:
        cls = partitioned_role_class(cls, partition)
    kw = {}
    if deli_devices is not None and deli_devices > 1:
        kw["deli_devices"] = deli_devices
    if device_plane is not None:
        kw["device_plane"] = device_plane
    if fold_backend is not None:
        kw["fold_backend"] = fold_backend
    if summary_ops is not None:
        kw["summary_ops"] = summary_ops
    if role == "ingress":
        # The front door routes by partition topology; admission knobs
        # themselves ride FLUID_INGRESS_* env (server.ingress).
        kw["n_partitions"] = ingress_partitions or 1
        kw["elastic"] = ingress_elastic
    r = cls(
        shared_dir, owner, ttl_s=ttl_s, batch=batch,
        ckpt_interval_s=ckpt_interval_s, ckpt_bytes=ckpt_bytes,
        log_format=log_format, ckpt_duty=ckpt_duty, **kw,
    )
    if hb_interval_s is not None:
        # Heartbeat throttle: the default (0 = every step) is the
        # classic liveness contract, but a registry snapshot per
        # record is pure tail latency at high step rates — the
        # latency bench runs its children at ~0.1s (still 20x inside
        # the staleness threshold; forced heartbeats — degraded
        # flags, fence rejections — always bypass the throttle).
        r.hb_interval_s = hb_interval_s
    print(f"READY {r.name} {owner}", flush=True)
    while True:
        try:
            r.step()
        except FencedError as exc:
            # Recovery-path rejection (step() handles its own): we are
            # a zombie; a successor owns the fence. Stand down loudly.
            r._m_fenced.inc()
            r.heartbeat()  # export the rejection before dying
            print(f"FENCED {role} {owner}: {exc}", flush=True)
            raise SystemExit(EXIT_FENCED)


# ---------------------------------------------------------------------------
# The supervisor
# ---------------------------------------------------------------------------


class ServiceSupervisor:
    """Launches the lambda farm as child processes and keeps it alive.

    Failure detection is two-signal: process exit (`Popen.poll`) and
    heartbeat staleness (a live-but-wedged child — SIGSTOP, deadlock —
    misses its heartbeat and is SIGKILLed before restart; fencing makes
    even a missed kill safe). Every restart spawns a fresh owner
    identity `<role>-g<generation>`, whose lease acquisition waits out
    the dead owner's TTL and advances the fence.
    """

    def __init__(self, shared_dir: str, roles: Tuple[str, ...] = ROLES,
                 ttl_s: float = 0.75, heartbeat_timeout_s: float = 2.0,
                 batch: int = 512, python: Optional[str] = None,
                 spawn_ready_timeout_s: float = 30.0,
                 deli_impl: Optional[str] = None,
                 ckpt_interval_s: float = 0.25,
                 ckpt_bytes: int = 256 * 1024,
                 log_format: Optional[str] = None,
                 ckpt_duty: float = 0.2,
                 deli_devices: Optional[int] = None,
                 child_env: Optional[Dict[str, str]] = None,
                 hb_interval_s: Optional[float] = None,
                 summary_ops: Optional[int] = None,
                 fused_hop: bool = False,
                 ingress: bool = False,
                 retention: bool = False,
                 retention_env: Optional[Dict[str, str]] = None,
                 device_plane: Optional[str] = None,
                 fold_backend: Optional[str] = None):
        """`child_env` adds/overrides spawn-environment variables for
        every child (the chaos harness's seam: it points CHILDREN at a
        disk-fault spec — `queue.DISK_FAULT_ENV` — without poisoning
        its own appends). `hb_interval_s` throttles the children's
        heartbeat-file writes (None keeps the classic every-step
        cadence; forced heartbeats always bypass the throttle).
        `summary_ops` sets the summarizer child's emission cadence
        (records per doc between summaries; None keeps the role
        default / ``FLUID_SUMMARY_OPS``). `fused_hop` collapses the
        scriptorium+broadcaster pair in `roles` into the fused
        durable+broadcast consumer (`ScriptoriumBroadcasterRole`) —
        same topics, same records, one fewer process wake and fsync
        per batch on the downstream hop pair. `ingress` puts the
        supervised admission front door (`server.ingress.IngressRole`)
        in front of the farm: clients submit to the ``ingress`` topic,
        and only admitted records reach ``rawdeltas`` — auth / size /
        rate / backpressure nacks land on the ``nacks`` topic
        instead."""
        if fused_hop:
            roles = fused_roles(tuple(roles))
        if ingress and "ingress" not in roles:
            roles = ("ingress",) + tuple(roles)
        if retention and "retention" not in roles:
            # Sixth role, the retention plane (`server.retention`):
            # summary-driven fenced op-log truncation + castore GC.
            # Opt-in — with it on, readers that need a topic's full
            # prefix must boot from the newest summary instead.
            roles = tuple(roles) + ("retention",)
        self.retention = bool(retention) or "retention" in roles
        self.ingress = bool(ingress) or "ingress" in roles
        self.fused_hop = bool(fused_hop)
        self.shared_dir = shared_dir
        self.child_env = dict(child_env or {})
        if self.retention:
            if default_log_format(log_format) != "columnar":
                raise ValueError(
                    "retention=True needs log_format='columnar' "
                    "(JSONL topics have no truncation header)"
                )
            if "summarizer" not in roles:
                raise ValueError(
                    "retention=True needs the summarizer in roles: "
                    "truncation only reclaims SUMMARY-covered records"
                )
            # The retention child's consumer set is THIS farm's actual
            # deltas consumers — a role that is not in the farm must
            # not block reclaim as a phantom offset-0 checkpoint.
            deltas_consumers = [
                r for r in roles
                if r in ("scriptorium", "broadcaster", "scribe",
                         "summarizer", ScriptoriumBroadcasterRole.name)
            ]
            self.child_env.setdefault(
                "FLUID_RETENTION_CONSUMERS", ",".join(deltas_consumers)
            )
            if self.ingress:
                # With the front door on, the admission topics are
                # growth surfaces too: `ingress` truncates behind the
                # admission role's own input checkpoint, `nacks`
                # behind its producer recovery window (PR 14
                # follow-up — the whole pipeline's disk is bounded,
                # not just the ordered half).
                self.child_env.setdefault(
                    "FLUID_RETENTION_TOPICS",
                    "deltas,rawdeltas,ingress,nacks",
                )
            for k, v in (retention_env or {}).items():
                self.child_env[k] = str(v)
        self.hb_interval_s = hb_interval_s
        self.summary_ops = (
            int(summary_ops) if summary_ops is not None else None
        )
        self.roles = tuple(roles)
        self.ttl_s = ttl_s
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.batch = batch
        self.ckpt_interval_s = ckpt_interval_s
        self.ckpt_bytes = ckpt_bytes
        self.ckpt_duty = ckpt_duty
        self.log_format = default_log_format(log_format)
        self.deli_impl = deli_impl or os.environ.get("FLUID_DELI", "scalar")
        if self.deli_impl not in DELI_IMPLS:
            raise ValueError(
                f"deli_impl {self.deli_impl!r} not in {DELI_IMPLS}"
            )
        # Multi-device deli: shard the kernel deli's [D, C] pool over
        # N devices. Children run under JAX_PLATFORMS=cpu, so the
        # spawn env also forces N virtual host devices — the CPU-CI
        # emulation of a real N-chip slice (utils.devices).
        self.deli_devices = (
            int(deli_devices) if deli_devices is not None else None
        )
        if self.deli_devices is not None and self.deli_devices > 1 \
                and self.deli_impl != "kernel":
            raise ValueError(
                f"deli_devices={self.deli_devices} needs "
                f"deli_impl='kernel' (the scalar deli has no device "
                f"axis); got {self.deli_impl!r}"
            )
        # 2-D device plane (parallel.device_plane): ONE docs x model
        # mesh serving the kernel deli (docs-axis slice) AND the
        # summarizer folds (whole pool). The parent only PARSES the
        # spec — children build the actual mesh under the forced
        # virtual-device env below; the spec also rides the child env
        # (PLANE_ENV) so ranged/partitioned roles inherit it.
        self.device_plane: Optional[str] = None
        self.plane_shape: Optional[Tuple[int, int]] = None
        self.fold_backend = fold_backend
        if fold_backend is not None and fold_backend not in (
                "kernel", "overlay"):
            raise ValueError(
                f"fold_backend {fold_backend!r} not in "
                f"('kernel', 'overlay')"
            )
        if device_plane is not None:
            from ..parallel.device_plane import PLANE_ENV, \
                parse_plane_spec

            if self.deli_impl != "kernel":
                raise ValueError(
                    f"device_plane={device_plane!r} needs "
                    f"deli_impl='kernel' (the scalar deli has no "
                    f"device axis); got {self.deli_impl!r}"
                )
            if self.deli_devices is not None and self.deli_devices > 1:
                raise ValueError(
                    "deli_devices and device_plane are exclusive: "
                    "the plane's docs axis IS the deli's device slice"
                )
            self.plane_shape = parse_plane_spec(device_plane)
            self.device_plane = (
                f"{self.plane_shape[0]}x{self.plane_shape[1]}"
            )
            self.child_env.setdefault(PLANE_ENV, self.device_plane)
        self.python = python or sys.executable
        self.spawn_ready_timeout_s = spawn_ready_timeout_s
        self.procs: Dict[str, subprocess.Popen] = {}
        self.spawned_at: Dict[str, float] = {}
        self._stdout_tails: Dict[str, str] = {}
        self.generation: Dict[str, int] = {r: 0 for r in self.roles}
        self.restarts: Dict[str, int] = {r: 0 for r in self.roles}
        self.events: List[str] = []
        # Timestamped twin of `events` (the fault/recovery timeline
        # chaos_run renders; events stays the stable string API).
        self.timeline: List[Tuple[float, str]] = []
        self._monitor = None
        os.makedirs(os.path.join(shared_dir, "hb"), exist_ok=True)

    def _event(self, text: str) -> None:
        self.events.append(text)
        self.timeline.append((time.time(), text))

    # ------------------------------------------------------------ spawn

    def _repo_root(self) -> str:
        return os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))

    def _child_cmd(self, role: str, owner: str) -> List[str]:
        """The child process's argv (the spawn seam subclasses override:
        `shard_fabric.ShardFabricSupervisor` launches lease-balanced
        shard workers through the same monitor/restart machinery).
        -c instead of -m: `-m pkg.mod` would import the package first
        and runpy then re-executes the module as __main__
        (RuntimeWarning + double module state)."""
        cmd = [self.python, "-c",
               "from fluidframework_tpu.server.supervisor import main; "
               "main()",
               "--role", role, "--dir", self.shared_dir,
               "--owner", owner, "--ttl", str(self.ttl_s),
               "--batch", str(self.batch),
               "--impl", self.deli_impl,
               "--log-format", self.log_format,
               "--ckpt-interval", str(self.ckpt_interval_s),
               "--ckpt-bytes", str(self.ckpt_bytes),
               "--ckpt-duty", str(self.ckpt_duty)]
        if self.deli_devices is not None and role == "deli":
            cmd += ["--deli-devices", str(self.deli_devices)]
        if self.device_plane is not None and role in ("deli",
                                                      "summarizer"):
            cmd += ["--device-plane", self.device_plane]
        if self.fold_backend is not None and role == "summarizer":
            cmd += ["--fold-backend", self.fold_backend]
        if self.summary_ops is not None and role == "summarizer":
            cmd += ["--summary-ops", str(self.summary_ops)]
        if self.hb_interval_s is not None:
            cmd += ["--hb-interval", str(self.hb_interval_s)]
        return cmd

    def _hb_file(self, role: str) -> str:
        """Where `role`'s liveness heartbeat lives (subclass seam: the
        shard fabric heartbeats per WORKER, not per role)."""
        return os.path.join(self.shared_dir, "hb", f"{role}.json")

    def _child_env(self) -> Dict[str, str]:
        """Child spawn environment. Children always run JAX on cpu;
        with a multi-device deli, the CPU backend is split into
        `deli_devices` virtual host devices so the sharded pool has a
        mesh to land on (the XLA flag only acts before the first jax
        import — exactly why it rides the spawn env); a device PLANE
        forces docs*model of them so the whole 2-D grid exists in
        every child."""
        if self.plane_shape is not None:
            from ..utils.devices import forced_host_device_env

            env = forced_host_device_env(
                self.plane_shape[0] * self.plane_shape[1]
            )
        elif self.deli_devices is not None and self.deli_devices > 1:
            from ..utils.devices import forced_host_device_env

            env = forced_host_device_env(self.deli_devices)
        else:
            env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.update(self.child_env)
        return env

    def _spawn(self, role: str) -> Optional[subprocess.Popen]:
        """Spawn one role child; returns None (and records the event)
        on failure rather than raising — a failed spawn must not kill
        the monitor loop that every OTHER role depends on. poll_once
        retries it on its next pass."""
        import select

        self.generation[role] += 1
        self.spawned_at[role] = time.time()  # paces respawn retries too
        owner = f"{role}-g{self.generation[role]}"
        try:
            proc = subprocess.Popen(
                self._child_cmd(role, owner),
                stdout=subprocess.PIPE, text=True,
                cwd=self._repo_root(),
                env=self._child_env(),
            )
        except OSError as exc:
            self.procs[role] = None
            self._event(f"spawn {owner} FAILED ({exc!r})")
            return None
        # Bounded READY wait: a child wedged before its banner must
        # not freeze the whole monitor loop. Raw-fd reads, not the
        # buffered text wrapper — bytes the child flushed in the same
        # write as its banner must reach the drain buffer below, not
        # die in a wrapper buffer the fd-level drain never sees.
        fd = proc.stdout.fileno()
        deadline = time.time() + self.spawn_ready_timeout_s
        buf = b""
        while b"\n" not in buf:
            left = deadline - time.time()
            if left <= 0:
                break
            ready, _, _ = select.select([fd], [], [], left)
            if not ready:
                break
            chunk = os.read(fd, 4096)
            if not chunk:
                break
            buf += chunk
        banner, _, rest = buf.partition(b"\n")
        line = banner.decode("utf-8", "replace").strip()
        if not line.startswith("READY"):
            try:
                proc.kill()
                proc.wait(timeout=10)
            except OSError:
                pass
            self.procs[role] = None
            self._event(f"spawn {owner} FAILED ({line!r})")
            return None
        # Post-banner output is drained non-blockingly by poll_once: a
        # long-lived child (shard worker) prints a line per deposed or
        # fenced partition, and an undrained 64KB pipe would eventually
        # block the child's print() — a whole-worker stall with no
        # real fault.
        os.set_blocking(fd, False)
        self._stdout_tails[role] = rest.decode("utf-8", "replace")[-2048:]
        self.procs[role] = proc
        self._event(f"spawn {owner}")
        return proc

    def start(self) -> "ServiceSupervisor":
        for role in self.roles:
            # Boot is strict: a farm that cannot even start should say
            # so immediately, not limp along partially supervised.
            if self._spawn(role) is None:
                self.stop()
                raise RuntimeError(
                    f"{role} failed to start: {self.events[-1]}"
                )
        return self

    # ---------------------------------------------------------- monitor

    def _heartbeat_age(self, role: str) -> float:
        """Staleness of `role`'s liveness signal. Clamped by the time
        since the current child was spawned: a fresh child that has
        not yet written its first heartbeat (or whose predecessor left
        an old one behind) gets a full grace period instead of an
        instant spurious restart."""
        since_spawn = time.time() - self.spawned_at.get(role, 0.0)
        try:
            with open(self._hb_file(role)) as f:
                hb = json.load(f)
            return min(time.time() - float(hb.get("t", 0)), since_spawn)
        except (OSError, ValueError):
            return since_spawn

    def _drain_stdout(self, role: str) -> None:
        """Pull whatever `role`'s child printed since the last pass into
        a bounded tail buffer (the fd is non-blocking after the banner).
        Only the tail is kept — poll_once quotes the last line when it
        restarts the child."""
        proc = self.procs.get(role)
        if proc is None or proc.stdout is None:
            return
        # os.read, not proc.stdout.read(): buffered text reads over a
        # non-blocking fd raise mid-stream (bpo-13322) instead of
        # returning the partial data, which would leave the pipe full.
        try:
            while True:
                chunk = os.read(proc.stdout.fileno(), 65536)
                if not chunk:
                    break
                tail = (self._stdout_tails.get(role, "")
                        + chunk.decode("utf-8", "replace"))
                self._stdout_tails[role] = tail[-2048:]
        except (OSError, ValueError):
            pass  # no data yet (EAGAIN) or fd already closed

    def poll_once(self) -> List[str]:
        """One supervision pass; returns the events it acted on."""
        acted: List[str] = []
        for role in self.roles:
            proc = self.procs.get(role)
            if proc is None:
                # Previous spawn attempt failed; retry, paced by the
                # lease TTL so a persistent failure can't hot-loop.
                if (role in self.generation
                        and time.time() - self.spawned_at.get(role, 0)
                        >= self.ttl_s):
                    acted.append(f"respawn {role}")
                    self._spawn(role)
                continue
            dead = proc.poll() is not None
            age = self._heartbeat_age(role)
            stale = not dead and age > self.heartbeat_timeout_s
            if not dead and not stale:
                self._drain_stdout(role)
                continue
            if stale:
                # Wedged (or stopped) but alive: kill before restart.
                # Fencing keeps us safe even if the kill were missed.
                try:
                    proc.kill()
                except OSError:
                    pass
                proc.wait(timeout=10)
            self._drain_stdout(role)
            tail = self._stdout_tails.pop(role, "").strip()
            why = (
                f"stale-heartbeat age={age:.2f}s" if stale
                else f"exit={proc.returncode}"
            )
            event = f"restart {role} ({why})" + (
                f" [{tail.splitlines()[-1]}]" if tail else ""
            )
            self.restarts[role] += 1
            self._event(event)
            acted.append(event)
            self._spawn(role)
        return acted

    def supervise(self, duration_s: float,
                  poll_interval_s: float = 0.1) -> None:
        """Run the monitor loop for `duration_s` (the harness's
        foreground mode; production would loop forever)."""
        deadline = time.time() + duration_s
        while time.time() < deadline:
            self.poll_once()
            time.sleep(poll_interval_s)

    # ---------------------------------------------------- observability

    def child_metrics(self) -> Dict[str, dict]:
        """Each role's last heartbeat metrics snapshot (children report
        up through the heartbeat channel; absent/torn files skip)."""
        out: Dict[str, dict] = {}
        for role in self.roles:
            try:
                with open(self._hb_file(role)) as f:
                    hb = json.load(f)
            except (OSError, ValueError):
                continue
            snap = hb.get("metrics")
            if isinstance(snap, dict):
                out[role] = snap
        return out

    def collect_metrics(self):
        """A fresh registry merging every child's heartbeat snapshot
        with the supervisor's own gauges — rebuilt per call, so a
        /metrics scrape always reflects the latest heartbeats without
        double counting."""
        from ..utils.metrics import MetricsRegistry

        reg = MetricsRegistry()
        for snap in self.child_metrics().values():
            reg.merge(snap)
        for role in self.roles:
            reg.gauge("supervisor_restarts", role=role).set(
                self.restarts[role]
            )
            proc = self.procs.get(role)
            alive = proc is not None and proc.poll() is None
            reg.gauge("supervisor_child_alive", role=role).set(
                1.0 if alive else 0.0
            )
            reg.gauge("supervisor_heartbeat_age_s", role=role).set(
                round(self._heartbeat_age(role), 3)
            )
        return reg

    def health(self) -> Dict[str, Any]:
        roles: Dict[str, Any] = {}
        ok = True
        for role in self.roles:
            proc = self.procs.get(role)
            alive = proc is not None and proc.poll() is None
            age = self._heartbeat_age(role)
            stale = age > self.heartbeat_timeout_s
            # A child limping through storage-fault backoff reports
            # itself `degraded` in its heartbeat — live (no restart
            # wanted) but worth an operator's eye.
            limping = bool(self._hb_field(role, "degraded"))
            roles[role] = {
                "alive": alive, "heartbeat_age_s": round(age, 3),
                "restarts": self.restarts[role],
                "degraded": limping,
            }
            ok = ok and alive and not stale and not limping
        return {"status": "ok" if ok else "degraded", "roles": roles,
                "deli_impl": self.deli_impl,
                "log_format": self.log_format,
                "fused_hop": self.fused_hop,
                "retention": self.retention,
                "device_plane": self.device_plane}

    def _hb_field(self, role: str, key: str) -> Any:
        """One field off `role`'s last heartbeat (None if absent)."""
        try:
            with open(self._hb_file(role)) as f:
                return json.load(f).get(key)
        except (OSError, ValueError):
            return None

    def child_slow_ops(self) -> List[dict]:
        """The farm's merged slow-op spans: every child's last
        heartbeat-reported flight-recorder buffer (wire-trace mode
        only — nothing feeds the recorders otherwise), slowest first.
        The `/traces` body for a supervised farm."""
        spans: List[dict] = []
        for role in self.roles:
            v = self._hb_field(role, "slow_ops")
            if isinstance(v, list):
                spans.extend(s for s in v if isinstance(s, dict))
        spans.sort(key=lambda s: -float(s.get("e2e_ms", 0.0)))
        return spans

    def serve_metrics(self, host: str = "127.0.0.1", port: int = 0):
        """The farm's live ops endpoint: `/metrics` (+ `/slo`) merges
        the children's heartbeat-reported registries per scrape;
        `/healthz` reports per-role liveness; `/traces` merges the
        children's slow-op buffers. Returns the
        `monitor.MetricsServer`."""
        if self._monitor is None:
            from .monitor import MetricsServer

            self._monitor = MetricsServer(
                registry=self.collect_metrics, health=self.health,
                host=host, port=port, traces=self.child_slow_ops,
            ).start()
        return self._monitor

    def stop(self) -> None:
        if self._monitor is not None:
            self._monitor.stop()
            self._monitor = None
        for role, proc in list(self.procs.items()):
            if proc is None:
                continue
            if proc.poll() is None:
                proc.kill()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        self.procs.clear()


# ---------------------------------------------------------------------------
# child entry
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)

    def _take(flag: str, default: Optional[str] = None) -> Optional[str]:
        if flag in args:
            i = args.index(flag)
            val = args[i + 1]
            del args[i:i + 2]
            return val
        return default

    role = _take("--role")
    shared_dir = _take("--dir")
    owner = _take("--owner") or f"{role}-pid{os.getpid()}"
    ttl = float(_take("--ttl", "1.0"))
    batch = int(_take("--batch", "512"))
    impl = _take("--impl") or os.environ.get("FLUID_DELI", "scalar")
    log_format = _take("--log-format")
    ckpt_interval = float(_take("--ckpt-interval", "0.25"))
    ckpt_bytes = int(_take("--ckpt-bytes", str(256 * 1024)))
    ckpt_duty = float(_take("--ckpt-duty", "0.2"))
    partition_s = _take("--partition")
    devices_s = _take("--deli-devices")
    hb_interval_s = _take("--hb-interval")
    summary_ops_s = _take("--summary-ops")
    device_plane_s = _take("--device-plane")
    fold_backend_s = _take("--fold-backend")
    ingress_parts_s = _take("--ingress-partitions")
    ingress_elastic = "--ingress-elastic" in args
    if ingress_elastic:
        args.remove("--ingress-elastic")
    if (role not in ROLES + (ScriptoriumBroadcasterRole.name, "ingress",
                             "retention")
            or shared_dir is None
            or impl not in DELI_IMPLS
            or (log_format is not None and log_format not in LOG_FORMATS)
            or (partition_s is not None and not partition_s.isdigit())
            or (devices_s is not None and not devices_s.isdigit())
            or (ingress_parts_s is not None
                and not ingress_parts_s.isdigit())
            or (summary_ops_s is not None
                and not summary_ops_s.isdigit())
            or (fold_backend_s is not None
                and fold_backend_s not in ("kernel", "overlay"))):
        print(
            "usage: python -m fluidframework_tpu.server.supervisor "
            "--role {deli|scriptorium|scribe|broadcaster|summarizer"
            "|scriptorium_broadcaster|ingress|retention} "
            "--dir D "
            "[--owner O] [--ttl S] [--batch N] [--impl scalar|kernel] "
            "[--log-format json|columnar] [--partition K] "
            "[--deli-devices N] [--device-plane DxM] "
            "[--fold-backend kernel|overlay] "
            "[--hb-interval S] [--summary-ops N] "
            "[--ingress-partitions N] [--ingress-elastic] "
            "[--ckpt-interval S] [--ckpt-bytes N] [--ckpt-duty F]",
            file=sys.stderr,
        )
        raise SystemExit(2)
    serve_role(shared_dir, role, owner, ttl_s=ttl, batch=batch,
               deli_impl=impl, ckpt_interval_s=ckpt_interval,
               ckpt_bytes=ckpt_bytes, log_format=log_format,
               ckpt_duty=ckpt_duty,
               partition=int(partition_s) if partition_s else None,
               deli_devices=int(devices_s) if devices_s else None,
               hb_interval_s=float(hb_interval_s)
               if hb_interval_s else None,
               summary_ops=int(summary_ops_s) if summary_ops_s else None,
               ingress_partitions=int(ingress_parts_s)
               if ingress_parts_s else None,
               ingress_elastic=ingress_elastic,
               device_plane=device_plane_s,
               fold_backend=fold_backend_s)


if __name__ == "__main__":
    main()
